"""Best-split search over a leaf histogram.

Reference analogs: ``FeatureHistogram::FindBestThresholdSequentially``
(src/treelearner/feature_histogram.hpp:832 — per-feature sequential scan with
missing-direction handling) and the CUDA per-(leaf,feature) scan kernel
(src/treelearner/cuda/cuda_best_split_finder.cu:776).

TPU-native formulation: one vectorized cumulative-sum over the bin axis for
ALL features at once, gains evaluated for every (feature, bin, missing-dir)
candidate simultaneously, then a single argmax.  The reference's two-direction
scan for missing values becomes two gain tensors (NaN bin counted left vs
right).  Gain math (L1 thresholding, L2, max_delta_step, min_data/min_hess
gates) follows feature_histogram.hpp:711-828.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

_EPS = 1e-15


def threshold_l1(g: jnp.ndarray, l1: float) -> jnp.ndarray:
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def leaf_gain(g, h, l1: float, l2: float):
    t = threshold_l1(g, l1)
    return (t * t) / (h + l2 + _EPS)


def leaf_output(g, h, l1: float, l2: float, max_delta_step: float = 0.0):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:711)."""
    out = -threshold_l1(g, l1) / (h + l2 + _EPS)
    if max_delta_step > 0.0:
        out = jnp.clip(out, -max_delta_step, max_delta_step)
    return out


class SplitCandidate(NamedTuple):
    """Best split for one leaf (reference: SplitInfo, split_info.hpp:22)."""

    gain: jnp.ndarray  # improvement over parent minus min_gain; <=0 means no split
    feature: jnp.ndarray  # used-feature index (int32)
    bin: jnp.ndarray  # threshold bin: bin <= threshold goes left
    default_left: jnp.ndarray  # bool: missing goes left
    left_g: jnp.ndarray
    left_h: jnp.ndarray
    left_cnt: jnp.ndarray
    right_g: jnp.ndarray
    right_h: jnp.ndarray
    right_cnt: jnp.ndarray


def constrained_output(
    g,
    h,
    l1: float,
    l2: float,
    max_delta_step: float,
    path_smooth: float = 0.0,
    num_data=None,
    parent_output=0.0,
    lb=None,
    ub=None,
):
    """CalculateSplittedLeafOutput with smoothing + monotone bounds
    (feature_histogram.hpp:717-755)."""
    out = leaf_output(g, h, l1, l2, max_delta_step)
    if path_smooth > 0.0 and num_data is not None:
        ratio = num_data / path_smooth
        out = out * ratio / (ratio + 1.0) + parent_output / (ratio + 1.0)
    if lb is not None:
        out = jnp.maximum(out, lb)
    if ub is not None:
        out = jnp.minimum(out, ub)
    return out


def gain_given_output(g, h, l1: float, l2: float, out):
    """GetLeafGainGivenOutput (feature_histogram.hpp:739)."""
    t = threshold_l1(g, l1)
    return -(2.0 * t * out + (h + l2 + _EPS) * out * out)


def best_split(
    hist: jnp.ndarray,  # [F, B, 3] (sum_grad, sum_hess, count)
    parent_g: jnp.ndarray,
    parent_h: jnp.ndarray,
    parent_cnt: jnp.ndarray,
    num_bins: jnp.ndarray,  # [F] total bins per feature (incl. NaN bin)
    nan_bins: jnp.ndarray,  # [F] NaN-bin index per feature, -1 if none
    feature_mask: jnp.ndarray,  # [F] bool — col-sampled features
    *,
    lambda_l1: float,
    lambda_l2: float,
    min_data_in_leaf: int,
    min_sum_hessian_in_leaf: float,
    min_gain_to_split: float,
    max_delta_step: float = 0.0,
    path_smooth: float = 0.0,
    monotone: Optional[jnp.ndarray] = None,  # [F] int8 in {-1, 0, +1}
    leaf_lb=None,  # scalar lower bound on child outputs (monotone)
    leaf_ub=None,
    parent_output=0.0,  # current output of the leaf (path smoothing)
) -> SplitCandidate:
    f, b, _ = hist.shape
    use_full_gain = monotone is not None or path_smooth > 0.0

    has_nan = nan_bins >= 0
    nan_idx = jnp.where(has_nan, nan_bins, 0)
    nan_stats = jnp.take_along_axis(hist, nan_idx[:, None, None], axis=1)[:, 0, :]
    nan_stats = nan_stats * has_nan[:, None]  # [F, 3]

    # zero out the NaN bin so the cumsum covers only ordered numeric bins
    bin_ids = jnp.arange(b, dtype=jnp.int32)[None, :]
    is_nan_bin = has_nan[:, None] & (bin_ids == nan_bins[:, None])
    hist_o = jnp.where(is_nan_bin[:, :, None], 0.0, hist)

    cum = jnp.cumsum(hist_o, axis=1)  # [F, B, 3] left stats (missing right)
    parent = jnp.stack(
        [parent_g.astype(jnp.float32), parent_h.astype(jnp.float32), parent_cnt.astype(jnp.float32)]
    )

    # candidate threshold at bin t is valid for t in [0, num_ordered_bins-2]
    num_ordered = num_bins - has_nan.astype(jnp.int32)
    valid_bin = bin_ids < (num_ordered[:, None] - 1)

    def eval_case(left):  # left: [F, B, 3]
        lg, lh, lc = left[..., 0], left[..., 1], left[..., 2]
        rg, rh, rc = parent[0] - lg, parent[1] - lh, parent[2] - lc
        ok = (
            valid_bin
            & (lc >= min_data_in_leaf)
            & (rc >= min_data_in_leaf)
            & (lh >= min_sum_hessian_in_leaf)
            & (rh >= min_sum_hessian_in_leaf)
            & feature_mask[:, None]
        )
        if not use_full_gain:
            gain = leaf_gain(lg, lh, lambda_l1, lambda_l2) + leaf_gain(
                rg, rh, lambda_l1, lambda_l2
            )
        else:
            # full path: constrained outputs + GetLeafGainGivenOutput
            # (GetSplitGains with USE_MC, feature_histogram.hpp:759-828)
            out_l = constrained_output(
                lg, lh, lambda_l1, lambda_l2, max_delta_step,
                path_smooth, lc, parent_output, leaf_lb, leaf_ub,
            )
            out_r = constrained_output(
                rg, rh, lambda_l1, lambda_l2, max_delta_step,
                path_smooth, rc, parent_output, leaf_lb, leaf_ub,
            )
            gain = gain_given_output(lg, lh, lambda_l1, lambda_l2, out_l) + \
                gain_given_output(rg, rh, lambda_l1, lambda_l2, out_r)
            if monotone is not None:
                mc = monotone[:, None]
                violated = ((mc > 0) & (out_l > out_r)) | ((mc < 0) & (out_l < out_r))
                ok = ok & ~violated
        return jnp.where(ok, gain, -jnp.inf)

    gain_right = eval_case(cum)  # missing -> right (default_left = False)
    gain_left = jnp.where(
        has_nan[:, None], eval_case(cum + nan_stats[:, None, :]), -jnp.inf
    )  # missing -> left; only distinct when a NaN bin exists

    gains = jnp.stack([gain_right, gain_left])  # [2, F, B]
    flat = jnp.argmax(gains)
    dl = (flat // (f * b)).astype(jnp.int32)
    rem = flat % (f * b)
    feat = (rem // b).astype(jnp.int32)
    tbin = (rem % b).astype(jnp.int32)
    best_gain_raw = gains.reshape(-1)[flat]

    left = cum[feat, tbin] + jnp.where(dl == 1, nan_stats[feat], 0.0)
    if not use_full_gain:
        parent_gain = leaf_gain(parent[0], parent[1], lambda_l1, lambda_l2)
    else:
        parent_gain = gain_given_output(
            parent[0], parent[1], lambda_l1, lambda_l2,
            constrained_output(
                parent[0], parent[1], lambda_l1, lambda_l2, max_delta_step,
                0.0, None, 0.0, leaf_lb, leaf_ub,
            ),
        )
    improvement = best_gain_raw - parent_gain - min_gain_to_split
    improvement = jnp.where(jnp.isfinite(best_gain_raw), improvement, -jnp.inf)

    return SplitCandidate(
        gain=improvement.astype(jnp.float32),
        feature=feat,
        bin=tbin,
        default_left=dl == 1,
        left_g=left[0],
        left_h=left[1],
        left_cnt=left[2],
        right_g=parent[0] - left[0],
        right_h=parent[1] - left[1],
        right_cnt=parent[2] - left[2],
    )
