"""Best-split search over a leaf histogram.

Reference analogs: ``FeatureHistogram::FindBestThresholdSequentially``
(src/treelearner/feature_histogram.hpp:832 — per-feature sequential scan with
missing-direction handling) and the CUDA per-(leaf,feature) scan kernel
(src/treelearner/cuda/cuda_best_split_finder.cu:776).

TPU-native formulation: one vectorized cumulative-sum over the bin axis for
ALL features at once, gains evaluated for every (feature, bin, missing-dir)
candidate simultaneously, then a single argmax.  The reference's two-direction
scan for missing values becomes two gain tensors (NaN bin counted left vs
right).  Gain math (L1 thresholding, L2, max_delta_step, min_data/min_hess
gates) follows feature_histogram.hpp:711-828.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax.numpy as jnp

_EPS = 1e-15


def threshold_l1(g: jnp.ndarray, l1: float) -> jnp.ndarray:
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def leaf_gain(g, h, l1: float, l2: float):
    t = threshold_l1(g, l1)
    return (t * t) / (h + l2 + _EPS)


def leaf_output(g, h, l1: float, l2: float, max_delta_step: float = 0.0):
    """CalculateSplittedLeafOutput (feature_histogram.hpp:711)."""
    out = -threshold_l1(g, l1) / (h + l2 + _EPS)
    if max_delta_step > 0.0:
        out = jnp.clip(out, -max_delta_step, max_delta_step)
    return out


class SplitCandidate(NamedTuple):
    """Best split for one leaf (reference: SplitInfo, split_info.hpp:22).

    For categorical splits ``is_cat`` is True and ``cat_mask`` is a bin-space
    bitmask ([B] bool, True = bin goes LEFT) — the TPU formulation of the
    reference's ``cat_threshold`` uint32 vector (bitset of categories); the
    mapping back to category values happens at host Tree materialization.
    ``cat_mask`` has width 1 when the grower runs without categorical
    features (static no-op)."""

    gain: jnp.ndarray  # improvement over parent minus min_gain; <=0 means no split
    feature: jnp.ndarray  # used-feature index (int32)
    bin: jnp.ndarray  # threshold bin: bin <= threshold goes left
    default_left: jnp.ndarray  # bool: missing goes left
    left_g: jnp.ndarray
    left_h: jnp.ndarray
    left_cnt: jnp.ndarray
    right_g: jnp.ndarray
    right_h: jnp.ndarray
    right_cnt: jnp.ndarray
    is_cat: jnp.ndarray  # bool
    cat_mask: jnp.ndarray  # [B] bool (or [1] when categorical is disabled)


def constrained_output(
    g,
    h,
    l1: float,
    l2: float,
    max_delta_step: float,
    path_smooth: float = 0.0,
    num_data=None,
    parent_output=0.0,
    lb=None,
    ub=None,
):
    """CalculateSplittedLeafOutput with smoothing + monotone bounds
    (feature_histogram.hpp:717-755)."""
    out = leaf_output(g, h, l1, l2, max_delta_step)
    if path_smooth > 0.0 and num_data is not None:
        ratio = num_data / path_smooth
        out = out * ratio / (ratio + 1.0) + parent_output / (ratio + 1.0)
    if lb is not None:
        out = jnp.maximum(out, lb)
    if ub is not None:
        out = jnp.minimum(out, ub)
    return out


def gain_given_output(g, h, l1: float, l2: float, out):
    """GetLeafGainGivenOutput (feature_histogram.hpp:739)."""
    t = threshold_l1(g, l1)
    return -(2.0 * t * out + (h + l2 + _EPS) * out * out)


class CatParams(NamedTuple):
    """Static categorical-split config (reference: Config fields consumed by
    FindBestThresholdCategoricalInner, src/treelearner/feature_histogram.cpp:147)."""

    max_cat_to_onehot: int = 4
    max_cat_threshold: int = 32
    cat_l2: float = 10.0
    cat_smooth: float = 10.0
    min_data_per_group: int = 100


def best_split(
    hist: jnp.ndarray,  # [F, B, 3] (sum_grad, sum_hess, count)
    parent_g: jnp.ndarray,
    parent_h: jnp.ndarray,
    parent_cnt: jnp.ndarray,
    num_bins: jnp.ndarray,  # [F] total bins per feature (incl. NaN bin)
    nan_bins: jnp.ndarray,  # [F] NaN-bin index per feature, -1 if none
    feature_mask: jnp.ndarray,  # [F] bool — col-sampled features
    *,
    lambda_l1: float,
    lambda_l2: float,
    min_data_in_leaf: int,
    min_sum_hessian_in_leaf: float,
    min_gain_to_split: float,
    max_delta_step: float = 0.0,
    path_smooth: float = 0.0,
    monotone: Optional[jnp.ndarray] = None,  # [F] int8 in {-1, 0, +1}
    leaf_lb=None,  # scalar lower bound on child outputs (monotone)
    leaf_ub=None,
    parent_output=0.0,  # current output of the leaf (path smoothing)
    is_cat: Optional[jnp.ndarray] = None,  # [F] bool — categorical features
    cat_params: Optional[CatParams] = None,  # static; required with is_cat
    cegb_penalty: Optional[jnp.ndarray] = None,  # [F] f32 per-feature penalty
    cegb_split_penalty: float = 0.0,  # tradeoff * cegb_penalty_split
    rand_bins: Optional[jnp.ndarray] = None,  # [F] extra_trees random bin
    per_feature_gains: bool = False,  # also return max gain per feature [F]
    monotone_penalty: float = 0.0,  # depth-scaled gain penalty for monotone
    #                   features (reference monotone_constraints.hpp:357-366,
    #                   applied at serial_tree_learner.cpp:1002); needs
    #                   ``leaf_depth`` and ``monotone`` to engage
    leaf_depth=None,  # scalar i32 — depth of THIS leaf (the penalty is
    #                   evaluated at leaf_depth + 1, the children's depth)
    feature_contri: Optional[jnp.ndarray] = None,  # [F] f32 per-feature gain
    #                   multipliers (reference FeatureMetainfo::penalty,
    #                   feature_histogram.hpp:1445-1448)
    adv_bounds=None,  # advanced monotone: (lb_l, ub_l, lb_r, ub_r) [F, B]
    #                   per-THRESHOLD child bounds (reference
    #                   AdvancedLeafConstraints / CumulativeFeatureConstraint,
    #                   monotone_constraints.hpp:858/:146) — applied to the
    #                   numeric candidates instead of the scalar leaf bounds
    with_margin: bool = False,  # also return the near-tie margin: the
    #                   relative gain gap between the winning candidate and
    #                   the global runner-up, +inf when either is non-finite.
    #                   The grower's int8-default histogram path re-
    #                   accumulates in f32 when this falls below
    #                   near_tie_tol (histogram engine v2).
    bundle_end: Optional[jnp.ndarray] = None,  # [F, B] i32 — EFB planes
    #                   (bundling.py): for a bundle-plane bin inside a member
    #                   feature's sub-range, the sub-range's LAST bin; -1
    #                   elsewhere.  A candidate at bundle bin t means
    #                   "member-local bin <= t - start goes left", i.e. left
    #                   child = everything except plane bins [t, end] — the
    #                   reference's per-feature scan over a feature group's
    #                   histogram with the out-of-range mass folded into the
    #                   feature's default bin.
) -> SplitCandidate:
    """cegb_*: Cost-Effective Gradient Boosting (reference:
    cost_effective_gradient_boosting.hpp DeltaGain — gain is reduced by
    tradeoff*penalty_split*num_data plus a per-feature penalty, here the
    coupled penalty for features not yet used anywhere in the model)."""
    f, b, _ = hist.shape
    use_full_gain = monotone is not None or path_smooth > 0.0
    use_cat = is_cat is not None

    has_nan = nan_bins >= 0
    nan_idx = jnp.where(has_nan, nan_bins, 0)
    nan_stats = jnp.take_along_axis(hist, nan_idx[:, None, None], axis=1)[:, 0, :]
    nan_stats = nan_stats * has_nan[:, None]  # [F, 3]

    # zero out the NaN bin so the cumsum covers only ordered numeric bins
    bin_ids = jnp.arange(b, dtype=jnp.int32)[None, :]
    is_nan_bin = has_nan[:, None] & (bin_ids == nan_bins[:, None])
    hist_o = jnp.where(is_nan_bin[:, :, None], 0.0, hist)

    cum = jnp.cumsum(hist_o, axis=1)  # [F, B, 3] left stats (missing right)
    parent = jnp.stack(
        [parent_g.astype(jnp.float32), parent_h.astype(jnp.float32), parent_cnt.astype(jnp.float32)]
    )

    # candidate threshold at bin t is valid for t in [0, num_ordered_bins-2]
    num_ordered = num_bins - has_nan.astype(jnp.int32)
    valid_bin = bin_ids < (num_ordered[:, None] - 1)
    if bundle_end is not None:
        # EFB bundle planes: left child at bundle bin t = parent minus the
        # owning member's plane bins [t, end] (everything else — the shared
        # default bin 0 and every OTHER member's mass — is "member at its
        # default", which goes left).  left = parent - (cum[end] - cum[t-1]).
        # Non-bundle bins keep the plain cumsum; every sub-range bin is a
        # valid candidate (t = start encodes "default alone goes left").
        bundled_bin = bundle_end >= 0  # [F, B]
        plane_bundled = bundled_bin.any(axis=1)  # [F]
        cum_end = jnp.take_along_axis(
            cum, jnp.clip(bundle_end, 0, b - 1)[:, :, None], axis=1
        )  # [F, B, 3]
        cum = jnp.where(
            bundled_bin[:, :, None],
            parent[None, None, :] - cum_end + cum - hist_o,
            cum,
        )
        valid_bin = jnp.where(plane_bundled[:, None], bundled_bin, valid_bin)
    if rand_bins is not None:
        # extra_trees (extremely randomized trees): only ONE random
        # threshold per feature competes (reference USE_RAND branch of
        # FindBestThresholdSequentially, feature_histogram.hpp:870)
        valid_bin = valid_bin & (bin_ids == rand_bins[:, None])
    num_feature_mask = feature_mask & ~is_cat if use_cat else feature_mask

    def eval_gain(lg, lh, lc, l2v, ok, bnds=None):
        """Masked split gain for [F, B] left-stat candidates (reference:
        GetSplitGains, feature_histogram.hpp:759-828).  ``bnds`` overrides
        the scalar leaf bounds with per-candidate (lb_l, ub_l, lb_r, ub_r)
        arrays (advanced monotone mode, numeric candidates only)."""
        rg, rh, rc = parent[0] - lg, parent[1] - lh, parent[2] - lc
        ok = (
            ok
            & (lc >= min_data_in_leaf)
            & (rc >= min_data_in_leaf)
            & (lh >= min_sum_hessian_in_leaf)
            & (rh >= min_sum_hessian_in_leaf)
        )
        if not use_full_gain:
            gain = leaf_gain(lg, lh, lambda_l1, l2v) + leaf_gain(
                rg, rh, lambda_l1, l2v
            )
        else:
            lb_l, ub_l, lb_r, ub_r = (
                bnds if bnds is not None
                else (leaf_lb, leaf_ub, leaf_lb, leaf_ub)
            )
            # full path: constrained outputs + GetLeafGainGivenOutput
            out_l = constrained_output(
                lg, lh, lambda_l1, l2v, max_delta_step,
                path_smooth, lc, parent_output, lb_l, ub_l,
            )
            out_r = constrained_output(
                rg, rh, lambda_l1, l2v, max_delta_step,
                path_smooth, rc, parent_output, lb_r, ub_r,
            )
            gain = gain_given_output(lg, lh, lambda_l1, l2v, out_l) + \
                gain_given_output(rg, rh, lambda_l1, l2v, out_r)
            if monotone is not None:
                mc = monotone[:, None]
                violated = ((mc > 0) & (out_l > out_r)) | ((mc < 0) & (out_l < out_r))
                ok = ok & ~violated
        return jnp.where(ok, gain, -jnp.inf)

    def eval_case(left):  # left: [F, B, 3] — numeric cumsum candidates
        return eval_gain(
            left[..., 0],
            left[..., 1],
            left[..., 2],
            lambda_l2,
            valid_bin & num_feature_mask[:, None],
            bnds=adv_bounds,
        )

    gain_right = eval_case(cum)  # missing -> right (default_left = False)
    gain_left = jnp.where(
        has_nan[:, None], eval_case(cum + nan_stats[:, None, :]), -jnp.inf
    )  # missing -> left; only distinct when a NaN bin exists

    cases = [gain_right, gain_left]
    if use_cat:
        # ---- categorical splits (FindBestThresholdCategoricalInner,
        # src/treelearner/feature_histogram.cpp:147-343).  TPU formulation:
        # the per-feature sequential sorted-subset scan becomes one argsort
        # over the bin axis + prefix sums evaluated for ALL (feature, k)
        # candidates at once; the winning subset is reconstructed as a
        # bin-space bitmask from the sort ranks.
        cp = cat_params if cat_params is not None else CatParams()
        g_, h_, c_ = hist[..., 0], hist[..., 1], hist[..., 2]
        # the NaN bin never moves LEFT: prediction sends categorical NaN to
        # the right child (reference CategoricalDecision, tree.h:346), so
        # keeping its rows right during training makes train == predict
        in_range = (bin_ids < num_bins[:, None]) & ~is_nan_bin
        catf = (is_cat & feature_mask)[:, None]
        use_onehot_f = (num_bins <= cp.max_cat_to_onehot)[:, None]
        oh_ok = in_range & catf & use_onehot_f
        if rand_bins is not None:
            # extra_trees randomizes categorical candidates too (reference
            # USE_RAND in FindBestThresholdCategoricalInner): one random
            # category for one-hot ...
            oh_ok = oh_ok & (
                bin_ids == (rand_bins % jnp.maximum(num_bins, 1))[:, None]
            )
        # case 2 — one-hot: left = the single category bin (:188-241)
        gain_oh = eval_gain(g_, h_, c_, lambda_l2, oh_ok)
        # cases 3/4 — sorted subset scan, both directions (:243-342)
        l2c = lambda_l2 + cp.cat_l2
        validb = in_range & (c_ >= cp.cat_smooth)
        ctr = g_ / (h_ + cp.cat_smooth)
        key = jnp.where(validb, ctr, jnp.inf)
        order = jnp.argsort(key, axis=1, stable=True)  # [F, B] bin ids
        rank = jnp.argsort(order, axis=1)  # [F, B] sorted position per bin

        def _sorted(x):
            return jnp.take_along_axis(jnp.where(validb, x, 0.0), order, axis=1)

        pre_g = jnp.cumsum(_sorted(g_), axis=1)
        pre_h = jnp.cumsum(_sorted(h_), axis=1)
        pre_c = jnp.cumsum(_sorted(c_), axis=1)
        used = validb.sum(axis=1).astype(jnp.int32)  # [F]
        tot_g, tot_h, tot_c = pre_g[:, -1:], pre_h[:, -1:], pre_c[:, -1:]
        max_num_cat = jnp.minimum(cp.max_cat_threshold, (used + 1) // 2)
        pos_ok = bin_ids < jnp.minimum(used, max_num_cat)[:, None]
        if rand_bins is not None:
            # ... and one random subset size for the sorted scan (:271)
            rpos = rand_bins % jnp.maximum(jnp.minimum(used, max_num_cat), 1)
            pos_ok = pos_ok & (bin_ids == rpos[:, None])
        ok_sorted = catf & ~use_onehot_f & pos_ok

        bidx = used[:, None] - 2 - bin_ids  # bwd prefix end (may be < 0)
        has_pre = bidx >= 0
        bidxc = jnp.clip(bidx, 0, b - 1)

        def _bwd(pre, tot):
            return tot - jnp.where(
                has_pre, jnp.take_along_axis(pre, bidxc, axis=1), 0.0
            )

        def _group_ok(lc):
            # min_data_per_group: the reference evaluates a candidate only
            # after >= min_data_per_group rows accumulated since the last
            # evaluated candidate (:278-312). Vectorized approximation:
            # evaluate where the cumulative count crosses a multiple of
            # min_data_per_group (exact when min_data_per_group <= 1).
            if cp.min_data_per_group <= 1:
                return jnp.ones(lc.shape, bool)
            prev = jnp.concatenate(
                [jnp.zeros((f, 1), lc.dtype), lc[:, :-1]], axis=1
            )
            m = float(cp.min_data_per_group)
            return jnp.floor(lc / m) > jnp.floor(prev / m)

        mdpg_ok_fwd = parent[2] - pre_c >= cp.min_data_per_group
        gain_fwd = eval_gain(
            pre_g, pre_h, pre_c, l2c,
            ok_sorted & _group_ok(pre_c) & mdpg_ok_fwd,
        )
        bg, bh, bc = _bwd(pre_g, tot_g), _bwd(pre_h, tot_h), _bwd(pre_c, tot_c)
        gain_bwd = eval_gain(
            bg, bh, bc, l2c,
            ok_sorted & _group_ok(bc) & (parent[2] - bc >= cp.min_data_per_group),
        )
        cases += [gain_oh, gain_fwd, gain_bwd]

    gains = jnp.stack(cases)  # [C, F, B]
    if not use_full_gain:
        parent_gain = leaf_gain(parent[0], parent[1], lambda_l1, lambda_l2)
    else:
        parent_gain = gain_given_output(
            parent[0], parent[1], lambda_l1, lambda_l2,
            constrained_output(
                parent[0], parent[1], lambda_l1, lambda_l2, max_delta_step,
                0.0, None, 0.0, leaf_lb, leaf_ub,
            ),
        )
    use_penalized = feature_contri is not None or (
        monotone is not None
        and monotone_penalty > 0.0
        and leaf_depth is not None
    )
    if cegb_penalty is not None and not use_penalized:
        # per-feature penalty shifts which candidate wins (DeltaGain's
        # coupled term); applied in improvement units so the parent-gain
        # subtraction below stays correct
        gains = gains - cegb_penalty[None, :, None]
    if use_penalized:
        # the reference applies these multipliers to the IMPROVEMENT (raw
        # gain minus parent gain minus min_gain_shift) before the
        # cross-feature comparison — FindBestThreshold's
        # ``output->gain *= meta_->penalty`` (feature_histogram.hpp:1445)
        # and ComputeMonotoneSplitGainPenalty at
        # serial_tree_learner.cpp:1002 — so they can change which feature
        # wins, not just rescale the winner
        mult = jnp.ones((f,), jnp.float32)
        if (
            monotone is not None
            and monotone_penalty > 0.0
            and leaf_depth is not None
        ):
            d = (jnp.asarray(leaf_depth) + 1).astype(jnp.float32)
            if monotone_penalty <= 1.0:
                base = 1.0 - monotone_penalty / jnp.exp2(d) + _EPS
            else:
                base = 1.0 - jnp.exp2(monotone_penalty - 1.0 - d) + _EPS
            pen = jnp.where(monotone_penalty >= d + 1.0, _EPS, base)
            mult = mult * jnp.where(monotone != 0, pen, 1.0)
        if feature_contri is not None:
            mult = mult * feature_contri.astype(jnp.float32)
        imp_all = gains - parent_gain - min_gain_to_split
        scaled = jnp.where(
            jnp.isfinite(gains), imp_all * mult[None, :, None], -jnp.inf
        )
        if cegb_penalty is not None:
            # reference order: penalty multiply, THEN the CEGB delta
            scaled = scaled - cegb_penalty[None, :, None]
        sel = scaled
    else:
        sel = gains
    flat = jnp.argmax(sel)
    if with_margin:
        # relative gap to the global runner-up across EVERY candidate
        # (cases x features x bins) — a flip anywhere in this tensor is a
        # structure change, so this is the conservative near-tie signal
        sel_flat = sel.reshape(-1)
        best_v = sel_flat[flat]
        sec_v = jnp.max(
            jnp.where(
                jnp.arange(sel_flat.shape[0], dtype=jnp.int32) == flat,
                -jnp.inf,
                sel_flat,
            )
        )
        margin = jnp.where(
            jnp.isfinite(best_v) & jnp.isfinite(sec_v),
            (best_v - sec_v) / jnp.maximum(jnp.abs(best_v), _EPS),
            jnp.inf,
        ).astype(jnp.float32)
    case = (flat // (f * b)).astype(jnp.int32)
    dl = (case == 1).astype(jnp.int32)
    rem = flat % (f * b)
    feat = (rem // b).astype(jnp.int32)
    tbin = (rem % b).astype(jnp.int32)
    best_gain_raw = gains.reshape(-1)[flat]

    left = cum[feat, tbin] + jnp.where(dl == 1, nan_stats[feat], 0.0)
    if use_cat:
        left_oh = hist[feat, tbin]
        left_fwd = jnp.stack([pre_g[feat, tbin], pre_h[feat, tbin], pre_c[feat, tbin]])
        left_bwd = jnp.stack([bg[feat, tbin], bh[feat, tbin], bc[feat, tbin]])
        left = jnp.select(
            [case == 2, case == 3, case == 4],
            [left_oh, left_fwd, left_bwd],
            left,
        )
        sel_rank = rank[feat]
        sel_valid = validb[feat]
        oh_mask = jnp.arange(b, dtype=jnp.int32) == tbin
        fwd_mask = sel_valid & (sel_rank <= tbin)
        bwd_mask = sel_valid & (sel_rank >= used[feat] - 1 - tbin)
        is_cat_win = case >= 2
        cat_mask = jnp.select(
            [case == 2, case == 3, case == 4],
            [oh_mask, fwd_mask, bwd_mask],
            jnp.zeros((b,), bool),
        )
    else:
        is_cat_win = jnp.asarray(False)
        cat_mask = jnp.zeros((b if bundle_end is not None else 1,), bool)
    if bundle_end is not None:
        # a bundle-plane winner partitions by plane-bin MEMBERSHIP (left =
        # everything except the member's bins [t, end]) — expressed through
        # the existing categorical-mask machinery so every partition /
        # replay / device-predict path applies it unchanged; the host Tree
        # decode (tree.py) turns it back into a numeric threshold on the
        # original feature
        bwin_end = bundle_end[feat, tbin]
        bundled_win = bwin_end >= 0
        bids = jnp.arange(b, dtype=jnp.int32)
        bundle_mask = ~((bids >= tbin) & (bids <= bwin_end))
        is_cat_win = jnp.asarray(is_cat_win) | bundled_win
        cat_mask = jnp.where(bundled_win, bundle_mask, cat_mask)
    if use_penalized:
        improvement = scaled.reshape(-1)[flat]
    else:
        improvement = best_gain_raw - parent_gain - min_gain_to_split
    if cegb_split_penalty:
        # uniform per-split data cost: tradeoff * penalty_split * num_data
        improvement = improvement - cegb_split_penalty * parent[2]
    improvement = jnp.where(jnp.isfinite(best_gain_raw), improvement, -jnp.inf)

    cand_out = SplitCandidate(
        gain=improvement.astype(jnp.float32),
        feature=feat,
        bin=tbin,
        default_left=dl == 1,
        left_g=left[0],
        left_h=left[1],
        left_cnt=left[2],
        right_g=parent[0] - left[0],
        right_h=parent[1] - left[1],
        right_cnt=parent[2] - left[2],
        is_cat=is_cat_win,
        cat_mask=cat_mask,
    )
    if per_feature_gains:
        # best IMPROVEMENT per feature (raw gain minus the same parent/
        # min_gain offset the winning candidate uses — including the
        # constrained-parent form under use_full_gain) — the voting-parallel
        # learner's LightSplitInfo gains (voting_parallel_tree_learner.cpp:152)
        if use_penalized:
            pf = sel.max(axis=(0, 2))
        else:
            pf = gains.max(axis=(0, 2)) - parent_gain - min_gain_to_split
        return (cand_out, pf, margin) if with_margin else (cand_out, pf)
    if with_margin:
        return cand_out, margin
    return cand_out
