"""Leaf-wise (best-first) tree grower, fully on-device under one jit.

Reference analogs: ``SerialTreeLearner::Train`` (src/treelearner/
serial_tree_learner.cpp:182 — BeforeTrain, then a loop of ConstructHistograms
-> FindBestSplitsFromHistograms -> argmax leaf -> Split) and the CUDA
single-GPU learner's per-leaf device loop (src/treelearner/cuda/
cuda_single_gpu_tree_learner.cpp:159-330).

TPU-native design decisions:
  * row->leaf membership is a dense ``leaf_id`` vector updated by a masked
    compare (the reference's DataPartition index-array shuffle and the CUDA
    prefix-sum scatter both become one vectorized ``where``);
  * the smaller child's histogram is built by a masked pass, the sibling by
    the parent-minus-smaller subtraction trick (serial_tree_learner.cpp:558);
  * per-leaf best splits are cached so each step only rescans the two leaves
    the previous split touched;
  * the whole num_leaves-1 loop is a ``lax.fori_loop`` with static shapes;
    a ``done`` flag makes trailing iterations no-ops once no leaf has a
    positive-gain split;
  * with ``axis_name`` set, histogram/root sums are ``psum``-ed across the
    data mesh axis — the data-parallel learner's ReduceScatter+Allreduce
    (src/treelearner/data_parallel_tree_learner.cpp) as XLA collectives.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..obs.collectives import timed_pmax, timed_pmin, timed_psum
from ..obs.jit import instrumented_jit
from .histogram import leaf_histogram
from .split import CatParams, SplitCandidate, best_split, leaf_gain, leaf_output


@dataclasses.dataclass(frozen=True)
class GrowerParams:
    """Static (compile-time) training parameters for one tree."""

    num_leaves: int
    max_bin: int  # B: padded bin-axis size of the histogram
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    hist_method: str = "auto"
    axis_name: Optional[str] = None
    # voting-parallel (PV-Tree, tree_learner=voting): local top-k election,
    # psum only the elected 2k features' histogram slices; 0 = off.  Active
    # only when F > 2*top_k (see voting_active) — below that dense psum is
    # exact and cheaper, so voting aliases onto the data-parallel path.
    voting_top_k: int = 0
    # feature-parallel (tree_learner=feature with rows REPLICATED,
    # reference feature_parallel_tree_learner.cpp:37): every shard holds all
    # rows, histograms/split-finding cover only its axis_index'th feature
    # slice (F must divide the shard count), the winning candidate is
    # all-reduced, and the partition runs locally on the full columns — the
    # reference's "every machine has full data" design, so no split-result
    # broadcast is needed.  Requires hist_mode gather/full (the leaf-id
    # formulation keeps full columns addressable).  Value = number of
    # feature shards; 0 = off.
    feature_shard: int = 0
    # named-mesh second axis (parallel/mesh.py): when set, feature shards
    # elect/broadcast over THIS axis while histogram/count psums keep
    # running over axis_name — the hybrid ('data','feature') 2D layout.
    # None preserves the one-axis world: feature_shard > 1 reuses
    # axis_name for the election (rows replicated, no histogram psum).
    feature_axis_name: Optional[str] = None
    # categorical split search (sorted-subset scan, feature_histogram.cpp:147);
    # False keeps every cat-related array at width 1 (static no-op)
    use_cat: bool = False
    cat_params: Optional[CatParams] = None
    # EFB bundle planes (bundling.py): the bundle_end operand routes bundled
    # split candidates through ops/split.py and their partitions through the
    # categorical-mask machinery (masks widen to B like use_cat)
    use_bundle: bool = False
    # forced splits (forcedsplits_filename JSON BFS,
    # serial_tree_learner.cpp:627): the first n_forced loop steps apply the
    # host-precomputed (leaf, feature, bin) splits instead of the best-gain
    # argmax; a negative-gain forced split aborts the remaining forced steps
    # (reference abort_last_forced_split) and normal growth resumes
    n_forced: int = 0
    # fuse the best-split scan into one Pallas kernel on the basic numeric
    # path (ops/pallas/split_scan.py — the CUDA FindBestSplitsForLeafKernel
    # shape); targets the per-split fixed cost, default off pending on-chip
    # measurement
    fused_split_scan: bool = False
    # CEGB (cost_effective_gradient_boosting.hpp): per-split data cost is
    # static; the per-feature coupled penalty arrives as a runtime operand
    use_cegb: bool = False
    cegb_split_penalty: float = 0.0
    # "seg": keep rows PHYSICALLY in leaf-segment order (packed 256B rows);
    # each split is a stable sort of the parent's contiguous window and each
    # histogram a contiguous DMA stream — no random gathers, which serialize
    # on TPU (~35ns/element measured; see ops/segpart.py);
    # "ordered": leaf-contiguous row permutation (the reference's
    # DataPartition index array, data_partition.hpp) with per-split index
    # gathers — O(parent segment) work but gather-bound on TPU;
    # "gather": leaf-id vector + per-split jnp.nonzero compaction; "full":
    # masked pass over all rows per split.
    hist_mode: str = "ordered"
    path_smooth: float = 0.0
    use_monotone: bool = False  # monotone_constraints
    # "basic": children bounded by the split midpoint (BasicLeafConstraints,
    # monotone_constraints.hpp:465).  "intermediate": bounds propagate to
    # CONTIGUOUS leaves across the split plane and affected leaves' cached
    # candidates are refreshed (IntermediateLeafConstraints, :516) — the
    # recursive GoUp/GoDownToFindLeavesToUpdate tree walk becomes a
    # vectorized box-adjacency test over per-leaf feature-range boxes
    # [L, F, 2]: leaf b is updated from new child c iff their boxes TOUCH
    # along exactly the one monotone feature separating them and intersect
    # along every other feature (equivalent: the walk ascends to the lowest
    # common ancestor — whose split feature is the unique separating one —
    # and the descent pruning keeps exactly the box-intersecting leaves).
    monotone_method: str = "basic"
    # candidate refreshes per split for bound-tightened leaves (intermediate
    # mode); leaves beyond the K stalest keep their cached candidate until
    # their next natural refresh (outputs are still clamped to the live
    # bounds, so monotonicity never depends on this)
    monotone_recompute_k: int = 8
    use_interaction: bool = False  # interaction_constraints
    feature_fraction_bynode: float = 1.0
    extra_trees: bool = False  # one random threshold per feature (USE_RAND)
    # frontier batching: split the top-K frontier leaves per compiled loop
    # step (K partitions over disjoint windows, one batched smaller-child
    # histogram pass, 2K candidate refreshes in one scan, and ONE psum per
    # collective kind under data-parallel).  Exactness by the prefix-commit
    # rule: with batch gains g1 >= ... >= gK, commit exactly the longest
    # prefix whose gi beats the running max gain of children created by
    # earlier batch members; uncommitted members are value-preserving no-ops
    # and their leaves stay in the frontier — the committed split sequence
    # is identical to serial leaf-wise growth.  1 = the serial fori_loop,
    # byte-identical to the pre-batching grower.
    leaf_batch: int = 1
    # fused Pallas grow step (ops/pallas/grow_step.py): partition + local
    # smaller-child election + histogram for all K frontier members in ONE
    # kernel launch, collapsing the per-step dispatch/fusion-boundary share.
    # Engages only on the seg fast path with NO axis_name (the data-parallel
    # election needs a mid-step psum of per-shard counts, so that mode keeps
    # the two-launch path); the XLA composition stays the fallback and
    # correctness oracle everywhere else.  boosting/gbdt.py resolves the
    # user-facing 'auto'/'on'/'off' config into this bool.
    grow_fused: bool = False
    # depth-scaled split-gain penalty on monotone features (reference
    # ComputeMonotoneSplitGainPenalty, monotone_constraints.hpp:357)
    monotone_penalty: float = 0.0
    # per-feature gain multipliers arrive via the feature_contri operand
    use_feature_contri: bool = False
    # measured collectives (obs/collectives): swap every psum/pmax/pmin site
    # for the timed/byte-counted wrapper.  Static on purpose — toggling it
    # must retrace, never silently reuse a trace without the callbacks.
    measure_collectives: bool = False
    # histogram accumulator (histogram engine v2): "auto" engages the
    # 2-digit int8 MXU accumulation by DEFAULT on the seg TPU path (true
    # f32 grads quantized once per iteration, seg.QMAX grid) with an f32
    # re-accumulate pass for near-tie split decisions; "bf16" keeps the
    # 3-term bf16 split everywhere; "int8" is "auto" without the opt-out.
    hist_acc: str = "auto"
    # relative gain gap below which the int8-default winner is considered
    # a near tie and its histogram is re-accumulated in f32 before the
    # structure decision (int8 grid step ~6e-5 relative; 1e-3 covers the
    # worst-case gain-domain amplification under gradient cancellation)
    near_tie_tol: float = 1e-3
    # double-buffered histogram collectives: under leaf_batch > 1 with a
    # histogram psum axis, split the [K, F, B, 3] frontier stack into two
    # half-window psums (sites "hist_db0"/"hist_db1") issued BETWEEN the
    # half-builds, so XLA's async all-reduce of buffer 0 overlaps the
    # histogram build of buffer 1.  Byte-identical to the single psum
    # (psum is elementwise per member; member order is preserved) and the
    # measured byte total is unchanged (obs.collectives sums every
    # psum/* site).  Structurally off at leaf_batch=1 — the serial loop
    # has nothing to overlap with.  gbdt resolves 'auto'/'on'/'off'.
    overlap_collectives: bool = False
    # vmapped model-fleet training (parallel/mesh.make_fleet_grow): name of
    # the vmap model axis.  Capacity-bucket switch indices are pmax'd over
    # this axis before the searchsorted: vmap's collective batching rule
    # reduces over the mapped dimension and returns an UNMAPPED value, so
    # the ladder switch lowers ONE shared branch for the whole fleet instead
    # of executing every branch (the select-all-branches rule for batched
    # switch indices — measured ~8x per-member at 64k rows).  Capacity only
    # pads, so the max member's bucket is value-preserving for the rest.
    fleet_axis_name: Optional[str] = None


def _hist_caps(n: int, full_range: bool = False) -> list:
    """Static capacity ladder for the smaller child: N/2, N/8, N/32, ...

    The smaller child of any split holds <= floor(parent/2) <= floor(N/2)
    rows, so the top capacity always fits; smaller buckets avoid paying the
    top capacity for deep (small) leaves.  ``full_range`` extends the top to
    N: under data-parallel sharding the GLOBALLY smaller child can still hold
    up to all local rows of one shard."""
    caps = []
    top = max(n, 1) if full_range else max(n // 2, 1)
    cap = 1 << max(0, (top - 1).bit_length())
    floor_cap = min(4096, cap)
    while cap > floor_cap:
        caps.append(cap)
        cap //= 2
    caps.append(cap)
    return caps  # descending


def _part_caps(n: int) -> list:
    """Static capacity ladder for PARENT segments in ordered mode: the root
    holds all n rows, so the top is pow2ceil(n); pow-2 steps down to 8192
    bound both the wasted work (<2x the true segment size) and the number of
    compiled partition branches."""
    caps = []
    cap = 1 << max(0, (max(n, 1) - 1).bit_length())
    floor_cap = min(8192, cap)
    while cap > floor_cap:
        caps.append(cap)
        cap //= 2
    caps.append(cap)
    return sorted(caps)  # ascending


class TreeArrays(NamedTuple):
    """SoA tree, mirroring the reference Tree (include/LightGBM/tree.h:497).

    Node child pointers use the reference convention: >=0 -> internal node
    index, negative -> ~leaf_index.
    Thresholds are in BIN space here; conversion to real-valued thresholds
    happens host-side at Tree materialization.
    """

    split_feature: jnp.ndarray  # [L-1] int32 (used-feature index)
    split_bin: jnp.ndarray  # [L-1] int32
    split_gain: jnp.ndarray  # [L-1] f32
    default_left: jnp.ndarray  # [L-1] bool
    left_child: jnp.ndarray  # [L-1] int32
    right_child: jnp.ndarray  # [L-1] int32
    internal_value: jnp.ndarray  # [L-1] f32 (raw output of the node)
    internal_weight: jnp.ndarray  # [L-1] f32 (sum hess)
    internal_count: jnp.ndarray  # [L-1] f32
    leaf_value: jnp.ndarray  # [L] f32 (raw, unshrunk)
    leaf_weight: jnp.ndarray  # [L] f32 (sum hess)
    leaf_count: jnp.ndarray  # [L] f32
    leaf_depth: jnp.ndarray  # [L] int32
    num_leaves: jnp.ndarray  # scalar int32
    # compiled grow-loop steps taken (serial: committed splits; batched: the
    # while_loop trip count) — the host derives the frontier-batch commit
    # rate (num_leaves-1)/(steps*K) from it to clamp leaf_batch adaptively
    grow_steps: jnp.ndarray  # scalar int32
    # committed split decisions that took the int8 near-tie f32 refine
    # (histogram engine v2); always 0 when int8 accumulation is off.  The
    # host derives hist/near_tie_refine_rate = refine_count / decisions
    # with decisions = 2*(num_leaves-1) + 1 (root + both children per split)
    refine_count: jnp.ndarray  # scalar int32
    split_is_cat: jnp.ndarray  # [L-1] bool
    cat_mask: jnp.ndarray  # [L-1, Bm] bool — bin goes left (Bm=1 if no cat)


class _State(NamedTuple):
    leaf_id: jnp.ndarray  # [N] (gather/full modes; empty in ordered mode)
    order: jnp.ndarray  # [N + maxcap] row permutation (ordered mode; else empty)
    leaf_begin: jnp.ndarray  # [L] segment start per leaf (ordered mode)
    leaf_nrows: jnp.ndarray  # [L] RAW row count per leaf (ordered mode)
    hist_buf: jnp.ndarray  # [L, F, B, 3]
    leaf_g: jnp.ndarray
    leaf_h: jnp.ndarray
    leaf_cnt: jnp.ndarray
    leaf_depth: jnp.ndarray
    leaf_parent: jnp.ndarray
    leaf_is_right: jnp.ndarray
    leaf_lb: jnp.ndarray  # [L] monotone output lower bound
    leaf_ub: jnp.ndarray  # [L] monotone output upper bound
    leaf_box: jnp.ndarray  # [L, F, 2] bin-space feature ranges (intermediate
    #                        monotone mode; [L, 0, 2] otherwise)
    leaf_allowed: jnp.ndarray  # [L, F] interaction-constraint feature mask
    cand: SplitCandidate  # arrays of shape [L]
    split_feature: jnp.ndarray
    split_bin: jnp.ndarray
    split_gain: jnp.ndarray
    default_left: jnp.ndarray
    split_is_cat: jnp.ndarray  # [L-1]
    node_cat_mask: jnp.ndarray  # [L-1, Bm]
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    internal_value: jnp.ndarray
    internal_weight: jnp.ndarray
    internal_count: jnp.ndarray
    num_leaves: jnp.ndarray
    done: jnp.ndarray
    forced_ok: jnp.ndarray  # still applying forced splits (n_forced > 0)
    cegb_used: jnp.ndarray  # [F] bool — feature bought (use_cegb)
    steps: jnp.ndarray  # scalar i32 — grow-loop steps (TreeArrays.grow_steps)
    refines: jnp.ndarray  # scalar i32 — committed near-tie f32 refines


def int8_acc_eligible(
    p: "GrowerParams", quantized: bool = False, monotone: bool = False
) -> bool:
    """Shared int8-accumulation gate (histogram engine v2).

    Every input is a static (GrowerParams fields, backend, interpret
    flag), so the SAME predicate serves both the trace-time engage
    decision inside ``grow_tree`` and the host-side ``hist/int8_engaged``
    telemetry gauge — a single source of truth instead of two copies that
    could drift.  Callers AND this with their own seg-path condition
    (``hist_mode == "seg"`` and a non-degenerate shape).
    """
    from .pallas import seg as _seg_mod

    if quantized or monotone:
        return False
    if p.hist_acc == "bf16" or p.axis_name is not None:
        return False
    if p.feature_shard > 1:
        # pure-feature mesh layout: axis_name is None but shards hold
        # feature slices, and the near-tie with_margin re-scan is not
        # plumbed through the feature-parallel election
        return False
    return jax.default_backend() == "tpu" or _seg_mod._INTERPRET


def live_plane_fraction(
    feature_mask, f: int, num_bins: int, n_forced: int = 0
) -> float:
    """Host-side mirror of ``grow_tree``'s ``seg_live`` plane-group mask.

    Returns the fraction of seg-histogram plane groups that stay live
    under the TREE-level feature mask (group 0 is always live; forced
    splits or a single group disable the skip -> 1.0).  Pure numpy on the
    already-host-resident mask, so the telemetry gauge
    ``hist/live_plane_skip_ratio`` = 1 - live_plane_fraction costs no
    device sync.
    """
    import numpy as np

    from .pallas.seg import hist_bpad, hist_group, hist_ngroups

    if n_forced > 0 or f <= 0:
        return 1.0
    gb = hist_group(f, hist_bpad(num_bins))
    ng = hist_ngroups(f, hist_bpad(num_bins))
    if ng <= 1:
        return 1.0
    fm = np.asarray(feature_mask).astype(bool)
    fm_pad = np.pad(fm, (0, ng * gb - f))
    live = fm_pad.reshape(ng, gb).any(axis=1)
    live[0] = True
    return float(live.sum()) / float(ng)


def voting_active(p: "GrowerParams", f: int) -> bool:
    """Voting-parallel engages only when the elected subset is actually
    smaller than F — below that, the dense psum is both exact and cheaper
    (the documented cutover: F <= 2*top_k aliases onto tree_learner=data)."""
    return (
        p.axis_name is not None and p.voting_top_k > 0 and f > 2 * p.voting_top_k
    )


def _adv_constrainers(box, boxes, mono, valid):
    """Which leaves bound a leaf with box ``box`` (advanced monotone mode).

    The reference finds constraining leaves by recursing up the tree and
    down opposite branches of monotone ancestor splits
    (AdvancedLeafConstraints::GoUpToFindConstrainingLeaves,
    monotone_constraints.hpp:1082).  The TPU formulation is a box test over
    all leaves at once: leaf b constrains this leaf iff the two boxes are
    ordered-DISJOINT along exactly ONE monotone feature and overlap along
    every other feature (points of the two leaves can then differ only in
    that monotone coordinate).

    box: [F, 2] bin-space box; boxes: [L, F, 2]; mono: [F] int8; valid: [L].
    Returns (lb_con [L], ub_con [L], ov [L, F])."""
    lo, hi = box[:, 0], box[:, 1]
    blo, bhi = boxes[:, :, 0], boxes[:, :, 1]
    ov = (blo <= hi[None, :]) & (lo[None, :] <= bhi)  # [L, F]
    nonov = ~ov
    one_nonov = nonov.sum(axis=1) == 1
    below = bhi < lo[None, :]  # leaf b strictly below this leaf along f
    above = blo > hi[None, :]
    mpos = (mono > 0)[None, :]
    mneg = (mono < 0)[None, :]
    lb_con = (
        one_nonov & valid
        & (nonov & ((below & mpos) | (above & mneg))).any(axis=1)
    )
    ub_con = (
        one_nonov & valid
        & (nonov & ((above & mpos) | (below & mneg))).any(axis=1)
    )
    return lb_con, ub_con, ov


def adv_scalar_bounds(box, boxes, outs, mono, valid):
    """Whole-box output bounds for one leaf from every constraining leaf's
    current output (the advanced analog of a recomputed BasicConstraint —
    RightToBasicConstraint/LeftToBasicConstraint after the cumulative
    update, monotone_constraints.hpp:286)."""
    lb_con, ub_con, _ = _adv_constrainers(box, boxes, mono, valid)
    lb = jnp.max(jnp.where(lb_con, outs, -jnp.inf))
    ub = jnp.min(jnp.where(ub_con, outs, jnp.inf))
    return lb, ub


def adv_planes(box, boxes, outs, mono, valid, b: int):
    """Per-THRESHOLD child bounds [F, B] for scanning one leaf (advanced
    monotone mode).

    Each constraining leaf bounds only the SLICE of the scan feature's bin
    axis where its box overlaps this leaf (reference: per-threshold
    FeatureMinOrMaxConstraints entries, monotone_constraints.hpp:99 +
    UpdateConstraints :871); when the scan feature IS the separating
    monotone feature, both children stay fully ordered against the
    constraining leaf, so the slice is the whole range.  Cumulative extrema
    over the bin axis then give, for every candidate threshold t, the bound
    on the left child (bins <= t) and right child (bins > t) — the
    reference's CumulativeFeatureConstraint (:146) as two cummax/cummin
    sweeps."""
    lb_con, ub_con, ov = _adv_constrainers(box, boxes, mono, valid)
    lo, hi = box[:, 0], box[:, 1]
    blo, bhi = boxes[:, :, 0], boxes[:, :, 1]
    s = jnp.where(ov, jnp.maximum(blo, lo[None, :]), lo[None, :])  # [L, F]
    e = jnp.where(ov, jnp.minimum(bhi, hi[None, :]), hi[None, :])
    bin_ids = jnp.arange(b, dtype=jnp.int32)[None, None, :]
    in_sl = (bin_ids >= s[:, :, None]) & (bin_ids <= e[:, :, None])  # [L,F,B]
    minp = jnp.max(
        jnp.where(in_sl & lb_con[:, None, None], outs[:, None, None], -jnp.inf),
        axis=0,
    )  # [F, B]
    maxp = jnp.min(
        jnp.where(in_sl & ub_con[:, None, None], outs[:, None, None], jnp.inf),
        axis=0,
    )
    lb_left = lax.cummax(minp, axis=1)
    ub_left = lax.cummin(maxp, axis=1)
    suf_min = lax.cummax(minp[:, ::-1], axis=1)[:, ::-1]  # extremum over [t:]
    suf_max = lax.cummin(maxp[:, ::-1], axis=1)[:, ::-1]
    ninf = jnp.full((minp.shape[0], 1), -jnp.inf)
    lb_right = jnp.concatenate([suf_min[:, 1:], ninf], axis=1)
    ub_right = jnp.concatenate([suf_max[:, 1:], -ninf], axis=1)
    return lb_left, ub_left, lb_right, ub_right


def _candidate_for_leaf(
    hist, g, h, c, num_bins, nan_bins, feature_mask, p: GrowerParams,
    monotone=None, lb=None, ub=None, parent_output=0.0, is_cat=None,
    cegb_penalty=None, rand_bins=None, adv=None, bundle_end=None,
    depth=None, feature_contri=None, with_margin=False,
):
    """Best split for one leaf.  ``hist`` is the GLOBAL (psummed) histogram
    normally; under voting-parallel it is the LOCAL histogram and only the
    globally-elected top-2k features' slices are psummed (PV-Tree,
    reference voting_parallel_tree_learner.cpp:152 GlobalVoting + :396
    elected-feature ReduceScatter)."""
    f = hist.shape[0]
    fused_ok = (
        # grow_fused implies the Pallas scan too: the fused grow step already
        # emits the stacked hist, so the scan is the only launch left to save
        (p.fused_split_scan or p.grow_fused)
        # basic numeric path only — every feature below changes the gain
        # math or the candidate set in ways the kernel does not implement
        and monotone is None
        and not p.use_cat
        and not p.use_cegb
        and not p.extra_trees
        and p.path_smooth == 0.0
        and p.max_delta_step == 0.0
        and lb is None and ub is None and adv is None
        and bundle_end is None
        and not voting_active(p, f)
        # the kernel unrolls one [16, B] x [B, B] matmul per feature into a
        # single Mosaic program — cap the program size / VMEM footprint and
        # fall back to best_split beyond it
        and f <= 64
        and p.max_bin <= 256
    )
    if fused_ok:
        from .pallas import split_scan as _ss

        on_tpu = jax.default_backend() == "tpu"
        if on_tpu or _ss._INTERPRET:
            return _ss.fused_best_split(
                hist, g, h, c, num_bins, nan_bins, feature_mask,
                lambda_l1=p.lambda_l1,
                lambda_l2=p.lambda_l2,
                min_data_in_leaf=p.min_data_in_leaf,
                min_sum_hessian_in_leaf=p.min_sum_hessian_in_leaf,
                min_gain_to_split=p.min_gain_to_split,
                feature_contri=feature_contri,
                interpret=not on_tpu,
                with_margin=with_margin,
            )
    use_mono_pen = monotone is not None and p.monotone_penalty > 0.0
    common = dict(
        lambda_l1=p.lambda_l1,
        lambda_l2=p.lambda_l2,
        min_data_in_leaf=p.min_data_in_leaf,
        min_sum_hessian_in_leaf=p.min_sum_hessian_in_leaf,
        min_gain_to_split=p.min_gain_to_split,
        max_delta_step=p.max_delta_step,
        path_smooth=p.path_smooth,
        leaf_lb=lb,
        leaf_ub=ub,
        parent_output=parent_output,
        cat_params=p.cat_params,
        cegb_split_penalty=p.cegb_split_penalty if p.use_cegb else 0.0,
        monotone_penalty=p.monotone_penalty if use_mono_pen else 0.0,
        leaf_depth=depth if use_mono_pen else None,
    )
    if not voting_active(p, f):
        return best_split(
            hist, g, h, c, num_bins, nan_bins, feature_mask,
            monotone=monotone,
            is_cat=is_cat if p.use_cat else None,
            cegb_penalty=cegb_penalty if p.use_cegb else None,
            rand_bins=rand_bins if p.extra_trees else None,
            adv_bounds=adv,
            bundle_end=bundle_end,
            feature_contri=feature_contri,
            with_margin=with_margin,
            **common,
        )
    if with_margin:
        # int8-default never engages under axis_name (grower gate), so the
        # voting path never needs the near-tie margin
        raise ValueError("with_margin is not supported on the voting path")
    # ---- PV-Tree election.  1) local per-feature best gains from the LOCAL
    # histogram (local parent stats derive from it: feature 0's bins cover
    # every local row)
    loc = hist[0].sum(axis=0)  # [3] local (g, h, cnt)
    _, gains_f = best_split(
        hist, loc[0], loc[1], loc[2], num_bins, nan_bins, feature_mask,
        monotone=monotone,
        is_cat=is_cat if p.use_cat else None,
        cegb_penalty=cegb_penalty if p.use_cegb else None,
        rand_bins=rand_bins if p.extra_trees else None,
        adv_bounds=adv,
        feature_contri=feature_contri,
        per_feature_gains=True,
        **common,
    )
    # 2) weighted gain (GlobalVoting: gain * leaf_count / mean_num_data) on
    # the local top-k only; pmax is the allgather-of-top-k + per-feature max
    nsh = timed_psum(
        jnp.float32(1.0), p.axis_name, site="counts",
        measure=p.measure_collectives,
    )
    w = loc[2] * nsh / jnp.maximum(c, 1.0)
    # gains_f is the per-feature IMPROVEMENT (split.gain in GlobalVoting,
    # voting_parallel_tree_learner.cpp:166) — best_split subtracts its own
    # (possibly constrained) local parent gain, so no shard-local offset
    # skews the cross-shard pmax merge
    wg = jnp.where(jnp.isfinite(gains_f) & (loc[2] > 0), gains_f * w, -jnp.inf)
    kth = lax.top_k(wg, min(p.voting_top_k, f))[0][-1]
    masked = jnp.where(wg >= kth, wg, -jnp.inf)
    glob = timed_pmax(
        masked, p.axis_name, site="elect", measure=p.measure_collectives
    )
    # 3) elect top-2k features globally; every shard elects the SAME ids
    _, ids = lax.top_k(glob, min(2 * p.voting_top_k, f))
    # 4) aggregate ONLY the elected slices ([2k, B, 3] over ICI instead of
    # [F, B, 3]) and scan them with GLOBAL parent stats
    sub = timed_psum(
        hist[ids], p.axis_name, site="hist", measure=p.measure_collectives
    )
    cand = best_split(
        sub, g, h, c, num_bins[ids], nan_bins[ids], feature_mask[ids],
        monotone=monotone[ids] if monotone is not None else None,
        is_cat=is_cat[ids] if (p.use_cat and is_cat is not None) else None,
        cegb_penalty=(
            cegb_penalty[ids] if (p.use_cegb and cegb_penalty is not None) else None
        ),
        rand_bins=(
            rand_bins[ids] if (p.extra_trees and rand_bins is not None) else None
        ),
        adv_bounds=(
            tuple(a[ids] for a in adv) if adv is not None else None
        ),
        feature_contri=(
            feature_contri[ids] if feature_contri is not None else None
        ),
        **common,
    )
    return cand._replace(feature=ids[cand.feature])


def _set_cand(
    cand: SplitCandidate, idx, new: SplitCandidate, gain_override=None, pred=None
) -> SplitCandidate:
    """Write `new` into row `idx`; with `pred` the write is value-preserving
    (old row back when pred is False) so it stays an in-place update with no
    conditional around it."""
    gain = new.gain if gain_override is None else gain_override
    vals = (gain, new.feature, new.bin, new.default_left, new.left_g, new.left_h,
            new.left_cnt, new.right_g, new.right_h, new.right_cnt,
            new.is_cat, new.cat_mask)
    if pred is None:
        return SplitCandidate(*[
            arr.at[idx].set(val) for arr, val in zip(cand, vals)
        ])
    return SplitCandidate(*[
        arr.at[idx].set(jnp.where(pred, val, arr[idx]))
        for arr, val in zip(cand, vals)
    ])


def _pack_tree_arrays_impl(ta: "TreeArrays"):
    """Pack a TreeArrays into (ints, floats) flat vectors so the host can
    fetch a whole tree in two transfers instead of ~14 (each transfer is a
    full round-trip on remote-attached TPUs)."""
    ints = jnp.concatenate(
        [
            ta.split_feature,
            ta.split_bin,
            ta.left_child,
            ta.right_child,
            ta.default_left.astype(jnp.int32),
            ta.leaf_depth,
            ta.num_leaves[None],
            ta.grow_steps[None],
            ta.refine_count[None],
            ta.split_is_cat.astype(jnp.int32),
            ta.cat_mask.astype(jnp.int32).reshape(-1),
        ]
    )
    floats = jnp.concatenate(
        [
            ta.split_gain,
            ta.internal_value,
            ta.internal_weight,
            ta.internal_count,
            ta.leaf_value,
            ta.leaf_weight,
            ta.leaf_count,
        ]
    )
    return ints, floats


# plain variant: the main training path still reads the TreeArrays after the
# fetch (leaf_value for the score update, split_* for the valid walk), so its
# buffers must survive the pack
pack_tree_arrays = instrumented_jit(
    _pack_tree_arrays_impl, label="pack_tree_arrays"
)
# donating variant for callers whose TreeArrays is dead after packing (the
# pipelined dispatcher hands the tree off and never touches it again): the
# ~14 per-tree buffers go back to the allocator instead of idling until GC
pack_tree_arrays_donated = instrumented_jit(
    _pack_tree_arrays_impl,
    label="pack_tree_arrays_donated",
    donate_argnums=(0,),
)


def unpack_tree_arrays(ints, floats, nn: int, L: int) -> "TreeArrays":
    """Decode host (ints, floats) from pack_tree_arrays into a TreeArrays."""
    io = [ints[i * nn : (i + 1) * nn] for i in range(4)]
    off = 4 * nn
    default_left = ints[off : off + nn].astype(bool)
    leaf_depth = ints[off + nn : off + nn + L]
    num_leaves = ints[off + nn + L]
    grow_steps = ints[off + nn + L + 1]
    refine_count = ints[off + nn + L + 2]
    off = off + nn + L + 3
    split_is_cat = ints[off : off + nn].astype(bool)
    off += nn
    bm = max(1, (len(ints) - off) // max(nn, 1))
    cat_mask = ints[off : off + nn * bm].astype(bool).reshape(nn, bm)
    fo = [floats[i * nn : (i + 1) * nn] for i in range(4)]
    off = 4 * nn
    fl = [floats[off + i * L : off + (i + 1) * L] for i in range(3)]
    return TreeArrays(
        split_feature=io[0],
        split_bin=io[1],
        split_gain=fo[0],
        default_left=default_left,
        left_child=io[2],
        right_child=io[3],
        internal_value=fo[1],
        internal_weight=fo[2],
        internal_count=fo[3],
        leaf_value=fl[0],
        leaf_weight=fl[1],
        leaf_count=fl[2],
        leaf_depth=leaf_depth,
        num_leaves=num_leaves,
        grow_steps=grow_steps,
        refine_count=refine_count,
        split_is_cat=split_is_cat,
        cat_mask=cat_mask,
    )


def fetch_tree_arrays(ta: "TreeArrays") -> "TreeArrays":
    """Pull a device TreeArrays to host as numpy with two transfers."""
    import numpy as np

    ints_d, floats_d = pack_tree_arrays(ta)
    nn = ta.split_feature.shape[0]  # L - 1
    L = ta.leaf_value.shape[0]
    return unpack_tree_arrays(np.asarray(ints_d), np.asarray(floats_d), nn, L)


# fleet variant: one vmapped pack of the whole [M, ...] stacked TreeArrays,
# so M models cost the SAME two host transfers as one (boosting/fleet.py)
pack_fleet_tree_arrays = instrumented_jit(
    jax.vmap(_pack_tree_arrays_impl), label="fleet/pack_tree_arrays"
)


def fetch_fleet_tree_arrays(ta: "TreeArrays"):
    """Pull a fleet-stacked [M, ...] device TreeArrays to host in two
    transfers; returns a list of M per-member host TreeArrays, each
    identical to what ``fetch_tree_arrays`` would return for that member's
    slice."""
    import numpy as np

    ints_d, floats_d = pack_fleet_tree_arrays(ta)
    m = ta.split_feature.shape[0]
    nn = ta.split_feature.shape[1]  # L - 1
    L = ta.leaf_value.shape[1]
    ints = np.asarray(ints_d)
    floats = np.asarray(floats_d)
    return [unpack_tree_arrays(ints[i], floats[i], nn, L) for i in range(m)]


@functools.partial(instrumented_jit, static_argnames=("params",))
def grow_tree(
    bins: jnp.ndarray,  # [N, F] int32
    grad: jnp.ndarray,  # [N] f32 (bagging/GOSS weights already applied)
    hess: jnp.ndarray,  # [N] f32
    count_mask: jnp.ndarray,  # [N] f32 — 1.0 for in-bag rows, 0.0 otherwise
    num_bins: jnp.ndarray,  # [F] int32
    nan_bins: jnp.ndarray,  # [F] int32 (-1 when the feature has no NaN bin)
    feature_mask: jnp.ndarray,  # [F] bool (feature_fraction sampling)
    params: GrowerParams,
    monotone: Optional[jnp.ndarray] = None,  # [F] int8 (use_monotone)
    interaction_sets: Optional[jnp.ndarray] = None,  # [S, F] bool
    rng: Optional[jax.Array] = None,  # for feature_fraction_bynode
    is_cat: Optional[jnp.ndarray] = None,  # [F] bool (use_cat)
    forced: Optional[Tuple] = None,  # (leaf, feat, bin, is_cat) arrays [n_forced]
    cegb_penalty: Optional[jnp.ndarray] = None,  # [F] f32 (use_cegb)
    cegb_used: Optional[jnp.ndarray] = None,  # [F] bool — already-bought features
    quant_scales=None,  # (g_scale, h_scale) for hist_method='pallas_int8'
    bundle_end: Optional[jnp.ndarray] = None,  # [F, B] i32 — EFB sub-range
    #   ends per plane bin (bundling.py / ops/split.py), -1 off-bundle
    feature_contri: Optional[jnp.ndarray] = None,  # [F] f32 gain multipliers
):
    """Grow one tree. Returns (TreeArrays, leaf_id[N])."""
    p = params
    n, f = bins.shape
    L, B = p.num_leaves, p.max_bin

    def _cap_size(x):
        # uniform capacity-bucket sizing across the fleet model axis
        # (see GrowerParams.fleet_axis_name)
        if not p.fleet_axis_name:
            return x
        return timed_pmax(
            x, p.fleet_axis_name, site="fleet_cap",
            measure=p.measure_collectives,
        )

    use_bundle = p.use_bundle and bundle_end is not None
    if not use_bundle:
        bundle_end = None
    else:
        # bundled split candidates reuse the categorical-mask partition and
        # the plain numeric gain path; modes that reinterpret the feature
        # axis or the candidate set per-feature are host-gated off
        # (boosting/gbdt.py raises first with friendlier messages)
        incompatible = [
            (p.n_forced > 0, "forced splits"),
            (p.extra_trees, "extra_trees"),
            (p.use_interaction, "interaction constraints"),
            (p.use_monotone and monotone is not None, "monotone constraints"),
            (p.use_cegb, "CEGB feature penalties"),
            (p.feature_shard > 1, "feature-parallel training"),
            (voting_active(p, bins.shape[1]), "voting-parallel training"),
            # bundle planes merge several features; a per-feature gain
            # multiplier has no well-defined plane-level analog
            (p.use_feature_contri and feature_contri is not None,
             "feature_contri"),
        ]
        for bad, what in incompatible:
            if bad:
                raise ValueError(
                    f"EFB feature bundling does not support {what}; "
                    "construct the Dataset with enable_bundle=false"
                )
    use_mono = p.use_monotone and monotone is not None
    use_inter_mono = use_mono and p.monotone_method in ("intermediate", "advanced")
    # advanced = intermediate propagation machinery + recomputed-from-boxes
    # bounds: per-threshold planes in the split scan, whole-box scalars at
    # commit (reference AdvancedLeafConstraints, monotone_constraints.hpp:858)
    use_adv_mono = use_mono and p.monotone_method == "advanced"
    mono_arr = monotone if use_mono else None

    def _leaf_outs_now(g_, h_, cnt_, parent_, ivals_, lb_, ub_):
        """Current would-be output of every leaf, matching the finalize
        sequence exactly (smoothing BEFORE the monotone clip) so advanced
        bound recomputation sees the same values the tree will emit."""
        out = leaf_output(g_, h_, p.lambda_l1, p.lambda_l2, p.max_delta_step)
        if p.path_smooth > 0.0:
            pouts = jnp.where(
                parent_ >= 0, ivals_[jnp.maximum(parent_, 0)], 0.0
            )
            ratio = cnt_ / p.path_smooth
            out = out * ratio / (ratio + 1.0) + pouts / (ratio + 1.0)
        return jnp.clip(out, lb_, ub_)
    use_cat = p.use_cat and is_cat is not None
    # cat-mask width (1 = static no-op); bundle splits ride the same mask
    # machinery, so bundling widens it too
    Bm = B if (use_cat or use_bundle) else 1
    is_cat_arr = is_cat if use_cat else None
    use_cegb = p.use_cegb and cegb_penalty is not None
    # per-feature gain multipliers (reference feature_contri /
    # feature_histogram.hpp:1445 — scales the IMPROVEMENT before the
    # cross-feature argmax)
    fc_arr = (
        feature_contri if (p.use_feature_contri and feature_contri is not None)
        else None
    )
    # monotone_penalty needs the splitting leaf's depth threaded into the scan
    use_mono_pen = (
        p.use_monotone and monotone is not None and p.monotone_penalty > 0.0
    )

    def _cegb_pen(used_mask):
        # coupled penalty only until the feature is first used in the MODEL
        # (cost_effective_gradient_boosting.hpp UpdateLeafBestSplits: buying
        # a feature unlocks it for every later candidate, same tree included)
        if not use_cegb:
            return None
        return jnp.where(used_mask, 0.0, cegb_penalty)

    def node_rand_bins(node_seed):
        """extra_trees: one uniform random candidate bin per feature for
        this node (reference rand.NextInt over the bin range)."""
        if not (p.extra_trees and rng is not None):
            return None
        key = jax.random.fold_in(jax.random.fold_in(rng, 7919), node_seed)
        num_ordered = num_bins - (nan_bins >= 0).astype(jnp.int32)
        hi = jnp.maximum(num_ordered - 1, 1)
        u = jax.random.uniform(key, (f,))
        return (u * hi).astype(jnp.int32)

    def _leaf_feature_mask(used_row):
        """Deterministic part of the per-node feature mask: bytree sampling +
        interaction constraints (allowed = union of constraint sets
        containing every feature used on the path)."""
        m = feature_mask
        if p.use_interaction and interaction_sets is not None:
            contains = (interaction_sets | ~used_row[None, :]).all(axis=1)  # [S]
            allowed = (contains[:, None] & interaction_sets).any(axis=0)  # [F]
            m = m & allowed
        return m

    def node_feature_mask(node_seed, used_row):
        """Per-node usable features: feature_fraction_bynode sampling
        (col_sampler.hpp by-node) + the deterministic mask."""
        m = _leaf_feature_mask(used_row)
        if p.feature_fraction_bynode < 1.0 and rng is not None:
            key = jax.random.fold_in(rng, node_seed)
            m = m & (jax.random.uniform(key, (f,)) < p.feature_fraction_bynode)
        return m

    use_seg = p.hist_mode == "seg" and f > 0 and n > 1
    use_ordered = p.hist_mode == "ordered" and f > 0 and n > 1
    use_gather = p.hist_mode == "gather" and f > 0 and n > 1
    # voting-parallel: histograms stay LOCAL; only elected slices are
    # psummed inside _candidate_for_leaf (scalar stats still psum globally)
    use_voting = voting_active(p, f)
    # feature-parallel: features sliced per shard over feat_axis; the only
    # feature-axis collective is the winner all-reduce (plus the
    # root-totals broadcast below).  One-axis world (feature_axis_name
    # None): feat_axis aliases axis_name, rows replicated, no histogram
    # psum.  Two-axis world (named mesh): election runs over the
    # 'feature' axis while rows stay sharded over axis_name, so histogram
    # and count psums keep running over the data axis (hybrid layout).
    feat_axis = (
        p.feature_axis_name
        if p.feature_axis_name is not None
        else (p.axis_name if p.feature_shard > 1 else None)
    )
    use_featpar = p.feature_shard > 1 and feat_axis is not None and f > 0
    # are rows partitioned across axis_name?  False when feature-parallel
    # reuses the one data axis for the election (rows replicated there)
    rows_sharded = p.axis_name is not None and (
        not use_featpar or feat_axis != p.axis_name
    )
    if use_featpar:
        if p.hist_mode not in ("gather", "full", "seg"):
            raise ValueError(
                "feature-parallel training needs hist_mode='gather', 'full' "
                "or 'seg' (ordered mode keeps no per-shard feature slices)"
            )
        if f % p.feature_shard:
            raise ValueError(
                f"feature count {f} must divide feature_shard="
                f"{p.feature_shard}"
            )
        if p.n_forced > 0:
            raise ValueError(
                "forced splits are not supported with feature-parallel "
                "training (histogram rows live on the owning shard)"
            )
        f_loc = f // p.feature_shard
        sh_lo = lax.axis_index(feat_axis) * f_loc

        def _fslice(arr, axis=0):
            return lax.dynamic_slice_in_dim(arr, sh_lo, f_loc, axis=axis)

        def _featpar_reduce(cand: SplitCandidate) -> SplitCandidate:
            """All-reduce the best candidate across feature shards
            (reference SyncUpGlobalBestSplit, feature_parallel_tree_learner
            .cpp:74 — here a pmax + owner-selected psum broadcast)."""
            gmax = timed_pmax(
                cand.gain, feat_axis, site="elect",
                measure=p.measure_collectives,
            )
            idx = lax.axis_index(feat_axis)
            owner = timed_pmin(
                jnp.where(cand.gain >= gmax, idx, p.feature_shard),
                feat_axis, site="elect", measure=p.measure_collectives,
            )
            mine = (idx == owner) & jnp.isfinite(gmax)

            def bc(x):
                xf = jnp.where(mine, x, jnp.zeros_like(x))
                return timed_psum(
                    xf, feat_axis, site="elect",
                    measure=p.measure_collectives,
                )

            return SplitCandidate(
                gain=gmax,
                feature=bc(cand.feature + sh_lo),
                bin=bc(cand.bin),
                default_left=bc(cand.default_left.astype(jnp.int32)) != 0,
                left_g=bc(cand.left_g),
                left_h=bc(cand.left_h),
                left_cnt=bc(cand.left_cnt),
                right_g=bc(cand.right_g),
                right_h=bc(cand.right_h),
                right_cnt=bc(cand.right_cnt),
                is_cat=bc(cand.is_cat.astype(jnp.int32)) != 0,
                cat_mask=bc(cand.cat_mask.astype(jnp.int32)) != 0,
            )
    else:
        f_loc = f

        def _fslice(arr, axis=0):
            return arr

    hist_axis = p.axis_name if (rows_sharded and not use_voting) else None
    # per-shard feature slice of the bin matrix (identity when not
    # feature-parallel) — used by the full-mode and root histograms
    bins_loc = _fslice(bins, axis=1) if f > 0 else bins

    # frontier batching scope: modes whose per-split bookkeeping is not
    # member-local (election/ownership state, cross-leaf bound propagation,
    # model-level CEGB purchases, path-dependent allowed-feature sets) keep
    # the serial loop.  boosting/gbdt.py clamps leaf_batch to 1 with a
    # warning before it gets here; a direct grow_tree caller gets the raise.
    leaf_k = max(1, min(p.leaf_batch, L - 1))
    if leaf_k > 1:
        unsupported = [
            (use_voting, "voting-parallel training"),
            (use_featpar, "feature-parallel training"),
            (use_cegb, "CEGB feature penalties"),
            (use_inter_mono, "intermediate/advanced monotone constraints"),
            (p.use_interaction and interaction_sets is not None,
             "interaction constraints"),
        ]
        for bad, what in unsupported:
            if bad:
                raise ValueError(
                    f"leaf_batch > 1 does not support {what}; set leaf_batch=1"
                )
    # double-buffered histogram collectives (see GrowerParams doc): only
    # meaningful when there IS a frontier stack to split and a histogram
    # psum axis to overlap against
    use_overlap = (
        p.overlap_collectives and leaf_k > 1 and hist_axis is not None
    )

    def cand_for_leaf(hist, g, h, c, fm, lb=None, ub=None, pout=0.0,
                      rand=None, cpen=None, adv=None, depth=None,
                      with_margin=False):
        with jax.named_scope("split_scan"):
            return _cand_for_leaf_impl(
                hist, g, h, c, fm, lb=lb, ub=ub, pout=pout,
                rand=rand, cpen=cpen, adv=adv, depth=depth,
                with_margin=with_margin,
            )

    def _cand_for_leaf_impl(hist, g, h, c, fm, lb=None, ub=None, pout=0.0,
                            rand=None, cpen=None, adv=None, depth=None,
                            with_margin=False):
        """Leaf candidate with the distributed-mode plumbing: per-feature
        operand slicing + winner all-reduce under feature-parallel; voting
        election happens inside _candidate_for_leaf."""
        if not use_featpar:
            return _candidate_for_leaf(
                hist, g, h, c, num_bins, nan_bins, fm, p,
                monotone=mono_arr, lb=lb, ub=ub, parent_output=pout,
                is_cat=is_cat_arr, cegb_penalty=cpen, rand_bins=rand,
                adv=adv, bundle_end=bundle_end, depth=depth,
                feature_contri=fc_arr, with_margin=with_margin,
            )
        if with_margin:
            # int8-default requires axis_name None, which excludes featpar
            raise ValueError(
                "with_margin is not supported under feature-parallel"
            )
        cand = _candidate_for_leaf(
            hist, g, h, c, _fslice(num_bins), _fslice(nan_bins),
            _fslice(fm), p,
            monotone=_fslice(mono_arr) if mono_arr is not None else None,
            lb=lb, ub=ub, parent_output=pout,
            is_cat=_fslice(is_cat_arr) if is_cat_arr is not None else None,
            cegb_penalty=_fslice(cpen) if cpen is not None else None,
            rand_bins=_fslice(rand) if rand is not None else None,
            adv=tuple(_fslice(a) for a in adv) if adv is not None else None,
            depth=depth,
            feature_contri=_fslice(fc_arr) if fc_arr is not None else None,
        )
        return _featpar_reduce(cand)

    if use_seg:
        from .pallas.seg import (
            MAX_WIDE_BIN,
            pack_rows,
            padded_rows,
            seg_hist,
            seg_hist_batch,
            stat_lanes,
        )
        from .segpart import (
            leaf_id_from_seg,
            leaf_of_positions,
            sort_partition,
            sort_partition_batch,
        )
        from .pallas.grow_step import fused_grow_step

        # bins byte-pack two features per i16 plane up to max_bin 256; wider
        # bin spaces use one u16 plane per feature (the reference's
        # DenseBin<uint16_t> upgrade, src/io/dense_bin.hpp:18)
        seg_wide = B > 256
        if B > MAX_WIDE_BIN:
            raise ValueError(
                f"hist_mode='seg' stores bins in u16 planes: max_bin "
                f"(padded to {B}) must be <= {MAX_WIDE_BIN}"
            )
        # feature-parallel seg: each shard packs ONLY its feature slice's bin
        # planes (rows replicated, histogram work /D); the winner feature's
        # go-left bits come from the owning shard via psum at partition time
        f_seg = f_loc if use_featpar else f
        if jax.default_backend() == "tpu":
            from .pallas.seg import seg_vmem_ok

            if not seg_vmem_ok(f_seg, B, use_cat or use_bundle):
                raise ValueError(
                    f"hist_mode='seg' at {f_seg} features x max_bin {B} "
                    "exceeds the histogram kernel's VMEM scratch budget — "
                    "use hist_mode='ordered' or a smaller max_bin"
                )
        n_pad_seg = padded_rows(n)
        seg0 = pack_rows(
            bins_loc, grad, hess, count_mask, n_pad_seg, wide=seg_wide
        )

        # explicit int8 opt-in (hist_method='pallas_int8' + quantized
        # gradients): integer grid accumulation, exact and ~2x throughput
        seg_qs = (
            quant_scales
            if (p.hist_method.startswith("pallas_int8") and quant_scales is not None)
            else None
        )
        # histogram engine v2: int8 2-digit accumulation is the DEFAULT on
        # the single-host seg TPU path — the true f32 grads are scaled onto
        # the QMAX grid once per iteration and every histogram launch runs
        # int8 x int8 -> i32 on the MXU; near-tie split decisions trigger an
        # f32 re-accumulate before the structure commit (with_margin below).
        # Excluded: explicit bf16 opt-out, quantized training (already on an
        # exact integer grid), any axis_name (distributed reduction semantics
        # and psum byte volumes stay untouched), monotone constraints (the
        # refine re-scan would need the full constraint plumbing).
        use_int8_acc = use_seg and int8_acc_eligible(
            p, quantized=seg_qs is not None, monotone=mono_arr is not None
        )
        if use_int8_acc:
            from .quantize import hist_acc_scales

            seg_qs = hist_acc_scales(grad, hess, count_mask)

        # live-plane skip: feature-plane groups with no usable feature under
        # the TREE-level deterministic mask (feature_fraction bytree / EFB
        # pruning) skip their one-hot build + matmul entirely.  Derived from
        # feature_mask ONLY — hist_buf rows are reused by descendants
        # (sibling subtraction, later parent reads) whose per-node bynode /
        # interaction masks differ, and those are subsets of feature_mask,
        # so masking at the tree level is the safe superset.  Group 0 stays
        # live (feature 0's plane carries the window totals); forced splits
        # may target masked-out features, so they disable the skip.
        seg_live = None
        if use_seg and not (p.n_forced > 0 and forced is not None):
            from .pallas.seg import hist_bpad, hist_group, hist_ngroups

            _gb = hist_group(f_seg, hist_bpad(B))
            _ng = hist_ngroups(f_seg, hist_bpad(B))
            if _ng > 1:
                fm_pad = jnp.pad(
                    _fslice(feature_mask).astype(bool),
                    (0, _ng * _gb - f_seg),
                )
                seg_live = (
                    fm_pad.reshape(_ng, _gb).any(axis=1)
                    .at[0].set(True).astype(jnp.int32)
                )

        def _seg_hist(seg_arr, start, cnt_rows, qs=seg_qs):
            hist = seg_hist(
                seg_arr,
                jnp.stack([start, cnt_rows]).astype(jnp.int32),
                f=f_seg,
                num_bins=B,
                n_pad=n_pad_seg,
                quant_scales=qs,
                wide=seg_wide,
                live=seg_live,
            )
            if hist_axis is not None:
                hist = timed_psum(
                    hist, hist_axis, site="hist",
                    measure=p.measure_collectives,
                )
            return hist

        # single-launch fused grow step: partition + smaller-child election +
        # histogram in one kernel.  Data-parallel (axis_name) keeps the
        # two-launch path — electing the smaller child there needs a psum of
        # per-shard partition counts BETWEEN partition and histogram, which a
        # single kernel launch cannot host.  Feature-parallel likewise: the
        # winner feature's go-left bits come from the owning shard via a
        # gl_vec psum at partition time.
        use_fused_grow = (
            p.grow_fused and p.axis_name is None and not use_featpar
        )
    else:
        use_fused_grow = False
        use_int8_acc = False
    if use_ordered or use_gather:
        caps = sorted(
            _hist_caps(
                n,
                full_range=rows_sharded,
            )
        )  # ascending child-histogram capacities
        caps_arr = jnp.asarray(caps, dtype=jnp.int32)
        # one zero padding row so fill indices contribute nothing
        bins_pad = jnp.concatenate([bins, jnp.zeros((1, f), bins.dtype)], axis=0)
        # feature-parallel: slice ONCE here — slicing inside the per-leaf
        # branch would gather rows at full F width first, negating the /D
        # data-volume split (gathers serialize on TPU)
        bins_pad_loc = _fslice(bins_pad, axis=1)
        grad_pad = jnp.concatenate([grad, jnp.zeros((1,), grad.dtype)])
        hess_pad = jnp.concatenate([hess, jnp.zeros((1,), hess.dtype)])
        mask_pad = jnp.concatenate([count_mask, jnp.zeros((1,), count_mask.dtype)])

    if use_gather:
        def _make_hist_branch(cap: int):
            # nonzero lives INSIDE the branch so its scatter is sized to the
            # branch capacity — deep (small) leaves compact into small buffers
            def branch(member):
                (idx,) = jnp.nonzero(member, size=cap, fill_value=n)
                return leaf_histogram(
                    bins_pad_loc[idx],
                    grad_pad[idx],
                    hess_pad[idx],
                    mask_pad[idx],
                    B,
                    method=p.hist_method,
                    axis_name=hist_axis,
                    quant_scales=quant_scales,
                    measure=p.measure_collectives,
                )

            return branch

        hist_branches = [_make_hist_branch(c) for c in caps]

        if leaf_k > 1:
            # frontier batching: each member compacts into ITS OWN capacity
            # bucket (pmax'd under data-parallel so every shard lowers the
            # same branch per member) — a shared max-over-members bucket was
            # measured 15% slower at the 1M-row bench shape because every
            # member paid the largest window's gather.  The inner histograms
            # run with axis_name=None and the [K, F, B, 3] stack psums ONCE
            # outside.
            def _make_hist_branch_loc(cap: int):
                def branch(member):  # [N] bool
                    (idx,) = jnp.nonzero(member, size=cap, fill_value=n)
                    return leaf_histogram(
                        bins_pad_loc[idx],
                        grad_pad[idx],
                        hess_pad[idx],
                        mask_pad[idx],
                        B,
                        method=p.hist_method,
                        axis_name=None,
                        quant_scales=quant_scales,
                    )

                return branch

            hist_branches_loc = [_make_hist_branch_loc(c) for c in caps]

    # transposed copy for contiguous per-feature column reads in the
    # partition step (bins is row-major; a column gather is strided)
    bins_t_cols = bins.T if f > 0 else bins.reshape(f, n)

    if use_ordered:
        # ---- ordered-partition machinery (reference DataPartition,
        # data_partition.hpp: one index array, leaves occupy contiguous
        # segments).  All per-split work is sized by a static capacity
        # bucket of the PARENT segment, never by N.
        pcaps = _part_caps(n)
        pcaps_arr = jnp.asarray(pcaps, dtype=jnp.int32)
        order_len = n + pcaps[-1]
        bins_t_pad = jnp.concatenate(
            [bins_t_cols, jnp.zeros((f, 1), bins.dtype)], axis=1
        )  # [F, n+1] — sentinel column for padded order entries

        def _make_part_branch(P: int):
            def branch(op):
                order, begin_l, cnt_l, feat, tbin, dl, cis, cmask = op
                idx = lax.dynamic_slice(order, (begin_l,), (P,))
                valid = jnp.arange(P, dtype=jnp.int32) < cnt_l
                featrow = lax.dynamic_slice_in_dim(bins_t_pad, feat, 1, axis=0)[0]
                colv = featrow[idx]
                nb = nan_bins[feat]
                gl = (colv <= tbin) | (dl & (nb >= 0) & (colv == nb))
                if use_cat or use_bundle:
                    gl = jnp.where(cis, cmask[jnp.minimum(colv, Bm - 1)], gl)
                gl = gl & valid
                gr = valid & ~gl
                nleft = jnp.sum(gl).astype(jnp.int32)
                # stable partition: left rows -> [0, nleft), right rows ->
                # [nleft, cnt_l), rows beyond the segment stay untouched
                pos_l = jnp.cumsum(gl) - 1
                pos_r = nleft + jnp.cumsum(gr) - 1
                pos = jnp.where(gl, pos_l, jnp.where(gr, pos_r, P)).astype(
                    jnp.int32
                )
                new_seg = (
                    jnp.full((P,), n, order.dtype).at[pos].set(idx, mode="drop")
                )
                new_seg = jnp.where(valid, new_seg, idx)
                order = lax.dynamic_update_slice(order, new_seg, (begin_l,))
                return order, nleft

            return branch

        part_branches = [_make_part_branch(c) for c in pcaps]

        def _make_hist_branch_ordered(C: int):
            def branch(op):
                order, start, child_cnt = op
                cidx = lax.dynamic_slice(order, (start,), (C,))
                vmask = (
                    jnp.arange(C, dtype=jnp.int32) < child_cnt
                ).astype(count_mask.dtype)
                return leaf_histogram(
                    bins_pad[cidx],
                    grad_pad[cidx],
                    hess_pad[cidx],
                    mask_pad[cidx] * vmask,
                    B,
                    method=p.hist_method,
                    axis_name=hist_axis,
                    quant_scales=quant_scales,
                    measure=p.measure_collectives,
                )

            return branch

        hist_branches_ordered = [_make_hist_branch_ordered(c) for c in caps]

        if leaf_k > 1:
            # batched analog: one (start, cnt) window at a time, each in ITS
            # OWN capacity bucket (per-member row counts are pmax'd under
            # data-parallel, so shards agree per member), inner hists local
            # (one stacked psum happens outside)
            def _make_hist_branch_ordered_loc(C: int):
                def branch(op):
                    order, start, child_cnt = op
                    cidx = lax.dynamic_slice(order, (start,), (C,))
                    vmask = (
                        jnp.arange(C, dtype=jnp.int32) < child_cnt
                    ).astype(count_mask.dtype)
                    return leaf_histogram(
                        bins_pad[cidx],
                        grad_pad[cidx],
                        hess_pad[cidx],
                        mask_pad[cidx] * vmask,
                        B,
                        method=p.hist_method,
                        axis_name=None,
                        quant_scales=quant_scales,
                    )

                return branch

            hist_branches_ordered_loc = [
                _make_hist_branch_ordered_loc(c) for c in caps
            ]

    cegb_used0 = (
        cegb_used
        if (use_cegb and cegb_used is not None)
        else jnp.zeros((max(f, 1),), bool)
    )
    with jax.named_scope("root_histogram"):  # jax.profiler trace labels
        if use_seg:
            hist0 = _seg_hist(seg0, jnp.int32(0), jnp.int32(n))
        else:
            hist0 = leaf_histogram(
                bins_loc, grad, hess, count_mask, B,
                method=p.hist_method,
                axis_name=hist_axis, quant_scales=quant_scales,
                measure=p.measure_collectives,
            )
    totals = hist0[0].sum(axis=0)  # every row lands in exactly one bin of feature 0
    if use_voting:
        totals = timed_psum(  # global root stats
            totals, p.axis_name, site="counts",
            measure=p.measure_collectives,
        )
    if use_featpar:
        # every shard derives totals from a DIFFERENT local feature's bins:
        # the values agree only up to summation order, and downstream gains
        # must be bit-identical across shards (out_specs declare the tree
        # replicated) — broadcast shard 0's totals
        idx0 = lax.axis_index(feat_axis) == 0
        totals = timed_psum(
            jnp.where(idx0, totals, jnp.zeros_like(totals)), feat_axis,
            site="counts", measure=p.measure_collectives,
        )
    root_used = jnp.zeros((f,), bool)
    neg_inf_s = jnp.float32(-jnp.inf)
    pos_inf_s = jnp.float32(jnp.inf)
    _root_kwargs = dict(
        lb=neg_inf_s if use_mono else None,
        ub=pos_inf_s if use_mono else None,
        pout=leaf_output(totals[0], totals[1], p.lambda_l1, p.lambda_l2, p.max_delta_step),
        cpen=_cegb_pen(cegb_used0),
        rand=node_rand_bins(0),
        depth=jnp.asarray(0, jnp.int32) if use_mono_pen else None,
    )
    cand0 = cand_for_leaf(
        hist0, totals[0], totals[1], totals[2],
        node_feature_mask(0, root_used),
        with_margin=use_int8_acc,
        **_root_kwargs,
    )
    if use_int8_acc:
        # near-tie f32 re-accumulate (histogram engine v2): when the root
        # winner's relative gain gap is inside near_tie_tol, redo the
        # window's histogram with direct f32 accumulation and re-scan
        # before the structure commit.  hist_buf keeps the INT8 histogram
        # (sibling subtraction must stay on one accumulation grid); the
        # refined copy exists only for this decision.
        cand0, margin0 = cand0
        near0 = margin0 < p.near_tie_tol
        hist0_f = _seg_hist(
            seg0, jnp.int32(0),
            jnp.where(near0, n, 0).astype(jnp.int32), qs=None,
        )
        cand0 = cand_for_leaf(
            jnp.where(near0, hist0_f, hist0),
            totals[0], totals[1], totals[2],
            node_feature_mask(0, root_used),
            **_root_kwargs,
        )

    neg_inf = jnp.full((L,), -jnp.inf, dtype=jnp.float32)
    cand = SplitCandidate(
        gain=neg_inf,
        feature=jnp.zeros((L,), jnp.int32),
        bin=jnp.zeros((L,), jnp.int32),
        default_left=jnp.zeros((L,), bool),
        left_g=jnp.zeros((L,), jnp.float32),
        left_h=jnp.zeros((L,), jnp.float32),
        left_cnt=jnp.zeros((L,), jnp.float32),
        right_g=jnp.zeros((L,), jnp.float32),
        right_h=jnp.zeros((L,), jnp.float32),
        right_cnt=jnp.zeros((L,), jnp.float32),
        is_cat=jnp.zeros((L,), bool),
        cat_mask=jnp.zeros((L, Bm), bool),
    )
    cand = _set_cand(cand, 0, cand0)

    if use_ordered:
        order0 = jnp.concatenate(
            [
                jnp.arange(n, dtype=jnp.int32),
                jnp.full((order_len - n,), n, jnp.int32),
            ]
        )
        leaf_begin0 = jnp.zeros((L,), jnp.int32)
        leaf_nrows0 = jnp.zeros((L,), jnp.int32).at[0].set(n)
        leaf_id0 = jnp.zeros((0,), jnp.int32)
    elif use_seg:
        # the order slot carries the packed segment matrix in seg mode
        order0 = seg0
        leaf_begin0 = jnp.zeros((L,), jnp.int32)
        leaf_nrows0 = jnp.zeros((L,), jnp.int32).at[0].set(n)
        leaf_id0 = jnp.zeros((0,), jnp.int32)
    else:
        order0 = jnp.zeros((0,), jnp.int32)
        leaf_begin0 = jnp.zeros((0,), jnp.int32)
        leaf_nrows0 = jnp.zeros((0,), jnp.int32)
        leaf_id0 = jnp.zeros((n,), jnp.int32)

    state = _State(
        leaf_id=leaf_id0,
        order=order0,
        leaf_begin=leaf_begin0,
        leaf_nrows=leaf_nrows0,
        hist_buf=jnp.zeros((L, f_loc, B, 3), jnp.float32).at[0].set(hist0),
        leaf_g=jnp.zeros((L,), jnp.float32).at[0].set(totals[0]),
        leaf_h=jnp.zeros((L,), jnp.float32).at[0].set(totals[1]),
        leaf_cnt=jnp.zeros((L,), jnp.float32).at[0].set(totals[2]),
        leaf_depth=jnp.zeros((L,), jnp.int32),
        leaf_parent=jnp.full((L,), -1, jnp.int32),
        leaf_is_right=jnp.zeros((L,), bool),
        leaf_lb=jnp.full((L,), -jnp.inf, jnp.float32),
        leaf_ub=jnp.full((L,), jnp.inf, jnp.float32),
        # root box spans the whole bin space of every feature
        leaf_box=(
            jnp.zeros((L, f, 2), jnp.int32).at[:, :, 1].set(B - 1)
            if use_inter_mono
            else jnp.zeros((L, 0, 2), jnp.int32)
        ),
        leaf_allowed=jnp.zeros((L, f), bool),  # stores USED features per path
        cand=cand,
        split_feature=jnp.zeros((L - 1,), jnp.int32),
        split_bin=jnp.zeros((L - 1,), jnp.int32),
        split_gain=jnp.zeros((L - 1,), jnp.float32),
        default_left=jnp.zeros((L - 1,), bool),
        split_is_cat=jnp.zeros((L - 1,), bool),
        node_cat_mask=jnp.zeros((L - 1, Bm), bool),
        # unused nodes point at leaf 0 (~0 = -1) so walking a trivial tree
        # (no splits recorded) terminates instead of spinning on node 0
        left_child=jnp.full((L - 1,), -1, jnp.int32),
        right_child=jnp.full((L - 1,), -1, jnp.int32),
        internal_value=jnp.zeros((L - 1,), jnp.float32),
        internal_weight=jnp.zeros((L - 1,), jnp.float32),
        internal_count=jnp.zeros((L - 1,), jnp.float32),
        num_leaves=jnp.asarray(1, jnp.int32),
        done=jnp.asarray(False),
        forced_ok=jnp.asarray(p.n_forced > 0),
        cegb_used=cegb_used0,
        steps=jnp.asarray(0, jnp.int32),
        refines=(
            near0.astype(jnp.int32)
            if use_int8_acc
            else jnp.asarray(0, jnp.int32)
        ),
    )

    node_ids = jnp.arange(L - 1, dtype=jnp.int32)
    use_forced_splits = p.n_forced > 0 and forced is not None

    def body(t, st: _State) -> _State:
        """One split step, fully UNCONDITIONAL.

        Round-2 measurement: threading the carry through ``lax.cond``/
        ``lax.switch`` branches makes XLA materialize defensive copies of
        every large array a modifying branch touches (~0.45 ms per copy at 1M
        rows — hist_buf is 22 MB at L=255, the packed seg matrix 0.3 GB at
        1M).  So instead of an `apply` branch, every state write below is
        value-preserving under ``~can_split`` (write the old value back at
        the same index), which keeps each update an in-place
        dynamic-update-slice on the loop carry with NO conditional in sight.
        A no-split step degenerates to zero-count partition/histogram work
        plus O(L·F·B) bookkeeping."""
        norm_leaf = jnp.argmax(st.cand.gain).astype(jnp.int32)

        # ---- local candidate for this step: the per-leaf best, or — for the
        # first n_forced steps — the host-provided forced split evaluated on
        # the leaf's histogram (reference ForceSplits,
        # serial_tree_learner.cpp:627 + GatherInfoForThreshold,
        # feature_histogram.hpp:475-595)
        if use_forced_splits:
            f_leaf_a, f_feat_a, f_bin_a, f_iscat_a = forced
            tf = jnp.minimum(t, p.n_forced - 1)
            is_f_step = (t < p.n_forced) & st.forced_ok
            f_leaf = f_leaf_a[tf]
            f_feat = f_feat_a[tf]
            f_bin = f_bin_a[tf]
            f_iscat = f_iscat_a[tf]
            hrow = st.hist_buf[f_leaf, f_feat]  # [B, 3]
            if use_voting:
                # voting keeps hist_buf LOCAL; a forced split needs the
                # global row for this one feature (tiny psum)
                hrow = timed_psum(
                    hrow, p.axis_name, site="hist",
                    measure=p.measure_collectives,
                )
            nbv = nan_bins[f_feat]
            has_nb = nbv >= 0
            nan_s = jnp.where(has_nb, hrow[jnp.maximum(nbv, 0)], 0.0)
            brow_ids = jnp.arange(B, dtype=jnp.int32)
            hrow_o = jnp.where(
                ((brow_ids == nbv) & has_nb)[:, None], 0.0, hrow
            )
            cumr = jnp.cumsum(hrow_o, axis=0)
            fpg, fph, fpc = (
                st.leaf_g[f_leaf],
                st.leaf_h[f_leaf],
                st.leaf_cnt[f_leaf],
            )
            # numeric: missing goes LEFT (GatherInfoForThresholdNumerical
            # sets default_left=true); categorical: one-hot on the bin
            f_left = jnp.where(f_iscat, hrow[f_bin], cumr[f_bin] + nan_s)
            f_lg, f_lh, f_lc = f_left[0], f_left[1], f_left[2]
            f_rg, f_rh, f_rc = fpg - f_lg, fph - f_lh, fpc - f_lc
            f_raw = leaf_gain(f_lg, f_lh, p.lambda_l1, p.lambda_l2) + leaf_gain(
                f_rg, f_rh, p.lambda_l1, p.lambda_l2
            )
            f_gain = (
                f_raw
                - leaf_gain(fpg, fph, p.lambda_l1, p.lambda_l2)
                - p.min_gain_to_split
            )
            use_forced = is_f_step & (f_gain > 0)
            # a failed forced split aborts the REMAINING forced steps
            # (abort_last_forced_split) and normal growth resumes
            forced_ok_next = st.forced_ok & (~is_f_step | use_forced)
            best_leaf = jnp.where(use_forced, f_leaf, norm_leaf)
        else:
            use_forced = None
            forced_ok_next = st.forced_ok
            best_leaf = norm_leaf

        l = best_leaf
        c_gain = st.cand.gain[l]
        c_feat = st.cand.feature[l]
        c_bin = st.cand.bin[l]
        c_dl = st.cand.default_left[l]
        c_cis = st.cand.is_cat[l]
        c_cmask = st.cand.cat_mask[l]
        c_lg, c_lh, c_lc = (
            st.cand.left_g[l],
            st.cand.left_h[l],
            st.cand.left_cnt[l],
        )
        c_rg, c_rh, c_rc = (
            st.cand.right_g[l],
            st.cand.right_h[l],
            st.cand.right_cnt[l],
        )
        if use_forced_splits:
            c_gain = jnp.where(use_forced, f_gain, c_gain)
            c_feat = jnp.where(use_forced, f_feat, c_feat)
            c_bin = jnp.where(use_forced, f_bin, c_bin)
            c_dl = jnp.where(use_forced, ~f_iscat, c_dl)
            c_cis = jnp.where(use_forced, f_iscat, c_cis)
            if use_cat:
                oh = jnp.arange(Bm, dtype=jnp.int32) == f_bin
                c_cmask = jnp.where(use_forced, oh, c_cmask)
            c_lg = jnp.where(use_forced, f_lg, c_lg)
            c_lh = jnp.where(use_forced, f_lh, c_lh)
            c_lc = jnp.where(use_forced, f_lc, c_lc)
            c_rg = jnp.where(use_forced, f_rg, c_rg)
            c_rh = jnp.where(use_forced, f_rh, c_rh)
            c_rc = jnp.where(use_forced, f_rc, c_rc)

        raw_can = c_gain > 0.0
        done = st.done | ~raw_can
        # once any step's best gain is <= 0 it stays <= 0 (cand is frozen),
        # but gate on st.done anyway so no stale candidate can ever re-split
        can_split = raw_can & ~st.done
        nl = (t + 1).astype(jnp.int32)
        feat, tbin, dl, cis, cmask = c_feat, c_bin, c_dl, c_cis, c_cmask

        # ---- partition rows of leaf l (reference DataPartition::Split) and
        # histogram the smaller child (serial_tree_learner.cpp:558-583), all
        # with a zero count when not splitting (value-level no-ops)
        if use_seg and use_fused_grow:
            # one kernel launch: partition + smaller-child election +
            # histogram (K=1 window) — dispatched as the XLA composition off
            # TPU, so structures are byte-identical to the two-launch path
            begin_l = st.leaf_begin[l]
            seg_cnt_l = jnp.where(can_split, st.leaf_nrows[l], 0)
            with jax.named_scope("fused_grow_step"):
                order, nl1, nr1, _cs1, _cc1, sm1 = fused_grow_step(
                    st.order,
                    begin_l[None],
                    seg_cnt_l[None],
                    feat[None],
                    tbin[None],
                    dl.astype(jnp.int32)[None],
                    nan_bins[feat][None],
                    cis.astype(jnp.int32)[None],
                    cmask.astype(jnp.float32)[None],
                    f=f_seg,
                    num_bins=B,
                    n_pad=n_pad_seg,
                    quant_scales=seg_qs,
                    wide=seg_wide,
                    live=seg_live,
                )
            nleft = nl1[0]
            nright = nr1[0]
            left_smaller = nleft <= nright
            sm = sm1[0]
            leaf_id = st.leaf_id
        elif use_seg:
            begin_l = st.leaf_begin[l]
            seg_cnt_l = jnp.where(can_split, st.leaf_nrows[l], 0)
            gl_vec = None
            if use_featpar:
                # only the OWNING shard holds the winner feature's bin
                # plane: it computes the go-left bits over the whole packed
                # matrix (segment order) and the psum broadcasts them —
                # every shard then applies the identical stable partition
                # (reference feature-parallel keeps partitioning local
                # because every machine holds all columns; here columns are
                # sliced, so the bits travel instead — O(N) f32 on ICI)
                from .segpart import _go_left as _seg_go_left

                owner = jnp.clip(feat // f_loc, 0, p.feature_shard - 1)
                lane = jnp.clip(feat - owner * f_loc, 0, max(f_loc - 1, 0))
                if seg_wide:
                    p16 = lax.dynamic_slice_in_dim(st.order, lane, 1, axis=0)[0]
                    colv = p16.astype(jnp.int32) & 0xFFFF
                else:
                    p16 = lax.dynamic_slice_in_dim(
                        st.order, lane >> 1, 1, axis=0
                    )[0]
                    colv = (
                        (p16.astype(jnp.int32) & 0xFFFF) >> ((lane & 1) * 8)
                    ) & 0xFF
                glv = _seg_go_left(
                    colv, tbin, dl.astype(jnp.int32), nan_bins[feat],
                    cis.astype(jnp.int32), cmask.astype(jnp.float32),
                )
                mine = lax.axis_index(feat_axis) == owner
                gl_vec = timed_psum(
                    jnp.where(mine, glv.astype(jnp.float32), 0.0),
                    feat_axis, site="partition",
                    measure=p.measure_collectives,
                )
            with jax.named_scope("partition"):
                order, nleft, nright = sort_partition(
                    st.order,
                    begin_l,
                    seg_cnt_l,
                    feat,
                    tbin,
                    dl.astype(jnp.int32),
                    nan_bins[feat],
                    cis.astype(jnp.int32),
                    cmask.astype(jnp.float32),
                    f=f_seg,
                    n_pad=n_pad_seg,
                    wide=seg_wide,
                    gl_vec=gl_vec,
                    fleet_axis_name=p.fleet_axis_name,
                    measure=p.measure_collectives,
                )
            if p.axis_name is not None:
                # global smaller-child choice (see gather-mode comment)
                left_smaller = timed_psum(
                    nleft, p.axis_name, site="counts",
                    measure=p.measure_collectives,
                ) <= timed_psum(
                    nright, p.axis_name, site="counts",
                    measure=p.measure_collectives,
                )
            else:
                left_smaller = nleft <= nright
            child_start = begin_l + jnp.where(left_smaller, 0, nleft)
            child_cnt = jnp.where(left_smaller, nleft, nright)
            with jax.named_scope("histogram"):
                sm = _seg_hist(order, child_start, child_cnt)
            leaf_id = st.leaf_id
        elif use_ordered:
            # stable in-place partition of the parent's contiguous
            # segment, sized by its capacity bucket — O(parent), not O(N)
            begin_l = st.leaf_begin[l]
            cnt_l = jnp.where(can_split, st.leaf_nrows[l], 0)
            pbucket = jnp.clip(
                jnp.searchsorted(pcaps_arr, _cap_size(cnt_l), side="left"),
                0,
                len(pcaps) - 1,
            ).astype(jnp.int32)
            with jax.named_scope("partition"):
                order, nleft = lax.switch(
                    pbucket,
                    part_branches,
                    (st.order, begin_l, cnt_l, feat, tbin, dl, cis, cmask),
                )
            nright = cnt_l - nleft
            leaf_id = st.leaf_id
            if p.axis_name is not None:
                # global smaller-child choice + pmax'd capacity bucket so
                # every shard histograms the SAME child (gather-mode comment)
                nleft_g = timed_psum(
                    nleft, p.axis_name, site="counts",
                    measure=p.measure_collectives,
                )
                nright_g = timed_psum(
                    nright, p.axis_name, site="counts",
                    measure=p.measure_collectives,
                )
                left_smaller = nleft_g <= nright_g
                tc = timed_pmax(
                    jnp.where(left_smaller, nleft, nright), p.axis_name,
                    site="counts", measure=p.measure_collectives,
                )
            else:
                left_smaller = nleft <= nright
                tc = jnp.minimum(nleft, nright)
            child_start = begin_l + jnp.where(left_smaller, 0, nleft)
            child_cnt = jnp.where(left_smaller, nleft, nright)
            cbucket = jnp.clip(
                jnp.searchsorted(caps_arr, _cap_size(tc), side="left"),
                0,
                len(caps) - 1,
            ).astype(jnp.int32)
            with jax.named_scope("histogram"):
                sm = lax.switch(
                    cbucket,
                    hist_branches_ordered,
                    (order, child_start, child_cnt),
                )
        elif use_gather:
            # gather mode: the child's rows are compacted into a
            # static-capacity buffer (jnp.nonzero with static size) and the
            # histogram runs over that buffer — the TPU formulation of the
            # reference's ordered_gradients gather (rows/tree ~ N log L)
            order = st.order
            begin_l = nleft = nright = jnp.int32(0)
            col = lax.dynamic_slice_in_dim(bins_t_cols, feat, 1, axis=0)[0]
            nb = nan_bins[feat]
            go_left = (col <= tbin) | (dl & (nb >= 0) & (col == nb))
            if use_cat or use_bundle:
                go_left = jnp.where(
                    cis, cmask[jnp.minimum(col, Bm - 1)], go_left
                )
            in_leaf = (st.leaf_id == l) & can_split
            leaf_id = jnp.where(in_leaf & ~go_left, nl, st.leaf_id)
            # smaller child by RAW row count (capacity bound); masked
            # (bagging) stats still flow through lc/rc
            rows_l = jnp.sum(in_leaf & go_left).astype(jnp.int32)
            rows_in = jnp.sum(in_leaf).astype(jnp.int32)
            rows_r = rows_in - rows_l
            if rows_sharded:
                # the smaller-child choice must be GLOBAL: if shards chose
                # locally, some would histogram the left child and others
                # the right, and the psum would mix the two (the reference
                # decides smaller/larger from global counts too,
                # serial_tree_learner.cpp:343).  The capacity bucket is the
                # max over shards of the chosen child's LOCAL rows — which
                # can exceed local_n/2 on imbalanced shards, hence the
                # full_range ladder.
                rows_l_g = timed_psum(
                    rows_l, p.axis_name, site="counts",
                    measure=p.measure_collectives,
                )
                rows_r_g = timed_psum(
                    rows_r, p.axis_name, site="counts",
                    measure=p.measure_collectives,
                )
                left_smaller = rows_l_g <= rows_r_g
                target = jnp.where(left_smaller, l, nl)
                tc = timed_pmax(
                    jnp.where(left_smaller, rows_l, rows_r), p.axis_name,
                    site="counts", measure=p.measure_collectives,
                )
            else:
                left_smaller = rows_l <= rows_r
                target = jnp.where(left_smaller, l, nl)
                tc = jnp.minimum(rows_l, rows_r)
            bucket = jnp.clip(
                jnp.searchsorted(caps_arr, _cap_size(tc), side="left"),
                0,
                len(caps) - 1,
            ).astype(jnp.int32)
            with jax.named_scope("histogram"):
                sm = lax.switch(bucket, hist_branches, (leaf_id == target) & can_split)
        else:
            order = st.order
            begin_l = nleft = nright = jnp.int32(0)
            leaf_id = st.leaf_id
            col = lax.dynamic_slice_in_dim(bins_t_cols, feat, 1, axis=0)[0]
            nb = nan_bins[feat]
            go_left = (col <= tbin) | (dl & (nb >= 0) & (col == nb))
            if use_cat or use_bundle:
                go_left = jnp.where(
                    cis, cmask[jnp.minimum(col, Bm - 1)], go_left
                )
            in_leaf = (st.leaf_id == l) & can_split
            leaf_id = jnp.where(in_leaf & ~go_left, nl, st.leaf_id)
            left_smaller = c_lc <= c_rc
            target = jnp.where(left_smaller, l, nl)
            mask = count_mask * (leaf_id == target) * can_split
            with jax.named_scope("histogram"):
                sm = leaf_histogram(
                    bins_loc, grad, hess, mask, B,
                    method=p.hist_method,
                    axis_name=hist_axis, quant_scales=quant_scales,
                    measure=p.measure_collectives,
                )

        def _set1(arr, idx, val):
            """Value-preserving write: old value back when not splitting."""
            return arr.at[idx].set(jnp.where(can_split, val, arr[idx]))

        with jax.named_scope("bookkeeping"):
            # ---- record node t (reference Tree::Split, src/io/tree.cpp:65)
            pg, ph, pc = st.leaf_g[l], st.leaf_h[l], st.leaf_cnt[l]
            left_child = _set1(st.left_child, t, -(l + 1))
            right_child = _set1(st.right_child, t, -(nl + 1))
            par = st.leaf_parent[l]
            is_r = st.leaf_is_right[l]
            fix = (node_ids == par) & (par >= 0) & can_split
            left_child = jnp.where(fix & ~is_r, t, left_child)
            right_child = jnp.where(fix & is_r, t, right_child)

            split_feature = _set1(st.split_feature, t, feat)
            split_bin = _set1(st.split_bin, t, tbin)
            split_gain = _set1(st.split_gain, t, c_gain + p.min_gain_to_split)
            default_left = _set1(st.default_left, t, dl)
            split_is_cat = _set1(st.split_is_cat, t, cis)
            node_cat_mask = _set1(st.node_cat_mask, t, cmask)
            internal_value = _set1(
                st.internal_value,
                t,
                leaf_output(pg, ph, p.lambda_l1, p.lambda_l2, p.max_delta_step),
            )
            internal_weight = _set1(st.internal_weight, t, ph)
            internal_count = _set1(st.internal_count, t, pc)

            # ---- leaf bookkeeping
            lg, lh, lc = c_lg, c_lh, c_lc
            rg, rh, rc = c_rg, c_rh, c_rc
            leaf_g = _set1(_set1(st.leaf_g, l, lg), nl, rg)
            leaf_h = _set1(_set1(st.leaf_h, l, lh), nl, rh)
            leaf_cnt = _set1(_set1(st.leaf_cnt, l, lc), nl, rc)
            d_new = st.leaf_depth[l] + 1
            leaf_depth = _set1(_set1(st.leaf_depth, l, d_new), nl, d_new)
            leaf_parent = _set1(_set1(st.leaf_parent, l, t), nl, t)
            leaf_is_right = _set1(
                _set1(st.leaf_is_right, l, jnp.asarray(False)), nl, jnp.asarray(True)
            )

            # ---- histograms: smaller child measured, sibling by subtraction
            parent_hist = st.hist_buf[l]
            other = parent_hist - sm
            left_hist = jnp.where(left_smaller, sm, other)
            right_hist = jnp.where(left_smaller, other, sm)
            hist_buf = st.hist_buf.at[l].set(
                jnp.where(can_split, left_hist, parent_hist)
            )
            hist_buf = hist_buf.at[nl].set(
                jnp.where(can_split, right_hist, st.hist_buf[nl])
            )

        # ---- monotone bounds for the children.
        # basic: split midpoint partitions the parent's output interval
        # (BasicLeafConstraints, monotone_constraints.hpp:465).
        # intermediate (:516): children are bounded by each other's ACTUAL
        # outputs, and the new outputs propagate to every CONTIGUOUS leaf
        # across the split plane — the reference's recursive GoUp/GoDown tree
        # walk is replaced by a vectorized box-adjacency test (see
        # GrowerParams.monotone_method); bound-tightened leaves get their
        # cached candidate refreshed below (top-K, = leaves_to_update_).
        leaf_lb, leaf_ub = st.leaf_lb, st.leaf_ub
        leaf_box = st.leaf_box
        lb_par, ub_par = st.leaf_lb[l], st.leaf_ub[l]
        inter_idxs = None
        inter_valid = None
        if use_mono:
            mc_f = mono_arr[feat]
            num_split = ~cis  # categorical splits carry no interval order
            if use_inter_mono:
                # children feature boxes (categorical: inherit unchanged)
                pbox = st.leaf_box[l]  # [F, 2]
                box_l = pbox.at[feat, 1].set(
                    jnp.where(num_split, tbin, pbox[feat, 1])
                )
                box_r = pbox.at[feat, 0].set(
                    jnp.where(num_split, tbin + 1, pbox[feat, 0])
                )
            if use_adv_mono:
                # advanced: children bounds RECOMPUTED from every existing
                # leaf's current output over the child's own box (reference
                # resets + GoUpToFindConstrainingLeaves rather than
                # inheriting the parent entry, monotone_constraints.hpp:396)
                # — the parent's old box overlaps both children everywhere,
                # so it never constrains its own children
                leaf_ids_p = jnp.arange(L, dtype=jnp.int32)
                valid_prev = (leaf_ids_p < st.num_leaves) & (leaf_ids_p != l)
                outs_prev = _leaf_outs_now(
                    st.leaf_g, st.leaf_h, st.leaf_cnt, st.leaf_parent,
                    st.internal_value, st.leaf_lb, st.leaf_ub,
                )
                lb_l0, ub_l0 = adv_scalar_bounds(
                    box_l, st.leaf_box, outs_prev, mono_arr, valid_prev
                )
                lb_r0, ub_r0 = adv_scalar_bounds(
                    box_r, st.leaf_box, outs_prev, mono_arr, valid_prev
                )
            else:
                lb_l0 = lb_r0 = lb_par
                ub_l0 = ub_r0 = ub_par
            out_l_c = jnp.clip(
                leaf_output(lg, lh, p.lambda_l1, p.lambda_l2, p.max_delta_step),
                lb_l0, ub_l0,
            )
            out_r_c = jnp.clip(
                leaf_output(rg, rh, p.lambda_l1, p.lambda_l2, p.max_delta_step),
                lb_r0, ub_r0,
            )
            if use_inter_mono:
                # sibling bounds from actual outputs
                # (UpdateConstraintsWithOutputs, :548)
                ub_l = jnp.where(
                    num_split & (mc_f > 0), jnp.minimum(ub_l0, out_r_c), ub_l0
                )
                lb_l = jnp.where(
                    num_split & (mc_f < 0), jnp.maximum(lb_l0, out_r_c), lb_l0
                )
                ub_r = jnp.where(
                    num_split & (mc_f < 0), jnp.minimum(ub_r0, out_l_c), ub_r0
                )
                lb_r = jnp.where(
                    num_split & (mc_f > 0), jnp.maximum(lb_r0, out_l_c), lb_r0
                )
                leaf_box = st.leaf_box.at[l].set(
                    jnp.where(can_split, box_l, pbox)
                )
                leaf_box = leaf_box.at[nl].set(
                    jnp.where(can_split, box_r, st.leaf_box[nl])
                )
                # propagate new outputs to contiguous leaves: b is updated
                # from child c iff their boxes TOUCH along a monotone feature
                # g and intersect along every other feature (== the leaves
                # GoDownToFindLeavesToUpdate reaches, :700)
                leaf_ids_r = jnp.arange(L, dtype=jnp.int32)
                valid_b = (
                    (leaf_ids_r <= t) & (leaf_ids_r != l)
                    & can_split & num_split
                )
                blo = leaf_box[:, :, 0]
                bhi = leaf_box[:, :, 1]
                mpos = (mono_arr > 0)[None, :]
                mneg = (mono_arr < 0)[None, :]

                def _prop(cbox, out_c, lb, ub, changed):
                    if use_adv_mono:
                        # advanced: ANY ordered-disjoint leaf across the
                        # monotone dim is constrained, not just the touching
                        # ones (the reference's recompute reaches every leaf
                        # of the opposite branches).  The set of leaves that
                        # RECEIVE a lower bound from c is, by symmetry,
                        # exactly the set that would impose an UPPER bound
                        # on c — reuse the one constrainer geometry
                        lbc, ubc = _adv_constrainers(
                            cbox, leaf_box, mono_arr, valid_b
                        )[:2]
                        need_lb, need_ub = ubc, lbc
                    else:
                        clo, chi = cbox[:, 0], cbox[:, 1]
                        ov = (blo <= chi[None, :]) & (clo[None, :] <= bhi)
                        others = (ov.sum(axis=1) == f - 1)[:, None] & ~ov
                        b_right = blo == chi[None, :] + 1  # b just right of c
                        b_left = bhi == clo[None, :] - 1
                        need_lb = (
                            others & ((b_right & mpos) | (b_left & mneg))
                        ).any(axis=1) & valid_b
                        need_ub = (
                            others & ((b_left & mpos) | (b_right & mneg))
                        ).any(axis=1) & valid_b
                    lb2 = jnp.where(need_lb, jnp.maximum(lb, out_c), lb)
                    ub2 = jnp.where(need_ub, jnp.minimum(ub, out_c), ub)
                    return lb2, ub2, changed | (lb2 > lb) | (ub2 < ub)

                ch0 = jnp.zeros((L,), bool)
                nlb, nub, ch0 = _prop(box_l, out_l_c, st.leaf_lb, st.leaf_ub, ch0)
                nlb, nub, ch0 = _prop(box_r, out_r_c, nlb, nub, ch0)
                leaf_lb = _set1(_set1(nlb, l, lb_l), nl, lb_r)
                leaf_ub = _set1(_set1(nub, l, ub_l), nl, ub_r)
                # leaves_to_update_: refresh the K highest-gain tightened
                # candidates (others keep stale-but-clamped candidates until
                # their next refresh; reference recomputes all, :717)
                inter_changed = ch0 & (st.cand.gain > 0)
                scores = jnp.where(inter_changed, st.cand.gain, -jnp.inf)
                top_vals, inter_idxs = lax.top_k(
                    scores, min(p.monotone_recompute_k, L)
                )
                inter_valid = top_vals > -jnp.inf
            else:
                mid = 0.5 * (out_l_c + out_r_c)
                lb_l = jnp.where(mc_f < 0, mid, lb_par)
                ub_l = jnp.where(mc_f > 0, mid, ub_par)
                lb_r = jnp.where(mc_f > 0, mid, lb_par)
                ub_r = jnp.where(mc_f < 0, mid, ub_par)
                leaf_lb = _set1(_set1(st.leaf_lb, l, lb_l), nl, lb_r)
                leaf_ub = _set1(_set1(st.leaf_ub, l, ub_l), nl, ub_r)
        else:
            lb_l = ub_l = lb_r = ub_r = None

        # path-used features for interaction constraints
        leaf_allowed = st.leaf_allowed
        if p.use_interaction:
            new_used = st.leaf_allowed[l] | (
                jnp.arange(f, dtype=jnp.int32) == feat
            )
            leaf_allowed = _set1(_set1(st.leaf_allowed, l, new_used), nl, new_used)
            used_l = used_r = new_used
        else:
            used_l = used_r = root_used

        cegb_used_new = (
            st.cegb_used.at[feat].set(st.cegb_used[feat] | can_split)
            if use_cegb
            else st.cegb_used
        )

        # ---- refresh split candidates for the two children in ONE vmapped
        # best_split (halves the per-split fixed scan cost vs two calls);
        # intermediate monotone mode appends the K bound-tightened leaves to
        # the same batch (the reference's leaves_to_update_ recompute)
        hist2 = jnp.stack([left_hist, right_hist])
        g2 = jnp.stack([lg, rg])
        h2 = jnp.stack([lh, rh])
        c2 = jnp.stack([lc, rc])
        fm2 = jnp.stack(
            [node_feature_mask(2 * t + 1, used_l),
             node_feature_mask(2 * t + 2, used_r)]
        )
        lb2 = ub2 = None
        if use_mono:
            lb2 = jnp.stack([lb_l, lb_r])
            ub2 = jnp.stack([ub_l, ub_r])
        seeds2 = jnp.stack([2 * t + 1, 2 * t + 2])
        if use_inter_mono:
            hist2 = jnp.concatenate([hist2, hist_buf[inter_idxs]])
            g2 = jnp.concatenate([g2, leaf_g[inter_idxs]])
            h2 = jnp.concatenate([h2, leaf_h[inter_idxs]])
            c2 = jnp.concatenate([c2, leaf_cnt[inter_idxs]])
            lb2 = jnp.concatenate([lb2, leaf_lb[inter_idxs]])
            ub2 = jnp.concatenate([ub2, leaf_ub[inter_idxs]])
            if p.use_interaction:
                # per-leaf usable features reconstructed from the path-used
                # sets; the feature_fraction_bynode random draw is NOT
                # replayed for refreshes (the original node seed is gone) —
                # refreshed candidates see the deterministic mask only
                fm_k = jax.vmap(_leaf_feature_mask)(leaf_allowed[inter_idxs])
            else:
                fm_k = jnp.broadcast_to(
                    feature_mask, (inter_idxs.shape[0], f)
                )
            fm2 = jnp.concatenate([fm2, fm_k])
            seeds2 = jnp.concatenate([seeds2, 7 * L + inter_idxs])
        po2 = leaf_output(g2, h2, p.lambda_l1, p.lambda_l2, p.max_delta_step)
        opt2 = []
        if use_mono:
            opt2 += [lb2, ub2]
        if use_adv_mono:
            # per-threshold bound planes for every leaf in the refresh batch,
            # from CURRENT leaf boxes/outputs (the advanced scan constraints)
            leaf_ids_b = jnp.arange(L, dtype=jnp.int32)
            nvalid = leaf_ids_b < (st.num_leaves + can_split.astype(jnp.int32))
            outs_new = _leaf_outs_now(
                leaf_g, leaf_h, leaf_cnt, leaf_parent,
                internal_value, leaf_lb, leaf_ub,
            )
            batch_idx = jnp.concatenate([jnp.stack([l, nl]), inter_idxs])
            adv2 = jax.vmap(
                lambda i: adv_planes(
                    leaf_box[i], leaf_box, outs_new, mono_arr,
                    nvalid & (leaf_ids_b != i), B,
                )
            )(batch_idx)
            opt2 += list(adv2)
        use_rand = p.extra_trees and rng is not None
        if use_rand:
            opt2 += [jax.vmap(node_rand_bins)(seeds2)]
        if use_mono_pen:
            depth2 = jnp.stack([d_new, d_new])
            if use_inter_mono:
                depth2 = jnp.concatenate([depth2, leaf_depth[inter_idxs]])
            opt2 += [depth2]
        cpen = _cegb_pen(cegb_used_new)

        def _child_cand(hist, g_, h_, c_, fm, po, *rest, wm=False):
            lbv = ubv = rbv = advv = dv = None
            i = 0
            if use_mono:
                lbv, ubv = rest[0], rest[1]
                i = 2
            if use_adv_mono:
                advv = tuple(rest[i:i + 4])
                i += 4
            if use_rand:
                rbv = rest[i]
                i += 1
            if use_mono_pen:
                dv = rest[i]
            return cand_for_leaf(
                hist, g_, h_, c_, fm,
                lb=lbv, ub=ubv, pout=po, cpen=cpen, rand=rbv, adv=advv,
                depth=dv, with_margin=wm,
            )

        with jax.named_scope("candidate_refresh"):
            if use_int8_acc:
                # near-tie f32 re-accumulate for the two refreshed children:
                # both child windows are re-histogrammed DIRECTLY (no
                # subtraction — the refine must not inherit the int8 grid
                # error it exists to remove), with cnt=0 for children whose
                # margin clears the tolerance (zero loop trips in-kernel)
                cand2, margins2 = jax.vmap(
                    functools.partial(_child_cand, wm=True)
                )(hist2, g2, h2, c2, fm2, po2, *opt2)
                near2 = margins2 < p.near_tie_tol  # [2]
                start2 = jnp.stack([begin_l, begin_l + nleft])
                cnt2 = jnp.where(near2, jnp.stack([nleft, nright]), 0)
                hist_rf = seg_hist_batch(
                    order,
                    jnp.stack([start2, cnt2], axis=1).astype(jnp.int32),
                    f=f_seg, num_bins=B, n_pad=n_pad_seg,
                    quant_scales=None, wide=seg_wide, live=seg_live,
                )
                hist2 = jnp.where(near2[:, None, None, None], hist_rf, hist2)
            cand2 = jax.vmap(_child_cand)(hist2, g2, h2, c2, fm2, po2, *opt2)
        cand_l = SplitCandidate(*[a[0] for a in cand2])
        cand_r = SplitCandidate(*[a[1] for a in cand2])
        depth_ok = (p.max_depth <= 0) | (d_new < p.max_depth)
        cand = _set_cand(
            st.cand, l, cand_l,
            jnp.where(depth_ok, cand_l.gain, -jnp.inf), pred=can_split,
        )
        cand = _set_cand(
            cand, nl, cand_r,
            jnp.where(depth_ok, cand_r.gain, -jnp.inf), pred=can_split,
        )
        if use_inter_mono:
            # write back the refreshed candidates of bound-tightened leaves
            for kk in range(inter_idxs.shape[0]):
                row = SplitCandidate(*[a[2 + kk] for a in cand2])
                cand = _set_cand(
                    cand, inter_idxs[kk], row,
                    pred=can_split & inter_valid[kk],
                )

        if use_ordered or use_seg:
            leaf_begin = _set1(st.leaf_begin, nl, begin_l + nleft)
            leaf_nrows = _set1(_set1(st.leaf_nrows, l, nleft), nl, nright)
        else:
            leaf_begin, leaf_nrows = st.leaf_begin, st.leaf_nrows

        return _State(
            leaf_id=leaf_id,
            order=order,
            leaf_begin=leaf_begin,
            leaf_nrows=leaf_nrows,
            hist_buf=hist_buf,
            leaf_g=leaf_g,
            leaf_h=leaf_h,
            leaf_cnt=leaf_cnt,
            leaf_depth=leaf_depth,
            leaf_parent=leaf_parent,
            leaf_is_right=leaf_is_right,
            leaf_lb=leaf_lb,
            leaf_ub=leaf_ub,
            leaf_box=leaf_box,
            leaf_allowed=leaf_allowed,
            cand=cand,
            split_feature=split_feature,
            split_bin=split_bin,
            split_gain=split_gain,
            default_left=default_left,
            split_is_cat=split_is_cat,
            node_cat_mask=node_cat_mask,
            left_child=left_child,
            right_child=right_child,
            internal_value=internal_value,
            internal_weight=internal_weight,
            internal_count=internal_count,
            num_leaves=st.num_leaves + can_split.astype(jnp.int32),
            done=done,
            forced_ok=forced_ok_next,
            cegb_used=cegb_used_new,
            # serial fori_loop runs L-1 trips regardless of early done;
            # count only productive steps so commit rate reads 1.0
            steps=st.steps + can_split.astype(jnp.int32),
            refines=st.refines + (
                jnp.sum(near2.astype(jnp.int32)) * can_split.astype(jnp.int32)
                if use_int8_acc
                else 0
            ),
        )

    def body_batched(st: _State) -> _State:
        """One frontier-batched step: split up to ``leaf_k`` leaves.

        The top-K frontier leaves by cached gain are partitioned over their
        DISJOINT row windows, the K smaller children are histogrammed in one
        batched pass (one [K, 2] count psum + one [K, F, B, 3] histogram
        psum under data-parallel), and all 2K child candidates refresh in
        one vmapped scan.  Exactness by the prefix-commit rule: member i
        commits iff every earlier member committed AND its gain strictly
        exceeds the best child gain any earlier member created — exactly
        when the serial argmax would have picked leaf i next.  Uncommitted
        members only reordered rows WITHIN their leaf's window (membership
        unchanged) and are value-preserving no-ops everywhere else; their
        leaves stay in the frontier for the next step.  Member 0 is the
        plain argmax, so every step with a positive best gain commits at
        least one split and the while loop terminates.  All commit
        decisions derive from psummed quantities, so every data-parallel
        shard runs the identical trip count."""
        K = leaf_k
        iota_k = jnp.arange(K, dtype=jnp.int32)
        base = st.num_leaves - 1  # node id taken by batch member 0
        t_k = base + iota_k  # node id per member under the prefix rule
        nl_k = t_k + 1  # new leaf index per member
        gains_k, l_k = lax.top_k(st.cand.gain, K)
        l_k = l_k.astype(jnp.int32)

        # ---- forced phase: commit exactly ONE (member 0) split per step so
        # the host-precomputed forced leaf numbering stays valid; a failed
        # forced split aborts the rest (abort_last_forced_split) and the
        # whole batch resumes normal growth the same step
        if use_forced_splits:
            f_leaf_a, f_feat_a, f_bin_a, f_iscat_a = forced
            tf = jnp.clip(base, 0, p.n_forced - 1)
            is_f_step = (base < p.n_forced) & st.forced_ok
            f_leaf = f_leaf_a[tf]
            f_feat = f_feat_a[tf]
            f_bin = f_bin_a[tf]
            f_iscat = f_iscat_a[tf]
            hrow = st.hist_buf[f_leaf, f_feat]  # [B, 3] (voting raises @K>1)
            nbv = nan_bins[f_feat]
            has_nb = nbv >= 0
            nan_s = jnp.where(has_nb, hrow[jnp.maximum(nbv, 0)], 0.0)
            brow_ids = jnp.arange(B, dtype=jnp.int32)
            hrow_o = jnp.where(
                ((brow_ids == nbv) & has_nb)[:, None], 0.0, hrow
            )
            cumr = jnp.cumsum(hrow_o, axis=0)
            fpg, fph, fpc = (
                st.leaf_g[f_leaf],
                st.leaf_h[f_leaf],
                st.leaf_cnt[f_leaf],
            )
            f_left = jnp.where(f_iscat, hrow[f_bin], cumr[f_bin] + nan_s)
            f_lg, f_lh, f_lc = f_left[0], f_left[1], f_left[2]
            f_rg, f_rh, f_rc = fpg - f_lg, fph - f_lh, fpc - f_lc
            f_raw = leaf_gain(f_lg, f_lh, p.lambda_l1, p.lambda_l2) + leaf_gain(
                f_rg, f_rh, p.lambda_l1, p.lambda_l2
            )
            f_gain = (
                f_raw
                - leaf_gain(fpg, fph, p.lambda_l1, p.lambda_l2)
                - p.min_gain_to_split
            )
            use_forced = is_f_step & (f_gain > 0)
            forced_ok_next = st.forced_ok & (~is_f_step | use_forced)
            l_k = l_k.at[0].set(jnp.where(use_forced, f_leaf, l_k[0]))
            gains_k = gains_k.at[0].set(
                jnp.where(use_forced, f_gain, gains_k[0])
            )
            forced_mask_k = jnp.where(
                use_forced, iota_k == 0, jnp.ones((K,), bool)
            )
        else:
            use_forced = None
            forced_ok_next = st.forced_ok
            forced_mask_k = jnp.ones((K,), bool)

        c_gain_k = gains_k
        c_feat_k = st.cand.feature[l_k]
        c_bin_k = st.cand.bin[l_k]
        c_dl_k = st.cand.default_left[l_k]
        c_cis_k = st.cand.is_cat[l_k]
        c_cmask_k = st.cand.cat_mask[l_k]  # [K, Bm]
        c_lg_k = st.cand.left_g[l_k]
        c_lh_k = st.cand.left_h[l_k]
        c_lc_k = st.cand.left_cnt[l_k]
        c_rg_k = st.cand.right_g[l_k]
        c_rh_k = st.cand.right_h[l_k]
        c_rc_k = st.cand.right_cnt[l_k]
        if use_forced_splits:
            def _f0(arr, val):
                return arr.at[0].set(jnp.where(use_forced, val, arr[0]))

            c_feat_k = _f0(c_feat_k, f_feat)
            c_bin_k = _f0(c_bin_k, f_bin)
            c_dl_k = _f0(c_dl_k, ~f_iscat)
            c_cis_k = _f0(c_cis_k, f_iscat)
            if use_cat:
                oh = jnp.arange(Bm, dtype=jnp.int32) == f_bin
                c_cmask_k = _f0(c_cmask_k, oh)
            c_lg_k = _f0(c_lg_k, f_lg)
            c_lh_k = _f0(c_lh_k, f_lh)
            c_lc_k = _f0(c_lc_k, f_lc)
            c_rg_k = _f0(c_rg_k, f_rg)
            c_rh_k = _f0(c_rh_k, f_rh)
            c_rc_k = _f0(c_rc_k, f_rc)

        pos_k = c_gain_k > 0.0
        # node ids are committed as a prefix, so member i's slot is statically
        # base + i; members past the node budget cannot commit
        room_k = t_k < (L - 1)
        active_k = pos_k & room_k & forced_mask_k & ~st.done
        done = st.done | ~pos_k[0]

        # ---- K partitions over disjoint windows + ONE batched smaller-child
        # histogram pass (speculative for members that end up uncommitted:
        # rows only move WITHIN their leaf's window, so nothing leaks)
        in_leaf_k = go_left_k = None
        if use_seg and use_fused_grow:
            # K partitions + K elections + K histograms in ONE kernel launch
            # (grid over members; windows are disjoint so members commute)
            begin_k = st.leaf_begin[l_k]
            cnt_k = jnp.where(active_k, st.leaf_nrows[l_k], 0)
            with jax.named_scope("fused_grow_step"):
                (
                    order,
                    nleft_k,
                    nright_k,
                    _cs_k,
                    _cc_k,
                    sm_k,
                ) = fused_grow_step(
                    st.order,
                    begin_k,
                    cnt_k,
                    c_feat_k,
                    c_bin_k,
                    c_dl_k.astype(jnp.int32),
                    nan_bins[c_feat_k],
                    c_cis_k.astype(jnp.int32),
                    c_cmask_k.astype(jnp.float32),
                    f=f_seg,
                    num_bins=B,
                    n_pad=n_pad_seg,
                    quant_scales=seg_qs,
                    wide=seg_wide,
                    live=seg_live,
                )
            left_smaller_k = nleft_k <= nright_k
        elif use_seg:
            begin_k = st.leaf_begin[l_k]
            cnt_k = jnp.where(active_k, st.leaf_nrows[l_k], 0)
            with jax.named_scope("partition"):
                order, nleft_k, nright_k = sort_partition_batch(
                    st.order,
                    begin_k,
                    cnt_k,
                    c_feat_k,
                    c_bin_k,
                    c_dl_k.astype(jnp.int32),
                    nan_bins[c_feat_k],
                    c_cis_k.astype(jnp.int32),
                    c_cmask_k.astype(jnp.float32),
                    f=f_seg,
                    n_pad=n_pad_seg,
                    wide=seg_wide,
                )
            if p.axis_name is not None:
                cnts_g = timed_psum(
                    jnp.stack([nleft_k, nright_k], axis=1), p.axis_name,
                    site="counts", measure=p.measure_collectives,
                )
                left_smaller_k = cnts_g[:, 0] <= cnts_g[:, 1]
            else:
                left_smaller_k = nleft_k <= nright_k
            child_start_k = begin_k + jnp.where(left_smaller_k, 0, nleft_k)
            child_cnt_k = jnp.where(left_smaller_k, nleft_k, nright_k)
            wins_k = jnp.stack([child_start_k, child_cnt_k], axis=1).astype(
                jnp.int32
            )

            def _seg_hist_win(w):
                return seg_hist_batch(
                    order,
                    w,
                    f=f_seg,
                    num_bins=B,
                    n_pad=n_pad_seg,
                    quant_scales=seg_qs,
                    wide=seg_wide,
                    live=seg_live,
                )

            if use_overlap:
                # double-buffered: build buffer 0, issue its psum, build
                # buffer 1 while the buffer-0 all-reduce is in flight
                kh = K // 2
                with jax.named_scope("histogram_db0"):
                    sm_a = _seg_hist_win(wins_k[:kh])
                sm_a = timed_psum(
                    sm_a, hist_axis, site="hist_db0",
                    measure=p.measure_collectives,
                )
                with jax.named_scope("histogram_db1"):
                    sm_b = _seg_hist_win(wins_k[kh:])
                sm_b = timed_psum(
                    sm_b, hist_axis, site="hist_db1",
                    measure=p.measure_collectives,
                )
                sm_k = jnp.concatenate([sm_a, sm_b], axis=0)
            else:
                with jax.named_scope("histogram"):
                    sm_k = _seg_hist_win(wins_k)
                if hist_axis is not None:
                    sm_k = timed_psum(
                        sm_k, hist_axis, site="hist",
                        measure=p.measure_collectives,
                    )
        elif use_ordered:
            begin_k = st.leaf_begin[l_k]
            cnt_k = jnp.where(active_k, st.leaf_nrows[l_k], 0)
            order = st.order
            with jax.named_scope("partition"):
                nleft_list = []
                for i in range(K):
                    pbucket_i = jnp.clip(
                        jnp.searchsorted(
                            pcaps_arr, _cap_size(cnt_k[i]), side="left"
                        ),
                        0,
                        len(pcaps) - 1,
                    ).astype(jnp.int32)
                    order, nleft_i = lax.switch(
                        pbucket_i,
                        part_branches,
                        (order, begin_k[i], cnt_k[i], c_feat_k[i], c_bin_k[i],
                         c_dl_k[i], c_cis_k[i], c_cmask_k[i]),
                    )
                    nleft_list.append(nleft_i)
                nleft_k = jnp.stack(nleft_list)
            nright_k = cnt_k - nleft_k
            if p.axis_name is not None:
                cnts_g = timed_psum(
                    jnp.stack([nleft_k, nright_k], axis=1), p.axis_name,
                    site="counts", measure=p.measure_collectives,
                )
                left_smaller_k = cnts_g[:, 0] <= cnts_g[:, 1]
                tc_k = timed_pmax(
                    jnp.where(left_smaller_k, nleft_k, nright_k), p.axis_name,
                    site="counts", measure=p.measure_collectives,
                )
            else:
                left_smaller_k = nleft_k <= nright_k
                tc_k = jnp.minimum(nleft_k, nright_k)
            child_start_k = begin_k + jnp.where(left_smaller_k, 0, nleft_k)
            child_cnt_k = jnp.where(left_smaller_k, nleft_k, nright_k)
            with jax.named_scope("histogram"):
                sm_list = []
                done_halves = []
                for i in range(K):
                    cbucket_i = jnp.clip(
                        jnp.searchsorted(
                            caps_arr, _cap_size(tc_k[i]), side="left"
                        ),
                        0,
                        len(caps) - 1,
                    ).astype(jnp.int32)
                    sm_list.append(
                        lax.switch(
                            cbucket_i,
                            hist_branches_ordered_loc,
                            (order, child_start_k[i], child_cnt_k[i]),
                        )
                    )
                    if use_overlap and i == K // 2 - 1:
                        # double-buffered: buffer 0's psum flies while the
                        # remaining members' histograms build
                        done_halves.append(timed_psum(
                            jnp.stack(sm_list), hist_axis, site="hist_db0",
                            measure=p.measure_collectives,
                        ))
                        sm_list = []
            if use_overlap:
                done_halves.append(timed_psum(
                    jnp.stack(sm_list), hist_axis, site="hist_db1",
                    measure=p.measure_collectives,
                ))
                sm_k = jnp.concatenate(done_halves, axis=0)
            else:
                sm_k = jnp.stack(sm_list)
                if hist_axis is not None:
                    sm_k = timed_psum(
                        sm_k, hist_axis, site="hist",
                        measure=p.measure_collectives,
                    )
        else:
            # gather / full: row membership per member, leaf_id writes
            # deferred to the commit decision below
            order = st.order
            begin_k = jnp.zeros((K,), jnp.int32)
            nleft_k = nright_k = jnp.zeros((K,), jnp.int32)
            gl_rows, in_rows = [], []
            for i in range(K):
                col = lax.dynamic_slice_in_dim(
                    bins_t_cols, c_feat_k[i], 1, axis=0
                )[0]
                nb = nan_bins[c_feat_k[i]]
                gli = (col <= c_bin_k[i]) | (
                    c_dl_k[i] & (nb >= 0) & (col == nb)
                )
                if use_cat or use_bundle:
                    gli = jnp.where(
                        c_cis_k[i], c_cmask_k[i][jnp.minimum(col, Bm - 1)], gli
                    )
                gl_rows.append(gli)
                in_rows.append((st.leaf_id == l_k[i]) & active_k[i])
            go_left_k = jnp.stack(gl_rows)  # [K, N]
            in_leaf_k = jnp.stack(in_rows)
            if use_gather:
                rows_l_k = jnp.sum(in_leaf_k & go_left_k, axis=1).astype(
                    jnp.int32
                )
                rows_r_k = (
                    jnp.sum(in_leaf_k, axis=1).astype(jnp.int32) - rows_l_k
                )
                if p.axis_name is not None:
                    cnts_g = timed_psum(
                        jnp.stack([rows_l_k, rows_r_k], axis=1), p.axis_name,
                        site="counts", measure=p.measure_collectives,
                    )
                    left_smaller_k = cnts_g[:, 0] <= cnts_g[:, 1]
                    tc_k = timed_pmax(
                        jnp.where(left_smaller_k, rows_l_k, rows_r_k),
                        p.axis_name, site="counts",
                        measure=p.measure_collectives,
                    )
                else:
                    left_smaller_k = rows_l_k <= rows_r_k
                    tc_k = jnp.minimum(rows_l_k, rows_r_k)
                member_k = in_leaf_k & jnp.where(
                    left_smaller_k[:, None], go_left_k, ~go_left_k
                )
                with jax.named_scope("histogram"):
                    sm_list = []
                    done_halves = []
                    for i in range(K):
                        bucket_i = jnp.clip(
                            jnp.searchsorted(
                                caps_arr, _cap_size(tc_k[i]), side="left"
                            ),
                            0,
                            len(caps) - 1,
                        ).astype(jnp.int32)
                        sm_list.append(
                            lax.switch(bucket_i, hist_branches_loc, member_k[i])
                        )
                        if use_overlap and i == K // 2 - 1:
                            done_halves.append(timed_psum(
                                jnp.stack(sm_list), hist_axis,
                                site="hist_db0",
                                measure=p.measure_collectives,
                            ))
                            sm_list = []
                    if use_overlap:
                        done_halves.append(timed_psum(
                            jnp.stack(sm_list), hist_axis, site="hist_db1",
                            measure=p.measure_collectives,
                        ))
                        sm_k = jnp.concatenate(done_halves, axis=0)
                    else:
                        sm_k = jnp.stack(sm_list)
            else:
                left_smaller_k = c_lc_k <= c_rc_k
                member_k = in_leaf_k & jnp.where(
                    left_smaller_k[:, None], go_left_k, ~go_left_k
                )

                def _full_hist(mask_win):
                    return jax.vmap(
                        lambda m: leaf_histogram(
                            bins_loc, grad, hess, m, B,
                            method=p.hist_method,
                            axis_name=None,
                            quant_scales=quant_scales,
                        )
                    )(mask_win)

                mask_k = count_mask[None, :] * member_k
                if use_overlap:
                    kh = K // 2
                    with jax.named_scope("histogram_db0"):
                        sm_a = _full_hist(mask_k[:kh])
                    sm_a = timed_psum(
                        sm_a, hist_axis, site="hist_db0",
                        measure=p.measure_collectives,
                    )
                    with jax.named_scope("histogram_db1"):
                        sm_b = _full_hist(mask_k[kh:])
                    sm_b = timed_psum(
                        sm_b, hist_axis, site="hist_db1",
                        measure=p.measure_collectives,
                    )
                    sm_k = jnp.concatenate([sm_a, sm_b], axis=0)
                else:
                    with jax.named_scope("histogram"):
                        sm_k = _full_hist(mask_k)
            if hist_axis is not None and not use_overlap:
                sm_k = timed_psum(
                    sm_k, hist_axis, site="hist",
                    measure=p.measure_collectives,
                )

        with jax.named_scope("bookkeeping"):
            # ---- sibling histograms by subtraction, per pair
            parent_hist_k = st.hist_buf[l_k]  # [K, f_loc, B, 3]
            other_k = parent_hist_k - sm_k
            ls4 = left_smaller_k[:, None, None, None]
            left_hist_k = jnp.where(ls4, sm_k, other_k)
            right_hist_k = jnp.where(ls4, other_k, sm_k)

        lg_k, lh_k, lc_k = c_lg_k, c_lh_k, c_lc_k
        rg_k, rh_k, rc_k = c_rg_k, c_rh_k, c_rc_k

        # basic monotone bounds are member-local: each member reads only its
        # OWN parent's interval, which no other batch member writes
        if use_mono:
            mc_f_k = mono_arr[c_feat_k]
            lb_par_k = st.leaf_lb[l_k]
            ub_par_k = st.leaf_ub[l_k]
            out_l_c = jnp.clip(
                leaf_output(
                    lg_k, lh_k, p.lambda_l1, p.lambda_l2, p.max_delta_step
                ),
                lb_par_k, ub_par_k,
            )
            out_r_c = jnp.clip(
                leaf_output(
                    rg_k, rh_k, p.lambda_l1, p.lambda_l2, p.max_delta_step
                ),
                lb_par_k, ub_par_k,
            )
            mid_k = 0.5 * (out_l_c + out_r_c)
            lb_l_k = jnp.where(mc_f_k < 0, mid_k, lb_par_k)
            ub_l_k = jnp.where(mc_f_k > 0, mid_k, ub_par_k)
            lb_r_k = jnp.where(mc_f_k > 0, mid_k, lb_par_k)
            ub_r_k = jnp.where(mc_f_k < 0, mid_k, ub_par_k)

        d_new_k = st.leaf_depth[l_k] + 1

        # ---- refresh all 2K child candidates in ONE vmapped scan
        hist2 = jnp.concatenate([left_hist_k, right_hist_k])
        g2 = jnp.concatenate([lg_k, rg_k])
        h2 = jnp.concatenate([lh_k, rh_k])
        c2 = jnp.concatenate([lc_k, rc_k])
        seeds2 = jnp.concatenate([2 * t_k + 1, 2 * t_k + 2])
        fm2 = jax.vmap(lambda s: node_feature_mask(s, root_used))(seeds2)
        po2 = leaf_output(g2, h2, p.lambda_l1, p.lambda_l2, p.max_delta_step)
        opt2 = []
        if use_mono:
            opt2 += [
                jnp.concatenate([lb_l_k, lb_r_k]),
                jnp.concatenate([ub_l_k, ub_r_k]),
            ]
        use_rand = p.extra_trees and rng is not None
        if use_rand:
            opt2 += [jax.vmap(node_rand_bins)(seeds2)]
        if use_mono_pen:
            opt2 += [jnp.concatenate([d_new_k, d_new_k])]

        def _child_cand_b(hist, g_, h_, c_, fm, po, *rest, wm=False):
            lbv = ubv = rbv = dv = None
            i = 0
            if use_mono:
                lbv, ubv = rest[0], rest[1]
                i = 2
            if use_rand:
                rbv = rest[i]
                i += 1
            if use_mono_pen:
                dv = rest[i]
            return cand_for_leaf(
                hist, g_, h_, c_, fm,
                lb=lbv, ub=ubv, pout=po, rand=rbv, depth=dv, with_margin=wm,
            )

        with jax.named_scope("candidate_refresh"):
            if use_int8_acc:
                # near-tie f32 re-accumulate over the 2K refreshed children
                # (one extra plane-tiled launch; cnt=0 rows cost nothing)
                cand2, margins2 = jax.vmap(
                    functools.partial(_child_cand_b, wm=True)
                )(hist2, g2, h2, c2, fm2, po2, *opt2)
                near2 = margins2 < p.near_tie_tol  # [2K]
                start2 = jnp.concatenate([begin_k, begin_k + nleft_k])
                cnt2 = jnp.where(
                    near2, jnp.concatenate([nleft_k, nright_k]), 0
                )
                hist_rf = seg_hist_batch(
                    order,
                    jnp.stack([start2, cnt2], axis=1).astype(jnp.int32),
                    f=f_seg, num_bins=B, n_pad=n_pad_seg,
                    quant_scales=None, wide=seg_wide, live=seg_live,
                )
                hist2 = jnp.where(near2[:, None, None, None], hist_rf, hist2)
            cand2 = jax.vmap(_child_cand_b)(hist2, g2, h2, c2, fm2, po2, *opt2)
        depth_ok_k = (p.max_depth <= 0) | (d_new_k < p.max_depth)
        gain_l_k = jnp.where(depth_ok_k, cand2.gain[:K], -jnp.inf)
        gain_r_k = jnp.where(depth_ok_k, cand2.gain[K:], -jnp.inf)
        child_best_k = jnp.maximum(gain_l_k, gain_r_k)

        # ---- prefix-commit: member i's gain must STRICTLY beat the best
        # child gain created by earlier members (a tie defers to the next
        # step, where the serial argmax tie-break applies natively), and all
        # earlier members must themselves have committed
        prev_max = lax.cummax(
            jnp.concatenate(
                [jnp.full((1,), -jnp.inf, jnp.float32), child_best_k[:-1]]
            )
        )
        ok_k = pos_k & room_k & forced_mask_k & (c_gain_k > prev_max)
        commit_k = lax.associative_scan(jnp.logical_and, ok_k) & ~st.done

        # ---- commit the prefix: value-preserving writes per member (node
        # ids t_i = base + i are disjoint, as are the members' leaf rows)
        def _setb(arr, idx, val, ok):
            return arr.at[idx].set(jnp.where(ok, val, arr[idx]))

        left_child = st.left_child
        right_child = st.right_child
        split_feature = st.split_feature
        split_bin = st.split_bin
        split_gain = st.split_gain
        default_left = st.default_left
        split_is_cat = st.split_is_cat
        node_cat_mask = st.node_cat_mask
        internal_value = st.internal_value
        internal_weight = st.internal_weight
        internal_count = st.internal_count
        leaf_g = st.leaf_g
        leaf_h = st.leaf_h
        leaf_cnt = st.leaf_cnt
        leaf_depth = st.leaf_depth
        leaf_parent = st.leaf_parent
        leaf_is_right = st.leaf_is_right
        leaf_lb, leaf_ub = st.leaf_lb, st.leaf_ub
        hist_buf = st.hist_buf
        cand = st.cand
        leaf_begin, leaf_nrows = st.leaf_begin, st.leaf_nrows
        leaf_id = st.leaf_id
        for i in range(K):
            ok = commit_k[i]
            t_i, l_i, nl_i = t_k[i], l_k[i], nl_k[i]
            left_child = _setb(left_child, t_i, -(l_i + 1), ok)
            right_child = _setb(right_child, t_i, -(nl_i + 1), ok)
            par = st.leaf_parent[l_i]  # no member writes another's leaf row
            is_r = st.leaf_is_right[l_i]
            fix = (node_ids == par) & (par >= 0) & ok
            left_child = jnp.where(fix & ~is_r, t_i, left_child)
            right_child = jnp.where(fix & is_r, t_i, right_child)
            split_feature = _setb(split_feature, t_i, c_feat_k[i], ok)
            split_bin = _setb(split_bin, t_i, c_bin_k[i], ok)
            split_gain = _setb(
                split_gain, t_i, c_gain_k[i] + p.min_gain_to_split, ok
            )
            default_left = _setb(default_left, t_i, c_dl_k[i], ok)
            split_is_cat = _setb(split_is_cat, t_i, c_cis_k[i], ok)
            node_cat_mask = _setb(node_cat_mask, t_i, c_cmask_k[i], ok)
            pg, ph, pc = st.leaf_g[l_i], st.leaf_h[l_i], st.leaf_cnt[l_i]
            internal_value = _setb(
                internal_value,
                t_i,
                leaf_output(pg, ph, p.lambda_l1, p.lambda_l2, p.max_delta_step),
                ok,
            )
            internal_weight = _setb(internal_weight, t_i, ph, ok)
            internal_count = _setb(internal_count, t_i, pc, ok)
            leaf_g = _setb(_setb(leaf_g, l_i, lg_k[i], ok), nl_i, rg_k[i], ok)
            leaf_h = _setb(_setb(leaf_h, l_i, lh_k[i], ok), nl_i, rh_k[i], ok)
            leaf_cnt = _setb(
                _setb(leaf_cnt, l_i, lc_k[i], ok), nl_i, rc_k[i], ok
            )
            leaf_depth = _setb(
                _setb(leaf_depth, l_i, d_new_k[i], ok), nl_i, d_new_k[i], ok
            )
            leaf_parent = _setb(
                _setb(leaf_parent, l_i, t_i, ok), nl_i, t_i, ok
            )
            leaf_is_right = _setb(
                _setb(leaf_is_right, l_i, jnp.asarray(False), ok),
                nl_i, jnp.asarray(True), ok,
            )
            hist_buf = hist_buf.at[l_i].set(
                jnp.where(ok, left_hist_k[i], hist_buf[l_i])
            )
            hist_buf = hist_buf.at[nl_i].set(
                jnp.where(ok, right_hist_k[i], hist_buf[nl_i])
            )
            if use_mono:
                leaf_lb = _setb(
                    _setb(leaf_lb, l_i, lb_l_k[i], ok), nl_i, lb_r_k[i], ok
                )
                leaf_ub = _setb(
                    _setb(leaf_ub, l_i, ub_l_k[i], ok), nl_i, ub_r_k[i], ok
                )
            cand_l_i = SplitCandidate(*[a[i] for a in cand2])
            cand_r_i = SplitCandidate(*[a[K + i] for a in cand2])
            cand = _set_cand(cand, l_i, cand_l_i, gain_l_k[i], pred=ok)
            cand = _set_cand(cand, nl_i, cand_r_i, gain_r_k[i], pred=ok)
            if use_ordered or use_seg:
                leaf_begin = _setb(
                    leaf_begin, nl_i, begin_k[i] + nleft_k[i], ok
                )
                leaf_nrows = _setb(
                    _setb(leaf_nrows, l_i, nleft_k[i], ok),
                    nl_i, nright_k[i], ok,
                )
        if in_leaf_k is not None:
            for i in range(K):
                leaf_id = jnp.where(
                    in_leaf_k[i] & ~go_left_k[i] & commit_k[i],
                    nl_k[i], leaf_id,
                )

        return _State(
            leaf_id=leaf_id,
            order=order,
            leaf_begin=leaf_begin,
            leaf_nrows=leaf_nrows,
            hist_buf=hist_buf,
            leaf_g=leaf_g,
            leaf_h=leaf_h,
            leaf_cnt=leaf_cnt,
            leaf_depth=leaf_depth,
            leaf_parent=leaf_parent,
            leaf_is_right=leaf_is_right,
            leaf_lb=leaf_lb,
            leaf_ub=leaf_ub,
            leaf_box=st.leaf_box,
            leaf_allowed=st.leaf_allowed,
            cand=cand,
            split_feature=split_feature,
            split_bin=split_bin,
            split_gain=split_gain,
            default_left=default_left,
            split_is_cat=split_is_cat,
            node_cat_mask=node_cat_mask,
            left_child=left_child,
            right_child=right_child,
            internal_value=internal_value,
            internal_weight=internal_weight,
            internal_count=internal_count,
            num_leaves=st.num_leaves + jnp.sum(commit_k.astype(jnp.int32)),
            done=done,
            forced_ok=forced_ok_next,
            cegb_used=st.cegb_used,
            steps=st.steps + 1,
            # near2 is [2K] ordered [K left, K right]; a refine counts only
            # when its member committed (speculative members re-run anyway)
            refines=st.refines + (
                jnp.sum(
                    jnp.where(
                        jnp.concatenate([commit_k, commit_k]),
                        near2.astype(jnp.int32),
                        0,
                    )
                )
                if use_int8_acc
                else 0
            ),
        )

    with jax.named_scope("leaf_loop"):
        if leaf_k > 1:
            # dynamic trip count: every step commits >= 1 split while any
            # leaf remains splittable, so this takes ceil((num_splits)/avg
            # batch) steps instead of a fixed L - 1
            state = lax.while_loop(
                lambda st: ~st.done & (st.num_leaves < L),
                body_batched,
                state,
            )
        else:
            state = lax.fori_loop(0, L - 1, body, state)

    leaf_idx = jnp.arange(L, dtype=jnp.int32)
    active = leaf_idx < state.num_leaves
    out = leaf_output(
        state.leaf_g, state.leaf_h, p.lambda_l1, p.lambda_l2, p.max_delta_step
    )
    if p.path_smooth > 0.0:
        parent_out = jnp.where(
            state.leaf_parent >= 0,
            state.internal_value[jnp.maximum(state.leaf_parent, 0)],
            0.0,
        )
        ratio = state.leaf_cnt / p.path_smooth
        out = out * ratio / (ratio + 1.0) + parent_out / (ratio + 1.0)
    if use_mono:
        out = jnp.clip(out, state.leaf_lb, state.leaf_ub)
    # a tree with no splits contributes NOTHING (reference outputs a const-0
    # tree and stops, gbdt.cpp:428) — zeroing here lets the booster dispatch
    # the score update before knowing num_leaves on host (async pipeline)
    leaf_value = jnp.where(active & (state.num_leaves > 1), out, 0.0)

    tree = TreeArrays(
        split_feature=state.split_feature,
        split_bin=state.split_bin,
        split_gain=state.split_gain,
        default_left=state.default_left,
        left_child=state.left_child,
        right_child=state.right_child,
        internal_value=state.internal_value,
        internal_weight=state.internal_weight,
        internal_count=state.internal_count,
        leaf_value=leaf_value.astype(jnp.float32),
        leaf_weight=state.leaf_h,
        leaf_count=state.leaf_cnt,
        leaf_depth=state.leaf_depth,
        num_leaves=state.num_leaves,
        grow_steps=state.steps,
        refine_count=state.refines,
        split_is_cat=state.split_is_cat,
        cat_mask=state.node_cat_mask,
    )

    if use_seg:
        # leaf per segment position (marker-cumsum) -> row order via ONE sort
        # (the scatter alternative serializes on TPU)
        lp = leaf_of_positions(
            state.leaf_begin, state.leaf_nrows, state.num_leaves, n
        )
        GLO = stat_lanes(f_seg, seg_wide)[0]
        ridx = (state.order[GLO + 5, :n].astype(jnp.int32) & 0xFFFF) | (
            (state.order[GLO + 6, :n].astype(jnp.int32) & 0xFFFF) << 16
        )
        return tree, leaf_id_from_seg(ridx, lp)
    if use_ordered:
        # reconstruct the per-row leaf-id vector from the segment layout in
        # ONE O(N) pass: mark each active leaf's segment start, turn starts
        # into segment ordinals via cumsum, map ordinals to leaf indices via
        # a begin-sorted permutation, scatter through the row permutation.
        # Zero-row leaves sort BEFORE the non-empty leaf sharing their begin
        # (key = 2*begin + (nrows>0)) so the cumsum lands on the real owner.
        begin_marks = jnp.where(active, state.leaf_begin, n)
        marker = (
            jnp.zeros((n,), jnp.int32).at[begin_marks].add(1, mode="drop")
        )
        sort_key = jnp.where(
            active,
            2 * state.leaf_begin + (state.leaf_nrows > 0).astype(jnp.int32),
            2 * n + 2,
        )
        sorted_leaf = jnp.argsort(sort_key, stable=True).astype(jnp.int32)
        seg_ord = jnp.clip(jnp.cumsum(marker) - 1, 0, L - 1)
        leaf_of_pos = sorted_leaf[seg_ord]
        leaf_id = (
            jnp.zeros((n,), jnp.int32)
            .at[state.order[:n]]
            .set(leaf_of_pos, mode="drop")
        )
        return tree, leaf_id
    return tree, state.leaf_id
