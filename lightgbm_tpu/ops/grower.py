"""Leaf-wise (best-first) tree grower, fully on-device under one jit.

Reference analogs: ``SerialTreeLearner::Train`` (src/treelearner/
serial_tree_learner.cpp:182 — BeforeTrain, then a loop of ConstructHistograms
-> FindBestSplitsFromHistograms -> argmax leaf -> Split) and the CUDA
single-GPU learner's per-leaf device loop (src/treelearner/cuda/
cuda_single_gpu_tree_learner.cpp:159-330).

TPU-native design decisions:
  * row->leaf membership is a dense ``leaf_id`` vector updated by a masked
    compare (the reference's DataPartition index-array shuffle and the CUDA
    prefix-sum scatter both become one vectorized ``where``);
  * the smaller child's histogram is built by a masked pass, the sibling by
    the parent-minus-smaller subtraction trick (serial_tree_learner.cpp:558);
  * per-leaf best splits are cached so each step only rescans the two leaves
    the previous split touched;
  * the whole num_leaves-1 loop is a ``lax.fori_loop`` with static shapes;
    a ``done`` flag makes trailing iterations no-ops once no leaf has a
    positive-gain split;
  * with ``axis_name`` set, histogram/root sums are ``psum``-ed across the
    data mesh axis — the data-parallel learner's ReduceScatter+Allreduce
    (src/treelearner/data_parallel_tree_learner.cpp) as XLA collectives.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from .histogram import leaf_histogram
from .split import SplitCandidate, best_split, leaf_output


@dataclasses.dataclass(frozen=True)
class GrowerParams:
    """Static (compile-time) training parameters for one tree."""

    num_leaves: int
    max_bin: int  # B: padded bin-axis size of the histogram
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    max_delta_step: float = 0.0
    hist_method: str = "auto"
    axis_name: Optional[str] = None
    # "gather": compact the smaller child's rows into a static-capacity
    # buffer before the histogram pass (rows touched ~ N*log L per tree,
    # the reference's ordered_gradients complexity); "full": masked pass
    # over all rows per split (rows touched ~ N*L).
    hist_mode: str = "gather"


def _hist_caps(n: int) -> list:
    """Static capacity ladder for the smaller child: N/2, N/8, N/32, ...

    The smaller child of any split holds <= floor(parent/2) <= floor(N/2)
    rows, so the top capacity always fits; smaller buckets avoid paying the
    top capacity for deep (small) leaves."""
    caps = []
    cap = 1 << max(0, (max(n // 2, 1) - 1).bit_length())
    floor_cap = min(4096, cap)
    while cap > floor_cap:
        caps.append(cap)
        cap //= 4
    caps.append(cap)
    return caps  # descending


class TreeArrays(NamedTuple):
    """SoA tree, mirroring the reference Tree (include/LightGBM/tree.h:497).

    Node child pointers use the reference convention: >=0 -> internal node
    index, negative -> ~leaf_index.
    Thresholds are in BIN space here; conversion to real-valued thresholds
    happens host-side at Tree materialization.
    """

    split_feature: jnp.ndarray  # [L-1] int32 (used-feature index)
    split_bin: jnp.ndarray  # [L-1] int32
    split_gain: jnp.ndarray  # [L-1] f32
    default_left: jnp.ndarray  # [L-1] bool
    left_child: jnp.ndarray  # [L-1] int32
    right_child: jnp.ndarray  # [L-1] int32
    internal_value: jnp.ndarray  # [L-1] f32 (raw output of the node)
    internal_weight: jnp.ndarray  # [L-1] f32 (sum hess)
    internal_count: jnp.ndarray  # [L-1] f32
    leaf_value: jnp.ndarray  # [L] f32 (raw, unshrunk)
    leaf_weight: jnp.ndarray  # [L] f32 (sum hess)
    leaf_count: jnp.ndarray  # [L] f32
    leaf_depth: jnp.ndarray  # [L] int32
    num_leaves: jnp.ndarray  # scalar int32


class _State(NamedTuple):
    leaf_id: jnp.ndarray
    hist_buf: jnp.ndarray  # [L, F, B, 3]
    leaf_g: jnp.ndarray
    leaf_h: jnp.ndarray
    leaf_cnt: jnp.ndarray
    leaf_depth: jnp.ndarray
    leaf_parent: jnp.ndarray
    leaf_is_right: jnp.ndarray
    cand: SplitCandidate  # arrays of shape [L]
    split_feature: jnp.ndarray
    split_bin: jnp.ndarray
    split_gain: jnp.ndarray
    default_left: jnp.ndarray
    left_child: jnp.ndarray
    right_child: jnp.ndarray
    internal_value: jnp.ndarray
    internal_weight: jnp.ndarray
    internal_count: jnp.ndarray
    num_leaves: jnp.ndarray
    done: jnp.ndarray


def _candidate_for_leaf(hist, g, h, c, num_bins, nan_bins, feature_mask, p: GrowerParams):
    return best_split(
        hist,
        g,
        h,
        c,
        num_bins,
        nan_bins,
        feature_mask,
        lambda_l1=p.lambda_l1,
        lambda_l2=p.lambda_l2,
        min_data_in_leaf=p.min_data_in_leaf,
        min_sum_hessian_in_leaf=p.min_sum_hessian_in_leaf,
        min_gain_to_split=p.min_gain_to_split,
        max_delta_step=p.max_delta_step,
    )


def _set_cand(cand: SplitCandidate, idx, new: SplitCandidate, gain_override=None) -> SplitCandidate:
    gain = new.gain if gain_override is None else gain_override
    return SplitCandidate(*[
        arr.at[idx].set(val)
        for arr, val in zip(
            cand,
            (gain, new.feature, new.bin, new.default_left, new.left_g, new.left_h,
             new.left_cnt, new.right_g, new.right_h, new.right_cnt),
        )
    ])


@functools.partial(jax.jit, static_argnames=("params",))
def grow_tree(
    bins: jnp.ndarray,  # [N, F] int32
    grad: jnp.ndarray,  # [N] f32 (bagging/GOSS weights already applied)
    hess: jnp.ndarray,  # [N] f32
    count_mask: jnp.ndarray,  # [N] f32 — 1.0 for in-bag rows, 0.0 otherwise
    num_bins: jnp.ndarray,  # [F] int32
    nan_bins: jnp.ndarray,  # [F] int32 (-1 when the feature has no NaN bin)
    feature_mask: jnp.ndarray,  # [F] bool (feature_fraction sampling)
    params: GrowerParams,
):
    """Grow one tree. Returns (TreeArrays, leaf_id[N])."""
    p = params
    n, f = bins.shape
    L, B = p.num_leaves, p.max_bin

    use_gather = p.hist_mode == "gather" and f > 0 and n > 1
    if use_gather:
        caps = sorted(_hist_caps(n))  # ascending
        caps_arr = jnp.asarray(caps, dtype=jnp.int32)
        cap0 = caps[-1]
        # one zero padding row so fill indices contribute nothing
        bins_pad = jnp.concatenate([bins, jnp.zeros((1, f), bins.dtype)], axis=0)
        grad_pad = jnp.concatenate([grad, jnp.zeros((1,), grad.dtype)])
        hess_pad = jnp.concatenate([hess, jnp.zeros((1,), hess.dtype)])
        mask_pad = jnp.concatenate([count_mask, jnp.zeros((1,), count_mask.dtype)])

        def _make_hist_branch(cap: int):
            def branch(idx):
                sub = idx[:cap]
                return leaf_histogram(
                    bins_pad[sub],
                    grad_pad[sub],
                    hess_pad[sub],
                    mask_pad[sub],
                    B,
                    method=p.hist_method,
                    axis_name=p.axis_name,
                )

            return branch

        hist_branches = [_make_hist_branch(c) for c in caps]

    hist0 = leaf_histogram(
        bins, grad, hess, count_mask, B, method=p.hist_method, axis_name=p.axis_name
    )
    totals = hist0[0].sum(axis=0)  # every row lands in exactly one bin of feature 0
    cand0 = _candidate_for_leaf(
        hist0, totals[0], totals[1], totals[2], num_bins, nan_bins, feature_mask, p
    )

    neg_inf = jnp.full((L,), -jnp.inf, dtype=jnp.float32)
    cand = SplitCandidate(
        gain=neg_inf,
        feature=jnp.zeros((L,), jnp.int32),
        bin=jnp.zeros((L,), jnp.int32),
        default_left=jnp.zeros((L,), bool),
        left_g=jnp.zeros((L,), jnp.float32),
        left_h=jnp.zeros((L,), jnp.float32),
        left_cnt=jnp.zeros((L,), jnp.float32),
        right_g=jnp.zeros((L,), jnp.float32),
        right_h=jnp.zeros((L,), jnp.float32),
        right_cnt=jnp.zeros((L,), jnp.float32),
    )
    cand = _set_cand(cand, 0, cand0)

    state = _State(
        leaf_id=jnp.zeros((n,), jnp.int32),
        hist_buf=jnp.zeros((L, f, B, 3), jnp.float32).at[0].set(hist0),
        leaf_g=jnp.zeros((L,), jnp.float32).at[0].set(totals[0]),
        leaf_h=jnp.zeros((L,), jnp.float32).at[0].set(totals[1]),
        leaf_cnt=jnp.zeros((L,), jnp.float32).at[0].set(totals[2]),
        leaf_depth=jnp.zeros((L,), jnp.int32),
        leaf_parent=jnp.full((L,), -1, jnp.int32),
        leaf_is_right=jnp.zeros((L,), bool),
        cand=cand,
        split_feature=jnp.zeros((L - 1,), jnp.int32),
        split_bin=jnp.zeros((L - 1,), jnp.int32),
        split_gain=jnp.zeros((L - 1,), jnp.float32),
        default_left=jnp.zeros((L - 1,), bool),
        left_child=jnp.zeros((L - 1,), jnp.int32),
        right_child=jnp.zeros((L - 1,), jnp.int32),
        internal_value=jnp.zeros((L - 1,), jnp.float32),
        internal_weight=jnp.zeros((L - 1,), jnp.float32),
        internal_count=jnp.zeros((L - 1,), jnp.float32),
        num_leaves=jnp.asarray(1, jnp.int32),
        done=jnp.asarray(False),
    )

    node_ids = jnp.arange(L - 1, dtype=jnp.int32)

    def body(t, st: _State) -> _State:
        best_leaf = jnp.argmax(st.cand.gain).astype(jnp.int32)
        can_split = st.cand.gain[best_leaf] > 0.0
        done = st.done | ~can_split

        def apply(st: _State) -> _State:
            l = best_leaf
            nl = (t + 1).astype(jnp.int32)
            feat = st.cand.feature[l]
            tbin = st.cand.bin[l]
            dl = st.cand.default_left[l]

            # ---- partition rows of leaf l (reference DataPartition::Split)
            col = jnp.take(bins, feat, axis=1)
            nb = nan_bins[feat]
            go_left = (col <= tbin) | (dl & (nb >= 0) & (col == nb))
            in_leaf = st.leaf_id == l
            leaf_id = jnp.where(in_leaf & ~go_left, nl, st.leaf_id)

            # ---- record node t (reference Tree::Split, src/io/tree.cpp:65)
            pg, ph, pc = st.leaf_g[l], st.leaf_h[l], st.leaf_cnt[l]
            left_child = st.left_child.at[t].set(-(l + 1))
            right_child = st.right_child.at[t].set(-(nl + 1))
            par = st.leaf_parent[l]
            is_r = st.leaf_is_right[l]
            fix = node_ids == par
            left_child = jnp.where(fix & (par >= 0) & ~is_r, t, left_child)
            right_child = jnp.where(fix & (par >= 0) & is_r, t, right_child)

            split_feature = st.split_feature.at[t].set(feat)
            split_bin = st.split_bin.at[t].set(tbin)
            split_gain = st.split_gain.at[t].set(st.cand.gain[l] + p.min_gain_to_split)
            default_left = st.default_left.at[t].set(dl)
            internal_value = st.internal_value.at[t].set(
                leaf_output(pg, ph, p.lambda_l1, p.lambda_l2, p.max_delta_step)
            )
            internal_weight = st.internal_weight.at[t].set(ph)
            internal_count = st.internal_count.at[t].set(pc)

            # ---- leaf bookkeeping
            lg, lh, lc = st.cand.left_g[l], st.cand.left_h[l], st.cand.left_cnt[l]
            rg, rh, rc = st.cand.right_g[l], st.cand.right_h[l], st.cand.right_cnt[l]
            leaf_g = st.leaf_g.at[l].set(lg).at[nl].set(rg)
            leaf_h = st.leaf_h.at[l].set(lh).at[nl].set(rh)
            leaf_cnt = st.leaf_cnt.at[l].set(lc).at[nl].set(rc)
            d_new = st.leaf_depth[l] + 1
            leaf_depth = st.leaf_depth.at[l].set(d_new).at[nl].set(d_new)
            leaf_parent = st.leaf_parent.at[l].set(t).at[nl].set(t)
            leaf_is_right = st.leaf_is_right.at[l].set(False).at[nl].set(True)

            # ---- histograms: pass over the smaller child only, subtraction
            # for the sibling (serial_tree_learner.cpp:558-583).  In gather
            # mode the child's rows are first compacted into a static-capacity
            # buffer (jnp.nonzero with static size) and the histogram runs
            # over that buffer — the TPU formulation of the reference's
            # ordered_gradients gather (rows touched per tree ~ N log L).
            parent_hist = st.hist_buf[l]
            if use_gather:
                # choose the smaller child by RAW row count (capacity bound);
                # masked (bagging) stats still flow through lc/rc above
                rows_l = jnp.sum(in_leaf & go_left).astype(jnp.int32)
                rows_in = jnp.sum(in_leaf).astype(jnp.int32)
                rows_r = rows_in - rows_l
                left_smaller = rows_l <= rows_r
                target = jnp.where(left_smaller, l, nl)
                tc = jnp.minimum(rows_l, rows_r)
                if p.axis_name is not None:
                    # uniform bucket across shards so the psum inside the
                    # selected branch lines up on every device
                    tc = lax.pmax(tc, p.axis_name)
                bucket = jnp.clip(
                    jnp.searchsorted(caps_arr, tc, side="left"), 0, len(caps) - 1
                ).astype(jnp.int32)
                (idx,) = jnp.nonzero(leaf_id == target, size=cap0, fill_value=n)
                sm = lax.switch(bucket, hist_branches, idx)
            else:
                left_smaller = lc <= rc
                target = jnp.where(left_smaller, l, nl)
                mask = count_mask * (leaf_id == target)
                sm = leaf_histogram(
                    bins, grad, hess, mask, B, method=p.hist_method, axis_name=p.axis_name
                )
            other = parent_hist - sm
            left_hist = jnp.where(left_smaller, sm, other)
            right_hist = jnp.where(left_smaller, other, sm)
            hist_buf = st.hist_buf.at[l].set(left_hist).at[nl].set(right_hist)

            # ---- refresh split candidates for the two children
            cand_l = _candidate_for_leaf(
                left_hist, lg, lh, lc, num_bins, nan_bins, feature_mask, p
            )
            cand_r = _candidate_for_leaf(
                right_hist, rg, rh, rc, num_bins, nan_bins, feature_mask, p
            )
            depth_ok = (p.max_depth <= 0) | (d_new < p.max_depth)
            cand = _set_cand(
                st.cand, l, cand_l, jnp.where(depth_ok, cand_l.gain, -jnp.inf)
            )
            cand = _set_cand(
                cand, nl, cand_r, jnp.where(depth_ok, cand_r.gain, -jnp.inf)
            )

            return _State(
                leaf_id=leaf_id,
                hist_buf=hist_buf,
                leaf_g=leaf_g,
                leaf_h=leaf_h,
                leaf_cnt=leaf_cnt,
                leaf_depth=leaf_depth,
                leaf_parent=leaf_parent,
                leaf_is_right=leaf_is_right,
                cand=cand,
                split_feature=split_feature,
                split_bin=split_bin,
                split_gain=split_gain,
                default_left=default_left,
                left_child=left_child,
                right_child=right_child,
                internal_value=internal_value,
                internal_weight=internal_weight,
                internal_count=internal_count,
                num_leaves=st.num_leaves + 1,
                done=done,
            )

        st = lax.cond(done, lambda s: s._replace(done=done), apply, st)
        return st

    state = lax.fori_loop(0, L - 1, body, state)

    leaf_idx = jnp.arange(L, dtype=jnp.int32)
    active = leaf_idx < state.num_leaves
    leaf_value = jnp.where(
        active,
        leaf_output(state.leaf_g, state.leaf_h, p.lambda_l1, p.lambda_l2, p.max_delta_step),
        0.0,
    )

    tree = TreeArrays(
        split_feature=state.split_feature,
        split_bin=state.split_bin,
        split_gain=state.split_gain,
        default_left=state.default_left,
        left_child=state.left_child,
        right_child=state.right_child,
        internal_value=state.internal_value,
        internal_weight=state.internal_weight,
        internal_count=state.internal_count,
        leaf_value=leaf_value.astype(jnp.float32),
        leaf_weight=state.leaf_h,
        leaf_count=state.leaf_cnt,
        leaf_depth=state.leaf_depth,
        num_leaves=state.num_leaves,
    )
    return tree, state.leaf_id
