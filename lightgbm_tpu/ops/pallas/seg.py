"""Segment-resident training layout + Pallas histogram over packed rows.

Reference analogs: ``DataPartition`` (src/treelearner/data_partition.hpp — an
index-array indirection over row-major bins) and
``DenseBin::ConstructHistogramInner`` (src/io/dense_bin.hpp:99).

Why this exists: XLA's random gather/scatter on TPU lowers to a serialized
per-element loop (~30-55 ns/element measured on v5e — 0.1-2 GB/s effective),
so the reference's "index array + gather ordered_gradients" formulation is
2-3 orders of magnitude off HBM roofline on TPU.  The TPU-native answer is to
keep the training rows PHYSICALLY in leaf-segment order, so that:

  * the per-split partition is a stable sort of the parent's contiguous
    window by the 2-bit go-left key (XLA's TPU sort moves ~170 MB/ms — the
    full 11-payload row sorts at ~6 ns/row, measured), implemented in
    ops/segpart.py as pure XLA;
  * the histogram of any leaf is one contiguous DMA stream over the packed
    rows — the kernel below — with zero gathers.

Storage layout: one PLANE-MAJOR i16 matrix ``[storage_lanes(F), n_pad]``
(used planes rounded to a 32-sublane tile; 128 is the hard cap) — plane p,
data-row r.  Planes [0, ceil(F/2)) hold bins byte-packed two features per
plane (feature j lives in byte j&1 of plane j>>1); then 7 stat planes:
g_lo16, g_hi16, h_lo16, h_hi16 (the EXACT f32 bit patterns of grad/hess
split into 16-bit halves — no precision loss), mask (0/1), ridx_lo, ridx_hi
(original row index, for the final segment-order -> row-order inverse
permutation).

Plane-major is the layout XLA itself assigns this loop-carried matrix (the
sort-partition reads whole planes); storing it that way keeps every consumer
layout-native — the row-major alternative made XLA insert TWO full-array
relayout copies per split (~0.3 ms each at 1M rows, measured).  The
histogram kernel DMAs [sub, T] column tiles covering only the used planes
(minor-dim starts 128-aligned, misalignment folded into the validity mask)
and transposes each tile in VMEM.

Histogram engine v2 (this file's kernel contract):

  * The kernel grid is PLANE-TILED: ``(K, G)`` where K is the frontier
    batch and G = ceil(F / group) is the number of feature-plane groups.
    Each program accumulates ONE group's [8, group*bpad] block, so the
    per-program VMEM scratch is O(group*bpad) instead of O(F*bpad) — wide
    (F, max_bin) shapes that previously failed ``seg_vmem_ok`` now fit.
    The trade: every program re-streams the window's stat planes (G-fold
    redundant DMA traffic); the one-hot matmul dominates per tile, so the
    extra DMA hides under compute for all shapes the gate admits.
  * Kernels emit RAW 8-sublane accumulator planes (f32 for the bf16 path,
    i32 for the int8 paths); the digit-recombine/dequantize runs OUTSIDE
    the kernel in plain XLA.  8 is exactly the f32/i32 VMEM tile height,
    which retires the three GL005 sublane-3 layouts the previous
    ``[3, F*bpad]`` outputs needed baselined.
  * int8 accumulation is 2-DIGIT: q = round(stat/scale) clipped to
    ±QMAX (127*128), split as q = hi*128 + lo with |hi| <= 127 and
    |lo| <= 64 — both int8-safe — accumulated as int8 x int8 -> i32 on the
    MXU and recombined outside as (S_hi*128 + S_lo)*scale.  For
    quantized-gradient training (|q| <= 127 so hi in {-1,0,1}) this is
    EXACT like the old 1-digit path; as the grower's default histogram
    accumulator ("hist_acc") it carries ~14 bits per addend (relative
    quantization step 1/16256 ~= 6e-5), and near-tie split candidates are
    re-accumulated in the bf16/f32 path before any structure decision
    (ops/grower.py near_tie_tol).
  * Dead plane groups are SKIPPED: a [G] live mask (SMEM) zeroes a
    program's tile loop, so feature_fraction / EFB-bundled workloads pay
    only for live bundles.  Group 0 is always live (the grower reads
    feature 0's row as the totals row).

Precision contract (ADVICE r2, tightened r3): the bf16 path accumulates
grad/hess as a THREE-TERM bf16 split (~26 mantissa bits per addend — i.e.
f32-accurate for all practical gradients, the extra rows ride the matmul's
6->8 sublane padding for free) with f32 accumulators, vs double histograms
in the reference.  Near-tie split decisions can still flip vs the f64
reference within f32 epsilon, which golden-model parity tests tolerate.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...obs.jit import instrumented_jit
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

LANES = 128  # hard cap on packed planes (128 i16 sublane budget)
TILE = 512  # rows per DMA tile in seg_hist
N_STAT_LANES = 7
MAX_SEG_BIN = 256  # byte-packed bins: values must fit u8 (narrow layout)
MAX_WIDE_BIN = 65536  # u16 planes (wide layout, max_bin > 256)

# 2-digit int8 quantization ceiling: q in [-QMAX, QMAX] splits as
# q = hi*128 + lo with |hi| <= 127, |lo| <= 64 — both int8-safe.
QMAX = 127 * 128

# Test hook: route the seg histogram through the Pallas interpret-mode
# kernels even off-TPU (tools/run_tests.sh int8 smoke).  Read at TRACE time,
# like grow_step._INTERPRET.  This is also the grower's signal that the
# int8-default histogram accumulator may engage off-TPU (the CPU fallback
# ignores hist_acc — its masked/windowed reference path is the byte-level
# oracle and stays f32).
_INTERPRET = False


def bin_lanes(f: int, wide: bool = False) -> int:
    """i16 lanes holding bins: byte-packed two per plane normally, one u16
    plane per feature when max_bin > 256 (``wide`` — the reference's
    DenseBin<uint16_t> analog, src/io/dense_bin.hpp:18)."""
    return f if wide else (f + 1) // 2


def stat_lanes(f: int, wide: bool = False) -> Tuple[int, int, int, int, int, int, int]:
    """Lane indices of (g_lo, g_hi, h_lo, h_hi, mask, ridx_lo, ridx_hi)."""
    s = bin_lanes(f, wide)
    return s, s + 1, s + 2, s + 3, s + 4, s + 5, s + 6


def used_lanes(f: int, wide: bool = False) -> int:
    return bin_lanes(f, wide) + N_STAT_LANES


def storage_lanes(f: int, wide: bool = False) -> int:
    """Allocated planes: used planes rounded to an i16 sublane-tile multiple
    (32).  Storing only these — not the full 128 cap — cuts the segment
    matrix HBM footprint 4x at F=28 (2.7 GB -> 0.7 GB at 10.5M rows)."""
    return min(LANES, -(-used_lanes(f, wide) // 32) * 32)


COL_ALIGN = 128  # minor-dim DMA starts must be 128-lane aligned
SEG_VMEM_BUDGET = 12 * 1024 * 1024  # scratch ceiling for the seg kernels


def seg_vmem_ok(f: int, num_bins: int, has_cat: bool = False) -> bool:
    """Whether the seg kernels' VMEM scratch fits at this (F, max_bin).

    The plane-tiled grid makes the histogram footprint O(group*bpad) per
    program — acc [8, group*bpad] + the matching out block + onehot
    [TILE, group*bpad] + the staging tile — independent of F.  The
    categorical partition additionally builds a [bmt, 256] one-hot (bf16)
    and is unchanged by the plane tiling, so it still binds wide-bin
    categorical configs."""
    bpad = hist_bpad(num_bins)
    gb = hist_group(f, bpad) * bpad
    hist = 2 * 8 * gb * 4 + TILE * gb * 2 + 128 * TILE * 2
    part = (max(256, bpad) * 256 * 2) if has_cat else 0
    return max(hist, part) <= SEG_VMEM_BUDGET


def padded_rows(n: int) -> int:
    """Storage rows: slack so the largest sort-partition window and the final
    column-aligned seg_hist tile stay in bounds."""
    return ((n + 2 * TILE + COL_ALIGN) + TILE - 1) // TILE * TILE


# ---------------------------------------------------------------------------
# host/XLA-side pack & unpack
# ---------------------------------------------------------------------------


def _u16(x: jnp.ndarray) -> jnp.ndarray:
    """Low 16 bits of an i32/u32 array as i16 (bit pattern preserved)."""
    return lax.bitcast_convert_type((x & 0xFFFF).astype(jnp.uint16), jnp.int16)


def pack_rows(
    bins: jnp.ndarray,  # [N, F] integer bins (values < 256, or < 65536 wide)
    grad: jnp.ndarray,  # [N] f32
    hess: jnp.ndarray,  # [N] f32
    mask: jnp.ndarray,  # [N] f32 in {0, 1}
    n_pad: int,
    wide: bool = False,
) -> jnp.ndarray:
    """Pack rows into the PLANE-MAJOR [LANES, n_pad] i16 layout (ridx = iota)."""
    n, f = bins.shape
    if used_lanes(f, wide) > LANES:
        cap = (LANES - N_STAT_LANES) if wide else 2 * (LANES - N_STAT_LANES)
        raise ValueError(
            f"seg layout supports at most {cap} features"
            f"{' at max_bin > 256' if wide else ''}, got {f}"
        )
    bt = bins.T.astype(jnp.int32)  # [F, N]
    if wide:
        # one u16 plane per feature (DenseBin<uint16_t>, dense_bin.hpp:18)
        bin16 = _u16(jnp.clip(bt, 0, MAX_WIDE_BIN - 1))  # [F, N]
    else:
        # byte-packed bins: values >= 256 would bleed into the paired feature
        bt = jnp.clip(bt, 0, MAX_SEG_BIN - 1)
        if f % 2:
            bt = jnp.concatenate([bt, jnp.zeros((1, n), jnp.int32)], axis=0)
        bin16 = _u16(bt[0::2] | (bt[1::2] << 8))  # [ceil(F/2), N]
    gbits = lax.bitcast_convert_type(grad.astype(jnp.float32), jnp.uint32).astype(jnp.int32)
    hbits = lax.bitcast_convert_type(hess.astype(jnp.float32), jnp.uint32).astype(jnp.int32)
    ridx = jnp.arange(n, dtype=jnp.int32)
    planes = [
        bin16,
        _u16(gbits)[None, :],
        _u16(gbits >> 16)[None, :],
        _u16(hbits)[None, :],
        _u16(hbits >> 16)[None, :],
        (mask > 0).astype(jnp.int16)[None, :],
        _u16(ridx)[None, :],
        _u16(ridx >> 16)[None, :],
    ]
    packed = jnp.concatenate(planes, axis=0)
    packed = jnp.pad(
        packed, ((0, storage_lanes(f, wide) - packed.shape[0]), (0, n_pad - n))
    )
    return packed


def _plane_u16(seg: jnp.ndarray, plane) -> jnp.ndarray:
    return seg[plane].astype(jnp.int32) & 0xFFFF


def unpack_stats(seg: jnp.ndarray, f: int, n: Optional[int] = None,
                 wide: bool = False):
    """Recover (bins[N,F] i32, g f32, h f32, mask f32, ridx i32) from the
    plane-major matrix (optionally only the first n data rows)."""
    GLO, GHI, HLO, HHI, M, RLO, RHI = stat_lanes(f, wide)
    if n is None:
        n = seg.shape[1]
    seg = seg[:, :n]
    packed = seg[: bin_lanes(f, wide)].astype(jnp.int32) & 0xFFFF  # [bl, N]
    if wide:
        bins = packed.T  # [N, F] — one u16 plane per feature
    else:
        lo = packed & 0xFF
        hi = (packed >> 8) & 0xFF
        bins = jnp.stack([lo, hi], axis=1).reshape(-1, n)[:f].T  # [N, F]
    g = lax.bitcast_convert_type(
        (_plane_u16(seg, GLO) | (_plane_u16(seg, GHI) << 16)).astype(jnp.uint32),
        jnp.float32,
    )
    h = lax.bitcast_convert_type(
        (_plane_u16(seg, HLO) | (_plane_u16(seg, HHI) << 16)).astype(jnp.uint32),
        jnp.float32,
    )
    m = seg[M].astype(jnp.float32)
    ridx = _plane_u16(seg, RLO) | (_plane_u16(seg, RHI) << 16)
    return bins, g, h, m, ridx


# ---------------------------------------------------------------------------
# seg_hist kernel — histogram of a contiguous packed-row range
# ---------------------------------------------------------------------------

_TARGET_LANES = 2048


def hist_bpad(num_bins: int) -> int:
    """Bin-axis padding (128-lane multiple) used by the hist kernels."""
    return (max(num_bins, 1) + 127) // 128 * 128


def hist_group(f: int, bpad: int) -> int:
    """Features per one-hot matmul group (bounded by the MXU lane target)."""
    return min(max(1, _TARGET_LANES // bpad), f)


def hist_ngroups(f: int, bpad: int) -> int:
    """Feature-plane groups — the second grid dimension of the plane-tiled
    hist kernels (each program accumulates exactly one group's block)."""
    return -(-f // hist_group(f, bpad))


def hist_sub(f: int, wide: bool) -> int:
    """DMA sublanes: only the used planes (bins + stats), padded to an i16
    sublane multiple — 32 planes at F=28, 4x less tile traffic than the
    128-plane cap."""
    return min(storage_lanes(f, wide), (used_lanes(f, wide) + 15) // 16 * 16)


def _hist_window(
    start,  # scalar i32 — window begin (data-row index)
    cnt,  # scalar i32 — window rows (0 = all-zero histogram)
    pt,  # scalar i32 — this program's feature-plane group (grid dim 1)
    live,  # scalar i32 — 0 skips the tile loop entirely (dead plane group)
    read_fn,  # (base_col: i32) -> [SUB, TILE] u16-in-i32 staged tile
    scales_ref,  # SMEM [2] f32: g_scale, h_scale (quantized mode; else 1s)
    acc,  # VMEM [8, group * bpad] f32 | i32 — RAW accumulator planes
    onehot,  # VMEM [TILE, group * bpad] bf16 | i8
    *,
    f: int,
    bpad: int,
    group: int,
    quantized: bool,
    wide: bool,
):
    """Histogram accumulation over ONE packed-row window (the per-program
    body of the seg hist kernel, factored out so the fused grow-step kernel
    can run it over just-partitioned data — its ``read_fn`` reads tiles
    through the output alias; see partition.read_aliased_tile).

    Fills ``acc`` with the program's RAW [8, group*bpad] accumulator block
    for plane group ``pt``; the caller copies it to the output and the
    digit recombine runs outside the kernel (``combine_hist_raw``).  Row
    convention (both dtypes): 0 g_hi, 1 h_hi, 2 count, 3 g_lo, 4 h_lo,
    5 zero, 6 g_lo2, 7 h_lo2 (int8 leaves 5-7 zero)."""
    abegin = (start // COL_ALIGN) * COL_ALIGN
    off = start - abegin
    nt = (off + cnt + TILE - 1) // TILE
    # dead plane group (feature_fraction / EFB bundling): zero trips — the
    # output block stays zero and the grower never reads those rows
    nt = jnp.where(live != 0, nt, 0)
    acc[...] = jnp.zeros_like(acc)
    # hoisted out of the tile loop: reciprocal-multiply instead of two
    # full-width divides per tile (quotients round to integers, so the
    # rounding difference cannot change the result)
    inv_g = 1.0 / scales_ref[0]
    inv_h = 1.0 / scales_ref[1]
    GLO, GHI, HLO, HHI, M, _, _ = stat_lanes(f, wide)
    iota_rows = jax.lax.broadcasted_iota(jnp.int32, (TILE, 1), 0)[:, 0]
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (TILE, bpad), 1)
    ngroups = hist_ngroups(f, bpad)

    def body(t, _):
        # transpose the plane-major tile to row-major for the one-hot matmul
        xu = read_fn(abegin + t * TILE).T  # [TILE, SUB]
        pos = iota_rows + t * TILE
        valid = ((pos >= off) & (pos < off + cnt)).astype(jnp.float32)
        g = lax.bitcast_convert_type(
            (xu[:, GLO] | (xu[:, GHI] << 16)).astype(jnp.uint32), jnp.float32
        )
        h = lax.bitcast_convert_type(
            (xu[:, HLO] | (xu[:, HHI] << 16)).astype(jnp.uint32), jnp.float32
        )
        m = xu[:, M].astype(jnp.float32) * valid
        gm = g * m
        hm = h * m
        if quantized:
            # int8 MXU path (2x bf16 throughput), 2-DIGIT: q is clipped to
            # +-QMAX and split q = hi*128 + lo (|hi| <= 127, |lo| <= 64 —
            # the +64 bias makes the shift round-to-nearest so the low
            # digit stays in int8 range).  Quantized-gradient training
            # (gradient_discretizer.cpp:70 grid, |q| <= 127 so hi is just
            # the sign spill) stays EXACT like the old 1-digit path: per-
            # bin integer sums are exact to 2^31/192 rows (~11M at the
            # |q|=127 extreme) in i32 and the f32 recombine is exact below
            # 2^24.  As the default hist accumulator the grid carries ~14
            # bits per addend — near ties are re-accumulated in bf16/f32
            # by the grower before any structure decision.
            qg = jnp.clip(jnp.round(gm * inv_g), -QMAX, QMAX).astype(jnp.int32)
            qh = jnp.clip(jnp.round(hm * inv_h), -QMAX, QMAX).astype(jnp.int32)
            g_hi = (qg + 64) >> 7
            g_lo = qg - (g_hi << 7)
            h_hi = (qh + 64) >> 7
            h_lo = qh - (h_hi << 7)
            # 5 live rows pad to the i32 output tile's 8 sublanes anyway,
            # so the zero rows are free MXU work (same argument as the
            # bf16 path's 6 -> 8 padding)
            stats = jnp.concatenate(
                [
                    g_hi.astype(jnp.int8)[:, None],
                    h_hi.astype(jnp.int8)[:, None],
                    m.astype(jnp.int8)[:, None],
                    g_lo.astype(jnp.int8)[:, None],
                    h_lo.astype(jnp.int8)[:, None],
                    jnp.zeros((TILE, 3), jnp.int8),
                ],
                axis=1,
            )  # [TILE, 8]
            oh_dtype, pref = jnp.int8, jnp.int32
        else:
            # THREE-term bf16 split of each f32 addend (~26 mantissa bits)
            # — the matmul M-dim pads 6 -> 8 sublanes anyway, so the two
            # extra residual rows are free MXU work (ADVICE r2: tighter
            # precision contract at zero cost)
            g_hi = gm.astype(jnp.bfloat16)
            g_r1 = gm - g_hi.astype(jnp.float32)
            g_lo = g_r1.astype(jnp.bfloat16)
            g_lo2 = (g_r1 - g_lo.astype(jnp.float32)).astype(jnp.bfloat16)
            h_hi = hm.astype(jnp.bfloat16)
            h_r1 = hm - h_hi.astype(jnp.float32)
            h_lo = h_r1.astype(jnp.bfloat16)
            h_lo2 = (h_r1 - h_lo.astype(jnp.float32)).astype(jnp.bfloat16)
            stats = jnp.concatenate(
                [
                    g_hi[:, None],
                    h_hi[:, None],
                    m.astype(jnp.bfloat16)[:, None],
                    g_lo[:, None],
                    h_lo[:, None],
                    jnp.zeros((TILE, 1), jnp.bfloat16),
                    g_lo2[:, None],
                    h_lo2[:, None],
                ],
                axis=1,
            )  # [TILE, 8]
            oh_dtype, pref = jnp.bfloat16, jnp.float32

        def build_onehot(gi):
            """One-hot block for STATIC plane group gi (feature columns are
            compile-time plane/byte selects, hence the unrolled dispatch on
            the dynamic program id below)."""
            basef = gi * group
            nf = min(group, f - basef)
            for j in range(nf):
                fj = basef + j
                if wide:
                    col = xu[:, fj]  # u16 plane per feature
                else:
                    col = (xu[:, fj >> 1] >> (8 * (fj & 1))) & 0xFF
                onehot[:, j * bpad : (j + 1) * bpad] = (
                    col[:, None] == iota_b
                ).astype(oh_dtype)
            if nf < group:
                onehot[:, nf * bpad :] = jnp.zeros(
                    (TILE, (group - nf) * bpad), oh_dtype
                )

        if ngroups == 1:
            build_onehot(0)
        else:
            for gi in range(ngroups):
                pl.when(pt == gi)(functools.partial(build_onehot, gi))
        # ONE matmul per tile per program — the plane-tiled grid moves the
        # old per-program group loop onto grid dim 1
        part = jax.lax.dot_general(
            stats,
            onehot[...],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=pref,
        )
        acc[...] += part
        return 0

    lax.fori_loop(0, nt, body, 0)


def combine_hist_raw(
    raw: jnp.ndarray,  # [K, G, 8, group * bpad] i32 | f32 raw planes
    scales: jnp.ndarray,  # [2] f32 (quantized; ignored otherwise)
    *,
    f: int,
    bpad: int,
    group: int,
    num_bins: int,
    quantized: bool,
) -> jnp.ndarray:
    """Recombine the kernels' raw 8-sublane accumulator planes into the
    [K, F, B, 3] (g, h, count) histogram — plain XLA, outside the kernel.

    int8: g = (S_hi*128 + S_lo)*g_scale (the *128 is a f32 exponent bump,
    exact; the digit sum is exact below 2^24 — same bound as the old
    in-kernel dequantize).  bf16: the same 3-term sums the kernel used to
    do in its epilogue."""
    k, ngroups = raw.shape[0], raw.shape[1]
    a = raw.reshape(k, ngroups, 8, group, bpad)
    a = a.transpose(0, 2, 1, 3, 4).reshape(k, 8, ngroups * group, bpad)
    a = a[:, :, :f, :]
    if quantized:
        af = a.astype(jnp.float32)
        g = (af[:, 0] * 128.0 + af[:, 3]) * scales[0]
        h = (af[:, 1] * 128.0 + af[:, 4]) * scales[1]
        c = af[:, 2]
    else:
        g = a[:, 0] + a[:, 3] + a[:, 6]
        h = a[:, 1] + a[:, 4] + a[:, 7]
        c = a[:, 2] + a[:, 5]
    return jnp.stack([g, h, c], axis=-1)[:, :, :num_bins, :]


def _seg_hist_kernel(
    scal_ref,  # SMEM [K, 2] i32: (start, cnt) per batch member
    scales_ref,  # SMEM [2] f32: g_scale, h_scale (quantized mode; else 1s)
    live_ref,  # SMEM [G] i32: per-plane-group live mask
    seg_any,  # ANY [LANES, n_pad] i16 (plane-major)
    out_ref,  # VMEM [1, 1, 8, group * bpad] f32 | i32 block (raw planes)
    in_stage,  # VMEM [SUB, TILE] i16 — only the used planes are DMA'd
    acc,  # VMEM [8, group * bpad] f32 | i32
    onehot,  # VMEM [TILE, group * bpad] bf16 | i8
    sem_in,
    *,
    f: int,
    bpad: int,
    group: int,
    sub: int,
    quantized: bool,
    wide: bool,
):
    i = pl.program_id(0)
    pt = pl.program_id(1)

    def read_fn(base_col):
        dma = pltpu.make_async_copy(
            seg_any.at[
                pl.ds(0, sub),
                pl.ds(pl.multiple_of(base_col, COL_ALIGN), TILE),
            ],
            in_stage,
            sem_in,
        )
        dma.start()
        dma.wait()
        return in_stage[...].astype(jnp.int32) & 0xFFFF

    _hist_window(
        scal_ref[i, 0],
        scal_ref[i, 1],
        pt,
        live_ref[pt],
        read_fn,
        scales_ref,
        acc,
        onehot,
        f=f,
        bpad=bpad,
        group=group,
        quantized=quantized,
        wide=wide,
    )
    out_ref[0, 0] = acc[...]


def seg_hist_pallas(
    seg: jnp.ndarray,
    scal: jnp.ndarray,  # [2] i32: start, cnt
    scales: Optional[jnp.ndarray] = None,  # [2] f32 grid scales (quantized)
    live: Optional[jnp.ndarray] = None,  # [G] i32 plane-group live mask
    *,
    f: int,
    num_bins: int,
    n_pad: int,
    quantized: bool = False,
    wide: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """Histogram [F, B, 3] (g, h, count) of packed rows [start, start+cnt).

    A thin K=1 wrapper over the batched plane-tiled kernel (one launch, G
    grid programs).  ``quantized=True`` (requires ``scales``): 2-digit
    integer accumulation on the int8 MXU path — exact on the quantized-
    training grid and ~2x the bf16 throughput."""
    out = seg_hist_pallas_batch(
        seg, scal.reshape(1, 2), scales, live,
        f=f, num_bins=num_bins, n_pad=n_pad, quantized=quantized, wide=wide,
        interpret=interpret,
    )
    return out[0]


@functools.partial(
    instrumented_jit,
    static_argnames=("f", "num_bins", "n_pad", "quantized", "wide", "interpret"),
)
def seg_hist_pallas_batch(
    seg: jnp.ndarray,
    scal: jnp.ndarray,  # [K, 2] i32: (start, cnt) per batch member
    scales: Optional[jnp.ndarray] = None,
    live: Optional[jnp.ndarray] = None,  # [G] i32 plane-group live mask
    *,
    f: int,
    num_bins: int,
    n_pad: int,
    quantized: bool = False,
    wide: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """K histograms [K, F, B, 3] of K disjoint packed-row windows in ONE
    plane-tiled launch: a (K, G) grid — batch member x feature-plane group
    — over the shared kernel (TPU grid programs run sequentially on the
    core, so the shared staging/accumulator scratch is reused safely
    program-to-program).  Frontier-batched growth (ops/grower.py
    leaf_batch) uses this to build all K smaller-child histograms per step
    with one launch's fixed cost; ``live`` (default all-ones) skips dead
    plane groups under feature_fraction / EFB bundling."""
    k = scal.shape[0]
    bpad = hist_bpad(num_bins)
    group = hist_group(f, bpad)
    ngroups = hist_ngroups(f, bpad)
    sub = hist_sub(f, wide)
    acc_dtype = jnp.int32 if quantized else jnp.float32
    kernel = functools.partial(
        _seg_hist_kernel, f=f, bpad=bpad, group=group, sub=sub,
        quantized=quantized, wide=wide,
    )
    if scales is None:
        scales = jnp.ones((2,), jnp.float32)
    if live is None:
        live = jnp.ones((ngroups,), jnp.int32)
    raw = pl.pallas_call(
        kernel,
        grid=(k, ngroups),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, 8, group * bpad), lambda i, pt: (i, pt, 0, 0),
            memory_space=pltpu.VMEM,
        ),
        out_shape=jax.ShapeDtypeStruct((k, ngroups, 8, group * bpad), acc_dtype),
        scratch_shapes=[
            pltpu.VMEM((sub, TILE), jnp.int16),
            pltpu.VMEM((8, group * bpad), acc_dtype),
            pltpu.VMEM(
                (TILE, group * bpad), jnp.int8 if quantized else jnp.bfloat16
            ),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(
        scal.astype(jnp.int32), scales.astype(jnp.float32),
        live.astype(jnp.int32), seg,
    )
    return combine_hist_raw(
        raw, scales.astype(jnp.float32), f=f, bpad=bpad, group=group,
        num_bins=num_bins, quantized=quantized,
    )


def seg_hist_ref(seg: jnp.ndarray, scal: jnp.ndarray, *, f: int, num_bins: int,
                 n_pad: int, wide: bool = False):
    """Pure-JAX reference/CPU path: masked histogram over the whole array
    (static shapes; rows outside [start, start+cnt) masked out)."""
    from ..histogram import leaf_histogram_segment

    start, cnt = scal[0], scal[1]
    bins, g, h, m, _ = unpack_stats(seg, f, wide=wide)
    idx = jnp.arange(seg.shape[1], dtype=jnp.int32)
    window = (idx >= start) & (idx < start + cnt)
    return leaf_histogram_segment(bins, g, h, m * window.astype(jnp.float32), num_bins)


# CPU windowing engages only above this row count: below it the plain
# masked full pass is cheap, and keeping small shapes on the original path
# keeps every existing golden dump byte-stable (a windowed sum can differ
# from the full-pass sum in -0.0/+0.0 only, but why risk even that).
_CPU_WINDOW_ROWS = 32 * TILE


def _window_caps(n_pad: int):
    """Capacity ladder for the windowed CPU pass: 16*TILE, x4 per rung,
    closed by the full array (mirrors the ordered path's _hist_caps)."""
    caps, c = [], 16 * TILE
    while c < n_pad:
        caps.append(c)
        c *= 4
    caps.append(n_pad)
    return caps


def _seg_hist_windowed(seg, scal, *, f: int, num_bins: int, n_pad: int,
                       wide: bool = False):
    """Windowed CPU seg histogram: slice the smallest TILE-aligned capacity
    bucket covering [start, start+cnt) and run the masked reference over
    just that window, so CPU histogram work is proportional to the leaf
    size instead of the full padded array (the dominant cost of the old
    full-pass fallback at 1M+ rows).  lax.switch keeps the trace static
    per capacity rung."""
    caps = _window_caps(n_pad)
    start = scal[0].astype(jnp.int32)
    cnt = scal[1].astype(jnp.int32)
    # TILE-aligning the window start costs < TILE rows of slack
    need = cnt + TILE

    def _branch(cap):
        def _b(seg, start, cnt):
            s0 = jnp.clip((start // TILE) * TILE, 0, n_pad - cap)
            win = lax.dynamic_slice_in_dim(seg, s0, cap, axis=1)
            return seg_hist_ref(
                win, jnp.stack([start - s0, cnt]), f=f, num_bins=num_bins,
                n_pad=cap, wide=wide,
            )
        return _b

    idx = jnp.int32(0)
    for c in caps[:-1]:
        idx = idx + (need > c).astype(jnp.int32)
    return lax.switch(idx, [_branch(c) for c in caps], seg, start, cnt)


def seg_hist_cpu(seg, scal, *, f: int, num_bins: int, n_pad: int,
                 wide: bool = False):
    """Off-TPU seg histogram: capacity-bucketed windowed pass at scale,
    plain masked full pass below the threshold (byte-identical to the
    original fallback, keeping small goldens bit-stable).  Shared by the
    two-launch dispatchers below AND the fused grow step's XLA oracle, so
    fused-vs-two-launch stays byte-identical by construction."""
    if n_pad > _CPU_WINDOW_ROWS:
        return _seg_hist_windowed(
            seg, scal, f=f, num_bins=num_bins, n_pad=n_pad, wide=wide
        )
    return seg_hist_ref(seg, scal, f=f, num_bins=num_bins, n_pad=n_pad,
                        wide=wide)


def seg_hist_batch_cpu(seg, scal_k, *, f: int, num_bins: int, n_pad: int,
                       wide: bool = False):
    """Off-TPU K-window histogram.  Above the windowing threshold each
    member picks its own capacity bucket via a sequential Python loop (K is
    small and static; vmapping lax.switch would execute every rung),
    below it the vmapped full pass matches the historical path exactly."""
    if n_pad > _CPU_WINDOW_ROWS:
        return jnp.stack([
            _seg_hist_windowed(
                seg, scal_k[i], f=f, num_bins=num_bins, n_pad=n_pad, wide=wide
            )
            for i in range(scal_k.shape[0])
        ])
    return jax.vmap(
        lambda s: seg_hist_ref(
            seg, s, f=f, num_bins=num_bins, n_pad=n_pad, wide=wide
        )
    )(scal_k)


def seg_hist(seg, scal, *, f: int, num_bins: int, n_pad: int,
             quant_scales=None, wide: bool = False, live=None):
    """Platform dispatch: Pallas on TPU (2-digit int8 grid accumulation
    when ``quant_scales`` is given — quantized training or the grower's
    int8-default hist accumulator), windowed/masked reference elsewhere."""
    quantized = quant_scales is not None
    scales = (
        jnp.stack([quant_scales[0], quant_scales[1]]).astype(jnp.float32)
        if quantized
        else jnp.ones((2,), jnp.float32)
    )
    if jax.default_backend() != "tpu":
        # no TPU registered: older jax lowers every platform_dependent
        # branch and the Pallas one cannot lower for CPU
        if _INTERPRET:
            return seg_hist_pallas(
                seg, scal, scales, live, f=f, num_bins=num_bins, n_pad=n_pad,
                quantized=quantized, wide=wide, interpret=True,
            )
        return seg_hist_cpu(seg, scal, f=f, num_bins=num_bins, n_pad=n_pad,
                            wide=wide)
    if live is None:
        live = jnp.ones((hist_ngroups(f, hist_bpad(num_bins)),), jnp.int32)
    return jax.lax.platform_dependent(
        seg,
        scal,
        scales,
        live,
        tpu=functools.partial(
            seg_hist_pallas, f=f, num_bins=num_bins, n_pad=n_pad,
            quantized=quantized, wide=wide,
        ),
        default=lambda seg, scal, _s, _l: seg_hist_cpu(
            seg, scal, f=f, num_bins=num_bins, n_pad=n_pad, wide=wide
        ),
    )


def seg_hist_batch(seg, scal_k, *, f: int, num_bins: int, n_pad: int,
                   quant_scales=None, wide: bool = False, live=None):
    """K-window histogram dispatch ([K, 2] (start, cnt) -> [K, F, B, 3]):
    one plane-tiled Pallas launch on TPU, the windowed/masked reference
    elsewhere."""
    quantized = quant_scales is not None
    scales = (
        jnp.stack([quant_scales[0], quant_scales[1]]).astype(jnp.float32)
        if quantized
        else jnp.ones((2,), jnp.float32)
    )

    if jax.default_backend() != "tpu":
        if _INTERPRET:
            return seg_hist_pallas_batch(
                seg, scal_k, scales, live, f=f, num_bins=num_bins,
                n_pad=n_pad, quantized=quantized, wide=wide, interpret=True,
            )
        return seg_hist_batch_cpu(seg, scal_k, f=f, num_bins=num_bins,
                                  n_pad=n_pad, wide=wide)
    if live is None:
        live = jnp.ones((hist_ngroups(f, hist_bpad(num_bins)),), jnp.int32)
    return jax.lax.platform_dependent(
        seg,
        scal_k,
        scales,
        live,
        tpu=functools.partial(
            seg_hist_pallas_batch, f=f, num_bins=num_bins, n_pad=n_pad,
            quantized=quantized, wide=wide,
        ),
        default=lambda seg, scal_k, _s, _l: seg_hist_batch_cpu(
            seg, scal_k, f=f, num_bins=num_bins, n_pad=n_pad, wide=wide
        ),
    )
