"""Segment-resident training layout + Pallas histogram over packed rows.

Reference analogs: ``DataPartition`` (src/treelearner/data_partition.hpp — an
index-array indirection over row-major bins) and
``DenseBin::ConstructHistogramInner`` (src/io/dense_bin.hpp:99).

Why this exists: XLA's random gather/scatter on TPU lowers to a serialized
per-element loop (~30-55 ns/element measured on v5e — 0.1-2 GB/s effective),
so the reference's "index array + gather ordered_gradients" formulation is
2-3 orders of magnitude off HBM roofline on TPU.  The TPU-native answer is to
keep the training rows PHYSICALLY in leaf-segment order, so that:

  * the per-split partition is a stable sort of the parent's contiguous
    window by the 2-bit go-left key (XLA's TPU sort moves ~170 MB/ms — the
    full 11-payload row sorts at ~6 ns/row, measured), implemented in
    ops/segpart.py as pure XLA;
  * the histogram of any leaf is one contiguous DMA stream over the packed
    rows — the kernel below — with zero gathers.

Storage layout: one PLANE-MAJOR i16 matrix ``[storage_lanes(F), n_pad]``
(used planes rounded to a 32-sublane tile; 128 is the hard cap) — plane p,
data-row r.  Planes [0, ceil(F/2)) hold bins byte-packed two features per
plane (feature j lives in byte j&1 of plane j>>1); then 7 stat planes:
g_lo16, g_hi16, h_lo16, h_hi16 (the EXACT f32 bit patterns of grad/hess
split into 16-bit halves — no precision loss), mask (0/1), ridx_lo, ridx_hi
(original row index, for the final segment-order -> row-order inverse
permutation).

Plane-major is the layout XLA itself assigns this loop-carried matrix (the
sort-partition reads whole planes); storing it that way keeps every consumer
layout-native — the row-major alternative made XLA insert TWO full-array
relayout copies per split (~0.3 ms each at 1M rows, measured).  The
histogram kernel DMAs [sub, T] column tiles covering only the used planes
(minor-dim starts 128-aligned, misalignment folded into the validity mask)
and transposes each tile in VMEM.

Precision contract (ADVICE r2, tightened r3): the histogram accumulates
grad/hess as a THREE-TERM bf16 split (~26 mantissa bits per addend — i.e.
f32-accurate for all practical gradients, the extra rows ride the matmul's
6->8 sublane padding for free) with f32 accumulators, vs double histograms
in the reference.  Near-tie split decisions can still flip vs the f64
reference within f32 epsilon, which golden-model parity tests tolerate.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ...obs.jit import instrumented_jit
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

LANES = 128  # hard cap on packed planes (128 i16 sublane budget)
TILE = 512  # rows per DMA tile in seg_hist
N_STAT_LANES = 7
MAX_SEG_BIN = 256  # byte-packed bins: values must fit u8 (narrow layout)
MAX_WIDE_BIN = 65536  # u16 planes (wide layout, max_bin > 256)


def bin_lanes(f: int, wide: bool = False) -> int:
    """i16 lanes holding bins: byte-packed two per plane normally, one u16
    plane per feature when max_bin > 256 (``wide`` — the reference's
    DenseBin<uint16_t> analog, src/io/dense_bin.hpp:18)."""
    return f if wide else (f + 1) // 2


def stat_lanes(f: int, wide: bool = False) -> Tuple[int, int, int, int, int, int, int]:
    """Lane indices of (g_lo, g_hi, h_lo, h_hi, mask, ridx_lo, ridx_hi)."""
    s = bin_lanes(f, wide)
    return s, s + 1, s + 2, s + 3, s + 4, s + 5, s + 6


def used_lanes(f: int, wide: bool = False) -> int:
    return bin_lanes(f, wide) + N_STAT_LANES


def storage_lanes(f: int, wide: bool = False) -> int:
    """Allocated planes: used planes rounded to an i16 sublane-tile multiple
    (32).  Storing only these — not the full 128 cap — cuts the segment
    matrix HBM footprint 4x at F=28 (2.7 GB -> 0.7 GB at 10.5M rows)."""
    return min(LANES, -(-used_lanes(f, wide) // 32) * 32)


COL_ALIGN = 128  # minor-dim DMA starts must be 128-lane aligned
SEG_VMEM_BUDGET = 12 * 1024 * 1024  # scratch ceiling for the seg kernels


def seg_vmem_ok(f: int, num_bins: int, has_cat: bool = False) -> bool:
    """Whether the seg kernels' VMEM scratch fits at this (F, max_bin).

    seg_hist: acc [8, F*bpad] f32 + out [3, F*bpad] f32 + onehot
    [TILE, ~max(bpad, 2048)] bf16 + the staging tile.  The categorical
    partition additionally builds a [bmt, 256] one-hot (bf16).  Narrow
    configs (max_bin <= 256) always fit; wide ones must be checked before
    auto-selecting seg mode."""
    bpad = (max(num_bins, 1) + 127) // 128 * 128
    hist = 11 * f * bpad * 4 + TILE * max(bpad, 2048) * 2 + 128 * TILE * 2
    part = (max(256, bpad) * 256 * 2) if has_cat else 0
    return max(hist, part) <= SEG_VMEM_BUDGET


def padded_rows(n: int) -> int:
    """Storage rows: slack so the largest sort-partition window and the final
    column-aligned seg_hist tile stay in bounds."""
    return ((n + 2 * TILE + COL_ALIGN) + TILE - 1) // TILE * TILE


# ---------------------------------------------------------------------------
# host/XLA-side pack & unpack
# ---------------------------------------------------------------------------


def _u16(x: jnp.ndarray) -> jnp.ndarray:
    """Low 16 bits of an i32/u32 array as i16 (bit pattern preserved)."""
    return lax.bitcast_convert_type((x & 0xFFFF).astype(jnp.uint16), jnp.int16)


def pack_rows(
    bins: jnp.ndarray,  # [N, F] integer bins (values < 256, or < 65536 wide)
    grad: jnp.ndarray,  # [N] f32
    hess: jnp.ndarray,  # [N] f32
    mask: jnp.ndarray,  # [N] f32 in {0, 1}
    n_pad: int,
    wide: bool = False,
) -> jnp.ndarray:
    """Pack rows into the PLANE-MAJOR [LANES, n_pad] i16 layout (ridx = iota)."""
    n, f = bins.shape
    if used_lanes(f, wide) > LANES:
        cap = (LANES - N_STAT_LANES) if wide else 2 * (LANES - N_STAT_LANES)
        raise ValueError(
            f"seg layout supports at most {cap} features"
            f"{' at max_bin > 256' if wide else ''}, got {f}"
        )
    bt = bins.T.astype(jnp.int32)  # [F, N]
    if wide:
        # one u16 plane per feature (DenseBin<uint16_t>, dense_bin.hpp:18)
        bin16 = _u16(jnp.clip(bt, 0, MAX_WIDE_BIN - 1))  # [F, N]
    else:
        # byte-packed bins: values >= 256 would bleed into the paired feature
        bt = jnp.clip(bt, 0, MAX_SEG_BIN - 1)
        if f % 2:
            bt = jnp.concatenate([bt, jnp.zeros((1, n), jnp.int32)], axis=0)
        bin16 = _u16(bt[0::2] | (bt[1::2] << 8))  # [ceil(F/2), N]
    gbits = lax.bitcast_convert_type(grad.astype(jnp.float32), jnp.uint32).astype(jnp.int32)
    hbits = lax.bitcast_convert_type(hess.astype(jnp.float32), jnp.uint32).astype(jnp.int32)
    ridx = jnp.arange(n, dtype=jnp.int32)
    planes = [
        bin16,
        _u16(gbits)[None, :],
        _u16(gbits >> 16)[None, :],
        _u16(hbits)[None, :],
        _u16(hbits >> 16)[None, :],
        (mask > 0).astype(jnp.int16)[None, :],
        _u16(ridx)[None, :],
        _u16(ridx >> 16)[None, :],
    ]
    packed = jnp.concatenate(planes, axis=0)
    packed = jnp.pad(
        packed, ((0, storage_lanes(f, wide) - packed.shape[0]), (0, n_pad - n))
    )
    return packed


def _plane_u16(seg: jnp.ndarray, plane) -> jnp.ndarray:
    return seg[plane].astype(jnp.int32) & 0xFFFF


def unpack_stats(seg: jnp.ndarray, f: int, n: Optional[int] = None,
                 wide: bool = False):
    """Recover (bins[N,F] i32, g f32, h f32, mask f32, ridx i32) from the
    plane-major matrix (optionally only the first n data rows)."""
    GLO, GHI, HLO, HHI, M, RLO, RHI = stat_lanes(f, wide)
    if n is None:
        n = seg.shape[1]
    seg = seg[:, :n]
    packed = seg[: bin_lanes(f, wide)].astype(jnp.int32) & 0xFFFF  # [bl, N]
    if wide:
        bins = packed.T  # [N, F] — one u16 plane per feature
    else:
        lo = packed & 0xFF
        hi = (packed >> 8) & 0xFF
        bins = jnp.stack([lo, hi], axis=1).reshape(-1, n)[:f].T  # [N, F]
    g = lax.bitcast_convert_type(
        (_plane_u16(seg, GLO) | (_plane_u16(seg, GHI) << 16)).astype(jnp.uint32),
        jnp.float32,
    )
    h = lax.bitcast_convert_type(
        (_plane_u16(seg, HLO) | (_plane_u16(seg, HHI) << 16)).astype(jnp.uint32),
        jnp.float32,
    )
    m = seg[M].astype(jnp.float32)
    ridx = _plane_u16(seg, RLO) | (_plane_u16(seg, RHI) << 16)
    return bins, g, h, m, ridx


# ---------------------------------------------------------------------------
# seg_hist kernel — histogram of a contiguous packed-row range
# ---------------------------------------------------------------------------

_TARGET_LANES = 2048


def hist_bpad(num_bins: int) -> int:
    """Bin-axis padding (128-lane multiple) used by the hist kernels."""
    return (max(num_bins, 1) + 127) // 128 * 128


def hist_group(f: int, bpad: int) -> int:
    """Features per one-hot matmul group (bounded by the MXU lane target)."""
    return min(max(1, _TARGET_LANES // bpad), f)


def hist_sub(f: int, wide: bool) -> int:
    """DMA sublanes: only the used planes (bins + stats), padded to an i16
    sublane multiple — 32 planes at F=28, 4x less tile traffic than the
    128-plane cap."""
    return min(storage_lanes(f, wide), (used_lanes(f, wide) + 15) // 16 * 16)


def _hist_window(
    start,  # scalar i32 — window begin (data-row index)
    cnt,  # scalar i32 — window rows (0 = all-zero histogram)
    read_fn,  # (base_col: i32) -> [SUB, TILE] u16-in-i32 staged tile
    scales_ref,  # SMEM [2] f32: g_scale, h_scale (quantized mode; else 1s)
    acc,  # VMEM [8 | 4, F * bpad] f32 | i32
    onehot,  # VMEM [TILE, group * bpad] bf16 | i8
    *,
    f: int,
    bpad: int,
    group: int,
    quantized: bool,
    wide: bool,
):
    """Histogram accumulation over ONE packed-row window (the per-program
    body of the seg hist kernel, factored out so the fused grow-step kernel
    can run it over just-partitioned data — its ``read_fn`` reads tiles
    through the output alias; see partition.read_aliased_tile).

    Returns (g_row, h_row, count_row), each [F * bpad] f32."""
    abegin = (start // COL_ALIGN) * COL_ALIGN
    off = start - abegin
    nt = (off + cnt + TILE - 1) // TILE
    acc[...] = jnp.zeros_like(acc)
    # hoisted out of the tile loop: reciprocal-multiply instead of two
    # full-width divides per tile (quotients round to integers, so the
    # rounding difference cannot change the result)
    inv_g = 1.0 / scales_ref[0]
    inv_h = 1.0 / scales_ref[1]
    GLO, GHI, HLO, HHI, M, _, _ = stat_lanes(f, wide)
    iota_rows = jax.lax.broadcasted_iota(jnp.int32, (TILE, 1), 0)[:, 0]
    iota_b = jax.lax.broadcasted_iota(jnp.int32, (TILE, bpad), 1)

    def body(t, _):
        # transpose the plane-major tile to row-major for the one-hot matmul
        xu = read_fn(abegin + t * TILE).T  # [TILE, SUB]
        pos = iota_rows + t * TILE
        valid = ((pos >= off) & (pos < off + cnt)).astype(jnp.float32)
        g = lax.bitcast_convert_type(
            (xu[:, GLO] | (xu[:, GHI] << 16)).astype(jnp.uint32), jnp.float32
        )
        h = lax.bitcast_convert_type(
            (xu[:, HLO] | (xu[:, HHI] << 16)).astype(jnp.uint32), jnp.float32
        )
        m = xu[:, M].astype(jnp.float32) * valid
        gm = g * m
        hm = h * m
        def _accumulate(stats_mat, oh_dtype, pref):
            """Shared group loop: build the one-hot block per feature group
            and contract rows on the MXU into acc."""
            ngroups = (f + group - 1) // group
            for gi in range(ngroups):
                basef = gi * group
                nf = min(group, f - basef)
                for j in range(nf):
                    fj = basef + j
                    if wide:
                        col = xu[:, fj]  # u16 plane per feature
                    else:
                        col = (xu[:, fj >> 1] >> (8 * (fj & 1))) & 0xFF
                    onehot[:, j * bpad : (j + 1) * bpad] = (
                        col[:, None] == iota_b
                    ).astype(oh_dtype)
                if nf < group:
                    onehot[:, nf * bpad :] = jnp.zeros(
                        (TILE, (group - nf) * bpad), oh_dtype
                    )
                part = jax.lax.dot_general(
                    stats_mat,
                    onehot[...],
                    dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=pref,
                )
                width = nf * bpad
                acc[:, basef * bpad : basef * bpad + width] += part[:, :width]

        if quantized:
            # quantized-gradient training: gm/hm are integer multiples of
            # the grid scales (gradient_discretizer.cpp:70) — accumulate
            # the small integers EXACTLY in i32 on the int8 MXU path (2x
            # bf16 throughput) and dequantize once at the end.  The clip
            # guards foreign (off-grid) inputs from int8 wrap, like
            # histogram_int8.py.  Exactness bound: per-bin integer sums
            # stay exact up to 2^31/|q|max rows per bin (~16.9M at the
            # |q|=127 extreme, ~1e9 at the default 4-bin grid) and the f32
            # dequantize is exact below 2^24 — beyond that the path is
            # approximate like the bf16 one, not wrong (clip keeps
            # per-addend magnitudes sane).
            qg = jnp.clip(jnp.round(gm * inv_g), -127, 127).astype(jnp.int8)
            qh = jnp.clip(jnp.round(hm * inv_h), -127, 127).astype(jnp.int8)
            ghcq = jnp.concatenate(
                [
                    qg[:, None],
                    qh[:, None],
                    m.astype(jnp.int8)[:, None],
                    jnp.zeros((TILE, 1), jnp.int8),
                ],
                axis=1,
            )  # [TILE, 4]
            _accumulate(ghcq, jnp.int8, jnp.int32)
            return 0
        # THREE-term bf16 split of each f32 addend (~26 mantissa bits) —
        # the matmul M-dim pads 6 -> 8 sublanes anyway, so the two extra
        # residual rows are free MXU work (ADVICE r2: tighter precision
        # contract at zero cost)
        g_hi = gm.astype(jnp.bfloat16)
        g_r1 = gm - g_hi.astype(jnp.float32)
        g_lo = g_r1.astype(jnp.bfloat16)
        g_lo2 = (g_r1 - g_lo.astype(jnp.float32)).astype(jnp.bfloat16)
        h_hi = hm.astype(jnp.bfloat16)
        h_r1 = hm - h_hi.astype(jnp.float32)
        h_lo = h_r1.astype(jnp.bfloat16)
        h_lo2 = (h_r1 - h_lo.astype(jnp.float32)).astype(jnp.bfloat16)
        ghc8 = jnp.concatenate(
            [
                g_hi[:, None],
                h_hi[:, None],
                m.astype(jnp.bfloat16)[:, None],
                g_lo[:, None],
                h_lo[:, None],
                jnp.zeros((TILE, 1), jnp.bfloat16),
                g_lo2[:, None],
                h_lo2[:, None],
            ],
            axis=1,
        )  # [TILE, 8]
        _accumulate(ghc8, jnp.bfloat16, jnp.float32)
        return 0

    lax.fori_loop(0, nt, body, 0)
    if quantized:
        row0 = acc[0, :].astype(jnp.float32) * scales_ref[0]
        row1 = acc[1, :].astype(jnp.float32) * scales_ref[1]
        row2 = acc[2, :].astype(jnp.float32)
    else:
        # rows: 0 g_hi, 1 h_hi, 2 count, 3 g_lo, 4 h_lo, 5 zero,
        # 6 g_lo2, 7 h_lo2
        row0 = acc[0, :] + acc[3, :] + acc[6, :]
        row1 = acc[1, :] + acc[4, :] + acc[7, :]
        row2 = acc[2, :] + acc[5, :]
    return row0, row1, row2


def _seg_hist_kernel(
    scal_ref,  # SMEM [K, 2] i32: (start, cnt) per grid program (K=1 serial)
    scales_ref,  # SMEM [2] f32: g_scale, h_scale (quantized mode; else 1s)
    seg_any,  # ANY [LANES, n_pad] i16 (plane-major)
    out_ref,  # VMEM [3, F * bpad] f32 (batched: [1, 3, F * bpad] block)
    in_stage,  # VMEM [SUB, TILE] i16 — only the used planes are DMA'd
    acc,  # VMEM [8 | 4, F * bpad] f32 | i32
    onehot,  # VMEM [TILE, group * bpad] bf16 | i8
    sem_in,
    *,
    f: int,
    bpad: int,
    group: int,
    sub: int,
    quantized: bool,
    wide: bool,
    batched: bool = False,
):
    i = pl.program_id(0)

    def read_fn(base_col):
        dma = pltpu.make_async_copy(
            seg_any.at[
                pl.ds(0, sub),
                pl.ds(pl.multiple_of(base_col, COL_ALIGN), TILE),
            ],
            in_stage,
            sem_in,
        )
        dma.start()
        dma.wait()
        return in_stage[...].astype(jnp.int32) & 0xFFFF

    row0, row1, row2 = _hist_window(
        scal_ref[i, 0],
        scal_ref[i, 1],
        read_fn,
        scales_ref,
        acc,
        onehot,
        f=f,
        bpad=bpad,
        group=group,
        quantized=quantized,
        wide=wide,
    )
    if batched:
        out_ref[0, 0, :] = row0
        out_ref[0, 1, :] = row1
        out_ref[0, 2, :] = row2
    else:
        out_ref[0, :] = row0
        out_ref[1, :] = row1
        out_ref[2, :] = row2


@functools.partial(
    instrumented_jit,
    static_argnames=("f", "num_bins", "n_pad", "quantized", "wide", "interpret"),
)
def seg_hist_pallas(
    seg: jnp.ndarray,
    scal: jnp.ndarray,  # [2] i32: start, cnt
    scales: Optional[jnp.ndarray] = None,  # [2] f32 grid scales (quantized)
    *,
    f: int,
    num_bins: int,
    n_pad: int,
    quantized: bool = False,
    wide: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """Histogram [F, B, 3] (g, h, count) of packed rows [start, start+cnt).

    ``quantized=True`` (requires ``scales``): integer grid accumulation on
    the int8 MXU path — exact and ~2x the bf16 throughput."""
    bpad = hist_bpad(num_bins)
    group = hist_group(f, bpad)
    sub = hist_sub(f, wide)
    kernel = functools.partial(
        _seg_hist_kernel, f=f, bpad=bpad, group=group, sub=sub,
        quantized=quantized, wide=wide,
    )
    if scales is None:
        scales = jnp.ones((2,), jnp.float32)
    out = pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((3, f * bpad), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((sub, TILE), jnp.int16),
            pltpu.VMEM(
                (4, f * bpad) if quantized else (8, f * bpad),
                jnp.int32 if quantized else jnp.float32,
            ),
            pltpu.VMEM(
                (TILE, group * bpad), jnp.int8 if quantized else jnp.bfloat16
            ),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(scal.reshape(1, 2), scales.astype(jnp.float32), seg)
    return out.reshape(3, f, bpad)[:, :, :num_bins].transpose(1, 2, 0)


@functools.partial(
    instrumented_jit,
    static_argnames=("f", "num_bins", "n_pad", "quantized", "wide", "interpret"),
)
def seg_hist_pallas_batch(
    seg: jnp.ndarray,
    scal: jnp.ndarray,  # [K, 2] i32: (start, cnt) per batch member
    scales: Optional[jnp.ndarray] = None,
    *,
    f: int,
    num_bins: int,
    n_pad: int,
    quantized: bool = False,
    wide: bool = False,
    interpret: bool = False,
) -> jnp.ndarray:
    """K histograms [K, F, B, 3] of K disjoint packed-row windows in ONE
    launch: a K-program grid over the serial kernel (TPU grid programs run
    sequentially on the core, so the shared staging/accumulator scratch is
    reused safely program-to-program).  Frontier-batched growth
    (ops/grower.py leaf_batch) uses this to build all K smaller-child
    histograms per step with one program's fixed cost."""
    k = scal.shape[0]
    bpad = hist_bpad(num_bins)
    group = hist_group(f, bpad)
    sub = hist_sub(f, wide)
    kernel = functools.partial(
        _seg_hist_kernel, f=f, bpad=bpad, group=group, sub=sub,
        quantized=quantized, wide=wide, batched=True,
    )
    if scales is None:
        scales = jnp.ones((2,), jnp.float32)
    out = pl.pallas_call(
        kernel,
        grid=(k,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (1, 3, f * bpad), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((k, 3, f * bpad), jnp.float32),
        scratch_shapes=[
            pltpu.VMEM((sub, TILE), jnp.int16),
            pltpu.VMEM(
                (4, f * bpad) if quantized else (8, f * bpad),
                jnp.int32 if quantized else jnp.float32,
            ),
            pltpu.VMEM(
                (TILE, group * bpad), jnp.int8 if quantized else jnp.bfloat16
            ),
            pltpu.SemaphoreType.DMA,
        ],
        interpret=interpret,
    )(scal.astype(jnp.int32), scales.astype(jnp.float32), seg)
    return out.reshape(k, 3, f, bpad)[:, :, :, :num_bins].transpose(0, 2, 3, 1)


def seg_hist_ref(seg: jnp.ndarray, scal: jnp.ndarray, *, f: int, num_bins: int,
                 n_pad: int, wide: bool = False):
    """Pure-JAX reference/CPU path: masked histogram over the whole array
    (static shapes; rows outside [start, start+cnt) masked out)."""
    from ..histogram import leaf_histogram_segment

    start, cnt = scal[0], scal[1]
    bins, g, h, m, _ = unpack_stats(seg, f, wide=wide)
    idx = jnp.arange(seg.shape[1], dtype=jnp.int32)
    window = (idx >= start) & (idx < start + cnt)
    return leaf_histogram_segment(bins, g, h, m * window.astype(jnp.float32), num_bins)


def seg_hist(seg, scal, *, f: int, num_bins: int, n_pad: int,
             quant_scales=None, wide: bool = False):
    """Platform dispatch: Pallas on TPU (int8 grid accumulation when
    ``quant_scales`` is given — quantized training), masked full pass
    elsewhere."""
    quantized = quant_scales is not None
    scales = (
        jnp.stack([quant_scales[0], quant_scales[1]]).astype(jnp.float32)
        if quantized
        else jnp.ones((2,), jnp.float32)
    )
    if jax.default_backend() != "tpu":
        # no TPU registered: older jax lowers every platform_dependent
        # branch and the Pallas one cannot lower for CPU
        return seg_hist_ref(seg, scal, f=f, num_bins=num_bins, n_pad=n_pad,
                            wide=wide)
    return jax.lax.platform_dependent(
        seg,
        scal,
        scales,
        tpu=functools.partial(
            seg_hist_pallas, f=f, num_bins=num_bins, n_pad=n_pad,
            quantized=quantized, wide=wide,
        ),
        default=lambda seg, scal, _s: seg_hist_ref(
            seg, scal, f=f, num_bins=num_bins, n_pad=n_pad, wide=wide
        ),
    )


def seg_hist_batch(seg, scal_k, *, f: int, num_bins: int, n_pad: int,
                   quant_scales=None, wide: bool = False):
    """K-window histogram dispatch ([K, 2] (start, cnt) -> [K, F, B, 3]):
    one K-program Pallas launch on TPU, a vmapped masked full pass
    elsewhere."""
    quantized = quant_scales is not None
    scales = (
        jnp.stack([quant_scales[0], quant_scales[1]]).astype(jnp.float32)
        if quantized
        else jnp.ones((2,), jnp.float32)
    )

    def _ref(seg, scal_k, _s):
        return jax.vmap(
            lambda s: seg_hist_ref(
                seg, s, f=f, num_bins=num_bins, n_pad=n_pad, wide=wide
            )
        )(scal_k)

    if jax.default_backend() != "tpu":
        return _ref(seg, scal_k, scales)
    return jax.lax.platform_dependent(
        seg,
        scal_k,
        scales,
        tpu=functools.partial(
            seg_hist_pallas_batch, f=f, num_bins=num_bins, n_pad=n_pad,
            quantized=quantized, wide=wide,
        ),
        default=_ref,
    )
