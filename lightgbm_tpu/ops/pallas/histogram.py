"""Pallas TPU histogram kernel — the framework's hottest op.

Reference analogs: the scalar gather loop ``DenseBin::ConstructHistogramInner``
(src/io/dense_bin.hpp:99) and the CUDA shared-memory kernel
(src/treelearner/cuda/cuda_histogram_constructor.cu:19-130,
NUM_DATA_PER_THREAD/SHARED_HIST_SIZE tuning in the .hpp).

TPU formulation: TPUs have no fast scatter-add, so the per-row bin increment
becomes a dense one-hot contraction on the MXU.  The naive per-feature matmul
``[TR,B] x [TR,3]`` has a 3-wide output — ~2% of the MXU lane width — so this
kernel instead:

  * tiles rows into VMEM (grid over row tiles, accumulating across steps);
  * builds the one-hot for a GROUP of features at once into a VMEM scratch
    ``[TR, FG*B_pad]`` via per-feature iota compares (VPU work, one [TR,B]
    block store per feature — no MXU involvement);
  * contracts ``ghc8[TR, 8] x onehot[TR, FG*B_pad] -> [8, FG*B_pad]`` — the
    contraction (TR) and lane (FG*B_pad ~ 2048) dims are both MXU-sized, so
    one wide matmul replaces FG narrow ones;
  * ghc8 packs (g, h) as a THREE-term bf16 split plus count hi/lo (the
    one-hot factor is exact in bf16 and the residuals carry ~16 extra
    mantissa bits — the 8-row operand is exactly the MXU's output sublane
    tile, so the extra residual rows are free; histogram engine v2 made
    the third term and the 8-row layout the default);
  * emits the RAW [8, F*bpad] accumulator planes — 8 sublanes is the
    f32/i32 VMEM tile height (GL005-clean, no baselined layout needed) —
    and the term recombine runs OUTSIDE the kernel in plain XLA
    (seg.combine_hist_raw, shared with the seg kernels).

HBM traffic is exactly bins + ghc read once; the VMEM-resident accumulation
mirrors the CUDA kernel's shared-memory histogram.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...obs.jit import instrumented_jit
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

# jax renamed TPUCompilerParams -> CompilerParams; support both
_CompilerParams = (
    getattr(pltpu, "CompilerParams", None)
    or getattr(pltpu, "TPUCompilerParams", None)
    if pltpu is not None
    else None
)

_TILE_ROWS = 1024
_TARGET_LANES = 2048  # FG*B_pad per matmul


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _hist_kernel(
    bins_ref,
    ghc_ref,
    out_ref,
    onehot_ref,
    *,
    num_features: int,
    bpad: int,
    group: int,
):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    ghc_t = ghc_ref[...]  # [TR, 3] f32 (mask already folded in)
    bins_t = bins_ref[...].astype(jnp.int32)  # [TR, F]
    tr = ghc_t.shape[0]
    # THREE-term bf16 split of g/h (count's residual is zero) packed as one
    # [TR, 8] operand -> single wide matmul.  Row convention (shared with
    # seg._hist_window / combine_hist_raw): 0 g_hi, 1 h_hi, 2 count,
    # 3 g_lo, 4 h_lo, 5 c_lo, 6 g_lo2, 7 h_lo2.
    ghc_hi = ghc_t.astype(jnp.bfloat16)
    r1 = ghc_t - ghc_hi.astype(jnp.float32)
    ghc_lo = r1.astype(jnp.bfloat16)
    ghc_lo2 = (r1[:, :2] - ghc_lo[:, :2].astype(jnp.float32)).astype(
        jnp.bfloat16
    )
    ghc8 = jnp.concatenate([ghc_hi, ghc_lo, ghc_lo2], axis=1)  # [TR, 8]

    iota = jax.lax.broadcasted_iota(jnp.int32, (tr, bpad), 1)
    ngroups = (num_features + group - 1) // group
    for gi in range(ngroups):
        base = gi * group
        nf = min(group, num_features - base)
        for j in range(nf):
            col = bins_t[:, base + j]
            onehot_ref[:, j * bpad : (j + 1) * bpad] = (
                col[:, None] == iota
            ).astype(jnp.bfloat16)
        if nf < group:  # tail group: clear stale columns
            onehot_ref[:, nf * bpad :] = jnp.zeros(
                (tr, (group - nf) * bpad), jnp.bfloat16
            )
        part = jax.lax.dot_general(
            ghc8,
            onehot_ref[...],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [8, FG*bpad]
        width = nf * bpad  # tail group writes only its live columns
        out_ref[:, base * bpad : base * bpad + width] += part[:, :width]


def tile_pallas_histogram(
    bins, ghc, num_bins, kernel_body, scratch_dtype, out_dtype, interpret
):
    """Shared tile/pad/group machinery for the histogram kernels (bf16
    3-term and 2-digit int8): rows tiled into VMEM, features grouped to
    ~_TARGET_LANES lanes, accumulation across row tiles.  Returns the RAW
    accumulator planes ([8, F*bpad], bpad) — callers recombine outside the
    kernel via seg.combine_hist_raw."""
    n, f = bins.shape
    bpad = _round_up(max(num_bins, 1), 128)
    group = min(max(1, _TARGET_LANES // bpad), f)
    tr = min(_TILE_ROWS, max(256, 1 << (n - 1).bit_length() if n > 1 else 256))
    pad = (-n) % tr
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        ghc = jnp.pad(ghc, ((0, pad), (0, 0)))
    tiles = (n + pad) // tr
    kernel = functools.partial(
        kernel_body, num_features=f, bpad=bpad, group=group
    )
    out = pl.pallas_call(
        kernel,
        grid=(tiles,),
        in_specs=[
            pl.BlockSpec((tr, f), lambda i: (i, 0)),
            pl.BlockSpec((tr, ghc.shape[1]), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((8, f * bpad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, f * bpad), out_dtype),
        scratch_shapes=[pltpu.VMEM((tr, group * bpad), scratch_dtype)],
        interpret=interpret,
        compiler_params=(
            _CompilerParams(dimension_semantics=("arbitrary",))
            if not interpret
            else None
        ),
    )(bins, ghc)
    return out, bpad


@functools.partial(instrumented_jit, static_argnames=("num_bins", "interpret"))
def histogram_pallas(
    bins: jnp.ndarray,  # [N, F] integer bins (int8/uint8/int32 ...)
    grad: jnp.ndarray,  # [N] f32
    hess: jnp.ndarray,  # [N] f32
    mask: jnp.ndarray,  # [N] f32
    num_bins: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Masked histogram [F, B, 3] = (sum_g, sum_h, count) per (feature, bin)."""
    n, f = bins.shape
    if f == 0:  # all-constant datasets: platform_dependent traces all branches
        return jnp.zeros((0, num_bins, 3), jnp.float32)
    if pltpu is None:  # no TPU pallas support in this install
        from ..histogram import leaf_histogram_segment

        return leaf_histogram_segment(bins, grad, hess, mask, num_bins)
    from .seg import combine_hist_raw

    ghc = jnp.stack([grad * mask, hess * mask, mask], axis=1)  # [N, 3]
    out, bpad = tile_pallas_histogram(
        bins, ghc, num_bins, _hist_kernel, jnp.bfloat16, jnp.float32, interpret
    )
    # raw [8, F*bpad] planes -> recombined [F, B, 3] outside the kernel
    return combine_hist_raw(
        out[None, None],
        jnp.ones((2,), jnp.float32),
        f=f, bpad=bpad, group=f, num_bins=num_bins, quantized=False,
    )[0]
