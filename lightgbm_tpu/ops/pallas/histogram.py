"""Pallas TPU histogram kernel — the framework's hottest op.

Reference analogs: the scalar gather loop ``DenseBin::ConstructHistogramInner``
(src/io/dense_bin.hpp:99) and the CUDA shared-memory kernel
(src/treelearner/cuda/cuda_histogram_constructor.cu:19-130,
NUM_DATA_PER_THREAD/SHARED_HIST_SIZE tuning in the .hpp).

TPU formulation: TPUs have no fast scatter-add, so the per-row bin increment
becomes a dense masked accumulation — but materializing the one-hot
``[rows, F, B]`` in HBM is a bandwidth disaster (measured 20x slowdown).
This kernel tiles rows into VMEM, forms each feature's ``[tile, B]`` one-hot
IN VMEM via an iota compare, and contracts it against the ``[tile, 3]``
(g, h, count) panel on the MXU, accumulating ``[F, B, 3]`` in the output ref
across sequential grid steps.  HBM traffic is exactly bins + ghc once — the
VMEM-resident accumulation mirrors the CUDA kernel's shared-memory histogram.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_TILE_ROWS = 2048


def _hist_kernel(bins_ref, ghc_ref, out_ref, *, num_features: int, num_bins: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    ghc_t = ghc_ref[...]  # [TR, 3] f32 (mask already folded in)
    bins_t = bins_ref[...]  # [TR, F] int32
    iota = jax.lax.iota(jnp.int32, num_bins)
    # Split each stat into two bf16 terms (hi + lo).  The one-hot factor is
    # exactly representable in bf16, so both partial products are EXACT and
    # only the f32 accumulation rounds — full fp32-sum accuracy at bf16 MXU
    # speed (2 fast passes instead of 6 under Precision.HIGHEST).
    ghc_hi = ghc_t.astype(jnp.bfloat16)
    ghc_lo = (ghc_t - ghc_hi.astype(jnp.float32)).astype(jnp.bfloat16)
    for f in range(num_features):
        col = bins_t[:, f]
        onehot = (col[:, None] == iota[None, :]).astype(jnp.bfloat16)  # [TR, B]
        dims = (((0,), (0,)), ((), ()))
        part = jax.lax.dot_general(
            onehot, ghc_hi, dimension_numbers=dims, preferred_element_type=jnp.float32
        ) + jax.lax.dot_general(
            onehot, ghc_lo, dimension_numbers=dims, preferred_element_type=jnp.float32
        )  # [B, 3]
        out_ref[f, :, :] += part


@functools.partial(jax.jit, static_argnames=("num_bins", "interpret"))
def histogram_pallas(
    bins: jnp.ndarray,  # [N, F] int32
    grad: jnp.ndarray,  # [N] f32
    hess: jnp.ndarray,  # [N] f32
    mask: jnp.ndarray,  # [N] f32
    num_bins: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Masked histogram [F, B, 3] = (sum_g, sum_h, count) per (feature, bin)."""
    n, f = bins.shape
    ghc = jnp.stack([grad * mask, hess * mask, mask], axis=1)  # [N, 3]
    tr = min(_TILE_ROWS, max(256, 1 << (n - 1).bit_length()))
    pad = (-n) % tr
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        ghc = jnp.pad(ghc, ((0, pad), (0, 0)))
    tiles = (n + pad) // tr

    kernel = functools.partial(_hist_kernel, num_features=f, num_bins=num_bins)
    return pl.pallas_call(
        kernel,
        grid=(tiles,),
        in_specs=[
            pl.BlockSpec((tr, f), lambda i: (i, 0)),
            pl.BlockSpec((tr, 3), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((f, num_bins, 3), lambda i: (0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((f, num_bins, 3), jnp.float32),
        interpret=interpret,
        compiler_params=(
            pltpu.CompilerParams(dimension_semantics=("arbitrary",))
            if (pltpu is not None and not interpret)
            else None
        ),
    )(bins.astype(jnp.int32), ghc)
