"""Per-leaf best-split scan as one Pallas kernel — the split-phase
fixed-cost killer.

Reference analog: the CUDA per-(leaf, feature) scan kernel
``FindBestSplitsForLeafKernel`` (src/treelearner/cuda/
cuda_best_split_finder.cu:776): take a leaf's histogram, produce each
feature's best (gain, threshold, missing-direction, left stats) in one
launch.  The XLA formulation (ops/split.py best_split) builds [C, F, B]
gain tensors through several fused-but-separate HBM-bound ops; at small
leaf counts the per-split FIXED cost (dispatch + launch chain) dominates
the v5e-16 north-star arithmetic (BENCH_NOTES r4: 0.2 ms/split => ~10
iters/s at 10.5M rows).  This kernel does the whole scan in VMEM:
cumulative sums by triangular matmul (exact for counts, ~2^-26 relative
for g/h via the three-digit bf16 split), gain evaluation, and per-feature
argmax, emitting an [F, 8] result row per feature.

Covers the BASIC numeric path (the hot one): no categorical, monotone,
path smoothing, CEGB, or extra-trees randomization — ``fused_eligible``
in ops/grower.py gates dispatch; everything else stays on best_split.
Missing-value direction handling (NaN bin counted left vs right) IS
covered, matching FindBestThresholdSequentially's two-direction scan
(src/treelearner/feature_histogram.hpp:832).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...obs.jit import instrumented_jit
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

_EPS = 1e-15
_NEG = float("-inf")  # plain float: a jnp scalar would be captured as a
#                       pallas closure constant, which is rejected

# tests flip this to route the grower's fused path through interpret mode
# off-TPU (production dispatch requires a real TPU backend)
_INTERPRET = False


def _digits3(x):
    """Split f32 [1, B] into three bf16 digit rows, exact to ~26 bits
    (integers < 2^24 split exactly — counts ride this for exact cumsums)."""
    d0 = x.astype(jnp.bfloat16)
    r1 = x - d0.astype(jnp.float32)
    d1 = r1.astype(jnp.bfloat16)
    d2 = (r1 - d1.astype(jnp.float32)).astype(jnp.bfloat16)
    return d0, d1, d2


def _split_scan_kernel(
    par_ref,  # SMEM [4] f32: parent g, h, cnt, pad
    num_ref,  # SMEM [F] i32: total bins per feature (incl. NaN bin)
    nanb_ref,  # SMEM [F] i32: NaN-bin index, -1 if none
    mask_ref,  # SMEM [F] f32: feature mask (col sampling / interaction)
    hist_ref,  # VMEM [3, F * bpad] f32 (g, h, count — plane-major)
    tri_ref,  # VMEM [bpad, bpad] bf16: tri[j, i] = (j <= i)
    out_ref,  # VMEM [fpad, 128] f32: per-feature
    #          (gain, bin, dl, lg, lh, lc, 0...) rows
    *,
    f: int,
    bpad: int,
    l1: float,
    l2: float,
    min_data: int,
    min_hess: float,
):
    pg = par_ref[0]
    ph = par_ref[1]
    pc = par_ref[2]
    iota_l = lax.broadcasted_iota(jnp.int32, (1, bpad), 1)
    iota_f32 = iota_l.astype(jnp.float32)
    iota_o = lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    out_ref[...] = jnp.zeros_like(out_ref)

    def leaf_gain(g, h):
        if l1 > 0.0:
            t = jnp.where(g > l1, g - l1, jnp.where(g < -l1, g + l1, 0.0))
        else:
            t = g
        return (t * t) / (h + l2 + _EPS)

    for fj in range(f):
        sl = slice(fj * bpad, (fj + 1) * bpad)
        gb = hist_ref[0:1, sl]  # [1, bpad] f32
        hb = hist_ref[1:2, sl]
        cb = hist_ref[2:3, sl]
        nb = nanb_ref[fj]
        nbins = num_ref[fj]
        fm = mask_ref[fj]

        # NaN-bin stats out, ordered cumsum over the rest (split.py:148-158)
        is_nan = (iota_l == nb).astype(jnp.float32)  # nb = -1 matches nothing
        nan_g = jnp.sum(gb * is_nan)
        nan_h = jnp.sum(hb * is_nan)
        nan_c = jnp.sum(cb * is_nan)
        keep = 1.0 - is_nan
        rows = []
        for x in (gb * keep, hb * keep, cb * keep):
            rows.extend(_digits3(x))
        digits = jnp.concatenate(
            rows + [jnp.zeros((7, bpad), jnp.bfloat16)], axis=0
        )  # [16, bpad]
        cum = lax.dot_general(
            digits, tri_ref[...],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [16, bpad] inclusive cumsums of the digit rows
        cg = cum[0:1] + cum[1:2] + cum[2:3]
        ch = cum[3:4] + cum[4:5] + cum[5:6]
        cc = cum[6:7] + cum[7:8] + cum[8:9]

        # candidate validity: threshold t in [0, num_ordered - 2]
        has_nan = 1 - ((nb >> 31) & 1)  # i32 0/1, no scalar-bool select
        num_ordered = nbins - has_nan
        base_ok = ((iota_l < num_ordered - 1).astype(jnp.float32)) * fm

        def dir_gain(lg_v, lh_v, lc_v, extra_ok):
            rg, rh, rc = pg - lg_v, ph - lh_v, pc - lc_v
            ok = (
                base_ok * extra_ok
                * (lc_v >= min_data).astype(jnp.float32)
                * (rc >= min_data).astype(jnp.float32)
                * (lh_v >= min_hess).astype(jnp.float32)
                * (rh >= min_hess).astype(jnp.float32)
            )
            gain = leaf_gain(lg_v, lh_v) + leaf_gain(rg, rh)
            return jnp.where(ok > 0.5, gain, _NEG)

        gain_r = dir_gain(cg, ch, cc, 1.0)  # missing -> right
        gain_l = dir_gain(
            cg + nan_g, ch + nan_h, cc + nan_c,
            jnp.float32(has_nan),  # only distinct when a NaN bin exists
        )

        m_r = jnp.max(gain_r)
        m_l = jnp.max(gain_l)
        # strictly-greater: ties keep missing->right, matching best_split's
        # case-major argmax order (case 0 = right first)
        go_left = m_l > m_r
        best_gain = jnp.maximum(m_r, m_l)
        cb_vec = jnp.broadcast_to(go_left, gain_r.shape)
        gwin = jnp.where(cb_vec, gain_l, gain_r)
        # first bin achieving the max (ties -> lowest bin, as in argmax)
        bin_f = jnp.min(jnp.where(gwin == best_gain, iota_f32, float(bpad)))
        onehot = (iota_f32 == bin_f).astype(jnp.float32)
        lg_vec = jnp.where(cb_vec, cg + nan_g, cg)
        lh_vec = jnp.where(cb_vec, ch + nan_h, ch)
        lc_vec = jnp.where(cb_vec, cc + nan_c, cc)
        lg_w = jnp.sum(lg_vec * onehot)
        lh_w = jnp.sum(lh_vec * onehot)
        lc_w = jnp.sum(lc_vec * onehot)

        # within-feature runner-up over BOTH directions (winner's (dir, bin)
        # excluded) — the grower's near-tie margin combines this with the
        # other features' best rows (fused_best_split)
        glose = jnp.where(cb_vec, gain_r, gain_l)
        sec = jnp.maximum(
            jnp.max(jnp.where(onehot > 0.0, _NEG, gwin)), jnp.max(glose)
        )

        row = jnp.where(iota_o == 0, best_gain, 0.0)
        row = jnp.where(iota_o == 1, bin_f, row)
        row = jnp.where(iota_o == 2, go_left.astype(jnp.float32), row)
        row = jnp.where(iota_o == 3, lg_w, row)
        row = jnp.where(iota_o == 4, lh_w, row)
        row = jnp.where(iota_o == 5, lc_w, row)
        row = jnp.where(iota_o == 6, sec, row)
        out_ref[fj, :] = row[0, :]


@functools.partial(
    instrumented_jit,
    static_argnames=(
        "f", "num_bins_pad", "l1", "l2", "min_data", "min_hess", "interpret"
    ),
)
def split_scan_pallas(
    hist: jnp.ndarray,  # [F, B, 3] f32 leaf histogram
    parent: jnp.ndarray,  # [3] f32 (g, h, cnt)
    num_bins: jnp.ndarray,  # [F] i32
    nan_bins: jnp.ndarray,  # [F] i32
    feature_mask: jnp.ndarray,  # [F] bool/f32
    *,
    f: int,
    num_bins_pad: int,
    l1: float,
    l2: float,
    min_data: int,
    min_hess: float,
    interpret: bool = False,
) -> jnp.ndarray:
    """Per-feature best numeric split rows [F, 8]:
    (gain, bin, default_left, left_g, left_h, left_cnt, second_gain, 0)."""
    bpad = (max(num_bins_pad, 1) + 127) // 128 * 128
    b = hist.shape[1]
    if b < bpad:
        hist = jnp.pad(hist, ((0, 0), (0, bpad - b), (0, 0)))
    h3 = hist.transpose(2, 0, 1).reshape(3, f * bpad).astype(jnp.float32)
    fpad = max(8, -(-f // 8) * 8)
    tri = jnp.tril(jnp.ones((bpad, bpad), jnp.bfloat16)).T  # tri[j,i] = j<=i
    par4 = jnp.concatenate(
        [parent.astype(jnp.float32), jnp.zeros((1,), jnp.float32)]
    )
    kernel = functools.partial(
        _split_scan_kernel, f=f, bpad=bpad, l1=float(l1), l2=float(l2),
        min_data=int(min_data), min_hess=float(min_hess),
    )
    out = pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fpad, 128), jnp.float32),
        interpret=interpret,
    )(
        par4,
        num_bins.astype(jnp.int32),
        nan_bins.astype(jnp.int32),
        feature_mask.astype(jnp.float32),
        h3,
        tri,
    )
    return out[:f, :8]


def fused_best_split(
    hist, parent_g, parent_h, parent_cnt, num_bins, nan_bins, feature_mask,
    *,
    lambda_l1: float,
    lambda_l2: float,
    min_data_in_leaf: int,
    min_sum_hessian_in_leaf: float,
    min_gain_to_split: float,
    feature_contri=None,
    interpret: bool = False,
    with_margin: bool = False,
):
    """best_split (basic numeric path) backed by the Pallas scan kernel.

    Returns the same SplitCandidate best_split would for configurations
    fused_eligible() admits (tie order differs only on exact cross-feature
    float-gain ties).

    ``feature_contri`` ([F] f32): per-feature gain multipliers (reference
    FeatureMetainfo::penalty) — applied OUTSIDE the kernel to the
    per-feature improvement rows before the cross-feature argmax, mirroring
    best_split's penalized path.

    ``with_margin``: also return the relative gain gap between the winner
    and the global runner-up (other features' best rows + the winning
    feature's in-kernel second-best, row col 6) — the int8-default
    histogram path's near-tie trigger (non-finite gains -> +inf margin,
    i.e. nothing to refine)."""
    from ..split import SplitCandidate, leaf_gain

    f, b, _ = hist.shape
    rows = split_scan_pallas(
        hist,
        jnp.stack([
            jnp.asarray(parent_g, jnp.float32),
            jnp.asarray(parent_h, jnp.float32),
            jnp.asarray(parent_cnt, jnp.float32),
        ]),
        num_bins, nan_bins, feature_mask,
        f=f, num_bins_pad=b, l1=lambda_l1, l2=lambda_l2,
        min_data=min_data_in_leaf, min_hess=min_sum_hessian_in_leaf,
        interpret=interpret,
    )
    gains = rows[:, 0]
    parent_gain = leaf_gain(
        jnp.asarray(parent_g, jnp.float32), jnp.asarray(parent_h, jnp.float32),
        lambda_l1, lambda_l2,
    )
    if feature_contri is not None:
        imp_f = gains - parent_gain - min_gain_to_split
        scaled = jnp.where(
            jnp.isfinite(gains),
            imp_f * feature_contri.astype(jnp.float32),
            -jnp.inf,
        )
        feat = jnp.argmax(scaled).astype(jnp.int32)
        r = rows[feat]
        improvement = scaled[feat]
    else:
        feat = jnp.argmax(gains).astype(jnp.int32)
        r = rows[feat]
        improvement = r[0] - parent_gain - min_gain_to_split
    improvement = jnp.where(jnp.isfinite(r[0]), improvement, -jnp.inf)
    if with_margin:
        # global runner-up gain: best of the OTHER features vs the winning
        # feature's own second-best (kernel row col 6); the parent/min_gain
        # offset cancels in (best - second) so raw gains suffice
        other = jnp.max(
            jnp.where(
                jnp.arange(f, dtype=jnp.int32) == feat, -jnp.inf, gains
            )
        ) if f > 1 else jnp.float32(-jnp.inf)
        sec = jnp.maximum(other, r[6])
        margin = jnp.where(
            jnp.isfinite(r[0]) & jnp.isfinite(sec),
            (r[0] - sec) / jnp.maximum(jnp.abs(r[0]), _EPS),
            jnp.inf,
        ).astype(jnp.float32)
    cand = SplitCandidate(
        gain=improvement.astype(jnp.float32),
        feature=feat,
        bin=r[1].astype(jnp.int32),
        default_left=r[2] > 0.5,
        left_g=r[3],
        left_h=r[4],
        left_cnt=r[5],
        right_g=parent_g - r[3],
        right_h=parent_h - r[4],
        right_cnt=parent_cnt - r[5],
        is_cat=jnp.asarray(False),
        cat_mask=jnp.zeros((1,), bool),
    )
    return (cand, margin) if with_margin else cand
