"""Integer histogram kernel for quantized-gradient training.

Reference analog: the 16/32-bit packed integer histogram accumulation that
quantized training enables in the reference
(src/treelearner/gradient_discretizer.cpp + feature_histogram.hpp's
PACKED_HIST_BIN_T int paths).

With ``use_quantized_grad`` the per-row (g, h) are small integers times a
scale (ops/quantize.py). This kernel recovers the int8 values, one-hots the
bins as int8, and contracts int8 x int8 -> int32 on the MXU — EXACT integer
accumulation (no bf16 hi/lo split needed) at twice the bf16 MXU rate. The
dequantized [F, B, 3] f32 histogram comes out multiplied by the scales, so
it drops into the existing split search unchanged.

Selected explicitly via ``hist_method='pallas_int8'`` (grower params); the
'auto' path keeps the bf16 hi/lo kernel until the int8 lowering is validated
on real hardware — interpret-mode tests pin numerics meanwhile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...obs.jit import instrumented_jit
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from .histogram import tile_pallas_histogram


def _hist_kernel_int8(
    bins_ref,
    ghc_ref,  # [TR, 3] int8 (already masked)
    out_ref,  # [3, F*bpad] int32
    onehot_ref,  # [TR, FG*bpad] int8 scratch
    *,
    num_features: int,
    bpad: int,
    group: int,
):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    ghc_t = ghc_ref[...]  # [TR, 3] int8
    bins_t = bins_ref[...].astype(jnp.int32)
    tr = ghc_t.shape[0]
    iota = jax.lax.broadcasted_iota(jnp.int32, (tr, bpad), 1)
    ngroups = (num_features + group - 1) // group
    for gi in range(ngroups):
        base = gi * group
        nf = min(group, num_features - base)
        for j in range(nf):
            col = bins_t[:, base + j]
            onehot_ref[:, j * bpad : (j + 1) * bpad] = (
                col[:, None] == iota
            ).astype(jnp.int8)
        if nf < group:
            onehot_ref[:, nf * bpad :] = jnp.zeros(
                (tr, (group - nf) * bpad), jnp.int8
            )
        part = jax.lax.dot_general(
            ghc_t,
            onehot_ref[...],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [3, FG*bpad] int32 — exact
        width = nf * bpad
        out_ref[:, base * bpad : base * bpad + width] += part[:, :width]


@functools.partial(
    instrumented_jit, static_argnames=("num_bins", "interpret")
)
def histogram_pallas_int8(
    bins: jnp.ndarray,  # [N, F] integer bins
    grad: jnp.ndarray,  # [N] f32 — QUANTIZED grid values (k * g_scale)
    hess: jnp.ndarray,  # [N] f32 — quantized grid values (k * h_scale)
    mask: jnp.ndarray,  # [N] f32 in {0, 1}
    num_bins: int,
    g_scale: jnp.ndarray,  # scalar f32
    h_scale: jnp.ndarray,  # scalar f32
    interpret: bool = False,
) -> jnp.ndarray:
    """[F, B, 3] (sum_g, sum_h, count) from int8 MXU accumulation."""
    n, f = bins.shape
    if f == 0:
        return jnp.zeros((0, num_bins, 3), jnp.float32)
    if pltpu is None:  # pragma: no cover
        from ..histogram import leaf_histogram_segment

        return leaf_histogram_segment(bins, grad, hess, mask, num_bins)
    m8 = mask.astype(jnp.int8)
    # grid integers are bounded by num_grad_quant_bins (<= 127, enforced by
    # quantize_gradients); the clip guards foreign inputs from int8 wrap
    qg = jnp.clip(jnp.round(grad / g_scale), -127, 127).astype(jnp.int8) * m8
    qh = jnp.clip(jnp.round(hess / h_scale), -127, 127).astype(jnp.int8) * m8
    ghc = jnp.stack([qg, qh, m8], axis=1)  # [N, 3] int8
    out, bpad = tile_pallas_histogram(
        bins, ghc, num_bins, _hist_kernel_int8, jnp.int8, jnp.int32, interpret
    )
    hist_i = out.reshape(3, f, bpad)[:, :, :num_bins].transpose(1, 2, 0)
    scales = jnp.stack(
        [g_scale.astype(jnp.float32), h_scale.astype(jnp.float32), jnp.float32(1.0)]
    )
    return hist_i.astype(jnp.float32) * scales
