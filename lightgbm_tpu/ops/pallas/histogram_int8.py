"""Integer histogram kernel for quantized-gradient training.

Reference analog: the 16/32-bit packed integer histogram accumulation that
quantized training enables in the reference
(src/treelearner/gradient_discretizer.cpp + feature_histogram.hpp's
PACKED_HIST_BIN_T int paths).

With ``use_quantized_grad`` the per-row (g, h) are small integers times a
scale (ops/quantize.py). This kernel recovers the grid integers as a
2-DIGIT int8 pair (q = hi*128 + lo, |hi| <= 127, |lo| <= 64 — histogram
engine v2's shared convention, see seg.py), one-hots the bins as int8, and
contracts int8 x int8 -> int32 on the MXU — EXACT integer accumulation on
the quantized grid (no bf16 hi/lo split needed) at twice the bf16 MXU
rate.  The kernel emits the RAW [8, F*bpad] i32 accumulator planes (the
i32 VMEM tile height — GL005-clean); the digit recombine/dequantize runs
outside in seg.combine_hist_raw, so the [F, B, 3] f32 histogram drops into
the existing split search unchanged.

Selected explicitly via ``hist_method='pallas_int8'`` (grower params); the
seg fast path engages the same 2-digit accumulation by DEFAULT via
``hist_acc`` (ops/grower.py), with an f32 re-accumulate for near ties.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...obs.jit import instrumented_jit
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from .histogram import tile_pallas_histogram
from .seg import QMAX, combine_hist_raw


def _hist_kernel_int8(
    bins_ref,
    ghc_ref,  # [TR, 8] int8 2-digit rows (already masked; built outside)
    out_ref,  # [8, F*bpad] int32 — RAW accumulator planes
    onehot_ref,  # [TR, FG*bpad] int8 scratch
    *,
    num_features: int,
    bpad: int,
    group: int,
):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    ghc_t = ghc_ref[...]  # [TR, 8] int8
    bins_t = bins_ref[...].astype(jnp.int32)
    tr = ghc_t.shape[0]
    iota = jax.lax.broadcasted_iota(jnp.int32, (tr, bpad), 1)
    ngroups = (num_features + group - 1) // group
    for gi in range(ngroups):
        base = gi * group
        nf = min(group, num_features - base)
        for j in range(nf):
            col = bins_t[:, base + j]
            onehot_ref[:, j * bpad : (j + 1) * bpad] = (
                col[:, None] == iota
            ).astype(jnp.int8)
        if nf < group:
            onehot_ref[:, nf * bpad :] = jnp.zeros(
                (tr, (group - nf) * bpad), jnp.int8
            )
        part = jax.lax.dot_general(
            ghc_t,
            onehot_ref[...],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )  # [8, FG*bpad] int32 — exact
        width = nf * bpad
        out_ref[:, base * bpad : base * bpad + width] += part[:, :width]


def int8_digit_rows(grad, hess, mask, g_scale, h_scale):
    """[N, 8] int8 2-digit stat rows (g_hi, h_hi, m, g_lo, h_lo, 0, 0, 0):
    q = round(stat/scale) clipped to +-QMAX, split q = hi*128 + lo with the
    +64 bias so both digits are int8-safe (|hi| <= 127, |lo| <= 64).  On
    the quantized-training grid (|q| <= 127) the split is exact."""
    n = grad.shape[0]
    m = (mask > 0).astype(jnp.int32)
    qg = jnp.clip(jnp.round(grad / g_scale), -QMAX, QMAX).astype(jnp.int32) * m
    qh = jnp.clip(jnp.round(hess / h_scale), -QMAX, QMAX).astype(jnp.int32) * m
    g_hi = (qg + 64) >> 7
    g_lo = qg - (g_hi << 7)
    h_hi = (qh + 64) >> 7
    h_lo = qh - (h_hi << 7)
    return jnp.stack(
        [g_hi, h_hi, m, g_lo, h_lo, jnp.zeros_like(m), jnp.zeros_like(m),
         jnp.zeros_like(m)],
        axis=1,
    ).astype(jnp.int8)


@functools.partial(
    instrumented_jit, static_argnames=("num_bins", "interpret")
)
def histogram_pallas_int8(
    bins: jnp.ndarray,  # [N, F] integer bins
    grad: jnp.ndarray,  # [N] f32 — QUANTIZED grid values (k * g_scale)
    hess: jnp.ndarray,  # [N] f32 — quantized grid values (k * h_scale)
    mask: jnp.ndarray,  # [N] f32 in {0, 1}
    num_bins: int,
    g_scale: jnp.ndarray,  # scalar f32
    h_scale: jnp.ndarray,  # scalar f32
    interpret: bool = False,
) -> jnp.ndarray:
    """[F, B, 3] (sum_g, sum_h, count) from 2-digit int8 MXU accumulation."""
    n, f = bins.shape
    if f == 0:
        return jnp.zeros((0, num_bins, 3), jnp.float32)
    if pltpu is None:  # pragma: no cover
        from ..histogram import leaf_histogram_segment

        return leaf_histogram_segment(bins, grad, hess, mask, num_bins)
    ghc = int8_digit_rows(grad, hess, mask, g_scale, h_scale)
    out, bpad = tile_pallas_histogram(
        bins, ghc, num_bins, _hist_kernel_int8, jnp.int8, jnp.int32, interpret
    )
    scales = jnp.stack(
        [g_scale.astype(jnp.float32), h_scale.astype(jnp.float32)]
    )
    return combine_hist_raw(
        out[None, None], scales, f=f, bpad=bpad, group=f, num_bins=num_bins,
        quantized=True,
    )[0]
