"""Streaming in-place stable partition of packed rows — Pallas TPU kernel.

Reference analogs: ``DataPartition::Split`` (src/treelearner/data_partition.hpp:101)
and the CUDA partition pipeline (``GenDataToLeftBitVectorKernel`` -> prefix
sums -> ``SplitInnerKernel``, src/treelearner/cuda/cuda_data_partition.cu).

Why this kernel exists: the round-2 design partitioned a leaf's contiguous
window with ``lax.sort`` over pow-2 capacity buckets (ops/segpart.py).  That
was already the fastest pure-XLA formulation (~6 ns/row for the 44-byte
packed row), but it pays (a) a multi-pass comparison sort for what is a
1-bit-key partition, (b) up to 2x window overshoot from the pow-2 ladder,
and (c) a defensive full-array copy per ``lax.switch`` branch (~0.45 ms per
1M rows, measured).  This kernel streams the EXACT window once, tile by
tile, and compacts rows with ONE-HOT MATMULS — the MXU as a crossbar.  TPUs
have no vector scatter/compaction primitive; a permutation applied as a
``[T, W]`` 0/1 matrix multiply is exact (i16 planes split into two 0..255
byte planes, each exact in bf16) and runs at MXU rate, far above the
serialized per-element path XLA lowers gathers/scatters to.

Algorithm (stable, in place, ~2.5 HBM passes over the window):
  pass 1: stream aligned ``[SUB, T]`` tiles of the window left to right.
    Per tile: evaluate the split predicate on the packed bin byte, then
    matmul-compact the tile's LEFT rows (plus the sub-tile alignment
    prefix) into a VMEM staging buffer and its RIGHT rows (plus the
    alignment suffix) into a second staging buffer.  Full staged blocks
    flush with aligned DMA writes: the left stream writes IN PLACE (flush
    position provably trails the read cursor), the right stream writes to
    an HBM scratch buffer.
  pass 2: stream the right scratch back through the same staging machinery,
    appending after the left stream — every block write is 128-aligned, and
    the two passes together rewrite exactly the tiles pass 1 read.

Stability: both children preserve original row order (streams keep tile
order and the in-tile compaction keeps column order), so results are
bit-identical to the stable-sort path this replaces.

The per-window body is factored into ``_partition_window`` so the fused
grow-step kernel (ops/pallas/grow_step.py) can run partition + smaller-child
histogram in ONE launch; ``read_aliased_tile`` is the shared
read-through-the-output-alias helper both kernels use (see its docstring
for the interpret-mode aliasing pitfall it guards against).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ...obs.jit import instrumented_jit
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from .seg import COL_ALIGN, used_lanes

T = 256  # streaming tile columns (rows of training data)
W = 2 * T  # staging width: residual (< T) + one tile's append (<= T)


def _bytes_bf16(xu):
    """Split u16 values [SUB, T] into two exact-in-bf16 byte planes."""
    lo = (xu & 0xFF).astype(jnp.bfloat16)
    hi = ((xu >> 8) & 0xFF).astype(jnp.bfloat16)
    return lo, hi


def read_aliased_tile(seg_in, seg_out, stage, sem, base_col, *,
                      read_via_input: bool = False):
    """DMA one aligned ``[sub, cols]`` tile of an IN-PLACE (input/output-
    aliased) packed segment matrix into VMEM ``stage``; return u16-in-i32.

    Reads go through the OUTPUT alias, not the input ref: on TPU they are
    the same HBM buffer, but batched grids re-read boundary tiles an
    earlier program (or an earlier phase of the SAME program, in the fused
    grow-step kernel) already rewrote — adjacent leaf windows share
    COL_ALIGN blocks — and Pallas interpret mode only makes those writes
    visible on the output ref.  Shared by the seg partition kernel and the
    fused grow-step kernel (ops/pallas/grow_step.py).

    ``read_via_input=True`` recreates the PR-3 aliasing bug by reading the
    input ref instead — a TEST-ONLY knob for the regression test in
    tests/test_partition_kernel.py; never set it from production code.
    """
    sub, cols = stage.shape
    src = seg_in if read_via_input else seg_out
    dma = pltpu.make_async_copy(
        # the input-ref read below is unreachable in production: it only
        # engages under the test-only read_via_input knob documented above
        src.at[pl.ds(0, sub), pl.ds(pl.multiple_of(base_col, COL_ALIGN), cols)],  # graftlint: disable=GL002
        stage,
        sem,
    )
    dma.start()
    dma.wait()
    return stage[...].astype(jnp.int32) & 0xFFFF


def _partition_window(
    sbegin,  # scalar i32 — segment begin
    cnt,  # scalar i32 — segment rows (0 = no-op)
    feat,  # scalar i32 — split feature (used-feature index)
    tbin,  # scalar i32
    dl,  # scalar i32 (default-left)
    nanb,  # scalar i32 (NaN bin or -1)
    iscat,  # scalar i32
    seg_any,  # ANY [LANES, n_pad] i16 (aliased to seg_out)
    seg_out,  # ANY [LANES, n_pad] i16 (aliased with seg_any)
    scratch_out,  # ANY [SUB, n_pad] i16 — right-stream spill
    cat_ref,  # VMEM [1, bmt] f32 — bin -> goes-left (categorical)
    tri_ref,  # VMEM [T, T] bf16 — tri[i, j] = (i <= j), cumsum-by-matmul
    gl_any,  # ANY [1, n_pad] f32 go-left bits, or None when not use_gl
    in_stage,  # VMEM [SUB, T] i16
    out_stage,  # VMEM [SUB, T] i16
    stage_lo,  # VMEM [SUB, W] f32 — left/main stream staging (lo bytes)
    stage_hi,  # VMEM [SUB, W] f32
    rstage_lo,  # VMEM [SUB, W] f32 — right stream staging
    rstage_hi,  # VMEM [SUB, W] f32
    gl_stage,  # VMEM [1, T] f32 go-left tile, or None when not use_gl
    sem_in,
    sem_out,
    sem_gl,
    *,
    use_cat: bool,
    sub: int,
    wide: bool,
    bmt: int,
    use_gl: bool,
    read_via_input: bool = False,
):
    """Stable in-place partition of ONE leaf window (the per-program body of
    the seg partition kernel, factored out so the fused grow-step kernel can
    run it before its histogram phase).  Returns nl — rows going left."""
    abegin = (sbegin // COL_ALIGN) * COL_ALIGN
    off = sbegin - abegin
    nt = (off + cnt + T - 1) // T

    iota_j = jax.lax.broadcasted_iota(jnp.int32, (1, T), 1)
    # tpu.iota only produces integers; cast for the f32 dest compare.
    # [W, T] orientation: dest stays a [1, T] row (Mosaic cannot legalize
    # the [1, T] -> [T, 1] transpose) and the compact matmul contracts the
    # shared T dim of lo/hi and Q ("NT" form).
    iota_q = jax.lax.broadcasted_iota(jnp.int32, (W, T), 0).astype(jnp.float32)

    stage_lo[...] = jnp.zeros_like(stage_lo)
    stage_hi[...] = jnp.zeros_like(stage_hi)
    rstage_lo[...] = jnp.zeros_like(rstage_lo)
    rstage_hi[...] = jnp.zeros_like(rstage_hi)

    def _append(lo, hi, keep, fill, slo, shi):
        """Matmul-compact `keep` columns of the tile into staging at `fill`.

        P[j, w] = keep[j] & (dest[j] == w) with dest[j] = fill - 1 +
        (#kept among cols <= j); built from iota compares plus one
        cumsum-by-triangular-matmul — no scatter anywhere."""
        keepf = keep.astype(jnp.bfloat16)  # [1, T]
        csum = jax.lax.dot_general(
            keepf, tri_ref[...],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [1, T] inclusive cumsum
        nkeep = csum[0, T - 1].astype(jnp.int32)
        # fold `keep` into dest arithmetically (dropped rows -> -1, matching
        # no staging lane): kept rows have csum >= 1 so dest >= fill >= 0
        keep32 = keep.astype(jnp.float32)
        dest = (csum + (fill - 1).astype(jnp.float32)) * keep32 - (
            1.0 - keep32
        )  # [1, T]
        Q = (iota_q == dest).astype(jnp.bfloat16)  # [W, T] one-hot rows
        slo[...] += jax.lax.dot_general(
            lo, Q, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        shi[...] += jax.lax.dot_general(
            hi, Q, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return fill + nkeep

    def _combine_block(slo, shi):
        lo32 = slo[:, :T].astype(jnp.int32)
        hi32 = shi[:, :T].astype(jnp.int32)
        u16 = (lo32 | (hi32 << 8)).astype(jnp.uint16)
        out_stage[...] = jax.lax.bitcast_convert_type(u16, jnp.int16)

    def _flush(fill, nblk, slo, shi, dst, dst_base):
        """If a full block is staged, DMA it out and shift staging left."""
        do = fill >= T

        @pl.when(do)
        def _():
            _combine_block(slo, shi)
            dma = pltpu.make_async_copy(
                out_stage,
                dst.at[
                    pl.ds(0, sub),
                    pl.ds(pl.multiple_of(dst_base + nblk * T, COL_ALIGN), T),
                ],
                sem_out,
            )
            dma.start()
            dma.wait()
            slo[:, :T] = slo[:, T:]
            slo[:, T:] = jnp.zeros((sub, T), jnp.float32)
            shi[:, :T] = shi[:, T:]
            shi[:, T:] = jnp.zeros((sub, T), jnp.float32)

        doi = do.astype(jnp.int32)
        return fill - doi * T, nblk + doi

    def body1(t, carry):
        fill_l, bl, fill_r, br, nl = carry
        # boundary tiles must come through the OUTPUT alias — see
        # read_aliased_tile for the interpret-mode pitfall this guards
        xu = read_aliased_tile(
            seg_any, seg_out, in_stage, sem_in, abegin + t * T,
            read_via_input=read_via_input,
        )
        rpos = iota_j + t * T
        in_seg = (rpos >= off) & (rpos < off + cnt)
        if use_gl:
            # precomputed go-left bits (feature-parallel seg: the winner's
            # plane lives on the owning shard; the bits arrived by psum)
            dma = pltpu.make_async_copy(
                gl_any.at[
                    pl.ds(0, 1),
                    pl.ds(pl.multiple_of(abegin + t * T, COL_ALIGN), T),
                ],
                gl_stage,
                sem_gl,
            )
            dma.start()
            dma.wait()
            go = gl_stage[...] > 0.5  # [1, T]
        else:
            # Mosaic has no value-level dynamic_slice: extract the feature's
            # lane with a one-hot row matmul over the exact bf16 byte planes
            # (0..255 each — the MXU as a dynamic row gather)
            lane = feat if wide else feat >> 1
            lane_oh = (
                jax.lax.broadcasted_iota(jnp.int32, (1, sub), 1) == lane
            ).astype(jnp.bfloat16)
            xlo, xhi = _bytes_bf16(xu)
            row_lo = jax.lax.dot_general(
                lane_oh, xlo, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)  # [1, T]
            row_hi = jax.lax.dot_general(
                lane_oh, xhi, dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            ).astype(jnp.int32)
            if wide:
                # one u16 plane per feature (max_bin > 256)
                colv = row_lo | (row_hi << 8)  # [1, T]
            else:
                # scalar-cond select over a vector fails Mosaic
                # legalization; broadcast the condition first
                odd = jnp.broadcast_to((feat & 1) != 0, row_lo.shape)
                colv = jnp.where(odd, row_hi, row_lo)
            go = (colv <= tbin) | ((dl != 0) & (nanb >= 0) & (colv == nanb))
            if use_cat:
                oh = (
                    colv == jax.lax.broadcasted_iota(jnp.int32, (bmt, T), 0)
                ).astype(jnp.bfloat16)  # [bmt, T]
                catv = jax.lax.dot_general(
                    cat_ref[...].astype(jnp.bfloat16), oh,
                    dimension_numbers=(((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )  # [1, T]
                # select over f32 operands: an i1-operand select needs an
                # i1 truncation Mosaic does not implement
                gof = jnp.where(
                    jnp.broadcast_to(iscat != 0, go.shape),
                    catv, go.astype(jnp.float32),
                )
                go = gof > 0.5
        keep_l = (rpos < off) | (in_seg & go)
        keep_r = jnp.logical_not(keep_l)
        nl = nl + jnp.sum((in_seg & go).astype(jnp.int32))
        lo, hi = _bytes_bf16(xu)
        fill_l = _append(lo, hi, keep_l, fill_l, stage_lo, stage_hi)
        fill_l, bl = _flush(fill_l, bl, stage_lo, stage_hi, seg_out, abegin)
        fill_r = _append(lo, hi, keep_r, fill_r, rstage_lo, rstage_hi)
        fill_r, br = _flush(fill_r, br, rstage_lo, rstage_hi, scratch_out, 0)
        return fill_l, bl, fill_r, br, nl

    fill_l, bl, fill_r, br, nl = lax.fori_loop(
        0,
        nt,
        body1,
        (jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.int32(0), jnp.int32(0)),
    )

    # spill the partial right-stream block (cols beyond fill_r are garbage;
    # pass 2 masks them out via the stream length)
    @pl.when(fill_r > 0)
    def _():
        _combine_block(rstage_lo, rstage_hi)
        dma = pltpu.make_async_copy(
            out_stage,
            scratch_out.at[
                pl.ds(0, sub), pl.ds(pl.multiple_of(br * T, COL_ALIGN), T)
            ],
            sem_out,
        )
        dma.start()
        dma.wait()

    # ---- pass 2: append the right stream after the left stream
    sr = nt * T - off - nl  # right-stream length (rights + alignment suffix)
    nt2 = (sr + T - 1) // T

    def body2(t2, carry):
        fill_l, bl = carry
        xu = read_aliased_tile(
            scratch_out, scratch_out, in_stage, sem_in, t2 * T,
        )
        spos = iota_j + t2 * T
        keep = spos < sr
        lo, hi = _bytes_bf16(xu)
        fill_l = _append(lo, hi, keep, fill_l, stage_lo, stage_hi)
        fill_l, bl = _flush(fill_l, bl, stage_lo, stage_hi, seg_out, abegin)
        return fill_l, bl

    lax.fori_loop(0, nt2, body2, (fill_l, bl))
    return nl


def _seg_partition_kernel(
    scal_ref,  # SMEM [K, 8] i32: sbegin, cnt, feat, tbin, dl, nanb, iscat,
    #          pad — one row per grid program (K=1 for the serial call)
    seg_any,  # ANY [LANES, n_pad] i16 (aliased to seg_out)
    cat_ref,  # VMEM [1, 256] f32 — bin -> goes-left (categorical); batched
    #          calls block a [K, bmt] table to one row per program
    tri_ref,  # VMEM [T, T] bf16 — tri[i, j] = (i <= j), cumsum-by-matmul
    gl_any,  # ANY [1, n_pad] f32 — precomputed go-left bits (use_gl; else
    #          a [1, COL_ALIGN] dummy)
    seg_out,  # ANY [LANES, n_pad] i16 (aliased with seg_any)
    scratch_out,  # ANY [SUB, n_pad] i16 — right-stream spill
    nl_ref,  # SMEM [K, 1] i32 — rows of the segment going left, per program
    in_stage,  # VMEM [SUB, T] i16
    out_stage,  # VMEM [SUB, T] i16
    stage_lo,  # VMEM [SUB, W] f32 — left/main stream staging (lo bytes)
    stage_hi,  # VMEM [SUB, W] f32
    rstage_lo,  # VMEM [SUB, W] f32 — right stream staging
    rstage_hi,  # VMEM [SUB, W] f32
    gl_stage,  # VMEM [1, T] f32 — go-left tile (use_gl)
    sem_in,
    sem_out,
    sem_gl,
    *,
    f: int,
    n_pad: int,
    use_cat: bool,
    sub: int,
    wide: bool,
    bmt: int,
    use_gl: bool,
    read_via_input: bool = False,
):
    pid = pl.program_id(0)
    nl = _partition_window(
        scal_ref[pid, 0],
        scal_ref[pid, 1],
        scal_ref[pid, 2],
        scal_ref[pid, 3],
        scal_ref[pid, 4],
        scal_ref[pid, 5],
        scal_ref[pid, 6],
        seg_any,
        seg_out,
        scratch_out,
        cat_ref,
        tri_ref,
        gl_any,
        in_stage,
        out_stage,
        stage_lo,
        stage_hi,
        rstage_lo,
        rstage_hi,
        gl_stage,
        sem_in,
        sem_out,
        sem_gl,
        use_cat=use_cat,
        sub=sub,
        wide=wide,
        bmt=bmt,
        use_gl=use_gl,
        read_via_input=read_via_input,
    )
    nl_ref[pid, 0] = nl


@functools.partial(
    instrumented_jit,
    static_argnames=("f", "n_pad", "use_cat", "wide", "interpret",
                     "read_via_input"),
)
def seg_partition_pallas(
    seg: jnp.ndarray,  # [LANES, n_pad] i16 plane-major packed rows
    scal: jnp.ndarray,  # [8] i32: sbegin, cnt, feat, tbin, dl, nanb, iscat, 0
    catmask: jnp.ndarray,  # [1, bmt] f32 (bmt >= 256, 128-multiple)
    gl_vec: jnp.ndarray = None,  # [n_pad] f32 go-left bits (featpar seg)
    *,
    f: int,
    n_pad: int,
    use_cat: bool,
    wide: bool = False,
    interpret: bool = False,
    read_via_input: bool = False,
):
    """Partition seg[sbegin : sbegin+cnt) by the split rule, in place.

    ``gl_vec``: the go-left decision comes from precomputed bits instead of
    the feature column (feature-parallel seg — only the owning shard holds
    the winner's bin plane).

    ``read_via_input``: test-only knob (see read_aliased_tile).

    Returns (seg', nl).  Left child lands at [sbegin, sbegin+nl), right at
    [sbegin+nl, sbegin+cnt), both in stable (original) order; every column
    outside the window keeps its value.
    """
    use_gl = gl_vec is not None
    # Mosaic requires second-minor DMA slice shapes in 8-sublane multiples
    sub = -(-used_lanes(f, wide) // 8) * 8
    lanes = seg.shape[0]
    tri = jnp.tril(jnp.ones((T, T), jnp.bfloat16)).T  # tri[i, j] = i <= j
    gl_arr = (
        gl_vec.reshape(1, n_pad).astype(jnp.float32)
        if use_gl
        else jnp.zeros((1, COL_ALIGN), jnp.float32)
    )
    kernel = functools.partial(
        _seg_partition_kernel, f=f, n_pad=n_pad, use_cat=use_cat, sub=sub,
        wide=wide, bmt=catmask.shape[1], use_gl=use_gl,
        read_via_input=read_via_input,
    )
    seg_new, _, nl = pl.pallas_call(
        kernel,
        grid=(1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((lanes, n_pad), jnp.int16),
            jax.ShapeDtypeStruct((sub, n_pad), jnp.int16),
            jax.ShapeDtypeStruct((1, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((sub, T), jnp.int16),
            pltpu.VMEM((sub, T), jnp.int16),
            pltpu.VMEM((sub, W), jnp.float32),
            pltpu.VMEM((sub, W), jnp.float32),
            pltpu.VMEM((sub, W), jnp.float32),
            pltpu.VMEM((sub, W), jnp.float32),
            pltpu.VMEM((1, T), jnp.float32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        input_output_aliases={1: 0},
        interpret=interpret,
    )(scal.reshape(1, 8), seg, catmask, tri, gl_arr)
    return seg_new, nl[0, 0]


@functools.partial(
    instrumented_jit,
    static_argnames=("f", "n_pad", "use_cat", "wide", "interpret",
                     "read_via_input"),
)
def seg_partition_pallas_batch(
    seg: jnp.ndarray,  # [LANES, n_pad] i16 plane-major packed rows
    scal: jnp.ndarray,  # [K, 8] i32 rows: sbegin, cnt, feat, tbin, dl,
    #                     nanb, iscat, 0 — one DISJOINT window per row
    catmask: jnp.ndarray,  # [K, bmt] f32 (bmt >= 256, 128-multiple)
    *,
    f: int,
    n_pad: int,
    use_cat: bool,
    wide: bool = False,
    interpret: bool = False,
    read_via_input: bool = False,
):
    """K in-place stable partitions over K disjoint windows in ONE launch.

    A K-program grid over the serial streaming kernel: TPU grid programs
    execute sequentially on the core, so the in-place aliasing and shared
    staging scratch stay safe — each program completes its read-rewrite of
    its (over-covered, boundary-preserving) window before the next starts.
    A zero-cnt row is a no-op (its window rewrite preserves every value).
    Frontier-batched growth (ops/grower.py leaf_batch) pays ONE program's
    fixed cost for K splits.

    ``read_via_input``: test-only knob (see read_aliased_tile).

    Returns (seg', nl[K])."""
    k = scal.shape[0]
    sub = -(-used_lanes(f, wide) // 8) * 8
    lanes = seg.shape[0]
    bmt = catmask.shape[1]
    tri = jnp.tril(jnp.ones((T, T), jnp.bfloat16)).T  # tri[i, j] = i <= j
    gl_arr = jnp.zeros((1, COL_ALIGN), jnp.float32)
    kernel = functools.partial(
        _seg_partition_kernel, f=f, n_pad=n_pad, use_cat=use_cat, sub=sub,
        wide=wide, bmt=bmt, use_gl=False, read_via_input=read_via_input,
    )
    seg_new, _, nl = pl.pallas_call(
        kernel,
        grid=(k,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            # one catmask row per program, so the kernel body sees the same
            # [1, bmt] block the serial call passes
            pl.BlockSpec((1, bmt), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((lanes, n_pad), jnp.int16),
            jax.ShapeDtypeStruct((sub, n_pad), jnp.int16),
            jax.ShapeDtypeStruct((k, 1), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((sub, T), jnp.int16),
            pltpu.VMEM((sub, T), jnp.int16),
            pltpu.VMEM((sub, W), jnp.float32),
            pltpu.VMEM((sub, W), jnp.float32),
            pltpu.VMEM((sub, W), jnp.float32),
            pltpu.VMEM((sub, W), jnp.float32),
            pltpu.VMEM((1, T), jnp.float32),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        input_output_aliases={1: 0},
        interpret=interpret,
    )(scal.astype(jnp.int32), seg, catmask, tri, gl_arr)
    return seg_new, nl[:, 0]
