"""Fused grow step — partition + smaller-child histogram in ONE Pallas launch.

The frontier-batched grower (ops/grower.py, leaf_batch=K) already amortizes
per-split fixed cost, but each compiled step still runs partition ->
election -> histogram as separately-launched regions with full HBM
round-trips and dispatch gaps between them (the 36% "bookkeeping" share in
BENCH_NOTES round 8).  This kernel fuses the per-member pipeline over a
PLANE-TILED ``(K, G)`` grid (batch member x feature-plane group — the
histogram-engine-v2 layout shared with seg.py): for each of the K disjoint
frontier windows, the member's FIRST plane program

  1. streams the window once and stably partitions it in place
     (partition._partition_window — the exact machinery of the standalone
     seg partition kernel);
  2. elects the smaller child locally and parks the decision in the
     persistent SMEM ``dec`` output (nl <= cnt - nl — the grower's
     single-host election; under tree_learner=data the election needs a
     psum of per-shard counts MID-STEP, which is why the fused path only
     engages when no axis_name is set and the two-launch path remains the
     data-parallel fallback);

and then EVERY plane program (i, pt) — grid programs run sequentially, so
(i, 0)'s writes are visible — reads the decision back and histograms its
plane group over the freshly-partitioned rows (seg._hist_window), reading
tiles through the OUTPUT alias so the histogram observes the partition's
writes (partition.read_aliased_tile — the same idiom that fixes
cross-program boundary reads, and the reason the fused kernel works at
all: the partition happened in an EARLIER program of the same sequential
grid).  Dead plane groups (feature_fraction / EFB) skip their tile loop
via the ``live`` mask.  Each program emits one RAW [8, group*bpad]
accumulator block (i32 on the int8 path, f32 on bf16); the digit
recombine runs outside the kernel (seg.combine_hist_raw).  The best-split
scan stays a separate launch: it needs the psummed histogram under
tree_learner=data and the parent-minus-child sibling subtraction, neither
of which is per-member-local.  On the basic numeric path it runs as the
existing fused Pallas scan (ops/pallas/split_scan.py), so the whole grow
step is two kernel launches instead of three compiled regions plus their
dispatch boundaries.

Plane-tiling trade (same as seg.py): per-program VMEM scratch shrinks to
O(group*bpad) — independent of F — at the cost of each plane program
re-streaming the window's stat planes (G-fold redundant DMA, hidden under
the one-hot matmul for every shape seg_vmem_ok admits).

The XLA composition (`sort_partition_xla` chain + local election + masked
reference histogram) is the always-available fallback AND the correctness
oracle — it is definitionally the same computation the two-launch grower
path performs (including the windowed CPU histogram, seg.seg_hist_cpu), so
CPU results are byte-identical by construction and tests/test_fused_step.py
asserts the Pallas kernel (interpret mode off-TPU) matches it bit-for-bit.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ...obs.jit import instrumented_jit
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from .partition import T, W, _partition_window, read_aliased_tile
from .seg import (
    COL_ALIGN,
    TILE,
    _hist_window,
    combine_hist_raw,
    hist_bpad,
    hist_group,
    hist_ngroups,
    hist_sub,
    used_lanes,
)

# Test hook: route the fused step through the Pallas interpret-mode kernel
# even off-TPU (tools/run_tests.sh smoke + tests/test_fused_step.py).  Read
# at TRACE time — flip it before the first train in a fresh process, or use
# params that force a fresh trace; a cached trace keeps the path it was
# traced with (the XLA oracle, which is parity-identical).
_INTERPRET = False


def _fused_grow_kernel(
    scal_ref,  # SMEM [K, 8] i32: sbegin, cnt, feat, tbin, dl, nanb, iscat, 0
    scales_ref,  # SMEM [2] f32: g_scale, h_scale (int8 mode; else 1s)
    live_ref,  # SMEM [G] i32: per-plane-group live mask
    seg_any,  # ANY [LANES, n_pad] i16 (aliased to seg_out)
    cat_ref,  # VMEM [1, bmt] f32 block — bin -> goes-left, one row/member
    tri_ref,  # VMEM [T, T] bf16 — tri[i, j] = (i <= j), cumsum-by-matmul
    gl_any,  # ANY [1, COL_ALIGN] f32 dummy (featpar never takes this path)
    seg_out,  # ANY [LANES, n_pad] i16 (aliased with seg_any)
    scratch_out,  # ANY [SUB_P, n_pad] i16 — partition right-stream spill
    dec_ref,  # SMEM [K, 4] i32: nl, nr, child_start, child_cnt per member
    hist_ref,  # VMEM [1, 1, 8, group * bpad] f32 | i32 block (raw planes)
    in_stage,  # VMEM [SUB_P, T] i16 — partition staging
    out_stage,  # VMEM [SUB_P, T] i16
    stage_lo,  # VMEM [SUB_P, W] f32
    stage_hi,  # VMEM [SUB_P, W] f32
    rstage_lo,  # VMEM [SUB_P, W] f32
    rstage_hi,  # VMEM [SUB_P, W] f32
    gl_stage,  # VMEM [1, T] f32 (unused: use_gl is always False here)
    hist_stage,  # VMEM [SUB_H, TILE] i16 — histogram staging
    acc,  # VMEM [8, group * bpad] f32 | i32
    onehot,  # VMEM [TILE, group * bpad] bf16 | i8
    sem_in,
    sem_out,
    sem_gl,
    sem_hist,
    *,
    f: int,
    n_pad: int,
    use_cat: bool,
    sub_p: int,
    sub_h: int,
    wide: bool,
    bmt: int,
    bpad: int,
    group: int,
    quantized: bool,
    read_via_input: bool = False,
):
    i = pl.program_id(0)
    pt = pl.program_id(1)
    sbegin = scal_ref[i, 0]
    cnt = scal_ref[i, 1]

    # ---- phases 1+2 run ONCE per member, on its first plane program
    @pl.when(pt == 0)
    def _partition_and_elect():
        # phase 1: in-place stable partition of this member's window
        nl = _partition_window(
            sbegin,
            cnt,
            scal_ref[i, 2],
            scal_ref[i, 3],
            scal_ref[i, 4],
            scal_ref[i, 5],
            scal_ref[i, 6],
            seg_any,
            seg_out,
            scratch_out,
            cat_ref,
            tri_ref,
            gl_any,
            in_stage,
            out_stage,
            stage_lo,
            stage_hi,
            rstage_lo,
            rstage_hi,
            gl_stage,
            sem_in,
            sem_out,
            sem_gl,
            use_cat=use_cat,
            sub=sub_p,
            wide=wide,
            bmt=bmt,
            use_gl=False,
            read_via_input=read_via_input,
        )
        # phase 2: local smaller-child election (single-host rule; the
        # data-parallel psummed election cannot live mid-kernel, so that
        # mode keeps the two-launch path — see module docstring).  The
        # decision lands in the persistent SMEM output so this member's
        # later plane programs can read it back.
        nr = cnt - nl
        left_smaller = nl <= nr
        dec_ref[i, 0] = nl
        dec_ref[i, 1] = nr
        dec_ref[i, 2] = sbegin + jnp.where(left_smaller, 0, nl)
        dec_ref[i, 3] = jnp.where(left_smaller, nl, nr)

    # ---- phase 3: this plane group's histogram over the JUST-partitioned
    # rows; tiles come through the output alias so phase 1's writes (from
    # this member's pt==0 program) are visible
    child_start = dec_ref[i, 2]
    child_cnt = dec_ref[i, 3]

    def read_fn(base_col):
        return read_aliased_tile(
            seg_any, seg_out, hist_stage, sem_hist, base_col,
            read_via_input=read_via_input,
        )

    _hist_window(
        child_start,
        child_cnt,
        pt,
        live_ref[pt],
        read_fn,
        scales_ref,
        acc,
        onehot,
        f=f,
        bpad=bpad,
        group=group,
        quantized=quantized,
        wide=wide,
    )
    hist_ref[0, 0] = acc[...]


@functools.partial(
    instrumented_jit,
    static_argnames=(
        "f", "num_bins", "n_pad", "use_cat", "quantized", "wide",
        "interpret", "read_via_input",
    ),
)
def fused_grow_step_pallas(
    seg: jnp.ndarray,  # [LANES, n_pad] i16 plane-major packed rows
    scal: jnp.ndarray,  # [K, 8] i32 rows: sbegin, cnt, feat, tbin, dl,
    #                     nanb, iscat, 0 — one DISJOINT window per member
    catmask: jnp.ndarray,  # [K, bmt] f32 (bmt >= 256, 128-multiple)
    scales: jnp.ndarray,  # [2] f32 grid scales (int8 mode; else 1s)
    live: jnp.ndarray,  # [G] i32 plane-group live mask
    *,
    f: int,
    num_bins: int,
    n_pad: int,
    use_cat: bool,
    quantized: bool = False,
    wide: bool = False,
    interpret: bool = False,
    read_via_input: bool = False,
):
    """K fused partition+election+histogram steps in ONE kernel launch.

    Returns (seg', dec[K, 4], hist[K, F, B, 3]) with dec rows
    (nl, nr, child_start, child_cnt).  Grid programs run sequentially on
    the core, so the in-place aliasing, the shared scratch, and the
    dec-written-at-pt==0 handoff stay safe program-to-program (same
    argument as the batched partition kernel)."""
    k = scal.shape[0]
    lanes = seg.shape[0]
    bmt = catmask.shape[1]
    # partition DMAs need second-minor 8-sublane multiples; hist tiles DMA
    # only the used planes padded to an i16 sublane multiple
    sub_p = -(-used_lanes(f, wide) // 8) * 8
    sub_h = hist_sub(f, wide)
    bpad = hist_bpad(num_bins)
    group = hist_group(f, bpad)
    ngroups = hist_ngroups(f, bpad)
    acc_dtype = jnp.int32 if quantized else jnp.float32
    tri = jnp.tril(jnp.ones((T, T), jnp.bfloat16)).T  # tri[i, j] = i <= j
    gl_arr = jnp.zeros((1, COL_ALIGN), jnp.float32)
    kernel = functools.partial(
        _fused_grow_kernel, f=f, n_pad=n_pad, use_cat=use_cat, sub_p=sub_p,
        sub_h=sub_h, wide=wide, bmt=bmt, bpad=bpad, group=group,
        quantized=quantized, read_via_input=read_via_input,
    )
    seg_new, _, dec, raw = pl.pallas_call(
        kernel,
        grid=(k, ngroups),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(
                (1, bmt), lambda i, pt: (i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(
                (1, 1, 8, group * bpad), lambda i, pt: (i, pt, 0, 0),
                memory_space=pltpu.VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((lanes, n_pad), jnp.int16),
            jax.ShapeDtypeStruct((sub_p, n_pad), jnp.int16),
            jax.ShapeDtypeStruct((k, 4), jnp.int32),
            jax.ShapeDtypeStruct((k, ngroups, 8, group * bpad), acc_dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((sub_p, T), jnp.int16),
            pltpu.VMEM((sub_p, T), jnp.int16),
            pltpu.VMEM((sub_p, W), jnp.float32),
            pltpu.VMEM((sub_p, W), jnp.float32),
            pltpu.VMEM((sub_p, W), jnp.float32),
            pltpu.VMEM((sub_p, W), jnp.float32),
            pltpu.VMEM((1, T), jnp.float32),
            pltpu.VMEM((sub_h, TILE), jnp.int16),
            pltpu.VMEM((8, group * bpad), acc_dtype),
            pltpu.VMEM(
                (TILE, group * bpad), jnp.int8 if quantized else jnp.bfloat16
            ),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
        ],
        input_output_aliases={3: 0},
        interpret=interpret,
    )(scal.astype(jnp.int32), scales.astype(jnp.float32),
      live.astype(jnp.int32), seg, catmask, tri, gl_arr)
    hist = combine_hist_raw(
        raw, scales.astype(jnp.float32), f=f, bpad=bpad, group=group,
        num_bins=num_bins, quantized=quantized,
    )
    return seg_new, dec, hist


def fused_grow_step(
    seg,
    sbegins,  # [K] i32 — segment begins (disjoint windows; K=1 for serial)
    cnts,  # [K] i32 — segment rows (0 = no-op member)
    feats,  # [K] i32
    tbins,  # [K] i32
    dls,  # [K] i32
    nanbs,  # [K] i32
    iscats,  # [K] i32
    catmasks,  # [K, Bm] f32
    *,
    f: int,
    num_bins: int,
    n_pad: int,
    quant_scales=None,
    wide: bool = False,
    live=None,  # [G] i32 plane-group live mask (None = all live)
):
    """Platform dispatch for the fused grow step.

    TPU: one (K, G)-program Pallas launch (2-digit int8 accumulation when
    ``quant_scales`` is given — quantized training or the grower's default
    hist accumulator, like seg_hist).  Elsewhere: the XLA oracle
    composition — sequential stable-sort partitions (disjoint windows make
    the chain order-independent), the same local election, and the
    windowed/masked reference histogram (seg.seg_hist_batch_cpu, the exact
    computation the two-launch grower path performs), so CPU training is
    byte-identical by construction.  The ``_INTERPRET`` hook routes off-TPU
    calls through the interpret-mode kernel instead, which is how tier-1
    exercises the kernel without a TPU.

    Returns (seg', nl[K], nr[K], child_start[K], child_cnt[K],
    hist[K, F, B, 3])."""
    # fault-injection consult (trace time — the moment a Mosaic compile
    # failure would surface); disarmed it costs one dict truthiness check
    from ...resilience import chaos

    chaos.maybe_raise_pallas("fused_grow_step")

    from ..segpart import sort_partition_xla
    from .seg import seg_hist_batch_cpu

    k = sbegins.shape[0]
    quantized = quant_scales is not None
    scales = (
        jnp.stack([quant_scales[0], quant_scales[1]]).astype(jnp.float32)
        if quantized
        else jnp.ones((2,), jnp.float32)
    )
    if live is None:
        live = jnp.ones((hist_ngroups(f, hist_bpad(num_bins)),), jnp.int32)

    def _pallas(seg, sbegins, cnts, feats, tbins, dls, nanbs, iscats,
                catmasks, scales, live, interpret=False):
        bm = catmasks.shape[1]
        bmt = max(256, -(-bm // 128) * 128)  # cat-table width (wide bins)
        catm = jnp.zeros((k, bmt), jnp.float32)
        catm = catm.at[:, :bm].set(catmasks.astype(jnp.float32))
        scal = jnp.stack(
            [sbegins, cnts, feats, tbins, dls, nanbs, iscats,
             jnp.zeros_like(sbegins)],
            axis=1,
        ).astype(jnp.int32)
        seg_new, dec, hist = fused_grow_step_pallas(
            seg, scal, catm, scales, live, f=f, num_bins=num_bins,
            n_pad=n_pad, use_cat=bm > 1, quantized=quantized, wide=wide,
            interpret=interpret,
        )
        return seg_new, dec[:, 0], dec[:, 1], dec[:, 2], dec[:, 3], hist

    def _xla(seg, sbegins, cnts, feats, tbins, dls, nanbs, iscats,
             catmasks, _scales, _live):
        # the oracle ignores quant_scales/live, matching seg_hist's CPU
        # behavior (f32 histograms of every plane — the byte-level
        # reference the int8/plane-skip fast path is validated against)
        nls = []
        for i in range(k):
            seg, nl_i, _ = sort_partition_xla(
                seg, sbegins[i], cnts[i], feats[i], tbins[i], dls[i],
                nanbs[i], iscats[i], catmasks[i],
                f=f, n_pad=n_pad, wide=wide, use_gl_vec=False,
            )
            nls.append(nl_i)
        nl = jnp.stack(nls)
        nr = cnts - nl
        left_smaller = nl <= nr
        child_start = sbegins + jnp.where(left_smaller, 0, nl)
        child_cnt = jnp.where(left_smaller, nl, nr)
        hist = seg_hist_batch_cpu(
            seg,
            jnp.stack([child_start, child_cnt], axis=1).astype(jnp.int32),
            f=f, num_bins=num_bins, n_pad=n_pad, wide=wide,
        )
        return seg, nl, nr, child_start, child_cnt, hist

    args = (seg, sbegins, cnts, feats, tbins, dls, nanbs, iscats, catmasks,
            scales, live)
    if jax.default_backend() != "tpu":
        if _INTERPRET:
            return _pallas(*args, interpret=True)
        return _xla(*args)
    return jax.lax.platform_dependent(*args, tpu=_pallas, default=_xla)
