"""Pallas forest-walk kernel — batched level-synchronous tree inference.

Reference analog: the fork's cache-blocked batch predictor
``PredictTreeBatchAVX512`` (include/LightGBM/tree_avx512.hpp:41): 8-row
level-synchronous walks with the tree resident in cache.  The TPU-native
formulation walks a 1024-row tile through EVERY tree with all trees' node
tables resident in VMEM.

Two layout decisions make it fast:
  * the walk state (current node per row) lives as ONE [8, 128] vreg per
    1024-row tile; node-table lookups are in-VMEM lane-gathers
    (``tpu.dynamic_gather`` spans one 128-lane vreg, so a 256-node table is
    two [8,128] gathers + a select — ~3 vector ops instead of the 16-vreg
    broadcasts a row-major formulation pays);
  * all per-node scalars (threshold, feature, default-left, NaN bin) are
    bit-packed into ONE i32 table, so a level costs two table lookups plus
    one bin fetch.

The XLA while-loop walker in predict.py pays ~35 ns/element of serialized
gather for each of these lookups; this kernel replaces them with VPU-rate
vector ops.

Supported: numeric splits in BIN space (v <= thr, NaN-bin default-left),
bin values < 256 (byte-packed), trees up to 256 nodes, F <= 128 features,
up to KPAD classes.  Categorical splits or wider models fall back to the
XLA walker.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

LANES = 128
ROW_TILE = 1024
MAX_NODES = 256  # two lane-gather halves
MAX_THR = 256  # bin values are byte-packed: thresholds/NaN bins must fit u8
#               (the packed node word has 9 bits of headroom, but fval reads
#               are 8-bit)
KPAD = 8  # output class columns padded for layout friendliness
BINS_PACKED = 32  # 128 features at 4 bins per i32 lane


class ForestTables(NamedTuple):
    """Per-tree node tables, shaped [T, 2, 128] (two lane-gather halves —
    the leading dim carries the tree index so per-tree slicing never hits
    the tiled-dim alignment rules)."""

    pk1: jnp.ndarray  # i32: thr | feat<<9 | dl<<16 | (nanb+1)<<17
    pk2: jnp.ndarray  # i32: (left+MAX_NODES) | (right+MAX_NODES)<<16 (negatives = ~leaf)
    leaf: jnp.ndarray  # f32 [T, 2, 128]: leaf value by LEAF index
    n_trees: int
    max_depth: int


def walk_eligible(
    records, nan_bins: np.ndarray, num_features: int, max_bin: int
) -> bool:
    """Numeric-only, <=255 splits/tree, bin space fits a byte."""
    if num_features > LANES:
        return False
    if max_bin > MAX_THR:
        # input bins would clip at 255 and could misroute at high thresholds
        return False
    if len(nan_bins) and int(np.max(nan_bins)) >= MAX_THR:
        return False  # NaN bin must fit the 8-bit fval (nanb+1 has 9 bits)
    for r in records:
        sf = r.get("split_feature")
        if sf is None or len(sf) >= MAX_NODES:
            return False
        sic = r.get("split_is_cat")
        if sic is not None and np.any(np.asarray(sic)):
            return False
        if len(sf) and int(np.max(np.asarray(r["split_bin"]))) >= MAX_THR:
            return False
    return True


def build_tables(records, nan_bins: np.ndarray) -> ForestTables:
    """Stack bin-space tree records (host dicts, see gbdt._bin_records) into
    kernel tables.  Caller must have checked `walk_eligible`."""
    t = len(records)
    pk1 = np.zeros((t, MAX_NODES), np.int32)
    pk2 = np.zeros((t, MAX_NODES), np.int32)
    leaf = np.zeros((t, MAX_NODES), np.float32)
    nan_bins = np.asarray(nan_bins, np.int64)
    max_depth = 1
    for i, r in enumerate(records):
        sf = np.asarray(r["split_feature"], np.int64)
        nn = len(sf)
        lv = np.asarray(r["leaf_value"], np.float32)
        leaf[i, : len(lv)] = lv
        if nn == 0:
            # single-leaf tree: node 0 routes every row to leaf 0
            pk2[i, 0] = (~0 + MAX_NODES) | ((~0 + MAX_NODES) << 16)
            continue
        thr = np.asarray(r["split_bin"], np.int64)
        dl = np.asarray(r["default_left"], np.int64)
        lc = np.asarray(r["left_child"], np.int64)
        rc = np.asarray(r["right_child"], np.int64)
        nb = nan_bins[sf] + 1  # 0 = no NaN bin
        pk1[i, :nn] = (thr | (sf << 9) | (dl << 16) | (nb << 17)).astype(np.int32)
        pk2[i, :nn] = ((lc + MAX_NODES) | ((rc + MAX_NODES) << 16)).astype(np.int32)
        depth = np.ones(nn, np.int32)
        for m in range(nn):
            for c in (lc[m], rc[m]):
                if c >= 0:
                    depth[c] = depth[m] + 1
        max_depth = max(max_depth, int(depth.max()) + 1)
    shape = (t, 2, LANES)
    return ForestTables(
        pk1=jnp.asarray(pk1.reshape(shape)),
        pk2=jnp.asarray(pk2.reshape(shape)),
        leaf=jnp.asarray(leaf.reshape(shape)),
        n_trees=t,
        max_depth=max_depth,
    )


def _lookup(table_2x128, cur):
    """table [2, 128] gathered by cur [8, 128] in [0, 256) -> [8, 128].
    One broadcast + two single-vreg lane-gathers + a select."""
    lo = jnp.broadcast_to(table_2x128[0:1, :], (8, LANES))
    hi = jnp.broadcast_to(table_2x128[1:2, :], (8, LANES))
    idx = cur & 127
    glo = jnp.take_along_axis(lo, idx, axis=1)
    ghi = jnp.take_along_axis(hi, idx, axis=1)
    return jnp.where(cur < 128, glo, ghi)


def _walk_kernel(
    bins_ref,  # VMEM [1, BINS_PACKED, 8, 128] i32 — 4 bins per i32, tile
    #           rows laid out as (sublane, lane); everything in the walk is a
    #           vreg-shaped [8, 128] op — no reshapes, no row-major crossings
    pk1_ref,  # VMEM [T, 2, 128] i32
    pk2_ref,
    leaf_ref,  # VMEM [T, 2, 128] f32
    out_ref,  # VMEM [1, KPAD, 8, 128] f32
    *,
    n_trees: int,
    max_depth: int,
    k: int,
):
    planes = [bins_ref[0, p] for p in range(BINS_PACKED)]  # 32 x [8, 128]
    out_ref[...] = jnp.zeros_like(out_ref)
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (KPAD, 8, LANES), 0)

    def select_plane(lane_idx):
        """31-select binary tree: out[s,l] = planes[lane_idx[s,l]][s,l]."""
        level_vals = planes
        for bit in range(5):
            b = (lane_idx >> bit) & 1
            level_vals = [
                jnp.where(b != 0, level_vals[2 * i + 1], level_vals[2 * i])
                for i in range(len(level_vals) // 2)
            ]
        return level_vals[0]

    def tree_body(t, _):
        pk1 = pk1_ref[t]  # [2, 128]
        pk2 = pk2_ref[t]
        lv = leaf_ref[t]

        def level(_, cur):
            curc = jnp.maximum(cur, 0)  # [8, 128]
            p1 = _lookup(pk1, curc)
            thr = p1 & 0x1FF
            feat = (p1 >> 9) & 0x7F
            dl = (p1 >> 16) & 1
            nb = ((p1 >> 17) & 0x1FF) - 1
            packed = select_plane(feat >> 2)
            fval = (packed >> ((feat & 3) * 8)) & 0xFF
            gl = (fval <= thr) | ((dl != 0) & (nb >= 0) & (fval == nb))
            p2 = _lookup(pk2, curc)
            child = jnp.where(gl, p2 & 0xFFFF, (p2 >> 16) & 0xFFFF) - MAX_NODES
            return jnp.where(cur >= 0, child, cur)

        nodes = lax.fori_loop(
            0, max_depth, level, jnp.zeros((8, LANES), jnp.int32)
        )
        val = jnp.where(
            nodes < 0,
            _lookup(lv, ~jnp.minimum(nodes, -1)),
            0.0,
        )
        col = t % k  # class of tree t (trees interleave classes)
        out_ref[0] += jnp.where(iota_k == col, val[None, :, :], 0.0)
        return 0

    lax.fori_loop(0, n_trees, tree_body, 0)


@functools.partial(
    jax.jit, static_argnames=("n_trees", "max_depth", "k", "interpret")
)
def forest_walk(
    bins: jnp.ndarray,  # [N_pad, BINS_PACKED] i32 (N_pad % ROW_TILE == 0)
    tables: ForestTables,
    *,
    n_trees: int,
    max_depth: int,
    k: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Raw scores [n_tiles, KPAD, 8, 128] (sum of leaf outputs per class;
    row n of tile i lives at [i, :, n // 128, n % 128])."""
    n_tiles = bins.shape[0]
    kernel = functools.partial(
        _walk_kernel, n_trees=n_trees, max_depth=max_depth, k=k
    )
    return pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, BINS_PACKED, 8, LANES), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((n_trees, 2, LANES), lambda i: (0, 0, 0)),
            pl.BlockSpec((n_trees, 2, LANES), lambda i: (0, 0, 0)),
            pl.BlockSpec((n_trees, 2, LANES), lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, KPAD, 8, LANES), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_tiles, KPAD, 8, LANES), jnp.float32),
        interpret=interpret,
    )(bins, tables.pk1, tables.pk2, tables.leaf)


@functools.partial(jax.jit, static_argnames=("n_pad",))
def _pack_bins_device(mat_u8: jnp.ndarray, n_pad: int) -> jnp.ndarray:
    """Device-side bin packing: [N, F] u8 -> [n_tiles, 32, 8, 128] i32."""
    n, f = mat_u8.shape
    b = jnp.zeros((n_pad, LANES), jnp.int32)
    b = b.at[:n, :f].set(mat_u8.astype(jnp.int32))
    packed = (
        b[:, 0::4] | (b[:, 1::4] << 8) | (b[:, 2::4] << 16) | (b[:, 3::4] << 24)
    )  # [n_pad, 32]
    return packed.reshape(n_pad // ROW_TILE, 8, LANES, BINS_PACKED).transpose(
        0, 3, 1, 2
    )


def pad_bins_for_walk(bins: np.ndarray) -> jnp.ndarray:
    """[N, F] int bins -> [n_tiles, BINS_PACKED, 8, 128] i32, 4 bins
    byte-packed per i32 (feature j in byte j&3 of pack j>>2); row n sits at
    [n // 1024, :, (n % 1024) // 128, n % 128].  Only the compact u8 matrix
    crosses host->device (the padded i32 form is 9x bigger — built on
    device)."""
    n, f = bins.shape
    n_pad = (n + ROW_TILE - 1) // ROW_TILE * ROW_TILE
    # clip: categorical columns may carry an out-of-range unseen-category
    # sentinel — numeric-only models never read them, but byte packing must
    # not bleed into neighbors
    mat_u8 = np.clip(bins, 0, 255).astype(np.uint8)
    return _pack_bins_device(jnp.asarray(mat_u8), n_pad)


def unpack_walk_scores(out: np.ndarray, n: int, k: int) -> np.ndarray:
    """[n_tiles, KPAD, 8, 128] -> [n, k] row-major scores."""
    t = out.shape[0]
    flat = out.transpose(0, 2, 3, 1).reshape(t * ROW_TILE, KPAD)
    return flat[:n, :k]


# ---------------------------------------------------------------------------
# device-side prediction binning (reference BinMapper::ValueToBin, bin.h:173)
# ---------------------------------------------------------------------------
#
# Host binning (searchsorted per feature) costs ~1.4s per 500k x 28 rows and
# dominated predict latency. On device, value->bin is a fused compare-reduce
# (bin = sum_b [ub_b < v], no gathers): ~ms at the same scale. Comparisons
# run in f32 (TPUs have no f64), so values within f32 epsilon of a bin
# boundary may bin differently from the f64 host path — the XLA-walker
# fallback keeps exact host binning.

def build_devbin_tables(mappers, used_features):
    """Pack numeric mappers into device arrays; None if any used feature is
    categorical (those need dict lookups — host binning handles them)."""
    ubs = []
    nanb = []
    mtype = []
    for j in used_features:
        m = mappers[j]
        if m.is_categorical:
            return None
        ubs.append(np.asarray(m.bin_upper_bound, np.float64))
        nanb.append(m.nan_bin)
        mtype.append(m.missing_type)
    bmax = max((len(u) for u in ubs), default=1)
    ub = np.full((len(ubs), bmax), np.inf, np.float64)
    for i, u in enumerate(ubs):
        ub[i, : len(u)] = u
    return (
        jnp.asarray(ub.astype(np.float32)),
        jnp.asarray(np.asarray(nanb, np.int32)),
        jnp.asarray(np.asarray(mtype, np.int32)),
    )


@jax.jit
def bin_numeric_device(
    X: jnp.ndarray,  # [N, F] f32 — used-feature columns
    ub: jnp.ndarray,  # [F, Bmax] f32, +inf padded
    nanb: jnp.ndarray,  # [F] i32
    mtype: jnp.ndarray,  # [F] i32
) -> jnp.ndarray:
    """Vectorized ValueToBin: searchsorted(ub, v, 'left') == sum(ub < v),
    with the NaN/zero missing rules of the host path."""
    from ...binning import K_ZERO_THRESHOLD, MissingType

    isnan = jnp.isnan(X)
    safe = jnp.where(isnan, 0.0, X)
    # fused compare+reduce per feature: no [N, F, Bmax] materialization
    bins = jnp.sum(
        ub[None, :, :] < safe[:, :, None], axis=2, dtype=jnp.int32
    )
    miss_zero = (mtype[None, :] == MissingType.ZERO) & (
        isnan | (jnp.abs(safe) <= K_ZERO_THRESHOLD)
    )
    miss_nan = (mtype[None, :] == MissingType.NAN) & isnan & (nanb[None, :] >= 0)
    return jnp.where(miss_zero | miss_nan, nanb[None, :], bins)
