"""Pallas forest-walk kernel — batched level-synchronous tree inference.

Reference analog: the fork's cache-blocked batch predictor
``PredictTreeBatchAVX512`` (include/LightGBM/tree_avx512.hpp:41): 8-row
level-synchronous walks with the tree resident in cache; categorical and
missing handling inline (:112-168).  The TPU-native formulation walks a
1024-row tile through EVERY tree with all trees' node tables resident in
VMEM.

Layout decisions:
  * the walk state (current node per row) lives as ONE [8, 128] vreg per
    1024-row tile; node-table lookups are in-VMEM lane-gathers
    (``tpu.dynamic_gather`` spans one 128-lane vreg, so an H*128-node table
    is H [8,128] gathers + a select tree — a handful of vector ops instead
    of the 16-vreg broadcasts a row-major formulation pays);
  * all per-node scalars (threshold, feature, default-left, NaN bin,
    is-categorical) are bit-packed into ONE i32 table, so a level costs two
    table lookups plus one bin fetch;
  * categorical splits read one word of the node's 256-bit category bitset:
    eight word-tables indexed like the node tables, selected by fval>>5
    (the reference's ``FindInBitset``, tree.h:346, as vector ops).

Supported: numeric + categorical splits in BIN space, bin values < 256
(byte-packed), trees up to 512 nodes / 512 leaves, F <= 512 features (4 per
i32 lane across ceil(F/128) plane groups; the plane-select tree deepens
with F), any class count (output padded to a multiple of 8).  Wider-bin
models fall back to the XLA walker.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

from ...obs.jit import instrumented_jit
from jax import lax
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

LANES = 128
ROW_TILE = 1024
MAX_NODES = 512  # hard cap (4 lane-gather halves); per-model H is smaller
MAX_THR = 256  # bin values are byte-packed: thresholds/NaN bins must fit u8
MAX_F = 512  # feature cap: 9-bit feature field, 128 packed i32 planes
KPAD = 8  # minimum output class columns (padded to a multiple of 8)
CAT_WORDS = 8  # 256-bit category bitset = 8 i32 words per node
VMEM_TABLE_BUDGET = 12 * 1024 * 1024  # fall back when tables outgrow VMEM


def n_planes(num_features: int) -> int:
    """Packed i32 bin planes for F features: pow2(ceil(F/4)), min 32."""
    p = 32
    while p * 4 < num_features:
        p *= 2
    return p


def tile_bucket(n_rows: int) -> int:
    """Bucketed tile count for an n_rows walk: the power-of-two ceiling of
    ceil(n_rows / ROW_TILE).  The pallas grid is sized by tile count, so
    without bucketing every distinct row count compiles a fresh executable;
    with it a stream of arbitrary batch sizes reuses a small ladder of
    cached programs (the streaming engine's bucket contract)."""
    tiles = max(1, -(-n_rows // ROW_TILE))
    b = 1
    while b < tiles:
        b <<= 1
    return b


def bucket_pad_rows(n_rows: int) -> int:
    """Row count padded to the tile-bucket boundary (bucket-shape entry:
    feed `pad_bins_for_walk`/`_pack_bins_device` this many rows)."""
    return tile_bucket(n_rows) * ROW_TILE


class ForestTables(NamedTuple):
    """Per-tree node tables, shaped [T, H, 128] (H lane-gather halves — the
    leading dim carries the tree index so per-tree slicing never hits the
    tiled-dim alignment rules)."""

    pk1: jnp.ndarray  # i32: thr | feat<<9 | dl<<18 | (nanb+1)<<19 | cat<<28
    pk2: jnp.ndarray  # i32: (left+m_nodes) | (right+m_nodes)<<16 (neg = ~leaf)
    leaf: jnp.ndarray  # f32 [T, H, 128]: leaf value by LEAF index
    catw: jnp.ndarray  # i32 [T, CAT_WORDS, H, 128] category bitset words
    #                    ([1, 1, 1, 128] dummy when the model has no cat)
    n_trees: int
    max_depth: int
    m_nodes: int  # 128 * H
    has_cat: bool


def walk_reject_reason(
    records, nan_bins: np.ndarray, num_features: int, max_bin: int
):
    """None when the kernel can run this model, else a human-readable reason
    (<=511 splits/tree, bin space fits a byte, F <= 512; categorical OK)."""
    if num_features > MAX_F:
        return f"{num_features} features > {MAX_F}"
    if max_bin > MAX_THR:
        # input bins would clip at 255 and could misroute at high thresholds
        return f"max_bin {max_bin} > {MAX_THR} (bins must fit a byte)"
    if len(nan_bins) and int(np.max(nan_bins)) >= MAX_THR:
        # NaN bin must fit the 8-bit fval (nanb+1 has 9 bits)
        return f"NaN bin {int(np.max(nan_bins))} >= {MAX_THR}"
    n_nodes_max = 1
    has_cat = False
    for r in records:
        sf = r.get("split_feature")
        if sf is None or len(sf) >= MAX_NODES:
            return (
                "a tree has no bin-space record"
                if sf is None
                else f"a tree has {len(sf)} splits >= {MAX_NODES}"
            )
        n_nodes_max = max(n_nodes_max, len(sf) + 1)
        sic = r.get("split_is_cat")
        if sic is not None and np.any(np.asarray(sic)):
            has_cat = True
            cm = r.get("cat_mask")
            if cm is None or (np.size(cm) and np.asarray(cm).shape[-1] > 256):
                return "a categorical mask is wider than 256 bins"
            cma = np.asarray(cm)
            if np.size(cma) and cma.shape[-1] == 256 and np.any(cma[..., 255]):
                # pad_bins_for_walk clips the unseen-category sentinel to
                # 255: if a real mask claims bin 255 goes left, the clipped
                # sentinel would misroute left (the walker/reference sends
                # unseen categories right) — fall back
                return "a categorical mask claims bin 255 (sentinel clash)"
        if len(sf) and int(np.max(np.asarray(r["split_bin"]))) >= MAX_THR:
            return f"a split threshold bin >= {MAX_THR}"
    h = max(1, -(-n_nodes_max // LANES))
    if h == 3:
        h = 4  # build_tables pads to a power-of-two of halves
    table_bytes = len(records) * h * LANES * 4 * (3 + (CAT_WORDS if has_cat else 0))
    if table_bytes > VMEM_TABLE_BUDGET:
        return (
            f"node tables ({table_bytes >> 20} MiB for {len(records)} trees) "
            "exceed the VMEM budget"
        )
    return None


def walk_eligible(
    records, nan_bins: np.ndarray, num_features: int, max_bin: int
) -> bool:
    return walk_reject_reason(records, nan_bins, num_features, max_bin) is None


def build_tables(records, nan_bins: np.ndarray) -> ForestTables:
    """Stack bin-space tree records (host dicts, see gbdt._bin_records) into
    kernel tables.  Caller must have checked `walk_eligible`."""
    t = len(records)
    n_nodes_max = 1
    has_cat = False
    for r in records:
        n_nodes_max = max(n_nodes_max, len(r["split_feature"]) + 1)
        sic = r.get("split_is_cat")
        if sic is not None and np.any(np.asarray(sic)):
            has_cat = True
    h = max(1, -(-n_nodes_max // LANES))
    if h == 3:
        h = 4  # select tree wants a power of two of halves
    m_nodes = h * LANES
    pk1 = np.zeros((t, m_nodes), np.int32)
    pk2 = np.zeros((t, m_nodes), np.int32)
    leaf = np.zeros((t, m_nodes), np.float32)
    catw = (
        np.zeros((t, CAT_WORDS, m_nodes), np.int32)
        if has_cat
        else np.zeros((1, 1, 1, LANES), np.int32)
    )
    nan_bins = np.asarray(nan_bins, np.int64)
    max_depth = 1
    for i, r in enumerate(records):
        sf = np.asarray(r["split_feature"], np.int64)
        nn = len(sf)
        lv = np.asarray(r["leaf_value"], np.float32)
        leaf[i, : len(lv)] = lv
        if nn == 0:
            # single-leaf tree: node 0 routes every row to leaf 0
            pk2[i, 0] = (~0 + m_nodes) | ((~0 + m_nodes) << 16)
            continue
        thr = np.asarray(r["split_bin"], np.int64)
        dl = np.asarray(r["default_left"], np.int64)
        lc = np.asarray(r["left_child"], np.int64)
        rc = np.asarray(r["right_child"], np.int64)
        nb = nan_bins[sf] + 1  # 0 = no NaN bin
        sic = r.get("split_is_cat")
        cat = (
            np.asarray(sic, np.int64)
            if sic is not None and np.size(sic)
            else np.zeros(nn, np.int64)
        )
        pk1[i, :nn] = (
            thr | (sf << 9) | (dl << 18) | (nb << 19) | (cat << 28)
        ).astype(np.int32)
        pk2[i, :nn] = ((lc + m_nodes) | ((rc + m_nodes) << 16)).astype(np.int32)
        if has_cat and cat.any():
            cm = np.asarray(r["cat_mask"], bool)  # [nn, Bm]
            bm = cm.shape[-1]
            for mi in range(nn):
                if not cat[mi]:
                    continue
                bits = np.zeros(256, np.int64)
                bits[:bm] = cm[mi]
                # word w bit b (LSB-first) = "bin 32w+b goes left"
                vals = (bits.reshape(8, 32) << np.arange(32)[None, :]).sum(axis=1)
                catw[i, :, mi] = vals.astype(np.uint32).view(np.int32)
        depth = np.ones(nn, np.int32)
        for m in range(nn):
            for c in (lc[m], rc[m]):
                if c >= 0:
                    depth[c] = depth[m] + 1
        max_depth = max(max_depth, int(depth.max()) + 1)
    shape = (t, h, LANES)
    return ForestTables(
        pk1=jnp.asarray(pk1.reshape(shape)),
        pk2=jnp.asarray(pk2.reshape(shape)),
        leaf=jnp.asarray(leaf.reshape(shape)),
        catw=jnp.asarray(
            catw.reshape(t, CAT_WORDS, h, LANES) if has_cat else catw
        ),
        n_trees=t,
        max_depth=max_depth,
        m_nodes=m_nodes,
        has_cat=has_cat,
    )


def _lookup(table_hx128, cur, h: int):
    """table [H, 128] gathered by cur [8, 128] in [0, H*128) -> [8, 128].
    H broadcasts + H single-vreg lane-gathers + a select tree."""
    idx = cur & 127
    halves = [
        jnp.take_along_axis(
            jnp.broadcast_to(table_hx128[i : i + 1, :], (8, LANES)), idx, axis=1
        )
        for i in range(h)
    ]
    hsel = cur >> 7
    bit = 0
    while len(halves) > 1:
        b = (hsel >> bit) & 1
        halves = [
            jnp.where(b != 0, halves[2 * i + 1], halves[2 * i])
            for i in range(len(halves) // 2)
        ]
        bit += 1
    return halves[0]


def _walk_kernel(
    bins_ref,  # VMEM [1, P, 8, 128] i32 — 4 bins per i32, tile rows laid
    #           out as (sublane, lane); everything in the walk is a
    #           vreg-shaped [8, 128] op — no reshapes, no row-major crossings
    pk1_ref,  # VMEM [T, H, 128] i32
    pk2_ref,
    leaf_ref,  # VMEM [T, H, 128] f32
    catw_ref,  # VMEM [T, CAT_WORDS, H, 128] i32 (dummy when not has_cat)
    out_ref,  # VMEM [1, kpad, 8, 128] f32
    *,
    n_trees: int,
    max_depth: int,
    k: int,
    kpad: int,
    h: int,
    m_nodes: int,
    has_cat: bool,
    planes_n: int,
):
    planes = [bins_ref[0, p] for p in range(planes_n)]  # P x [8, 128]
    out_ref[...] = jnp.zeros_like(out_ref)
    iota_k = jax.lax.broadcasted_iota(jnp.int32, (kpad, 8, LANES), 0)
    sel_bits = planes_n.bit_length() - 1  # planes_n is a power of two

    def select_plane(lane_idx):
        """(P-1)-select binary tree: out[s,l] = planes[lane_idx[s,l]][s,l]."""
        level_vals = planes
        for bit in range(sel_bits):
            b = (lane_idx >> bit) & 1
            level_vals = [
                jnp.where(b != 0, level_vals[2 * i + 1], level_vals[2 * i])
                for i in range(len(level_vals) // 2)
            ]
        return level_vals[0]

    def tree_body(t, _):
        pk1 = pk1_ref[t]  # [H, 128]
        pk2 = pk2_ref[t]
        lv = leaf_ref[t]
        if has_cat:
            # mostly-numeric models: only trees that actually contain a
            # categorical node pay the 8-word bitset lookup per level (one
            # vector reduce per tree buys a lax.cond skip of ~8H gathers +
            # selects per level for the all-numeric trees)
            tree_cat = jnp.any(((pk1 >> 28) & 1) != 0)

        def level(_, cur):
            curc = jnp.maximum(cur, 0)  # [8, 128]
            p1 = _lookup(pk1, curc, h)
            thr = p1 & 0x1FF
            feat = (p1 >> 9) & 0x1FF
            dl = (p1 >> 18) & 1
            nb = ((p1 >> 19) & 0x1FF) - 1
            packed = select_plane(feat >> 2)
            fval = (packed >> ((feat & 3) * 8)) & 0xFF
            gl = (fval <= thr) | ((dl != 0) & (nb >= 0) & (fval == nb))
            if has_cat:
                def cat_gl(g32):
                    # one bitset word per row: 8 word-tables gathered by
                    # node, selected by fval>>5, tested at bit fval&31 (the
                    # vectorized CategoricalDecision, tree.h:346; bins >= the
                    # mask width have zero bits and route right like unseen
                    # categories)
                    words = [
                        _lookup(catw_ref[t, w], curc, h)
                        for w in range(CAT_WORDS)
                    ]
                    wi = fval >> 5
                    bit = 0
                    while len(words) > 1:
                        b = (wi >> bit) & 1
                        words = [
                            jnp.where(b != 0, words[2 * i + 1], words[2 * i])
                            for i in range(len(words) // 2)
                        ]
                        bit += 1
                    catgo = (words[0] >> (fval & 31)) & 1
                    isc = (p1 >> 28) & 1
                    # i32-operand select: Mosaic cannot truncate to the i1
                    # operands the direct boolean select would need
                    return jnp.where(isc != 0, catgo, g32)

                # the cond carries i32, not i1: Mosaic cannot legalize an
                # scf.if whose result is an i1 vector
                gl = lax.cond(
                    tree_cat, cat_gl, lambda g: g, gl.astype(jnp.int32)
                ) != 0
            p2 = _lookup(pk2, curc, h)
            child = jnp.where(gl, p2 & 0xFFFF, (p2 >> 16) & 0xFFFF) - m_nodes
            return jnp.where(cur >= 0, child, cur)

        nodes = lax.fori_loop(
            0, max_depth, level, jnp.zeros((8, LANES), jnp.int32)
        )
        val = jnp.where(
            nodes < 0,
            _lookup(lv, ~jnp.minimum(nodes, -1), h),
            0.0,
        )
        col = t % k  # class of tree t (trees interleave classes)
        out_ref[0] += jnp.where(iota_k == col, val[None, :, :], 0.0)
        return 0

    lax.fori_loop(0, n_trees, tree_body, 0)


def forest_walk(
    bins: jnp.ndarray,  # [n_tiles, P, 8, 128] i32 (P = n_planes(F))
    tables: ForestTables,
    *,
    n_trees: int,
    max_depth: int,
    k: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Raw scores [n_tiles, kpad, 8, 128] (sum of leaf outputs per class;
    row n of tile i lives at [i, :, n // 128, n % 128])."""
    return _forest_walk_jit(
        bins,
        tables.pk1,
        tables.pk2,
        tables.leaf,
        tables.catw,
        n_trees=n_trees,
        max_depth=max_depth,
        k=k,
        m_nodes=tables.m_nodes,
        has_cat=tables.has_cat,
        interpret=interpret,
    )


@functools.partial(
    instrumented_jit,
    static_argnames=(
        "n_trees", "max_depth", "k", "m_nodes", "has_cat", "interpret"
    ),
)
def _forest_walk_jit(
    bins, pk1, pk2, leaf, cw, *, n_trees, max_depth, k, m_nodes, has_cat,
    interpret,
):
    n_tiles = bins.shape[0]
    planes_n = bins.shape[1]
    h = pk1.shape[1]
    kpad = max(KPAD, -(-k // 8) * 8)
    kernel = functools.partial(
        _walk_kernel,
        n_trees=n_trees,
        max_depth=max_depth,
        k=k,
        kpad=kpad,
        h=h,
        m_nodes=m_nodes,
        has_cat=has_cat,
        planes_n=planes_n,
    )
    return pl.pallas_call(
        kernel,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec((1, planes_n, 8, LANES), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((n_trees, h, LANES), lambda i: (0, 0, 0)),
            pl.BlockSpec((n_trees, h, LANES), lambda i: (0, 0, 0)),
            pl.BlockSpec((n_trees, h, LANES), lambda i: (0, 0, 0)),
            pl.BlockSpec(cw.shape, lambda i: (0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, kpad, 8, LANES), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n_tiles, kpad, 8, LANES), jnp.float32),
        interpret=interpret,
    )(bins, pk1, pk2, leaf, cw)


@functools.partial(instrumented_jit, static_argnames=("n_pad",))
def _pack_bins_device(mat_u8: jnp.ndarray, n_pad: int) -> jnp.ndarray:
    """Device-side bin packing: [N, F] u8 -> [n_tiles, P, 8, 128] i32."""
    n, f = mat_u8.shape
    p = n_planes(f)
    b = jnp.zeros((n_pad, 4 * p), jnp.int32)
    b = b.at[:n, :f].set(mat_u8.astype(jnp.int32))
    packed = (
        b[:, 0::4] | (b[:, 1::4] << 8) | (b[:, 2::4] << 16) | (b[:, 3::4] << 24)
    )  # [n_pad, P]
    return packed.reshape(n_pad // ROW_TILE, 8, LANES, p).transpose(
        0, 3, 1, 2
    )


def pad_bins_for_walk(bins: np.ndarray, n_pad: int = 0) -> jnp.ndarray:
    """[N, F] int bins -> [n_tiles, P, 8, 128] i32, 4 bins
    byte-packed per i32 (feature j in byte j&3 of pack j>>2); row n sits at
    [n // 1024, :, (n % 1024) // 128, n % 128].  Only the compact u8 matrix
    crosses host->device (the padded i32 form is 9x bigger — built on
    device).  ``n_pad`` overrides the padded row count (pass
    ``bucket_pad_rows(n)`` to land on the bucket ladder); 0 keeps the
    minimal ROW_TILE ceiling."""
    n, f = bins.shape
    if n_pad <= 0:
        n_pad = (n + ROW_TILE - 1) // ROW_TILE * ROW_TILE
    # clip: categorical columns may carry an out-of-range unseen-category
    # sentinel — clipping to 255 keeps byte packing intact, and bin 255 is
    # outside every cat mask (<= 256 wide only when max_bin == 256... the
    # mask bit there is 0 unless bin 255 is a real seen category, in which
    # case the sentinel equals it; walk_eligible enforces max_bin <= 256)
    mat_u8 = np.clip(bins, 0, 255).astype(np.uint8)
    return _pack_bins_device(jnp.asarray(mat_u8), n_pad)


def unpack_walk_scores(out: np.ndarray, n: int, k: int) -> np.ndarray:
    """[n_tiles, kpad, 8, 128] -> [n, k] row-major scores."""
    t, kpad = out.shape[0], out.shape[1]
    flat = out.transpose(0, 2, 3, 1).reshape(t * ROW_TILE, kpad)
    return flat[:n, :k]


# ---------------------------------------------------------------------------
# device-side prediction binning (reference BinMapper::ValueToBin, bin.h:173)
# ---------------------------------------------------------------------------
#
# Host binning (searchsorted per feature) costs ~1.4s per 500k x 28 rows and
# dominated predict latency. On device, value->bin is a fused compare-reduce
# (bin = sum_b [ub_b < v], no gathers): ~ms at the same scale. Comparisons
# run in f32 (TPUs have no f64), so values within f32 epsilon of a bin
# boundary may bin differently from the f64 host path — the XLA-walker
# fallback keeps exact host binning.

def build_devbin_tables(mappers, used_features):
    """Pack numeric mappers into device arrays; None if any used feature is
    categorical (those need dict lookups — host binning handles them)."""
    ubs = []
    nanb = []
    mtype = []
    for j in used_features:
        m = mappers[j]
        if m.is_categorical:
            return None
        ubs.append(np.asarray(m.bin_upper_bound, np.float64))
        nanb.append(m.nan_bin)
        mtype.append(m.missing_type)
    bmax = max((len(u) for u in ubs), default=1)
    ub = np.full((len(ubs), bmax), np.inf, np.float64)
    for i, u in enumerate(ubs):
        ub[i, : len(u)] = u
    return (
        jnp.asarray(ub.astype(np.float32)),
        jnp.asarray(np.asarray(nanb, np.int32)),
        jnp.asarray(np.asarray(mtype, np.int32)),
    )


@instrumented_jit
def bin_numeric_device(
    X: jnp.ndarray,  # [N, F] f32 — used-feature columns
    ub: jnp.ndarray,  # [F, Bmax] f32, +inf padded
    nanb: jnp.ndarray,  # [F] i32
    mtype: jnp.ndarray,  # [F] i32
):
    """Vectorized ValueToBin: searchsorted(ub, v, 'left') == sum(ub < v),
    with the NaN/zero missing rules of the host path.

    Returns (bins [N, F] i32, suspect [N] bool): a row is suspect when any
    value sits within a few f32 ulps of a bin boundary — there the f32
    compare may disagree with the f64 host rule, so the caller re-bins
    those rows on host (prediction stays bit-identical to the host path)."""
    from ...binning import K_ZERO_THRESHOLD, MissingType

    isnan = jnp.isnan(X)
    safe = jnp.where(isnan, 0.0, X)
    # fused compare+reduce per feature: no [N, F, Bmax] materialization
    cmp = ub[None, :, :] < safe[:, :, None]
    bins = jnp.sum(cmp, axis=2, dtype=jnp.int32)
    tol = 8.0 * jnp.finfo(jnp.float32).eps * jnp.maximum(
        jnp.abs(safe)[:, :, None], jnp.abs(ub)[None, :, :]
    )
    near = jnp.abs(safe[:, :, None] - ub[None, :, :]) <= tol
    suspect = jnp.any(near & jnp.isfinite(ub)[None, :, :], axis=(1, 2))
    miss_zero = (mtype[None, :] == MissingType.ZERO) & (
        isnan | (jnp.abs(safe) <= K_ZERO_THRESHOLD)
    )
    miss_nan = (mtype[None, :] == MissingType.NAN) & isnan & (nanb[None, :] >= 0)
    return jnp.where(miss_zero | miss_nan, nanb[None, :], bins), suspect
