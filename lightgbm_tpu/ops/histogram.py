"""Histogram construction — the hottest kernel of GBDT training.

Reference analogs: ``DenseBin::ConstructHistogramInner`` (src/io/dense_bin.hpp:99,
the scalar gather loop), ``MultiValBinWrapper::ConstructHistograms``
(include/LightGBM/train_share_states.h:48, thread-block histograms + merge)
and the CUDA shared-memory kernel (src/treelearner/cuda/
cuda_histogram_constructor.cu:19-130).

TPU-native formulation: TPUs have no fast random scatter, so the
scatter-add becomes either
  * a ``segment_sum`` over flattened (feature, bin) ids (XLA sorted-scatter),
    or
  * a chunked one-hot matmul ``one_hot(bins) @ (g,h,c)`` that runs on the
    MXU — the dense-masked analog of the CUDA shared-mem accumulation.
Rows outside the target leaf contribute zeros via the mask (dense masked
ops instead of the reference's ordered_gradients gather).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..obs.collectives import timed_psum


@functools.lru_cache(maxsize=None)
def _segment_hist_fn(num_bins: int):
    """Per-``num_bins`` segment-sum histogram with a fleet-aware vmap rule.

    Under ``jax.vmap`` (model-fleet training batches grad/hess/mask over a
    leading member axis M) the default batching of ``segment_sum`` emits one
    scatter per member.  The custom rule instead folds the member axis into
    the segment ids — ``id += member * (F * B)`` — so all M histograms
    accumulate in a single segment_sum launch over ``M * F * B`` segments.
    Float adds happen in the same per-(row, feature, bin) order as the
    unbatched kernel, so each member's [F, B, 3] plane is byte-identical to
    its solo run.  ``num_bins`` is closed over (lru_cached) because
    custom_vmap arguments must all be array operands.
    """

    @jax.custom_batching.custom_vmap
    def impl(bins, grad, hess, mask):
        n, f = bins.shape
        ids = (bins + jnp.arange(f, dtype=jnp.int32)[None, :] * num_bins).reshape(-1)
        g = (grad * mask)[:, None]
        h = (hess * mask)[:, None]
        c = mask[:, None]
        data = jnp.broadcast_to(
            jnp.concatenate([g, h, c], axis=1)[:, None, :], (n, f, 3)
        ).reshape(-1, 3)
        hist = jax.ops.segment_sum(data, ids, num_segments=f * num_bins)
        return hist.reshape(f, num_bins, 3)

    @impl.def_vmap
    def impl_vmap(axis_size, in_batched, bins, grad, hess, mask):
        m = axis_size

        def bcast(x, batched):
            return x if batched else jnp.broadcast_to(x[None], (m,) + x.shape)

        bins_b = bcast(bins, in_batched[0])
        grad_b = bcast(grad, in_batched[1])
        hess_b = bcast(hess, in_batched[2])
        mask_b = bcast(mask, in_batched[3])
        _, n, f = bins_b.shape
        ids = bins_b + jnp.arange(f, dtype=jnp.int32)[None, None, :] * num_bins
        ids = ids + (jnp.arange(m, dtype=jnp.int32) * (f * num_bins))[:, None, None]
        ghc = jnp.stack(
            [grad_b * mask_b, hess_b * mask_b, mask_b], axis=-1
        )  # [M, N, 3]
        data = jnp.broadcast_to(ghc[:, :, None, :], (m, n, f, 3)).reshape(-1, 3)
        hist = jax.ops.segment_sum(
            data, ids.reshape(-1), num_segments=m * f * num_bins
        )
        return hist.reshape(m, f, num_bins, 3), True

    return impl


def leaf_histogram_segment(
    bins: jnp.ndarray,  # [N, F] int32 bin indices
    grad: jnp.ndarray,  # [N] f32
    hess: jnp.ndarray,  # [N] f32
    mask: jnp.ndarray,  # [N] f32 — 1 for rows of the target leaf (in-bag), else 0
    num_bins: int,
) -> jnp.ndarray:
    """Masked histogram via segment_sum. Returns [F, B, 3] (g, h, count).

    Vmapping over a leading member axis (fleet training) collapses into one
    flattened segment_sum launch — see ``_segment_hist_fn``."""
    return _segment_hist_fn(int(num_bins))(bins, grad, hess, mask)


def leaf_histogram_onehot(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    mask: jnp.ndarray,
    num_bins: int,
    chunk: int = 16384,
) -> jnp.ndarray:
    """Masked histogram as chunked one-hot matmuls (MXU-friendly).

    hist[f, b, k] = sum_n [bins[n, f] == b] * ghc[n, k]
    computed as a batched dot_general over feature with the row axis
    contracted, scanning over fixed-size row chunks to bound memory.
    """
    n, f = bins.shape
    ghc = jnp.stack([grad * mask, hess * mask, mask], axis=1)  # [N, 3]
    pad = (-n) % chunk
    if pad:
        bins = jnp.pad(bins, ((0, pad), (0, 0)))
        ghc = jnp.pad(ghc, ((0, pad), (0, 0)))
    nchunks = (n + pad) // chunk
    bins_c = bins.reshape(nchunks, chunk, f)
    ghc_c = ghc.reshape(nchunks, chunk, 3)

    def body(acc, xs):
        b_c, v_c = xs
        onehot = jax.nn.one_hot(b_c, num_bins, dtype=jnp.float32)  # [chunk, F, B]
        # contract over rows: [F, B, chunk] x [chunk, 3] -> [F, B, 3]
        part = jax.lax.dot_general(
            onehot,
            v_c,
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        return acc + part, None

    init = jnp.zeros((f, num_bins, 3), dtype=jnp.float32)
    hist, _ = jax.lax.scan(body, init, (bins_c, ghc_c))
    return hist


def leaf_histogram(
    bins: jnp.ndarray,
    grad: jnp.ndarray,
    hess: jnp.ndarray,
    mask: jnp.ndarray,
    num_bins: int,
    *,
    method: str = "auto",
    axis_name: Optional[str] = None,
    quant_scales=None,  # (g_scale, h_scale) for the pallas_int8 methods
    measure: bool = False,  # timed-psum instrumentation (obs/collectives)
    psum_site: str = "hist",  # measured-site label (hist | hist_db0 | hist_db1)
) -> jnp.ndarray:
    """Dispatch histogram impl; psum across the data mesh axis if given.

    The psum is the TPU-native replacement for the reference's histogram
    ReduceScatter (src/treelearner/data_parallel_tree_learner.cpp:286, XLA
    collective over ICI instead of hand-rolled TCP recursive-halving).
    ``measure`` (static, from ``GrowerParams.measure_collectives``) swaps
    the bare psum for the timed/byte-counted wrapper.  ``psum_site``
    lets double-buffered callers label which buffer this reduction feeds
    (the grower's overlap path psums half the frontier under
    ``hist_db0`` while building the other half, then ``hist_db1``).
    """
    if method == "auto":
        # Dispatch on the LOWERING platform, not the process-global default
        # backend: with a TPU backend registered but the computation placed on
        # CPU devices (virtual CPU mesh tests, dryrun_multichip), selecting
        # Pallas would crash ("Only interpret mode is supported on CPU
        # backend").  lax.platform_dependent specializes per lowering target.
        # The axon (tunneled TPU) backend lowers with platform name "tpu", so
        # the tpu= branch covers it (verified empirically).
        from .pallas.histogram import histogram_pallas

        if jax.default_backend() != "tpu":
            # no TPU registered at all: skip platform_dependent — older jax
            # lowers EVERY branch per platform and the Pallas one refuses to
            # lower for CPU ("Only interpret mode is supported")
            hist = leaf_histogram_segment(bins, grad, hess, mask, num_bins)
        else:
            hist = jax.lax.platform_dependent(
                bins,
                grad,
                hess,
                mask,
                tpu=functools.partial(histogram_pallas, num_bins=num_bins),
                default=functools.partial(leaf_histogram_segment, num_bins=num_bins),
            )
        if axis_name is not None:
            hist = timed_psum(hist, axis_name, site=psum_site, measure=measure)
        return hist
    if method == "pallas":
        from .pallas.histogram import histogram_pallas

        hist = histogram_pallas(bins, grad, hess, mask, num_bins)
    elif method == "pallas_interpret":
        from .pallas.histogram import histogram_pallas

        hist = histogram_pallas(bins, grad, hess, mask, num_bins, interpret=True)
    elif method in ("pallas_int8", "pallas_int8_interpret"):
        # quantized-gradient integer kernel: exact int32 accumulation of the
        # int8 grid (requires use_quantized_grad so the scales exist)
        if quant_scales is None:
            raise ValueError(
                f"method={method!r} needs quantized gradients "
                "(use_quantized_grad=True provides the scales)"
            )
        from .pallas.histogram_int8 import histogram_pallas_int8

        hist = histogram_pallas_int8(
            bins, grad, hess, mask, num_bins,
            quant_scales[0], quant_scales[1],
            interpret=method.endswith("interpret"),
        )
    elif method == "onehot":
        hist = leaf_histogram_onehot(bins, grad, hess, mask, num_bins)
    elif method == "segment":
        hist = leaf_histogram_segment(bins, grad, hess, mask, num_bins)
    else:
        raise ValueError(f"unknown histogram method {method!r}")
    if axis_name is not None:
        hist = timed_psum(hist, axis_name, site=psum_site, measure=measure)
    return hist
