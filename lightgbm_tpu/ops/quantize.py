"""Quantized-gradient training (reference: GradientDiscretizer,
src/treelearner/gradient_discretizer.cpp).

The reference discretizes gradients/hessians to int8 bins so histogram
accumulation runs in narrow integers; split gains multiply the integer sums
by the per-iteration scales. The TPU formulation quantizes to the SAME grid
but keeps the values as f32 multiples of the scale — numerically identical
sums (f32 represents the small-integer grid exactly and the histogram's
accumulation order is unchanged) with zero changes to the grower; a narrow
int8 Pallas accumulation can later slot in underneath as a pure optimization.

Leaf outputs are renewed from the TRUE gradients after the tree is grown
(RenewIntGradTreeOutput, gradient_discretizer.cpp:209) when
``quant_train_renew_leaf``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..obs.collectives import timed_psum
from ..obs.jit import instrumented_jit

from .split import leaf_output


@functools.partial(
    instrumented_jit, static_argnames=("num_bins", "stochastic", "constant_hessian")
)
def quantize_gradients(
    grad: jnp.ndarray,  # [N] f32
    hess: jnp.ndarray,  # [N] f32
    rng: jax.Array,
    num_bins: int = 4,
    stochastic: bool = True,
    constant_hessian: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Quantize (grad, hess) onto the reference's integer grid
    (DiscretizeGradients, gradient_discretizer.cpp:70-160: scales from the
    max |value|, truncation toward zero, optional stochastic rounding).

    Returns (qg, qh, g_scale, h_scale): qg/qh are f32 grid MULTIPLES
    (qg = k * g_scale with integer k), and the scales let integer kernels
    recover k exactly (ops/pallas/histogram_int8.py)."""
    if num_bins > 127:
        raise ValueError(
            "num_grad_quant_bins must be <= 127 (int8 grid)"
        )
    max_g = jnp.max(jnp.abs(grad))
    max_h = jnp.max(jnp.abs(hess))
    g_scale = jnp.maximum(max_g / (num_bins // 2), 1e-30)
    h_scale = jnp.maximum(
        max_h if constant_hessian else max_h / num_bins, 1e-30
    )
    gi = grad / g_scale
    hi = hess / h_scale
    if stochastic:
        kg, kh = jax.random.split(rng)
        # dtype pinned: the default float dtype is f64 under enable_x64,
        # which would silently widen the whole rounding chain (GL012)
        rg = jax.random.uniform(kg, grad.shape, dtype=jnp.float32)
        rh = jax.random.uniform(kh, hess.shape, dtype=jnp.float32)
    else:
        rg = jnp.float32(0.5)
        rh = jnp.float32(0.5)
    # C's int8 cast truncates toward zero; rounding offset follows the sign
    qg = jnp.trunc(jnp.where(gi >= 0, gi + rg, gi - rg))
    qh = jnp.trunc(hi + rh)  # hessians are non-negative
    if constant_hessian:
        qh = jnp.ones_like(qh)
    return qg * g_scale, qh * h_scale, g_scale, h_scale


def hist_acc_scales(
    grad: jnp.ndarray,  # [N] f32 TRUE gradients
    hess: jnp.ndarray,  # [N] f32
    mask: Optional[jnp.ndarray] = None,  # [N] in-bag mask (None = all)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-iteration scales for the DEFAULT int8 histogram accumulator
    (histogram engine v2): unlike ``quantize_gradients`` — which changes
    the training values themselves — these scales only parameterize how
    the seg kernels accumulate UNCHANGED f32 gradients on the int8 MXU
    path.  The grid is the kernels' 2-digit ceiling (seg.QMAX = 16256), so
    every in-bag |g| maps to at most QMAX with a relative quantization
    step of ~1/16256 ~= 6e-5 — inside the near-tie tolerance the grower's
    f32 re-accumulate pass covers (GrowerParams.near_tie_tol).

    Computed ONCE per boosting iteration (the max is over the in-bag
    rows), reused by every histogram launch of the tree."""
    from .pallas.seg import QMAX

    if mask is not None:
        grad = grad * mask
        hess = hess * mask
    g_scale = jnp.maximum(jnp.max(jnp.abs(grad)) / QMAX, 1e-30)
    h_scale = jnp.maximum(jnp.max(jnp.abs(hess)) / QMAX, 1e-30)
    return g_scale.astype(jnp.float32), h_scale.astype(jnp.float32)


@functools.partial(
    instrumented_jit,
    static_argnames=(
        "num_leaves",
        "lambda_l1",
        "lambda_l2",
        "max_delta_step",
        "axis_name",
        "measure",
    ),
)
def renew_leaf_values(
    leaf_id: jnp.ndarray,  # [N] int32 from grow_tree
    grad: jnp.ndarray,  # [N] TRUE (unquantized) gradients
    hess: jnp.ndarray,
    mask: jnp.ndarray,  # [N] in-bag mask
    num_leaves_used: jnp.ndarray,  # scalar from TreeArrays.num_leaves
    num_leaves: int,
    lambda_l1: float,
    lambda_l2: float,
    max_delta_step: float,
    axis_name: Optional[str] = None,
    measure: bool = False,
) -> jnp.ndarray:
    """Per-leaf outputs from true gradient sums
    (RenewIntGradTreeOutput, gradient_discretizer.cpp:209; the data-parallel
    branch GlobalSums the per-leaf stats — here a psum when axis_name,
    routed through the timed wrapper so ``collective_measured/*`` and the
    perf contract see the quantized-training path)."""
    sum_g = jax.ops.segment_sum(grad * mask, leaf_id, num_segments=num_leaves)
    sum_h = jax.ops.segment_sum(hess * mask, leaf_id, num_segments=num_leaves)
    if axis_name is not None:
        sum_g = timed_psum(sum_g, axis_name, site="quant", measure=measure)
        sum_h = timed_psum(sum_h, axis_name, site="quant", measure=measure)
    out = leaf_output(sum_g, sum_h, lambda_l1, lambda_l2, max_delta_step)
    active = jnp.arange(num_leaves, dtype=jnp.int32) < num_leaves_used
    return jnp.where(active & (num_leaves_used > 1), out, 0.0).astype(
        jnp.float32
    )
