"""Tree-to-MXU compiled inference: forests as dense contractions.

The streaming walker (predict.py) advances a [rows, trees] node-index
matrix one level per ``lax.while_loop`` step — two gathers and a compare
per level.  TPUs punish exactly those data-dependent gathers while the
MXUs idle; Hummingbird (Nakandala et al., OSDI 2020) showed small/medium
trees compile into GEMM pipelines that beat pointer chasing on tensor
hardware.  This module is that compiler for the bin-space forest: each
tree is padded to the forest's perfect depth D and the whole forest
evaluates as three contractions with ZERO data-dependent control flow:

1. **feature select** (int8 MXU): ``X_sel = bins @ S`` where ``S`` is the
   {0,1} one-hot of each perfect node's split feature.  Exactness uses
   the 2-digit base-128 trick from the histogram-v2 int8 accumulator
   (ops/quantize.py / ops/pallas/seg.py): ``bins`` splits into hi/lo
   int8 digits, each contracts with ``preferred_element_type=int32``,
   and ``X_sel = 128*hi@S + lo@S`` recombines exactly in i32 — every
   per-node operand is the exact integer bin, not an approximation.
2. **path composition** (int8 MXU): per-node compare bits become signs
   ``sgn = 2*go_left - 1`` in {-1,+1}; ``routes`` holds each perfect
   leaf's ancestor directions in {-1,0,+1} (shared across trees — the
   perfect topology only depends on D); ``score = sgn @ routes`` in i32
   hits D exactly for the one leaf consistent with all D decisions.
3. **leaf select** (f32): ``out = onehot(score == D) @ leaf_values``.
   This one stays f32 on purpose: products are exactly ±0.0 or the
   stored leaf value, so the result is byte-identical to the walker's
   gather.  A bf16 contraction here would round leaf values and break
   the byte-parity contract (``Precision.HIGHEST`` pins true f32 on
   MXU — DEFAULT would run bf16 passes).

The per-node decision is the walker's, verbatim, evaluated for ALL
perfect nodes at once::

    go_left = (x <= thr) | (default_left & (nan_bin >= 0) & (x == nan_bin))

Padding rules (belt and braces): filler internal nodes always route
left (``thr`` above any recombinable bin value), and every real leaf's
value/index is replicated across ALL perfect leaves of its subtree —
so the selected perfect leaf carries the right answer even though only
the leftmost one is ever selected.

Eligibility mirrors ``packed_reject_reason``: the serving sweet spot is
<= 64 leaves, actual depth <= 8 (the perfect layout costs 2^D), a few
hundred trees, numeric-only splits with thresholds inside the packed-bin
envelope.  Anything else stays on the walker (predict.py resolves the
engine and emits the fallback telemetry).
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np
import jax.numpy as jnp
from jax import lax

# eligibility envelope: the perfect layout costs 2^D slots per tree and
# the select matrix F x T*(2^D - 1) int8 bytes — these caps bound it at
# 512 * 512 * 255 ≈ 67 MB worst case while covering the serving sweet
# spot the issue names (<= 64 leaves, <= a few hundred trees)
TF_MAX_DEPTH = 8
TF_MAX_LEAVES = 64
TF_MAX_TREES = 512
TF_MAX_F = 512  # mirrors predict._PACK_F
TF_MAX_BIN = 512  # thresholds/NaN bins inside the packed envelope (_PACK_THR)
_DIGIT_ENVELOPE = 1 << 14  # 2 base-128 int8 digits recombine exactly below this
_ALWAYS_LEFT = (1 << 20) - 1  # filler threshold: above any recombined bin


class TensorForest(NamedTuple):
    """Forest compiled to perfect-depth-D tensor form.

    P = T * (2^D - 1) perfect internal nodes (tree-major), Lp = 2^D
    perfect leaves per tree.  ``routes`` is shared across trees."""

    sel: jnp.ndarray  # [F, P] int8 one-hot of each node's split feature
    thr: jnp.ndarray  # [P] i32 split bin (filler: _ALWAYS_LEFT)
    nanb: jnp.ndarray  # [P] i32 NaN bin of the node's feature (-1 = none)
    dleft: jnp.ndarray  # [P] bool default-left
    routes: jnp.ndarray  # [2^D - 1, 2^D] int8 ancestor directions {-1,0,+1}
    leaf_val: jnp.ndarray  # [T, 2^D] f32, replicated over padded subtrees
    leaf_idx: jnp.ndarray  # [T, 2^D] i32 original leaf index, replicated


def _record_depth(record: dict) -> int:
    """Actual depth (internal decisions on the deepest root->leaf path)."""
    lc = np.asarray(record["left_child"], np.int64)
    rc = np.asarray(record["right_child"], np.int64)
    if lc.size == 0:
        return 0
    depth = 0
    stack = [(0, 0)]
    while stack:
        node, lvl = stack.pop()
        if node < 0:
            depth = max(depth, lvl)
        else:
            stack.append((int(lc[node]), lvl + 1))
            stack.append((int(rc[node]), lvl + 1))
    return depth


def tensor_reject_reason(
    records: List[Optional[dict]],
    nan_bins: np.ndarray,
    num_features: int,
    max_bin: Optional[int] = None,
) -> Optional[str]:
    """None when the tensor engine covers this forest exactly, else why
    not (the `packed_reject_reason` idiom: the caller falls back to the
    walker and surfaces the reason through telemetry)."""
    if not records:
        return "no trees in range"
    if len(records) > TF_MAX_TREES:
        return f"{len(records)} trees > {TF_MAX_TREES}"
    if num_features > TF_MAX_F:
        return f"{num_features} bin columns > {TF_MAX_F}"
    if max_bin is not None and int(max_bin) > _DIGIT_ENVELOPE:
        return (
            f"bin width {int(max_bin)} exceeds the 2-digit int8 envelope "
            f"({_DIGIT_ENVELOPE})"
        )
    nan_bins = np.asarray(nan_bins)
    if nan_bins.size and int(np.max(nan_bins)) >= TF_MAX_BIN:
        return f"a NaN bin >= {TF_MAX_BIN}"
    for r in records:
        if r is None or r.get("no_bin_form"):
            return "a tree has no bin-space record"
        sic = r.get("split_is_cat")
        if sic is not None and np.any(np.asarray(sic)):
            return "categorical splits"
        if len(r["leaf_value"]) > TF_MAX_LEAVES:
            return f"{len(r['leaf_value'])} leaves > {TF_MAX_LEAVES}"
        sf = r["split_feature"]
        if len(sf) and int(np.max(np.asarray(r["split_bin"]))) >= TF_MAX_BIN:
            return f"a split threshold bin >= {TF_MAX_BIN}"
        d = _record_depth(r)
        if d > TF_MAX_DEPTH:
            return f"tree depth {d} > {TF_MAX_DEPTH}"
    return None


def _perfect_routes(depth: int) -> np.ndarray:
    """[2^D - 1, 2^D] ancestor-direction matrix: routes[q, L] = +1 when
    leaf L lies in heap node q's left subtree, -1 right, 0 not an
    ancestor.  sgn @ routes == D selects the unique consistent leaf."""
    ptree = (1 << depth) - 1
    lp = 1 << depth
    routes = np.zeros((ptree, lp), np.int8)
    for leaf in range(lp):
        for lvl in range(depth):
            q = (1 << lvl) - 1 + (leaf >> (depth - lvl))
            went_right = (leaf >> (depth - 1 - lvl)) & 1
            routes[q, leaf] = -1 if went_right else 1
    return routes


def build_tensor_forest(
    records: List[dict], nan_bins: np.ndarray, num_features: int
) -> TensorForest:
    """Compile bin-space records into tensor form; the caller checked
    ``tensor_reject_reason``.  Host-side numpy only."""
    t = len(records)
    depth = max(1, max(_record_depth(r) for r in records))
    ptree = (1 << depth) - 1
    lp = 1 << depth
    p_total = t * ptree
    nanb_by_f = np.asarray(nan_bins, np.int64)

    feat = np.zeros(p_total, np.int64)
    thr = np.full(p_total, _ALWAYS_LEFT, np.int32)
    nanb = np.full(p_total, -1, np.int32)
    dleft = np.zeros(p_total, bool)
    leaf_val = np.zeros((t, lp), np.float32)
    leaf_idx = np.zeros((t, lp), np.int32)

    for i, r in enumerate(records):
        lv = np.asarray(r["leaf_value"], np.float32)
        sf = np.asarray(r["split_feature"], np.int64)
        if len(sf) == 0:
            # single-leaf tree: every perfect leaf carries leaf 0
            leaf_val[i, :] = lv[0] if lv.size else 0.0
            continue
        sb = np.asarray(r["split_bin"], np.int64)
        dl = np.asarray(r["default_left"], bool)
        lc = np.asarray(r["left_child"], np.int64)
        rc = np.asarray(r["right_child"], np.int64)
        stack = [(0, 0, 0)]  # (node-or-~leaf, heap slot, level)
        while stack:
            node, q, lvl = stack.pop()
            if node < 0:
                leaf = ~node
                lo = (q - ((1 << lvl) - 1)) << (depth - lvl)
                hi = lo + (1 << (depth - lvl))
                leaf_val[i, lo:hi] = lv[leaf]
                leaf_idx[i, lo:hi] = leaf
                continue
            p = i * ptree + q
            f = int(sf[node])
            feat[p] = f
            thr[p] = sb[node]
            dleft[p] = dl[node]
            nanb[p] = nanb_by_f[f] if f < nanb_by_f.size else -1
            stack.append((int(lc[node]), 2 * q + 1, lvl + 1))
            stack.append((int(rc[node]), 2 * q + 2, lvl + 1))

    sel = np.zeros((num_features, p_total), np.int8)
    sel[feat, np.arange(p_total)] = 1
    return TensorForest(
        sel=jnp.asarray(sel),
        thr=jnp.asarray(thr),
        nanb=jnp.asarray(nanb),
        dleft=jnp.asarray(dleft),
        routes=jnp.asarray(_perfect_routes(depth)),
        leaf_val=jnp.asarray(leaf_val),
        leaf_idx=jnp.asarray(leaf_idx),
    )


def _forest_depth(forest: TensorForest) -> int:
    """Static D back out of the routes shape (2^D - 1 perfect nodes)."""
    return int(forest.routes.shape[0] + 1).bit_length() - 1


def _tensor_scores(forest: TensorForest, bins: jnp.ndarray) -> jnp.ndarray:
    """[N, T, 2^D] i32 path scores; == D selects the reached leaf."""
    # contraction 1: exact feature select via 2-digit base-128 int8 MXU
    # dots (the quantize.py digit-sum trick) recombined in i32
    hi = (bins >> 7).astype(jnp.int8)
    lo = (bins & 127).astype(jnp.int8)
    dn = (((1,), (0,)), ((), ()))
    xsel = (
        lax.dot_general(hi, forest.sel, dn, preferred_element_type=jnp.int32)
        * 128
        + lax.dot_general(lo, forest.sel, dn, preferred_element_type=jnp.int32)
    )  # [N, P] the exact bin value at each perfect node's feature
    gl = (xsel <= forest.thr[None, :]) | (
        forest.dleft[None, :]
        & (forest.nanb[None, :] >= 0)
        & (xsel == forest.nanb[None, :])
    )
    sgn = jnp.where(gl, jnp.int8(1), jnp.int8(-1))
    # contraction 2: per-leaf agreement count with the ancestor directions
    n = bins.shape[0]
    t, lp = forest.leaf_val.shape
    ptree = forest.routes.shape[0]
    score = lax.dot_general(
        sgn.reshape(n * t, ptree),
        forest.routes,
        dn,
        preferred_element_type=jnp.int32,
    )
    return score.reshape(n, t, lp)


def _tensor_bins_pertree_impl(
    forest: TensorForest, bins: jnp.ndarray
) -> jnp.ndarray:
    """Per-tree leaf outputs [N, T] f32 — byte-identical to the walker's
    gather (engine-facing order: tables first, data chunk last)."""
    score = _tensor_scores(forest, bins)
    onehot = (score == _forest_depth(forest)).astype(jnp.float32)
    # contraction 3: one-hot x leaf values.  HIGHEST pins true f32 on the
    # MXU; every product is exactly ±0.0 or the stored leaf value, so the
    # sum is exact regardless of order
    return jnp.einsum(
        "ntl,tl->nt", onehot, forest.leaf_val,
        precision=lax.Precision.HIGHEST,
    )


def _tensor_bins_leaves_impl(
    forest: TensorForest, bins: jnp.ndarray
) -> jnp.ndarray:
    """Leaf index per (row, tree) [N, T] i32 (masked sum, not a gather)."""
    score = _tensor_scores(forest, bins)
    hit = score == _forest_depth(forest)
    # dtype pinned: an unpinned integer sum widens to i64 under enable_x64
    return jnp.sum(
        jnp.where(hit, forest.leaf_idx[None, :, :], 0),
        axis=-1,
        dtype=jnp.int32,
    )


# --------------------------------------------------------------- host probe
def _host_walk_values(records, nan_bins, bins):
    """Reference numpy walk -> ([N, T] f32 values, [N, T] i32 leaves).
    Decision rule identical to predict.py's bin walker."""
    nan_bins = np.asarray(nan_bins, np.int64)
    n = bins.shape[0]
    vals = np.zeros((n, len(records)), np.float32)
    leaves = np.zeros((n, len(records)), np.int32)
    for i, r in enumerate(records):
        lv = np.asarray(r["leaf_value"], np.float32)
        sf = np.asarray(r["split_feature"], np.int64)
        if len(sf) == 0:
            vals[:, i] = lv[0] if lv.size else 0.0
            continue
        sb = np.asarray(r["split_bin"], np.int64)
        dl = np.asarray(r["default_left"], bool)
        lc = np.asarray(r["left_child"], np.int64)
        rc = np.asarray(r["right_child"], np.int64)
        nodes = np.zeros(n, np.int64)
        while True:
            live = nodes >= 0
            if not live.any():
                break
            cur = np.where(live, nodes, 0)
            f = sf[cur]
            x = bins[np.arange(n), f]
            nb = nan_bins[f]
            go_left = (x <= sb[cur]) | (dl[cur] & (nb >= 0) & (x == nb))
            nxt = np.where(go_left, lc[cur], rc[cur])
            nodes = np.where(live, nxt, nodes)
        leaf = ~nodes
        vals[:, i] = lv[leaf]
        leaves[:, i] = leaf
    return vals, leaves


def _host_tensor_values(forest: TensorForest, bins):
    """Numpy mirror of the three contractions (exact integer + f32 masked
    select — bitwise-identical to the device result by construction)."""
    sel = np.asarray(forest.sel, np.int64)
    thr = np.asarray(forest.thr, np.int64)
    nanb = np.asarray(forest.nanb, np.int64)
    dleft = np.asarray(forest.dleft)
    routes = np.asarray(forest.routes, np.int64)
    leaf_val = np.asarray(forest.leaf_val)
    leaf_idx = np.asarray(forest.leaf_idx, np.int64)
    hi, lo = bins >> 7, bins & 127
    xsel = (hi @ sel) * 128 + lo @ sel
    gl = (xsel <= thr) | (dleft & (nanb >= 0) & (xsel == nanb))
    sgn = np.where(gl, 1, -1)
    n = bins.shape[0]
    t, lp = leaf_val.shape
    depth = int(routes.shape[0] + 1).bit_length() - 1
    score = (sgn.reshape(n * t, -1) @ routes).reshape(n, t, lp)
    hit = score == depth
    vals = np.where(hit, leaf_val[None], np.float32(0.0)).sum(
        axis=-1, dtype=np.float32
    )
    leaves = np.where(hit, leaf_idx[None], 0).sum(axis=-1).astype(np.int32)
    return vals, leaves


def parity_probe_reason(
    records: List[dict],
    nan_bins: np.ndarray,
    forest: TensorForest,
    num_features: int,
    max_bin: int,
    rows: int = 64,
) -> Optional[str]:
    """Compile-time byte-parity probe for ``pred_engine=auto``: evaluate a
    deterministic bin batch through a reference numpy walk AND the numpy
    mirror of the tensor contractions; any value/leaf mismatch keeps the
    walker.  Host-only — no device compiles, so warmed ladders stay flat."""
    rng = np.random.default_rng(0xF0BE5)
    span = max(2, int(max_bin))
    bins = rng.integers(0, span, size=(rows, num_features), dtype=np.int64)
    nb = np.asarray(nan_bins, np.int64)
    for f in range(min(num_features, nb.size)):
        if nb[f] >= 0:
            # plant each feature's NaN bin so default-direction routing is
            # exercised, not just the threshold compare
            bins[f % rows, f] = nb[f]
    ref_vals, ref_leaves = _host_walk_values(records, nb, bins)
    got_vals, got_leaves = _host_tensor_values(forest, bins)
    if ref_vals.tobytes() != got_vals.tobytes():
        bad = int(np.sum(ref_vals != got_vals))
        return f"parity probe failed: {bad} leaf values disagree"
    if not np.array_equal(ref_leaves, got_leaves):
        return "parity probe failed: leaf indices disagree"
    return None
