"""Sort-based segment partition — the TPU-native DataPartition::Split.

Reference analog: ``DataPartition::Split`` (src/treelearner/data_partition.hpp:101)
and the CUDA partition pipeline (``GenDataToLeftBitVectorKernel`` -> prefix
sums -> ``SplitInnerKernel``, src/treelearner/cuda/cuda_data_partition.cu).

The reference keeps an index indirection and gathers `ordered_gradients`;
on TPU random gathers serialize (~35 ns/element), so instead the rows live
physically in leaf-segment order (see ops/pallas/seg.py for the row layout)
and each split STABLY SORTS the parent's contiguous window by a small key:

  key 0: rows before the segment (window over-covers for static shapes)
  key 1: rows of the segment going left
  key 2: rows of the segment going right
  key 3: rows after the segment

A stable sort leaves groups 0 and 3 exactly where they were (so the
over-covered window writes back without corrupting neighbors) and compacts
the left/right children into contiguous runs — XLA's TPU sort moves the
full 256-byte packed row (viewed as 11 i32 lanes for F<=28) at ~6 ns/row,
within ~2x of a pure streaming copy and with zero custom-kernel risk.

Static shapes: window capacities come from a pow-2 ladder (`lax.switch`),
like the reference's histogram-pool size classes.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ..obs.jit import instrumented_jit
from jax import lax

from .pallas.seg import _u16, used_lanes


def window_caps(n_pad: int, floor: int = 8192) -> list:
    """Ascending pow-2 window capacities, topped by the whole array."""
    caps = []
    cap = min(floor, n_pad)
    while cap < n_pad:
        caps.append(cap)
        cap *= 2
    caps.append(n_pad)
    return caps


def _go_left(colv, tbin, dl, nanb, iscat, catmask):
    """Split predicate in bin space — must match ops/grower.py partition:
    numeric v <= t with NaN-bin default-left; categorical membership mask."""
    num = (colv <= tbin) | ((dl != 0) & (nanb >= 0) & (colv == nanb))
    bm = catmask.shape[0]
    cat = (catmask[jnp.clip(colv, 0, bm - 1)] > 0.5) & (colv < bm)
    return jnp.where(iscat != 0, cat, num)


@functools.partial(
    instrumented_jit,
    static_argnames=("f", "n_pad", "wide", "use_gl_vec"),
)
def sort_partition_xla(
    seg: jnp.ndarray,  # [LANES, n_pad] i16 packed rows, PLANE-MAJOR — the
    #                    layout XLA assigns this loop carry anyway; storing it
    #                    that way avoids full-array relayout copies per split
    sbegin: jnp.ndarray,  # scalar i32 — segment begin
    cnt: jnp.ndarray,  # scalar i32 — segment rows
    feat: jnp.ndarray,  # scalar i32 — split feature (used-feature index)
    tbin: jnp.ndarray,  # scalar i32
    dl: jnp.ndarray,  # scalar i32 (default-left)
    nanb: jnp.ndarray,  # scalar i32 (NaN bin or -1)
    iscat: jnp.ndarray,  # scalar i32
    catmask: jnp.ndarray,  # [Bm] f32 — bin -> goes left (categorical)
    gl_vec: Optional[jnp.ndarray] = None,  # [n_pad] f32 go-left bits
    *,
    f: int,
    n_pad: int,
    wide: bool = False,
    use_gl_vec: bool = False,
    cnt_cap: Optional[jnp.ndarray] = None,  # fleet-wide max cnt (bucket
    #   sizing only; defaults to cnt — see sort_partition)
):
    """Partition seg[sbegin : sbegin+cnt) by the split rule.

    ``use_gl_vec``: the go-left decision comes from a precomputed [n_pad]
    bit vector instead of the feature column (feature-parallel seg mode —
    only the owning shard holds the winner's bin plane; the bits arrive by
    psum and every shard applies the identical stable partition).

    Returns (seg', nl, nr): left child at [sbegin, sbegin+nl), right child at
    [sbegin+nl, sbegin+cnt), both in stable order; rows outside untouched.
    """
    n_ops = (used_lanes(f, wide) + 1) // 2  # i32 lanes that carry real data
    caps = window_caps(n_pad)
    if gl_vec is None:
        gl_vec = jnp.zeros((n_pad,), jnp.float32)

    def make_branch(P: int):
        def branch(op):
            seg, sbegin, cnt, feat, tbin, dl, nanb, iscat, glv = op
            start = jnp.minimum(sbegin, n_pad - P)
            off = sbegin - start
            # window-first: only O(P) data is ever materialized — a
            # full-array bitcast/reassemble here would copy the whole
            # 256B-per-row matrix on every split
            # only the used planes are sliced/rewritten (the rest are zero)
            win16 = lax.dynamic_slice(seg, (0, start), (2 * n_ops, P))
            uT = win16.astype(jnp.int32) & 0xFFFF  # [2*n_ops, P]
            pos = jnp.arange(P, dtype=jnp.int32)
            in_seg = (pos >= off) & (pos < off + cnt)
            if use_gl_vec:
                gl = (lax.dynamic_slice(glv, (start,), (P,)) > 0.5) & in_seg
            else:
                if wide:
                    # one u16 plane per feature (max_bin > 256)
                    colv = lax.dynamic_slice(uT, (feat, 0), (1, P))[0]
                else:
                    # feature column: byte j&1 of i16 lane j>>1
                    lane = feat >> 1
                    shift = (feat & 1) * 8
                    col16 = lax.dynamic_slice(uT, (lane, 0), (1, P))[0]
                    colv = (col16 >> shift) & 0xFF
                gl = _go_left(colv, tbin, dl, nanb, iscat, catmask) & in_seg
            key = jnp.where(
                pos < off,
                0,
                jnp.where(gl, 1, jnp.where(in_seg, 2, 3)),
            ).astype(jnp.int32)
            # combine i16 lane pairs into i32 payloads with strided slices
            # (a widening bitcast would materialize a [P, 64, 2] tensor whose
            # 2-wide minor dim tile-pads 64x)
            win32T = uT[0::2] | (uT[1::2] << 16)  # [n_ops, P]
            ops_in = (key,) + tuple(win32T[i] for i in range(n_ops))
            sorted_ops = lax.sort(ops_in, num_keys=1, is_stable=True)
            wsT = jnp.stack(sorted_ops[1:], axis=0)  # [n_ops, P] i32
            outT = jnp.zeros((2 * n_ops, P), jnp.int32)
            outT = outT.at[0::2].set(wsT & 0xFFFF)
            outT = outT.at[1::2].set((wsT >> 16) & 0xFFFF)
            win16_new = _u16(outT)  # [2*n_ops, P]
            seg = lax.dynamic_update_slice(seg, win16_new, (0, start))
            nl = jnp.sum(gl).astype(jnp.int32)
            return seg, nl

        return branch

    caps_arr = jnp.asarray(caps, dtype=jnp.int32)
    # fleet-vmapped growth: the caller pre-reduces cnt over the model axis
    # (cnt_cap) so ONE window branch lowers for the whole fleet — the
    # collective stays OUTSIDE the platform branches (sort_partition)
    if cnt_cap is None:
        cnt_cap = cnt
    bucket = jnp.clip(
        jnp.searchsorted(caps_arr, cnt_cap, side="left"), 0, len(caps) - 1
    ).astype(jnp.int32)
    branches = [make_branch(P) for P in caps]
    seg_new, nl = lax.switch(
        bucket, branches,
        (seg, sbegin, cnt, feat, tbin, dl, nanb, iscat, gl_vec),
    )
    nr = cnt - nl
    return seg_new, nl, nr


def sort_partition(
    seg, sbegin, cnt, feat, tbin, dl, nanb, iscat, catmask, *, f: int,
    n_pad: int, wide: bool = False, gl_vec=None, fleet_axis_name=None,
    measure: bool = False,
):
    """Platform dispatch for the segment partition: the Pallas streaming
    kernel on TPU (ops/pallas/partition.py — exact window, in place, no
    defensive copies), the stable-sort formulation elsewhere.  Both are
    stable partitions with bit-identical results.

    ``gl_vec`` (feature-parallel seg): the go-left decision comes from a
    precomputed [n_pad] bit vector; the Pallas kernel DMAs a bits tile per
    row tile instead of reading the feature column."""
    from .pallas.partition import seg_partition_pallas
    from ..obs.collectives import timed_pmax

    use_gl = gl_vec is not None
    # fleet-vmapped growth: reduce cnt over the model axis HERE, outside
    # the platform branches, so both lower the same collective sequence
    # (none) and the XLA window ladder sizes one shared branch
    if fleet_axis_name is not None:
        cnt_cap = timed_pmax(
            cnt, fleet_axis_name, site="fleet_cap", measure=measure
        )
    else:
        cnt_cap = cnt

    def _pallas(seg, sbegin, cnt, cnt_cap, feat, tbin, dl, nanb, iscat,
                catmask, *maybe_gl):
        bm = catmask.shape[0]
        bmt = max(256, -(-bm // 128) * 128)  # cat-table width (wide bins)
        catm = jnp.zeros((1, bmt), jnp.float32)
        catm = catm.at[0, :bm].set(catmask.astype(jnp.float32))
        scal = jnp.stack(
            [sbegin, cnt, feat, tbin, dl, nanb, iscat, jnp.int32(0)]
        ).astype(jnp.int32)
        seg_new, nl = seg_partition_pallas(
            seg, scal, catm, maybe_gl[0] if maybe_gl else None,
            f=f, n_pad=n_pad, use_cat=bm > 1, wide=wide,
        )
        return seg_new, nl, cnt - nl

    def _xla(seg, sbegin, cnt, cnt_cap, feat, tbin, dl, nanb, iscat,
             catmask, *maybe_gl):
        return sort_partition_xla(
            seg, sbegin, cnt, feat, tbin, dl, nanb, iscat, catmask,
            maybe_gl[0] if maybe_gl else None,
            f=f, n_pad=n_pad, wide=wide, use_gl_vec=use_gl,
            cnt_cap=cnt_cap,
        )

    args = (seg, sbegin, cnt, cnt_cap, feat, tbin, dl, nanb, iscat, catmask)
    if use_gl:
        args = args + (gl_vec,)
    if jax.default_backend() != "tpu":
        # no TPU registered: older jax lowers every platform_dependent
        # branch and the Pallas one cannot lower for CPU
        return _xla(*args)
    return jax.lax.platform_dependent(*args, tpu=_pallas, default=_xla)


def sort_partition_batch(
    seg,
    sbegins,  # [K] i32 — segment begins (disjoint windows)
    cnts,  # [K] i32 — segment rows (0 = no-op member)
    feats,  # [K] i32
    tbins,  # [K] i32
    dls,  # [K] i32
    nanbs,  # [K] i32
    iscats,  # [K] i32
    catmasks,  # [K, Bm] f32
    *,
    f: int,
    n_pad: int,
    wide: bool = False,
):
    """K stable partitions over K DISJOINT leaf windows (frontier-batched
    growth, ops/grower.py leaf_batch).  One K-program Pallas launch on TPU;
    elsewhere a sequential chain of the stable-sort partitions (disjoint
    windows make the chain order-independent and bit-identical to K serial
    calls).  Returns (seg', nl[K], nr[K])."""
    from .pallas.partition import seg_partition_pallas_batch

    k = sbegins.shape[0]

    def _pallas(seg, sbegins, cnts, feats, tbins, dls, nanbs, iscats,
                catmasks):
        bm = catmasks.shape[1]
        bmt = max(256, -(-bm // 128) * 128)
        catm = jnp.zeros((k, bmt), jnp.float32)
        catm = catm.at[:, :bm].set(catmasks.astype(jnp.float32))
        scal = jnp.stack(
            [sbegins, cnts, feats, tbins, dls, nanbs, iscats,
             jnp.zeros_like(sbegins)],
            axis=1,
        ).astype(jnp.int32)
        seg_new, nl = seg_partition_pallas_batch(
            seg, scal, catm, f=f, n_pad=n_pad, use_cat=bm > 1, wide=wide,
        )
        return seg_new, nl, cnts - nl

    def _xla(seg, sbegins, cnts, feats, tbins, dls, nanbs, iscats, catmasks):
        nls = []
        for i in range(k):
            seg, nl_i, _ = sort_partition_xla(
                seg, sbegins[i], cnts[i], feats[i], tbins[i], dls[i],
                nanbs[i], iscats[i], catmasks[i],
                f=f, n_pad=n_pad, wide=wide, use_gl_vec=False,
            )
            nls.append(nl_i)
        nl = jnp.stack(nls)
        return seg, nl, cnts - nl

    args = (seg, sbegins, cnts, feats, tbins, dls, nanbs, iscats, catmasks)
    if jax.default_backend() != "tpu":
        return _xla(*args)
    return jax.lax.platform_dependent(*args, tpu=_pallas, default=_xla)


def leaf_of_positions(
    leaf_sbegin: jnp.ndarray,  # [L] i32 (active leaves' segment begins)
    leaf_rows: jnp.ndarray,  # [L] i32
    num_leaves: jnp.ndarray,  # scalar i32
    n: int,
) -> jnp.ndarray:
    """leaf index per segment POSITION via the marker-cumsum trick (no
    scatter of rows): mark each active leaf's begin, cumsum to segment
    ordinals, map ordinals through a begin-sorted leaf permutation."""
    L = leaf_sbegin.shape[0]
    active = jnp.arange(L, dtype=jnp.int32) < num_leaves
    begin_marks = jnp.where(active & (leaf_rows > 0), leaf_sbegin, n)
    marker = jnp.zeros((n,), jnp.int32).at[begin_marks].add(1, mode="drop")
    sort_key = jnp.where(active & (leaf_rows > 0), leaf_sbegin, 2 * n + 2)
    sorted_leaf = jnp.argsort(sort_key).astype(jnp.int32)
    seg_ord = jnp.clip(jnp.cumsum(marker) - 1, 0, L - 1)
    return sorted_leaf[seg_ord]


def leaf_id_from_seg(
    ridx: jnp.ndarray,  # [n] i32 — original row index per segment position
    leaf_pos: jnp.ndarray,  # [n] i32 — leaf per segment position
) -> jnp.ndarray:
    """Invert the segment permutation with one sort (XLA TPU sort is fast;
    a scatter here would serialize)."""
    _, leaf_id = lax.sort((ridx, leaf_pos), num_keys=1)
    return leaf_id
