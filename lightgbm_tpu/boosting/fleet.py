"""Vmapped model-fleet training: M boosters, one compiled grow step.

A *fleet* trains M same-shape models in lockstep over one shared binned
dataset: the [N, F] bin planes, bin counts and NaN bins are broadcast
(unmapped) operands while gradients, hessians, bagging masks, feature
masks and RNG keys carry a leading model axis.  Each boosting iteration
issues ONE batched grow per tree class (``parallel.mesh.make_fleet_grow``,
a ``jax.vmap`` of the compiled grow step) instead of M serial grows, so
the whole sweep shares a single executable and the histogram phase runs
all M members per kernel launch.  Under ``tree_learner=data`` the member
histograms travel in one stacked psum payload per step.

Byte parity: the batched grow is value-identical per member to the solo
``grow_tree`` call (capacity buckets are unified across the fleet via an
``axis_name`` pmax — padding-only, see ``GrowerParams.fleet_axis_name``),
and the host-side preamble/commit reuse the Booster's own
``_fleet_begin_iter`` / ``_commit_class_tree`` methods, so every member's
model dump is byte-identical to the model its params would produce in a
solo ``lgb.train`` run.

v1 scope: members must share the training Dataset and identical
``GrowerParams`` (sweeps over seeds, learning_rate, bagging/GOSS
fractions, extra_seed, and CV-fold row masks).  Finished or early-stopped
members become value-preserving no-op lanes (zero gradients, outputs
discarded) so the executable never retraces as the fleet drains.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..obs.registry import get_session
from ..obs.flight import get_flight
from ..obs.device import sample_device_memory
from ..utils.timer import global_timer
from .gbdt import Booster


def _same_grower_params(a, b) -> bool:
    """GrowerParams are frozen dataclasses of hashable leaves; direct
    equality is the exact static-trace-compatibility test (anything that
    differs would have produced a different executable)."""
    return a == b


def _arrays_equal(a, b) -> bool:
    if a is None or b is None:
        return a is None and b is None
    return np.array_equal(np.asarray(a), np.asarray(b))


class FleetTrainer:
    """Lockstep trainer for a list of same-shape Boosters.

    One ``update()`` call advances every active member by one boosting
    iteration with a single batched grow per tree class.  Members that
    finish (no positive-gain split) or are stopped externally
    (``stop_member``, e.g. per-member early stopping) keep their final
    state and ride along as zero-gradient lanes — the operand shapes
    never change, so the warm executable is reused to the end.
    """

    def __init__(self, boosters: List[Booster]):
        if not boosters:
            raise ValueError("fleet needs at least one booster")
        self.boosters = list(boosters)
        self._stopped = [False] * len(self.boosters)
        self._round = 0
        self._validate()
        b0 = self.boosters[0]
        import dataclasses

        from ..parallel.mesh import MeshSpec, make_fleet_grow

        # the fused Pallas grow step is a serial-path specialization; the
        # two-launch XLA composition is its byte-identical oracle, so the
        # fleet always traces the XLA path (safe under vmap everywhere)
        params = dataclasses.replace(b0._grower_params, grow_fused=False)
        spec = getattr(b0, "_mesh_spec", None)
        if spec is None:
            size = b0._mesh.size if b0._mesh is not None else 1
            spec = MeshSpec("data", data=size)
        self._grow = make_fleet_grow(b0._mesh, params, spec)
        self._mesh_spec = spec
        f_used = b0._bins.shape[1]
        # dummy operands for statically-gated-off features (same contract
        # as Booster._setup_sharded_grower: concrete arrays stand in for
        # absent optionals and are dead code inside the trace)
        self._mono_arg = (
            b0._monotone
            if b0._monotone is not None
            else jnp.zeros((f_used,), jnp.int8)
        )
        self._inter_arg = (
            b0._interaction_sets
            if b0._interaction_sets is not None
            else jnp.ones((1, f_used), bool)
        )
        self._iscat_arg = (
            b0._is_cat if b0._is_cat is not None else jnp.zeros((f_used,), bool)
        )
        self._bundle_end_arg = (
            b0._bundle_end
            if b0._bundle_end is not None
            else jnp.full((1, 1), -1, jnp.int32)
        )
        self._contri_arg = (
            b0._feature_contri
            if b0._feature_contri is not None
            else jnp.ones((f_used,), jnp.float32)
        )
        self._cegb_p_arg = jnp.zeros((f_used,), jnp.float32)
        self._cegb_u_arg = jnp.zeros((f_used,), bool)
        self._qs_arg = (jnp.float32(1.0), jnp.float32(1.0))
        self._zero_key = jnp.zeros((2,), jnp.uint32)

    # ------------------------------------------------------------ validation

    def _validate(self) -> None:
        b0 = self.boosters[0]
        for i, b in enumerate(self.boosters):
            where = f"fleet member {i}"
            if type(b) is not Booster:
                raise ValueError(
                    f"{where}: fleet v1 supports plain gbdt/goss Boosters "
                    f"only, got {type(b).__name__}"
                )
            if b.train_set is not b0.train_set:
                raise ValueError(
                    f"{where}: all fleet members must share the SAME "
                    "training Dataset object (same-shape sweeps; use "
                    "set_row_mask for CV folds)"
                )
            if not _same_grower_params(b._grower_params, b0._grower_params):
                raise ValueError(
                    f"{where}: GrowerParams differ from member 0 — fleet "
                    "members must be trace-compatible (identical "
                    "num_leaves/max_bin/hist_mode/regularization/...); "
                    "sweep seeds, learning_rate, or sampling fractions "
                    "instead"
                )
            cfg = b.config
            if b.objective is None:
                raise ValueError(f"{where}: fleet needs a built-in objective")
            if b.objective.is_renew_tree_output:
                raise ValueError(
                    f"{where}: objectives with renew_tree_output "
                    f"({type(b.objective).__name__}) are not fleet-capable"
                )
            for flag in ("linear_tree", "use_quantized_grad"):
                if getattr(cfg, flag):
                    raise ValueError(f"{where}: {flag} is not fleet-capable")
            if b._cegb_coupled is not None:
                raise ValueError(f"{where}: CEGB is not fleet-capable")
            if getattr(b, "_multiproc", False):
                raise ValueError(
                    f"{where}: multi-process feeding is not fleet-capable"
                )
            if b._forced is not None:
                raise ValueError(
                    f"{where}: forced splits are not fleet-capable"
                )
            if b._grower_params.hist_mode == "seg":
                raise ValueError(
                    f"{where}: hist_mode='seg' (Pallas sort path) is not "
                    "fleet-capable yet; use ordered/gather/full"
                )
            if b.num_tree_per_iteration != b0.num_tree_per_iteration:
                raise ValueError(f"{where}: num_tree_per_iteration differs")
            if list(b._class_need_train) != list(b0._class_need_train):
                raise ValueError(f"{where}: _class_need_train differs")
            if len(b.models_) or b._iter:
                raise ValueError(f"{where}: fleet members must be untrained")
            # dataset-derived static operands must match member 0 so the
            # shared (unmapped) operands are correct for every lane
            for name in ("_monotone", "_interaction_sets", "_is_cat",
                         "_bundle_end", "_feature_contri"):
                if not _arrays_equal(getattr(b, name), getattr(b0, name)):
                    raise ValueError(
                        f"{where}: {name} differs from member 0"
                    )

    # -------------------------------------------------------------- controls

    @property
    def size(self) -> int:
        return len(self.boosters)

    def active_members(self) -> List[int]:
        return [
            i
            for i, b in enumerate(self.boosters)
            if not (b._finished or self._stopped[i])
        ]

    def stop_member(self, i: int) -> None:
        """Externally deactivate a member (early stopping); its state is
        frozen and its lane degrades to a zero-fed no-op."""
        self._stopped[i] = True

    def done(self) -> bool:
        return not self.active_members()

    # ------------------------------------------------------------- iteration

    def update_launch(self, n: int) -> int:
        """Advance up to ``n`` lockstep rounds in ONE compiled launch
        (scan-over-vmap — boosting/launch.py).  Per-member models stay
        byte-identical to the serial round loop; externally-stopped
        members ride as select-frozen no-op lanes.  Returns the number of
        rounds consumed."""
        if int(n) <= 1:
            self.update()
            return 1
        from .launch import FleetLaunchRunner

        cache = getattr(self, "_launch_runners", None)
        if cache is None:
            cache = self._launch_runners = {}
        runner = cache.get(int(n))
        if runner is None:
            runner = cache[int(n)] = FleetLaunchRunner(self, int(n))
        return runner.run()

    def update(self) -> List[bool]:
        """One lockstep boosting iteration.  Returns the per-member
        inactive flags (True = finished or stopped) after the round."""
        boosters = self.boosters
        m = len(boosters)
        active = self.active_members()
        if not active:
            return [True] * m
        ses = get_session()
        b0 = boosters[0]
        k = b0.num_tree_per_iteration
        ops: Dict[int, dict] = {}
        for i in active:
            ops[i] = boosters[i]._fleet_begin_iter()
        if ses.enabled:
            ses.set_gauge("fleet/size", m)
            ses.set_gauge("fleet/active", len(active))

        should = {i: False for i in active}
        template = ops[active[0]]
        zero_row = jnp.zeros_like(template["grad"][0])
        ones_fm = jnp.ones_like(template["feature_mask"])
        for kk in range(k):
            if not (b0._class_need_train[kk] and b0._bins.shape[1] > 0):
                for i in active:
                    o = ops[i]
                    if boosters[i]._commit_class_tree(
                        kk, None, o["grad"], o["hess"], o["mask"],
                        o["init_scores"],
                    ):
                        should[i] = True
                continue
            grown = self._grow_fleet_class(kk, ops, zero_row, ones_fm)
            for i in active:
                o = ops[i]
                if boosters[i]._commit_class_tree(
                    kk, grown[i], o["grad"], o["hess"], o["mask"],
                    o["init_scores"],
                ):
                    should[i] = True

        for i in active:
            boosters[i]._fleet_end_iter(should[i])
        self._round += 1
        inactive = [
            b._finished or self._stopped[i] for i, b in enumerate(boosters)
        ]
        if ses.enabled:
            ses.inc("fleet/iterations")
            self._note_collectives(ses, k)
        flight = get_flight()
        if flight.active:
            flight.note_event(
                {
                    "event": "fleet_iteration",
                    "round": self._round,
                    "fleet": m,
                    "active": len(active),
                    "finished": sum(1 for f in inactive if f),
                }
            )
        return inactive

    def _grow_fleet_class(self, kk, ops, zero_row, ones_fm):
        """One batched grow for tree class kk: stack the per-member traced
        operands (inactive lanes get value-preserving zero slots), dispatch
        the single vmapped executable, then bulk-fetch all member trees in
        one transfer.  Returns {member index: (ta, ta_host, leaf_id)} for
        active members."""
        boosters = self.boosters
        b0 = boosters[0]
        grad_rows, hess_rows, mask_rows, fm_rows, keys = [], [], [], [], []
        for i in range(len(boosters)):
            o = ops.get(i)
            if o is None:
                grad_rows.append(zero_row)
                hess_rows.append(zero_row)
                mask_rows.append(zero_row)
                fm_rows.append(ones_fm)
                keys.append(self._zero_key)
            else:
                grad_rows.append(o["grad"][kk])
                hess_rows.append(o["hess"][kk])
                mask_rows.append(o["mask"])
                fm_rows.append(o["feature_mask"])
                r = o["tree_rngs"][kk]
                keys.append(self._zero_key if r is None else r)
        with global_timer.timed("tree/grow"), get_session().phase("grow"):
            fta, fleaf = self._grow(
                b0._bins,
                jnp.stack(grad_rows),
                jnp.stack(hess_rows),
                jnp.stack(mask_rows),
                b0._num_bins,
                b0._nan_bins,
                jnp.stack(fm_rows),
                self._mono_arg,
                self._inter_arg,
                jnp.stack(keys),
                self._iscat_arg,
                None,
                self._cegb_p_arg,
                self._cegb_u_arg,
                self._qs_arg,
                self._bundle_end_arg,
                self._contri_arg,
            )
            get_session().sync(fleaf)
            sample_device_memory("grow")
        from ..ops.grower import fetch_fleet_tree_arrays

        with get_session().phase("host_materialize"):
            ta_hosts = fetch_fleet_tree_arrays(fta)
        grown = {}
        for i in ops:
            b = boosters[i]
            ta_i = jax.tree_util.tree_map(lambda a: a[i], fta)
            ta_host = ta_hosts[i]
            if b.config.check_numerics:
                b._guard_tree(ta_host, b._iter)
            b._note_refine_rate(ta_host)
            grown[i] = (ta_i, ta_host, fleaf[i])
        return grown

    def _note_collectives(self, ses, k: int) -> None:
        """Analytic psum gauges for the fleet step under a data mesh: one
        stacked [M, ...] payload per step instead of M separate rounds."""
        b0 = self.boosters[0]
        if b0._mesh is None or b0.config.tree_learner == "voting":
            return
        from ..parallel.mesh import fleet_psum_bytes_per_iteration

        coll = fleet_psum_bytes_per_iteration(
            max(1, b0.config.num_leaves - 1),
            int(b0._bins.shape[1]),
            int(b0._grower_params.max_bin),
            fleet=len(self.boosters),
            leaf_batch=int(b0.config.leaf_batch),
            spec=self._mesh_spec,
        )
        coll = {k2: v * k if k2 != "fleet" else v for k2, v in coll.items()}
        ses.set_gauge("fleet/psum_hist_bytes", coll["hist_bytes"])
        ses.set_gauge("fleet/psum_count_bytes", coll["count_bytes"])
        ses.set_gauge(
            "fleet/psum_ring_bytes_per_device", coll["ring_bytes_per_device"]
        )


__all__ = ["FleetTrainer"]
