"""Device-resident boosting: scan N iterations per compiled launch.

``train_steps_per_launch=N`` fuses gradient/hessian computation, the full
tree-grow step and the score update for N consecutive boosting iterations
into ONE compiled ``lax.scan`` program, so the host loop advances N trees
per dispatch instead of returning to Python every iteration.  The scanned
carry is the already-device-resident trainer state: the [K, N] score
cache (donated), the RNG key, the persistent bagging mask, and the
finished/bad-step latches.  Per-iteration bagging/GOSS mask derivation is
folded inside the scan (``SampleStrategy.scan_sample``), and the N grown
trees ride out as packed (ints, floats) stacks — the same two-transfer
encoding ``fetch_tree_arrays`` uses — to be materialized, validated and
committed on the host after the launch returns.

Byte parity is the contract: every eligible config produces model dumps
byte-identical to the N=1 serial loop.  The load-bearing details:

* RNG stream: the serial loop consumes one ``split`` for gradients, one
  for bagging (ALWAYS, even on non-refresh iterations — the key is drawn
  and discarded), and one per trained class only when the grower needs
  device RNG.  The scan body replays exactly that order with the same
  ``fold_in`` gating on explicit ``bagging_seed``/``extra_seed``.
* Host branches become whole-array selects: bagging refresh and GOSS
  warmup are ``jnp.where`` selects of complete arrays (never
  ``x + where(p, delta, 0)``, which can flip ``-0.0`` to ``+0.0``), and
  a halted step's carry is select-protected so a mid-window finish
  freezes score/RNG/mask bit-exactly.
* The grow step always traces the two-launch XLA composition
  (``grow_fused=False``) — the same byte-identical oracle the fleet path
  uses — so the scan body is scan/vmap-safe everywhere, including under
  ``tree_learner=data`` mesh specs (the histogram psums scan cleanly
  inside shard_map).

Host-boundary semantics: eval, early stopping, callbacks, checkpoints,
snapshots and flight-recorder events bucket to launch boundaries; the
validator (:func:`resolve_launch_steps`) clamps N to divide every active
period and warns once.  ``check_numerics`` failures are detected on the
device carry (a ``bad`` latch records the first offending iteration; no
per-step host pull) and re-raised after the launch with the window named;
the trees grown BEFORE the bad step are committed first, so "model state
is intact up to the previous iteration" still holds.  Accepted
divergence: the serial loop raises after consuming only the gradient key
of the bad iteration, while the scan consumed that step's full key
budget — only the dead trainer's RNG differs, committed models and
scores are identical.
"""

from __future__ import annotations

import dataclasses
import math
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..obs.flight import get_flight
from ..obs.jit import compile_count as _compile_count
from ..obs.jit import instrumented_jit
from ..obs.registry import get_session
from ..obs.device import sample_device_memory
from ..obs.trace import get_tracer
from ..ops.grower import _pack_tree_arrays_impl, grow_tree, unpack_tree_arrays
from ..resilience import NumericsError, chaos
from ..utils.log import log_warning

_EPS = 1e-15


# --------------------------------------------------------------- validation


def resolve_requested_steps(cfg) -> int:
    """The user-requested N: 'auto' resolves to 8 on TPU backends (where
    the per-dispatch fixed cost dominates the <100 ms/iteration budget)
    and 1 elsewhere."""
    req = cfg.train_steps_per_launch
    if req == "auto":
        return 8 if jax.default_backend() == "tpu" else 1
    return max(1, int(req))


def clamp_steps(n: int, periods) -> int:
    """Clamp a requested steps-per-launch so every host-boundary feature
    still fires on its configured period: N is reduced to
    ``gcd(N, period)`` for each ACTIVE period (eval via ``metric_freq``
    when eval work exists, ``checkpoint_interval`` when a checkpoint dir
    is set, ``snapshot_freq`` when > 0), so launch boundaries always land
    exactly on the iterations the serial loop would have acted on."""
    n = max(1, int(n))
    for p in periods:
        p = int(p)
        if p > 0:
            n = math.gcd(n, p)
    return max(1, n)


def launch_ineligible_reason(booster) -> Optional[str]:
    """Why this booster cannot scan iterations on device (None = eligible).

    The exclusions mirror the fleet trainer's: paths with per-iteration
    host work woven into the update (renew_tree_output's host leaf
    renewal, linear-tree least squares, CEGB's host-side used-feature
    latch), per-iteration host RNG the scan cannot reproduce
    (quantized-gradient stochastic rounding draws a key inside
    ``_quant_grow_inputs``), subclassed boosting schedules (dart's drop
    state, rf's bag-of-iterations), multi-process feeding, and armed
    chaos drills (their kill/poison hooks are host-gated per iteration).
    ``hist_mode='seg'`` stays ELIGIBLE: the scan traces the two-launch
    XLA composition, the seg path's byte-identical oracle.
    """
    from .gbdt import Booster

    cfg = booster.config
    if type(booster) is not Booster:
        return f"boosting type {type(booster).__name__} (dart/rf schedules)"
    if booster.objective is None:
        return "custom objective (host-side fobj)"
    if booster.objective.is_renew_tree_output:
        return (
            f"objective {type(booster.objective).__name__} renews leaf "
            "outputs on host each iteration"
        )
    if cfg.linear_tree:
        return "linear_tree fits leaf models on host each iteration"
    if cfg.use_quantized_grad:
        return "use_quantized_grad draws a host RNG key per iteration"
    if getattr(booster, "_cegb_coupled", None) is not None:
        return "CEGB updates its used-feature penalty on host each iteration"
    if getattr(booster, "_multiproc", False):
        return "multi-process feeding reassembles gradients on host"
    if chaos._ARMED:
        return "chaos drill armed (per-iteration host fault hooks)"
    if booster._bins.shape[1] <= 0 or not any(booster._class_need_train):
        return "no trainable tree class"
    return None


def resolve_launch_steps(booster, *, has_eval_work: bool) -> int:
    """Final steps-per-launch for a train run: requested N, eligibility
    fallback, then the period clamp.  Warns (once per train — this runs
    once per train) when the user's explicit request is overridden."""
    cfg = booster.config
    n = resolve_requested_steps(cfg)
    if n <= 1:
        return 1
    explicit = cfg.train_steps_per_launch != "auto"
    reason = launch_ineligible_reason(booster)
    if reason is not None:
        if explicit:
            log_warning(
                f"[launch] train_steps_per_launch={n} ignored ({reason}); "
                "falling back to one iteration per dispatch"
            )
        return 1
    periods = []
    if has_eval_work:
        periods.append(max(1, cfg.metric_freq))
    if cfg.checkpoint_dir and cfg.checkpoint_interval > 0:
        periods.append(cfg.checkpoint_interval)
    if cfg.snapshot_freq > 0:
        periods.append(cfg.snapshot_freq)
    clamped = clamp_steps(n, periods)
    if clamped != n:
        log_warning(
            f"[launch] train_steps_per_launch clamped {n} -> {clamped} so "
            "launch boundaries divide the active eval/checkpoint/snapshot "
            f"periods {sorted(set(int(p) for p in periods))} (host-boundary "
            "features fire every N iterations)"
        )
    return clamped


def resolve_fleet_launch_steps(trainer, *, has_eval_work: bool) -> int:
    """Fleet variant of :func:`resolve_launch_steps`: every member must be
    launch-eligible, and the clamp covers every member's eval period (the
    fleet path has no checkpoint/snapshot work)."""
    b0 = trainer.boosters[0]
    n = resolve_requested_steps(b0.config)
    if n <= 1:
        return 1
    explicit = b0.config.train_steps_per_launch != "auto"
    for i, b in enumerate(trainer.boosters):
        reason = launch_ineligible_reason(b)
        if reason is not None:
            if explicit:
                log_warning(
                    f"[launch] train_steps_per_launch={n} ignored for the "
                    f"fleet (member {i}: {reason}); falling back to one "
                    "lockstep round per dispatch"
                )
            return 1
    periods = []
    if has_eval_work:
        periods.extend(
            max(1, b.config.metric_freq) for b in trainer.boosters
        )
    clamped = clamp_steps(n, periods)
    if clamped != n:
        log_warning(
            f"[launch] fleet train_steps_per_launch clamped {n} -> "
            f"{clamped} so launch boundaries divide every member's eval "
            "period"
        )
    return clamped


# ------------------------------------------------------------- solo runner


class LaunchRunner:
    """Compiled N-iteration scan for one Booster.

    Built lazily by ``Booster.update_launch`` and cached per N; the
    static snapshot (sampler, objective, grower params, pad/fixed-mask
    gating) is taken at build time, and :meth:`stale` tells the booster
    when a rebuild is needed (e.g. ``set_row_mask`` between trains).
    One ``run()`` = one device dispatch advancing up to N iterations,
    followed by host materialization of the N packed trees through the
    SAME ``_commit_class_tree`` path the serial loop uses (with only the
    already-applied train-score update skipped).
    """

    def __init__(self, booster, n: int):
        self._b = booster
        self._n = int(n)
        cfg = booster.config
        self._k = booster.num_tree_per_iteration
        self._trains = [
            bool(booster._class_need_train[kk] and booster._bins.shape[1] > 0)
            for kk in range(self._k)
        ]
        self._L = int(booster._grower_params.num_leaves)
        self._nn = self._L - 1
        self._any_pad = bool(booster._pad_rows) or getattr(
            booster, "_multiproc", False
        )
        self._has_fixed = getattr(booster, "_fixed_row_mask", None) is not None
        self._params = dataclasses.replace(
            booster._grower_params, grow_fused=False
        )
        # STRONG refs to the snapshotted objects: they pin the snapshot for
        # the runner's lifetime so the identity checks in stale() cannot be
        # fooled by CPython allocating a replacement object at a freed
        # object's address (id reuse would silently revive a cached
        # executable traced against the old sampler/objective constants)
        self._snap_sampler = booster._sampler
        self._snap_objective = booster.objective
        self._snap_grower_params = booster._grower_params
        self._snap_bins_shape = booster._bins.shape
        self._fn = instrumented_jit(
            self._launch_impl,
            label=f"grow/scan{self._n}",
            donate_argnums=(0,),
        )

    def stale(self, booster) -> bool:
        return not (
            booster._sampler is self._snap_sampler
            and booster.objective is self._snap_objective
            and booster._grower_params is self._snap_grower_params
            and (getattr(booster, "_fixed_row_mask", None) is not None)
            == self._has_fixed
            and booster._bins.shape == self._snap_bins_shape
        )

    # ----------------------------------------------------------- trace body

    def _grow(self, bins, g, h, mask, fm, tkey):
        """Per-class grow inside the scan body: the mesh-sharded shard_map
        path (unchanged executable semantics — shard_map traces cleanly
        under scan) or serial ``grow_tree`` with the fused dispatcher
        forced to its XLA oracle."""
        b = self._b
        if b._mesh is not None:
            return b._sharded_grow(
                bins,
                g,
                h,
                mask,
                b._num_bins,
                b._nan_bins,
                fm,
                b._mono_arg,
                b._inter_arg,
                tkey if tkey is not None else jax.random.PRNGKey(0),
                b._iscat_arg,
                b._forced,
                *b._cegb_args(),
                b._quant_scales_arg(),
                b._bundle_end_arg,
                b._contri_arg,
            )
        return grow_tree(
            bins,
            g,
            h,
            mask,
            b._num_bins,
            b._nan_bins,
            fm,
            self._params,
            monotone=b._monotone,
            interaction_sets=b._interaction_sets,
            rng=tkey,
            is_cat=b._is_cat,
            forced=b._forced,
            quant_scales=None,
            bundle_end=b._bundle_end,
            feature_contri=b._feature_contri,
        )

    def _launch_impl(self, score, rng, bag, its, fms, bins, ones_mask, fixed):
        b = self._b
        cfg = b.config
        k = self._k
        sampler = b._sampler
        objective = b.objective
        shrink = float(b._shrinkage_rate)
        check = bool(cfg.check_numerics)
        any_pad = self._any_pad
        has_fixed = self._has_fixed
        fold_bag = "bagging_seed" in cfg.raw
        need_tkey = bool(cfg.feature_fraction_bynode < 1.0 or cfg.extra_trees)
        fold_extra = bool(cfg.extra_trees and "extra_seed" in cfg.raw)

        def step(carry, xs):
            score, rng, bag, finished, bad = carry
            it = xs["it"]
            fm = xs["fm"]
            halted = jnp.logical_or(finished, bad >= 0)
            # 1) gradient key + gradients (serial: _get_gradients)
            pair = jax.random.split(rng)
            rng_g, gkey = pair[0], pair[1]
            grad, hess = objective.get_gradients(score, gkey)
            # 2) device-side numerics latch (serial: _guard_gradients pulls
            # one host bool per iteration; here the verdict rides the carry)
            if check:
                ok = jnp.logical_and(
                    jnp.isfinite(grad).all(), jnp.isfinite(hess).all()
                )
            else:
                ok = jnp.asarray(True)
            # 3) pad/fixed-mask zeroing BEFORE sampling (serial: _sample)
            if any_pad or has_fixed:
                live = ones_mask[None] > 0
                if has_fixed:
                    live = jnp.logical_and(live, fixed[None] > 0)
                grad = jnp.where(live, grad, 0.0)
                hess = jnp.where(live, hess, 0.0)
            # 4) bagging key — drawn EVERY iteration like the serial loop
            pair = jax.random.split(rng_g)
            rng_b, bkey = pair[0], pair[1]
            if fold_bag:
                bkey = jax.random.fold_in(bkey, cfg.bagging_seed)
            mask, grad, hess, bag_new = sampler.scan_sample(
                it, grad, hess, bkey, bag
            )
            if any_pad:
                mask = mask * ones_mask
            if has_fixed:
                mask = mask * fixed
            # 5) per-class grow + gated score update
            rng_cur = rng_b
            new_score = score
            any_split = jnp.asarray(False)
            live_step = jnp.logical_and(jnp.logical_not(halted), ok)
            ints_rows: List[Any] = [None] * k
            floats_rows: List[Any] = [None] * k
            for kk in range(k):
                if not self._trains[kk]:
                    continue
                tkey = None
                if need_tkey:
                    pair = jax.random.split(rng_cur)
                    rng_cur, tkey = pair[0], pair[1]
                    if fold_extra:
                        tkey = jax.random.fold_in(tkey, cfg.extra_seed)
                ta, leaf_id = self._grow(
                    bins, grad[kk], hess[kk], mask, fm, tkey
                )
                has_split = ta.num_leaves > 1
                upd = jnp.logical_and(live_step, has_split)
                shrunk = ta.leaf_value * shrink
                # whole-array select (NOT add-of-masked-delta): a skipped
                # step must keep the old score bit patterns, -0.0 included
                cand = new_score.at[kk].add(shrunk[leaf_id])
                new_score = jnp.where(upd, cand, new_score)
                any_split = jnp.logical_or(any_split, has_split)
                ii, ff = _pack_tree_arrays_impl(ta)
                ints_rows[kk] = ii
                floats_rows[kk] = ff
            zi = next(v for v in ints_rows if v is not None)
            zf = next(v for v in floats_rows if v is not None)
            ints = jnp.stack(
                [v if v is not None else jnp.zeros_like(zi) for v in ints_rows]
            )
            floats = jnp.stack(
                [v if v is not None else jnp.zeros_like(zf) for v in floats_rows]
            )
            # 6) latches + select-protected carry
            finished2 = jnp.logical_or(
                finished,
                jnp.logical_and(live_step, jnp.logical_not(any_split)),
            )
            bad2 = jnp.where(
                jnp.logical_and(
                    bad < 0,
                    jnp.logical_and(
                        jnp.logical_not(halted), jnp.logical_not(ok)
                    ),
                ),
                it,
                bad,
            )
            rng_out = jnp.where(halted, rng, rng_cur)
            bag_out = jnp.where(halted, bag, bag_new)
            return (new_score, rng_out, bag_out, finished2, bad2), {
                "ints": ints,
                "floats": floats,
            }

        carry0 = (
            score,
            rng,
            bag,
            jnp.zeros((), bool),
            jnp.full((), -1, jnp.int32),
        )
        return jax.lax.scan(step, carry0, {"it": its, "fm": fms})

    # ------------------------------------------------------------ execution

    def run(self) -> Tuple[int, bool]:
        """One launch: up to N iterations on device, then host replay of
        the packed trees through the serial commit path.  Returns
        ``(steps_consumed, is_finished)`` with the serial loop's
        semantics: the finishing (all-constant, rolled-back) iteration
        counts as consumed but does not advance ``_iter``."""
        b = self._b
        cfg = b.config
        k = self._k
        from .sampling import BaggingStrategy

        b._drain_pending()
        if b._finished:
            return 0, True
        # boost-from-average prologue — replicated from _update_impl so the
        # scan's step-0 gradients see the boosted score
        init_scores = [0.0] * k
        if (
            not b.models_
            and not b._has_init_score
            and b.objective is not None
            and cfg.boost_from_average
        ):
            for kk in range(k):
                s = b.objective.boost_from_score(kk)
                if abs(s) > _EPS:
                    init_scores[kk] = s
                    b._score = b._score.at[kk].add(s)
                    for entry in b._valid:
                        entry.score = entry.score.at[kk].add(s)
        elif (
            not b.models_
            and b.objective is not None
            and not cfg.boost_from_average
            and not b._has_init_score
        ):
            # first-round constant-tree hazard: if no class splits at
            # iteration 0, the serial commit injects boost_from_score into
            # the score cache on host — unreplayable mid-scan, so the first
            # iteration runs serially and launches start from iteration 1
            return 1, b.update()

        ses = get_session()
        flight = get_flight()
        wd = getattr(b, "_watchdog", None)
        it0 = int(b._iter)
        S = self._n
        its = jnp.asarray(np.arange(it0, it0 + S, dtype=np.int32))
        fm_rows = []
        for it in range(it0, it0 + S):
            m = b._feature_mask_np_for(it)
            b._note_live_plane(
                None if m.all() else m, int(b._bins.shape[1])
            )
            fm_rows.append(m)
        fms = jnp.asarray(np.stack(fm_rows))
        is_bagging = isinstance(b._sampler, BaggingStrategy)
        bag0 = b._sampler._mask if is_bagging else jnp.zeros((1,), jnp.float32)
        fixed = getattr(b, "_fixed_row_mask", None)
        fixed_arg = fixed if fixed is not None else jnp.zeros((1,), jnp.float32)

        compiles_before = _compile_count()
        tracer = get_tracer()
        # launch span: the phase("launch") child attaches under it via the
        # tls stack; synthetic per-iteration children are reconstructed from
        # the device counter records in _note_launch, which also ends it
        lsp = tracer.begin(
            "train/launch",
            "train",
            args={"launch_begin": it0, "steps_per_launch": S},
            attach=True,
            ambient=True,
        )
        t0 = time.perf_counter()
        if ses.enabled:
            ses.begin_iteration()
        try:
            try:
                with ses.phase("launch"):
                    carry, ys = self._fn(
                        b._score,
                        b._rng,
                        bag0,
                        its,
                        fms,
                        b._bins,
                        b._ones_mask,
                        fixed_arg,
                    )
                    score, rng, bag, finished_dev, bad_dev = carry
                    # donated score: rebind before anything can raise
                    b._score = score
                    b._rng = rng
                    if is_bagging:
                        b._sampler._mask = bag
            finally:
                phases = ses.end_iteration() if ses.enabled else {}
            ints = np.asarray(ys["ints"])  # [S, k, ints_len] — blocks = synced
            floats = np.asarray(ys["floats"])
            bad = int(bad_dev)
        except BaseException:
            # scan failure skips _note_launch — end the span here to keep
            # the tls span stack balanced for the fault path
            if lsp is not None:
                tracer.end(lsp, extra={"error": True})
                lsp = None
            raise
        wall_ms = (time.perf_counter() - t0) * 1e3

        # ---- host replay: materialize + commit in serial iteration order
        steps_done = 0
        records = []
        is_finished = False
        try:
            for s in range(S):
                it = it0 + s
                chaos.on_iteration(it)
                if bad >= 0 and it == int(bad):
                    b._fault_dump("numerics_gradients")
                    raise NumericsError(
                        f"non-finite gradients/hessians at iteration {it} "
                        f"inside launch window [{it0}, {it0 + S}) "
                        f"(train_steps_per_launch={S}, "
                        f"objective={b._objective_name()}); model state is "
                        "intact up to the previous iteration — inspect "
                        "labels, init_score, and learning_rate"
                    )
                isc = init_scores if s == 0 else [0.0] * k
                should = False
                rec = {
                    "iter": it,
                    "trees_materialized": 0,
                    "splits": 0,
                    "grow_steps": 0,
                    "refine_count": 0,
                }
                for kk in range(k):
                    grown = None
                    if self._trains[kk]:
                        ta_host = unpack_tree_arrays(
                            ints[s, kk], floats[s, kk], self._nn, self._L
                        )
                        if cfg.check_numerics:
                            b._guard_tree(ta_host, it)
                        b._note_refine_rate(ta_host)
                        rec["grow_steps"] += int(ta_host.grow_steps)
                        rec["refine_count"] += int(ta_host.refine_count)
                        if int(ta_host.num_leaves) > 1:
                            ta_dev = jax.tree_util.tree_map(
                                jnp.asarray, ta_host
                            )
                            grown = (ta_dev, ta_host, None)
                            rec["trees_materialized"] += 1
                            rec["splits"] += int(ta_host.num_leaves) - 1
                    if b._commit_class_tree(
                        kk, grown, None, None, None, isc,
                        skip_train_score=True,
                    ):
                        should = True
                records.append(rec)
                steps_done += 1
                if b._finish_iteration(should):
                    is_finished = True
                    break
        finally:
            self._note_launch(
                ses, flight, wd, it0, steps_done, wall_ms, phases,
                _compile_count() - compiles_before, records, is_finished,
                span=lsp,
            )
        return steps_done, is_finished

    def _note_launch(
        self, ses, flight, wd, it0, steps_done, wall_ms, phases,
        compiles_delta, records, is_finished, span=None,
    ) -> None:
        """One batched observability event per launch: the flight ring and
        watchdog see a single record carrying the N per-iteration
        sub-records (device-side counters — grow_steps, refine_count,
        splits — rode the packed carry out).  ``wall_ms`` is normalized
        per iteration so the watchdog's throughput EMA stays comparable
        with serial runs."""
        b = self._b
        steps = max(1, steps_done)
        event = {
            "event": "launch",
            "iter": it0 + steps - 1,
            "launch_begin": it0,
            "steps": steps_done,
            "steps_per_launch": self._n,
            "wall_ms": wall_ms / steps,
            "launch_wall_ms": wall_ms,
            "compiles_delta": compiles_delta,
            "trees_materialized": sum(
                r["trees_materialized"] for r in records
            ),
            "splits": sum(r["splits"] for r in records),
            "records": records,
            "finished": bool(is_finished),
        }
        if phases:
            event["phases"] = {k2: v * 1e3 for k2, v in phases.items()}
        if (
            b._mesh is not None
            and b.config.tree_learner != "voting"
            and ses.enabled
        ):
            from ..parallel.mesh import (
                MeshSpec,
                mesh_psum_bytes_per_iteration,
            )

            spec = getattr(b, "_mesh_spec", None) or MeshSpec(
                "data", data=int(b._mesh.devices.size)
            )
            coll = mesh_psum_bytes_per_iteration(
                max(1, b.config.num_leaves - 1),
                int(b._bins.shape[1]),
                int(b._grower_params.max_bin),
                leaf_batch=int(b.config.leaf_batch),
                spec=spec,
                launch_steps=steps,
            )
            coll = {k2: v * self._k for k2, v in coll.items()}
            event["collective"] = coll
            ses.set_gauge("collective_hist_bytes", coll["hist_bytes"])
            ses.set_gauge("collective_count_bytes", coll["count_bytes"])
            ses.set_gauge(
                "collective_ring_bytes_per_device",
                coll["ring_bytes_per_device"],
            )
        tracer = get_tracer()
        if span is not None:
            # synthetic per-iteration children: the device ran the S
            # iterations inside ONE scan, so the host reconstructs S
            # equal-width child spans under the launch span.  Boundaries
            # are estimated (device-uniform division of the launch wall);
            # the per-iteration counters (splits, grow_steps, refine_count)
            # are exact device values that rode the packed scan carry out.
            slice_us = (wall_ms * 1000.0) / steps
            for s, rec in enumerate(records):
                tracer.add_span(
                    "train/iteration",
                    "train",
                    int(span.t0_us + s * slice_us),
                    max(1, int(slice_us)),
                    trace_id=span.trace_id,
                    parent_id=span.span_id,
                    args={
                        "iter": rec["iter"],
                        "trees_materialized": rec["trees_materialized"],
                        "splits": rec["splits"],
                        "grow_steps": rec["grow_steps"],
                        "refine_count": rec["refine_count"],
                        "from_launch": True,
                    },
                    synthetic=True,
                    tid=span.tid,
                )
            tracer.end(
                span,
                extra={
                    "steps": steps_done,
                    "launch_wall_ms": wall_ms,
                    "compiles_delta": compiles_delta,
                    "finished": bool(is_finished),
                },
            )
        if ses.enabled:
            ses.inc("iterations", steps_done)
            ses.inc("launch/launches")
            ses.set_gauge("train/steps_per_launch_effective", float(steps_done))
            sample_device_memory("iteration")
            # per-iteration JSONL shape compatibility: one replayed
            # iteration event per consumed step, flagged from_launch so
            # offline tools (telemetry_summary.py) keep their
            # event=="iteration" filter across serial and launched runs.
            # Recorded BEFORE the deferred launch event so late eval
            # annotations still land on the launch JSONL line.
            for rec in records:
                ses.record({
                    "event": "iteration",
                    "iter": rec["iter"],
                    "wall_ms": wall_ms / steps,
                    "trees_materialized": rec["trees_materialized"],
                    "splits": rec["splits"],
                    "from_launch": True,
                })
            ses.record(event, defer=True)
        if flight.active:
            flight.note_event(event)
        if wd is not None:
            wd.observe(event, ses)


# ------------------------------------------------------------ fleet runner


class FleetLaunchRunner:
    """Scan-over-vmap: N lockstep fleet iterations per compiled launch.

    The carry holds every member's score cache, RNG key, bagging mask and
    finished/bad latches as parallel tuples; each scan step replays the
    fleet round exactly — per-member gradients/sampling in member order,
    then ONE vmapped grow per tree class with halted members select-fed
    the same zero-lane operands the serial fleet gives inactive members.
    Members that finish mid-window freeze bit-exactly (their carry slots
    are select-protected) and keep riding as no-op lanes, so the
    executable shape never changes as the fleet drains.
    """

    def __init__(self, trainer, n: int):
        self._t = trainer
        self._n = int(n)
        b0 = trainer.boosters[0]
        self._k = b0.num_tree_per_iteration
        self._trains = [
            bool(b0._class_need_train[kk] and b0._bins.shape[1] > 0)
            for kk in range(self._k)
        ]
        self._L = int(b0._grower_params.num_leaves)
        self._nn = self._L - 1
        self._fn = instrumented_jit(
            self._launch_impl,
            label=f"fleet/scan{self._n}",
            donate_argnums=(0,),
        )

    def _launch_impl(self, scores, rngs, bags, halted0, its, fms, bins):
        t = self._t
        boosters = t.boosters
        m = len(boosters)
        k = self._k

        def member_inputs(i, score_i, rng_i, bag_i, it, fm_i):
            """Gradients + sampling for member i — the scan-form mirror of
            ``_fleet_begin_iter`` (same key order, same fold_in gating)."""
            b = boosters[i]
            cfg = b.config
            pair = jax.random.split(rng_i)
            rng_g, gkey = pair[0], pair[1]
            grad, hess = b.objective.get_gradients(score_i, gkey)
            if cfg.check_numerics:
                ok = jnp.logical_and(
                    jnp.isfinite(grad).all(), jnp.isfinite(hess).all()
                )
            else:
                ok = jnp.asarray(True)
            any_pad = bool(b._pad_rows)
            fixed = getattr(b, "_fixed_row_mask", None)
            if any_pad or fixed is not None:
                live = b._ones_mask[None] > 0
                if fixed is not None:
                    live = jnp.logical_and(live, fixed[None] > 0)
                grad = jnp.where(live, grad, 0.0)
                hess = jnp.where(live, hess, 0.0)
            pair = jax.random.split(rng_g)
            rng_b, bkey = pair[0], pair[1]
            if "bagging_seed" in cfg.raw:
                bkey = jax.random.fold_in(bkey, cfg.bagging_seed)
            mask, grad, hess, bag_new = b._sampler.scan_sample(
                it, grad, hess, bkey, bag_i
            )
            if any_pad:
                mask = mask * b._ones_mask
            if fixed is not None:
                mask = mask * fixed
            rng_cur = rng_b
            tkeys = []
            need_tkey = bool(
                cfg.feature_fraction_bynode < 1.0 or cfg.extra_trees
            )
            for kk in range(k):
                if not self._trains[kk] or not need_tkey:
                    tkeys.append(None)
                    continue
                pair = jax.random.split(rng_cur)
                rng_cur, tkey = pair[0], pair[1]
                if cfg.extra_trees and "extra_seed" in cfg.raw:
                    tkey = jax.random.fold_in(tkey, cfg.extra_seed)
                tkeys.append(tkey)
            return grad, hess, mask, tkeys, rng_cur, bag_new, ok

        def step(carry, xs):
            scores, rngs, bags, finished, bad = carry
            it = xs["it"]
            fms_step = xs["fm"]  # [M, F]
            halted = [
                jnp.logical_or(finished[i], bad[i] >= 0) for i in range(m)
            ]
            mem = [
                member_inputs(
                    i, scores[i], rngs[i], bags[i], it, fms_step[i]
                )
                for i in range(m)
            ]
            live = [
                jnp.logical_and(jnp.logical_not(halted[i]), mem[i][6])
                for i in range(m)
            ]
            zero_row = jnp.zeros_like(mem[0][0][0])
            ones_fm = jnp.ones_like(fms_step[0])
            new_scores = list(scores)
            any_split = [jnp.asarray(False) for _ in range(m)]
            ints_cls: List[Any] = []
            floats_cls: List[Any] = []
            for kk in range(k):
                if not self._trains[kk]:
                    continue
                grad_rows, hess_rows, mask_rows, fm_rows, keys = (
                    [], [], [], [], [],
                )
                for i in range(m):
                    grad, hess, mask, tkeys, _, _, _ = mem[i]
                    # serial fleet feeds inactive lanes value-preserving
                    # zero operands; select-feed the same here
                    grad_rows.append(
                        jnp.where(halted[i], zero_row, grad[kk])
                    )
                    hess_rows.append(
                        jnp.where(halted[i], zero_row, hess[kk])
                    )
                    mask_rows.append(jnp.where(halted[i], zero_row, mask))
                    fm_rows.append(
                        jnp.where(halted[i], ones_fm, fms_step[i])
                    )
                    key_i = (
                        tkeys[kk] if tkeys[kk] is not None else t._zero_key
                    )
                    keys.append(jnp.where(halted[i], t._zero_key, key_i))
                b0 = boosters[0]
                fta, fleaf = t._grow(
                    bins,
                    jnp.stack(grad_rows),
                    jnp.stack(hess_rows),
                    jnp.stack(mask_rows),
                    b0._num_bins,
                    b0._nan_bins,
                    jnp.stack(fm_rows),
                    t._mono_arg,
                    t._inter_arg,
                    jnp.stack(keys),
                    t._iscat_arg,
                    None,
                    t._cegb_p_arg,
                    t._cegb_u_arg,
                    t._qs_arg,
                    t._bundle_end_arg,
                    t._contri_arg,
                )
                ii, ff = jax.vmap(_pack_tree_arrays_impl)(fta)
                ints_cls.append(ii)
                floats_cls.append(ff)
                for i in range(m):
                    num_leaves_i = fta.num_leaves[i]
                    has_split = num_leaves_i > 1
                    upd = jnp.logical_and(live[i], has_split)
                    shrunk = fta.leaf_value[i] * float(
                        boosters[i]._shrinkage_rate
                    )
                    cand = new_scores[i].at[kk].add(shrunk[fleaf[i]])
                    new_scores[i] = jnp.where(upd, cand, new_scores[i])
                    any_split[i] = jnp.logical_or(any_split[i], has_split)
            finished2 = [
                jnp.logical_or(
                    finished[i],
                    jnp.logical_and(
                        live[i], jnp.logical_not(any_split[i])
                    ),
                )
                for i in range(m)
            ]
            bad2 = [
                jnp.where(
                    jnp.logical_and(
                        bad[i] < 0,
                        jnp.logical_and(
                            jnp.logical_not(halted[i]),
                            jnp.logical_not(mem[i][6]),
                        ),
                    ),
                    it,
                    bad[i],
                )
                for i in range(m)
            ]
            rngs2 = [
                jnp.where(halted[i], rngs[i], mem[i][4]) for i in range(m)
            ]
            bags2 = [
                jnp.where(halted[i], bags[i], mem[i][5]) for i in range(m)
            ]
            carry2 = (
                tuple(new_scores),
                tuple(rngs2),
                tuple(bags2),
                tuple(finished2),
                tuple(bad2),
            )
            # ys: [n_trained_classes, M, ...] per step
            return carry2, {
                "ints": jnp.stack(ints_cls),
                "floats": jnp.stack(floats_cls),
            }

        carry0 = (
            scores,
            rngs,
            bags,
            tuple(halted0),
            tuple(jnp.full((), -1, jnp.int32) for _ in range(m)),
        )
        return jax.lax.scan(step, carry0, {"it": its, "fm": fms})

    def run(self) -> int:
        """One fleet launch; returns the number of lockstep rounds
        consumed (the engine advances its round counter by this)."""
        t = self._t
        boosters = t.boosters
        m = len(boosters)
        k = self._k
        from .sampling import BaggingStrategy

        active = t.active_members()
        if not active:
            return 0
        # first-round constant-tree hazard scan BEFORE any score mutation:
        # if ANY active member needs the serial fallback (boost_from_average
        # off, no models, no init score), take it for the WHOLE fleet now.
        # Falling back after boosting earlier members would re-apply
        # boost_from_average inside _fleet_begin_iter (their models_ is
        # still empty), silently double-boosting train and valid scores.
        for i in active:
            b = boosters[i]
            if (
                not b.models_
                and b.objective is not None
                and not b.config.boost_from_average
                and not b._has_init_score
            ):
                t.update()
                return 1
        # first-round prologue per member (see LaunchRunner.run)
        init_scores_by_member = {}
        for i in active:
            b = boosters[i]
            cfg = b.config
            isc = [0.0] * k
            if (
                not b.models_
                and not b._has_init_score
                and b.objective is not None
                and cfg.boost_from_average
            ):
                for kk in range(k):
                    s = b.objective.boost_from_score(kk)
                    if abs(s) > _EPS:
                        isc[kk] = s
                        b._score = b._score.at[kk].add(s)
                        for entry in b._valid:
                            entry.score = entry.score.at[kk].add(s)
            init_scores_by_member[i] = isc

        ses = get_session()
        flight = get_flight()
        it0 = int(boosters[0]._iter)
        S = self._n
        its = jnp.asarray(np.arange(it0, it0 + S, dtype=np.int32))
        f_used = int(boosters[0]._bins.shape[1])
        fm_cube = np.zeros((S, m, f_used), dtype=bool)
        for i in range(m):
            b = boosters[i]
            for s in range(S):
                fm_cube[s, i] = b._feature_mask_np_for(it0 + s)
        fms = jnp.asarray(fm_cube)
        active_set = set(active)
        # traced [M] entries, NOT trace-time constants: externally-stopped
        # members enter as halted input VALUES so draining the fleet never
        # changes the executable shape (zero retraces as members stop)
        halted0 = tuple(
            jnp.asarray(i not in active_set) for i in range(m)
        )
        bags0 = tuple(
            b._sampler._mask
            if isinstance(b._sampler, BaggingStrategy)
            else jnp.zeros((1,), jnp.float32)
            for b in boosters
        )

        t0 = time.perf_counter()
        carry, ys = self._fn(
            tuple(b._score for b in boosters),
            tuple(b._rng for b in boosters),
            bags0,
            halted0,
            its,
            fms,
            boosters[0]._bins,
        )
        scores, rngs, bags, finished_dev, bad_dev = carry
        for i, b in enumerate(boosters):
            b._score = scores[i]
            if i in init_scores_by_member:  # active: carry advanced them
                b._rng = rngs[i]
                if isinstance(b._sampler, BaggingStrategy):
                    b._sampler._mask = bags[i]
        ints = np.asarray(ys["ints"])  # [S, n_trained, M, ints_len]
        floats = np.asarray(ys["floats"])
        bad = [int(x) for x in bad_dev]
        wall_ms = (time.perf_counter() - t0) * 1e3

        trained_idx = [kk for kk in range(k) if self._trains[kk]]
        steps_done = 0
        for s in range(S):
            it = it0 + s
            live_members = [
                i
                for i in active
                if not boosters[i]._finished
            ]
            if not live_members:
                break
            steps_done += 1
            for i in live_members:
                b = boosters[i]
                if bad[i] >= 0 and it == bad[i]:
                    b._fault_dump("numerics_gradients")
                    raise NumericsError(
                        f"non-finite gradients/hessians at iteration {it} "
                        f"for fleet member {i} inside launch window "
                        f"[{it0}, {it0 + S}) (train_steps_per_launch={S}, "
                        f"objective={b._objective_name()})"
                    )
                isc = (
                    init_scores_by_member[i] if s == 0 else [0.0] * k
                )
                should = False
                for kk in range(k):
                    grown = None
                    if self._trains[kk]:
                        ci = trained_idx.index(kk)
                        ta_host = unpack_tree_arrays(
                            ints[s, ci, i], floats[s, ci, i],
                            self._nn, self._L,
                        )
                        if b.config.check_numerics:
                            b._guard_tree(ta_host, it)
                        b._note_refine_rate(ta_host)
                        if int(ta_host.num_leaves) > 1:
                            ta_dev = jax.tree_util.tree_map(
                                jnp.asarray, ta_host
                            )
                            grown = (ta_dev, ta_host, None)
                    if b._commit_class_tree(
                        kk, grown, None, None, None, isc,
                        skip_train_score=True,
                    ):
                        should = True
                b._fleet_end_iter(should)
        t._round += steps_done
        if ses.enabled:
            ses.inc("fleet/iterations", steps_done)
            ses.set_gauge("fleet/size", m)
            ses.set_gauge("fleet/active", len(t.active_members()))
            ses.set_gauge(
                "train/steps_per_launch_effective", float(max(1, steps_done))
            )
        if flight.active:
            flight.note_event(
                {
                    "event": "fleet_launch",
                    "round": t._round,
                    "launch_begin": it0,
                    "steps": steps_done,
                    "steps_per_launch": S,
                    "fleet": m,
                    "wall_ms": wall_ms,
                    "active": len(t.active_members()),
                }
            )
        return steps_done


__all__ = [
    "LaunchRunner",
    "FleetLaunchRunner",
    "clamp_steps",
    "launch_ineligible_reason",
    "resolve_fleet_launch_steps",
    "resolve_launch_steps",
    "resolve_requested_steps",
]
