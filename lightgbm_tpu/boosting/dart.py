"""DART boosting (reference: src/boosting/dart.hpp — DroppingTrees :97,
Normalize :145).

The reference performs a 3-step shrink/add dance per dropped tree so each
score updater sees the right delta; algebraically the net effect is: rescale
each dropped tree's output v to v' = v * k/(k+1) (xgboost mode: k/(k+lr)) and
add (v' - v) to BOTH train and valid scores — which is how it is written here
(one bin-space walk per dropped tree per score).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax.numpy as jnp

from ..dataset import Dataset
from ..predict import add_tree_to_score
from .gbdt import Booster, _EPS


class DARTBooster(Booster):
    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._tree_weight = []  # per-iteration weight (uniform_drop off)
        self._sum_weight = 0.0
        self._drop_rng = np.random.default_rng(self.config.drop_seed)

    def _walk_add(self, rec, leaf_delta: np.ndarray, kk: int, include_valid: bool) -> None:
        """Add a tree's (delta) outputs to train (and optionally valid) scores."""
        delta = jnp.asarray(leaf_delta, dtype=jnp.float32)
        if len(rec["split_feature"]) == 0:
            self._score = self._score.at[kk].add(float(leaf_delta[0]))
            if include_valid:
                for entry in self._valid:
                    entry.score = entry.score.at[kk].add(float(leaf_delta[0]))
            return
        args = (
            jnp.asarray(rec["split_feature"]),
            jnp.asarray(rec["split_bin"]),
            jnp.asarray(rec["default_left"]),
            jnp.asarray(rec["left_child"]),
            jnp.asarray(rec["right_child"]),
            delta,
        )
        self._score = self._score.at[kk].set(
            add_tree_to_score(self._score[kk], self._bins, self._nan_bins, *args)
        )
        if include_valid:
            for entry in self._valid:
                entry.score = entry.score.at[kk].set(
                    add_tree_to_score(
                        entry.score[kk],
                        entry.dataset.device_bins(),
                        self._nan_bins,
                        *args,
                    )
                )

    def _select_drops(self):
        cfg = self.config
        drop_index = []
        if self._drop_rng.random() >= cfg.skip_drop:
            drop_rate = cfg.drop_rate
            if not cfg.uniform_drop:
                if self._sum_weight > 0:
                    inv_avg = len(self._tree_weight) / self._sum_weight
                    if cfg.max_drop > 0:
                        drop_rate = min(
                            drop_rate, cfg.max_drop * inv_avg / self._sum_weight
                        )
                    for i in range(self._iter):
                        if self._drop_rng.random() < drop_rate * self._tree_weight[i] * inv_avg:
                            drop_index.append(i)
                            if len(drop_index) >= cfg.max_drop > 0:
                                break
            else:
                if cfg.max_drop > 0 and self._iter > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / self._iter)
                for i in range(self._iter):
                    if self._drop_rng.random() < drop_rate:
                        drop_index.append(i)
                        if len(drop_index) >= cfg.max_drop > 0:
                            break
        return drop_index

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        cfg = self.config
        k = self.num_tree_per_iteration

        drop_index = self._select_drops()
        kdrop = len(drop_index)
        # remove dropped trees from the TRAIN score so gradients see the
        # reduced ensemble (reference DroppingTrees :97)
        for i in drop_index:
            for kk in range(k):
                idx = i * k + kk
                self._walk_add(
                    self._bin_records[idx],
                    -np.asarray(self.models_[idx].leaf_value, dtype=np.float32),
                    kk,
                    include_valid=False,
                )
        if not cfg.xgboost_dart_mode:
            self._shrinkage_rate = cfg.learning_rate / (1.0 + kdrop)
        else:
            self._shrinkage_rate = (
                cfg.learning_rate
                if kdrop == 0
                else cfg.learning_rate / (cfg.learning_rate + kdrop)
            )

        finished = super().update(train_set, fobj)
        if finished:
            # restore dropped trees' contributions
            for i in drop_index:
                for kk in range(k):
                    idx = i * k + kk
                    self._walk_add(
                        self._bin_records[idx],
                        np.asarray(self.models_[idx].leaf_value, dtype=np.float32),
                        kk,
                        include_valid=False,
                    )
            return True

        # Normalize (reference :145): v -> v * factor on dropped trees;
        # train gets v*factor added back (it has 0 now), valid gets v*(factor-1)
        if kdrop > 0:
            factor = (
                kdrop / (kdrop + 1.0)
                if not cfg.xgboost_dart_mode
                else kdrop / (kdrop + cfg.learning_rate)
            )
            for i in drop_index:
                for kk in range(k):
                    idx = i * k + kk
                    v = np.asarray(self.models_[idx].leaf_value, dtype=np.float64)
                    self.models_[idx].apply_shrinkage(factor)
                    self._bin_records[idx]["leaf_value"] = np.asarray(
                        self.models_[idx].leaf_value, dtype=np.float32
                    )
                    self._bump_model_version()
                    self._walk_add(
                        self._bin_records[idx], (v * factor).astype(np.float32), kk, False
                    )
                    # valid: subtract the lost fraction
                    delta_valid = (v * (factor - 1.0)).astype(np.float32)
                    dv = jnp.asarray(delta_valid)
                    rec = self._bin_records[idx]
                    for entry in self._valid:
                        if len(rec["split_feature"]) == 0:
                            entry.score = entry.score.at[kk].add(float(delta_valid[0]))
                        else:
                            entry.score = entry.score.at[kk].set(
                                add_tree_to_score(
                                    entry.score[kk],
                                    entry.dataset.device_bins(),
                                    self._nan_bins,
                                    jnp.asarray(rec["split_feature"]),
                                    jnp.asarray(rec["split_bin"]),
                                    jnp.asarray(rec["default_left"]),
                                    jnp.asarray(rec["left_child"]),
                                    jnp.asarray(rec["right_child"]),
                                    dv,
                                )
                            )
                if not cfg.uniform_drop:
                    self._sum_weight -= self._tree_weight[i] * (1.0 - factor)
                    self._tree_weight[i] *= factor
        if not cfg.uniform_drop:
            self._tree_weight.append(self._shrinkage_rate)
            self._sum_weight += self._shrinkage_rate
        return False
