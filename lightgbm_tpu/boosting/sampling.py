"""Row-sampling strategies: bagging and GOSS.

Reference analogs: ``SampleStrategy`` (include/LightGBM/sample_strategy.h),
``BaggingSampleStrategy`` (src/boosting/bagging.hpp — per-row Bernoulli
``NextFloat() < bagging_fraction`` :239, balanced pos/neg variant :248) and
``GOSSStrategy`` (src/boosting/goss.hpp:30 — keep top ``top_rate`` rows by
sum_k |g_k*h_k|, sample ``other_rate`` of the rest, reweight by
(cnt-top_k)/other_k; no sampling for the first 1/learning_rate iterations).

TPU-native formulation: the reference's bag_data_indices index arrays become a
dense ``[N]`` f32 mask (1 = in bag) consumed by the masked histogram kernel —
shapes stay static, no gather/compaction.  GOSS's ArgMaxAtK partial sort
becomes a ``top_k``-style threshold via ``jnp.sort``.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Config


class SampleStrategy:
    """Base: no sampling."""

    is_hessian_change = False

    def __init__(self, config: Config, num_data: int):
        self.config = config
        self.num_data = num_data
        self._ones = jnp.ones((num_data,), jnp.float32)
        self._live_count: int | None = None

    def set_live_count(self, n: int | None) -> None:
        """Row count the strategy should size itself against when a fixed
        row mask (Booster.set_row_mask — CV folds, holdouts) restricts
        training to a subset: GOSS derives top_k/other_k and its
        reweighting factor from the LIVE rows, not the full matrix.  None
        restores full-data sizing; bagging is per-row Bernoulli and needs
        no adjustment (the fixed mask intersects it downstream)."""
        self._live_count = int(n) if n is not None else None

    def sample(
        self, iteration: int, grad: jnp.ndarray, hess: jnp.ndarray, rng: jax.Array
    ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        return self._ones, grad, hess

    # -- device-resident boosting (boosting/launch.py): a trace-safe step
    # form of sample().  ``iteration`` is a traced i32 scalar inside the
    # lax.scan body, so the host-side refresh/warmup branches become
    # whole-array jnp.where selects (byte-equivalent: the rng key is drawn
    # every iteration in the serial loop too, and a select of untouched
    # inputs preserves their exact bit patterns — including -0.0).
    # ``carried_mask`` threads the bagging mask through the scan carry;
    # strategies without persistent state pass it through unchanged.

    def scan_sample(self, iteration, grad, hess, rng, carried_mask):
        ones = jnp.ones((self.num_data,), jnp.float32)
        return ones, grad, hess, carried_mask


class BaggingStrategy(SampleStrategy):
    """Per-row Bernoulli bagging, refreshed every ``bagging_freq`` iterations.

    ``query_sizes`` switches to per-QUERY bagging (reference
    ``bagging_by_query``, src/boosting/bagging.hpp:52): whole queries are
    kept or dropped as units so lambdarank's within-query pairs never see a
    partially-sampled query.  The reference rebuilds ``bag_data_indices``
    query by query; the TPU formulation draws one Bernoulli per query and
    expands it to rows with a static-shape ``jnp.repeat`` (query sizes are
    host constants — no gather)."""

    def __init__(self, config: Config, num_data: int, is_pos=None,
                 query_sizes=None, pad_query_mask=None):
        super().__init__(config, num_data)
        self._mask = self._ones
        self._last_refresh = -1
        self._is_pos = is_pos  # device bool [N] for balanced bagging, or None
        self._qsizes = None
        if query_sizes is not None:
            qs = np.asarray(query_sizes, np.int64)
            padq = (
                np.zeros(len(qs), bool)
                if pad_query_mask is None
                else np.asarray(pad_query_mask, bool)
            )
            pad = num_data - int(qs.sum())
            if pad < 0:
                raise ValueError(
                    f"query sizes sum {qs.sum()} > num_data {num_data}"
                )
            if pad:
                # trailing padding rows form a pseudo-query, never in bag
                # (multi-process feeding interleaves per-block pad entries
                # via pad_query_mask instead)
                qs = np.append(qs, pad)
                padq = np.append(padq, True)
            self._qsizes = qs
            self._qpad_dev = jnp.asarray(~padq, jnp.float32)

    def sample(self, iteration, grad, hess, rng):
        freq = max(1, self.config.bagging_freq)
        if iteration % freq == 0:
            self._mask = self._fresh_mask(rng)
        return self._mask, grad, hess

    def _fresh_mask(self, rng):
        cfg = self.config
        if self._qsizes is not None:
            nq = len(self._qsizes)
            qmask = jax.random.bernoulli(
                rng, cfg.bagging_fraction, (nq,)
            ).astype(jnp.float32)
            qmask = qmask * self._qpad_dev
            return jnp.repeat(
                qmask, self._qsizes, total_repeat_length=self.num_data
            )
        if self._is_pos is not None:
            p = jnp.where(
                self._is_pos, cfg.pos_bagging_fraction, cfg.neg_bagging_fraction
            )
            return (jax.random.uniform(rng, (self.num_data,)) < p).astype(
                jnp.float32
            )
        return jax.random.bernoulli(
            rng, cfg.bagging_fraction, (self.num_data,)
        ).astype(jnp.float32)

    def scan_sample(self, iteration, grad, hess, rng, carried_mask):
        freq = max(1, self.config.bagging_freq)
        fresh = self._fresh_mask(rng)
        mask = jnp.where(iteration % freq == 0, fresh, carried_mask)
        return mask, grad, hess, mask


class GOSSStrategy(SampleStrategy):
    """Gradient-based One-Side Sampling (src/boosting/goss.hpp)."""

    is_hessian_change = True

    def __init__(self, config: Config, num_data: int):
        super().__init__(config, num_data)
        if config.top_rate + config.other_rate > 1.0:
            raise ValueError("top_rate + other_rate must be <= 1.0")
        if config.top_rate <= 0 or config.other_rate <= 0:
            raise ValueError("top_rate and other_rate must be > 0 for GOSS")
        self._warmup = int(1.0 / max(config.learning_rate, 1e-12))

    def sample(self, iteration, grad, hess, rng):
        if iteration < self._warmup:
            return self._ones, grad, hess
        cfg = self.config
        # with a fixed row mask the excluded rows reach us as exact zeros
        # (|g*h| = 0, never in the top set); sizing against the live count
        # keeps the effective top/other rates right for the subset
        n = self._live_count if self._live_count is not None else self.num_data
        metric = jnp.abs(grad * hess).sum(axis=0)  # sum over classes [N]
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        threshold = jnp.sort(metric)[self.num_data - top_k]
        is_top = metric >= threshold
        rest_prob = other_k / max(1, n - top_k)
        sampled = jax.random.uniform(rng, (n,)) < rest_prob
        in_bag = is_top | (~is_top & sampled)
        multiply = (n - top_k) / other_k
        factor = jnp.where(is_top, 1.0, multiply)[None, :]
        mask = in_bag.astype(jnp.float32)
        return mask, grad * factor * mask[None, :], hess * factor * mask[None, :]

    def scan_sample(self, iteration, grad, hess, rng, carried_mask):
        mask, g, h = self.sample(self._warmup, grad, hess, rng)
        warm = iteration < self._warmup
        ones = jnp.ones((self.num_data,), jnp.float32)
        return (
            jnp.where(warm, ones, mask),
            jnp.where(warm, grad, g),
            jnp.where(warm, hess, h),
            carried_mask,
        )


def bagging_is_active(config: Config) -> bool:
    """Whether any bagging mask will ever be drawn (used by the factory AND
    by the Booster to decide whether query info must be collected)."""
    need_balanced = (
        config.pos_bagging_fraction < 1.0 or config.neg_bagging_fraction < 1.0
    )
    return (
        config.bagging_freq > 0
        and (config.bagging_fraction < 1.0 or need_balanced)
    ) or config.boosting == "rf"


def create_sample_strategy(
    config: Config, num_data: int, is_pos=None, query_sizes=None,
    pad_query_mask=None,
) -> SampleStrategy:
    """Factory (reference: SampleStrategy::CreateSampleStrategy,
    src/boosting/sample_strategy.cpp)."""
    is_goss = (
        config.boosting == "goss"
        or (config.raw or {}).get("data_sample_strategy") == "goss"
    )
    need_balanced = (
        config.pos_bagging_fraction < 1.0 or config.neg_bagging_fraction < 1.0
    )
    bagging_active = bagging_is_active(config)
    qs = query_sizes if config.bagging_by_query else None
    if config.bagging_by_query and bagging_active:
        # by-query sampling can't be combined with row-level strategies:
        # both would partially sample queries, the exact thing it forbids
        if is_goss:
            raise ValueError(
                "bagging_by_query cannot be combined with GOSS (GOSS "
                "samples individual rows, splitting queries)"
            )
        if need_balanced:
            raise ValueError(
                "bagging_by_query cannot be combined with pos/neg "
                "balanced bagging (balanced bagging samples individual "
                "rows, splitting queries)"
            )
        if query_sizes is None:
            raise ValueError(
                "bagging_by_query=True needs query information (set "
                "`group` on the train Dataset)"
            )
    if is_goss:
        return GOSSStrategy(config, num_data)
    pq = pad_query_mask if config.bagging_by_query else None
    if config.bagging_freq > 0 and (config.bagging_fraction < 1.0 or need_balanced):
        return BaggingStrategy(
            config, num_data, is_pos if need_balanced else None,
            query_sizes=qs, pad_query_mask=pq,
        )
    if config.boosting == "rf":
        # RF requires bagging (reference rf.hpp:25 CHECK)
        return BaggingStrategy(config, num_data, query_sizes=qs,
                               pad_query_mask=pq)
    return SampleStrategy(config, num_data)
