"""Random-forest mode (reference: src/boosting/rf.hpp).

Semantics kept from the reference: no shrinkage; gradients computed ONCE from
the constant per-class init score (not the evolving ensemble); bagging (row or
feature) is mandatory; the running score is the AVERAGE of tree outputs
(``MultiplyScore`` dance, rf.hpp:111-160); every tree absorbs the init score
via AddBias so the saved model divides cleanly by tree count
(``average_output`` flag in the model header).
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax.numpy as jnp

from ..dataset import Dataset
from ..ops.grower import fetch_tree_arrays, grow_tree
from ..predict import add_tree_to_score
from ..tree import Tree
from .gbdt import Booster, _EPS


class RFBooster(Booster):
    def _init_train(self, train_set: Dataset) -> None:
        super()._init_train(train_set)
        cfg = self.config
        ok_bag = cfg.bagging_freq > 0 and 0.0 < cfg.bagging_fraction < 1.0
        ok_feat = 0.0 < cfg.feature_fraction < 1.0
        if not (ok_bag or ok_feat):
            raise ValueError(
                "random forest requires bagging (bagging_freq > 0 and "
                "bagging_fraction < 1.0) or feature_fraction < 1.0"
            )
        self.average_output = True
        self._shrinkage_rate = 1.0
        # constant init scores and one-time gradients (rf.hpp Boosting())
        k = self.num_tree_per_iteration
        n = train_set.num_data
        self._init_scores = [
            self.objective.boost_from_score(kk) if self.objective else 0.0
            for kk in range(k)
        ]
        base = jnp.asarray(
            np.tile(np.asarray(self._init_scores, dtype=np.float32)[:, None], (1, n))
        )
        self._rf_grad, self._rf_hess = self.objective.get_gradients(base, self._next_rng())

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        if fobj is not None:
            raise ValueError("RF mode does not support custom objective functions")
        cfg = self.config
        k = self.num_tree_per_iteration
        mask, grad, hess = self._sampler.sample(
            self._iter, self._rf_grad, self._rf_hess, self._bagging_rng()
        )
        feature_mask = self._feature_mask_for_iter()

        any_tree = False
        for kk in range(k):
            if self._class_need_train[kk] and self._bins.shape[1] > 0:
                ta, leaf_id = grow_tree(
                    self._bins,
                    grad[kk],
                    hess[kk],
                    mask,
                    self._num_bins,
                    self._nan_bins,
                    feature_mask,
                    self._grower_params,
                )
                ta_host = fetch_tree_arrays(ta)
                n_leaves = int(ta_host.num_leaves)
            else:
                n_leaves = 1

            if n_leaves > 1:
                any_tree = True
                leaf_value = ta.leaf_value
                if self.objective is not None and self.objective.is_renew_tree_output:
                    init = self._init_scores[kk]
                    lv = self.objective.renew_tree_output(
                        np.full(self.train_set.num_data, init),
                        np.asarray(leaf_id),
                        np.asarray(ta_host.leaf_value, dtype=np.float64),
                        np.asarray(mask),
                    )
                    leaf_value = jnp.asarray(lv, dtype=jnp.float32)
                    ta = ta._replace(leaf_value=leaf_value)
                    ta_host = ta_host._replace(leaf_value=lv)
                if abs(self._init_scores[kk]) > _EPS:
                    leaf_value = leaf_value + self._init_scores[kk]
                    ta = ta._replace(leaf_value=leaf_value)
                    ta_host = ta_host._replace(
                        leaf_value=np.asarray(ta_host.leaf_value, dtype=np.float64)
                        + self._init_scores[kk]
                    )
                # running average: score = (score*t + tree)/(t+1)  (rf.hpp:149)
                t = float(self._iter)
                self._score = self._score.at[kk].set(
                    (self._score[kk] * t + leaf_value[leaf_id]) / (t + 1.0)
                )
                for entry in self._valid:
                    updated = add_tree_to_score(
                        entry.score[kk] * t,
                        entry.dataset.device_bins(),
                        self._nan_bins,
                        ta.split_feature,
                        ta.split_bin,
                        ta.default_left,
                        ta.left_child,
                        ta.right_child,
                        leaf_value,
                    )
                    entry.score = entry.score.at[kk].set(updated / (t + 1.0))
                tree = Tree.from_device_arrays(
                    ta_host,
                    self.train_set.bin_mappers,
                    self.train_set.used_features,
                )
                nn = n_leaves - 1
                self._bin_records.append(
                    {
                        "split_feature": np.asarray(ta_host.split_feature)[:nn],
                        "split_bin": np.asarray(ta_host.split_bin)[:nn],
                        "default_left": np.asarray(ta_host.default_left)[:nn],
                        "left_child": np.asarray(ta_host.left_child)[:nn],
                        "right_child": np.asarray(ta_host.right_child)[:nn],
                        "leaf_value": np.asarray(tree.leaf_value, dtype=np.float32),
                    }
                )
                self.models_.append(tree)
                self._bump_model_version()
            else:
                output = 0.0
                if len(self.models_) < k and not self._class_need_train[kk]:
                    output = (
                        self.objective.boost_from_score(kk) if self.objective else 0.0
                    )
                tree = Tree.constant_tree(output)
                self._bin_records.append(
                    {
                        "split_feature": np.zeros(0, np.int32),
                        "split_bin": np.zeros(0, np.int32),
                        "default_left": np.zeros(0, bool),
                        "left_child": np.zeros(0, np.int32),
                        "right_child": np.zeros(0, np.int32),
                        "leaf_value": np.asarray(tree.leaf_value, dtype=np.float32),
                    }
                )
                self.models_.append(tree)
                self._bump_model_version()
        self._iter += 1
        return not any_tree
