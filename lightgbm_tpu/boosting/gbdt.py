"""GBDT boosting loop and the user-facing Booster.

Reference analogs: ``GBDT`` (src/boosting/gbdt.cpp — Init :59, TrainOneIter
:352, BoostFromAverage :327, UpdateScore :501, EvalAndCheckEarlyStopping
:482), model text IO (src/boosting/gbdt_model_text.cpp), the C-API ``Booster``
wrapper (src/c_api.cpp:166) and the python-package ``Booster``
(python-package/lightgbm/basic.py:3541) rolled into one class — there is no
C ABI layer here; the "native" side is XLA.

Per-iteration device work (all jitted, scores stay in HBM):
  gradients (objectives/) -> per-class grow_tree (ops/grower.py) ->
  score gather-update; valid scores advance by a bin-space tree walk
  (predict.add_tree_to_score).  Host work per iteration is O(num_leaves):
  materializing the tree into the model list (exactly the CUDA learner's
  host/device split, SURVEY §3.5).
"""

from __future__ import annotations

import functools
import math
import time
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

from ..config import Config
from ..dataset import Dataset
from ..metrics import Metric, create_metric
from ..obs.collectives import collectives_snapshot, measured_summary
from ..obs.device import sample_device_memory
from ..obs.flight import get_flight
from ..obs.health import HealthWatchdog
from ..obs.jit import compile_count as _obs_compile_count
from ..obs.registry import get_session
from ..obs.trace import get_tracer
from ..objectives import ObjectiveFunction, create_objective
from ..resilience import NumericsError, chaos
from ..obs.jit import instrumented_jit
from ..ops.grower import (
    GrowerParams,
    fetch_tree_arrays,
    grow_tree,
    pack_tree_arrays_donated,
    unpack_tree_arrays,
)
from ..predict import (
    BinTreeBatch,
    StreamingPredictor,
    _add_tree_to_score_impl,
    add_tree_to_score,
    stack_bin_trees,
    stack_real_trees,
)
from ..tree import Tree

_EPS = 1e-15
_MODEL_VERSION = "v4"


@functools.partial(instrumented_jit, donate_argnums=(0,))
def _apply_tree_score(
    score: jnp.ndarray,  # [K, N] f32 (donated: rebound by every caller)
    leaf_value: jnp.ndarray,  # [L] f32, ALREADY shrunk
    leaf_id: jnp.ndarray,  # [N] i32
    kk: jnp.ndarray,  # scalar i32 class row
) -> jnp.ndarray:
    """Train-score update (one gather, reference UpdateScore :501) as a
    donated entry: the old score cache goes back to the allocator instead
    of coexisting with its successor for a full [K, N] f32."""
    return score.at[kk].add(leaf_value[leaf_id])


@functools.partial(instrumented_jit, donate_argnums=(0,))
def _apply_tree_valid_score(
    score: jnp.ndarray,  # [K, N] f32 (donated)
    bins: jnp.ndarray,  # [N, F_used]
    nan_bins: jnp.ndarray,  # [F_used]
    split_feature: jnp.ndarray,  # [L-1]
    split_bin: jnp.ndarray,
    default_left: jnp.ndarray,
    left_child: jnp.ndarray,
    right_child: jnp.ndarray,
    leaf_value: jnp.ndarray,  # [L] ALREADY shrunk
    split_is_cat: jnp.ndarray,  # [L-1] bool
    cat_mask: jnp.ndarray,  # [L-1, Bm] bool
    kk: jnp.ndarray,  # scalar i32 class row
) -> jnp.ndarray:
    """Valid-score update: bin-space walk of the new tree added into row
    ``kk`` of the donated [K, N] score cache (one entry instead of a
    slice/walk/set chain, so the whole old cache is donated — not just the
    [N] row the walk reads)."""
    new_row = _add_tree_to_score_impl(
        score[kk],
        bins,
        nan_bins,
        split_feature,
        split_bin,
        default_left,
        left_child,
        right_child,
        leaf_value,
        split_is_cat,
        cat_mask,
    )
    return score.at[kk].set(new_row)


def _ceil_pow2(x: int) -> int:
    return max(1, 1 << (int(x) - 1).bit_length())


class _EvalEntry:
    """Per-dataset eval state: device bins + score, metrics."""

    def __init__(self, name: str, dataset: Dataset, metrics: List[Metric]):
        self.name = name
        self.dataset = dataset
        self.metrics = metrics
        self.score: Optional[jnp.ndarray] = None  # [K, N(+pad)]
        self.dev_bins = None  # row-sharded over the booster mesh when set
        self.pad = 0  # mesh row padding of score/dev_bins

    @property
    def bins(self) -> jnp.ndarray:
        if self.dev_bins is None:
            return self.dataset.device_bins()
        return self.dev_bins


# forest-walk predict feed size; module-level so tests can shrink it to
# exercise the multi-chunk lookahead drain without 1M+ rows
_PREDICT_CHUNK = 1 << 20
# run the forest-walk kernel in Pallas interpret mode off-TPU (tests only:
# covers the chunked feed + device-binning pipeline without hardware)
_WALK_INTERPRET = False


class Booster:
    """LightGBM-compatible Booster (train + predict + model IO)."""

    def __init__(
        self,
        params: Optional[Dict[str, Any]] = None,
        train_set: Optional[Dataset] = None,
        model_file: Optional[str] = None,
        model_str: Optional[str] = None,
    ) -> None:
        self.params: Dict[str, Any] = dict(params or {})
        self.best_iteration = -1
        self.best_score: Dict[str, Dict[str, float]] = {}
        self._pending: Optional[dict] = None  # async tree fetch in flight
        self._finished = False  # no-more-splits latch (pipelined path)
        self.models_: List[Tree] = []
        self._bin_records: List[Optional[dict]] = []  # bin-space mirror per tree
        self.train_set: Optional[Dataset] = None
        self._valid: List[_EvalEntry] = []
        self._iter = 0
        self.objective: Optional[ObjectiveFunction] = None
        self.feature_names: List[str] = []
        self.feature_infos: List[str] = []
        self.max_feature_idx = -1
        self.label_idx = 0
        self.num_class = 1
        self.num_tree_per_iteration = 1
        self.average_output = False
        self._loaded_params_str = ""
        self.config = Config.from_params(self.params)
        self.pandas_categorical = None
        self._stack_cache: Dict[Any, BinTreeBatch] = {}
        # bumped on EVERY models_/_bin_records mutation (append, pop, DART
        # renormalize, merge) so _stacked_bins never serves a stale batch
        # after rollback-then-retrain at the same tree count
        self._model_version = 0

        if model_file is not None:
            with open(model_file) as f:
                self._load_model_string(f.read())
            if self.config.pred_aot_compile:
                self.compile_predict()
            return
        if model_str is not None:
            self._load_model_string(model_str)
            if self.config.pred_aot_compile:
                self.compile_predict()
            return
        if train_set is None:
            raise ValueError("Booster needs train_set, model_file, or model_str")
        self._init_train(train_set)

    # ------------------------------------------------------------- pipelining
    # Under a remote-attached TPU every host fetch is a full tunnel round
    # trip (~100ms measured), where the reference pays nothing (in-process
    # C++).  The pipelined update path therefore copies the packed tree
    # arrays back ASYNCHRONOUSLY and materializes host Trees one iteration
    # late, overlapping the transfer with the next iteration's device
    # compute.  models_/_bin_records are properties so ANY reader first
    # drains the in-flight fetch — host state is always consistent.

    @property
    def models_(self) -> List[Tree]:
        self._drain_pending()
        return self._models_store

    @models_.setter
    def models_(self, value: List[Tree]) -> None:
        self._models_store = value

    @property
    def _bin_records(self) -> List[Optional[dict]]:
        self._drain_pending()
        return self._bin_records_store

    @_bin_records.setter
    def _bin_records(self, value: List[Optional[dict]]) -> None:
        self._bin_records_store = value

    def _drain_pending(self) -> None:
        pend = getattr(self, "_pending", None)
        if pend is None:
            return
        self._pending = None
        with get_session().phase("host_materialize"):
            self._process_pending(pend)

    def _process_pending(self, pend: dict) -> None:
        decoded = []
        should_continue = False
        for kk, ints_d, floats_d, nn, L in pend["classes"]:
            if ints_d is None:
                decoded.append((kk, None))
                continue
            ta_host = unpack_tree_arrays(
                np.asarray(ints_d), np.asarray(floats_d), nn, L
            )
            if self.config.check_numerics:
                self._guard_tree(ta_host, pend.get("iter", self._iter - 1))
            if int(ta_host.num_leaves) > 1:
                should_continue = True
                self._note_commit_rate(ta_host)
            self._note_refine_rate(ta_host)
            decoded.append((kk, ta_host))
        if not should_continue:
            # no class found a positive-gain split: the iteration left no
            # trace (leaf values were zeroed on device), undo its counter and
            # latch finished — reference returns is_finished without
            # appending (gbdt.cpp:428)
            self._iter -= 1
            self._finished = True
            return
        for kk, ta_host in decoded:
            if ta_host is not None and int(ta_host.num_leaves) > 1:
                tree = Tree.from_device_arrays(
                    ta_host,
                    self.train_set.bin_mappers,
                    self.train_set.used_features,
                    bundle_layout=self._bundle,
                )
                if self.config.verbosity >= 2:
                    tree.validate()  # debug CHECK paths (tree.py)
                tree.apply_shrinkage(pend["rate"])
                nn = int(ta_host.num_leaves) - 1
                rec = {
                    "split_feature": np.asarray(ta_host.split_feature)[:nn],
                    "split_bin": np.asarray(ta_host.split_bin)[:nn],
                    "default_left": np.asarray(ta_host.default_left)[:nn],
                    "left_child": np.asarray(ta_host.left_child)[:nn],
                    "right_child": np.asarray(ta_host.right_child)[:nn],
                    "leaf_value": np.asarray(tree.leaf_value, dtype=np.float32),
                    "split_is_cat": np.asarray(ta_host.split_is_cat)[:nn],
                    "cat_mask": np.asarray(ta_host.cat_mask)[:nn],
                }
                self._cegb_mark_used(rec["split_feature"])
            else:
                tree = Tree.constant_tree(0.0)
                rec = {
                    "split_feature": np.zeros(0, np.int32),
                    "split_bin": np.zeros(0, np.int32),
                    "default_left": np.zeros(0, bool),
                    "left_child": np.zeros(0, np.int32),
                    "right_child": np.zeros(0, np.int32),
                    "leaf_value": np.asarray(tree.leaf_value, dtype=np.float32),
                }
            self._models_store.append(tree)
            self._bin_records_store.append(rec)
            self._bump_model_version()

    def _note_commit_rate(self, ta_host) -> None:
        """Frontier-batch commit-rate gauge + adaptive leaf_batch clamp.

        commit rate = splits committed / split slots offered
        = (num_leaves - 1) / (grow_steps * K).  Round-8 measured K=8 at
        3.4% SLOWER than serial near the 255-leaf cap: late batched steps
        mostly speculate (partition + histogram work for members whose gain
        an earlier member's children beat).  When the EMA commit rate drops
        below leaf_batch_min_commit_rate the cap halves.  Sticky DOWNWARD
        only: each K owns its own compiled loop, so a cap that oscillated
        would retrace on every flip — halving costs at most log2(K) traces
        per run."""
        k = int(self._grower_params.leaf_batch)
        if k <= 1 or self._mesh is not None:
            # mesh path: grower params are baked into the shard_map closure
            # at _init_train time; fused grow doesn't engage there either
            return
        steps = int(ta_host.grow_steps)
        if steps <= 0:
            return
        rate = (int(ta_host.num_leaves) - 1) / float(steps * k)
        ema = getattr(self, "_commit_rate_ema", None)
        ema = rate if ema is None else 0.7 * ema + 0.3 * rate
        self._commit_rate_ema = ema
        ses = get_session()
        ses.set_gauge("grower.commit_rate", ema)
        ses.set_gauge("grower.leaf_batch_effective", float(k))
        cfg = self.config
        if cfg.leaf_batch_adaptive and ema < cfg.leaf_batch_min_commit_rate:
            self._leaf_batch_cap = max(1, k // 2)
            self._commit_rate_ema = None  # fresh EMA window for the new K
            self._grower_params = self._make_grower_params()
            ses.set_gauge(
                "grower.leaf_batch_effective",
                float(self._grower_params.leaf_batch),
            )
            if self.config.verbosity >= 2:
                from ..utils.log import log_info

                log_info(
                    f"leaf_batch clamp: commit rate {ema:.3f} < "
                    f"{cfg.leaf_batch_min_commit_rate} at K={k}; "
                    f"continuing with K={self._grower_params.leaf_batch}"
                )

    def _note_refine_rate(self, ta_host) -> None:
        """Histogram-engine-v2 gauges from an already-fetched tree: the
        count of committed split decisions that took the int8 near-tie f32
        refine, and its rate over the tree's decisions (root + both
        children per committed split = 2*(num_leaves-1) + 1).  The
        watchdog's refine-rate rule reads the rate gauge."""
        ses = get_session()
        if not ses.enabled or not self._int8_engaged():
            return
        refines = int(ta_host.refine_count)
        decisions = 2 * max(0, int(ta_host.num_leaves) - 1) + 1
        ses.set_gauge("hist/near_tie_refines", float(refines))
        ses.set_gauge("hist/near_tie_refine_rate", refines / decisions)
        ses.inc("hist/near_tie_refines_total", refines)

    def _int8_engaged(self) -> bool:
        """Host mirror of grow_tree's int8-accumulation engage decision
        (every input is a static — see ops.grower.int8_acc_eligible)."""
        from ..ops.grower import int8_acc_eligible

        p = getattr(self, "_grower_params", None)
        if p is None or self.train_set is None:
            return False
        return (
            p.hist_mode == "seg"
            and int(self._bins.shape[1]) > 0
            and int(self.train_set.num_data) > 1
            and int8_acc_eligible(
                p,
                quantized=self.config.use_quantized_grad,
                monotone=self._monotone is not None,
            )
        )

    def _note_live_plane(self, mask_host, f: int) -> None:
        """hist/live_plane_skip_ratio gauge: fraction of seg histogram
        plane groups skipped under this iteration's tree-level feature
        mask.  Pure host numpy (the mask is built host-side), mirroring
        grow_tree's seg_live derivation; skipped when the skip itself
        cannot engage (non-seg mode, feature-parallel shards)."""
        ses = get_session()
        if not ses.enabled:
            return
        p = getattr(self, "_grower_params", None)
        if p is None or p.hist_mode != "seg" or self._featpar:
            return
        from ..ops.grower import live_plane_fraction

        if mask_host is None:
            frac = 1.0  # full mask: every plane group stays live
        else:
            frac = live_plane_fraction(
                mask_host, f, int(p.max_bin), n_forced=int(p.n_forced)
            )
        ses.set_gauge("hist/live_plane_skip_ratio", 1.0 - frac)

    def _update_pipelined(self, grad, hess, mask, feature_mask, k: int) -> bool:
        """Dispatch one iteration's device work; defer host bookkeeping.

        The PREVIOUS iteration's pending fetch is processed AFTER this
        iteration's device work is queued, so the tunnel transfer and host
        bookkeeping overlap device compute (steady-state wall time per iter
        = max(device tree time, fetch latency))."""
        prev = self._pending
        self._pending = None
        score_snapshot = self._score
        valid_snapshots = [e.score for e in self._valid]
        pend = []
        for kk in range(k):
            if self._class_need_train[kk] and self._bins.shape[1] > 0:
                qg, qh = self._quant_grow_inputs(grad[kk], hess[kk])
                ta, leaf_id = self._grow_one(
                    qg,
                    qh,
                    mask,
                    feature_mask,
                    self._tree_rng(),
                )
                ta = self._quant_renew(ta, leaf_id, grad[kk], hess[kk], mask)
                with get_session().phase("score_update"):
                    shrunk = ta.leaf_value * self._shrinkage_rate
                    self._score = self._score.at[kk].add(shrunk[leaf_id])
                    for entry in self._valid:
                        entry.score = entry.score.at[kk].set(
                            add_tree_to_score(
                                entry.score[kk],
                                entry.bins,
                                self._nan_bins,
                                ta.split_feature,
                                ta.split_bin,
                                ta.default_left,
                                ta.left_child,
                                ta.right_child,
                                shrunk,
                                ta.split_is_cat,
                                ta.cat_mask,
                            )
                        )
                    get_session().sync(self._score)
                # ta is dead after the pack (only .shape metadata is read
                # below): donation retires its ~18 buffers at dispatch
                # instead of Python GC.  The concatenated outputs can never
                # alias the inputs, so jax warns "not usable" on the one
                # trace — expected here, silenced to keep training quiet.
                with warnings.catch_warnings():
                    warnings.filterwarnings(
                        "ignore", message="Some donated buffers were not usable"
                    )
                    ints_d, floats_d = pack_tree_arrays_donated(ta)
                ints_d.copy_to_host_async()
                floats_d.copy_to_host_async()
                pend.append(
                    (kk, ints_d, floats_d, ta.split_feature.shape[0], ta.leaf_value.shape[0])
                )
            else:
                pend.append((kk, None, None, 0, 0))
        self._pending = {
            "classes": pend,
            "rate": self._shrinkage_rate,
            "iter": self._iter,
        }
        self._iter += 1
        if prev is not None:
            with get_session().phase("host_materialize"):
                self._process_pending(prev)
            if self._finished:
                # the previous iteration found no split: training stopped
                # THERE, so the iteration just dispatched must leave no trace
                # — restore the score snapshots and drop it (its gradients
                # could differ under bagging, so zero-contribution is not
                # guaranteed otherwise)
                self._score = score_snapshot
                for e, s in zip(self._valid, valid_snapshots):
                    e.score = s
                self._pending = None
                self._iter -= 1
                return True
        return False

    # ================================================================ training
    def _init_train(self, train_set: Dataset) -> None:
        """Reference: GBDT::Init (src/boosting/gbdt.cpp:59)."""
        if not train_set._constructed:
            # merge booster params the dataset doesn't set itself — the
            # reference pushes train() params into the Dataset before lazy
            # construction (basic.py Dataset._update_params), so e.g.
            # categorical_feature/max_bin passed to train() must bind here
            merged = {**self.params, **train_set.params}
            if merged != train_set.params:
                train_set.params = merged
                train_set.config = type(train_set.config).from_params(merged)
        else:
            # dataset parameters are frozen at construction: a second booster
            # with conflicting binning-relevant params must error, not
            # silently train on the first booster's binning (reference
            # basic.py _update_params "Cannot change {} after constructed";
            # ADVICE r2)
            from ..config import _PARAM_ALIASES

            _frozen = (
                "max_bin", "max_bin_by_feature", "min_data_in_bin",
                "bin_construct_sample_cnt", "use_missing", "zero_as_missing",
                "feature_pre_filter", "pre_partition", "linear_tree",
            )
            dcfg = train_set.config
            # NOTE: train_set.params may already carry the FIRST booster's
            # merged value for these keys, so the comparison must run for
            # every frozen key, against the dataset's bound config value
            for key, val in self.params.items():
                canon = _PARAM_ALIASES.get(key, key)
                if canon in _frozen:
                    bound = getattr(dcfg, canon)
                    new = getattr(type(dcfg).from_params({key: val}), canon)
                    if new != bound:
                        raise ValueError(
                            f"Cannot change {canon} (bound {bound!r} -> "
                            f"requested {new!r}) after the Dataset was "
                            "constructed; build a new Dataset or pass "
                            "free_raw_data=False and call set params before "
                            "construction"
                        )
        train_set.construct()
        self.train_set = train_set
        cfg = self.config
        if cfg.telemetry:
            get_session().configure(
                enabled=True,
                sync_timing=cfg.obs_sync_timing,
                sink_path=cfg.telemetry_out,
                device_accounting=cfg.obs_device_accounting,
                measure_collectives=cfg.obs_collectives,
            )
        # live ops plane: the flight ring records the tail of every train
        # run (dump-on-fault lands next to the checkpoint dir when one is
        # configured, else next to the telemetry sink); the watchdog
        # evaluates alert rules once per update
        import os as _os

        fault_dir = cfg.checkpoint_dir or (
            _os.path.dirname(_os.path.abspath(cfg.telemetry_out))
            if cfg.telemetry_out
            else ""
        )
        flight = get_flight()
        flight.reset()  # ring events are per-run; capacity/dir persist
        flight.configure(
            capacity=cfg.flight_capacity,
            fault_dir=fault_dir,
            run_info={
                "objective": cfg.objective,
                "num_leaves": cfg.num_leaves,
                "leaf_batch": cfg.leaf_batch,
                "tree_learner": cfg.tree_learner,
            },
        )
        self._watchdog = HealthWatchdog() if cfg.health_watchdog else None
        self.objective = create_objective(cfg)
        md = train_set.metadata
        n = train_set.num_data

        # ---- distributed: tree_learner data/feature/voting over a device
        # mesh (reference parallel learners, src/treelearner/
        # data_parallel_tree_learner.cpp — parallel/__init__.py documents the
        # psum mapping). Rows are padded to a multiple of the mesh size with
        # weight-0 rows so shards stay equal-sized (static shapes).
        self._mesh = None
        self._pad_rows = 0
        self._multiproc = False  # process-local rows (pre_partition multi-host)
        self._featpar = 0  # feature-parallel shard count (rows replicated)
        self._proc_row_offset = 0
        self._mesh_spec = None
        if cfg.tree_learner in ("data", "feature", "voting"):
            import dataclasses as _dc

            from ..parallel import choose_devices
            from ..parallel.mesh import build_mesh, choose_spec

            devices = choose_devices()
            # named-mesh layout (parallel/mesh.py): the tree_learner maps to
            # a default mesh shape and mesh_layout overrides it — data
            # (rows sharded), feature (features sliced, rows replicated,
            # reference feature_parallel_tree_learner.cpp:37) or hybrid
            # (2-D).  Every shape runs the same jitted grow path.
            layout = cfg.mesh_layout
            if layout == "auto":
                layout = "feature" if cfg.tree_learner == "feature" else "data"
            spec = (
                choose_spec(layout, len(devices), train_set.num_planes)
                if devices is not None
                else None
            )
            if (
                spec is not None
                and spec.data > 1
                and self.objective is not None
                and self.objective.need_query
                # multi-process feeding keeps ALL devices: trimming by the
                # LOCAL row count would leave a mesh spanning processes
                # unevenly (non-uniform sharding); the equal-rows-divisible
                # check below enforces the no-padding invariant instead
                and not (jax.process_count() > 1 and cfg.pre_partition)
            ):
                # ranking rows can't be weight-0 padded: shrink the DATA
                # axis until rows divide it (the feature axis never pads)
                dd = spec.data
                while dd > 1 and n % dd != 0:
                    dd -= 1
                spec = _dc.replace(spec, data=dd)
            if spec is not None and spec.size > 1:
                self._mesh_spec = spec
                self._mesh = build_mesh(spec, devices)
                self._featpar = spec.feature if spec.feature > 1 else 0
                nproc = jax.process_count()
                if nproc > 1 and cfg.pre_partition and self._featpar:
                    raise ValueError(
                        "feature-sliced mesh layouts need the full data on "
                        "every process (feature_parallel_tree_learner.cpp:37)"
                        " — they cannot combine with pre_partition row "
                        "partitioning; use the pure data layout for "
                        "multi-host training"
                    )
                if nproc > 1 and cfg.pre_partition:
                    # ---- process-local data feeding (reference: each machine
                    # loads only its partition under pre_partition,
                    # src/io/dataset_loader.cpp:210; distributed binning sync
                    # already ran at Dataset.construct).  Every per-row array
                    # is built from LOCAL rows and placed with
                    # make_array_from_process_local_data — no process ever
                    # holds the global matrix.  Local rows are weight-0
                    # padded to a common per-process width so shards stay
                    # equal-sized (static shapes).
                    from jax.experimental import multihost_utils

                    self._multiproc = True
                    if cfg.linear_tree:
                        raise ValueError(
                            "linear_tree is not supported with multi-process "
                            "pre_partition training"
                        )
                    pidx = jax.process_index()
                    nloc_dev = len(
                        [d for d in devices[: spec.size]
                         if d.process_index == pidx]
                    )
                    counts = multihost_utils.process_allgather(
                        np.asarray([n], np.int64)
                    ).reshape(-1)
                    if self.objective is not None and self.objective.need_query:
                        if int(counts.max()) != int(counts.min()) or n % nloc_dev:
                            raise ValueError(
                                "ranking with pre_partition needs equal "
                                "per-process row counts divisible by the "
                                "local device count (queries cannot be "
                                "weight-0 padded)"
                            )
                    lpad = -(-int(counts.max()) // nloc_dev) * nloc_dev
                    self._pad_rows = lpad - n
                    self._proc_row_counts = counts
                    self._proc_row_offset = int(counts[:pidx].sum())
                    self._n_global = int(counts.sum())
                    self._n_dev_global = lpad * nproc
                else:
                    # pad to a multiple of the DATA-axis size — the feature
                    # axis replicates rows, so padding by the total device
                    # count would over-pad any 2-D (or pure-feature) mesh
                    from ..parallel import pad_rows_for

                    self._pad_rows = pad_rows_for(n, self._mesh)
        pad = self._pad_rows
        n_dev = n + pad  # LOCAL device rows (== global when single-process)

        # the objective is initialized on the UNPADDED data so its host-side
        # statistics (class priors, is_unbalance weights, percentiles) are
        # exact; only its per-row DEVICE arrays get padded + mesh-placed below
        if self.objective is not None:
            if self._multiproc and not self.objective.need_query:
                # global host statistics (reference: Network::Allreduce inside
                # ObtainAutomaticInitialScore / label-count sync): gather the
                # label/weight COLUMNS across processes — O(8 bytes/row),
                # negligible next to the bin matrix which stays local.  The
                # per-row device arrays are re-sliced to local rows below.
                # Ranking objectives skip this: their init statistics are
                # per-query and queries never straddle processes.
                from ..parallel import allgather_host_varlen

                glabel = allgather_host_varlen(np.asarray(md.label))
                gweight = (
                    allgather_host_varlen(np.asarray(md.weight))
                    if md.weight is not None
                    else None
                )
                self._gathered_label = glabel  # reused by pos/neg bagging
                self.objective.init(glabel, gweight, None, None)
            else:
                self.objective.init(
                    md.label, md.weight, md.query_boundaries, md.position
                )
            self.num_class = self.objective.num_class
        else:
            self.num_class = max(1, cfg.num_class)
        self.num_tree_per_iteration = (
            self.objective.num_tree_per_iteration if self.objective else self.num_class
        )
        self.feature_names = list(train_set.feature_names)
        self.feature_infos = [m.feature_info_str() for m in train_set.bin_mappers]
        self.max_feature_idx = train_set.num_total_features - 1
        # recorded category orders (pandas categoricals / Arrow dictionary
        # columns) so predict on a fresh frame remaps codes identically
        self.pandas_categorical = (
            getattr(train_set, "pandas_categorical", None)
            or getattr(train_set, "arrow_categories", None)
        )
        self.average_output = cfg.boosting == "rf"

        k = self.num_tree_per_iteration
        init = np.zeros((k, n_dev), dtype=np.float32)
        if md.init_score is not None:
            isc = np.asarray(md.init_score, dtype=np.float32)
            init[:, :n] += isc.reshape(k, n) if isc.size == k * n else isc.reshape(1, n)
            self._has_init_score = True
        else:
            self._has_init_score = False

        # device data: ONE placement path for every mesh layout, driven by
        # the logical-axis-rule table (parallel/mesh.py AXIS_RULES).  Rows
        # shard over the 'data' axis and replicate over 'feature'; on a
        # pure-feature (1, F) mesh the data axis has size 1, so the same
        # specs degenerate to full replication (pad_rows is 0 there).
        if self._mesh is not None:
            from ..parallel import pad_rows_np, shard_cols, shard_rows

            self._score = shard_cols(init, self._mesh, process_local=self._multiproc)
            self._bins = shard_rows(
                pad_rows_np(train_set.bins, pad), self._mesh,
                process_local=self._multiproc,
            )
            # the objective's per-row device arrays ride the same sharding as
            # the score (zero-padded; padded rows' gradients are zeroed
            # explicitly in _sample — NOT via synthetic weights, which would
            # change semantics for objectives with non-multiplicative weights
            # like cross_entropy_lambda, xentropy_objective.hpp:184)
            if self.objective is not None:
                for holder, name, axis in self.objective.per_row_device_arrays():
                    arr = getattr(holder, name, None)
                    if arr is None:
                        continue
                    a = np.asarray(arr, dtype=np.float32)
                    if self._multiproc and a.shape[axis] == self._n_global:
                        # global-statistics init left global-length arrays on
                        # the objective: keep only this process's rows
                        off = self._proc_row_offset
                        a = np.take(a, np.arange(off, off + n), axis=axis)
                    if pad:
                        widths = [(0, 0)] * a.ndim
                        widths[axis] = (0, pad)
                        a = np.pad(a, widths)
                    setattr(
                        holder,
                        name,
                        shard_rows(a, self._mesh, process_local=self._multiproc)
                        if axis == 0
                        else shard_cols(a, self._mesh, process_local=self._multiproc),
                    )
        else:
            self._score = jnp.asarray(init)
            self._bins = train_set.device_bins()
        # per-COLUMN operand arrays: with EFB a bin-matrix column is a
        # bundle plane, without it a used feature (dataset plane accessors
        # return the right thing either way)
        self._bundle = getattr(train_set, "bundle_layout", None)
        self._has_bundle = bool(
            self._bundle is not None and self._bundle.has_bundles
        )
        nb = train_set.plane_num_bins()
        self._num_bins = jnp.asarray(nb, dtype=jnp.int32)
        nan_bins = train_set.plane_nan_bins()
        if len(nan_bins) == 0:
            nan_bins = np.array([-1], dtype=np.int32)  # pairs with the dummy column
        self._nan_bins = jnp.asarray(nan_bins)
        isc = train_set.plane_is_cat()
        if len(isc) == 0:
            isc = np.array([False])
        self._has_cat = bool(isc.any())
        self._is_cat = jnp.asarray(isc) if self._has_cat else None
        self._max_bin_padded = _ceil_pow2(int(nb.max()) if len(nb) else 2)
        self._bundle_end = (
            jnp.asarray(self._bundle.bundle_end_array(self._max_bin_padded))
            if self._has_bundle
            else None
        )
        self._check_bundle_compat()
        self._setup_constraints()
        self._forced = self._build_forced_splits()
        self._setup_cegb()
        self._grower_params = self._make_grower_params()
        f_used = self._bins.shape[1]
        if self._mesh is not None:
            from ..parallel import shard_rows

            base = np.ones(n_dev, np.float32)
            base[n:] = 0.0
            # rows role: sharded over 'data', replicated over 'feature' —
            # on a pure-feature mesh the data axis is 1, so this IS the
            # old replicate placement
            self._ones_mask = shard_rows(
                base, self._mesh, process_local=self._multiproc
            )
            self._setup_sharded_grower()
        else:
            self._ones_mask = jnp.ones((n,), jnp.float32)
        self._full_feature_mask = jnp.ones((f_used,), bool)
        self._rng = jax.random.PRNGKey(cfg.seed if cfg.seed is not None else 0)
        self._shrinkage_rate = cfg.learning_rate

        from .sampling import create_sample_strategy

        # the sampler draws GLOBAL-width masks (every process runs the same
        # rng program, so the bagging subset is consistent across shards)
        n_sampler = self._n_dev_global if self._multiproc else n_dev
        is_pos = None
        if cfg.pos_bagging_fraction < 1.0 or cfg.neg_bagging_fraction < 1.0:
            if self._multiproc:
                from ..parallel import allgather_host_varlen

                lpad = n_dev
                gl = getattr(self, "_gathered_label", None)
                if gl is None:
                    gl = allgather_host_varlen(np.asarray(md.label))
                gl = gl > 0
                blocks, o = [], 0
                for c in self._proc_row_counts:
                    blocks.append(gl[o : o + int(c)])
                    blocks.append(np.zeros(lpad - int(c), bool))
                    o += int(c)
                ip = np.concatenate(blocks)
            else:
                ip = np.asarray(md.label) > 0
                if pad:
                    ip = np.concatenate([ip, np.zeros(pad, bool)])
            is_pos = jnp.asarray(ip)
        from .sampling import bagging_is_active

        query_sizes = None
        pad_query_mask = None
        if cfg.bagging_by_query and bagging_is_active(cfg):
            qb = md.query_boundaries
            if qb is not None and self._multiproc:
                # global query-size list in PROCESS-BLOCK order: every
                # process's local queries followed by its block's padding
                # rows as a never-in-bag pseudo-query — all processes build
                # the identical list (allgather), so the shared rng stream
                # yields the same per-query mask everywhere (SPMD)
                from ..parallel import allgather_host_varlen

                local_sizes = np.diff(np.asarray(qb, np.int64))
                gsizes, gcounts = allgather_host_varlen(
                    local_sizes, return_counts=True
                )
                lpad = n_dev  # the per-process padded block width
                sizes, padm, off = [], [], 0
                for p, cq in enumerate(gcounts):
                    block = gsizes[off : off + int(cq)]
                    off += int(cq)
                    sizes.extend(int(s) for s in block)
                    padm.extend([False] * int(cq))
                    blk_pad = lpad - int(block.sum())
                    if blk_pad:
                        sizes.append(blk_pad)
                        padm.append(True)
                query_sizes = np.asarray(sizes, np.int64)
                pad_query_mask = np.asarray(padm, bool)
            elif qb is not None:
                query_sizes = np.diff(np.asarray(qb, np.int64))
        self._sampler = create_sample_strategy(
            cfg, n_sampler, is_pos, query_sizes=query_sizes,
            pad_query_mask=pad_query_mask,
        )
        self._gathered_label = None  # free the init-time global label copy

        # metrics for the training set.  Multi-process pre_partition: metric
        # aggregation across processes is not wired yet — train with
        # metric='none' and evaluate on a loaded model instead (the reference
        # evaluates rank-locally too, metric.cpp is per-machine).
        train_metrics = self._create_metrics()
        if self._multiproc and train_metrics:
            from ..utils.log import log_warning

            log_warning(
                "training metrics are disabled under multi-process "
                "pre_partition training (per-process rows only)"
            )
            train_metrics = []
        self._train_entry = _EvalEntry("training", train_set, train_metrics)
        for m in self._train_entry.metrics:
            m.init(md.label, md.weight, md.query_boundaries)
        self._class_need_train = [
            self.objective.class_need_train(kk) if self.objective else True
            for kk in range(k)
        ]

    def _check_bundle_compat(self) -> None:
        """EFB-bundled datasets reuse the numeric gain path + mask partition;
        modes that reinterpret the column axis per-feature (or per-candidate)
        are not wired through bundle planes — fail with the fix spelled out
        (the grower re-checks statically as a backstop)."""
        if not self._has_bundle:
            return
        cfg = self.config
        conflicts = [
            (
                cfg.monotone_constraints
                and any(v != 0 for v in cfg.monotone_constraints),
                "monotone_constraints",
            ),
            (
                isinstance(cfg.interaction_constraints, str)
                and cfg.interaction_constraints.strip() != ""
                or isinstance(cfg.interaction_constraints, (list, tuple))
                and len(cfg.interaction_constraints) > 0,
                "interaction_constraints",
            ),
            (bool(cfg.forcedsplits_filename), "forcedsplits_filename"),
            (cfg.extra_trees, "extra_trees"),
            (
                cfg.cegb_tradeoff < 1.0
                or cfg.cegb_penalty_split > 0.0
                or bool(cfg.cegb_penalty_feature_coupled),
                "CEGB penalties",
            ),
            (
                cfg.tree_learner in ("feature", "voting"),
                f"tree_learner='{cfg.tree_learner}'",
            ),
        ]
        for bad, what in conflicts:
            if bad:
                raise ValueError(
                    f"{what} is not supported together with EFB feature "
                    "bundling; pass enable_bundle=false in the Dataset "
                    "params to train this configuration"
                )

    def _setup_constraints(self) -> None:
        """Map per-original-feature constraints onto used columns."""
        cfg = self.config
        ds = self.train_set
        used = ds.used_features
        self._monotone = None
        if cfg.monotone_constraints and any(v != 0 for v in cfg.monotone_constraints):
            mc = np.zeros(len(used), dtype=np.int8)
            for ci, j in enumerate(used):
                if j < len(cfg.monotone_constraints):
                    mc[ci] = cfg.monotone_constraints[j]
            self._monotone = jnp.asarray(mc)
        # per-feature gain multipliers (reference feature_contri,
        # feature_histogram.hpp:1445) mapped onto used columns; all-ones is
        # the identity, so only materialize when some entry differs
        self._feature_contri = None
        if cfg.feature_contri and any(v != 1.0 for v in cfg.feature_contri):
            fc = np.ones(len(used), dtype=np.float32)
            for ci, j in enumerate(used):
                if j < len(cfg.feature_contri):
                    fc[ci] = cfg.feature_contri[j]
            self._feature_contri = jnp.asarray(fc)
        self._interaction_sets = None
        ic = cfg.interaction_constraints
        sets: List[List[int]] = []
        if isinstance(ic, str) and ic.strip():
            import re

            for grp in re.findall(r"\[([^\]]*)\]", ic):
                sets.append([int(x) for x in grp.split(",") if x.strip() != ""])
        elif isinstance(ic, (list, tuple)) and ic:
            sets = [list(map(int, g)) for g in ic]
        if sets:
            mat = np.zeros((len(sets), len(used)), dtype=bool)
            orig_to_used = {j: ci for ci, j in enumerate(used)}
            for si, grp in enumerate(sets):
                for j in grp:
                    if j in orig_to_used:
                        mat[si, orig_to_used[j]] = True
            self._interaction_sets = jnp.asarray(mat)

    def _setup_sharded_grower(self) -> None:
        """(Re)build the shard_map'd grower for the current GrowerParams.
        shard_map needs concrete arrays for every operand: dummies stand in
        for the optional ones (statically gated off inside grow_tree)."""
        from ..parallel.mesh import MeshSpec, make_mesh_grow

        f_used = self._bins.shape[1]
        spec = getattr(self, "_mesh_spec", None)
        if spec is None and self._mesh is not None:
            # meshes restored outside the constructor path (tests building
            # boosters by hand) default to the pure-data layout
            spec = MeshSpec("data", data=self._mesh.size)
        self._sharded_grow = make_mesh_grow(
            self._mesh, self._grower_params, spec
        )
        self._mono_arg = (
            self._monotone
            if self._monotone is not None
            else jnp.zeros((f_used,), jnp.int8)
        )
        self._inter_arg = (
            self._interaction_sets
            if self._interaction_sets is not None
            else jnp.ones((1, f_used), bool)
        )
        self._iscat_arg = (
            self._is_cat
            if self._is_cat is not None
            else jnp.zeros((f_used,), bool)
        )
        self._bundle_end_arg = (
            self._bundle_end
            if self._bundle_end is not None
            else jnp.full((1, 1), -1, jnp.int32)  # static no-op dummy
        )
        self._contri_arg = (
            self._feature_contri
            if self._feature_contri is not None
            else jnp.ones((f_used,), jnp.float32)
        )

    def _quant_grow_inputs(self, grad_k, hess_k):
        """Quantized-gradient training (GradientDiscretizer): tree growth
        sees grid-quantized gradients; leaf values are renewed from the true
        ones afterwards when quant_train_renew_leaf."""
        cfg = self.config
        if not cfg.use_quantized_grad:
            return grad_k, hess_k
        from ..ops.quantize import quantize_gradients

        qg, qh, g_scale, h_scale = quantize_gradients(
            grad_k,
            hess_k,
            self._next_rng(),
            num_bins=cfg.num_grad_quant_bins,
            stochastic=cfg.stochastic_rounding,
            constant_hessian=bool(
                self.objective is not None and self.objective.is_constant_hessian
            ),
        )
        self._quant_scales = (g_scale, h_scale)  # for the int8 histogram
        return qg, qh

    def _quant_renew(self, ta, leaf_id, grad_k, hess_k, mask):
        """RenewIntGradTreeOutput (gradient_discretizer.cpp:209) on device."""
        cfg = self.config
        if not (cfg.use_quantized_grad and cfg.quant_train_renew_leaf):
            return ta
        from ..ops.quantize import renew_leaf_values

        lv = renew_leaf_values(
            leaf_id,
            grad_k,
            hess_k,
            mask,
            ta.num_leaves,
            self._grower_params.num_leaves,
            cfg.lambda_l1,
            cfg.lambda_l2,
            cfg.max_delta_step,
            measure=self._grower_params.measure_collectives,
        )
        return ta._replace(leaf_value=lv)

    def _quant_scales_arg(self):
        """Concrete scales operand for shard_map (the int8-without-
        quantized-gradients config error is raised once at
        _make_grower_params time)."""
        scales = getattr(self, "_quant_scales", None)
        if scales is None:
            return (jnp.float32(1.0), jnp.float32(1.0))  # unused dummy
        return scales

    def _grow_one(self, grad_k, hess_k, mask, feature_mask, rng):
        """Grow one tree: serial grow_tree or the mesh-sharded shard_map path
        (reference: SerialTreeLearner vs DataParallelTreeLearner dispatch,
        src/boosting/gbdt.cpp:59 tree_learner selection)."""
        from ..utils.timer import global_timer

        ses = get_session()
        with global_timer.timed("tree/grow"), ses.phase("grow"):
            fused = self._mesh is None and bool(self._grower_params.grow_fused)
            try:
                if fused:
                    # fault-injection consult: stands in for a Mosaic
                    # compile/launch failure surfacing at dispatch
                    chaos.maybe_raise_pallas("fused_grow_step", self._iter)
                res = self._grow_one_inner(grad_k, hess_k, mask, feature_mask, rng)
                ses.sync(res)
            except Exception as exc:
                if not fused:
                    raise
                self._degrade_fused(exc)
                res = self._grow_one_inner(grad_k, hess_k, mask, feature_mask, rng)
                ses.sync(res)
            sample_device_memory("grow")
            return res

    def _degrade_fused(self, exc: Exception) -> None:
        """Permanently fall back from the fused Pallas grow step to the
        two-launch XLA composition (the byte-identical correctness oracle)
        after a kernel compile/launch failure.  The latch flips grow_fused
        off in GrowerParams, so the cost is ONE bounded retrace — not a
        retrace storm — and the run completes instead of dying."""
        from ..utils.log import log_warning

        self._grow_fused_disabled = True
        self._grower_params = self._make_grower_params()
        ses = get_session()
        ses.inc("degradations")
        event = {
            "event": "degradation",
            "component": "fused_grow_step",
            "action": "fallback_to_xla_oracle",
            "iter": int(self._iter),
            "error": f"{type(exc).__name__}: {exc}"[:300],
        }
        ses.record(event)
        # the latch is a survivable fault, but the triggering context is
        # exactly what a postmortem needs — dump the flight ring now
        flight = get_flight()
        flight.note_event(event)
        get_tracer().instant(
            "lifecycle/degradation",
            "lifecycle",
            args={
                "component": "fused_grow_step",
                "iter": int(self._iter),
                "error": event["error"],
            },
        )
        flight.dump("degradation")
        log_warning(
            "[resilience] fused Pallas grow step failed "
            f"({type(exc).__name__}); permanently falling back to the "
            "two-launch XLA path for the rest of the run"
        )

    def _grow_one_inner(self, grad_k, hess_k, mask, feature_mask, rng):
        if self._mesh is not None:
            return self._sharded_grow(
                self._bins,
                grad_k,
                hess_k,
                mask,
                self._num_bins,
                self._nan_bins,
                feature_mask,
                self._mono_arg,
                self._inter_arg,
                rng if rng is not None else jax.random.PRNGKey(0),
                self._iscat_arg,
                self._forced,
                *self._cegb_args(),
                self._quant_scales_arg(),
                self._bundle_end_arg,
                self._contri_arg,
            )
        return grow_tree(
            self._bins,
            grad_k,
            hess_k,
            mask,
            self._num_bins,
            self._nan_bins,
            feature_mask,
            self._grower_params,
            monotone=self._monotone,
            interaction_sets=self._interaction_sets,
            rng=rng,
            is_cat=self._is_cat,
            forced=self._forced,
            quant_scales=getattr(self, "_quant_scales", None),
            bundle_end=self._bundle_end,
            feature_contri=self._feature_contri,
            **(
                dict(zip(("cegb_penalty", "cegb_used"), self._cegb_args()))
                if self._cegb_coupled is not None
                else {}
            ),
        )

    def _setup_cegb(self) -> None:
        """Cost-Effective Gradient Boosting state (reference:
        cost_effective_gradient_boosting.hpp). The coupled per-feature
        penalty applies until a feature is first used ANYWHERE in the model
        (is_feature_used_in_split_ persists across trees); the lazy per-row
        penalty is not supported and warns."""
        cfg = self.config
        used = self.train_set.used_features
        self._cegb_coupled = None
        self._cegb_used = None
        coupled = cfg.cegb_penalty_feature_coupled
        enabled = (
            cfg.cegb_tradeoff < 1.0
            or cfg.cegb_penalty_split > 0.0
            or bool(coupled)
        )
        if cfg.cegb_penalty_feature_lazy:
            from ..utils.log import log_warning

            log_warning(
                "cegb_penalty_feature_lazy is not supported; ignoring"
            )
        if not enabled:
            return
        f_used = max(1, len(used))
        arr = np.zeros(f_used, np.float64)
        if coupled:
            for ci, j in enumerate(used):
                if j < len(coupled):
                    arr[ci] = coupled[j]
        self._cegb_coupled = arr * cfg.cegb_tradeoff
        self._cegb_used = np.zeros(f_used, bool)

    def _cegb_mark_used(self, split_features) -> None:
        if self._cegb_used is not None and len(split_features):
            self._cegb_used[np.asarray(split_features)] = True

    def _cegb_args(self):
        """(penalty, used) operands; concrete dummies when CEGB is off so the
        shard_map operand structure stays fixed (statically gated inside
        grow_tree by use_cegb)."""
        f = self._bins.shape[1]
        if self._cegb_coupled is None:
            return jnp.zeros((f,), jnp.float32), jnp.zeros((f,), bool)
        return (
            jnp.asarray(self._cegb_coupled, jnp.float32),
            jnp.asarray(self._cegb_used),
        )

    def _build_forced_splits(self):
        """forcedsplits_filename JSON -> BFS step arrays in the grower's
        leaf-id convention (step t splits `leaf`; left keeps the id, right
        becomes t+1).  Reference: SerialTreeLearner::ForceSplits
        (serial_tree_learner.cpp:627) — queue-ordered, thresholds quantized
        through the BinMapper like BinThreshold."""
        fn = self.config.forcedsplits_filename
        if not fn:
            return None
        import json as _json
        from collections import deque

        with open(fn) as fp:
            root = _json.load(fp)
        ds = self.train_set
        orig_to_used = {j: ci for ci, j in enumerate(ds.used_features)}
        steps = []  # (leaf, used_feat, bin, is_cat)
        q = deque([(root, 0)])
        max_steps = self.config.num_leaves - 1
        while q and len(steps) < max_steps:
            node, leaf = q.popleft()
            if (
                not isinstance(node, dict)
                or "feature" not in node
                or "threshold" not in node
            ):
                continue
            orig = int(node["feature"])
            if orig not in orig_to_used:
                break  # unused feature: abort remaining (reference warns)
            ci = orig_to_used[orig]
            mapper = ds.bin_mappers[orig]
            if mapper.is_categorical:
                bn = (mapper.cat_to_bin or {}).get(int(node["threshold"]))
                if bn is None:
                    break
                steps.append((leaf, ci, int(bn), True))
            else:
                ub = np.asarray(mapper.bin_upper_bound)
                bn = int(np.searchsorted(ub, float(node["threshold"]), side="left"))
                steps.append((leaf, ci, min(bn, mapper.num_bins - 1), False))
            t = len(steps) - 1
            if "left" in node:
                q.append((node["left"], leaf))
            if "right" in node:
                q.append((node["right"], t + 1))
        if not steps:
            return None
        arr = np.asarray(steps, dtype=np.int64)
        return (
            jnp.asarray(arr[:, 0].astype(np.int32)),
            jnp.asarray(arr[:, 1].astype(np.int32)),
            jnp.asarray(arr[:, 2].astype(np.int32)),
            jnp.asarray(arr[:, 3].astype(bool)),
        )

    def _make_grower_params(self) -> GrowerParams:
        from ..ops.split import CatParams

        cfg = self.config
        hist_method = str(self.params.get("hist_method", "auto"))
        # segment-resident mode (streaming partition + histogram kernels,
        # ops/pallas/) is the fast path on TPU: eligible whenever bins fit
        # a byte and the packed row fits 128 i16 lanes; hist_method
        # 'pallas_int8' rides the seg path's own int8 grid kernel (r3).
        # The budget counts bin-matrix COLUMNS — with EFB that is bundle
        # planes, which is exactly how 50k one-hot columns fit the seg path.
        n_used = int(self._bins.shape[1]) if self.train_set else 0
        import jax as _jax

        # the ONE config-time validation for int8 kernels (both seg and
        # ordered paths; _quant_scales_arg relies on this running first)
        if hist_method.startswith("pallas_int8") and not cfg.use_quantized_grad:
            raise ValueError(
                "hist_method='pallas_int8' needs quantized gradients "
                "(use_quantized_grad=True provides the scales)"
            )

        # feature budget: bins byte-pack two per i16 plane up to max_bin 256
        # (242 features), one u16 plane per feature beyond (121 features —
        # the reference's DenseBin<uint16_t> analog, dense_bin.hpp:18); wide
        # configs must also fit the histogram kernel's VMEM scratch
        from ..ops.pallas.seg import seg_vmem_ok

        # feature-parallel seg: each shard packs only its feature slice, so
        # the lane/VMEM budgets apply to the PER-SHARD feature count
        n_eff = n_used // self._featpar if self._featpar else n_used
        seg_fcap = 242 if self._max_bin_padded <= 256 else 121
        seg_fits = seg_vmem_ok(
            max(n_eff, 1), self._max_bin_padded, getattr(self, "_has_cat", False)
        )
        seg_ok = (
            self._max_bin_padded <= 65536
            and seg_fits
            and 0 < n_eff <= seg_fcap
            # the seg path has its own kernels: the default bf16 three-term
            # one and (r3) an int8 grid variant for quantized training;
            # other explicit kernel choices keep the ordered path
            # (pallas_int8_interpret stays on the ordered path: the seg
            # dispatcher has no interpret plumbing)
            and hist_method in ("auto", "pallas_int8")
            # off-TPU the seg histogram falls back to a masked full-N pass
            # per split — ordered mode's O(parent segment) wins there
            and _jax.default_backend() == "tpu"
        )
        if (
            not seg_ok
            and not self._featpar
            and _jax.default_backend() == "tpu"
            and hist_method == "auto"
            and n_used > 0
        ):
            # loud fence (VERDICT r2 #10): the ordered fallback is measured
            # 1.4-10x slower than seg mode at scale (BENCH_NOTES.md)
            from ..utils.log import log_warning

            if self._max_bin_padded > 65536:
                why = f"max_bin padded to {self._max_bin_padded} > 65536"
            elif not seg_fits:
                why = (
                    f"histogram VMEM scratch at {n_used} features x "
                    f"max_bin {self._max_bin_padded} exceeds the budget"
                )
            else:
                why = (
                    f"{n_used} used features > {seg_fcap} (packed row "
                    "exceeds 128 i16 lanes)"
                )
            log_warning(
                "segment-resident training is unavailable: " + why +
                "; falling back to hist_mode='ordered' (1.4-10x slower at "
                "scale). Consider feature selection"
                + (" or a smaller max_bin" if seg_fcap == 121 or not seg_fits
                   else "") + "."
            )
        hist_mode = str(
            self.params.get(
                "hist_mode",
                "seg" if seg_ok
                else ("gather" if self._featpar else "ordered"),
            )
        )
        # frontier batching scope: modes whose per-split state is not
        # member-local keep the serial loop (grow_tree raises on these at
        # K > 1; downgrade here with a warning instead)
        leaf_k = max(1, int(cfg.leaf_batch))
        if leaf_k > 1:
            inter_mono = (
                self._monotone is not None
                and cfg.monotone_constraints_method
                in ("intermediate", "advanced")
            )
            blockers = [
                (cfg.tree_learner == "voting" and self._mesh is not None,
                 "tree_learner='voting'"),
                (bool(self._featpar), "feature-parallel training"),
                (self._cegb_coupled is not None, "CEGB feature penalties"),
                (inter_mono,
                 "monotone_constraints_method='intermediate'/'advanced'"),
                (self._interaction_sets is not None,
                 "interaction_constraints"),
            ]
            why = [what for bad, what in blockers if bad]
            if why:
                from ..utils.log import log_warning

                log_warning(
                    "leaf_batch > 1 does not support "
                    + ", ".join(why)
                    + "; falling back to serial (leaf_batch=1) growth"
                )
                leaf_k = 1
        # remaining-leaf budget: a tree can never commit more than
        # num_leaves - 1 splits, so offering more slots only speculates
        leaf_k = min(leaf_k, max(1, cfg.num_leaves - 1))
        # adaptive commit-rate clamp: a prior tree's low commit rate halved
        # the cap (see _note_commit_rate); sticky for the rest of the run
        cap = getattr(self, "_leaf_batch_cap", None)
        if cap is not None:
            leaf_k = min(leaf_k, cap)
        if cfg.grow_fused == "on":
            grow_fused = True
        elif cfg.grow_fused == "off":
            grow_fused = False
        else:  # 'auto' — on when the seg fast path is active
            grow_fused = hist_mode == "seg"
        if getattr(self, "_grow_fused_disabled", False):
            # a runtime kernel failure latched the XLA fallback
            # (_degrade_fused); the latch survives checkpoint/restore
            grow_fused = False
        # double-buffered histogram collectives: 'auto' engages whenever
        # the frontier batch exists and a mesh is up (the grower further
        # gates on an actual histogram psum axis — see use_overlap); kept
        # False for serial/leaf_batch=1 configs so their trace keys are
        # unchanged
        overlap = (
            cfg.overlap_collectives != "off"
            and leaf_k > 1
            and self._mesh is not None
        )
        return GrowerParams(
            num_leaves=cfg.num_leaves,
            max_bin=self._max_bin_padded,
            hist_mode=hist_mode,
            hist_method=hist_method,
            max_depth=cfg.max_depth,
            min_data_in_leaf=cfg.min_data_in_leaf,
            min_sum_hessian_in_leaf=cfg.min_sum_hessian_in_leaf,
            lambda_l1=cfg.lambda_l1,
            lambda_l2=cfg.lambda_l2,
            min_gain_to_split=cfg.min_gain_to_split,
            max_delta_step=cfg.max_delta_step,
            path_smooth=cfg.path_smooth,
            use_monotone=self._monotone is not None,
            monotone_method=cfg.monotone_constraints_method,
            # PV-Tree election (ops/grower.voting_active gates on F > 2k —
            # below that the dense psum is exact and cheaper, the documented
            # alias onto tree_learner=data)
            voting_top_k=(
                cfg.top_k
                if (cfg.tree_learner == "voting" and self._mesh is not None)
                else 0
            ),
            feature_shard=self._featpar,
            use_interaction=self._interaction_sets is not None,
            feature_fraction_bynode=cfg.feature_fraction_bynode,
            extra_trees=cfg.extra_trees,
            use_cat=self._has_cat,
            cat_params=CatParams(
                max_cat_to_onehot=cfg.max_cat_to_onehot,
                max_cat_threshold=cfg.max_cat_threshold,
                cat_l2=cfg.cat_l2,
                cat_smooth=cfg.cat_smooth,
                min_data_per_group=cfg.min_data_per_group,
            )
            if self._has_cat
            else None,
            n_forced=0 if self._forced is None else len(self._forced[0]),
            use_cegb=self._cegb_coupled is not None,
            cegb_split_penalty=cfg.cegb_tradeoff * cfg.cegb_penalty_split,
            fused_split_scan=cfg.fused_split_scan,
            use_bundle=self._has_bundle,
            leaf_batch=leaf_k,
            grow_fused=grow_fused,
            overlap_collectives=overlap,
            monotone_penalty=cfg.monotone_penalty,
            use_feature_contri=self._feature_contri is not None,
            # measured collectives only make sense with a mesh; static so the
            # toggle retraces (obs/collectives module docstring)
            measure_collectives=bool(
                cfg.telemetry and cfg.obs_collectives and self._mesh is not None
            ),
            # histogram engine v2: int8-by-default accumulation on the seg
            # TPU path ('auto'/'int8'), near-tie f32 re-accumulate tolerance
            hist_acc=cfg.hist_acc,
            near_tie_tol=cfg.hist_near_tie_tol,
        )

    def _fit_linear_leaves(
        self,
        tree: Tree,
        leaf_id: np.ndarray,
        grad: np.ndarray,
        hess: np.ndarray,
        mask: Optional[np.ndarray],
    ) -> None:
        """Fit a linear model per leaf on the path's numerical features
        (reference: LinearTreeLearner::CalculateLinear,
        src/treelearner/linear_tree_learner.cpp:182 — weighted normal
        equations XᵀHX w = -Xᵀg with linear_lambda ridge, Eigen solve;
        NumPy lstsq here — this is per-tree host work like the reference)."""
        ds = self.train_set
        raw = ds.raw if ds.raw is not None else self._raw_for_replay(ds)
        lam = self.config.linear_lambda
        n_leaves = tree.num_leaves
        # path features per leaf from the tree structure
        paths: List[List[int]] = [[] for _ in range(n_leaves)]

        def walk(node: int, feats: List[int]):
            if node < 0:
                paths[~node] = feats
                return
            fsplit = int(tree.split_feature[node])
            is_cat = bool(tree.decision_type[node] & 1)
            nxt = feats if is_cat else feats + [fsplit]
            walk(int(tree.left_child[node]), nxt)
            walk(int(tree.right_child[node]), nxt)

        if n_leaves > 1:
            walk(0, [])
        tree.is_linear = True
        tree.leaf_const = np.array(tree.leaf_value, dtype=np.float64)
        tree.leaf_features = []
        tree.leaf_coeff = []
        sel_all = np.ones(len(leaf_id), bool) if mask is None else mask > 0
        for leaf in range(n_leaves):
            feats = sorted(set(paths[leaf]))
            rows = np.nonzero((leaf_id == leaf) & sel_all)[0]
            if not feats or len(rows) < len(feats) + 1:
                tree.leaf_features.append(np.zeros(0, dtype=np.int32))
                tree.leaf_coeff.append(np.zeros(0))
                continue
            Xl = raw[np.ix_(rows, feats)]
            ok = ~np.isnan(Xl).any(axis=1)
            if ok.sum() < len(feats) + 1:
                tree.leaf_features.append(np.zeros(0, dtype=np.int32))
                tree.leaf_coeff.append(np.zeros(0))
                continue
            Xl = Xl[ok]
            g = grad[rows][ok]
            h = hess[rows][ok]
            design = np.concatenate([Xl, np.ones((len(Xl), 1))], axis=1)
            A = design.T @ (design * h[:, None])
            A[np.arange(len(feats)), np.arange(len(feats))] += lam
            b = -design.T @ g
            try:
                w = np.linalg.solve(A + 1e-10 * np.eye(len(A)), b)
            except np.linalg.LinAlgError:
                tree.leaf_features.append(np.zeros(0, dtype=np.int32))
                tree.leaf_coeff.append(np.zeros(0))
                continue
            if not np.isfinite(w).all():
                tree.leaf_features.append(np.zeros(0, dtype=np.int32))
                tree.leaf_coeff.append(np.zeros(0))
                continue
            tree.leaf_features.append(np.asarray(feats, dtype=np.int32))
            tree.leaf_coeff.append(w[:-1])
            tree.leaf_const[leaf] = w[-1]

    def _create_metrics(self) -> List[Metric]:
        cfg = self.config
        names = cfg.metric if cfg.metric else cfg.default_metric()
        out = []
        for name in names:
            m = create_metric(name, cfg)
            if m is not None:
                out.append(m)
        return out

    def add_valid(self, data: Dataset, name: str) -> "Booster":
        if getattr(self, "_multiproc", False):
            raise ValueError(
                "validation sets are not supported under multi-process "
                "pre_partition training; evaluate the saved model per process"
            )
        data.construct()
        entry = _EvalEntry(name, data, self._create_metrics())
        md = data.metadata
        for m in entry.metrics:
            m.init(md.label, md.weight, md.query_boundaries)
        k = self.num_tree_per_iteration
        nv = data.num_data
        if self._mesh is not None:
            entry.pad = (-nv) % self._mesh.size
        init = np.zeros((k, nv + entry.pad), dtype=np.float32)
        if md.init_score is not None:
            isc = np.asarray(md.init_score, dtype=np.float32)
            init[:, :nv] += (
                isc.reshape(k, nv) if isc.size == k * nv else isc.reshape(1, nv)
            )
        if self._mesh is not None:
            from ..parallel import pad_rows_np, shard_cols, shard_rows

            entry.score = shard_cols(init, self._mesh)
            entry.dev_bins = shard_rows(
                pad_rows_np(data.bins, entry.pad), self._mesh
            )
        else:
            entry.score = jnp.asarray(init)
        # replay existing trees onto the valid score
        vbins = entry.bins
        vraw = None
        for idx, rec in enumerate(self._bin_records):
            k_id = idx % k
            if rec is not None and rec.get("no_bin_form"):
                if vraw is None:
                    vraw = self._raw_for_replay(data)
                entry.score = entry.score.at[k_id].add(
                    self._pad_delta(self.models_[idx].predict(vraw), entry.pad)
                )
                continue
            if rec is None or len(rec["split_feature"]) == 0:
                tree = self.models_[idx]
                entry.score = entry.score.at[k_id].add(float(tree.leaf_value[0]))
                continue
            entry.score = entry.score.at[k_id].set(
                add_tree_to_score(
                    entry.score[k_id],
                    vbins,
                    self._nan_bins,
                    jnp.asarray(rec["split_feature"]),
                    jnp.asarray(rec["split_bin"]),
                    jnp.asarray(rec["default_left"]),
                    jnp.asarray(rec["left_child"]),
                    jnp.asarray(rec["right_child"]),
                    jnp.asarray(np.asarray(self.models_[idx].leaf_value, dtype=np.float32)),
                    *self._rec_cat_args(rec),
                )
            )
        self._valid.append(entry)
        return self

    def _next_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _tree_rng(self):
        """Per-tree RNG for feature_fraction_bynode / extra_trees draws.

        An EXPLICIT extra_seed (present in the raw params, reference
        config.h extra_seed) folds into the stream so changing it changes
        the extra-trees thresholds; unset, the stream is untouched and
        training stays byte-identical to the pre-wiring behavior."""
        cfg = self.config
        if not (cfg.feature_fraction_bynode < 1.0 or cfg.extra_trees):
            return None
        rng = self._next_rng()
        if cfg.extra_trees and "extra_seed" in cfg.raw:
            rng = jax.random.fold_in(rng, cfg.extra_seed)
        return rng

    def _bagging_rng(self) -> jax.Array:
        """Row-sampling RNG; an EXPLICIT bagging_seed folds in (reference
        config.h bagging_seed — a distinct deterministic bagging stream),
        unset keeps the historical stream byte-identical."""
        rng = self._next_rng()
        cfg = self.config
        if "bagging_seed" in cfg.raw:
            rng = jax.random.fold_in(rng, cfg.bagging_seed)
        return rng

    @staticmethod
    def _rec_cat_args(rec):
        """(split_is_cat, cat_mask) device args for a bin record; records
        from older model loads may lack them (numeric-only trees)."""
        sic = rec.get("split_is_cat")
        cm = rec.get("cat_mask")
        nn = len(rec["split_feature"])
        if sic is None or cm is None or np.size(cm) == 0:
            return jnp.zeros((nn,), bool), jnp.zeros((nn, 1), bool)
        return jnp.asarray(sic), jnp.asarray(cm)

    @staticmethod
    def _pad_delta(delta, pad: int) -> jnp.ndarray:
        """Pad a real-space [N] per-row score delta to the mesh row width."""
        from ..parallel import pad_rows_np

        return jnp.asarray(pad_rows_np(np.asarray(delta, dtype=np.float32), pad))

    def _get_gradients(self):
        """Objective gradients in the GLOBAL score sharding.

        Elementwise objectives run straight on the sharded score.  Ranking
        objectives under multi-process feeding are per-query and queries
        never straddle processes (the init contract at _init_train), so
        each process computes gradients on its LOCAL score columns and the
        results are reassembled into the global sharded array from local
        device buffers — no host round trip of the global matrix
        (reference: rank_objective gradients are rank-local too; the
        Allreduce happens later on histograms)."""
        if not (self._multiproc and self.objective.need_query):
            return self.objective.get_gradients(self._score, self._next_rng())
        import jax as _jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        shards = sorted(
            self._score.addressable_shards,
            key=lambda s: s.index[1].start or 0,
        )
        # per-device shards -> one host-local [K, lpad] block (small: the
        # score column slice of this process only)
        local = np.concatenate([np.asarray(s.data) for s in shards], axis=1)
        n = self.train_set.num_data  # local unpadded rows
        g, h = self.objective.get_gradients(
            jnp.asarray(local[:, :n]), self._next_rng()
        )
        lpad = local.shape[1]
        if lpad > n:
            z = jnp.zeros((g.shape[0], lpad - n), g.dtype)
            g = jnp.concatenate([g, z], axis=1)
            h = jnp.concatenate([h, z], axis=1)
        pidx = _jax.process_index()
        # mesh devices along the data axis, this process's block (process
        # blocks are contiguous: the mesh is built from jax.devices())
        mine = [
            d for d in self._mesh.devices.flat if d.process_index == pidx
        ]
        chunk = lpad // len(mine)
        sh = NamedSharding(self._mesh, P(None, "data"))
        gshape = (g.shape[0], self._n_dev_global)

        def _assemble(a):
            pieces = [
                _jax.device_put(a[:, i * chunk : (i + 1) * chunk], d)
                for i, d in enumerate(mine)
            ]
            return _jax.make_array_from_single_device_arrays(
                gshape, sh, pieces
            )

        return _assemble(g), _assemble(h)

    def _objective_name(self) -> str:
        if self.objective is not None:
            return type(self.objective).__name__
        return str(self.params.get("objective", "custom"))

    def _fault_dump(self, reason: str) -> str:
        """Black-box the run before a numerics abort: register a critical
        alert (so the dump carries it and ``health()`` reflects it), then
        atomically write the flight ring next to the checkpoint dir.
        Returns the dump path ("" when no fault_dir is configured)."""
        ses = get_session()
        ses.inc("numerics/guard_trips")
        flight = get_flight()
        it = int(self._iter)
        wd = getattr(self, "_watchdog", None)
        if wd is not None:
            alert = wd.note_fault("numerics", it, reason, ses=ses)
        else:
            alert = {
                "event": "alert", "rule": "numerics",
                "severity": "critical", "iter": it, "message": reason,
                "value": 1.0, "threshold": 0.0,
            }
        ses.record_alert(alert)
        flight.note_alert(alert)
        get_tracer().instant(
            "lifecycle/fault",
            "lifecycle",
            args={"reason": reason, "iter": it},
        )
        return flight.dump(reason)

    def _guard_gradients(self, grad, hess) -> None:
        """check_numerics guard: ONE device-side finiteness reduce over
        gradients+hessians per iteration, pulled as a single host bool.
        Catches poisoned labels/init_score/learning-rate blowups at the
        iteration that produced them instead of training NaN into the
        model silently."""
        ok = bool(jnp.isfinite(grad).all() & jnp.isfinite(hess).all())
        if not ok:
            self._fault_dump("numerics_gradients")
            raise NumericsError(
                f"non-finite gradients/hessians at iteration {self._iter} "
                f"(objective={self._objective_name()}); model state is "
                "intact up to the previous iteration — inspect labels, "
                "init_score, and learning_rate"
            )

    def _guard_tree(self, ta_host, iteration: int) -> None:
        """check_numerics guard: split gains and leaf values of a
        materialized tree must be finite (host-side; arrays already
        fetched, so this costs two np reductions)."""
        nn = max(0, int(ta_host.num_leaves) - 1)
        gains = np.asarray(ta_host.split_gain)[:nn]
        leaves = np.asarray(ta_host.leaf_value)[: int(ta_host.num_leaves)]
        if not (np.isfinite(gains).all() and np.isfinite(leaves).all()):
            self._fault_dump("numerics_tree")
            raise NumericsError(
                f"non-finite split gain or leaf value in the tree grown at "
                f"iteration {iteration} (objective={self._objective_name()})"
            )

    def set_row_mask(self, row_mask) -> None:
        """Restrict training to a fixed row subset (CV folds, holdouts).

        The mask rides the same live-row machinery as mesh padding: excluded
        rows get exact-zero gradients BEFORE sampling (so GOSS never selects
        them) and a zero sample mask after. Shape must be [num_data] (unpadded
        length); pass None to clear. Scores for excluded rows still advance —
        that is what makes out-of-fold prediction on the train-set scores
        possible."""
        sampler = getattr(self, "_sampler", None)
        if row_mask is None:
            self._fixed_row_mask = None
            if sampler is not None:
                sampler.set_live_count(None)
            return
        m = np.asarray(row_mask, dtype=np.float32).reshape(-1)
        if m.shape[0] != self.train_set.num_data:
            raise ValueError(
                f"row_mask length {m.shape[0]} != num_data "
                f"{self.train_set.num_data}"
            )
        live = int((m > 0).sum())
        if live == 0:
            raise ValueError("row_mask excludes every row")
        if self._pad_rows:
            m = np.concatenate([m, np.zeros(self._pad_rows, np.float32)])
        self._fixed_row_mask = jnp.asarray(m)
        if sampler is not None:
            sampler.set_live_count(live)

    def _sample(self, grad, hess):
        """Bagging/GOSS row sampling; padded (mesh-fill) rows never count.

        Padded rows' gradients are forced to exact zeros FIRST — objectives
        compute unspecified (finite or NaN) values on the zero-filled padding
        labels, and a NaN would poison the masked histogram (nan*0=nan)."""
        # the gate must be PROCESS-INVARIANT: under multi-process feeding a
        # per-process `_pad_rows` test would make processes issue different
        # op sequences on the same global arrays (SPMD violation — only some
        # processes reaching the next collective deadlocks the cluster)
        any_pad = bool(self._pad_rows) or getattr(self, "_multiproc", False)
        fixed = getattr(self, "_fixed_row_mask", None)
        if any_pad or fixed is not None:
            live = self._ones_mask[None] > 0
            if fixed is not None:
                live = jnp.logical_and(live, fixed[None] > 0)
            grad = jnp.where(live, grad, 0.0)
            hess = jnp.where(live, hess, 0.0)
        mask, grad, hess = self._sampler.sample(
            self._iter, grad, hess, self._bagging_rng()
        )
        if any_pad:
            mask = mask * self._ones_mask
        if fixed is not None:
            mask = mask * fixed
        ses = get_session()
        if ses.enabled:
            # host pull of a scalar; only paid when telemetry is on
            ses.set_gauge("bagging_rows", int(jnp.sum(mask > 0)))
        return mask, grad, hess

    def update(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        """One boosting iteration (reference GBDT::TrainOneIter gbdt.cpp:352).

        Returns True when training cannot continue (no positive-gain split),
        mirroring the reference's is_finished flag.
        """
        chaos.on_iteration(self._iter)  # no-op unless a test armed a fault
        ses = get_session()
        flight = get_flight()
        wd = getattr(self, "_watchdog", None)
        if not ses.enabled:
            # telemetry off: the always-on flight ring still gets a minimal
            # iteration event (one dict per iteration) and the watchdog
            # still sees walls; gauges/counters stay empty so gauge-based
            # rules simply never fire
            it = self._iter
            tracer = get_tracer()
            t0 = time.perf_counter()
            sp = tracer.begin(
                "train/iteration",
                "train",
                args={"iter": it},
                attach=True,
                ambient=True,
            )
            finished = False
            try:
                finished = self._update_impl(train_set, fobj)
            finally:
                if sp is not None:
                    tracer.end(sp, extra={"finished": bool(finished)})
            if flight.active or wd is not None:
                event = {
                    "event": "iteration",
                    "iter": it,
                    "wall_ms": (time.perf_counter() - t0) * 1e3,
                    "finished": bool(finished),
                }
                flight.note_event(event)
                if wd is not None:
                    wd.observe(event, ses)
            return finished
        it = self._iter
        trees_before = len(self._bin_records_store)
        compiles_before = _obs_compile_count()
        tracer = get_tracer()
        t0 = time.perf_counter()
        # iteration span opens BEFORE begin_iteration so phase timers
        # (registry._PhaseTimer -> note_phase) attach as children; ambient
        # parents the collective io_callback spans fired off-thread
        sp = tracer.begin(
            "train/iteration",
            "train",
            args={"iter": it},
            attach=True,
            ambient=True,
        )
        ses.begin_iteration()
        finished = False
        try:
            try:
                finished = self._update_impl(train_set, fobj)
            finally:
                phases = ses.end_iteration()
            # under obs_sync_timing wall_ms is the fully synchronized
            # iteration time; otherwise it is dispatch time (async runtime)
            ses.sync(self._score)
        finally:
            # the finally keeps the tls span stack balanced when
            # _update_impl raises (NumericsError -> _fault_dump)
            if sp is not None:
                tracer.end(sp, extra={"finished": bool(finished)})
        wall_ms = (time.perf_counter() - t0) * 1e3
        # host bookkeeping (and hence these records) lags one iteration on
        # the pipelined path — splits here count trees MATERIALIZED this call
        new_recs = [r for r in self._bin_records_store[trees_before:] if r]
        compiles_now = _obs_compile_count()
        event = {
            "event": "iteration",
            "iter": it,
            "wall_ms": wall_ms,
            "phases": {k2: v * 1e3 for k2, v in phases.items()},
            "compile_count": compiles_now,
            "compiles_delta": compiles_now - compiles_before,
            "trees_materialized": len(new_recs),
            "splits": int(sum(len(r["split_feature"]) for r in new_recs)),
            "leaf_batch": int(self.config.leaf_batch),
            "finished": bool(finished),
        }
        if (
            self._mesh is not None
            # voting's elected-slice psums are data-dependent (top-k per
            # shard), so the analytic shape model covers every layout BUT it
            and self.config.tree_learner != "voting"
        ):
            from ..parallel.mesh import MeshSpec, mesh_psum_bytes_per_iteration

            spec = getattr(self, "_mesh_spec", None) or MeshSpec(
                "data", data=int(self._mesh.devices.size)
            )
            k = max(1, self.num_tree_per_iteration)
            per_tree = (
                event["splits"] // max(1, len(new_recs))
                if new_recs
                else max(1, self.config.num_leaves - 1)
            )
            coll = mesh_psum_bytes_per_iteration(
                per_tree,
                int(self._bins.shape[1]),
                # PADDED bin-axis size: the psum moves the [F, B, 3] padded
                # histogram, so the measured cross-check only matches with
                # the same B the trace actually uses
                int(self._grower_params.max_bin),
                leaf_batch=int(self.config.leaf_batch),
                spec=spec,
            )
            coll = {k2: v * k for k2, v in coll.items()}
            event["collective"] = coll
            ses.set_gauge("collective_hist_bytes", coll["hist_bytes"])
            ses.set_gauge("collective_count_bytes", coll["count_bytes"])
            ses.set_gauge(
                "collective_ring_bytes_per_device",
                coll["ring_bytes_per_device"],
            )
        if self._mesh is not None and self._grower_params.measure_collectives:
            snap = collectives_snapshot(reset=True)
            if snap:
                meas = measured_summary(snap, int(self._mesh.devices.size))
                event["collective_measured"] = meas
                ses.set_gauge("collective_measured_bytes", meas["bytes"])
                ses.set_gauge(
                    "collective_measured_psum_bytes", meas["psum_bytes"]
                )
                ses.set_gauge("collective_measured_wall_ms", meas["wall_ms"])
                ses.inc("collective_measured_bytes_total", int(meas["bytes"]))
        sample_device_memory("iteration")
        ses.inc("iterations")
        ses.set_gauge("hist/int8_engaged", float(self._int8_engaged()))
        # deferred: the engine annotates eval metrics into this event before
        # the JSONL line is flushed (next record / flush_pending)
        ses.record(event, defer=True)
        flight.note_event(event)
        if wd is not None:
            # alerts are recorded via record_alert, which leaves the
            # deferred iteration event pending (late eval annotations
            # still land on its JSONL line)
            wd.observe(event, ses)
        return finished

    def _launch_runner_for(self, n: int):
        """Cached compiled N-iteration launch runner (boosting/launch.py).
        Rebuilt when the static snapshot went stale (set_row_mask /
        reset_parameter between trains swap the sampler or grower
        params)."""
        from .launch import LaunchRunner

        cache = getattr(self, "_launch_runners", None)
        if cache is None:
            cache = self._launch_runners = {}
        runner = cache.get(int(n))
        if runner is None or runner.stale(self):
            runner = cache[int(n)] = LaunchRunner(self, int(n))
        return runner

    def update_launch(self, n: int) -> Tuple[int, bool]:
        """Advance up to ``n`` boosting iterations in ONE compiled device
        launch (lax.scan over the iteration loop — boosting/launch.py).
        Model dumps are byte-identical to ``n`` serial ``update()`` calls
        for every eligible config; the caller (engine.train) handles
        eligibility and period clamping via ``resolve_launch_steps``.
        Returns ``(steps_consumed, is_finished)`` — the finishing
        all-constant iteration counts as consumed, like ``update()``
        returning True."""
        if int(n) <= 1:
            return 1, self.update()
        return self._launch_runner_for(int(n)).run()

    def _update_impl(self, train_set: Optional[Dataset] = None, fobj=None) -> bool:
        if train_set is not None and train_set is not self.train_set:
            self._init_train(train_set)
        ses = get_session()
        cfg = self.config
        k = self.num_tree_per_iteration
        n = self.train_set.num_data

        if self._finished:
            return True
        # pipeline gate BEFORE any drain: reading models_ would block on the
        # in-flight fetch and serialize host bookkeeping with device compute
        eff_len = len(self._models_store) + (
            k if getattr(self, "_pending", None) is not None else 0
        )
        if (
            fobj is None
            and self.objective is not None
            and not self.objective.is_renew_tree_output
            and not cfg.linear_tree
            and type(self) is Booster
            and eff_len >= k  # init/boost-from-avg settled
        ):
            with ses.phase("gradients"):
                grad, hess = self._get_gradients()
                ses.sync(grad)
            grad, hess = chaos.maybe_poison_gradients(grad, hess, self._iter)
            if cfg.check_numerics:
                self._guard_gradients(grad, hess)
            with ses.phase("sample"):
                mask, grad, hess = self._sample(grad, hess)
                ses.sync(mask)
            feature_mask = self._feature_mask_for_iter()
            return self._update_pipelined(grad, hess, mask, feature_mask, k)

        self._drain_pending()
        if self._finished:
            return True

        init_scores = [0.0] * k
        if fobj is None:
            if (
                not self.models_
                and not self._has_init_score
                and self.objective is not None
                and cfg.boost_from_average
            ):
                for kk in range(k):
                    s = self.objective.boost_from_score(kk)
                    if abs(s) > _EPS:
                        init_scores[kk] = s
                        self._score = self._score.at[kk].add(s)
                        for entry in self._valid:
                            entry.score = entry.score.at[kk].add(s)
            with ses.phase("gradients"):
                grad, hess = self._get_gradients()
                ses.sync(grad)
        else:
            if self._multiproc:
                raise ValueError(
                    "custom fobj is not supported under multi-process "
                    "pre_partition training (scores are process-sharded)"
                )
            g, h = fobj(
                np.asarray(self._score)[:, :n].reshape(-1)
                if k > 1
                else np.asarray(self._score[0])[:n],
                self.train_set,
            )
            g = np.asarray(g, dtype=np.float32).reshape(k, n)
            h = np.asarray(h, dtype=np.float32).reshape(k, n)
            if self._pad_rows:
                zeros = np.zeros((k, self._pad_rows), np.float32)
                g = np.concatenate([g, zeros], axis=1)
                h = np.concatenate([h, zeros], axis=1)
            grad = jnp.asarray(g)
            hess = jnp.asarray(h)

        grad, hess = chaos.maybe_poison_gradients(grad, hess, self._iter)
        if cfg.check_numerics:
            self._guard_gradients(grad, hess)

        # bagging / GOSS (reference: SampleStrategy::Bagging gbdt.cpp:384)
        with ses.phase("sample"):
            mask, grad, hess = self._sample(grad, hess)
            ses.sync(mask)
        feature_mask = self._feature_mask_for_iter()

        should_continue = False
        for kk in range(k):
            grown = None
            if self._class_need_train[kk] and self._bins.shape[1] > 0:
                grown = self._grow_class(
                    kk, grad, hess, mask, feature_mask, self._tree_rng()
                )
            if self._commit_class_tree(kk, grown, grad, hess, mask, init_scores):
                should_continue = True

        return self._finish_iteration(should_continue)

    def _grow_class(self, kk, grad, hess, mask, feature_mask, rng):
        """Grow + host-materialize one class's tree.

        Returns (ta, ta_host, leaf_id); the commit step is separate so a
        fleet trainer can substitute one batched grow for M solo grows and
        still reuse the per-member commit path unchanged."""
        cfg = self.config
        qg, qh = self._quant_grow_inputs(grad[kk], hess[kk])
        ta, leaf_id = self._grow_one(qg, qh, mask, feature_mask, rng)
        ta = self._quant_renew(ta, leaf_id, grad[kk], hess[kk], mask)
        # two bulk transfers instead of ~14 small ones (remote TPU
        # round-trips dominate otherwise)
        with get_session().phase("host_materialize"):
            ta_host = fetch_tree_arrays(ta)
        if cfg.check_numerics:
            self._guard_tree(ta_host, self._iter)
        self._note_refine_rate(ta_host)
        return ta, ta_host, leaf_id

    def _commit_class_tree(self, kk, grown, grad, hess, mask, init_scores,
                           skip_train_score=False):
        """Commit one class's grown tree into the model: score updates,
        Tree materialization, bin records. `grown` is `_grow_class`'s
        result or None for a skipped class. Returns True when the tree
        has at least one split (the iteration should continue).

        ``skip_train_score`` is the device-resident launch path
        (boosting/launch.py): the scan already applied this tree's train
        score delta inside the compiled program, so only the valid-score
        walk and host materialization run here."""
        cfg = self.config
        k = self.num_tree_per_iteration
        n = self.train_set.num_data
        n_leaves = int(grown[1].num_leaves) if grown is not None else 1

        if n_leaves > 1:
            ta, ta_host, leaf_id = grown
            leaf_value = ta.leaf_value
            if self.objective is not None and self.objective.is_renew_tree_output:
                lv = self.objective.renew_tree_output(
                    np.asarray(self._score[kk], dtype=np.float64)[:n],
                    np.asarray(leaf_id)[:n],
                    np.asarray(ta_host.leaf_value, dtype=np.float64),
                    np.asarray(mask)[:n],
                )
                leaf_value = jnp.asarray(lv, dtype=jnp.float32)
                ta = ta._replace(leaf_value=leaf_value)
                ta_host = ta_host._replace(leaf_value=lv)
            tree = Tree.from_device_arrays(
                ta_host,
                self.train_set.bin_mappers,
                self.train_set.used_features,
                bundle_layout=self._bundle,
            )
            if cfg.verbosity >= 2:
                tree.validate()  # debug CHECK paths (tree.py)
            is_linear = bool(cfg.linear_tree)
            if is_linear:
                self._fit_linear_leaves(
                    tree,
                    np.asarray(leaf_id)[:n],
                    np.asarray(grad[kk], dtype=np.float64)[:n],
                    np.asarray(hess[kk], dtype=np.float64)[:n],
                    np.asarray(mask)[:n],
                )
            tree.apply_shrinkage(self._shrinkage_rate)

            if is_linear:
                # linear leaves: per-row output depends on raw features;
                # scores advance by a host tree walk (the reference's
                # LinearTreeLearner AddPredictionToScore equivalent)
                delta = tree.predict(self._raw_for_replay(self.train_set))
                self._score = self._score.at[kk].add(
                    self._pad_delta(delta, self._pad_rows)
                )
                for entry in self._valid:
                    vdelta = tree.predict(self._raw_for_replay(entry.dataset))
                    entry.score = entry.score.at[kk].add(
                        self._pad_delta(vdelta, entry.pad)
                    )
            else:
                shrunk = leaf_value * self._shrinkage_rate
                # train score update: one gather (reference UpdateScore
                # :501); the donated entry retires the old score cache
                if not skip_train_score:
                    self._score = _apply_tree_score(
                        self._score, shrunk, leaf_id, jnp.int32(kk)
                    )
                # valid score updates: bin-space walk of the new tree
                for entry in self._valid:
                    entry.score = _apply_tree_valid_score(
                        entry.score,
                        entry.bins,
                        self._nan_bins,
                        ta.split_feature,
                        ta.split_bin,
                        ta.default_left,
                        ta.left_child,
                        ta.right_child,
                        shrunk,
                        ta.split_is_cat,
                        ta.cat_mask,
                        jnp.int32(kk),
                    )
            if abs(init_scores[kk]) > _EPS:
                tree.add_bias(init_scores[kk])
            nn = n_leaves - 1
            rec = {
                "split_feature": np.asarray(ta_host.split_feature)[:nn],
                "split_bin": np.asarray(ta_host.split_bin)[:nn],
                "default_left": np.asarray(ta_host.default_left)[:nn],
                "left_child": np.asarray(ta_host.left_child)[:nn],
                "right_child": np.asarray(ta_host.right_child)[:nn],
                "leaf_value": np.asarray(tree.leaf_value, dtype=np.float32),
                "split_is_cat": np.asarray(ta_host.split_is_cat)[:nn],
                "cat_mask": np.asarray(ta_host.cat_mask)[:nn],
            }
            self._cegb_mark_used(rec["split_feature"])
            if is_linear:
                rec["no_bin_form"] = True  # device walker can't see coeffs
            self._bin_records.append(rec)
            self.models_.append(tree)
            self._bump_model_version()
        else:
            # constant tree (reference gbdt.cpp:428-441)
            if len(self.models_) < k:
                if (
                    self.objective is not None
                    and not cfg.boost_from_average
                    and not self._has_init_score
                ):
                    init_scores[kk] = self.objective.boost_from_score(kk)
                    self._score = self._score.at[kk].add(init_scores[kk])
                    for entry in self._valid:
                        entry.score = entry.score.at[kk].add(init_scores[kk])
                tree = Tree.constant_tree(init_scores[kk])
            else:
                tree = Tree.constant_tree(0.0)
            self._bin_records.append(
                {
                    "split_feature": np.zeros(0, np.int32),
                    "split_bin": np.zeros(0, np.int32),
                    "default_left": np.zeros(0, bool),
                    "left_child": np.zeros(0, np.int32),
                    "right_child": np.zeros(0, np.int32),
                    "leaf_value": np.asarray(tree.leaf_value, dtype=np.float32),
                }
            )
            self.models_.append(tree)
            self._bump_model_version()

        return n_leaves > 1

    def _finish_iteration(self, should_continue: bool) -> bool:
        """Iteration epilogue shared by solo and fleet-lockstep paths:
        roll back the all-constant round or advance the iteration
        counter. Returns the is_finished flag."""
        k = self.num_tree_per_iteration
        if not should_continue:
            if len(self.models_) > k:
                for _ in range(k):
                    self.models_.pop()
                    self._bin_records.pop()
                self._bump_model_version()
            return True
        self._iter += 1
        return False

    def _fleet_begin_iter(self):
        """Per-iteration preamble for lockstep fleet training.

        Mirrors the non-pipelined `_update_impl` preamble EXACTLY —
        including RNG consumption order, which is what makes a fleet
        member's model dump byte-identical to its solo run: gradients
        consume one key, bagging one key, then one per-class tree key
        drawn only for classes that actually train and only when the
        grower needs device RNG (`_tree_rng` returns None otherwise).
        Returns the iteration operands the fleet trainer stacks across
        members before the single batched grow."""
        ses = get_session()
        cfg = self.config
        k = self.num_tree_per_iteration
        init_scores = [0.0] * k
        if (
            not self.models_
            and not self._has_init_score
            and self.objective is not None
            and cfg.boost_from_average
        ):
            for kk in range(k):
                s = self.objective.boost_from_score(kk)
                if abs(s) > _EPS:
                    init_scores[kk] = s
                    self._score = self._score.at[kk].add(s)
                    for entry in self._valid:
                        entry.score = entry.score.at[kk].add(s)
        with ses.phase("gradients"):
            grad, hess = self._get_gradients()
            ses.sync(grad)
        grad, hess = chaos.maybe_poison_gradients(grad, hess, self._iter)
        if cfg.check_numerics:
            self._guard_gradients(grad, hess)
        with ses.phase("sample"):
            mask, grad, hess = self._sample(grad, hess)
            ses.sync(mask)
        feature_mask = self._feature_mask_for_iter()
        tree_rngs = [
            self._tree_rng()
            if (self._class_need_train[kk] and self._bins.shape[1] > 0)
            else None
            for kk in range(k)
        ]
        return {
            "init_scores": init_scores,
            "grad": grad,
            "hess": hess,
            "mask": mask,
            "feature_mask": feature_mask,
            "tree_rngs": tree_rngs,
        }

    def _fleet_end_iter(self, should_continue: bool) -> bool:
        """Fleet-lockstep epilogue: `_finish_iteration` plus latching the
        finished flag so this member becomes a value-preserving no-op slot
        (zero gradients, discarded outputs) while the rest of the fleet
        keeps training."""
        finished = self._finish_iteration(should_continue)
        if finished:
            self._finished = True
        return finished

    def _feature_mask_np_for(self, iteration: int) -> np.ndarray:
        """Host-side feature mask for an arbitrary iteration — the pure
        part of ``_feature_mask_for_iter``, reusable by the launch path
        (boosting/launch.py), which precomputes the masks for a whole
        N-iteration window before dispatching the scan."""
        cfg = self.config
        f = self._bins.shape[1]
        if cfg.feature_fraction >= 1.0 or f == 0:
            return np.ones(f, dtype=bool)
        rng = np.random.default_rng(cfg.feature_fraction_seed + iteration)
        used = max(1, int(round(f * cfg.feature_fraction)))
        chosen = rng.choice(f, size=used, replace=False)
        m = np.zeros(f, dtype=bool)
        m[chosen] = True
        return m

    def _feature_mask_for_iter(self) -> jnp.ndarray:
        f = self._bins.shape[1]
        if self.config.feature_fraction >= 1.0 or f == 0:
            self._note_live_plane(None, f)
            return self._full_feature_mask
        m = self._feature_mask_np_for(self._iter)
        self._note_live_plane(m, f)
        return jnp.asarray(m)

    def rollback_one_iter(self) -> "Booster":
        """Reference GBDT::RollbackOneIter (gbdt.cpp:462)."""
        if self._iter <= 0:
            return self
        k = self.num_tree_per_iteration
        for kk in range(k):
            idx = len(self.models_) - k + kk
            tree = self.models_[idx]
            rec = self._bin_records[idx]
            neg = jnp.asarray(-np.asarray(tree.leaf_value, dtype=np.float32))
            if rec.get("no_bin_form"):
                # linear trees / re-expressed init-model trees: the bin-space
                # walk with plain leaf_value would ignore per-leaf linear
                # coefficients — un-apply with the same real-valued predict
                # the forward path used
                self._score = self._score.at[kk].add(
                    -self._pad_delta(
                        tree.predict(self._train_raw_for_replay()), self._pad_rows
                    )
                )
                for entry in self._valid:
                    entry.score = entry.score.at[kk].add(
                        -self._pad_delta(
                            tree.predict(self._raw_for_replay(entry.dataset)),
                            entry.pad,
                        )
                    )
            elif len(rec["split_feature"]):
                self._score = self._score.at[kk].set(
                    add_tree_to_score(
                        self._score[kk],
                        self._bins,
                        self._nan_bins,
                        jnp.asarray(rec["split_feature"]),
                        jnp.asarray(rec["split_bin"]),
                        jnp.asarray(rec["default_left"]),
                        jnp.asarray(rec["left_child"]),
                        jnp.asarray(rec["right_child"]),
                        neg,
                        *self._rec_cat_args(rec),
                    )
                )
                for entry in self._valid:
                    entry.score = entry.score.at[kk].set(
                        add_tree_to_score(
                            entry.score[kk],
                            entry.bins,
                            self._nan_bins,
                            jnp.asarray(rec["split_feature"]),
                            jnp.asarray(rec["split_bin"]),
                            jnp.asarray(rec["default_left"]),
                            jnp.asarray(rec["left_child"]),
                            jnp.asarray(rec["right_child"]),
                            neg,
                            *self._rec_cat_args(rec),
                        )
                    )
            else:
                self._score = self._score.at[kk].add(-float(tree.leaf_value[0]))
                for entry in self._valid:
                    entry.score = entry.score.at[kk].add(-float(tree.leaf_value[0]))
        for _ in range(k):
            self.models_.pop()
            self._bin_records.pop()
        self._bump_model_version()
        self._iter -= 1
        self._finished = False
        return self

    # ================================================================== eval
    def _eval_entry(self, entry: _EvalEntry, feval=None) -> List[Tuple[str, str, float, bool]]:
        dev_score = self._score if entry is self._train_entry else entry.score
        n_real = entry.dataset.num_data
        out = []
        score = None  # host copy, pulled only if some metric needs it
        dev_sliced = None
        for m in entry.metrics:
            res = None
            if feval is None and hasattr(m, "eval_device"):
                # device-side metric: only the result scalar crosses to host
                # (the [K, N] score pull dominates eval at 10M+ rows)
                if dev_sliced is None:
                    dev_sliced = dev_score[:, :n_real]
                res = m.eval_device(dev_sliced, self.objective)
            if res is None:
                if score is None:
                    score = np.asarray(dev_score, dtype=np.float64)[:, :n_real]
                res = m.eval(score, self.objective)
            for name, val in res:
                out.append((entry.name, name, val, m.is_higher_better))
        if score is None and feval is not None:
            score = np.asarray(dev_score, dtype=np.float64)[:, :n_real]
        if feval is not None:
            fevals = feval if isinstance(feval, (list, tuple)) else [feval]
            # feval receives transformed predictions, matching the reference
            # (GBDT::GetPredictAt applies ConvertOutput before handing the
            # score to python feval)
            if self.objective is not None:
                pred_for_feval = np.asarray(
                    self.objective.convert_output(
                        jnp.asarray(score.T if self.num_class > 1 else score[0])
                    )
                )
            else:
                pred_for_feval = score.T if self.num_class > 1 else score[0]
            for f in fevals:
                res = f(pred_for_feval, entry.dataset)
                results = res if isinstance(res, list) else [res]
                for name, val, hib in results:
                    out.append((entry.name, name, val, hib))
        return out

    def eval_train(self, feval=None):
        return self._eval_entry(self._train_entry, feval)

    def eval_valid(self, feval=None):
        out = []
        for entry in self._valid:
            out.extend(self._eval_entry(entry, feval))
        return out

    def eval(self, data: Dataset, name: str, feval=None):
        for entry in self._valid:
            if entry.dataset is data:
                return self._eval_entry(entry, feval)
        if data is self.train_set:
            return self.eval_train(feval)
        raise ValueError("dataset was not added with add_valid")

    # =============================================================== predict
    def telemetry(self) -> Dict[str, Any]:
        """Snapshot of the process-global telemetry session: per-iteration
        events, counters/gauges (including the ``cost/*`` / ``memory/*`` /
        ``collective_measured*`` families — see README "Deep profiling"),
        and jit retrace counts (global and by label)."""
        from ..obs.jit import compile_counts_by_label

        ses = get_session()
        ses.flush_pending()
        return {
            "enabled": ses.enabled,
            "events": list(ses.events),
            "counters": dict(ses.counters),
            "gauges": dict(ses.gauges),
            "compile_count": _obs_compile_count(),
            "compile_counts_by_label": compile_counts_by_label(),
        }

    def health(self) -> Dict[str, Any]:
        """Live health snapshot: watchdog status (``ok``/``warn``/
        ``critical``), active alerts, the counter/gauge tables and flight-
        recorder state.  Same document as the exporter's ``GET /healthz``
        (see README "Live observability")."""
        from ..obs.export import health_snapshot

        return health_snapshot(getattr(self, "_watchdog", None))

    def dump_trace(self, path: str) -> str:
        """Write the span recorder's ring as a Chrome trace-event JSON file
        (atomic tmp+rename).  Load the file in Perfetto
        (https://ui.perfetto.dev) or ``chrome://tracing`` to see the
        train-launch / iteration / phase / collective span timeline.  The
        same document is served live at ``GET /trace`` when
        ``obs_export_port`` is set, and dumped automatically next to every
        flight-recorder fault dump.  Returns the path written."""
        return get_tracer().dump(path)

    def current_iteration(self) -> int:
        return self._iter

    def num_trees(self) -> int:
        return len(self.models_)

    @property
    def num_model_per_iteration(self) -> int:
        return self.num_tree_per_iteration

    def num_feature(self) -> int:
        return self.max_feature_idx + 1

    def _tree_range(self, start_iteration: int, num_iteration: Optional[int]):
        k = self.num_tree_per_iteration
        total_iters = len(self.models_) // k
        start = max(0, start_iteration)
        if num_iteration is None:
            # LightGBM contract: default to best_iteration when early
            # stopping recorded one (basic.py predict docs)
            end = self.best_iteration if self.best_iteration > 0 else total_iters
            end = min(end, total_iters)
        elif num_iteration <= 0:
            end = total_iters
        else:
            end = min(total_iters, start + num_iteration)
        return start * k, max(end, start) * k

    def predict(
        self,
        data: Union[np.ndarray, "Any"],
        start_iteration: int = 0,
        num_iteration: Optional[int] = None,
        raw_score: bool = False,
        pred_leaf: bool = False,
        pred_contrib: bool = False,
        validate_features: bool = False,
        **kwargs,
    ) -> np.ndarray:
        """Batch prediction (reference: LGBM_BoosterPredictForMat ->
        PredictBatchDirect, src/c_api.cpp:2531/:528; per-tree walk
        tree_avx512.hpp:41 -> predict.py level-sync walker).

        Unlike the fork's quirk (PredictRawBatch skipping ConvertOutput,
        SURVEY §2.9), the sigmoid/softmax transform IS applied unless
        raw_score is requested.
        """
        X = self._coerce_predict_input(data)
        t0, t1 = self._tree_range(start_iteration, num_iteration)
        if pred_contrib:
            if hasattr(X, "toarray"):
                X = np.asarray(X.toarray(), dtype=np.float64)
            return self._predict_contrib(X, t0, t1)
        k = self.num_tree_per_iteration
        if t1 <= t0 or not self.models_:
            n = X.shape[0]
            if pred_leaf:
                return np.zeros((n, 0), dtype=np.int32)
            base = np.zeros((n, k) if k > 1 else n)
            return base

        use_bins = (
            self.train_set is not None
            and self.train_set.bin_mappers
            # merged init-model trees may have no exact bin-space form
            # (e.g. categorical splits); fall back to the host walker then
            and not any(
                r.get("no_bin_form") for r in self._bin_records[t0:t1]
            )
        )
        es_requested = bool(
            kwargs.get("pred_early_stop", self.config.pred_early_stop)
        ) and self._early_stop_type(k) != "none"
        knobs = self._predict_knobs(kwargs)
        if use_bins:
            # resolve the prediction engine up front: a matmul/auto request
            # that resolves to the tensor engine skips the Pallas walk fast
            # path (the contractions ARE the MXU path); a walker resolution
            # keeps the existing routing byte-for-byte
            resolved_engine, _ = self._stream_engine().resolve_engine(
                knobs["engine"], "bin", t0, t1
            )
            if (
                resolved_engine == "walk"
                and not pred_leaf
                and not es_requested
            ):
                # fast path: Pallas forest-walk kernel (the fork's
                # tree_avx512 batch predictor, TPU-shaped) with device-side
                # binning — falls back to the streaming XLA engine off-TPU
                # or for categorical/wide trees
                raw_fw = self._forest_walk_raw(
                    X, t0, t1, k,
                    exact_binning=bool(kwargs.get("pred_exact_binning", False)),
                )
                if raw_fw is not None:
                    return self._finish_predict(raw_fw, t0, t1, k, raw_score)
            space = "bin"
        else:
            if hasattr(X, "toarray"):  # real-space walkers need dense values
                X = np.asarray(X.toarray(), dtype=np.float64)
            # linear trees carry per-leaf coefficients the device walker
            # doesn't model — host walk (Tree.predict applies them)
            has_linear = any(t.is_linear for t in self.models_[t0:t1])
            if has_linear and not pred_leaf:
                per_tree = np.stack(
                    [t.predict(X) for t in self.models_[t0:t1]], axis=1
                )
                n = X.shape[0]
                if es_requested:
                    raw = self._apply_pred_early_stop(per_tree, k, kwargs)
                else:
                    raw = per_tree.reshape(n, (t1 - t0) // k, k).sum(axis=1)
                return self._finish_predict(raw, t0, t1, k, raw_score)
            space = "real"

        # streaming engine: chunked, bucket-padded, double-buffered walks
        # (real-space chunks carry the f64 suspect re-walk patch inside)
        eng = self._stream_engine()
        if pred_leaf:
            return eng.run(X, t0, t1, space=space, kind="leaf", **knobs)
        n = X.shape[0]
        iters = (t1 - t0) // k
        if es_requested:
            per_tree = eng.run(X, t0, t1, space=space, kind="value", **knobs)
            raw = self._apply_pred_early_stop(per_tree, k, kwargs)
        else:
            raw = eng.run(
                X,
                t0,
                t1,
                space=space,
                kind="value",
                reduce_fn=lambda blk, rows: blk.reshape(rows, iters, k).sum(
                    axis=1
                ),
                **knobs,
            )
        return self._finish_predict(raw, t0, t1, k, raw_score)

    def _predict_knobs(self, kwargs: Dict[str, Any]) -> Dict[str, Any]:
        """Streaming-engine tuning knobs: per-call kwargs win over params."""
        cfg = self.config
        return {
            "chunk": int(kwargs.get("pred_chunk_rows", cfg.pred_chunk_rows)),
            "num_buffers": int(
                kwargs.get("pred_num_buffers", cfg.pred_num_buffers)
            ),
            "shard_devices": int(
                kwargs.get("pred_shard_devices", cfg.pred_shard_devices)
            ),
            "engine": str(
                kwargs.get("pred_engine", getattr(cfg, "pred_engine", "walk"))
            ),
        }

    def _stream_engine(self) -> StreamingPredictor:
        eng = getattr(self, "_stream", None)
        if eng is None:
            eng = self._stream = StreamingPredictor(self)
        return eng

    @property
    def last_predict_stats(self) -> Dict[str, Any]:
        """Phase breakdown of the most recent predict() call (bin_ms,
        transfer_ms, walk_ms, host_ms, chunks, buckets, compiles)."""
        stats = getattr(self, "_fw_stats", None)
        eng = getattr(self, "_stream", None)
        if eng is not None and eng.last_stats:
            return eng.last_stats
        return stats or {}

    def _bin_matrix_width(self) -> int:
        """Column count of the host-binned prediction matrix: bundle planes
        under EFB, used features otherwise, 1 dummy when nothing is used."""
        ds = self.train_set
        layout = getattr(ds, "bundle_layout", None)
        if layout is not None and getattr(layout, "has_bundles", False):
            return max(1, ds.num_planes)
        return max(1, len(ds.used_features))

    def compile_predict(
        self,
        start_iteration: int = 0,
        num_iteration: Optional[int] = None,
        kinds=("value",),
        chunk: Optional[int] = None,
        pred_engine: Optional[str] = None,
    ) -> int:
        """AOT-lower and cache the streaming engine's bucket-ladder
        executables so the first predict() pays no compile (pred_aot_compile
        runs this at Booster load).  ``chunk`` overrides the config's
        ``pred_chunk_rows`` ladder top (the serving registry warms at its
        ``serve_max_batch``); ``pred_engine`` overrides the config's engine
        (the registry warms at the serve-level engine).  Returns the number
        of executables compiled."""
        t0, t1 = self._tree_range(start_iteration, num_iteration)
        if t1 <= t0 or not self.models_:
            return 0
        knobs = self._predict_knobs(
            {} if pred_engine is None else {"pred_engine": pred_engine}
        )
        if chunk is None:
            chunk = knobs["chunk"]
        return self._stream_engine().warmup(
            t0,
            t1,
            space=self._predict_space(t0, t1),
            chunk=max(256, int(chunk)),
            shard_devices=knobs["shard_devices"],
            kinds=kinds,
            engine=knobs["engine"],
        )

    def _predict_space(self, t0: int, t1: int) -> str:
        """Which walker space predict() will use for this tree range: exact
        bin-space when the training BinMappers are present and every tree
        has a bin-space form, else real-value space."""
        use_bins = (
            self.train_set is not None
            and self.train_set.bin_mappers
            and not any(
                r.get("no_bin_form") for r in self._bin_records[t0:t1]
            )
        )
        return "bin" if use_bins else "real"

    def _real_walk_suspects(self, X: np.ndarray, t0: int, t1: int) -> np.ndarray:
        """Row indices whose f32 walk could disagree with the reference's
        f64 NumericalDecision: some feature value lies within f32 rounding
        distance of some numeric threshold on that feature (categorical
        splits compare exact small integers and cannot flip)."""
        key = ("thr", t0, t1, self._model_version)
        if key not in self._stack_cache:
            # one live entry: staged-prediction loops would otherwise pin a
            # threshold map per (t0, t1) range forever
            self._stack_cache = {
                kk: v for kk, v in self._stack_cache.items()
                if kk[0] != "thr"
            }
            per_feat: Dict[int, list] = {}
            for tr in self.models_[t0:t1]:
                cat = (np.asarray(tr.decision_type) & 1) != 0
                for f_, th in zip(
                    np.asarray(tr.split_feature)[~cat],
                    np.asarray(tr.threshold, np.float64)[~cat],
                ):
                    per_feat.setdefault(int(f_), []).append(float(th))
            self._stack_cache[key] = {
                f_: np.unique(np.asarray(v, np.float64))
                for f_, v in per_feat.items()
            }
        sus = np.zeros(X.shape[0], bool)
        for f_, thr in self._stack_cache[key].items():
            if f_ >= X.shape[1] or thr.size == 0:
                continue
            x = X[:, f_]
            j = np.clip(np.searchsorted(thr, x), 0, thr.size - 1)
            jm = np.clip(j - 1, 0, thr.size - 1)
            near = np.minimum(np.abs(x - thr[j]), np.abs(x - thr[jm]))
            # a flip needs |x - thr| within the f32 rounding of either
            # operand; 8 ulps is comfortably conservative and still keeps
            # the suspect rate ~1e-5
            eps = 8.0 * np.float64(
                np.spacing(
                    np.maximum(np.abs(x), np.abs(thr[j])).astype(np.float32)
                )
            )
            sus |= near <= eps
        return np.flatnonzero(sus)

    def _finish_predict(self, raw: np.ndarray, t0, t1, k, raw_score):
        if self.average_output:
            raw = raw / ((t1 - t0) // k)
        if k == 1:
            raw = raw[:, 0]
        if raw_score or self.objective is None:
            return raw
        n = raw.shape[0]
        if n == 0:
            return raw
        # pad rows to a power of two before the (row-local) output transform
        # so convert_output compiles per bucket, not per distinct row count
        n_pad = _ceil_pow2(n)
        if n_pad != n:
            widths = [(0, n_pad - n)] + [(0, 0)] * (raw.ndim - 1)
            padded = np.pad(raw, widths)
        else:
            padded = raw
        return np.asarray(
            self.objective.convert_output(jnp.asarray(padded))
        )[:n]

    def _forest_walk_raw(self, X, t0, t1, k, exact_binning: bool = False):
        """Raw class scores via the Pallas forest-walk kernel
        (ops/pallas/forest_walk.py — the fork's tree_avx512 batch path,
        TPU-shaped), or None when ineligible.  Binning runs on device
        when every used feature is numeric (the f32 compare-reduce form of
        BinMapper::ValueToBin) with boundary-adjacent rows re-binned on
        host for f64 exactness; ``predict(..., pred_exact_binning=True)``
        forces the host path entirely."""
        import jax as _jax

        from ..ops.pallas.forest_walk import (
            _pack_bins_device,
            bin_numeric_device,
            bucket_pad_rows,
            build_devbin_tables,
            build_tables,
            forest_walk,
            pad_bins_for_walk,
            unpack_walk_scores,
            walk_reject_reason,
        )

        if _jax.default_backend() != "tpu" and not _WALK_INTERPRET:
            return None
        if getattr(self, "_has_bundle", False):
            # EFB models carry plane-membership nodes the walk kernel's
            # threshold tables don't model; the XLA bin walker handles them
            return None
        n = X.shape[0]
        n_used = self.train_set.num_planes
        recs = self._bin_records[t0:t1]
        nanb = np.asarray(self._nan_bins)
        reason = walk_reject_reason(recs, nanb, n_used, self._max_bin_padded)
        if reason is not None:
            # loud fence (VERDICT r3 weak #6): the XLA walker is an order of
            # magnitude slower — tell the user WHY the fast path was lost
            if not getattr(self, "_warned_walk_fallback", False):
                self._warned_walk_fallback = True
                from ..utils.log import log_warning

                log_warning(
                    "prediction fast path (forest-walk kernel) unavailable: "
                    + reason + "; using the slower XLA walker"
                )
            return None
        key = ("fw", t0, t1, self._model_version)
        if key not in self._stack_cache:
            self._stack_cache = {
                kk: v for kk, v in self._stack_cache.items() if kk[0] != "fw"
            }
            self._stack_cache[key] = build_tables(recs, nanb)
        tables = self._stack_cache[key]

        dense_np = isinstance(X, np.ndarray) and X.ndim == 2
        dbt = None
        if dense_np and not exact_binning:
            if ("devbin",) not in self._stack_cache:
                self._stack_cache[("devbin",)] = build_devbin_tables(
                    self.train_set.bin_mappers, self.train_set.used_features
                )
            dbt = self._stack_cache[("devbin",)]

        def _walk(packed):
            return forest_walk(
                packed,
                tables,
                n_trees=tables.n_trees,
                max_depth=tables.max_depth,
                k=k,
                interpret=_WALK_INTERPRET,
            )

        import time as _time

        t_start = _time.perf_counter()

        def _fw_stats(bin_ms=0.0, walk_ms=0.0, chunks=1):
            self._fw_stats = {
                "path": "forest_walk",
                "rows": n,
                "chunks": chunks,
                "bin_ms": round(bin_ms, 3),
                "transfer_ms": 0.0,
                "walk_ms": round(walk_ms, 3),
                "host_ms": 0.0,
            }
            # engine stats would shadow these (last_predict_stats prefers
            # the engine when it ran last) — clear its record
            if getattr(self, "_stream", None) is not None:
                self._stream.last_stats = {}

        if dbt is None:
            t_b = _time.perf_counter()
            host_bins = self._bin_input_host(X)
            bin_ms = (_time.perf_counter() - t_b) * 1e3
            out = _walk(pad_bins_for_walk(host_bins, bucket_pad_rows(n)))
            res = unpack_walk_scores(np.asarray(out), n, k).astype(np.float64)
            _fw_stats(bin_ms, (_time.perf_counter() - t_start) * 1e3 - bin_ms)
            return res

        # device binning + chunked feed: fixed-size chunks keep ONE compiled
        # (bin, pack, walk) pipeline, and dispatching chunk i+1's host slice
        # prep while chunk i computes overlaps transfer with the walk (the
        # ROUND_NOTES r3 double-buffering plan; jax's async dispatch is the
        # buffer)
        CHUNK = _PREDICT_CHUNK
        used = self.train_set.used_features

        def _bin_chunk(xs_np, x_orig, rows):
            """[CHUNK, F] f32 used-feature slice -> exact device bins.

            ``x_orig`` is the ORIGINAL full-width f64 rows of this chunk —
            the suspect re-bin must run the exact host path on the
            unrounded values (and _bin_input_host indexes by global
            feature id)."""
            mat_dev, suspect = bin_numeric_device(jnp.asarray(xs_np), *dbt)
            # device binning compares in f32; rows with a value within a
            # few ulps of a bin boundary are re-binned with the exact f64
            # host path so predictions match it bit-for-bit (ADVICE r2; the
            # boundary test is conservative, suspects are typically none)
            sidx = np.flatnonzero(np.asarray(suspect[:rows]))
            if len(sidx):
                patch = self._bin_input_host(x_orig[sidx])
                mat_dev = mat_dev.at[jnp.asarray(sidx)].set(
                    jnp.asarray(patch.astype(np.int32))
                )
            return mat_dev

        if n <= CHUNK:
            xs = np.ascontiguousarray(X[:, used], dtype=np.float32)
            # bucketed tile count: varying batch sizes reuse a small ladder
            # of compiled walk programs instead of one per distinct size
            out = _walk(_pack_bins_device(_bin_chunk(xs, X, n), bucket_pad_rows(n)))
            res = unpack_walk_scores(np.asarray(out), n, k).astype(np.float64)
            _fw_stats(0.0, (_time.perf_counter() - t_start) * 1e3)
            return res

        # one-chunk lookahead drain: chunk i dispatches asynchronously, then
        # chunk i-1 transfers to host — compute/transfer overlap without
        # letting every chunk's device output accumulate in HBM (~32+ MB per
        # 1M-row chunk; an unbounded predict would OOM the accelerator)
        parts = []
        pending = None  # (device_out, rows)
        for lo in range(0, n, CHUNK):
            rows = min(CHUNK, n - lo)
            xo = X[lo : lo + rows]
            xs = np.zeros((CHUNK, len(used)), np.float32)
            xs[:rows] = xo[:, used]
            out = _walk(_pack_bins_device(_bin_chunk(xs, xo, rows), CHUNK))
            if pending is not None:
                parts.append(unpack_walk_scores(np.asarray(pending[0]), pending[1], k))
            pending = (out, rows)
        parts.append(unpack_walk_scores(np.asarray(pending[0]), pending[1], k))
        res = np.concatenate(parts, axis=0).astype(np.float64)
        _fw_stats(0.0, (_time.perf_counter() - t_start) * 1e3, chunks=-(-n // CHUNK))
        return res

    def _early_stop_type(self, k: int) -> str:
        """Reference c_api chooses the margin rule from the objective
        (src/c_api.cpp: binary/multiclassova objectives -> 'binary'/'multiclass')."""
        if k > 1:
            return "multiclass"
        name = self.objective.name if self.objective is not None else ""
        if name in ("binary", "cross_entropy", "cross_entropy_lambda"):
            return "binary"
        return "none"

    def _apply_pred_early_stop(
        self, per_tree: np.ndarray, k: int, kwargs: Dict[str, Any]
    ) -> np.ndarray:
        """Margin-based prediction early stopping, vectorized over rows
        (reference: prediction_early_stop.cpp:26-75 + the per-iteration
        counter loop in gbdt_prediction.cpp:18-36).  Each row's accumulation
        freezes at the FIRST checkpoint (every pred_early_stop_freq
        iterations) whose margin exceeds pred_early_stop_margin — identical
        outputs to the reference's sequential loop, computed as one cumsum."""
        freq = max(1, int(kwargs.get("pred_early_stop_freq",
                                     self.config.pred_early_stop_freq)))
        margin_thr = float(kwargs.get("pred_early_stop_margin",
                                      self.config.pred_early_stop_margin))
        n, total = per_tree.shape
        iters = total // k
        cum = np.cumsum(per_tree.reshape(n, iters, k), axis=1)  # [N, I, K]
        if k == 1:
            margin = 2.0 * np.abs(cum[:, :, 0])
        else:
            s = np.sort(cum, axis=2)
            margin = s[:, :, -1] - s[:, :, -2]
        checkpoint = (np.arange(1, iters + 1) % freq) == 0
        stop = (margin > margin_thr) & checkpoint[None, :]
        any_stop = stop.any(axis=1)
        first = np.where(any_stop, stop.argmax(axis=1), iters - 1)
        return cum[np.arange(n), first]

    def _predict_category_maps(self, cat_names):
        """Recorded train-time category orders as a {name: values} dict.

        ``pandas_categorical`` loaded from a reference-produced model file is
        a list-of-lists ordered like the frame's categorical columns
        (reference: basic.py ``_data_from_pandas`` zips them in column
        order); ours is already a dict keyed by column name."""
        maps = self.pandas_categorical or getattr(
            self.train_set, "arrow_categories", None
        ) or getattr(self.train_set, "pandas_categorical", None)
        if isinstance(maps, list):
            maps = dict(zip(cat_names, maps))
        if not maps and cat_names:
            from ..utils.log import log_warning

            log_warning(
                "predict input has categorical columns but the Booster has "
                "no recorded category order (model trained on pre-coded "
                "data?); raw dictionary codes will be used and may not "
                "match training"
            )
        return maps or {}

    def _coerce_predict_input(self, data):
        from ..dataset import (
            _arrow_to_numpy,
            _is_arrow,
            _is_cat_dtype,
            _pandas_to_numpy,
        )

        if _is_arrow(data):
            import pyarrow as pa  # _is_arrow guaranteed pyarrow is loaded

            dict_cols = [
                str(f.name)
                for f in data.schema
                if pa.types.is_dictionary(f.type)
            ]
            data = _arrow_to_numpy(data, self._predict_category_maps(dict_cols))[0]
        try:
            import pandas as pd  # type: ignore
        except Exception:
            pd = None
        if pd is not None and isinstance(data, pd.DataFrame):
            cat_cols = [
                str(c) for c in data.columns if _is_cat_dtype(data[c].dtype)
            ]
            data = _pandas_to_numpy(
                data, self._predict_category_maps(cat_cols)
            )[0]
        if hasattr(data, "tocsc") and hasattr(data, "nnz"):
            # scipy sparse stays sparse: the bin path bins per-column from
            # CSC; paths that need dense values densify themselves
            return data
        X = np.asarray(data, dtype=np.float64)
        if X.ndim == 1:
            X = X[None, :]
        return X

    def _bin_input_host(self, X) -> np.ndarray:
        ds = self.train_set
        csc = X.tocsc() if hasattr(X, "tocsc") else None
        if csc is not None and csc.shape[1] < ds.num_total_features:
            # copy before resize: tocsc() aliases csc_matrix inputs and
            # resize() would mutate the caller's matrix
            csc = csc.copy()
            csc.resize(csc.shape[0], ds.num_total_features)

        def _feature_bins(j):
            mapper = ds.bin_mappers[j]
            if csc is not None:
                sl = slice(csc.indptr[j], csc.indptr[j + 1])
                col = np.zeros(csc.shape[0], np.float64)
                col[csc.indices[sl]] = csc.data[sl]
            else:
                col = X[:, j]
            b = mapper.values_to_bins(col)
            if mapper.is_categorical:
                # unseen categories must fall through to the right child
                # (reference CategoricalDecision, tree.h:382): bin 0 would
                # wrongly send them left, so route them to a sentinel bin
                vals = np.asarray(col)
                nan_mask = np.isnan(vals)
                iv = np.where(nan_mask, -1, vals).astype(np.int64)
                known = np.isin(iv, mapper.bin_to_cat) & (iv >= 0)
                sentinel = np.int32(1 << 20)
                b = np.where(known | (nan_mask & (mapper.nan_bin >= 0)), b, sentinel)
            return b

        layout = getattr(ds, "bundle_layout", None)
        if layout is not None:
            # EFB: predict input packs into the SAME plane columns training
            # used, so bin-space walks see identical decisions
            return layout.pack_columns(X.shape[0], _feature_bins).astype(
                np.int32
            )
        cols = [_feature_bins(j) for j in ds.used_features]
        mat = (
            np.stack(cols, axis=1)
            if cols
            # no used features (all trivial): keep one dummy column so the
            # walker's gathers stay in range; constant trees never read it
            else np.zeros((X.shape[0], 1), dtype=np.int32)
        )
        return mat.astype(np.int32)

    def _bump_model_version(self) -> None:
        self._model_version = getattr(self, "_model_version", 0) + 1

    def _stacked_real(self, t0: int, t1: int):
        """Cached real-space tree batch (same invalidation discipline as
        _stacked_bins: any models_ mutation bumps _model_version)."""
        key = ("real", t0, t1, self._model_version)
        if key not in self._stack_cache:
            self._stack_cache = {
                k: v for k, v in self._stack_cache.items() if k[0] != "real"
            }
            self._stack_cache[key] = stack_real_trees(self.models_[t0:t1])
        return self._stack_cache[key]

    def _stacked_bins(self, t0: int, t1: int) -> BinTreeBatch:
        key = (t0, t1, self._model_version)
        if key not in self._stack_cache:
            # evict older BIN stacks only; real-space batches, forest-walk
            # tables and the model-independent devbin tables stay valid
            self._stack_cache = {
                k: v
                for k, v in self._stack_cache.items()
                if k[0] in ("real", "fw", "devbin")
            }
            self._stack_cache[key] = stack_bin_trees(
                self._bin_records[t0:t1], self.config.num_leaves
            )
        return self._stack_cache[key]

    def _predict_contrib(self, X: np.ndarray, t0: int, t1: int) -> np.ndarray:
        """SHAP values via TreeSHAP (reference: GBDT::PredictContrib ->
        Tree::PredictContrib, src/io/tree.cpp TreeSHAP path)."""
        from ..shap import predict_contrib

        return predict_contrib(self, X, t0, t1)

    # ============================================================== model IO
    def model_to_string(
        self,
        num_iteration: Optional[int] = None,
        start_iteration: int = 0,
        importance_type: Optional[str] = None,
    ) -> str:
        """Reference: GBDT::SaveModelToString (gbdt_model_text.cpp:314).

        ``importance_type`` defaults to the ``saved_feature_importance_type``
        param (reference config.h:616 / gbdt.h:169): 0 -> "split", 1 ->
        "gain"."""
        if importance_type is None:
            importance_type = (
                "gain"
                if getattr(self.config, "saved_feature_importance_type", 0)
                else "split"
            )
        t0, t1 = self._tree_range(start_iteration, num_iteration)
        lines = ["tree"]
        lines.append(f"version={_MODEL_VERSION}")
        lines.append(f"num_class={self.num_class}")
        lines.append(f"num_tree_per_iteration={self.num_tree_per_iteration}")
        lines.append(f"label_index={self.label_idx}")
        lines.append(f"max_feature_idx={self.max_feature_idx}")
        if self.objective is not None:
            lines.append(f"objective={self.objective.to_string()}")
        elif self.config.objective:
            lines.append(f"objective={self.config.objective}")
        if self.average_output:
            lines.append("average_output")
        lines.append("feature_names=" + " ".join(self.feature_names))
        lines.append("feature_infos=" + " ".join(self.feature_infos))

        tree_strs = [
            self.models_[i].to_string(i - t0) for i in range(t0, t1)
        ]
        sizes = [len(s) + 1 for s in tree_strs]  # +1: joining newline
        lines.append("tree_sizes=" + " ".join(str(s) for s in sizes))
        lines.append("")
        body = "\n".join(tree_strs)
        out = "\n".join(lines) + "\n" + body + ("\n" if body else "") + "end of trees\n"

        imp = self.feature_importance(importance_type=importance_type)
        pairs = sorted(
            [
                (imp[i], self.feature_names[i])
                for i in range(len(imp))
                if imp[i] > 0
            ],
            key=lambda p: -p[0],
        )
        out += "\nfeature_importances:\n"
        for v, name in pairs:
            # split counts print as integers (reference
            # gbdt_model_text.cpp:435 writes size_t; gain writes doubles)
            out += f"{name}={int(v) if importance_type == 'split' else v}\n"
        out += "\nparameters:\n"
        for key, val in (self.params or {}).items():
            out += f"[{key}: {val}]\n"
        out += "end of parameters\n"
        # trailing category-order record, same slot AND shape as the
        # reference model file (python-package/lightgbm/basic.py save_model
        # appends ``pandas_categorical:<json>`` after the parameters block):
        # a list-of-lists zipped positionally with the frame's categorical
        # columns — a {name: cats} dict would pass the reference loader's
        # len check and then silently NaN every category.  Internally the
        # dict is insertion-ordered by frame column, so values() IS the
        # positional order; loading accepts both forms.
        import json as _json

        cats = self.pandas_categorical
        if isinstance(cats, dict):
            cats = list(cats.values())
        out += "\npandas_categorical:%s\n" % _json.dumps(cats, default=str)
        return out

    def save_model(
        self,
        filename: str,
        num_iteration: Optional[int] = None,
        start_iteration: int = 0,
        importance_type: Optional[str] = None,
    ) -> "Booster":
        # None defers to saved_feature_importance_type (model_to_string)
        # tmp+fsync+rename: a kill mid-save leaves the previous file intact,
        # never a truncated model (resilience/checkpoint.py idiom)
        from ..resilience.checkpoint import atomic_write_text

        atomic_write_text(
            str(filename),
            self.model_to_string(num_iteration, start_iteration, importance_type),
        )
        return self

    def _load_model_string(self, s: str) -> None:
        """Reference: GBDT::LoadModelFromString (gbdt_model_text.cpp:468)."""
        # trailing category-order record; ours is a {name: values} dict, the
        # reference python package writes a list-of-lists (kept as-is and
        # zipped with the frame's categorical columns at predict time).
        # Reset first: a model string without the trailer (e.g. produced by
        # the reference CLI) must not inherit a previous model's maps.
        self.pandas_categorical = None
        for line in s.rsplit("\n", 8)[1:]:
            if line.startswith("pandas_categorical:"):
                import json as _json

                try:
                    self.pandas_categorical = _json.loads(
                        line[len("pandas_categorical:"):]
                    )
                except ValueError:
                    pass
        # parameters block round-trips (reference GBDT::LoadModelFromString
        # restores loaded_parameter_); explicitly passed ctor params win,
        # alias-aware (shrinkage_rate passed + learning_rate in the file
        # must not override each other)
        head, marker, rest = s.rpartition("\nparameters:\n")
        file_params = {}
        if marker:
            from ..config import _PARAM_ALIASES as PARAM_ALIASES

            # a RELOAD must not keep the previous file's params: only the
            # user's own (non-file) params shield against the new file
            for k in getattr(self, "_file_param_keys", ()):
                self.params.pop(k, None)
            have = {
                PARAM_ALIASES.get(str(k), str(k)) for k in self.params
            }
            for line in rest.partition("end of parameters")[0].splitlines():
                line = line.strip()
                if line.startswith("[") and line.endswith("]") and ":" in line:
                    pk, pv = line[1:-1].split(":", 1)
                    pk = pk.strip()
                    if PARAM_ALIASES.get(pk, pk) not in have:
                        file_params[pk] = pv.strip()
        self._file_param_keys = tuple(file_params)
        if marker:
            self.params.update(file_params)
            self.config = Config.from_params(self.params)
        header, _, rest = s.partition("Tree=")
        kv = {}
        for line in header.splitlines():
            line = line.strip()
            if "=" in line:
                key, v = line.split("=", 1)
                kv[key] = v
            elif line == "average_output":
                self.average_output = True
        self.num_class = int(kv.get("num_class", 1))
        self.num_tree_per_iteration = int(kv.get("num_tree_per_iteration", 1))
        self.label_idx = int(kv.get("label_index", 0))
        self.max_feature_idx = int(kv.get("max_feature_idx", 0))
        self.feature_names = kv.get("feature_names", "").split()
        self.feature_infos = kv.get("feature_infos", "").split()
        obj_str = kv.get("objective", "")
        if obj_str:
            parts = obj_str.split()
            obj_params = dict(self.params)
            obj_params["objective"] = parts[0]
            for tok in parts[1:]:
                if ":" in tok:
                    pk, pv = tok.split(":", 1)
                    obj_params[pk] = pv
                elif tok == "sqrt":
                    obj_params["reg_sqrt"] = True
            self.config = Config.from_params(obj_params)
            try:
                self.objective = create_objective(self.config)
            except ValueError:
                self.objective = None
        trees_part, _, _tail = ("Tree=" + rest).partition("end of trees")
        blocks = trees_part.split("Tree=")
        self.models_ = []
        self._bin_records = []
        for block in blocks:
            if not block.strip():
                continue
            self.models_.append(Tree.from_string(block))
        self._bump_model_version()
        self._iter = len(self.models_) // max(1, self.num_tree_per_iteration)
        # objective needs label stats for convert_output only for a few
        # objectives; predict-time convert uses config scalars, so a light
        # init with dummy labels is enough when we have no dataset.
        if self.objective is not None:
            try:
                self.objective.init(np.zeros(1), None)
            except Exception:
                pass
            self.objective.num_data = 0

    def dump_model(
        self, num_iteration: Optional[int] = None, start_iteration: int = 0
    ) -> dict:
        t0, t1 = self._tree_range(start_iteration, num_iteration)
        return {
            "name": "tree",
            "version": _MODEL_VERSION,
            "num_class": self.num_class,
            "num_tree_per_iteration": self.num_tree_per_iteration,
            "label_index": self.label_idx,
            "max_feature_idx": self.max_feature_idx,
            "objective": self.objective.to_string() if self.objective else "",
            "average_output": self.average_output,
            "feature_names": self.feature_names,
            "feature_infos": self.feature_infos,
            "tree_info": [
                {"tree_index": i - t0, **self.models_[i].to_json()}
                for i in range(t0, t1)
            ],
            "feature_importances": {
                self.feature_names[i]: float(v)
                for i, v in enumerate(self.feature_importance("split"))
                if v > 0
            },
        }

    # ============================================================ inspection
    def feature_importance(
        self, importance_type: str = "split", iteration: Optional[int] = None
    ) -> np.ndarray:
        """Reference: GBDT::FeatureImportance (gbdt_model_text.cpp:654)."""
        num_f = self.max_feature_idx + 1
        k = self.num_tree_per_iteration
        end = len(self.models_) if iteration is None or iteration <= 0 else iteration * k
        out = np.zeros(num_f)
        for tree in self.models_[:end]:
            if importance_type == "split":
                out += tree.split_counts(num_f)
            else:
                out += tree.gain_sums(num_f)
        return out

    def feature_name(self) -> List[str]:
        return list(self.feature_names)

    def model_from_string(self, model_str: str) -> "Booster":
        """Load a model from text IN PLACE (reference basic.py model_from_string)."""
        self._load_model_string(model_str)
        return self

    def shuffle_models(
        self, start_iteration: int = 0, end_iteration: int = -1
    ) -> "Booster":
        """Permute ITERATION blocks in [start, end) (reference
        GBDT::ShuffleModels, gbdt.h:89 — whole iterations move together so a
        multiclass model's per-class tree slots stay aligned; deterministic
        seed like the reference's Random(17))."""
        k = self.num_tree_per_iteration
        total_iter = len(self.models_) // k
        i0 = max(0, start_iteration)
        i1 = total_iter if end_iteration <= 0 else min(total_iter, end_iteration)
        block_perm = np.arange(i0, i1)
        np.random.default_rng(17).shuffle(block_perm)
        perm = list(range(len(self.models_)))
        for pos, src_it in enumerate(block_perm):
            for kk in range(k):
                perm[(i0 + pos) * k + kk] = src_it * k + kk
        models = self.models_
        recs = self._bin_records
        self.models_ = [models[i] for i in perm]
        if len(recs) == len(models):
            self._bin_records = [recs[i] for i in perm]
        self._bump_model_version()
        return self

    def set_train_data_name(self, name: str) -> "Booster":
        """Reference basic.py set_train_data_name."""
        self._train_data_name = name
        return self

    def set_network(
        self, machines=None, local_listen_port: int = 12400,
        listen_time_out: int = 120, num_machines: int = 1,
    ) -> "Booster":
        """Compatibility shim: the reference wires its TCP machine list here;
        the TPU-native path forms clusters via jax.distributed
        (parallel.init_distributed / parallel.launcher) instead."""
        from ..utils.log import log_warning

        if num_machines > 1:
            log_warning(
                "set_network is a no-op: use lightgbm_tpu.parallel."
                "init_distributed / the launcher for multi-host training"
            )
        return self

    def get_split_value_histogram(
        self, feature, bins=None, xgboost_style: bool = False
    ):
        """Histogram of a feature's split thresholds across the model
        (reference basic.py get_split_value_histogram)."""
        if isinstance(feature, str):
            feature = self.feature_names.index(feature)
        values = []
        for t in self.models_:
            nn = t.num_leaves - 1
            for node in range(nn):
                if int(t.split_feature[node]) == feature:
                    if t.decision_type[node] & 1:
                        raise ValueError(
                            "Cannot compute split value histogram for the "
                            "categorical feature"
                        )
                    values.append(float(t.threshold[node]))
        values = np.asarray(values)
        n_unique = len(np.unique(values))
        # reference default: one bin per unique split value; an explicit int
        # is clamped to n_unique under xgboost_style (basic.py:5123)
        if bins is None or (
            xgboost_style and isinstance(bins, int) and bins > n_unique
        ):
            bins = max(n_unique, 1)
        hist, edges = np.histogram(values, bins=bins)
        if xgboost_style:
            # reference drops zero-count bins and falls back to a numpy
            # array when pandas is unavailable (basic.py)
            ret = np.column_stack((edges[1:], hist))
            ret = ret[ret[:, 1] > 0]
            try:
                import pandas as pd  # type: ignore

                return pd.DataFrame(ret, columns=["SplitValue", "Count"])
            except ImportError:
                return ret
        return hist, edges

    def get_leaf_output(self, tree_id: int, leaf_id: int) -> float:
        """Reference: Booster.get_leaf_output (basic.py:4913)."""
        return float(self.models_[tree_id].leaf_value[leaf_id])

    def set_leaf_output(self, tree_id: int, leaf_id: int, value: float) -> "Booster":
        """Reference: Booster.set_leaf_output (LGBM_BoosterSetLeafValue)."""
        self.models_[tree_id].leaf_value[leaf_id] = value
        if tree_id < len(self._bin_records):  # loaded models keep no records
            rec = self._bin_records[tree_id]
            if rec is not None and len(rec.get("leaf_value", ())) > leaf_id:
                rec["leaf_value"][leaf_id] = value
        self._bump_model_version()
        return self

    def lower_bound(self) -> float:
        """Minimum possible model output (reference: Booster.lower_bound ->
        GBDT::GetLowerBoundValue, sum of per-tree minimum leaves)."""
        return float(
            sum(float(np.min(t.leaf_value[: t.num_leaves])) for t in self.models_)
        )

    def upper_bound(self) -> float:
        """Maximum possible model output (GBDT::GetUpperBoundValue)."""
        return float(
            sum(float(np.max(t.leaf_value[: t.num_leaves])) for t in self.models_)
        )

    def trees_to_dataframe(self):
        """Per-node model table (reference: Booster.trees_to_dataframe,
        basic.py:4060 — same column set and node naming S/L scheme)."""
        import pandas as pd  # type: ignore

        rows = []
        for ti, tree in enumerate(self.models_):
            n = tree.num_leaves
            feat_names = self.feature_names

            def node_name(idx, is_leaf):
                return f"{ti}-L{idx}" if is_leaf else f"{ti}-S{idx}"

            def emit(node, depth, parent):
                if node < 0:
                    leaf = ~node
                    rows.append(
                        {
                            "tree_index": ti,
                            "node_depth": depth,
                            "node_index": node_name(leaf, True),
                            "left_child": None,
                            "right_child": None,
                            "parent_index": parent,
                            "split_feature": None,
                            "split_gain": None,
                            "threshold": None,
                            "decision_type": None,
                            "value": float(tree.leaf_value[leaf]),
                            "weight": float(tree.leaf_weight[leaf])
                            if len(tree.leaf_weight) > leaf
                            else None,
                            "count": int(tree.leaf_count[leaf])
                            if len(tree.leaf_count) > leaf
                            else None,
                        }
                    )
                    return ()
                fidx = int(tree.split_feature[node])
                is_cat = bool(tree.decision_type[node] & 1)
                rows.append(
                    {
                        "tree_index": ti,
                        "node_depth": depth,
                        "node_index": node_name(node, False),
                        "left_child": node_name(
                            ~int(tree.left_child[node])
                            if tree.left_child[node] < 0
                            else int(tree.left_child[node]),
                            tree.left_child[node] < 0,
                        ),
                        "right_child": node_name(
                            ~int(tree.right_child[node])
                            if tree.right_child[node] < 0
                            else int(tree.right_child[node]),
                            tree.right_child[node] < 0,
                        ),
                        "parent_index": parent,
                        "split_feature": feat_names[fidx]
                        if fidx < len(feat_names)
                        else str(fidx),
                        "split_gain": float(tree.split_gain[node]),
                        "threshold": float(tree.threshold[node]),
                        "decision_type": "==" if is_cat else "<=",
                        "value": float(tree.internal_value[node])
                        if len(tree.internal_value) > node
                        else None,
                        "weight": float(tree.internal_weight[node])
                        if len(tree.internal_weight) > node
                        else None,
                        "count": int(tree.internal_count[node])
                        if len(tree.internal_count) > node
                        else None,
                    }
                )
                me = node_name(node, False)
                # children pushed right-first so the left subtree emits first
                return (
                    (int(tree.right_child[node]), depth + 1, me),
                    (int(tree.left_child[node]), depth + 1, me),
                )

            # explicit stack: leaf-wise trees can be ~num_leaves deep, past
            # Python's recursion limit
            stack = [(0 if n > 1 else ~0, 1, None)]
            while stack:
                node, depth, parent = stack.pop()
                stack.extend(emit(node, depth, parent))
        return pd.DataFrame(rows)

    def reset_parameter(self, params: Dict[str, Any]) -> "Booster":
        """Reference: Booster::ResetConfig via LGBM_BoosterResetParameter."""
        self.params.update(params)
        self.config = Config.from_params(self.params)
        self._shrinkage_rate = self.config.learning_rate
        self._finished = False
        if self.train_set is not None:
            self._setup_constraints()
            self._forced = self._build_forced_splits()
            self._setup_cegb()
            self._grower_params = self._make_grower_params()
            if self._mesh is not None:
                # the shard_map'd grower closed over the OLD params
                self._setup_sharded_grower()
        return self

    def refit(
        self,
        data,
        label,
        decay_rate: float = 0.9,
        reference: Optional[Dataset] = None,
        weight=None,
        group=None,
        init_score=None,
        feature_name="auto",
        categorical_feature="auto",
        dataset_params: Optional[Dict[str, Any]] = None,
        free_raw_data: bool = True,
        validate_features: bool = False,
        **kwargs,
    ) -> "Booster":
        """Refit leaf values on new data, keeping every tree's structure
        (reference: GBDT::RefitTree src/boosting/gbdt.cpp:266 +
        SerialTreeLearner::FitByExistingTree serial_tree_learner.cpp:250 +
        python Booster.refit basic.py:4746).

        leaf_output = decay_rate * old + (1 - decay_rate) * new, where new is
        the regularized optimal output of the leaf's gradient/hessian sums on
        the new data, times shrinkage."""
        if self.objective is None:
            raise ValueError("Cannot refit: no objective (custom-objective model)")
        from ..ops.split import leaf_output as _leaf_out

        leaf_preds = np.asarray(
            self.predict(data, pred_leaf=True, **kwargs), dtype=np.int64
        )  # [N, T]
        new_params = dict(self.params)
        new_params.update(dataset_params or {})
        new_params["refit_decay_rate"] = decay_rate
        train_set = Dataset(
            data,
            label,
            reference=reference,
            weight=weight,
            group=group,
            init_score=init_score,
            feature_name=feature_name,
            categorical_feature=categorical_feature,
            params=new_params,
            free_raw_data=free_raw_data,
        )
        nb = Booster(new_params, train_set)
        import copy as _copy

        nb.models_ = [_copy.deepcopy(t) for t in self.models_]
        k = nb.num_tree_per_iteration
        n = train_set.num_data
        cfg = nb.config
        n_iters = len(nb.models_) // k
        for it in range(n_iters):
            grad, hess = nb.objective.get_gradients(nb._score, nb._next_rng())
            g = np.asarray(grad, dtype=np.float64)[:, :n]
            h = np.asarray(hess, dtype=np.float64)[:, :n]
            for kk in range(k):
                mi = it * k + kk
                tree = nb.models_[mi]
                lp = leaf_preds[:, mi]
                nl = tree.num_leaves
                sum_g = np.bincount(lp, weights=g[kk], minlength=nl)[:nl]
                sum_h = np.bincount(lp, weights=h[kk], minlength=nl)[:nl] + 1e-15
                out = np.asarray(
                    _leaf_out(
                        jnp.asarray(sum_g),
                        jnp.asarray(sum_h),
                        cfg.lambda_l1,
                        cfg.lambda_l2,
                        cfg.max_delta_step,
                    )
                )
                new_out = out * (tree.shrinkage if tree.shrinkage else 1.0)
                tree.leaf_value = (
                    decay_rate * np.asarray(tree.leaf_value, dtype=np.float64)
                    + (1.0 - decay_rate) * new_out
                )
                # advance the new-data score with the refitted outputs
                delta = tree.leaf_value[np.minimum(lp, nl - 1)]
                nb._score = nb._score.at[kk].add(
                    self._pad_delta(delta, nb._pad_rows)
                )
        # bin-space mirrors against the NEW dataset's binning
        nb._bin_records = [nb._bin_record_from_tree(t) for t in nb.models_]
        nb._bump_model_version()
        nb._iter = n_iters
        return nb

    # ============================================================== resilience
    def _checkpoint_state(self) -> Dict[str, Any]:
        """Full trainer-state snapshot for resilience/checkpoint.py.

        Everything the update loop reads that evolves across iterations:
        host model + bin records, device score caches (train and valid),
        the RNG key, the bagging-mask cache, the adaptive leaf_batch
        EMA/cap, the fused-fallback latch, the CEGB feature-usage set, and
        telemetry counters.  Restoring this dict into a freshly constructed
        Booster over the same Dataset+params reproduces the uninterrupted
        run byte-for-byte (the kill/resume parity tests assert it).
        """
        if self.train_set is None:
            raise ValueError("checkpointing requires a training Booster")
        if getattr(self, "_multiproc", False):
            raise NotImplementedError(
                "checkpointing under multi-process feeding is not supported "
                "(scores are process-sharded); checkpoint from a "
                "single-process run"
            )
        from .sampling import BaggingStrategy

        models = self.models_  # property: drains the in-flight fetch first
        sampler_state = None
        if isinstance(self._sampler, BaggingStrategy):
            sampler_state = {"mask": np.asarray(self._sampler._mask)}
        ses = get_session()
        return {
            "format_version": 1,
            "iter": int(self._iter),
            "finished": bool(self._finished),
            "models": list(models),
            "bin_records": [dict(r) if r else r for r in self._bin_records_store],
            "score": np.asarray(self._score),
            "valid_scores": {
                e.name: np.asarray(e.score)
                for e in self._valid
                if e.score is not None
            },
            "rng": np.asarray(self._rng),
            "sampler": sampler_state,
            "commit_rate_ema": getattr(self, "_commit_rate_ema", None),
            "leaf_batch_cap": getattr(self, "_leaf_batch_cap", None),
            "grow_fused_disabled": bool(
                getattr(self, "_grow_fused_disabled", False)
            ),
            "cegb_used": (
                None if self._cegb_used is None else np.asarray(self._cegb_used)
            ),
            "shrinkage_rate": float(self._shrinkage_rate),
            "best_iteration": int(self.best_iteration),
            "num_tree_per_iteration": int(self.num_tree_per_iteration),
            "num_features": int(self._bins.shape[1]),
            "seed": self.config.seed,
            "telemetry_counters": dict(ses.counters) if ses.enabled else None,
        }

    def _restore_checkpoint_state(self, state: Dict[str, Any]) -> None:
        """Rehydrate a training Booster from a _checkpoint_state dict.

        The Booster must already be constructed over the SAME Dataset and
        params as the checkpointed run (engine.train does this before
        calling restore); cheap invariants guard against mixups."""
        if self.train_set is None:
            raise ValueError("restore requires a training Booster")
        if getattr(self, "_multiproc", False):
            raise NotImplementedError(
                "checkpoint restore under multi-process feeding is not "
                "supported"
            )
        if int(state["num_tree_per_iteration"]) != self.num_tree_per_iteration:
            raise ValueError(
                "checkpoint num_tree_per_iteration mismatch: "
                f"{state['num_tree_per_iteration']} vs "
                f"{self.num_tree_per_iteration}"
            )
        if int(state["num_features"]) != int(self._bins.shape[1]):
            raise ValueError(
                "checkpoint was taken on a different dataset "
                f"({state['num_features']} features vs {self._bins.shape[1]})"
            )
        if state.get("seed") != self.config.seed:
            raise ValueError(
                f"checkpoint seed {state.get('seed')} differs from params "
                f"seed {self.config.seed}; the RNG streams would diverge"
            )
        from .sampling import BaggingStrategy

        self._pending = None
        self._models_store = list(state["models"])
        self._bin_records_store = list(state["bin_records"])
        self._bump_model_version()
        self._iter = int(state["iter"])
        self._finished = bool(state["finished"])
        self._shrinkage_rate = float(state["shrinkage_rate"])
        self.best_iteration = int(state.get("best_iteration", -1))
        # re-place scores with the sharding _init_train chose (device_put
        # handles replicated / col-sharded / single-device alike)
        self._score = jax.device_put(
            jnp.asarray(np.asarray(state["score"], np.float32)),
            self._score.sharding,
        )
        valid_scores = state.get("valid_scores") or {}
        for e in self._valid:
            sc = valid_scores.get(e.name)
            if sc is not None and e.score is not None:
                e.score = jax.device_put(
                    jnp.asarray(np.asarray(sc, np.float32)), e.score.sharding
                )
        self._rng = jnp.asarray(np.asarray(state["rng"]))
        sampler_state = state.get("sampler")
        if sampler_state is not None:
            if not isinstance(self._sampler, BaggingStrategy):
                raise ValueError(
                    "checkpoint carries a bagging mask but bagging is not "
                    "active under the current params"
                )
            self._sampler._mask = jnp.asarray(
                np.asarray(sampler_state["mask"])
            )
        self._commit_rate_ema = state.get("commit_rate_ema")
        cap = state.get("leaf_batch_cap")
        if cap is not None:
            self._leaf_batch_cap = int(cap)
        if state.get("grow_fused_disabled"):
            self._grow_fused_disabled = True
        cegb_used = state.get("cegb_used")
        if cegb_used is not None and self._cegb_used is not None:
            self._cegb_used[:] = np.asarray(cegb_used, bool)
        # grower params depend on the restored leaf_batch cap + fused latch
        self._grower_params = self._make_grower_params()
        if self._mesh is not None:
            self._setup_sharded_grower()
        counters = state.get("telemetry_counters")
        if counters:
            get_session().restore_counters(counters)

    def merge_from(self, other: "Booster") -> "Booster":
        """Continued training from an init model (reference: GBDT
        MergeFrom/continued-training via num_init_iteration_, gbdt.h:614)."""
        if other.num_tree_per_iteration != self.num_tree_per_iteration:
            raise ValueError("init model has different num_tree_per_iteration")
        k = self.num_tree_per_iteration
        for idx, tree in enumerate(other.models_):
            self.models_.append(tree)
            rec = self._bin_record_from_tree(tree)
            self._bin_records.append(rec)
            self._bump_model_version()
            kk = idx % k
            # replay onto the train score
            self._score = self._score.at[kk].add(
                self._pad_delta(
                    tree.predict(self._train_raw_for_replay()), self._pad_rows
                )
            )
        n_init = len(other.models_) // k
        self._iter += n_init
        self._replay_rng_stream(self._iter - n_init, n_init)
        return self

    def _replay_rng_stream(self, start_iter: int, n_iters: int) -> None:
        """Advance the per-iteration RNG stream (and the bagging-mask cache)
        as if iterations [start_iter, start_iter + n_iters) had been trained.

        Continued training via init_model used to restart the key stream at
        the fold-0 position, so a 10+10 run drew different bagging masks and
        extra-trees thresholds than the uninterrupted 20-iteration run.
        Replaying the exact draw order of _update_impl — one gradient split,
        one bagging split (plus the BaggingStrategy mask refresh), then per
        trained class one quantize split and one tree split when those
        features are active — makes the continuation byte-identical.
        (Custom-fobj runs draw no gradient split and are not replayable.)
        """
        if not hasattr(self, "_rng"):
            return  # model-only booster: no live training state to sync
        from .sampling import BaggingStrategy

        cfg = self.config
        trained = (
            sum(1 for need in self._class_need_train if need)
            if self._bins.shape[1] > 0
            else 0
        )
        per_class = 0
        if cfg.use_quantized_grad:
            per_class += 1  # _quant_grow_inputs
        if cfg.feature_fraction_bynode < 1.0 or cfg.extra_trees:
            per_class += 1  # _tree_rng
        for it in range(start_iter, start_iter + n_iters):
            self._next_rng()  # objective gradients (_get_gradients)
            rng_bag = self._bagging_rng()  # row sampling (_sample)
            if isinstance(self._sampler, BaggingStrategy):
                # refresh the cached mask exactly as training would (the
                # strategy ignores grad/hess); iterations between refreshes
                # reuse it, so the resumed run starts from the right mask
                self._sampler.sample(it, None, None, rng_bag)
            for _ in range(trained * per_class):
                self._next_rng()

    def _train_raw_for_replay(self) -> np.ndarray:
        return self._raw_for_replay(self.train_set)

    def _raw_for_replay(self, ds: Dataset) -> np.ndarray:
        if ds.raw is not None:
            if hasattr(ds.raw, "toarray"):  # sparse kept via free_raw_data=False
                return np.asarray(ds.raw.toarray(), dtype=np.float64)
            return ds.raw
        # reconstruct representative values from bins (inverse binning):
        # exact for the tree decisions because thresholds are bin bounds
        cols = np.zeros((ds.num_data, ds.num_total_features))
        layout = getattr(ds, "bundle_layout", None)
        for ci, j in enumerate(ds.used_features):
            mapper = ds.bin_mappers[j]
            if layout is None:
                b = ds.bins[:, ci].astype(np.int64)
            else:
                # unpack the feature's local bins from its EFB plane column
                p, k = layout.feature_position(j)
                pb = ds.bins[:, p].astype(np.int64)
                if layout.is_bundle(p):
                    s = layout.starts[p][k]
                    w = layout.widths[p][k]
                    b = np.where((pb >= s) & (pb < s + w), pb - s + 1, 0)
                else:
                    b = pb
            if mapper.is_categorical:
                table = np.asarray(mapper.bin_to_cat, dtype=np.float64)
                table = np.concatenate([table, [np.nan]])
                cols[:, j] = table[np.minimum(b, len(table) - 1)]
            else:
                ub = np.asarray(mapper.bin_upper_bound)
                reps = np.concatenate([ub[:-1], [mapper.max_value], [np.nan]])
                cols[:, j] = reps[np.minimum(b, len(reps) - 1)]
        return cols

    def _bin_record_from_tree(self, tree: Tree) -> dict:
        """Re-express a real-valued tree in bin space for the device predictor."""
        ds = self.train_set
        layout = getattr(ds, "bundle_layout", None)
        nn = tree.num_leaves - 1
        sf_used = np.zeros(nn, dtype=np.int32)
        sbin = np.zeros(nn, dtype=np.int32)
        sic = np.zeros(nn, dtype=bool)
        cmask = np.zeros((nn, self._max_bin_padded), dtype=bool)
        orig_to_used = {j: ci for ci, j in enumerate(ds.used_features)}
        ok = True
        has_cat = False
        for t in range(nn):
            orig = int(tree.split_feature[t])
            if orig not in orig_to_used:
                ok = False
                break
            mapper = ds.bin_mappers[orig]
            if layout is not None:
                p, k = layout.feature_position(orig)
                sf_used[t] = p
                if layout.is_bundle(p) and not (tree.decision_type[t] & 1):
                    # numeric split on a bundled member -> plane-bin
                    # membership mask (left = everything except the member's
                    # bins above the threshold), mirroring training's form
                    ub = np.asarray(mapper.bin_upper_bound)
                    thr = float(tree.threshold[t])
                    tl = int(np.searchsorted(ub, thr, side="left"))
                    bval = ub[tl] if tl < len(ub) else np.inf
                    if not (
                        bval == thr
                        or abs(bval - thr) <= 1e-10 * max(1.0, abs(thr))
                    ):
                        ok = False
                        break
                    s = layout.starts[p][k]
                    w = layout.widths[p][k]
                    has_cat = True
                    sic[t] = True
                    bids = np.arange(self._max_bin_padded)
                    cmask[t] = ~((bids >= s + tl) & (bids < s + w))
                    continue
            else:
                sf_used[t] = orig_to_used[orig]
            if tree.decision_type[t] & 1:
                # categorical: map the cat_threshold value-bitset back onto
                # this dataset's bins (cat value -> bin via cat_to_bin)
                if tree.cat_boundaries is None or mapper.cat_to_bin is None:
                    ok = False
                    break
                has_cat = True
                sic[t] = True
                ci = int(tree.threshold[t])
                b0, b1 = int(tree.cat_boundaries[ci]), int(tree.cat_boundaries[ci + 1])
                for w in range(b0, b1):
                    word = int(tree.cat_threshold[w])
                    base = (w - b0) * 32
                    for bit in range(32):
                        if word >> bit & 1:
                            bn = mapper.cat_to_bin.get(base + bit)
                            if bn is None or bn >= cmask.shape[1]:
                                # category in the bitset but absent from this
                                # dataset's bins: bin space would send it
                                # right while real space sends it left
                                ok = False
                                break
                            cmask[t, bn] = True
                    if not ok:
                        break
                if not ok:
                    break
            else:
                ub = np.asarray(mapper.bin_upper_bound)
                thr = float(tree.threshold[t])
                sbin[t] = int(np.searchsorted(ub, thr, side="left"))
                # bin space is exact only when the threshold coincides with a
                # bin boundary of THIS dataset's mapper — foreign thresholds
                # (refit / continued training on re-binned data) would be
                # silently requantized otherwise
                bval = ub[sbin[t]] if sbin[t] < len(ub) else np.inf
                if not (bval == thr or abs(bval - thr) <= 1e-10 * max(1.0, abs(thr))):
                    ok = False
                    break
        if not ok:
            return {
                "split_feature": np.zeros(0, np.int32),
                "split_bin": np.zeros(0, np.int32),
                "default_left": np.zeros(0, bool),
                "left_child": np.zeros(0, np.int32),
                "right_child": np.zeros(0, np.int32),
                "leaf_value": np.asarray(tree.leaf_value, dtype=np.float32),
                "no_bin_form": True,
            }
        return {
            "split_feature": sf_used,
            "split_bin": sbin,
            "default_left": (np.asarray(tree.decision_type) & 2) != 0,
            "left_child": np.asarray(tree.left_child),
            "right_child": np.asarray(tree.right_child),
            "leaf_value": np.asarray(tree.leaf_value, dtype=np.float32),
            "split_is_cat": sic,
            "cat_mask": cmask if has_cat else np.zeros((nn, 1), bool),
        }

    def __copy__(self):
        return self

    def free_dataset(self) -> "Booster":
        return self

    def free_network(self) -> "Booster":
        return self
