"""Boosting implementations: GBDT, DART, RF + factory.

Reference analog: ``Boosting::CreateBoosting`` (src/boosting/boosting.cpp:37).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..config import Config
from ..dataset import Dataset
from .gbdt import Booster


def create_booster(params: Optional[Dict[str, Any]], train_set: Dataset) -> Booster:
    cfg = Config.from_params(params)
    boosting = cfg.boosting
    if boosting in ("dart",):
        from .dart import DARTBooster

        return DARTBooster(params, train_set)
    if boosting in ("rf", "random_forest"):
        from .rf import RFBooster

        return RFBooster(params, train_set)
    if boosting in ("gbdt", "gbrt", "goss"):
        return Booster(params, train_set)
    raise ValueError(f"unknown boosting type: {boosting!r}")


__all__ = ["Booster", "create_booster"]
