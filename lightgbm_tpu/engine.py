"""Training entry points: train() and cv().

Reference analogs: python-package/lightgbm/engine.py — ``train`` (:109, the
canonical loop: construct Booster, per-iteration callbacks + booster.update()
+ eval) and ``cv`` (:627, folds + aggregated eval).
"""

from __future__ import annotations

import copy
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from .boosting import create_booster
from .boosting.gbdt import Booster
from .callback import CallbackEnv, EarlyStopException, early_stopping, log_evaluation
from .config import Config
from .dataset import Dataset
from .obs.aggregate import global_rollup
from .obs.flight import (
    get_flight,
    install_sigterm_handler,
    uninstall_sigterm_handler,
)
from .obs.profiler import TraceWindow
from .obs.registry import get_session
from .utils.log import log_info
from .utils.timer import global_timer


def train(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    valid_sets: Optional[Union[Dataset, Sequence[Dataset]]] = None,
    valid_names: Optional[Sequence[str]] = None,
    feval: Optional[Callable] = None,
    init_model: Optional[Union[str, Booster]] = None,
    keep_training_booster: bool = False,
    callbacks: Optional[List[Callable]] = None,
    fobj: Optional[Callable] = None,
    resume_from: Optional[str] = None,
) -> Booster:
    """Train a GBDT model (reference: engine.py:109).

    ``resume_from`` (or the ``resume_from`` param) names a resilience
    checkpoint file or directory (latest checkpoint wins) written by a run
    with ``checkpoint_dir``/``checkpoint_interval`` set; the restored run
    continues the SAME RNG/score/model state, so with identical
    params+data it reproduces the uninterrupted run byte-for-byte.  Under
    resume, ``num_boost_round`` counts TOTAL iterations (the resumed run
    trains ``num_boost_round - restored_iteration`` more)."""
    # fresh per-run phase report (repeated fits would double-count otherwise)
    global_timer.reset()
    params = dict(params or {})
    cfg = Config.from_params(params)
    ses = get_session()
    if cfg.telemetry:
        ses.configure(
            enabled=True,
            sync_timing=cfg.obs_sync_timing,
            sink_path=cfg.telemetry_out,
            device_accounting=cfg.obs_device_accounting,
            measure_collectives=cfg.obs_collectives,
        )
    # distributed tracing: always-on span recorder (independent of the
    # telemetry session) — iteration/launch spans land under one train/run
    # root span, dumped via Booster.dump_trace / GET /trace / on fault
    from .obs.trace import get_tracer

    tracer = get_tracer()
    tracer.configure(
        active=cfg.trace_spans,
        capacity=cfg.trace_capacity,
        default_rate=cfg.trace_sample,
    )
    trace = (
        TraceWindow(
            cfg.profile_trace_dir,
            start_iter=cfg.profile_iter_start,
            end_iter=cfg.profile_iter_end,
        )
        if cfg.profile_trace_dir
        else None
    )
    if "num_iterations" in cfg.raw:
        num_boost_round = cfg.num_iterations
    if cfg.objective in ("none", "custom", "na", "null", "") and fobj is None:
        fobj = params.pop("_fobj", None)

    if isinstance(valid_sets, Dataset):
        valid_sets = [valid_sets]
    valid_sets = list(valid_sets or [])
    valid_names = list(valid_names or [])

    booster = create_booster(params, train_set)
    if init_model is not None:
        init_booster = (
            init_model if isinstance(init_model, Booster) else Booster(model_file=init_model)
        )
        booster.merge_from(init_booster)

    is_valid_contain_train = False
    train_data_name = "training"
    for i, vs in enumerate(valid_sets):
        name = valid_names[i] if i < len(valid_names) else f"valid_{i}"
        if vs is train_set:
            is_valid_contain_train = True
            train_data_name = name
            continue
        booster.add_valid(vs, name)

    callbacks = list(callbacks or [])
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        callbacks.append(
            early_stopping(
                cfg.early_stopping_round, cfg.first_metric_only,
                verbose=cfg.verbosity > 0,
                min_delta=cfg.early_stopping_min_delta,
            )
        )
    if cfg.verbosity > 0 and cfg.metric_freq > 0 and not any(
        getattr(cb, "order", None) == 10 and not getattr(cb, "before_iteration", False)
        for cb in callbacks
    ):
        pass  # reference prints via Log; python API requires explicit log_evaluation
    callbacks_before = [cb for cb in callbacks if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    resume_path = resume_from if resume_from is not None else (cfg.resume_from or None)
    resumed = False
    if resume_path:
        from .resilience.checkpoint import restore_checkpoint

        restore_checkpoint(booster, resume_path)
        resumed = True

    # live ops plane: opt-in Prometheus endpoint for the run's duration,
    # and a SIGTERM handler that black-boxes the flight ring (preemption
    # notice -> flight_<ts>.json next to the checkpoint dir) before dying
    exporter = None
    if cfg.obs_export_port > 0:
        from .obs.export import MetricsExporter

        exporter = MetricsExporter(
            cfg.obs_export_port, health_provider=booster.health
        )
        exporter.start()
        if cfg.verbosity >= 1:
            log_info(
                f"[obs] metrics exporter serving {exporter.url}/metrics "
                f"and {exporter.url}/healthz"
            )
    sigterm_installed = install_sigterm_handler()

    begin_iteration = booster.current_iteration()
    if resumed:
        # total-iteration semantics: the resumed run stops where the
        # uninterrupted run would have
        end_iteration = max(begin_iteration, num_boost_round)
    else:
        end_iteration = begin_iteration + num_boost_round
    evaluation_result_list: List = []
    # hoisted "no eval work" fast path: without valid sets the loop used to
    # re-derive the eval-period modulo every iteration just to call an
    # eval_valid() that returns [] — decide once, skip the block entirely
    has_eval_work = bool(is_valid_contain_train or booster._valid)
    # device-resident boosting: one compiled launch advances launch_n
    # iterations; host-boundary work below buckets to launch boundaries
    launch_n = 1
    if fobj is None:
        from .boosting.launch import resolve_launch_steps

        launch_n = resolve_launch_steps(booster, has_eval_work=has_eval_work)
        if launch_n > 1 and callbacks_before:
            from .utils.log import log_warning

            log_warning(
                "[launch] train_steps_per_launch disabled: before-iteration "
                "callbacks (e.g. reset_parameter) mutate per-iteration state "
                "the compiled scan cannot observe"
            )
            launch_n = 1
    # per-launch host overhead: wall between the end of one device dispatch
    # and the start of the next (callbacks, eval, telemetry, Python loop).
    # The sample window is bounded (long serial runs would otherwise grow
    # one float per iteration, and the list outlives train()); running
    # totals keep the whole-run average exact for bench reporting.
    booster._host_overhead_ms = deque(maxlen=128)
    booster._host_overhead_total_ms = 0.0
    booster._host_overhead_n = 0
    prev_dispatch_end: Optional[float] = None
    # root span for the whole training run: iteration/launch spans created
    # by Booster.update / LaunchRunner.run attach as children (tls stack)
    run_span = tracer.begin(
        "train/run",
        "train",
        args={
            "begin_iteration": begin_iteration,
            "end_iteration": end_iteration,
            "steps_per_launch": launch_n,
        },
        attach=True,
        ambient=True,
    )
    try:
        it = begin_iteration
        while it < end_iteration:
            for cb in callbacks_before:
                cb(
                    CallbackEnv(
                        model=booster,
                        params=params,
                        iteration=it,
                        begin_iteration=begin_iteration,
                        end_iteration=end_iteration,
                        evaluation_result_list=None,
                    )
                )
            if trace is not None:
                trace.on_iteration_start(it)
            # serial tail: a partial window would compile a second scan
            # length — fall back to one-iteration dispatches instead.
            # Alignment: windows must START on a multiple of launch_n so the
            # (it_last + 1) % period checks below land on the iterations the
            # serial loop acts on (resolve_launch_steps only guarantees
            # launch_n divides each period, not that begin_iteration is
            # aligned — an init_model or a first-round serial fallback can
            # leave `it` unaligned); one-iteration dispatches re-align it
            use_launch = (
                launch_n > 1
                and it % launch_n == 0
                and it + launch_n <= end_iteration
            )
            t_dispatch = time.perf_counter()
            if prev_dispatch_end is not None:
                host_ms = (t_dispatch - prev_dispatch_end) * 1e3
                booster._host_overhead_ms.append(host_ms)
                booster._host_overhead_total_ms += host_ms
                booster._host_overhead_n += 1
                if ses.enabled:
                    ses.set_gauge("train/host_overhead_ms", host_ms)
            with global_timer.timed("boosting/update"):
                if use_launch:
                    steps, is_finished = booster.update_launch(launch_n)
                else:
                    is_finished = booster.update(fobj=fobj)
                    steps = 1
                    if ses.enabled and launch_n > 1:
                        ses.set_gauge("train/steps_per_launch_effective", 1.0)
            prev_dispatch_end = time.perf_counter()
            it_last = it + max(1, steps) - 1
            if trace is not None:
                trace.on_iteration_end(it_last)

            # periodic model snapshot (reference GBDT::Train gbdt.cpp:258)
            sf = booster.config.snapshot_freq
            if sf > 0 and (it_last + 1) % sf == 0:
                booster.save_model(
                    f"{booster.config.output_model}.snapshot_iter_{it_last + 1}"
                )

            # resilience checkpoint: full trainer state, atomic (tmp+rename);
            # unlike the model snapshot above it captures RNG/score/sampler
            # state so the resumed run is byte-identical
            ck_dir = booster.config.checkpoint_dir
            ck_int = booster.config.checkpoint_interval
            if ck_dir and ck_int > 0 and (it_last + 1) % ck_int == 0:
                from .resilience.checkpoint import save_checkpoint

                with global_timer.timed("boosting/checkpoint"):
                    save_checkpoint(booster, ck_dir)

            evaluation_result_list = []
            if has_eval_work and (
                (it_last + 1) % max(1, booster.config.metric_freq) == 0
                or it_last + 1 == end_iteration
            ):
                with global_timer.timed("boosting/eval"):
                    if is_valid_contain_train:
                        res = booster.eval_train(feval)
                        evaluation_result_list.extend(
                            [(train_data_name, n, v, hib) for (_, n, v, hib) in res]
                        )
                    evaluation_result_list.extend(booster.eval_valid(feval))
                if ses.enabled and evaluation_result_list:
                    # lands inside the deferred iteration JSONL line
                    ses.annotate_last({
                        "eval": {
                            f"{d}/{n}": v
                            for (d, n, v, _hib) in evaluation_result_list
                        }
                    })
            for cb in callbacks_after:
                cb(
                    CallbackEnv(
                        model=booster,
                        params=params,
                        iteration=it_last,
                        begin_iteration=begin_iteration,
                        end_iteration=end_iteration,
                        evaluation_result_list=evaluation_result_list,
                    )
                )
            if is_finished:
                break
            it += max(1, steps)
    except EarlyStopException as e:
        booster.best_iteration = e.best_iteration + 1
        evaluation_result_list = e.best_score
    finally:
        if run_span is not None:
            tracer.end(run_span)
        if trace is not None:
            trace.close()
        if sigterm_installed:
            uninstall_sigterm_handler()
        if exporter is not None:
            exporter.stop()
        if ses.enabled:
            # multi-host rollup (GlobalSyncUp analog; identity on one
            # process) and one train_summary event carrying the final
            # counters/gauges for offline tools (telemetry_summary.py)
            global_rollup(ses)
            ses.record(
                {
                    "event": "train_summary",
                    "counters": dict(ses.counters),
                    "gauges": dict(ses.gauges),
                }
            )
        ses.flush_pending()
    booster.best_score = {}
    for item in evaluation_result_list or []:
        data_name, eval_name, val = item[0], item[1], item[2]
        booster.best_score.setdefault(data_name, {})[eval_name] = val
    if booster.config.verbosity >= 1:
        # per-phase wall summary (reference global_timer at shutdown,
        # utils/common.h:979)
        log_info(global_timer.summary())
        if ses.enabled:
            log_info(_deep_obs_summary(ses))
    return booster


def train_fleet(
    params_list: Union[Dict[str, Any], Sequence[Dict[str, Any]]],
    train_set: Dataset,
    num_boost_round: int = 100,
    valid_sets: Optional[Union[Dataset, Sequence[Dataset]]] = None,
    valid_names: Optional[Sequence[str]] = None,
    feval: Optional[Callable] = None,
    callbacks: Optional[List[Callable]] = None,
    row_masks: Optional[Sequence] = None,
    boosters: Optional[List[Booster]] = None,
) -> List[Booster]:
    """Train M same-shape models for far less than M runs.

    All members share the binned dataset and ONE compiled, vmapped grow
    step per tree class (boosting/fleet.py): histograms for every member
    accumulate in a single kernel launch, and under ``tree_learner=data``
    the per-member psums collapse into one stacked payload per step.
    Every member's model is byte-identical to the model its params would
    produce in a solo :func:`train` run.

    ``params_list`` is either an explicit list of per-member params dicts
    (same-shape sweeps: seeds, ``learning_rate``, bagging/GOSS fractions,
    ``extra_seed``) or ONE dict expanded to ``num_fleet`` members whose
    seeds are offset by the member index.  ``row_masks`` optionally
    restricts each member to a fixed row subset (CV folds) via
    :meth:`Booster.set_row_mask`.  ``callbacks`` are FACTORIES invoked
    once per member (stateful callbacks like ``early_stopping`` must not
    share state across members); per-member early stopping freezes that
    member while the rest of the fleet keeps training in the same warm
    executable.  ``boosters`` bypasses construction (used by ``cv``).

    Not supported in v1 (raises): custom fobj, init_model/resume,
    checkpointing, dart/rf boosting, linear trees, quantized gradients,
    CEGB, multi-process feeding.
    """
    from .boosting.fleet import FleetTrainer

    global_timer.reset()
    if boosters is None:
        if isinstance(params_list, dict):
            base = dict(params_list)
            cfg0 = Config.from_params(base)
            seed0 = cfg0.seed if cfg0.seed is not None else 0
            params_list = []
            for i in range(max(1, cfg0.num_fleet)):
                p = dict(base)
                p["seed"] = seed0 + i
                params_list.append(p)
        boosters = [create_booster(dict(p), train_set) for p in params_list]
    if row_masks is not None:
        if len(row_masks) != len(boosters):
            raise ValueError(
                f"row_masks has {len(row_masks)} entries for "
                f"{len(boosters)} fleet members"
            )
        for b, m in zip(boosters, row_masks):
            if m is not None:
                b.set_row_mask(m)

    cfg = boosters[0].config
    ses = get_session()
    if cfg.telemetry:
        ses.configure(
            enabled=True,
            sync_timing=cfg.obs_sync_timing,
            sink_path=cfg.telemetry_out,
            device_accounting=cfg.obs_device_accounting,
            measure_collectives=cfg.obs_collectives,
        )
    if "num_iterations" in cfg.raw:
        num_boost_round = cfg.num_iterations

    if isinstance(valid_sets, Dataset):
        valid_sets = [valid_sets]
    valid_sets = list(valid_sets or [])
    valid_names = list(valid_names or [])
    for b in boosters:
        for i, vs in enumerate(valid_sets):
            name = valid_names[i] if i < len(valid_names) else f"valid_{i}"
            b.add_valid(vs, name)

    # per-member callback instances: early_stopping keeps closure state,
    # so each member needs its own (factories, not shared instances)
    factories = list(callbacks or [])
    per_member_after: List[List[Callable]] = []
    for b in boosters:
        cbs = [f() for f in factories]
        bc = b.config
        if bc.early_stopping_round and bc.early_stopping_round > 0:
            cbs.append(
                early_stopping(
                    bc.early_stopping_round, bc.first_metric_only,
                    verbose=bc.verbosity > 0,
                    min_delta=bc.early_stopping_min_delta,
                )
            )
        cbs = [cb for cb in cbs if not getattr(cb, "before_iteration", False)]
        cbs.sort(key=lambda cb: getattr(cb, "order", 0))
        per_member_after.append(cbs)

    trainer = FleetTrainer(boosters)
    # device-resident boosting composed with the fleet: one compiled
    # launch advances launch_n lockstep rounds (scan-over-vmap); eval and
    # per-member early stopping bucket to launch boundaries
    from .boosting.launch import resolve_fleet_launch_steps

    launch_n = resolve_fleet_launch_steps(
        trainer, has_eval_work=any(b._valid for b in boosters)
    )
    last_eval: List[List] = [[] for _ in boosters]
    it = 0
    while it < num_boost_round:
        was_active = trainer.active_members()
        # same alignment rule as train(): a first-round serial fallback
        # (constant-tree hazard) consumes one round, so windows must wait
        # for `it` to re-align or the per-member metric_freq checks below
        # would stop landing on the serial loop's eval iterations
        use_launch = (
            launch_n > 1
            and it % launch_n == 0
            and it + launch_n <= num_boost_round
        )
        if use_launch:
            steps = trainer.update_launch(launch_n)
        else:
            trainer.update()
            steps = 1
        it_last = it + max(1, steps) - 1
        for i in was_active:
            b = boosters[i]
            evals: List = []
            if (it_last + 1) % max(1, b.config.metric_freq) == 0 or (
                it_last + 1 == num_boost_round
            ):
                with global_timer.timed("boosting/eval"):
                    evals = b.eval_valid(feval)
                if evals:
                    last_eval[i] = evals
            try:
                for cb in per_member_after[i]:
                    cb(
                        CallbackEnv(
                            model=b,
                            params=b.params,
                            iteration=it_last,
                            begin_iteration=0,
                            end_iteration=num_boost_round,
                            evaluation_result_list=evals,
                        )
                    )
            except EarlyStopException as e:
                b.best_iteration = e.best_iteration + 1
                last_eval[i] = e.best_score
                trainer.stop_member(i)
        if trainer.done():
            break
        it += max(1, steps)
    for b, evals in zip(boosters, last_eval):
        b.best_score = {}
        for item in evals or []:
            data_name, eval_name, val = item[0], item[1], item[2]
            b.best_score.setdefault(data_name, {})[eval_name] = val
    if cfg.verbosity >= 1:
        log_info(global_timer.summary())
        if ses.enabled:
            log_info(_deep_obs_summary(ses))
    return boosters


def _fmt_bytes(v: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(v) < 1024.0 or unit == "GiB":
            return f"{v:.1f} {unit}" if unit != "B" else f"{v:.0f} B"
        v /= 1024.0
    return f"{v:.1f} GiB"


def _deep_obs_summary(ses) -> str:
    """End-of-train deep-observability block next to the GlobalTimer one:
    peak HBM, analytic vs measured collective bytes, retraces by label."""
    from .obs.jit import compile_counts_by_label

    lines = ["deep observability:"]
    peak = ses.gauges.get("memory/hbm_peak_bytes")
    if peak is not None:
        lines.append(f"  peak HBM (all local devices): {_fmt_bytes(peak)}")
    else:
        lines.append(
            "  peak HBM: n/a (backend reports no memory_stats, or "
            "obs_device_accounting off)"
        )
    iters = max(1, ses.counters.get("iterations", 1))
    hist_b = ses.gauges.get("collective_hist_bytes")
    cnt_b = ses.gauges.get("collective_count_bytes")
    if hist_b is not None:
        analytic = (hist_b + (cnt_b or 0.0)) * iters
        lines.append(
            f"  collective bytes (analytic model): {_fmt_bytes(analytic)}"
        )
    measured = ses.counters.get("collective_measured_bytes_total")
    if measured is not None:
        lines.append(f"  collective bytes (measured): {_fmt_bytes(measured)}")
    by_label = compile_counts_by_label()
    if by_label:
        top = sorted(by_label.items(), key=lambda kv: -kv[1])
        lines.append(
            "  retraces by label: "
            + ", ".join(f"{k}={v}" for k, v in top)
        )
    return "\n".join(lines)


class CVBooster:
    """Container of per-fold boosters (reference: engine.py CVBooster)."""

    def __init__(self):
        self.boosters: List[Booster] = []
        self.best_iteration = -1

    def append(self, booster: Booster) -> None:
        self.boosters.append(booster)

    def __getattr__(self, name: str):
        def handler(*args, **kwargs):
            return [getattr(b, name)(*args, **kwargs) for b in self.boosters]

        return handler


def _make_n_folds(
    full_data: Dataset,
    nfold: int,
    params: Dict[str, Any],
    seed: int,
    stratified: bool,
    shuffle: bool,
    group_aware: bool = False,
):
    """Yields (train_idx, test_idx, train_group, test_group); the group
    entries are None except for ranking data, where whole QUERIES are
    assigned to folds (reference engine.py:559 group_kfold split)."""
    full_data.construct()
    num_data = full_data.num_data
    rng = np.random.default_rng(seed)
    label = full_data.get_label()
    qb = full_data.metadata.query_boundaries
    if group_aware and qb is not None:
        nq = len(qb) - 1
        if nq < nfold:
            raise ValueError(
                f"ranking cv needs at least nfold queries: have {nq} "
                f"queries for nfold={nfold}"
            )
        order = np.arange(nq)
        if shuffle:
            rng.shuffle(order)
        fold_of_query = np.zeros(nq, dtype=np.int64)
        fold_of_query[order] = np.arange(nq) % nfold
        sizes = np.diff(qb)
        row_fold = np.repeat(fold_of_query, sizes)
        for k in range(nfold):
            test_q = fold_of_query == k
            yield (
                np.nonzero(row_fold != k)[0],
                np.nonzero(row_fold == k)[0],
                sizes[~test_q],
                sizes[test_q],
            )
        return
    if stratified:
        # per-class round-robin assignment after an optional shuffle
        fold_id = np.zeros(num_data, dtype=np.int64)
        for cls in np.unique(label):
            idx = np.nonzero(label == cls)[0]
            if shuffle:
                rng.shuffle(idx)
            fold_id[idx] = np.arange(len(idx)) % nfold
    else:
        idx = np.arange(num_data)
        if shuffle:
            rng.shuffle(idx)
        fold_id = np.zeros(num_data, dtype=np.int64)
        fold_id[idx] = np.arange(num_data) % nfold
    for k in range(nfold):
        test_mask = fold_id == k
        yield np.nonzero(~test_mask)[0], np.nonzero(test_mask)[0], None, None


def _fold_groups(train_set: Dataset, fold, need_query: bool):
    """(train_group, test_group) for a user-supplied (train_idx, test_idx)
    fold: for ranking data the indices must cover whole queries; their
    per-query sizes are derived from the dataset's boundaries."""
    if not need_query:
        return None, None
    qb = train_set.metadata.query_boundaries
    if qb is None:
        return None, None
    query_of_row = np.repeat(np.arange(len(qb) - 1), np.diff(qb))

    full_sizes = np.diff(qb)

    def sizes_for(idx):
        # respect the GIVEN row order: group sizes are emitted per run of
        # consecutive same-query rows, and each run must cover its query
        # exactly (any order inside the run is fine for listwise losses)
        idx = np.asarray(idx)
        qs = query_of_row[idx]
        change = np.nonzero(np.diff(qs))[0] + 1
        bounds = np.concatenate([[0], change, [len(qs)]])
        run_q = qs[bounds[:-1]]
        run_len = np.diff(bounds)
        bad = (
            len(np.unique(run_q)) != len(run_q)
            or not np.array_equal(run_len, full_sizes[run_q])
        )
        if not bad:
            for b0, b1, q in zip(bounds[:-1], bounds[1:], run_q):
                if not np.array_equal(
                    np.sort(idx[b0:b1]), np.arange(qb[q], qb[q + 1])
                ):
                    bad = True
                    break
        if bad:
            raise ValueError(
                "ranking cv folds must contain whole queries with each "
                "query's rows consecutive; a supplied fold splits or "
                "interleaves a query"
            )
        return run_len

    return sizes_for(fold[0]), sizes_for(fold[1])


def cv(
    params: Dict[str, Any],
    train_set: Dataset,
    num_boost_round: int = 100,
    folds=None,
    nfold: int = 5,
    stratified: bool = True,
    shuffle: bool = True,
    metrics: Optional[Union[str, Sequence[str]]] = None,
    feval: Optional[Callable] = None,
    init_model=None,
    seed: int = 0,
    callbacks: Optional[List[Callable]] = None,
    eval_train_metric: bool = False,
    return_cvbooster: bool = False,
    fobj: Optional[Callable] = None,
    fleet: bool = False,
) -> Dict[str, List[float]]:
    """K-fold cross-validation (reference: engine.py:627).

    ``fleet=True`` trains all folds in lockstep through ONE vmapped grow
    executable (boosting/fleet.py): folds become per-member row masks on
    the SHARED full-data binning instead of per-fold rebuilt Datasets, so
    one batched grow per iteration replaces nfold serial grows.  Metric
    values differ slightly from the legacy loop (shared bin boundaries
    and boost_from_average computed over the full data rather than per
    fold); each fold's trained model is byte-identical to a solo
    mask-based run of that fold.  Ranking objectives and custom ``fobj``
    fall back to the legacy per-fold loop with a warning."""
    params = dict(params or {})
    if metrics is not None:
        params["metric"] = metrics
    cfg = Config.from_params(params)
    if "num_iterations" in cfg.raw:
        num_boost_round = cfg.num_iterations
    if cfg.objective not in ("binary", "multiclass", "multiclassova"):
        stratified = False

    train_set.construct()
    data_np = train_set.bins  # binned copy exists; rebuild folds from raw-ish data
    label = train_set.get_label()
    weight = train_set.get_weight()

    # folds on raw arrays: reconstruct per-fold Datasets sharing bin mappers
    from .objectives import create_objective

    _obj = create_objective(cfg)
    need_query = bool(_obj is not None and _obj.need_query)
    if folds is None:
        folds = list(
            _make_n_folds(
                train_set, nfold, params, seed, stratified, shuffle,
                group_aware=need_query,
            )
        )
    else:
        folds = [
            f if len(f) == 4 else (*f, *_fold_groups(train_set, f, need_query))
            for f in folds
        ]

    if fleet:
        if need_query or fobj is not None or init_model is not None:
            from .utils.log import log_warning

            log_warning(
                "cv(fleet=True) supports non-ranking objectives without "
                "fobj/init_model; falling back to the legacy per-fold loop"
            )
        else:
            return _cv_fleet(
                params, cfg, train_set, num_boost_round, folds, feval,
                callbacks, eval_train_metric, return_cvbooster,
            )

    cvbooster = CVBooster()
    raw = train_set.raw
    if raw is None:
        raise ValueError(
            "cv requires the training Dataset to keep raw data; construct it "
            "with free_raw_data=False"
        )
    for train_idx, test_idx, train_group, test_group in folds:
        dtrain = Dataset(
            raw[train_idx],
            label[train_idx],
            weight=None if weight is None else weight[train_idx],
            group=train_group,
            params=params,
            free_raw_data=False,
        )
        dtest = dtrain.create_valid(
            raw[test_idx],
            label[test_idx],
            weight=None if weight is None else weight[test_idx],
            group=test_group,
        )
        booster = create_booster(params, dtrain)
        booster.add_valid(dtest, "valid")
        cvbooster.append(booster)

    results: Dict[str, List[float]] = {}
    callbacks = list(callbacks or [])
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        callbacks.append(early_stopping(
            cfg.early_stopping_round, cfg.first_metric_only, verbose=False,
            min_delta=cfg.early_stopping_min_delta,
        ))
    callbacks_after = sorted(
        [cb for cb in callbacks if not getattr(cb, "before_iteration", False)],
        key=lambda cb: getattr(cb, "order", 0),
    )

    try:
        for it in range(num_boost_round):
            all_res: Dict[str, Any] = {}
            for booster in cvbooster.boosters:
                booster.update(fobj=fobj)
                res = booster.eval_valid(feval)
                if eval_train_metric:
                    res = booster.eval_train(feval) + res
                for data_name, name, val, hib in res:
                    entry = all_res.setdefault(f"{data_name} {name}", ([], hib))
                    entry[0].append(val)
            agg = []
            for key, (vals, hib) in all_res.items():
                mean = float(np.mean(vals))
                std = float(np.std(vals))
                results.setdefault(f"{key}-mean", []).append(mean)
                results.setdefault(f"{key}-stdv", []).append(std)
                agg.append(("cv_agg", key, mean, hib, std))
            for cb in callbacks_after:
                cb(
                    CallbackEnv(
                        model=cvbooster,
                        params=params,
                        iteration=it,
                        begin_iteration=0,
                        end_iteration=num_boost_round,
                        evaluation_result_list=agg,
                    )
                )
    except EarlyStopException as e:
        cvbooster.best_iteration = e.best_iteration + 1
        for key in list(results.keys()):
            results[key] = results[key][: cvbooster.best_iteration]
    if return_cvbooster:
        results["cvbooster"] = cvbooster  # type: ignore[assignment]
    return results


def _cv_fleet(
    params: Dict[str, Any],
    cfg: Config,
    train_set: Dataset,
    num_boost_round: int,
    folds,
    feval: Optional[Callable],
    callbacks: Optional[List[Callable]],
    eval_train_metric: bool,
    return_cvbooster: bool,
) -> Dict[str, List[float]]:
    """Fleet-mode cv: every fold is a row-masked member of ONE lockstep
    fleet over the shared full-data binning — one vmapped grow per
    iteration instead of nfold serial grows (see boosting/fleet.py).

    The oracle for this path is the sequential mask-based loop: training
    fold i alone with ``set_row_mask(fold_i)`` produces the byte-identical
    model (tests/test_fleet.py); the legacy rebuild-the-Dataset cv differs
    by bin boundaries, which is a documented fleet-mode trade."""
    from .boosting.fleet import FleetTrainer

    raw = train_set.raw
    if raw is None:
        raise ValueError(
            "cv requires the training Dataset to keep raw data; construct it "
            "with free_raw_data=False"
        )
    label = train_set.get_label()
    weight = train_set.get_weight()
    n = train_set.num_data
    cvbooster = CVBooster()
    for train_idx, test_idx, _tg, _ttg in folds:
        booster = create_booster(params, train_set)
        mask = np.zeros(n, np.float32)
        mask[np.asarray(train_idx)] = 1.0
        booster.set_row_mask(mask)
        dtest = train_set.create_valid(
            raw[test_idx],
            label[test_idx],
            weight=None if weight is None else weight[test_idx],
        )
        booster.add_valid(dtest, "valid")
        cvbooster.append(booster)

    results: Dict[str, List[float]] = {}
    callbacks = list(callbacks or [])
    if cfg.early_stopping_round and cfg.early_stopping_round > 0:
        callbacks.append(early_stopping(
            cfg.early_stopping_round, cfg.first_metric_only, verbose=False,
            min_delta=cfg.early_stopping_min_delta,
        ))
    callbacks_after = sorted(
        [cb for cb in callbacks if not getattr(cb, "before_iteration", False)],
        key=lambda cb: getattr(cb, "order", 0),
    )

    trainer = FleetTrainer(cvbooster.boosters)
    try:
        for it in range(num_boost_round):
            trainer.update()
            all_res: Dict[str, Any] = {}
            for booster in cvbooster.boosters:
                res = booster.eval_valid(feval)
                if eval_train_metric:
                    res = booster.eval_train(feval) + res
                for data_name, name, val, hib in res:
                    entry = all_res.setdefault(f"{data_name} {name}", ([], hib))
                    entry[0].append(val)
            agg = []
            for key, (vals, hib) in all_res.items():
                mean = float(np.mean(vals))
                std = float(np.std(vals))
                results.setdefault(f"{key}-mean", []).append(mean)
                results.setdefault(f"{key}-stdv", []).append(std)
                agg.append(("cv_agg", key, mean, hib, std))
            for cb in callbacks_after:
                cb(
                    CallbackEnv(
                        model=cvbooster,
                        params=params,
                        iteration=it,
                        begin_iteration=0,
                        end_iteration=num_boost_round,
                        evaluation_result_list=agg,
                    )
                )
            if trainer.done():
                break
    except EarlyStopException as e:
        cvbooster.best_iteration = e.best_iteration + 1
        for key in list(results.keys()):
            results[key] = results[key][: cvbooster.best_iteration]
    if return_cvbooster:
        results["cvbooster"] = cvbooster  # type: ignore[assignment]
    return results
