"""Quantile binning: raw feature values -> small integer bins.

Reference analog: ``BinMapper`` (include/LightGBM/bin.h:85, src/io/bin.cpp
GreedyFindBin / FindBinWithZeroAsOneBin).  Host-side NumPy, run once at
Dataset construction; the result is a dense ``uint8``/``uint16``
``[num_rows, num_features]`` device array — the TPU-native replacement for
the reference's per-feature Bin column stores (dense_bin.hpp/sparse_bin.hpp).

Semantics kept from the reference:
  * equal-count greedy bins from a row sample, bin boundary = midpoint
    between adjacent distinct values;
  * zero gets its own bin (the [-kZeroThreshold, kZeroThreshold) band);
  * missing handling: MissingType None/Zero/NaN; NaN gets a dedicated last
    bin when ``use_missing`` and NaNs are present; ``zero_as_missing`` folds
    zeros into the missing bin;
  * categorical features are binned by descending frequency, cut at 99% of
    total count and at ``max_bin`` categories;
  * features with a single effective bin are marked trivial and dropped from
    training.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

K_ZERO_THRESHOLD = 1e-35
_EPS = 1e-300


class MissingType:
    NONE = 0
    ZERO = 1
    NAN = 2


def _greedy_find_bin(
    distinct_values: np.ndarray,
    counts: np.ndarray,
    max_bin: int,
    total_sample_cnt: int,
    min_data_in_bin: int,
) -> List[float]:
    """Equal-count greedy binning over sorted distinct values.

    Returns the list of bin upper bounds (last is +inf).  The native C++
    loop (native/binning.cpp greedy_find_bin — the reference's C++
    GreedyFindBin analog, src/io/bin.cpp) runs when available; the Python
    fallback below is operation-identical.
    """
    n = len(distinct_values)
    if n == 0:
        return []
    if n > 4096:  # native pays off past a few thousand distincts
        try:
            from .native import load_native

            lib = load_native()
        except Exception:  # pragma: no cover
            lib = None
        if lib is not None:
            dv = np.ascontiguousarray(distinct_values, dtype=np.float64)
            ct = np.ascontiguousarray(counts, dtype=np.float64)
            out = np.empty(max(max_bin, 1), np.float64)
            nb = lib.greedy_find_bin(
                dv.ctypes.data, ct.ctypes.data, n, int(max_bin),
                float(total_sample_cnt), float(min_data_in_bin),
                out.ctypes.data,
            )
            return list(out[:nb]) + [np.inf]
    if n <= max_bin:
        # every distinct value its own bin, but honor min_data_in_bin
        bounds: List[float] = []
        cur_cnt = 0
        for i in range(n - 1):
            cur_cnt += counts[i]
            if cur_cnt >= min_data_in_bin or max_bin >= n:
                bounds.append((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                cur_cnt = 0
        bounds.append(np.inf)
        return bounds

    # more distinct values than bins: greedy equal-count with heavy values
    # forced into their own bin (reference GreedyFindBin's is_big_count_value)
    max_bin = max(1, max_bin)
    mean_bin_size = total_sample_cnt / max_bin
    is_big = counts >= mean_bin_size
    # suffix counts of heavy values so the rebudget branch is O(1)
    big_suffix = np.concatenate(
        [np.cumsum(is_big[::-1])[::-1], np.zeros(1, np.int64)]
    )
    rest_cnt = total_sample_cnt - counts[is_big].sum()
    rest_bins = max_bin - int(is_big.sum())
    if rest_bins > 0:
        mean_bin_size = rest_cnt / rest_bins
    bounds = []
    cur_cnt = 0
    remaining_bins = max_bin
    for i in range(n - 1):
        if not is_big[i]:
            rest_cnt -= counts[i]
        cur_cnt += counts[i]
        # close the bin if it is full enough, or the next value is heavy
        if (
            is_big[i]
            or cur_cnt >= mean_bin_size
            or (is_big[i + 1] and cur_cnt >= max(1.0, mean_bin_size * 0.5))
        ):
            bounds.append((distinct_values[i] + distinct_values[i + 1]) / 2.0)
            cur_cnt = 0
            remaining_bins -= 1
            if remaining_bins <= 1:
                break
            if not is_big[i] and rest_bins > 0:
                rest_bins_left = remaining_bins - int(big_suffix[i + 1])
                if rest_bins_left > 0:
                    mean_bin_size = max(1.0, rest_cnt / rest_bins_left)
    bounds.append(np.inf)
    return bounds


def _find_bin_zero_as_one(
    values: np.ndarray,
    counts_total: int,
    max_bin: int,
    min_data_in_bin: int,
) -> List[float]:
    """Numerical binning with zero forced into its own bin.

    Reference: FindBinWithZeroAsOneBin (src/io/bin.cpp) — negatives and
    positives are binned separately with bin budget split proportionally,
    and the zero band [-kZeroThreshold, kZeroThreshold] forms one bin.
    """
    values = values[np.isfinite(values)]
    neg = values[values < -K_ZERO_THRESHOLD]
    pos = values[values > K_ZERO_THRESHOLD]
    n_zero = counts_total - len(neg) - len(pos)
    n_total = counts_total
    if n_total == 0:
        return [np.inf]

    budget = max_bin - 1  # one bin reserved for zero
    n_neg, n_pos = len(neg), len(pos)
    nonzero = n_neg + n_pos
    if nonzero == 0:
        return [np.inf]
    neg_bins = int(round(budget * (n_neg / n_total))) if n_neg > 0 else 0
    if n_neg > 0:
        neg_bins = max(1, neg_bins)
    pos_bins = budget - neg_bins
    if n_pos > 0:
        pos_bins = max(1, pos_bins)

    bounds: List[float] = []
    if n_neg > 0:
        dv, cnt = np.unique(neg, return_counts=True)
        b = _greedy_find_bin(dv, cnt, max(1, neg_bins), n_neg, min_data_in_bin)
        # last bound of the negative side closes at the zero band
        if b:
            b[-1] = -K_ZERO_THRESHOLD
            bounds.extend(b)
        else:
            bounds.append(-K_ZERO_THRESHOLD)
    if n_zero > 0 or (n_neg > 0 and n_pos > 0):
        bounds.append(K_ZERO_THRESHOLD)
    if n_pos > 0:
        dv, cnt = np.unique(pos, return_counts=True)
        b = _greedy_find_bin(dv, cnt, max(1, pos_bins), n_pos, min_data_in_bin)
        bounds.extend(b)
    if not bounds or bounds[-1] != np.inf:
        bounds.append(np.inf)
    # dedupe while preserving order
    out: List[float] = []
    for x in bounds:
        if not out or x > out[-1]:
            out.append(x)
    return out


def _find_bin_forced(
    values: np.ndarray,
    counts_total: int,
    max_bin: int,
    min_data_in_bin: int,
    forced: List[float],
) -> List[float]:
    """Numerical binning honoring user-forced bin upper bounds.

    Reference: FindBinWithPredefinedBin (src/io/bin.cpp:161-244) — seed the
    bound list with the zero-band bounds plus the forced bounds (capped at
    max_bin), then spread the remaining bin budget across the regions
    BETWEEN consecutive seeded bounds proportionally to their sample mass,
    greedy-binning each region independently.
    """
    values = values[np.isfinite(values)]
    dv, cnt = np.unique(values, return_counts=True)
    n = len(dv)
    n_zero = counts_total - len(values)
    if n_zero > 0:
        # implied zeros from sparse inputs join the distinct-value list
        zi = int(np.searchsorted(dv, 0.0))
        if zi < n and dv[zi] == 0.0:
            cnt[zi] += n_zero
        else:
            dv = np.insert(dv, zi, 0.0)
            cnt = np.insert(cnt, zi, n_zero)
            n += 1
    if n == 0:
        return [np.inf]

    # zero-band bounds (bin.cpp:168-200)
    left_cnt = int(np.searchsorted(dv, -K_ZERO_THRESHOLD, side="right"))
    right_start = int(np.searchsorted(dv, K_ZERO_THRESHOLD, side="right"))
    has_right = right_start < n
    bounds: List[float] = []
    if max_bin == 2:
        bounds.append(K_ZERO_THRESHOLD if left_cnt == 0 else -K_ZERO_THRESHOLD)
    elif max_bin >= 3:
        if left_cnt > 0:
            bounds.append(-K_ZERO_THRESHOLD)
        if has_right:
            bounds.append(K_ZERO_THRESHOLD)
    bounds.append(np.inf)

    # forced bounds, zeros excluded, capped at the remaining budget
    max_to_insert = max_bin - len(bounds)
    inserted = 0
    for b in forced:
        if inserted >= max_to_insert:
            break
        if abs(b) > K_ZERO_THRESHOLD:
            bounds.append(float(b))
            inserted += 1
    bounds.sort()

    # spread the remaining budget across inter-bound regions (bin.cpp:218)
    free_bins = max_bin - len(bounds)
    to_add: List[float] = []
    value_ind = 0
    total = max(1, counts_total)
    for i, ub in enumerate(bounds):
        start = value_ind
        cnt_in_bin = 0
        while value_ind < n and dv[value_ind] < ub:
            cnt_in_bin += int(cnt[value_ind])
            value_ind += 1
        remaining = max_bin - len(bounds) - len(to_add)
        if i == len(bounds) - 1:
            sub = remaining + 1
        else:
            sub = min(int(round(cnt_in_bin * free_bins / total)), remaining) + 1
        if sub > 1 and value_ind > start:
            new_b = _greedy_find_bin(
                dv[start:value_ind], cnt[start:value_ind], sub,
                cnt_in_bin, min_data_in_bin,
            )
            to_add.extend(new_b[:-1])  # last bound is +inf
    bounds.extend(to_add)
    bounds.sort()
    # dedupe while preserving order
    out: List[float] = []
    for x in bounds:
        if not out or x > out[-1]:
            out.append(x)
    return out


@dataclasses.dataclass
class BinMapper:
    """Per-feature value->bin mapping (reference: include/LightGBM/bin.h:85)."""

    bin_upper_bound: np.ndarray  # [num_numeric_bins] float64, last == +inf
    is_categorical: bool = False
    missing_type: int = MissingType.NONE
    num_bins: int = 1  # total bins incl. NaN bin if present
    nan_bin: int = -1  # bin index NaN maps to, -1 if none
    cat_to_bin: Optional[Dict[int, int]] = None
    bin_to_cat: Optional[np.ndarray] = None
    min_value: float = 0.0
    max_value: float = 0.0
    default_bin: int = 0  # bin of value 0.0 (reference default_bin concept)

    @property
    def is_trivial(self) -> bool:
        return self.num_bins <= 1

    # ---------------------------------------------------------------- build
    @classmethod
    def from_sample(
        cls,
        values: np.ndarray,
        max_bin: int,
        *,
        is_categorical: bool = False,
        min_data_in_bin: int = 3,
        use_missing: bool = True,
        zero_as_missing: bool = False,
        total_cnt: Optional[int] = None,
        forced_bounds: Optional[List[float]] = None,
    ) -> "BinMapper":
        values = np.asarray(values, dtype=np.float64).ravel()
        total_cnt = int(total_cnt if total_cnt is not None else len(values))
        nan_mask = np.isnan(values)
        has_nan = bool(nan_mask.any())
        finite = values[~nan_mask]

        if is_categorical:
            return cls._from_sample_categorical(
                finite, max_bin, has_nan and use_missing, min_data_in_bin
            )

        if zero_as_missing:
            missing_type = MissingType.ZERO if use_missing else MissingType.NONE
        elif has_nan and use_missing:
            missing_type = MissingType.NAN
        else:
            missing_type = MissingType.NONE

        if len(finite) == 0:
            if has_nan and use_missing:
                return cls(
                    bin_upper_bound=np.array([np.inf]),
                    missing_type=MissingType.NAN,
                    num_bins=2,
                    nan_bin=1,
                )
            return cls(bin_upper_bound=np.array([np.inf]), num_bins=1)

        if forced_bounds:
            # user-forced upper bounds replace the greedy split entirely —
            # including under zero_as_missing (reference: MissingType::Zero
            # also routes through FindBinWithZeroAsOneBin's forced overload,
            # bin.cpp:304-312/:386; the zero/missing bin mapping below is
            # unchanged)
            bounds = _find_bin_forced(
                finite, total_cnt - int(nan_mask.sum()), max_bin,
                min_data_in_bin, forced_bounds,
            )
        elif zero_as_missing:
            # zeros are folded into the missing bin: bin the nonzero values,
            # missing bin appended at the end
            nonzero = finite[np.abs(finite) > K_ZERO_THRESHOLD]
            if len(nonzero) == 0:
                bounds = [np.inf]
            else:
                dv, cnt = np.unique(nonzero, return_counts=True)
                bounds = _greedy_find_bin(dv, cnt, max_bin - 1, len(nonzero), min_data_in_bin)
        else:
            # total_cnt may exceed len(values) for sparse inputs: the
            # difference is an implied count of zeros (sparse_bin.hpp loaders
            # never materialize them)
            bounds = _find_bin_zero_as_one(
                finite, total_cnt - int(nan_mask.sum()), max_bin, min_data_in_bin
            )

        num_numeric = len(bounds)
        nan_bin = -1
        num_bins = num_numeric
        if missing_type == MissingType.NAN or missing_type == MissingType.ZERO:
            nan_bin = num_numeric
            num_bins = num_numeric + 1

        ub = np.asarray(bounds, dtype=np.float64)
        default_bin = int(np.searchsorted(ub, 0.0, side="left"))
        if missing_type == MissingType.ZERO:
            default_bin = nan_bin
        return cls(
            bin_upper_bound=ub,
            missing_type=missing_type,
            num_bins=num_bins,
            nan_bin=nan_bin,
            min_value=float(finite.min()),
            max_value=float(finite.max()),
            default_bin=default_bin,
        )

    @classmethod
    def _from_sample_categorical(
        cls, finite: np.ndarray, max_bin: int, has_nan_bin: bool, min_data_in_bin: int
    ) -> "BinMapper":
        cats = finite.astype(np.int64)
        if len(cats) == 0:
            return cls(bin_upper_bound=np.array([np.inf]), is_categorical=True, num_bins=1)
        if cats.min() < 0:
            raise ValueError("categorical feature values must be non-negative")
        uniq, cnt = np.unique(cats, return_counts=True)
        order = np.argsort(-cnt, kind="stable")
        uniq, cnt = uniq[order], cnt[order]
        # cut at 99% of total count and max_bin categories (reference bin.cpp)
        cutoff = 0.99 * cnt.sum()
        keep = min(len(uniq), max_bin - (1 if has_nan_bin else 0))
        csum = np.cumsum(cnt)
        while keep > 1 and csum[keep - 1] - cnt[keep - 1] >= cutoff:
            keep -= 1
        uniq = uniq[:keep]
        cat_to_bin = {int(c): i for i, c in enumerate(uniq)}
        num_bins = keep
        nan_bin = -1
        if has_nan_bin:
            nan_bin = num_bins
            num_bins += 1
        return cls(
            bin_upper_bound=np.array([np.inf]),
            is_categorical=True,
            missing_type=MissingType.NAN if has_nan_bin else MissingType.NONE,
            num_bins=num_bins,
            nan_bin=nan_bin,
            cat_to_bin=cat_to_bin,
            bin_to_cat=uniq.copy(),
            min_value=float(uniq.min()),
            max_value=float(uniq.max()),
        )

    # ------------------------------------------------------------- mapping
    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized value->bin (reference BinMapper::ValueToBin bin.h:173)."""
        values = np.asarray(values, dtype=np.float64).ravel()
        if self.is_categorical:
            out = np.zeros(len(values), dtype=np.int32)
            nan_mask = np.isnan(values)
            iv = np.where(nan_mask, 0, values).astype(np.int64)
            if self.bin_to_cat is not None and len(self.bin_to_cat):
                sorter = np.argsort(self.bin_to_cat)
                sorted_cats = self.bin_to_cat[sorter]
                pos = np.searchsorted(sorted_cats, iv)
                pos = np.clip(pos, 0, len(sorted_cats) - 1)
                found = sorted_cats[pos] == iv
                out = np.where(found, sorter[pos], 0).astype(np.int32)
            if self.nan_bin >= 0:
                out[nan_mask] = self.nan_bin
            return out

        out = self._values_to_bins_native(values)
        if out is not None:
            return out
        nan_mask = np.isnan(values)
        if self.missing_type == MissingType.ZERO:
            miss = nan_mask | (np.abs(values) <= K_ZERO_THRESHOLD)
            safe = np.where(nan_mask, 0.0, values)
            out = np.searchsorted(self.bin_upper_bound, safe, side="left").astype(np.int32)
            out[miss] = self.nan_bin
            return out
        safe = np.where(nan_mask, 0.0, values)
        out = np.searchsorted(self.bin_upper_bound, safe, side="left").astype(np.int32)
        if self.missing_type == MissingType.NAN and self.nan_bin >= 0:
            out[nan_mask] = self.nan_bin
        return out

    def _values_to_bins_native(self, values: np.ndarray):
        """OpenMP binning for large numeric columns (native/binning.cpp —
        the reference's C++ DenseBin::Push ingestion analog). None when the
        native library is unavailable or the column is small.  Even
        single-core the fused loop beats NumPy's multi-pass form (~1.3x
        measured); multi-core hosts get the full OpenMP speedup."""
        if len(values) < 65536:
            return None
        try:
            from .native import load_native
        except Exception:  # pragma: no cover
            return None
        lib = load_native()
        if lib is None:
            return None
        vals = np.ascontiguousarray(values, dtype=np.float64)
        ub = np.ascontiguousarray(self.bin_upper_bound, dtype=np.float64)
        out = np.empty(len(vals), dtype=np.int32)
        lib.bin_numeric_f64(
            vals.ctypes.data,
            len(vals),
            ub.ctypes.data,
            len(ub),
            int(self.missing_type),
            int(self.nan_bin),
            K_ZERO_THRESHOLD,
            out.ctypes.data,
        )
        return out

    def bin_to_threshold(self, bin_idx: int) -> float:
        """Real-valued split threshold for 'bin <= bin_idx goes left'."""
        if self.is_categorical:
            raise ValueError("categorical bins have no scalar threshold")
        b = int(bin_idx)
        if b >= len(self.bin_upper_bound) - 1:
            return float(self.bin_upper_bound[-2]) if len(self.bin_upper_bound) > 1 else 0.0
        return float(self.bin_upper_bound[b])

    def feature_info_str(self) -> str:
        """feature_infos entry for the model file (reference dataset.cpp)."""
        if self.is_trivial:
            return "none"
        if self.is_categorical:
            cats = sorted(int(c) for c in (self.bin_to_cat if self.bin_to_cat is not None else []))
            return ":".join(str(c) for c in cats)
        return f"[{self.min_value:g}:{self.max_value:g}]"

    # ------------------------------------------------- distributed transport
    # (reference: DatasetLoader::ConstructBinMappersFromTextData syncs
    # per-rank BinMappers over the network via CopyTo/CopyFrom byte buffers,
    # src/io/dataset_loader.cpp:1079 + bin.cpp SizesInByte; here the wire is
    # a fixed-width float64 vector so process_allgather can carry it)

    def to_vector(self, width: int) -> np.ndarray:
        """Serialize into a fixed-width float64 vector."""
        ub = np.asarray(self.bin_upper_bound, dtype=np.float64)
        cats = (
            np.asarray(self.bin_to_cat, dtype=np.float64)
            if self.bin_to_cat is not None
            else np.zeros((0,), np.float64)
        )
        head = np.array(
            [
                self.num_bins,
                1.0 if self.is_categorical else 0.0,
                float(self.missing_type),
                float(self.nan_bin),
                self.min_value,
                self.max_value,
                float(self.default_bin),
                float(len(ub)),
                float(len(cats)),
            ],
            dtype=np.float64,
        )
        out = np.zeros((width,), np.float64)
        vec = np.concatenate([head, ub, cats])
        if len(vec) > width:
            raise ValueError(f"mapper needs {len(vec)} slots, width={width}")
        out[: len(vec)] = vec
        return out

    @classmethod
    def from_vector(cls, vec: np.ndarray) -> "BinMapper":
        n_ub = int(vec[7])
        n_cat = int(vec[8])
        ub = np.asarray(vec[9 : 9 + n_ub], dtype=np.float64)
        cats = vec[9 + n_ub : 9 + n_ub + n_cat].astype(np.int64)
        bin_to_cat = cats if n_cat else None
        return cls(
            bin_upper_bound=ub,
            is_categorical=bool(vec[1]),
            missing_type=int(vec[2]),
            num_bins=int(vec[0]),
            nan_bin=int(vec[3]),
            cat_to_bin={int(c): i for i, c in enumerate(cats)} if n_cat else None,
            bin_to_cat=bin_to_cat,
            min_value=float(vec[4]),
            max_value=float(vec[5]),
            default_bin=int(vec[6]),
        )
