"""Metric-gated continual refresh: refit on traffic, promote via hot-swap.

The loop accumulates observed traffic ``(X, y)`` pairs (the serving
front end feeds them in as labels arrive), and on each cycle:

1. snapshots the buffer (observation continues concurrently);
2. builds a candidate from the LIVE model — ``Booster.refit`` (leaf-value
   refresh keeping tree structure, the cheap path) or an ``init_model``
   training continuation (``mode="extend"``, byte-exact per PR 7);
3. gates promotion on a held-in metric over the accumulated batch: the
   candidate must not score worse than the live model by more than
   ``tolerance``;
4. on promotion, writes the durable artifact via the atomic
   ``save_model`` (tmp+fsync+rename — a kill mid-save never corrupts the
   previous artifact), then cuts over through the registry's hot-swap so
   in-flight requests keep their generation.

Every promotion/rejection lands in the flight recorder's sticky deque and
the ``serve/promotions_*`` counters.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..obs.flight import get_flight
from ..obs.registry import get_session
from ..obs.trace import get_tracer
from .registry import ModelRegistry


def _score(booster, X: np.ndarray, y: np.ndarray, metric: str) -> float:
    """Lower-is-better score of ``booster`` on ``(X, y)``."""
    preds = np.asarray(booster.predict(X))
    y = np.asarray(y, dtype=np.float64)
    if metric == "l2":
        return float(np.mean((preds - y) ** 2))
    if metric == "l1":
        return float(np.mean(np.abs(preds - y)))
    if metric == "binary_logloss":
        p = np.clip(preds, 1e-15, 1 - 1e-15)
        return float(-np.mean(y * np.log(p) + (1 - y) * np.log(1 - p)))
    if metric == "binary_error":
        return float(np.mean((preds > 0.5).astype(np.float64) != y))
    raise ValueError(
        f"unknown refresh metric '{metric}' "
        "(expected l2, l1, binary_logloss, binary_error, or a callable)"
    )


class RefreshLoop:
    """Accumulate traffic, refit the live model, promote when not worse."""

    def __init__(
        self,
        registry: ModelRegistry,
        model_id: str,
        *,
        min_rows: int = 256,
        decay_rate: float = 0.9,
        metric: Any = "l2",
        tolerance: float = 0.0,
        save_path: str = "",
        mode: str = "refit",
        extend_rounds: int = 10,
        interval_s: float = 0.0,
    ) -> None:
        if mode not in ("refit", "extend"):
            raise ValueError("mode must be 'refit' or 'extend'")
        self.registry = registry
        self.model_id = model_id
        self.min_rows = int(min_rows)
        self.decay_rate = float(decay_rate)
        self.metric = metric
        self.tolerance = float(tolerance)
        self.save_path = save_path
        self.mode = mode
        self.extend_rounds = int(extend_rounds)
        self.interval_s = float(interval_s)
        self._lock = threading.Lock()
        self._buf_X: List[np.ndarray] = []
        self._buf_y: List[np.ndarray] = []
        self._buf_rows = 0
        self._thread: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        self.promotions = 0
        self.rejections = 0
        self.last_report: Dict[str, Any] = {}

    # ------------------------------------------------------------- traffic
    def observe(self, X: np.ndarray, y: np.ndarray) -> None:
        """Feed labeled traffic into the refresh buffer."""
        X = np.asarray(X)
        if X.ndim == 1:
            X = X[None, :]
        y = np.asarray(y).reshape(-1)
        if X.shape[0] != y.shape[0]:
            raise ValueError(
                f"rows/labels mismatch: {X.shape[0]} vs {y.shape[0]}"
            )
        with self._lock:
            self._buf_X.append(X)
            self._buf_y.append(y)
            self._buf_rows += X.shape[0]

    def buffered_rows(self) -> int:
        with self._lock:
            return self._buf_rows

    def _take_buffer(self) -> Tuple[Optional[np.ndarray], Optional[np.ndarray]]:
        with self._lock:
            if self._buf_rows < self.min_rows:
                return None, None
            X = np.concatenate(self._buf_X, axis=0)
            y = np.concatenate(self._buf_y, axis=0)
            self._buf_X, self._buf_y, self._buf_rows = [], [], 0
            return X, y

    # -------------------------------------------------------------- cycle
    def run_once(self) -> Dict[str, Any]:
        """One refresh cycle; returns a report dict (also kept as
        ``last_report``)."""
        X, y = self._take_buffer()
        if X is None:
            report = {
                "promoted": False,
                "reason": "insufficient_rows",
                "buffered_rows": self.buffered_rows(),
                "min_rows": self.min_rows,
            }
            self.last_report = report
            return report
        base = self.registry.booster(self.model_id)
        tracer = get_tracer()
        # refit-cycle span: candidate build + metric gate (the promotion's
        # swap_warm/swap_flip spans land separately via registry.hot_swap)
        with tracer.span(
            "lifecycle/refresh_cycle",
            "lifecycle",
            args={
                "model_id": self.model_id,
                "mode": self.mode,
                "rows": int(X.shape[0]),
            },
        ):
            if self.mode == "refit":
                candidate = base.refit(X, y, decay_rate=self.decay_rate)
            else:
                from .. import engine
                from ..dataset import Dataset

                candidate = engine.train(
                    dict(base.params),
                    Dataset(X, y),
                    num_boost_round=self.extend_rounds,
                    init_model=base,
                )
            if callable(self.metric):
                metric_name = getattr(self.metric, "__name__", "custom")
                base_score = float(self.metric(base, X, y))
                cand_score = float(self.metric(candidate, X, y))
            else:
                metric_name = self.metric
                base_score = _score(base, X, y, self.metric)
                cand_score = _score(candidate, X, y, self.metric)
        promote = cand_score <= base_score + self.tolerance
        report = {
            "promoted": promote,
            "mode": self.mode,
            "rows": int(X.shape[0]),
            "metric": metric_name,
            "base_score": base_score,
            "candidate_score": cand_score,
            "tolerance": self.tolerance,
        }
        ses = get_session()
        if promote:
            if self.save_path:
                candidate.save_model(self.save_path)
                report["artifact"] = self.save_path
            entry = self.registry.hot_swap(self.model_id, candidate)
            report["version"] = entry.version
            report["generation"] = entry.generation
            self.promotions += 1
            if ses.enabled:
                ses.inc("serve/promotions_total")
                ses.set_gauge(
                    "serve/last_promotion_gain", base_score - cand_score
                )
            tracer.instant(
                "lifecycle/refresh_promote",
                "lifecycle",
                args={
                    "model_id": self.model_id,
                    "version": report["version"],
                    "metric": metric_name,
                    "base_score": base_score,
                    "candidate_score": cand_score,
                },
            )
            get_flight().note_sticky(
                {"event": "serve_promotion", "model_id": self.model_id, **report}
            )
        else:
            self.rejections += 1
            if ses.enabled:
                ses.inc("serve/promotions_rejected_total")
            tracer.instant(
                "lifecycle/refresh_reject",
                "lifecycle",
                args={
                    "model_id": self.model_id,
                    "metric": metric_name,
                    "base_score": base_score,
                    "candidate_score": cand_score,
                },
            )
            get_flight().note_sticky(
                {
                    "event": "serve_promotion_rejected",
                    "model_id": self.model_id,
                    **report,
                }
            )
        self.last_report = report
        return report

    # --------------------------------------------------------- background
    def start(self) -> None:
        """Run :meth:`run_once` every ``interval_s`` seconds until stop."""
        if self.interval_s <= 0:
            raise ValueError("start() requires interval_s > 0")
        if self._thread is not None:
            return
        self._stop_event.clear()

        def loop():
            while not self._stop_event.wait(self.interval_s):
                try:
                    self.run_once()
                except Exception:
                    # a failed cycle (e.g. injected swap fault) must not
                    # kill the refresh thread; the registry already dumped
                    if get_session().enabled:
                        get_session().inc("serve/refresh_errors_total")

        self._thread = threading.Thread(
            target=loop, name=f"lgbtpu-refresh-{self.model_id}", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop_event.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10.0)
