"""Async micro-batcher: coalesce small predict requests into ladder chunks.

The serving plane's latency/throughput trade lives here.  Requests (any
row count, one feature-space matrix each) enter a queue; a single worker
thread coalesces them into batches under two flush triggers:

* **bucket-full** — accumulated rows reached ``max_batch`` (adding the
  next request would overflow it);
* **deadline** — the oldest queued request has waited ``deadline_ms``.

Every dispatched matrix is padded to a ``bucket_rows`` ladder bucket (and
batches larger than the chunk are sliced into chunk-sized plans), so the
downstream ``StreamingPredictor`` only ever sees row counts it AOT-warmed
— zero recompiles after warmup, by construction.  Tree walks and the
output transform are row-local, so coalescing, padding and slicing are
bit-identical per row to calling ``Booster.predict`` on each request
alone (asserted in tests/test_serving.py).

A whole request always lands in ONE dispatch call: the dispatcher
acquires a single registry entry per call, so no request can ever observe
mixed-model outputs across a hot-swap.

Deadline-miss accounting: a request "missed" when its queue wait exceeded
the deadline plus a small scheduling slack — under healthy load the
deadline flush fires within the slack, so misses measure real overload
(the worker busy with the previous dispatch), not the coalescing wait
itself.  The windowed miss rate drives the ``serve_deadline`` watchdog
rule.

Host-only threading code: no jax imports, no device syncs.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..obs.flight import get_flight
from ..obs.registry import get_session
from ..obs.trace import format_traceparent, get_tracer, parse_traceparent
from ..predict import LADDER_MIN, bucket_rows

# Plans: (padded_matrix, live_rows) pairs — one dispatch call predicts
# them all under a single model acquisition and returns the concatenated
# live-row predictions plus an info dict (model id/version/generation).
DispatchFn = Callable[[List[Tuple[np.ndarray, int]]], Tuple[np.ndarray, Dict[str, Any]]]

_STATS_WINDOW = 1024  # requests per latency window


class ServeResponse(NamedTuple):
    """One request's predictions plus the model identity that served it."""

    values: np.ndarray
    info: Dict[str, Any]


class _Request(NamedTuple):
    X: np.ndarray
    future: Future
    t_enqueue: float
    # tracing: span opened in submit() (ends when the response resolves);
    # traceparent is the caller's W3C header, echoed back through info
    span: Any = None
    traceparent: Optional[str] = None


class _Stop:
    pass


_STOP = _Stop()


class MicroBatcher:
    """Single-model async coalescer feeding a warm bucket ladder."""

    def __init__(
        self,
        dispatch: DispatchFn,
        *,
        deadline_ms: float = 5.0,
        max_batch: int = 4096,
        name: str = "default",
        on_window: Optional[Callable[[Dict[str, Any]], None]] = None,
    ) -> None:
        if deadline_ms <= 0:
            raise ValueError("deadline_ms must be > 0")
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self._dispatch = dispatch
        self.deadline_s = float(deadline_ms) / 1e3
        self.max_batch = int(max_batch)
        # ladder chunk: bucket_rows floors at LADDER_MIN, so the effective
        # ladder top is at least that even for tiny max_batch settings
        self.chunk = max(LADDER_MIN, self.max_batch)
        # misses measure overload, not the coalescing wait: healthy
        # deadline flushes land within this slack of the deadline
        self.miss_slack_s = max(0.5 * self.deadline_s, 2e-3)
        self.name = name
        self._on_window = on_window
        self._queue: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._latencies_ms: collections.deque = collections.deque(
            maxlen=_STATS_WINDOW
        )
        self._miss_window: collections.deque = collections.deque(
            maxlen=_STATS_WINDOW
        )
        self._fill_window: collections.deque = collections.deque(maxlen=256)
        # latency attribution windows: where a request's wall went —
        # queue_wait (coalescing + worker backlog) vs device_dispatch
        self._queue_window: collections.deque = collections.deque(
            maxlen=_STATS_WINDOW
        )
        self._device_window: collections.deque = collections.deque(
            maxlen=_STATS_WINDOW
        )
        self._queue_ms_total = 0.0
        self._device_ms_total = 0.0
        self.counters: Dict[str, int] = {
            "requests": 0,
            "rows": 0,
            "batches": 0,
            "deadline_flush": 0,
            "full_flush": 0,
            "deadline_miss": 0,
            "errors": 0,
        }
        self._carry: Optional[_Request] = None  # overflow request -> next batch head
        self._running = True
        self._thread = threading.Thread(
            target=self._run, name=f"lgbtpu-serve-{name}", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- client
    def submit(
        self, X: np.ndarray, traceparent: Optional[str] = None
    ) -> "Future":
        """Enqueue one request; the Future resolves to a ServeResponse.

        ``traceparent`` is an optional W3C trace-context header from the
        caller: the request's serve span joins that trace (the span id is
        echoed back via ``ServeResponse.info["traceparent"]``)."""
        if not self._running:
            raise RuntimeError(f"batcher '{self.name}' is stopped")
        X = np.asarray(X)
        if X.ndim == 1:
            X = X[None, :]
        if X.ndim != 2 or X.shape[0] == 0:
            raise ValueError(
                f"expected a non-empty [rows, features] matrix, got shape "
                f"{X.shape}"
            )
        # request span opens at enqueue and closes when the response
        # resolves in _flush (cross-thread: no tls attach); its queue_wait
        # child and the flush's batch-stage spans decompose the latency
        tracer = get_tracer()
        ctx = parse_traceparent(traceparent) if traceparent else None
        span = tracer.begin(
            "serve/request",
            "serve",
            trace_id=ctx[0] if ctx else None,
            parent=ctx[1] if ctx else None,
            args={"rows": int(X.shape[0]), "batcher": self.name},
        )
        fut: Future = Future()
        self._queue.put(
            _Request(X, fut, time.perf_counter(), span, traceparent)
        )
        return fut

    def stop(self, timeout: float = 10.0) -> None:
        """Drain the queue, dispatch what remains, stop the worker."""
        if not self._running:
            return
        self._running = False
        self._queue.put(_STOP)
        self._thread.join(timeout=timeout)

    # ------------------------------------------------------------- worker
    def _run(self) -> None:
        while True:
            if self._carry is not None:
                first, self._carry = self._carry, None
            else:
                first = self._queue.get()
            if isinstance(first, _Stop):
                break
            batch = [first]
            rows = first.X.shape[0]
            deadline = first.t_enqueue + self.deadline_s
            reason = "deadline"
            while rows < self.max_batch:
                remaining = deadline - time.perf_counter()
                try:
                    if remaining <= 0:
                        # deadline already passed (e.g. backlog while the
                        # worker dispatched): don't wait, but DO drain
                        # whatever is queued right now — under overload
                        # this coalesces the backlog into full buckets
                        # instead of thrashing one-request dispatches
                        nxt = self._queue.get_nowait()
                    else:
                        nxt = self._queue.get(timeout=remaining)
                except queue.Empty:
                    break
                if isinstance(nxt, _Stop):
                    # flush what we have, then exit
                    self._flush(batch, rows, "stop")
                    batch = []
                    break
                if rows + nxt.X.shape[0] > self.max_batch:
                    # keep whole requests in one batch (hot-swap atomicity);
                    # carry it over as the next batch's head and flush full
                    self._carry = nxt
                    reason = "full"
                    break
                batch.append(nxt)
                rows += nxt.X.shape[0]
            else:
                reason = "full"
            if not batch:
                break
            self._flush(batch, rows, reason)
        # resolve anything still queued after stop
        if self._carry is not None:
            carry, self._carry = self._carry, None
            self._flush([carry], carry.X.shape[0], "stop")
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if not isinstance(item, _Stop):
                item.future.set_exception(
                    RuntimeError(f"batcher '{self.name}' stopped")
                )

    def _flush(self, batch: List[_Request], rows: int, reason: str) -> None:
        tracer = get_tracer()
        t_disp = time.perf_counter()
        X = (
            batch[0].X
            if len(batch) == 1
            else np.concatenate([r.X for r in batch], axis=0)
        )
        # slice into ladder plans: every dispatched matrix is a warm bucket
        # (<= chunk coalesced batches produce exactly one plan)
        plans: List[Tuple[np.ndarray, int]] = []
        for lo in range(0, rows, self.chunk):
            live = min(self.chunk, rows - lo)
            bucket = bucket_rows(live, self.chunk)
            mat = X[lo : lo + live]
            if bucket > live:
                padded = np.zeros((bucket, X.shape[1]), dtype=X.dtype)
                padded[:live] = mat
                mat = padded
            plans.append((mat, live))
        t_asm_done = time.perf_counter()
        try:
            preds, info = self._dispatch(plans)
        except Exception as e:
            with self._lock:
                self.counters["errors"] += 1
            for r in batch:
                if r.span is not None:
                    tracer.end(r.span, extra={"error": type(e).__name__})
                r.future.set_exception(e)
            return
        t_dev_done = time.perf_counter()
        lo = 0
        for r in batch:
            n = r.X.shape[0]
            resp_info = info
            if r.span is not None or r.traceparent:
                # echo trace context so the caller can correlate: the
                # request span's own ids when it was sampled, otherwise
                # the caller's header unchanged
                resp_info = dict(info)
                resp_info["traceparent"] = (
                    format_traceparent(r.span.trace_id, r.span.span_id)
                    if r.span is not None
                    else r.traceparent
                )
            r.future.set_result(ServeResponse(preds[lo : lo + n], resp_info))
            lo += n
        t_done = time.perf_counter()
        bucket_total = sum(m.shape[0] for m, _ in plans)
        self._note_trace(
            batch, rows, bucket_total, reason, info,
            t_disp, t_asm_done, t_dev_done, t_done,
        )
        queue_ms = [(t_disp - r.t_enqueue) * 1e3 for r in batch]
        device_ms = (t_dev_done - t_asm_done) * 1e3
        with self._lock:
            for r, q_ms in zip(batch, queue_ms):
                self._latencies_ms.append((t_done - r.t_enqueue) * 1e3)
                self._queue_window.append(q_ms)
                self._queue_ms_total += q_ms
                missed = (
                    t_disp - r.t_enqueue
                    > self.deadline_s + self.miss_slack_s
                )
                self._miss_window.append(1 if missed else 0)
                if missed:
                    self.counters["deadline_miss"] += 1
            # device time is shared by every request in the batch: each
            # rider attributes the full dispatch wall to itself (that IS
            # the latency it observed waiting on the device)
            for _ in batch:
                self._device_window.append(device_ms)
                self._device_ms_total += device_ms
            self._fill_window.append(rows / max(1, bucket_total))
            self.counters["requests"] += len(batch)
            self.counters["rows"] += rows
            self.counters["batches"] += 1
            self.counters[
                "full_flush" if reason == "full" else "deadline_flush"
            ] += 1
            window = self._stats_locked()
        self._publish(window, rows, bucket_total, reason, len(batch))

    def _note_trace(
        self, batch, rows, bucket_total, reason, info,
        t_disp, t_asm_done, t_dev_done, t_done,
    ) -> None:
        """Span decomposition for one flush: a ``serve/batch`` span with
        ``batch_assembly`` / ``device_dispatch`` / ``unpad_respond`` stage
        children, plus each rider request's ``queue_wait`` child and the
        close of its ``serve/request`` span.  perf_counter() and the
        tracer's perf_counter_ns() share an epoch, so the second-based
        timestamps convert to span microseconds directly."""
        tracer = get_tracer()
        if not tracer.active:
            return
        us = lambda t: int(t * 1e6)  # noqa: E731
        batch_id = tracer.add_span(
            "serve/batch",
            "serve",
            us(t_disp),
            max(1, us(t_done) - us(t_disp)),
            args={
                "batcher": self.name,
                "requests": len(batch),
                "rows": rows,
                "bucket_rows": bucket_total,
                "reason": reason,
                "model": info.get("model_id", info.get("model", "")),
            },
        )
        for stage, a, z in (
            ("batch_assembly", t_disp, t_asm_done),
            ("device_dispatch", t_asm_done, t_dev_done),
            ("unpad_respond", t_dev_done, t_done),
        ):
            tracer.add_span(
                f"serve/{stage}",
                "serve",
                us(a),
                max(1, us(z) - us(a)),
                parent_id=batch_id,
            )
        for r in batch:
            if r.span is None:
                continue
            # the rider's queue_wait covers enqueue -> flush start; the
            # batch span id rides in args (the request may belong to a
            # caller's distributed trace, so it is NOT reparented)
            tracer.add_span(
                "serve/queue_wait",
                "serve",
                us(r.t_enqueue),
                max(1, us(t_disp) - us(r.t_enqueue)),
                trace_id=r.span.trace_id,
                parent_id=r.span.span_id,
                tid=r.span.tid,
            )
            tracer.end(
                r.span,
                extra={"batch_span": batch_id, "reason": reason},
                end_us=us(t_done),
            )

    # -------------------------------------------------------------- stats
    def _stats_locked(self) -> Dict[str, Any]:
        lat = sorted(self._latencies_ms)
        misses = list(self._miss_window)
        fills = list(self._fill_window)
        q = sorted(self._queue_window)
        d = sorted(self._device_window)

        def pct_of(arr: List[float], p: float) -> float:
            if not arr:
                return 0.0
            return arr[min(len(arr) - 1, int(p * (len(arr) - 1)))]

        def pct(p: float) -> float:
            return pct_of(lat, p)

        return {
            "name": self.name,
            "p50_ms": pct(0.50),
            "p99_ms": pct(0.99),
            "batch_fill": (sum(fills) / len(fills)) if fills else 0.0,
            "deadline_miss_rate": (
                sum(misses) / len(misses) if misses else 0.0
            ),
            "window_requests": len(lat),
            "queue_ms_p50": pct_of(q, 0.50),
            "queue_ms_p99": pct_of(q, 0.99),
            "queue_ms_sum": self._queue_ms_total,
            "device_ms_p50": pct_of(d, 0.50),
            "device_ms_p99": pct_of(d, 0.99),
            "device_ms_sum": self._device_ms_total,
            **dict(self.counters),
        }

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return self._stats_locked()

    def _publish(
        self,
        window: Dict[str, Any],
        rows: int,
        bucket_total: int,
        reason: str,
        n_requests: int,
    ) -> None:
        ses = get_session()
        if ses.enabled:
            ses.update_gauges(
                {
                    "serve/p50_ms": window["p50_ms"],
                    "serve/p99_ms": window["p99_ms"],
                    "serve/batch_fill": window["batch_fill"],
                    "serve/deadline_miss_rate": window["deadline_miss_rate"],
                    # latency attribution: feed the /metrics summaries and
                    # the serve_deadline watchdog rule's blame message
                    "serve/queue_ms_p50": window["queue_ms_p50"],
                    "serve/queue_ms_p99": window["queue_ms_p99"],
                    "serve/queue_ms_sum": window["queue_ms_sum"],
                    "serve/device_ms_p50": window["device_ms_p50"],
                    "serve/device_ms_p99": window["device_ms_p99"],
                    "serve/device_ms_sum": window["device_ms_sum"],
                }
            )
            ses.inc("serve/requests_total", n_requests)
            ses.inc("serve/rows_total", rows)
            ses.inc("serve/batches_total")
            ses.inc(f"serve/{reason}_flush_total")
        get_flight().note_event(
            {
                "event": "serve_batch",
                "batcher": self.name,
                "requests": n_requests,
                "rows": rows,
                "bucket_rows": bucket_total,
                "reason": reason,
            }
        )
        if self._on_window is not None:
            try:
                self._on_window(
                    {
                        "event": "serve_window",
                        "iter": window["batches"],
                        "requests": window["window_requests"],
                        "deadline_miss_rate": window["deadline_miss_rate"],
                        "p99_ms": window["p99_ms"],
                        "queue_ms_p99": window["queue_ms_p99"],
                        "device_ms_p99": window["device_ms_p99"],
                        "batcher": self.name,
                    }
                )
            except Exception:
                pass
