"""Low-latency serving plane: micro-batching, model registry, hot-swap.

The request-facing half of the production story (ROADMAP item 3):

* :class:`MicroBatcher` — async coalescing of small predict requests
  into warm bucket-ladder chunks under a latency deadline;
* :class:`ModelRegistry` — co-resident models with AOT-warmed ladders,
  per-model executable-cache scoping, LRU eviction under a device-memory
  budget, and atomic generation-counted hot-swap;
* :class:`RefreshLoop` — metric-gated continual refresh (refit/extend on
  accumulated traffic, promote via hot-swap, atomic artifacts);
* :class:`ServingServer` / :func:`serve` — the ``lgb.serve()`` wiring
  plus the HTTP/JSON front end colocated with the obs exporter.
"""

from .batcher import MicroBatcher, ServeResponse  # noqa: F401
from .refresh import RefreshLoop  # noqa: F401
from .registry import ModelEntry, ModelRegistry  # noqa: F401
from .server import ServingServer, serve  # noqa: F401

__all__ = [
    "MicroBatcher",
    "ServeResponse",
    "ModelEntry",
    "ModelRegistry",
    "RefreshLoop",
    "ServingServer",
    "serve",
]
