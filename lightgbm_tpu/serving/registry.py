"""Multi-model registry: warmed ladders, LRU budget, atomic hot-swap.

Each loaded model gets a ``ModelEntry`` carrying its Booster, a
per-model-version **scope** string, and a scoped ``StreamingPredictor``
installed as the booster's engine — so co-resident models never collide
on an executable-cache key (scoped keys) and their retrace labels are
separable (``predict/stream/{scope}/{variant}``).

Load and hot-swap both warm the FULL bucket ladder before the model can
serve a request: ``compile_predict`` AOT-lowers every ladder executable,
then one dummy predict per bucket primes the (row-local) output-transform
jits at each padded size — after that, no request of any size compiles
anything (tests assert ``compile_counts_by_label`` stays flat).

Hot-swap atomicity: the new version is built and warmed entirely off to
the side; the cutover is a single dict assignment under the registry lock
tagged with a monotonic generation counter.  Dispatchers acquire ONE
entry per dispatch call (refcounted), so every request's rows are served
by exactly one model version.  The old entry is retired — its scoped
executables evicted — only once its in-flight count drains to zero.  A
warm-up failure (including an injected ``kill_during_warmup`` chaos
fault) leaves the old generation serving and dumps the flight ring.

LRU eviction: ``memory_budget_bytes`` bounds the summed device-table
footprint estimate across resident models; loading past the budget
evicts least-recently-used idle models first.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs.flight import get_flight
from ..obs.registry import get_session
from ..obs.trace import get_tracer
from ..predict import (
    LADDER_MIN,
    StreamingPredictor,
    evict_exec_scope,
    ladder_buckets,
)
from ..resilience import chaos


class ModelEntry:
    """One resident model version; refcounted for drain-before-retire."""

    def __init__(self, model_id: str, version: int, booster) -> None:
        self.model_id = model_id
        self.version = int(version)
        self.scope = f"{model_id}@v{version}"
        self.booster = booster
        self.generation = 0  # assigned at publish, under the registry lock
        self.inflight = 0
        self.retired = False
        self.device_bytes = 0
        self.warm_compiles = 0
        self.pred_engine = "walk"  # resolved at warm time
        self.last_used = time.monotonic()

    def describe(self) -> Dict[str, Any]:
        return {
            "model_id": self.model_id,
            "version": self.version,
            "generation": self.generation,
            "scope": self.scope,
            "inflight": self.inflight,
            "device_bytes": self.device_bytes,
            "num_trees": len(self.booster.models_),
            "pred_engine": self.pred_engine,
        }


class ModelRegistry:
    """Keyed model store with warmed ladders and atomic cutover."""

    def __init__(
        self,
        *,
        chunk: int = 4096,
        memory_budget_bytes: int = 0,
        num_buffers: int = 2,
        kinds=("value",),
        pred_engine: Optional[str] = None,
    ) -> None:
        self.chunk = max(LADDER_MIN, int(chunk))
        self.memory_budget_bytes = int(memory_budget_bytes)
        self.num_buffers = int(num_buffers)
        self.kinds = tuple(kinds)
        # serve-level pred_engine override; None defers to each booster's
        # own config (lgb.serve(params={"pred_engine": ...}) lands here)
        self.pred_engine = pred_engine
        self._lock = threading.RLock()
        self._live: Dict[str, ModelEntry] = {}
        self._generation = 0

    # ----------------------------------------------------------- lifecycle
    def load(self, model_id: str, booster, *, warm: bool = True) -> ModelEntry:
        """Register a new model id; warms its full ladder before it is
        visible to dispatchers.  Evicts LRU idle models past the budget."""
        with self._lock:
            if model_id in self._live:
                raise ValueError(
                    f"model '{model_id}' already loaded; use hot_swap"
                )
        entry = ModelEntry(model_id, 1, booster)
        if warm:
            with get_tracer().span(
                "lifecycle/model_warm",
                "lifecycle",
                args={"model_id": model_id, "version": entry.version},
            ):
                self._warm(entry)
        evicted = []
        with self._lock:
            if model_id in self._live:
                raise ValueError(
                    f"model '{model_id}' already loaded; use hot_swap"
                )
            evicted = self._evict_for_budget_locked(entry.device_bytes)
            self._generation += 1
            entry.generation = self._generation
            self._live[model_id] = entry
        for old in evicted:
            self._retire_now(old)
        self._note_lifecycle("serve_model_load", entry)
        ses = get_session()
        if ses.enabled:
            ses.inc("serve/load_total")
        return entry

    def register_fleet(
        self,
        boosters,
        *,
        model_ids=None,
        prefix: str = "fleet",
        warm: bool = True,
    ) -> List[ModelEntry]:
        """Bulk-register a trained model fleet (engine.train_fleet output).

        Members become independent entries named ``{prefix}/{i}`` (or the
        explicit ``model_ids``), each AOT-warmed before it is visible to
        dispatchers.  Every load runs under the registry's existing memory
        budget: a fleet larger than the budget admits members in order and
        LRU-evicts idle earlier ones, exactly like any other load — there
        is no fleet-wide reservation.  On a member's warm-up failure the
        members already registered STAY live and the error propagates, so
        callers can retry or shrink the fleet without losing progress."""
        boosters = list(boosters)
        if model_ids is not None:
            ids = [str(m) for m in model_ids]
            if len(ids) != len(boosters):
                raise ValueError(
                    f"model_ids has {len(ids)} entries for "
                    f"{len(boosters)} boosters"
                )
        else:
            ids = [f"{prefix}/{i}" for i in range(len(boosters))]
        if len(set(ids)) != len(ids):
            raise ValueError("fleet model ids must be unique")
        with self._lock:
            clash = [m for m in ids if m in self._live]
        if clash:
            raise ValueError(
                f"model ids already loaded: {clash}; use hot_swap"
            )
        entries = []
        for mid, b in zip(ids, boosters):
            entries.append(self.load(mid, b, warm=warm))
        ses = get_session()
        if ses.enabled:
            ses.inc("serve/fleet_register_total")
            ses.set_gauge("serve/fleet_size", len(entries))
        return entries

    def hot_swap(self, model_id: str, booster) -> ModelEntry:
        """Atomically replace the live version of ``model_id``.

        The replacement's FULL ladder is warmed before the cutover; the
        cutover is one dict assignment under the lock with a fresh
        generation.  On warm-up failure the old generation keeps serving,
        the attempt's scoped executables are dropped, and the flight
        recorder dumps (reason ``swap_warmup_failure``)."""
        with self._lock:
            old = self._live.get(model_id)
            if old is None:
                raise KeyError(f"model '{model_id}' is not loaded")
            version = old.version + 1
        entry = ModelEntry(model_id, version, booster)
        try:
            with get_tracer().span(
                "lifecycle/swap_warm",
                "lifecycle",
                args={"model_id": model_id, "to_version": version},
            ):
                self._warm(entry)
        except BaseException as e:
            evict_exec_scope(entry.scope)
            flight = get_flight()
            flight.note_sticky(
                {
                    "event": "serve_swap_failed",
                    "model_id": model_id,
                    "from_version": old.version,
                    "to_version": version,
                    "error": repr(e),
                }
            )
            get_tracer().instant(
                "lifecycle/swap_failed",
                "lifecycle",
                args={
                    "model_id": model_id,
                    "to_version": version,
                    "error": repr(e)[:200],
                },
            )
            flight.dump(f"swap_warmup_failure:{model_id}")
            ses = get_session()
            if ses.enabled:
                ses.inc("serve/swap_failed_total")
            raise
        with self._lock:
            old = self._live.get(model_id)
            self._generation += 1
            entry.generation = self._generation
            self._live[model_id] = entry
            retire_now = None
            if old is not None:
                old.retired = True
                if old.inflight == 0:
                    retire_now = old
        get_tracer().instant(
            "lifecycle/swap_flip",
            "lifecycle",
            args={
                "model_id": model_id,
                "from_version": old.version if old is not None else None,
                "to_version": entry.version,
                "generation": entry.generation,
            },
        )
        if retire_now is not None:
            self._retire_now(retire_now)
        self._note_lifecycle(
            "serve_model_swap",
            entry,
            from_version=old.version if old is not None else None,
            from_generation=old.generation if old is not None else None,
        )
        ses = get_session()
        if ses.enabled:
            ses.inc("serve/swap_total")
        return entry

    def unload(self, model_id: str) -> None:
        with self._lock:
            entry = self._live.pop(model_id, None)
            if entry is None:
                return
            entry.retired = True
            retire_now = entry.inflight == 0
        if retire_now:
            self._retire_now(entry)
        self._note_lifecycle("serve_model_unload", entry)

    def close(self) -> None:
        for model_id in list(self._live):
            self.unload(model_id)

    # ------------------------------------------------------------ dispatch
    def dispatch(
        self,
        model_id: str,
        plans: List[Tuple[np.ndarray, int]],
        **predict_kwargs,
    ) -> Tuple[np.ndarray, Dict[str, Any]]:
        """Predict a batcher's plan list under ONE entry acquisition.

        Every plan matrix is a warm ladder bucket; ``pred_chunk_rows`` is
        pinned to the registry chunk so dispatch hits exactly the warmed
        executables.  Returns the concatenated live-row predictions and
        the serving model's identity."""
        entry = self.acquire(model_id)
        if self.pred_engine is not None:
            predict_kwargs.setdefault("pred_engine", self.pred_engine)
        try:
            outs = [
                np.asarray(
                    entry.booster.predict(
                        mat,
                        pred_chunk_rows=self.chunk,
                        pred_num_buffers=self.num_buffers,
                        **predict_kwargs,
                    )
                )[:live]
                for mat, live in plans
            ]
            preds = outs[0] if len(outs) == 1 else np.concatenate(outs, axis=0)
            return preds, {
                "model_id": entry.model_id,
                "version": entry.version,
                "generation": entry.generation,
            }
        finally:
            self.release(entry)

    def acquire(self, model_id: str) -> ModelEntry:
        with self._lock:
            entry = self._live.get(model_id)
            if entry is None:
                raise KeyError(f"model '{model_id}' is not loaded")
            entry.inflight += 1
            entry.last_used = time.monotonic()
            return entry

    def release(self, entry: ModelEntry) -> None:
        with self._lock:
            entry.inflight -= 1
            retire_now = entry.retired and entry.inflight == 0
        if retire_now:
            self._retire_now(entry)

    def booster(self, model_id: str):
        """The live Booster for ``model_id`` (refresh loop's refit base)."""
        with self._lock:
            entry = self._live.get(model_id)
            if entry is None:
                raise KeyError(f"model '{model_id}' is not loaded")
            return entry.booster

    # ------------------------------------------------------------- introspect
    def models(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [e.describe() for e in self._live.values()]

    def generation(self, model_id: Optional[str] = None) -> int:
        with self._lock:
            if model_id is None:
                return self._generation
            entry = self._live.get(model_id)
            return entry.generation if entry is not None else -1

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.device_bytes for e in self._live.values())

    # -------------------------------------------------------------- warmup
    def _warm(self, entry: ModelEntry) -> None:
        """AOT-warm the full ladder for this entry's scoped engine, then
        prime the output transform with one dummy predict per bucket.

        The booster's ``pred_engine`` resolves ONCE here: a matmul/auto
        model that passes eligibility gets BOTH ladders warmed per scope
        (tensor + walker fallback), an ineligible one skips the matmul
        ladder entirely — warm time and HBM never double for executables
        the model can't use."""
        b = entry.booster
        engine = StreamingPredictor(b, scope=entry.scope)
        b._stream = engine  # predict() now routes through the scoped engine
        requested = self.pred_engine or getattr(b.config, "pred_engine", "walk")
        t0, t1 = b._tree_range(0, None)
        if t1 > t0 and b.models_:
            entry.pred_engine = engine.resolve_engine(
                requested, b._predict_space(t0, t1), t0, t1
            )[0]
        compiles = 0
        n_features = max(1, b.max_feature_idx + 1)
        for step, bucket in enumerate(ladder_buckets(self.chunk)):
            # chaos seam: kill_during_warmup injects a fault mid-ladder
            # (models the warmup worker dying) — hot_swap must leave the
            # old generation serving and dump the flight ring
            chaos.maybe_kill_warmup(entry.scope, step)
            compiles += b.compile_predict(
                chunk=bucket, kinds=self.kinds, pred_engine=requested
            )
            # dummy predict at exactly this bucket's padded size: the
            # convert_output/average transforms are row-count-shaped jits
            b.predict(
                np.zeros((bucket, n_features)),
                pred_chunk_rows=self.chunk,
                pred_num_buffers=self.num_buffers,
                pred_engine=requested,
            )
        entry.warm_compiles = compiles
        entry.device_bytes = self._table_bytes(engine, b, requested)

    @staticmethod
    def _table_bytes(
        engine: StreamingPredictor, booster, requested: str = "walk"
    ) -> int:
        """Estimated device residency: the stacked forest tables the
        streaming executables take as call arguments (compiled code and
        transient output buffers are not counted)."""
        import jax

        t0, t1 = booster._tree_range(0, None)
        if t1 <= t0:
            return 0
        space = booster._predict_space(t0, t1)
        resolved, _ = engine.resolve_engine(requested, space, t0, t1)
        # a matmul resolution keeps BOTH engines' tables resident (the
        # walker ladder is warmed as the compile-free fallback)
        engines = ("matmul", "walk") if resolved == "matmul" else ("walk",)
        total = 0
        for eng in engines:
            _, tables, _ = engine._tables(space, t0, t1, engine=eng)
            total += sum(
                a.nbytes
                for a in jax.tree_util.tree_leaves(tables)
                if hasattr(a, "nbytes")
            )
        return int(total)

    # ------------------------------------------------------------ eviction
    def _evict_for_budget_locked(self, incoming_bytes: int) -> List[ModelEntry]:
        """Pop LRU idle entries until the incoming model fits the budget.
        Called under the lock; retirement happens outside it."""
        if self.memory_budget_bytes <= 0:
            return []
        evicted: List[ModelEntry] = []
        while True:
            resident = sum(e.device_bytes for e in self._live.values())
            if resident + incoming_bytes <= self.memory_budget_bytes:
                break
            idle = [e for e in self._live.values() if e.inflight == 0]
            if not idle:
                break  # nothing evictable: over-budget, but keep serving
            victim = min(idle, key=lambda e: e.last_used)
            del self._live[victim.model_id]
            victim.retired = True
            evicted.append(victim)
        return evicted

    def _retire_now(self, entry: ModelEntry) -> None:
        dropped = evict_exec_scope(entry.scope)
        entry.booster._stream = None
        get_tracer().instant(
            "lifecycle/drain_retire",
            "lifecycle",
            args={
                "model_id": entry.model_id,
                "version": entry.version,
                "executables_dropped": dropped,
            },
        )
        get_flight().note_event(
            {
                "event": "serve_model_retired",
                "model_id": entry.model_id,
                "version": entry.version,
                "executables_dropped": dropped,
            }
        )
        ses = get_session()
        if ses.enabled:
            ses.inc("serve/retire_total")

    # ------------------------------------------------------------ telemetry
    def _note_lifecycle(self, event: str, entry: ModelEntry, **extra) -> None:
        get_flight().note_sticky(
            {"event": event, **entry.describe(), **extra}
        )
        ses = get_session()
        if ses.enabled:
            with self._lock:
                ses.update_gauges(
                    {
                        "serve/active_generation": float(self._generation),
                        "serve/models_loaded": float(len(self._live)),
                        "serve/resident_bytes": float(
                            sum(
                                e.device_bytes for e in self._live.values()
                            )
                        ),
                        f"serve/generation/{entry.model_id}": float(
                            entry.generation
                        ),
                    }
                )
