"""``lgb.serve()``: the request-facing wiring for the serving plane.

``ServingServer`` composes the pieces built in this package — a
``ModelRegistry`` of AOT-warmed models, one ``MicroBatcher`` per model,
the serving health-watchdog rules, and (optionally) an HTTP/JSON front
end colocated on the obs ``MetricsExporter`` endpoint:

* ``GET  /metrics``  — Prometheus text, including ``lgbtpu_serve_*``
* ``GET  /healthz``  — health doc with the ``serving`` block
* ``GET  /models``   — registry listing (id, version, generation)
* ``POST /predict``  — ``{"rows": [[...]], "model": "id"?}`` →
  ``{"predictions": [...], "model_id", "version", "generation"}``

Ports: ``serve_port > 0`` binds that port, ``-1`` binds an ephemeral one
(reported via ``.url``), ``0`` disables HTTP — the in-process
``predict``/``predict_async`` API works either way.

``serve()`` enables the telemetry session if the caller has not already
configured it: the observable serving plane is the point.
"""

from __future__ import annotations

import json
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Union

import numpy as np

from ..config import Config
from ..obs.export import (
    MetricsExporter,
    get_serving_provider,
    health_snapshot,
    set_serving_provider,
)
from ..obs.health import HealthWatchdog
from ..obs.registry import get_session
from .batcher import MicroBatcher, ServeResponse
from .refresh import RefreshLoop
from .registry import ModelRegistry


def _normalize_boosters(boosters) -> Dict[str, Any]:
    """Accept one Booster, a list, or an {id: Booster} dict."""
    if isinstance(boosters, dict):
        if not boosters:
            raise ValueError("serve() needs at least one model")
        return dict(boosters)
    if isinstance(boosters, (list, tuple)):
        if not boosters:
            raise ValueError("serve() needs at least one model")
        return {f"model{i}": b for i, b in enumerate(boosters)}
    return {"default": boosters}


class ServingServer:
    """Live serving plane over one or more Boosters."""

    def __init__(
        self,
        boosters,
        params: Optional[Dict[str, Any]] = None,
        *,
        deadline_ms: Optional[float] = None,
        max_batch: Optional[int] = None,
        memory_budget_mb: Optional[float] = None,
        port: Optional[int] = None,
        host: str = "127.0.0.1",
        raw_score: bool = False,
        watchdog: Optional[HealthWatchdog] = None,
    ) -> None:
        cfg = Config.from_params(params)
        self.deadline_ms = float(
            deadline_ms if deadline_ms is not None else cfg.serve_deadline_ms
        )
        self.max_batch = int(
            max_batch if max_batch is not None else cfg.serve_max_batch
        )
        budget_mb = float(
            memory_budget_mb
            if memory_budget_mb is not None
            else cfg.serve_memory_budget_mb
        )
        self._port_req = int(port if port is not None else cfg.serve_port)
        self.raw_score = bool(raw_score)
        ses = get_session()
        if not ses.enabled:
            ses.configure(enabled=True)
        self.registry = ModelRegistry(
            chunk=self.max_batch,
            memory_budget_bytes=int(budget_mb * (1 << 20)),
            # an explicit pred_engine in serve params overrides every
            # booster's trained-in engine (validated by Config above)
            pred_engine=(
                cfg.pred_engine
                if params and "pred_engine" in params
                else None
            ),
        )
        self._watchdog = watchdog or HealthWatchdog()
        self._batchers: Dict[str, MicroBatcher] = {}
        models = _normalize_boosters(boosters)
        self.default_model = next(iter(models))
        for model_id, booster in models.items():
            self.registry.load(model_id, booster)
            self._batchers[model_id] = self._make_batcher(model_id)
        # capture the bound method once (a fresh bound-method object per
        # access would defeat the identity check in stop)
        self._provider_fn = self.serving_snapshot
        self._prev_provider = set_serving_provider(self._provider_fn)
        self._exporter: Optional[MetricsExporter] = None
        if self._port_req != 0:
            self._exporter = MetricsExporter(
                max(0, self._port_req),
                host=host,
                health_provider=self.health,
                routes={
                    ("POST", "/predict"): self._http_predict,
                    ("GET", "/models"): self._http_models,
                },
            )
            self._exporter.start()
        self._stopped = False

    def _make_batcher(self, model_id: str) -> MicroBatcher:
        def dispatch(plans):
            return self.registry.dispatch(
                model_id, plans, raw_score=self.raw_score
            )

        return MicroBatcher(
            dispatch,
            deadline_ms=self.deadline_ms,
            max_batch=self.max_batch,
            name=model_id,
            on_window=self._on_window,
        )

    def _on_window(self, event: Dict[str, Any]) -> None:
        self._watchdog.observe_serving(event)

    # ------------------------------------------------------------- predict
    def _batcher(self, model_id: Optional[str]) -> MicroBatcher:
        mid = model_id or self.default_model
        batcher = self._batchers.get(mid)
        if batcher is None:
            raise KeyError(f"model '{mid}' is not being served")
        return batcher

    def predict_async(
        self,
        X,
        model_id: Optional[str] = None,
        traceparent: Optional[str] = None,
    ) -> "Future[ServeResponse]":
        """Enqueue one request; resolves to (values, model-identity info).

        ``traceparent`` (optional W3C header) joins the request's serve
        span to the caller's distributed trace; the assigned span id is
        echoed via ``ServeResponse.info["traceparent"]``."""
        return self._batcher(model_id).submit(X, traceparent=traceparent)

    def predict(
        self,
        X,
        model_id: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> np.ndarray:
        """Blocking micro-batched predict — bit-identical per row to
        ``Booster.predict(X)`` on the serving model."""
        return self.predict_async(X, model_id).result(timeout=timeout).values

    # ------------------------------------------------------------ lifecycle
    def swap(self, model_id: str, booster) -> Dict[str, Any]:
        """Warm + atomically cut over ``model_id`` to a new Booster."""
        entry = self.registry.hot_swap(model_id, booster)
        return entry.describe()

    def load(self, model_id: str, booster) -> Dict[str, Any]:
        """Add a new co-resident model (own batcher, own warmed ladder)."""
        entry = self.registry.load(model_id, booster)
        self._batchers[model_id] = self._make_batcher(model_id)
        return entry.describe()

    def refresh_loop(self, model_id: Optional[str] = None, **kwargs) -> RefreshLoop:
        """A RefreshLoop bound to this server's registry."""
        return RefreshLoop(
            self.registry, model_id or self.default_model, **kwargs
        )

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        for batcher in self._batchers.values():
            batcher.stop()
        # restore the previous provider, but only if the registration is
        # still ours — a newer server may have taken over since
        if get_serving_provider() is self._provider_fn:
            set_serving_provider(self._prev_provider)
        if self._exporter is not None:
            self._exporter.stop()
        self.registry.close()

    def __enter__(self) -> "ServingServer":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- observe
    @property
    def url(self) -> str:
        return self._exporter.url if self._exporter is not None else ""

    @property
    def port(self) -> int:
        return self._exporter.port if self._exporter is not None else 0

    def serving_snapshot(self) -> Dict[str, Any]:
        """The health document's ``serving`` block."""
        return {
            "models": self.registry.models(),
            "generation": self.registry.generation(),
            "resident_bytes": self.registry.resident_bytes(),
            "deadline_ms": self.deadline_ms,
            "max_batch": self.max_batch,
            "batchers": {
                mid: b.stats() for mid, b in self._batchers.items()
            },
        }

    def health(self) -> Dict[str, Any]:
        return health_snapshot(self._watchdog)

    def stats(self, model_id: Optional[str] = None) -> Dict[str, Any]:
        return self._batcher(model_id).stats()

    # ---------------------------------------------------------------- http
    def _http_predict(self, body: bytes, headers: Optional[Dict[str, str]] = None):
        # W3C trace-context: accept the caller's traceparent header and
        # echo the request span's own ids back as a response header (and
        # in the JSON body) so the caller can correlate its trace with
        # the serve timeline in GET /trace
        traceparent = (headers or {}).get("traceparent")
        try:
            doc = json.loads(body.decode("utf-8"))
            rows = np.asarray(doc["rows"], dtype=np.float64)
        except Exception as e:
            return (
                400,
                "application/json",
                json.dumps({"error": f"bad request: {e}"}).encode("utf-8"),
            )
        try:
            resp = self.predict_async(
                rows, doc.get("model"), traceparent=traceparent
            ).result(timeout=30.0)
        except KeyError as e:
            return (
                404,
                "application/json",
                json.dumps({"error": str(e)}).encode("utf-8"),
            )
        out = {
            "predictions": np.asarray(resp.values).tolist(),
            **resp.info,
        }
        extra_headers = {}
        if resp.info.get("traceparent"):
            extra_headers["traceparent"] = resp.info["traceparent"]
        return (
            200,
            "application/json",
            json.dumps(out).encode("utf-8"),
            extra_headers,
        )

    _http_predict.wants_headers = True

    def _http_models(self, body: bytes):
        return (
            200,
            "application/json",
            json.dumps(
                {
                    "models": self.registry.models(),
                    "generation": self.registry.generation(),
                }
            ).encode("utf-8"),
        )


def serve(
    boosters: Union[Any, List[Any], Dict[str, Any]],
    params: Optional[Dict[str, Any]] = None,
    **kwargs,
) -> ServingServer:
    """Start the async micro-batching serving plane over ``boosters``.

    ``boosters`` is one Booster, a list, or an ``{id: Booster}`` dict.
    Knobs come from ``params`` (``serve_deadline_ms``, ``serve_max_batch``,
    ``serve_memory_budget_mb``, ``serve_port``, ``pred_engine``) or keyword
    overrides (``deadline_ms``, ``max_batch``, ``memory_budget_mb``,
    ``port``).  A ``pred_engine`` in ``params`` overrides every served
    booster's own engine at warm and dispatch time.
    Every model's bucket ladder is AOT-warmed before the call returns, so
    the first request pays no compile.  Use as a context manager or call
    ``.stop()``.
    """
    return ServingServer(boosters, params, **kwargs)
