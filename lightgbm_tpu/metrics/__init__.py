"""Evaluation metrics (reference: src/metric/*, factory src/metric/metric.cpp:21).

Host-side NumPy: metrics run once per ``metric_freq`` iterations on the raw
score vector pulled from device, exactly as the reference computes them on the
CPU score copy.  Sorting metrics (AUC, NDCG, MAP) use NumPy sorts — the
reference's ParallelSort equivalents.  All metrics support row weights.

Each metric's ``eval(score, objective)`` takes a ``[num_class, N]`` raw-score
array and returns ``[(name, value)]``; ``is_higher_better`` mirrors the
reference's ``factor_to_bigger_better``.
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

import numpy as np

from ..config import Config
from ..obs.jit import instrumented_jit

_EPS = 1e-15


def _to_np(x) -> np.ndarray:
    return np.asarray(x, dtype=np.float64)


def _convert(score: np.ndarray, objective) -> np.ndarray:
    """Apply the objective's raw->output transform (reference: metrics call
    objective->ConvertOutput when an objective is attached)."""
    if objective is None:
        return score
    import jax.numpy as jnp

    return np.asarray(objective.convert_output(jnp.asarray(score)))


class Metric:
    """Base metric (reference: include/LightGBM/metric.h:44)."""

    name: str = ""
    is_higher_better: bool = False

    def __init__(self, config: Config):
        self.config = config

    def init(self, label: np.ndarray, weight: Optional[np.ndarray], query_boundaries=None) -> None:
        self.label = _to_np(label)
        self.weight = None if weight is None else _to_np(weight)
        self.num_data = len(self.label)
        self.sum_weights = float(self.num_data if weight is None else self.weight.sum())
        self.query_boundaries = query_boundaries

    def eval(self, score: np.ndarray, objective) -> List[Tuple[str, float]]:
        raise NotImplementedError


# ======================================================== pointwise regression
class _PointwiseMetric(Metric):
    """Average of a pointwise loss (reference: RegressionMetric,
    src/metric/regression_metric.hpp:22)."""

    convert_score = True

    def loss(self, label: np.ndarray, score: np.ndarray, xp=np) -> np.ndarray:
        raise NotImplementedError

    def average(self, sum_loss: float, sum_weights: float) -> float:
        return sum_loss / sum_weights

    def eval(self, score, objective):
        s = score[0] if score.ndim == 2 else score
        if self.convert_score:
            s = _convert(s, objective)
        pt = self.loss(self.label, _to_np(s))
        if self.weight is not None:
            pt = pt * self.weight
        return [(self.name, self.average(float(pt.sum()), self.sum_weights))]

    def eval_device(self, score_dev, objective):
        """Pointwise loss summed ON DEVICE — at 10M+ rows this avoids the
        [K, N] score pull to host every eval iteration (VERDICT weak #4);
        only the final scalar crosses to host. Returns None (host fallback)
        when labels/weights do not round-trip float32 exactly — the host path
        is f64 and large-magnitude labels would silently change the metric."""
        import jax.numpy as jnp

        if not hasattr(self, "_f32_ok"):
            # f32 label rounding is RELATIVE (~6e-8); it only moves the
            # metric materially when |label| dwarfs the residual scale, so
            # gate on magnitude (timestamps/ids-as-labels fall back to the
            # exact f64 host path) rather than exact round-trip
            ok = bool(np.all(np.isfinite(self.label))) and float(
                np.abs(self.label).max(initial=0.0)
            ) < 1e6
            if ok and self.weight is not None:
                ok = float(np.abs(self.weight).max(initial=0.0)) < 1e6
            self._f32_ok = bool(ok)
            if self._f32_ok:
                self._label_dev = jnp.asarray(self.label, jnp.float32)
                self._weight_dev = (
                    None
                    if self.weight is None
                    else jnp.asarray(self.weight, jnp.float32)
                )
        if not self._f32_ok:
            return None
        s = score_dev[0] if score_dev.ndim == 2 else score_dev
        if self.convert_score and objective is not None:
            s = objective.convert_output(s)
        try:
            pt = self.loss(self._label_dev, s, xp=jnp)
        except TypeError:
            # a subclass overrode loss() without the xp parameter
            return None
        if self._weight_dev is not None:
            pt = pt * self._weight_dev
        return [(self.name, self.average(float(pt.sum()), self.sum_weights))]


class L2Metric(_PointwiseMetric):
    name = "l2"

    def loss(self, label, score, xp=np):
        d = score - label
        return d * d


class RMSEMetric(L2Metric):
    name = "rmse"

    def average(self, sum_loss, sum_weights):
        return math.sqrt(sum_loss / sum_weights)


class L1Metric(_PointwiseMetric):
    name = "l1"

    def loss(self, label, score, xp=np):
        return xp.abs(score - label)


class QuantileMetric(_PointwiseMetric):
    name = "quantile"

    def loss(self, label, score, xp=np):
        a = self.config.alpha
        delta = label - score
        return xp.where(delta < 0, (a - 1.0) * delta, a * delta)


class HuberMetric(_PointwiseMetric):
    name = "huber"

    def loss(self, label, score, xp=np):
        a = self.config.alpha
        diff = score - label
        ad = xp.abs(diff)
        return xp.where(ad <= a, 0.5 * diff * diff, a * (ad - 0.5 * a))


class FairMetric(_PointwiseMetric):
    name = "fair"

    def loss(self, label, score, xp=np):
        c = self.config.fair_c
        x = xp.abs(score - label)
        return c * x - c * c * xp.log1p(x / c)


class PoissonMetric(_PointwiseMetric):
    name = "poisson"

    def loss(self, label, score, xp=np):
        s = xp.maximum(score, 1e-10)
        return s - label * xp.log(s)


class MAPEMetric(_PointwiseMetric):
    name = "mape"

    def loss(self, label, score, xp=np):
        return xp.abs(label - score) / xp.maximum(1.0, xp.abs(label))


class GammaMetric(_PointwiseMetric):
    name = "gamma"

    def loss(self, label, score, xp=np):
        # negative log-likelihood with psi = 1 (regression_metric.hpp:261)
        # (f32-safe floors on device: 1e-300 underflows to 0 in f32)
        floor = 1e-300 if xp is np else 1e-35
        theta = -1.0 / xp.maximum(score, floor)
        b = -xp.log(xp.maximum(-theta, floor))
        return -(label * theta - b)


class GammaDevianceMetric(_PointwiseMetric):
    name = "gamma_deviance"

    def loss(self, label, score, xp=np):
        floor = 1e-300 if xp is np else 1e-35
        tmp = label / (score + 1e-9)
        return tmp - xp.log(xp.maximum(tmp, floor)) - 1.0

    def average(self, sum_loss, sum_weights):
        return sum_loss * 2.0


class TweedieMetric(_PointwiseMetric):
    name = "tweedie"

    def loss(self, label, score, xp=np):
        rho = self.config.tweedie_variance_power
        s = xp.maximum(score, 1e-10)
        a = label * xp.exp((1.0 - rho) * xp.log(s)) / (1.0 - rho)
        b = xp.exp((2.0 - rho) * xp.log(s)) / (2.0 - rho)
        return -a + b


# ================================================================== binary
class BinaryLoglossMetric(_PointwiseMetric):
    name = "binary_logloss"

    def loss(self, label, prob, xp=np):
        p = xp.clip(prob, _EPS, 1.0 - _EPS)
        return xp.where(label > 0, -xp.log(p), -xp.log(1.0 - p))


class BinaryErrorMetric(_PointwiseMetric):
    name = "binary_error"

    def loss(self, label, prob, xp=np):
        pred_pos = prob > 0.5
        return xp.where(pred_pos != (label > 0), 1.0, 0.0)


def _weighted_auc(label_pos: np.ndarray, score: np.ndarray, weight: Optional[np.ndarray]) -> float:
    """Weighted AUC by threshold sweep (reference: AUCMetric::Eval,
    src/metric/binary_metric.hpp:159 — global sort + tie-aware accumulate)."""
    w = np.ones_like(score) if weight is None else weight
    order = np.argsort(-score, kind="stable")
    s = score[order]
    y = label_pos[order]
    ww = w[order]
    pos_w = ww * y
    neg_w = ww * (1.0 - y)
    # ties contribute cur_neg * (cur_pos/2 + sum_pos_before)
    group_id = np.zeros(len(s), dtype=np.int64)
    if len(s) > 1:
        group_id[1:] = np.cumsum(np.diff(s) != 0)
    n_groups = int(group_id[-1]) + 1 if len(s) else 0
    gp = np.bincount(group_id, weights=pos_w, minlength=n_groups)
    gn = np.bincount(group_id, weights=neg_w, minlength=n_groups)
    sum_pos_before = np.concatenate([[0.0], np.cumsum(gp)[:-1]])
    accum = float((gn * (0.5 * gp + sum_pos_before)).sum())
    sum_pos = float(gp.sum())
    sum_all = float(ww.sum())
    if sum_pos > 0 and sum_pos != sum_all:
        return accum / (sum_pos * (sum_all - sum_pos))
    return 1.0


class AUCMetric(Metric):
    name = "auc"
    is_higher_better = True

    def eval(self, score, objective):
        s = _to_np(score[0] if score.ndim == 2 else score)
        y = (self.label > 0).astype(np.float64)
        return [(self.name, _weighted_auc(y, s, self.weight))]

    def eval_device(self, score_dev, objective):
        """Tie-aware weighted AUC on device: sort + segment-summed groups
        (the host path's bincount becomes a static-size segment_sum)."""
        import jax
        import jax.numpy as jnp

        s = score_dev[0] if score_dev.ndim == 2 else score_dev
        n = s.shape[0]
        if n < 2:
            return None
        # f32 cumsums drift at very large n / big weights; fall back to the
        # exact f64 host sweep there (mirrors _PointwiseMetric._f32_ok)
        if n > 5_000_000 or (
            self.weight is not None and float(np.abs(self.weight).max()) > 1e3
        ):
            return None
        if not hasattr(self, "_label_dev"):
            self._label_dev = jnp.asarray(self.label > 0, jnp.float32)
            self._weight_dev = (
                None if self.weight is None else jnp.asarray(self.weight, jnp.float32)
            )
        w = (
            jnp.ones((n,), jnp.float32)
            if self._weight_dev is None
            else self._weight_dev
        )
        order = jnp.argsort(-s, stable=True)
        ss = s[order]
        y = self._label_dev[order]
        ww = w[order]
        pos_w = ww * y
        neg_w = ww * (1.0 - y)
        group_id = jnp.concatenate(
            [jnp.zeros(1, jnp.int32), jnp.cumsum(jnp.diff(ss) != 0).astype(jnp.int32)]
        )
        gp = jax.ops.segment_sum(pos_w, group_id, num_segments=n)
        gn = jax.ops.segment_sum(neg_w, group_id, num_segments=n)
        sum_pos_before = jnp.concatenate([jnp.zeros(1), jnp.cumsum(gp)[:-1]])
        accum = (gn * (0.5 * gp + sum_pos_before)).sum()
        sum_pos = gp.sum()
        sum_all = ww.sum()
        denom = sum_pos * (sum_all - sum_pos)
        auc = jnp.where(denom > 0, accum / jnp.maximum(denom, 1e-30), 1.0)
        return [(self.name, float(auc))]


class AveragePrecisionMetric(Metric):
    """Weighted average precision (reference: binary_metric.hpp
    AveragePrecisionMetric)."""

    name = "average_precision"
    is_higher_better = True

    def eval(self, score, objective):
        s = _to_np(score[0] if score.ndim == 2 else score)
        w = np.ones_like(s) if self.weight is None else self.weight
        order = np.argsort(-s, kind="stable")
        y = (self.label[order] > 0).astype(np.float64)
        ww = w[order]
        tp = np.cumsum(ww * y)
        fp = np.cumsum(ww * (1.0 - y))
        total_pos = tp[-1] if len(tp) else 0.0
        if total_pos == 0:
            return [(self.name, 1.0)]
        precision = tp / np.maximum(tp + fp, _EPS)
        recall_delta = np.diff(np.concatenate([[0.0], tp])) / total_pos
        return [(self.name, float((precision * recall_delta).sum()))]


# =============================================================== multiclass
def _mlogloss_device(score, label, weight):
    """ONE jitted program for the device-side multiclass logloss: a single
    dispatch on the sharded score instead of an op-by-op chain (each
    op-by-op step compiles/dispatches its own tiny sharded program — a
    large surface that tickled an XLA CPU segfault deep into long
    compile-heavy processes)."""
    import jax
    import jax.numpy as jnp

    logp = jax.nn.log_softmax(score, axis=0)  # [K, N]
    # one-hot contraction instead of take_along_axis: no gather on the
    # sharded array (gathers also serialize on TPU)
    k = score.shape[0]
    onehot = jax.nn.one_hot(label, k, axis=0, dtype=logp.dtype)  # [K, N]
    p = jnp.sum(logp * onehot, axis=0)
    # _EPS is a weak-typed Python float; pin the dtype so the traced
    # constant cannot drift with promotion rules (graftlint GL004)
    loss = -jnp.maximum(p, jnp.log(jnp.asarray(_EPS, p.dtype)))
    if weight is not None:
        loss = loss * weight
    return loss.sum()


_mlogloss_device_jit = None


class MultiLoglossMetric(Metric):
    name = "multi_logloss"

    def eval(self, score, objective):
        probs = _convert(np.asarray(score).T, objective)  # [N, K] softmax
        li = self.label.astype(np.int64)
        p = np.clip(probs[np.arange(len(li)), li], _EPS, None)
        loss = -np.log(p)
        if self.weight is not None:
            loss = loss * self.weight
        return [(self.name, float(loss.sum()) / self.sum_weights)]

    def eval_device(self, score_dev, objective):
        import jax
        import jax.numpy as jnp

        # log_softmax is the softmax objective's convert_output in log
        # space; other objectives (e.g. multiclassova) convert differently
        if objective is None or getattr(objective, "name", "") != "multiclass":
            return None
        if not hasattr(self, "_label_dev"):
            self._label_dev = jnp.asarray(self.label.astype(np.int32))
            self._weight_dev = (
                None if self.weight is None else jnp.asarray(self.weight, jnp.float32)
            )
        global _mlogloss_device_jit
        if _mlogloss_device_jit is None:
            _mlogloss_device_jit = instrumented_jit(_mlogloss_device, label="metrics/mlogloss")
        total = _mlogloss_device_jit(
            score_dev, self._label_dev, self._weight_dev
        )
        return [(self.name, float(total) / self.sum_weights)]


class MultiErrorMetric(Metric):
    def __init__(self, config: Config):
        super().__init__(config)
        k = config.multi_error_top_k
        self.top_k = k
        self.name = "multi_error" if k == 1 else f"multi_error@{k}"

    def eval(self, score, objective):
        s = np.asarray(score).T  # [N, K]
        li = self.label.astype(np.int64)
        own = s[np.arange(len(li)), li][:, None]
        num_larger = (s >= own).sum(axis=1)
        err = (num_larger > self.top_k).astype(np.float64)
        if self.weight is not None:
            err = err * self.weight
        return [(self.name, float(err.sum()) / self.sum_weights)]


class AucMuMetric(Metric):
    """AUC-mu (reference: AucMuMetric, multiclass_metric.hpp:182;
    Kleiman & Page, ICML'19)."""

    name = "auc_mu"
    is_higher_better = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.num_class = config.num_class
        k = self.num_class
        if config.auc_mu_weights:
            self.class_weights = np.asarray(config.auc_mu_weights, dtype=np.float64).reshape(k, k)
        else:
            self.class_weights = np.ones((k, k)) - np.eye(k)

    def eval(self, score, objective):
        s = np.asarray(score)  # [K, N]
        k = self.num_class
        li = self.label.astype(np.int64)
        w = np.ones(self.num_data) if self.weight is None else self.weight
        total = 0.0
        for i in range(k):
            for j in range(i + 1, k):
                curr_v = self.class_weights[i] - self.class_weights[j]
                t1 = curr_v[i] - curr_v[j]
                sel = (li == i) | (li == j)
                if not sel.any():
                    continue
                v = t1 * (curr_v @ s[:, sel])
                y = (li[sel] == i).astype(np.float64)  # class i as "positive"
                total += _weighted_auc(y, v, w[sel])
        denom = k * (k - 1) / 2
        return [(self.name, total / denom)]


# ================================================================== ranking
def _default_label_gain(max_label: int = 31) -> np.ndarray:
    return (2.0 ** np.arange(max_label + 1)) - 1.0


class NDCGMetric(Metric):
    """NDCG@k (reference: rank_metric.hpp + dcg_calculator.cpp)."""

    name = "ndcg"
    is_higher_better = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.eval_at = list(config.eval_at) or [1, 2, 3, 4, 5]
        lg = config.label_gain
        self.label_gain = np.asarray(lg, dtype=np.float64) if lg else _default_label_gain()

    def eval(self, score, objective):
        s = _to_np(score[0] if score.ndim == 2 else score)
        qb = self.query_boundaries
        if qb is None:
            raise ValueError("ndcg metric requires query data")
        ks = self.eval_at
        sums = np.zeros(len(ks))
        sum_q_weight = 0.0
        max_q = int(np.max(np.diff(qb)))
        disc = 1.0 / np.log2(np.arange(2, 2 + max_q))
        for qi in range(len(qb) - 1):
            b, e = qb[qi], qb[qi + 1]
            lab = self.label[b:e].astype(np.int64)
            sc = s[b:e]
            qw = 1.0  # per-query weight = mean row weight (reference query_weights)
            if self.weight is not None:
                qw = float(self.weight[b:e].mean())
            order = np.argsort(-sc, kind="stable")
            gains = self.label_gain[lab]
            ideal = np.sort(gains)[::-1]
            for ki, k in enumerate(ks):
                kk = min(k, e - b)
                max_dcg = float((ideal[:kk] * disc[:kk]).sum())
                if max_dcg <= 0:
                    sums[ki] += 1.0 * qw  # all-zero-label query counts as perfect
                else:
                    dcg = float((gains[order[:kk]] * disc[:kk]).sum())
                    sums[ki] += (dcg / max_dcg) * qw
            sum_q_weight += qw
        return [(f"{self.name}@{k}", float(sums[ki] / sum_q_weight)) for ki, k in enumerate(ks)]


class MapMetric(Metric):
    """MAP@k (reference: map_metric.hpp CalMapAtK)."""

    name = "map"
    is_higher_better = True

    def __init__(self, config: Config):
        super().__init__(config)
        self.eval_at = list(config.eval_at) or [1, 2, 3, 4, 5]

    def eval(self, score, objective):
        s = _to_np(score[0] if score.ndim == 2 else score)
        qb = self.query_boundaries
        if qb is None:
            raise ValueError("map metric requires query data")
        ks = self.eval_at
        sums = np.zeros(len(ks))
        sum_q_weight = 0.0
        for qi in range(len(qb) - 1):
            b, e = qb[qi], qb[qi + 1]
            lab = self.label[b:e]
            sc = s[b:e]
            qw = 1.0
            if self.weight is not None:
                qw = float(self.weight[b:e].mean())
            order = np.argsort(-sc, kind="stable")
            is_pos = lab[order] > 0.5
            npos = int(is_pos.sum())
            hits = np.cumsum(is_pos)
            ap_terms = np.where(is_pos, hits / (np.arange(e - b) + 1.0), 0.0)
            for ki, k in enumerate(ks):
                kk = min(k, e - b)
                if npos > 0:
                    sums[ki] += (ap_terms[:kk].sum() / min(npos, kk)) * qw
                else:
                    sums[ki] += 1.0 * qw
            sum_q_weight += qw
        return [(f"{self.name}@{k}", float(sums[ki] / sum_q_weight)) for ki, k in enumerate(ks)]


# ================================================================= xentropy
class CrossEntropyMetric(_PointwiseMetric):
    name = "cross_entropy"

    def loss(self, label, prob, xp=np):
        p = xp.clip(prob, _EPS, 1.0 - _EPS)
        return -label * xp.log(p) - (1.0 - label) * xp.log(1.0 - p)


class CrossEntropyLambdaMetric(Metric):
    """xentlambda (reference: xentropy_metric.hpp CrossEntropyLambdaMetric)."""

    name = "cross_entropy_lambda"

    def eval(self, score, objective):
        s = _to_np(score[0] if score.ndim == 2 else score)
        hhat = np.log1p(np.exp(s))
        w = np.ones_like(s) if self.weight is None else self.weight
        z = np.clip(1.0 - np.exp(-w * hhat), _EPS, 1.0 - _EPS)
        loss = -self.label * np.log(z) - (1.0 - self.label) * np.log(1.0 - z)
        # reference xentropy_metric.hpp keeps sum_weights_ = num_data for
        # xentlambda: weights enter only through z, not the normalizer
        return [(self.name, float(loss.sum()) / max(len(self.label), 1))]


class KullbackLeiblerDivergence(Metric):
    """kldiv (reference: xentropy_metric.hpp KullbackLeiblerDivergence)."""

    name = "kullback_leibler"

    def eval(self, score, objective):
        s = _to_np(score[0] if score.ndim == 2 else score)
        p = np.clip(1.0 / (1.0 + np.exp(-s)), _EPS, 1.0 - _EPS)
        y = np.clip(self.label, 0.0, 1.0)
        with np.errstate(divide="ignore", invalid="ignore"):
            term_p = np.where(y > 0, y * np.log(np.maximum(y, _EPS) / p), 0.0)
            term_n = np.where(y < 1, (1 - y) * np.log(np.maximum(1 - y, _EPS) / (1 - p)), 0.0)
        loss = term_p + term_n
        if self.weight is not None:
            loss = loss * self.weight
        return [(self.name, float(loss.sum()) / self.sum_weights)]


# ================================================================== factory
_METRIC_ALIASES = {
    "l2": "l2",
    "mean_squared_error": "l2",
    "mse": "l2",
    "regression": "l2",
    "regression_l2": "l2",
    "l2_root": "rmse",
    "root_mean_squared_error": "rmse",
    "rmse": "rmse",
    "l1": "l1",
    "mean_absolute_error": "l1",
    "mae": "l1",
    "regression_l1": "l1",
    "quantile": "quantile",
    "huber": "huber",
    "fair": "fair",
    "poisson": "poisson",
    "mape": "mape",
    "mean_absolute_percentage_error": "mape",
    "gamma": "gamma",
    "gamma_deviance": "gamma_deviance",
    "tweedie": "tweedie",
    "binary_logloss": "binary_logloss",
    "binary": "binary_logloss",
    "binary_error": "binary_error",
    "auc": "auc",
    "average_precision": "average_precision",
    "multi_logloss": "multi_logloss",
    "multiclass": "multi_logloss",
    "softmax": "multi_logloss",
    "multiclassova": "multi_logloss",
    "multiclass_ova": "multi_logloss",
    "ova": "multi_logloss",
    "ovr": "multi_logloss",
    "multi_error": "multi_error",
    "auc_mu": "auc_mu",
    "ndcg": "ndcg",
    "lambdarank": "ndcg",
    "rank_xendcg": "ndcg",
    "xendcg": "ndcg",
    "map": "map",
    "mean_average_precision": "map",
    "cross_entropy": "cross_entropy",
    "xentropy": "cross_entropy",
    "cross_entropy_lambda": "cross_entropy_lambda",
    "xentlambda": "cross_entropy_lambda",
    "kullback_leibler": "kullback_leibler",
    "kldiv": "kldiv",
}
_METRIC_ALIASES["kldiv"] = "kullback_leibler"

_METRICS = {
    "l2": L2Metric,
    "rmse": RMSEMetric,
    "l1": L1Metric,
    "quantile": QuantileMetric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "mape": MAPEMetric,
    "gamma": GammaMetric,
    "gamma_deviance": GammaDevianceMetric,
    "tweedie": TweedieMetric,
    "binary_logloss": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "average_precision": AveragePrecisionMetric,
    "multi_logloss": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "auc_mu": AucMuMetric,
    "ndcg": NDCGMetric,
    "map": MapMetric,
    "cross_entropy": CrossEntropyMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kullback_leibler": KullbackLeiblerDivergence,
}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    """Factory (reference: Metric::CreateMetric, src/metric/metric.cpp:21)."""
    base = name.split("@")[0].strip()
    if "@" in name:
        ats = [int(x) for x in name.split("@")[1].split(",")]
        config = Config.from_params({**config.raw, "eval_at": ats})
    canon = _METRIC_ALIASES.get(base)
    if canon is None:
        if base in ("none", "null", "custom", "na", ""):
            return None
        raise ValueError(f"unknown metric: {name!r}")
    return _METRICS[canon](config)
