"""Custom text-parser plugin registry.

Reference analog: ``Parser::CreateParser``'s customized-parser add-on
(include/LightGBM/dataset.h:445-455, src/io/parser.cpp:288) — the
reference resolves a ``className`` from ``parser_config_file`` against a
C++ ``ParserFactory`` of linked-in parser classes.  The TPU build's
plugin surface is Python-native: register a factory callable under a
class name, and any text-file load whose ``parser_config_file`` names it
routes every data line through the returned parser instead of the
CSV/TSV/LibSVM auto-detection.

    import lightgbm_tpu as lgb

    def my_factory(config_str):
        # config_str = the parser_config_file content (+ the loader's
        # appended label_idx/header lines, as GenerateParserConfigStr does)
        def parse_line(line):
            toks = line.split("|")
            return [float(t) for t in toks[1:]], float(toks[0])
        return parse_line

    lgb.register_parser("MyParser", my_factory)
    lgb.train({"parser_config_file": "my_parser.conf"}, lgb.Dataset("x.txt"))

``parse_line`` returns ``(features, label)`` where features is either a
dense list of floats or a sparse list of ``(col_idx, value)`` pairs.
"""

from __future__ import annotations

from typing import Callable, Dict

_REGISTRY: Dict[str, Callable] = {}


def register_parser(class_name: str, factory: Callable) -> None:
    """Register ``factory(config_str) -> parse_line`` under ``class_name``
    (the reference's ParserFactory::addParser)."""
    _REGISTRY[class_name] = factory


def get_from_parser_config(config_str: str, key: str) -> str:
    """key=value lookup in a parser config blob
    (Common::GetFromParserConfig, include/LightGBM/utils/common.h)."""
    for line in config_str.splitlines():
        if "=" in line:
            k, v = line.split("=", 1)
            if k.strip() == key:
                return v.strip()
    return ""


def generate_parser_config_str(
    config_path: str, header: bool, label_idx: int
) -> str:
    """Read the parser config file and append loader context
    (Parser::GenerateParserConfigStr — the reference saves header/label_idx
    into the persisted config string)."""
    try:
        with open(config_path) as fh:
            s = fh.read()
    except OSError:
        # warn loudly: silently falling back to format auto-detection on a
        # typo'd path would feed custom-format files to the CSV parser
        from .utils.log import log_warning

        log_warning(
            f"Could not open parser_config_file {config_path!r}; falling "
            "back to CSV/TSV/LibSVM auto-detection."
        )
        return ""
    if s and not s.endswith("\n"):
        s += "\n"
    if get_from_parser_config(s, "header") == "":
        s += f"header={'true' if header else 'false'}\n"
    if get_from_parser_config(s, "label_idx") == "":
        s += f"label_idx={label_idx}\n"
    return s


def create_parser(parser_config_str: str):
    """Instantiate the registered parser named by the config's className,
    or None when the config names none (falls back to format
    auto-detection, matching CreateParser's dispatch)."""
    if not parser_config_str:
        return None
    name = get_from_parser_config(parser_config_str, "className")
    if not name:
        return None
    if name not in _REGISTRY:
        raise ValueError(
            f"parser_config_file names className={name!r} but no parser "
            f"with that name is registered — call "
            f"lightgbm_tpu.register_parser({name!r}, factory) first "
            f"(registered: {sorted(_REGISTRY)})"
        )
    return _REGISTRY[name](parser_config_str)
