"""lightgbm_tpu: a TPU-native gradient-boosted decision tree framework.

A from-scratch reimplementation of the capabilities of LightGBM
(nick-zocdoc/LightGBM) designed for TPUs: histogram construction, split
search and partitioning run as jitted JAX/XLA (Pallas kernels for the hot
ops), distributed training maps the reference's socket/MPI collectives onto
XLA collectives over a ``jax.sharding.Mesh``.

Public surface mirrors the reference python-package (lightgbm/__init__.py):
``Dataset``, ``Booster``, ``train``, ``cv``, callbacks, sklearn wrappers.
"""

from .basic import (  # noqa: F401
    LGBMDeprecationWarning,
    LightGBMError,
)

# common user-code alias for the reference error class
LGBMError = LightGBMError
from .boosting.gbdt import Booster
from .callback import (
    EarlyStopException,
    TelemetryCallback,
    checkpoint_callback,
    early_stopping,
    log_evaluation,
    print_evaluation,
    record_evaluation,
    reset_parameter,
)
from .config import Config
from .dataset import Dataset
from .engine import CVBooster, cv, train, train_fleet
from .dask import DaskLGBMClassifier, DaskLGBMRanker, DaskLGBMRegressor
from .dataset import Sequence
from .plotting import (
    create_tree_digraph,
    plot_importance,
    plot_metric,
    plot_split_value_histogram,
    plot_tree,
)
from .obs import (
    compile_count,
    compile_counts_by_label,
    get_session,
)
from .resilience import (
    NumericsError,
    latest_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from .parser import register_parser
from .serving import ModelRegistry, RefreshLoop, ServingServer, serve
from .utils.log import register_logger, unregister_logger
from .utils.timer import global_timer

try:
    from .sklearn import LGBMClassifier, LGBMModel, LGBMRanker, LGBMRegressor
except Exception:  # pragma: no cover - sklearn not installed
    LGBMClassifier = LGBMModel = LGBMRanker = LGBMRegressor = None

__version__ = "0.1.0"

__all__ = [
    "LGBMError",
    "LightGBMError",
    "Dataset",
    "Booster",
    "CVBooster",
    "train",
    "train_fleet",
    "cv",
    "early_stopping",
    "log_evaluation",
    "print_evaluation",
    "record_evaluation",
    "reset_parameter",
    "EarlyStopException",
    "register_logger",
    "unregister_logger",
    "register_parser",
    "global_timer",
    "TelemetryCallback",
    "get_session",
    "compile_count",
    "compile_counts_by_label",
    "NumericsError",
    "serve",
    "ServingServer",
    "ModelRegistry",
    "RefreshLoop",
    "checkpoint_callback",
    "save_checkpoint",
    "restore_checkpoint",
    "latest_checkpoint",
    "plot_importance",
    "plot_metric",
    "plot_split_value_histogram",
    "plot_tree",
    "create_tree_digraph",
    "Sequence",
    "DaskLGBMClassifier",
    "DaskLGBMRegressor",
    "DaskLGBMRanker",
    "Config",
    "LGBMModel",
    "LGBMClassifier",
    "LGBMRegressor",
    "LGBMRanker",
]
