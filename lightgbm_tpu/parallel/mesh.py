"""Named-mesh SPMD layouts: one sharding spec for data/feature/hybrid.

The t5x-style architecture (SNIPPETS [1]-[3]): a 2-D device mesh
``Mesh(('data', 'feature'))`` plus a small logical-axis-rule table mapping
array ROLES (bin planes, per-row gradient state, score state, tree arrays)
to mesh axes via ``PartitionSpec``.  Every layout is then a mesh SHAPE, not
a code path:

* data-parallel      — ``(N, 1)``: rows sharded over ``'data'``, histogram
  and count psums over ``'data'`` (the reference's histogram ReduceScatter,
  data_parallel_tree_learner.cpp:225);
* feature-parallel   — ``(1, N)``: the ``'data'`` axis has size 1, so the
  SAME row rules degenerate to replication; features are sliced by
  ``axis_index('feature')`` inside the grower and the winner candidate is
  all-reduced over ``'feature'`` (feature_parallel_tree_learner.cpp:74);
* hybrid             — ``(D, F)``: rows sharded over ``'data'`` AND
  features sliced over ``'feature'``; histogram/count psums run over
  ``'data'`` on 1/F-width feature slices while the election broadcasts
  over ``'feature'`` — the 2-D layout a v5e-16 pod actually wants.

One ``shard_map``-wrapped ``grow_tree`` (``make_mesh_grow``) consumes the
spec; ``boosting/gbdt.py`` holds no per-layout forks.  On a trivial mesh
(1 device, or no mesh at all) the wrapper falls back to a plain ``jax.jit``
— the SNIPPETS [1] pjit-or-jit pattern — so the whole path stays testable
on the CI virtual CPU mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs.jit import instrumented_jit
from ..ops.grower import GrowerParams, TreeArrays, grow_tree
from . import _shard_map

# The two mesh axis names.  graftlint GL008 treats string literals drawn
# from this table as ONE consistent axis-name source per jitted region
# (lint/rules_spmd.py) — the sanctioned spelling for mesh-axis collectives.
MESH_AXIS_NAMES = ("data", "feature")
DATA_AXIS = MESH_AXIS_NAMES[0]
FEATURE_AXIS = MESH_AXIS_NAMES[1]

# ---- logical-axis rules: array role -> PartitionSpec over the 2-D mesh.
# Axes a spec does not mention are REPLICATED, so the same table serves
# every layout: on a (1, F) mesh the 'data' entries degenerate to
# replication and on a (D, 1) mesh the feature slicing is a no-op.
#   bins   [N, F]  — rows sharded; the grower slices features internally
#                    (a column slice by axis_index, not a mesh dim)
#   rows   [N]     — grad / hess / count_mask / leaf_id
#   score  [K, N]  — per-class score state, rows in the trailing dim
#   tree   [...]   — TreeArrays and split metadata: replicated (every
#                    shard computes the identical tree by construction)
AXIS_RULES = {
    "bins": P(DATA_AXIS, None),
    "rows": P(DATA_AXIS),
    "score": P(None, DATA_AXIS),
    "tree": P(),
    "replicated": P(),
}


def role_spec(role: str) -> P:
    """PartitionSpec for a logical array role (KeyError on unknown roles —
    a new array kind must be added to the table, never guessed)."""
    return AXIS_RULES[role]


def role_sharding(mesh: Mesh, role: str) -> NamedSharding:
    return NamedSharding(mesh, role_spec(role))


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """One distributed layout: the mesh shape plus its name.

    ``data`` / ``feature`` are the axis SIZES.  ``layout`` is the
    user-facing name ('data' | 'feature' | 'hybrid') — purely descriptive;
    every consumer reads the sizes.
    """

    layout: str
    data: int = 1
    feature: int = 1

    def __post_init__(self):
        if self.layout not in ("data", "feature", "hybrid"):
            raise ValueError(f"unknown mesh layout {self.layout!r}")
        if self.data < 1 or self.feature < 1:
            raise ValueError("mesh axis sizes must be >= 1")

    @property
    def size(self) -> int:
        return self.data * self.feature


def choose_spec(
    layout: str, n_devices: int, n_planes: int = 0
) -> Optional[MeshSpec]:
    """Pick a mesh shape for ``layout`` on ``n_devices`` devices.

    Returns None when the layout degenerates to serial (e.g. feature
    parallelism with no device count dividing the plane count — the
    reference likewise degrades to serial at num_machines==1, config.cpp).

    * 'data'    — all devices on the data axis.
    * 'feature' — the largest device count dividing ``n_planes`` (mirrors
      the pre-mesh gbdt selection so existing dryruns keep their shard
      count); rows replicated, so the data axis is 1.
    * 'hybrid'  — the largest feature-axis size ``fd`` with
      ``fd <= n_devices // fd``, ``fd | n_devices`` and
      ``fd | n_planes`` (feature slices must be equal); falls back to the
      data layout when no such factorization exists.
    """
    if n_devices < 2:
        return None
    if layout == "data":
        return MeshSpec("data", data=n_devices)
    if layout == "feature":
        for d in range(min(n_devices, max(n_planes, 1)), 1, -1):
            if n_planes % d == 0:
                return MeshSpec("feature", feature=d)
        return None
    if layout == "hybrid":
        for fd in range(int(n_devices**0.5), 1, -1):
            if n_devices % fd == 0 and n_planes > 0 and n_planes % fd == 0:
                return MeshSpec("hybrid", data=n_devices // fd, feature=fd)
        return MeshSpec("data", data=n_devices)
    raise ValueError(f"unknown mesh layout {layout!r}")


def build_mesh(spec: MeshSpec, devices: Optional[Sequence] = None) -> Mesh:
    """2-D device mesh for a spec: ``spec.size`` devices reshaped to
    ``(data, feature)`` with the canonical axis names."""
    if devices is None:
        devices = jax.devices()
    if len(devices) < spec.size:
        raise ValueError(
            f"mesh spec {spec} needs {spec.size} devices, "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[: spec.size]).reshape(spec.data, spec.feature)
    return Mesh(grid, MESH_AXIS_NAMES)


def grower_axis_params(params: GrowerParams, spec: MeshSpec) -> GrowerParams:
    """GrowerParams with the axis fields derived from the spec — the ONLY
    place layout becomes grower configuration:

    * ``axis_name``          — 'data' when rows are actually sharded;
    * ``feature_axis_name``  — 'feature' when features are sliced;
    * ``feature_shard``      — the feature-axis size (0 = off).

    A size-1 axis is dropped entirely so the grower traces the exact
    one-axis (or serial) program it always has — a (N, 1) mesh stays
    byte-identical to the pre-mesh data-parallel path.
    """
    return dataclasses.replace(
        params,
        axis_name=DATA_AXIS if spec.data > 1 else None,
        feature_axis_name=FEATURE_AXIS if spec.feature > 1 else None,
        feature_shard=spec.feature if spec.feature > 1 else 0,
    )


def make_mesh_grow(mesh: Optional[Mesh], params: GrowerParams,
                   spec: Optional[MeshSpec] = None):
    """The single jitted grow path: ``grow_tree`` shard_map'd over the 2-D
    mesh with in/out specs drawn from AXIS_RULES.

    All three layouts flow through THIS function — the spec (mesh shape +
    derived GrowerParams axis fields) is the only thing that changes.
    With no mesh (or a 1-device one) the same grower jits directly
    (SNIPPETS [1] fallback), which is what CI exercises off the virtual
    mesh.  The jit label is kept at ``parallel/sharded_grow`` so the perf
    contract's retrace keys cover the mesh path unchanged.
    """
    if spec is None:
        spec = MeshSpec("data", data=mesh.size if mesh is not None else 1)
    p = grower_axis_params(params, spec)

    def local(bins, grad, hess, mask, num_bins, nan_bins, feature_mask,
              monotone, interaction_sets, rng, is_cat, forced, cegb_penalty,
              cegb_used, quant_scales, bundle_end, feature_contri):
        return grow_tree(
            bins, grad, hess, mask, num_bins, nan_bins, feature_mask, p,
            monotone=monotone, interaction_sets=interaction_sets, rng=rng,
            is_cat=is_cat, forced=forced, cegb_penalty=cegb_penalty,
            cegb_used=cegb_used, quant_scales=quant_scales,
            bundle_end=bundle_end, feature_contri=feature_contri,
        )

    if mesh is None or mesh.size == 1:
        return instrumented_jit(local, label="parallel/sharded_grow")

    rep = role_spec("replicated")
    rows = role_spec("rows")
    fn = _shard_map(
        local,
        mesh=mesh,
        in_specs=(role_spec("bins"), rows, rows, rows, rep, rep, rep, rep,
                  rep, rep, rep, rep, rep, rep, rep, rep, rep),
        out_specs=(
            jax.tree.map(
                lambda _: role_spec("tree"),
                TreeArrays(*([0] * len(TreeArrays._fields))),
            ),
            rows,
        ),
    )
    return instrumented_jit(fn, label="parallel/sharded_grow")


# vmap model-axis name for fleet training.  Distinct from the mesh axes:
# the fleet axis is a vmap batching axis INSIDE the shard_map body, used
# only to unmap capacity-bucket indices (GrowerParams.fleet_axis_name).
FLEET_AXIS = "fleet"


def make_fleet_grow(mesh: Optional[Mesh], params: GrowerParams,
                    spec: Optional[MeshSpec] = None):
    """The fleet grow path: ``grow_tree`` vmapped over a leading model axis
    M, composed INSIDE the same shard_map the solo path uses.

    Operand batching (leading [M] axis): grad, hess, count_mask,
    feature_mask, rng.  Everything else — the [N, P] bin planes, bin
    metadata, constraint tables — is shared across members, so the batched
    histogram builds reuse ONE resident bin matrix and the data-mesh
    histogram psum moves one stacked [M, K, F, B, 3] payload per step
    instead of M separate ones.  Outputs come back stacked: TreeArrays
    [M, ...] and leaf_id [M, N].

    Member arrays ride the mesh with the member axis REPLICATED and rows
    sharded (``P(None, 'data')``) — each shard holds its row slice of every
    member.  The vmap carries ``axis_name=FLEET_AXIS`` so the grower can
    pmax capacity-bucket indices across members (one shared ladder branch;
    see GrowerParams.fleet_axis_name).  Per-member byte parity vs the solo
    path is the acceptance oracle (tests/test_fleet.py).
    """
    if spec is None:
        spec = MeshSpec("data", data=mesh.size if mesh is not None else 1)
    p = dataclasses.replace(
        grower_axis_params(params, spec), fleet_axis_name=FLEET_AXIS
    )

    def local(bins, grad, hess, mask, num_bins, nan_bins, feature_mask,
              monotone, interaction_sets, rng, is_cat, forced, cegb_penalty,
              cegb_used, quant_scales, bundle_end, feature_contri):
        return grow_tree(
            bins, grad, hess, mask, num_bins, nan_bins, feature_mask, p,
            monotone=monotone, interaction_sets=interaction_sets, rng=rng,
            is_cat=is_cat, forced=forced, cegb_penalty=cegb_penalty,
            cegb_used=cegb_used, quant_scales=quant_scales,
            bundle_end=bundle_end, feature_contri=feature_contri,
        )

    # member axis on grad/hess/mask/feature_mask/rng; all else shared
    in_axes = (None, 0, 0, 0, None, None, 0, None, None, 0, None, None,
               None, None, None, None, None)
    batched = jax.vmap(local, in_axes=in_axes, axis_name=FLEET_AXIS)

    if mesh is None or mesh.size == 1:
        return instrumented_jit(batched, label="fleet/grow")

    rep = role_spec("replicated")
    mrows = P(None, DATA_AXIS)  # [M, N]: members replicated, rows sharded
    fn = _shard_map(
        batched,
        mesh=mesh,
        in_specs=(role_spec("bins"), mrows, mrows, mrows, rep, rep, rep, rep,
                  rep, rep, rep, rep, rep, rep, rep, rep, rep),
        out_specs=(
            jax.tree.map(
                lambda _: role_spec("tree"),
                TreeArrays(*([0] * len(TreeArrays._fields))),
            ),
            mrows,
        ),
    )
    return instrumented_jit(fn, label="fleet/grow")


def fleet_psum_bytes_per_iteration(
    n_splits: int,
    n_features: int,
    num_bins: int,
    fleet: int,
    leaf_batch: int = 1,
    spec: Optional[MeshSpec] = None,
) -> dict:
    """Analytic per-iteration psum bytes for an M-member fleet: the batched
    grow issues the SAME collective sites as one member with every payload
    carrying an extra leading [M] axis, so each entry is exactly M x the
    solo model.  Kept as its own function (not a multiplier at call sites)
    so the perf gate and the fleet bench pin one shared formula."""
    solo = mesh_psum_bytes_per_iteration(
        n_splits, n_features, num_bins, leaf_batch=leaf_batch, spec=spec
    )
    m = max(1, int(fleet))
    out = {k: v * m for k, v in solo.items()}
    out["steps"] = solo["steps"]  # lockstep: shared trip count, M x payload
    out["fleet"] = m
    return out


def mesh_psum_bytes_per_iteration(
    n_splits: int,
    n_features: int,
    num_bins: int,
    leaf_batch: int = 1,
    spec: Optional[MeshSpec] = None,
    launch_steps: int = 1,
) -> dict:
    """Layout-aware analytic psum bytes for one boosting iteration — the
    2-D generalization of ``parallel.psum_bytes_per_iteration`` (which it
    reproduces exactly on a pure-data spec).

    Per-axis traffic:

    * data axis (``spec.data > 1``): histogram psums on the LOCAL feature
      width ``F / feature`` plus the smaller-child count psums — the
      dominant volume, unchanged in total across overlap on/off (the
      double-buffered sites split one payload into two);
    * feature axis (``spec.feature > 1``): the per-candidate winner
      election — 11 scalar-ish broadcast psums per elected candidate
      (2 per split step + the root refresh) plus the root-totals
      broadcast.  O(100 B/step): negligible next to histograms but
      modeled so measured-vs-analytic stays a tight assertion on every
      layout.
    """
    if spec is None:
        spec = MeshSpec("data", data=1)
    f, b = int(n_features), int(num_bins)
    k = max(1, int(leaf_batch))
    splits = max(0, int(n_splits))
    steps = -(-splits // k) if splits else 0
    f_loc = f // spec.feature if spec.feature > 1 else f
    hist_bytes = 0
    count_bytes = 0
    elect_bytes = 0
    if spec.data > 1:
        hist_payload = f_loc * b * 3 * 4  # [F_loc, B, 3] f32
        hist_bytes = (steps * k + 1) * hist_payload  # + 1 root histogram
        count_bytes = steps * k * 2 * 4 + (0 if spec.feature > 1 else 8)
    if spec.feature > 1:
        # winner election (bc() in ops/grower._featpar_reduce): 10 scalar
        # psums + the width-1 cat mask, for each of 2 candidate refreshes
        # per split step + 1 root candidate; plus the [3] root-totals
        # broadcast.  pmax/pmin ride separate measured keys.
        elections = 2 * steps + 1
        elect_bytes = elections * 11 * 4
        count_bytes += 3 * 4  # root-totals broadcast psum
    d = max(1, spec.size)
    ring = 2.0 * (d - 1) / d
    # device-resident boosting (boosting/launch.py): one compiled launch
    # scans ``launch_steps`` iterations, each issuing the SAME collective
    # sites — per-launch traffic is an exact multiple of the per-iteration
    # model (the scan body contains each psum site once; trip count and
    # payloads are iteration-invariant)
    ls = max(1, int(launch_steps))
    hist_bytes *= ls
    count_bytes *= ls
    elect_bytes *= ls
    total = hist_bytes + count_bytes + elect_bytes
    return {
        "steps": steps * ls,
        "hist_bytes": hist_bytes,
        "count_bytes": count_bytes,
        "elect_bytes": elect_bytes,
        "psum_bytes": total,
        "ring_bytes_per_device": total * ring,
    }
