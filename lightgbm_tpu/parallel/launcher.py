"""Multi-process launcher for distributed training.

Reference analog: the machine-list/MPI launch story (reference
docs/Parallel-Learning-Guide.rst: run the same CLI on every machine with
``machine_list_file``; or mpirun) and the Dask interface
(python-package/lightgbm/dask.py) as the cluster front-end.

The JAX-native equivalent is a multi-controller run: the SAME program runs in
every process, ``jax.distributed.initialize`` forms the cluster, and meshes
span all processes' devices. This module provides

  * env-driven ``init_distributed()`` defaults (set by the launcher):
    LGBM_TPU_COORDINATOR, LGBM_TPU_NUM_PROCESSES, LGBM_TPU_PROCESS_ID;
  * ``python -m lightgbm_tpu.parallel.launcher -n N script.py [args...]`` —
    spawns N copies of ``script.py`` on this host with those env vars set
    (the single-host analog of running the CLI on N machines; on a real pod
    each host runs one process and the coordinator address is shared).

Single-host TPU training does NOT need any of this: a Mesh over the local
chips (tree_learner=data) already scales there. With ``pre_partition=true``
each process loads/bins ONLY its own rows (mappers are synced at construct,
dataset.py) and the Booster feeds them process-locally via
``jax.make_array_from_process_local_data`` — no process materializes the
global bin matrix (reference: rank-partitioned loading,
src/io/dataset_loader.cpp:210).
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
from typing import List, Optional

ENV_COORD = "LGBM_TPU_COORDINATOR"
ENV_NPROC = "LGBM_TPU_NUM_PROCESSES"
ENV_PID = "LGBM_TPU_PROCESS_ID"


def env_distributed_config() -> Optional[dict]:
    """Read the launcher's env vars; None when not under the launcher."""
    if ENV_COORD not in os.environ:
        return None
    return {
        "coordinator_address": os.environ[ENV_COORD],
        "num_processes": int(os.environ.get(ENV_NPROC, "1")),
        "process_id": int(os.environ.get(ENV_PID, "0")),
    }


def launch(
    num_processes: int,
    argv: List[str],
    coordinator_port: int = 9462,
    extra_env: Optional[dict] = None,
    retries: int = 1,
    startup_window: float = 20.0,
    backoff: float = 1.0,
) -> int:
    """Spawn ``num_processes`` copies of ``python argv...`` with the
    coordination env set; returns the first nonzero exit code (0 if all ok).

    With ``retries`` > 1, a group that dies nonzero within
    ``startup_window`` seconds (the signature of a coordination-service
    bind race or TIME_WAIT port collision, not a training failure) is
    relaunched after exponential backoff, up to ``retries`` attempts."""
    import time

    attempts = max(1, int(retries))
    for attempt in range(attempts):
        t0 = time.monotonic()
        rc = _launch_once(num_processes, argv, coordinator_port, extra_env)
        elapsed = time.monotonic() - t0
        if rc == 0 or attempt + 1 >= attempts or elapsed >= startup_window:
            return rc
        delay = backoff * (2.0**attempt)
        print(
            f"[resilience] launch group died rc={rc} after {elapsed:.1f}s "
            f"(startup failure); retrying in {delay:.1f}s "
            f"(attempt {attempt + 2}/{attempts})",
            file=sys.stderr,
        )
        time.sleep(delay)
    return rc


def _launch_once(
    num_processes: int,
    argv: List[str],
    coordinator_port: int,
    extra_env: Optional[dict],
) -> int:
    procs = []
    for pid in range(num_processes):
        env = dict(os.environ)
        env[ENV_COORD] = f"localhost:{coordinator_port}"
        env[ENV_NPROC] = str(num_processes)
        env[ENV_PID] = str(pid)
        env.update(extra_env or {})
        procs.append(
            subprocess.Popen([sys.executable] + argv, env=env)
        )
    import time

    # poll instead of sequential wait: one worker dying before the
    # coordination barrier would leave the others (and us) hung forever
    rc = 0
    alive = list(procs)
    while alive:
        for pr in list(alive):
            ret = pr.poll()
            if ret is None:
                continue
            alive.remove(pr)
            if ret and not rc:
                rc = ret
                for other in alive:  # fail fast: tear the cluster down
                    other.terminate()
        time.sleep(0.2)
    return rc


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def launch_collect(
    num_processes: int,
    argv: List[str],
    coordinator_port: Optional[int] = None,
    extra_env: Optional[dict] = None,
    timeout: float = 300.0,
    retries: int = 2,
    startup_window: float = 20.0,
    backoff: float = 1.0,
):
    """Like ``launch`` but captures each process's stdout (argv includes the
    interpreter). Returns (first_nonzero_rc, [stdout per process]).
    Picks a free coordinator port by default so concurrent launches (e.g.
    parallel test runs) don't collide.

    A group that dies nonzero within ``startup_window`` seconds is treated
    as a startup failure (bind race / stale port) and relaunched on a FRESH
    port after exponential backoff, up to ``retries`` attempts; timeouts
    (rc 124) and slow failures are returned as-is — those are real."""
    import time

    attempts = max(1, int(retries))
    for attempt in range(attempts):
        port = coordinator_port if coordinator_port is not None else _free_port()
        t0 = time.monotonic()
        rc, outs = _launch_collect_once(
            num_processes, argv, port, extra_env, timeout
        )
        elapsed = time.monotonic() - t0
        if (
            rc == 0
            or rc == 124
            or attempt + 1 >= attempts
            or elapsed >= startup_window
        ):
            return rc, outs
        delay = backoff * (2.0**attempt)
        print(
            f"[resilience] launch group died rc={rc} after {elapsed:.1f}s "
            f"(startup failure); retrying on a fresh port in {delay:.1f}s "
            f"(attempt {attempt + 2}/{attempts})",
            file=sys.stderr,
        )
        time.sleep(delay)
    return rc, outs


def _launch_collect_once(
    num_processes: int,
    argv: List[str],
    coordinator_port: int,
    extra_env: Optional[dict],
    timeout: float,
):
    procs = []
    for pid in range(num_processes):
        env = dict(os.environ)
        env[ENV_COORD] = f"localhost:{coordinator_port}"
        env[ENV_NPROC] = str(num_processes)
        env[ENV_PID] = str(pid)
        env.update(extra_env or {})
        procs.append(
            subprocess.Popen(
                argv,
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    # drain every pipe concurrently: a worker that fills its ~64KB pipe
    # buffer before a collective would deadlock the whole group if the
    # parent read the pipes sequentially
    import threading

    outs = [""] * num_processes
    rcs = [0] * num_processes

    def drain(i, pr):
        try:
            out, _ = pr.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            pr.kill()
            out, _ = pr.communicate()
            rcs[i] = 124
        outs[i] = out or ""
        if pr.returncode and not rcs[i]:
            rcs[i] = pr.returncode

    threads = [
        threading.Thread(target=drain, args=(i, pr))
        for i, pr in enumerate(procs)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rc = next((r for r in rcs if r), 0)
    return rc, outs


def main(args=None) -> None:
    ap = argparse.ArgumentParser(
        description="Run script.py in N coordinated processes"
    )
    ap.add_argument("-n", "--num-processes", type=int, required=True)
    ap.add_argument("--port", type=int, default=9462)
    ap.add_argument(
        "--retries",
        type=int,
        default=1,
        help="relaunch the group up to N times on fast startup failures",
    )
    ap.add_argument("script", nargs=argparse.REMAINDER)
    ns = ap.parse_args(args)
    if not ns.script:
        ap.error("script.py [args...] required")
    raise SystemExit(
        launch(ns.num_processes, ns.script, ns.port, retries=ns.retries)
    )


if __name__ == "__main__":
    main()
