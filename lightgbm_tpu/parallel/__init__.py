"""Distributed training over a jax.sharding.Mesh.

Reference analogs: the Network layer (src/network/network.cpp — hand-rolled
Bruck allgather, recursive-halving reduce-scatter over TCP/MPI) and the
parallel tree learners (src/treelearner/data_parallel_tree_learner.cpp,
feature_parallel_tree_learner.cpp, voting_parallel_tree_learner.cpp).

TPU-native design (SURVEY §2.7/§2.8): rows are sharded over a mesh axis
``'data'``; the histogram ReduceScatter + best-split Allreduce become a single
``psum`` inside the jitted grower (XLA lowers it onto ICI rings / DCN between
hosts — no hand-rolled topology code).  Because every shard sees identical
psummed histograms, every shard computes the IDENTICAL tree — the best-split
Allreduce of SplitInfo (data_parallel_tree_learner.cpp:443) is subsumed by
determinism, and global leaf counts (:453) come out of the psummed counts for
free.  Multi-host: initialize ``jax.distributed`` and build the same Mesh over
all processes; the same shard_map then spans hosts (DCN) — the analog of the
reference's machine-list TCP setup (src/network/linkers_socket.cpp:25).

``tree_learner='feature'`` (features sharded, all rows everywhere) is a comm
optimization of the same semantics; on ICI bandwidth the plain psum is
usually fastest, so it is accepted and mapped onto the same path (results
are identical regardless).  ``tree_learner='voting'`` implements the real
PV-Tree election (ops/grower._candidate_for_leaf): histograms stay LOCAL,
each shard's top-``top_k`` weighted gains are pmax-merged, and only the
elected 2k features' ``[2k, B, 3]`` slices are psummed — engaged only when
``F > 2 * top_k`` (below that the dense psum is exact and cheaper, the
documented cutover; reference voting_parallel_tree_learner.cpp:152).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Callable, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs.jit import instrumented_jit
from ..ops.grower import GrowerParams, TreeArrays, grow_tree

DATA_AXIS = "data"


def psum_bytes_per_iteration(
    n_splits: int,
    n_features: int,
    num_bins: int,
    leaf_batch: int = 1,
    mesh_size: int = 1,
) -> dict:
    """Analytic bytes moved by the grower's psums for one boosting iteration
    under ``tree_learner=data`` (recorded as telemetry gauges).

    The psums sit inside a jitted while_loop — traced once, executed per
    split step — so runtime interception can't count them; the payloads are
    fully determined by shapes instead (tools/collective_model.py):

    * root: one ``[F, B, 3]`` f32 histogram psum per tree;
    * serial (``leaf_batch=1``): per split, one smaller-child ``[F, B, 3]``
      f32 histogram psum plus a ``[2]`` i32 count psum;
    * batched (``leaf_batch=K``): per loop step, ONE ``[K, F, B, 3]``
      histogram psum plus ONE ``[K, 2]`` count psum.  The prefix-commit rule
      may commit fewer than K members per step, so ``ceil(splits / K)``
      steps is a lower bound — the model's documented approximation.

    ``ring_bytes_per_device`` scales the summed payload by the ring
    all-reduce factor ``2 * (D - 1) / D``.

    The timed-psum wrappers (obs/collectives, ``obs_collectives=True``)
    MEASURE the same traffic at runtime; tests/test_observability.py asserts
    the measured psum bytes land within 10% of ``hist_bytes + count_bytes``
    on an 8-device dryrun, and tools/perf_gate.py freezes both sides in the
    committed perf contract.
    """
    f, b, k = int(n_features), int(num_bins), max(1, int(leaf_batch))
    splits = max(0, int(n_splits))
    hist_payload = f * b * 3 * 4  # [F, B, 3] f32
    steps = -(-splits // k) if splits else 0
    hist_bytes = (steps * k + 1) * hist_payload  # + 1 root histogram
    count_bytes = steps * k * 2 * 4 + 8  # [K, 2] i32 + root totals
    d = max(1, int(mesh_size))
    ring = 2.0 * (d - 1) / d
    return {
        "steps": steps,
        "hist_bytes": hist_bytes,
        "count_bytes": count_bytes,
        "ring_bytes_per_device": (hist_bytes + count_bytes) * ring,
    }


def _shard_map(f, *, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: the top-level alias (check_vma)
    landed after 0.4.x, where the API lives in jax.experimental.shard_map
    with the equivalent knob spelled check_rep."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as sm_old

    return sm_old(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def choose_devices(min_devices: int = 2):
    """Devices for distributed training: the default backend's devices, or —
    when it has a single chip (e.g. tests on a 1-chip host with a virtual CPU
    mesh) — the CPU backend's. Returns None when no multi-device backend
    exists, signalling serial training (the reference likewise degrades
    ``tree_learner=data`` to serial when num_machines==1, config.cpp).
    ``LGBM_TPU_FORCE_NDEV`` caps the mesh width (scaling experiments)."""
    import os

    cap = int(os.environ.get("LGBM_TPU_FORCE_NDEV", "0"))

    def _cap(devs):
        return devs[:cap] if cap > 0 else devs

    devices = _cap(jax.devices())
    if len(devices) >= min_devices:
        return devices
    try:
        cpu = _cap(jax.devices("cpu"))
    except RuntimeError:
        cpu = []
    if len(cpu) >= min_devices:
        return cpu
    return None


def data_axis_size(mesh: Optional[Mesh]) -> int:
    """Size of the row-sharding ('data') axis of ``mesh``.

    Row padding and per-shard row math must divide THIS, not the total
    device count: on a 2-D ``(data, feature)`` mesh rows are replicated
    over the feature axis, so a hybrid (4, 2) mesh needs rows % 4 == 0,
    not rows % 8.  A mesh without a 'data' axis (or no mesh) shards
    nothing, hence size 1."""
    if mesh is None:
        return 1
    return int(dict(mesh.shape).get(DATA_AXIS, 1))


def pad_rows_for(n_rows: int, mesh: Optional[Mesh]) -> int:
    """Rows of padding so ``n_rows`` divides the mesh's DATA axis."""
    return (-int(n_rows)) % data_axis_size(mesh)


def pad_rows_np(arr: np.ndarray, pad: int, fill=0):
    """Pad axis 0 of a host array with ``fill`` so rows divide the mesh's
    data axis (compute ``pad`` with ``pad_rows_for``)."""
    if pad == 0:
        return arr
    widths = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, widths, constant_values=fill)


def make_sharded_grow(
    mesh: Mesh,
    params: GrowerParams,
    axis_name: str = DATA_AXIS,
    feature_parallel: bool = False,
):
    """shard_map'd grow_tree over the mesh's data axis.

    Data-parallel (default): every shard runs the identical leaf loop on its
    local rows; histograms and root totals are psummed inside
    (ops/grower.py) so all shards compute the IDENTICAL tree — the
    reference's histogram ReduceScatter + SplitInfo Allreduce
    (src/treelearner/data_parallel_tree_learner.cpp:225-302) as XLA
    collectives. Inputs: row-sharded (bins, grad, hess, mask), replicated
    (num_bins, nan_bins, feature_mask, monotone, interaction_sets, rng).
    Returns (TreeArrays replicated, leaf_id row-sharded).

    Feature-parallel (``feature_parallel=True``): every operand is
    REPLICATED (each shard holds all rows) and the grower slices features by
    axis_index internally; the only collective is the winner all-reduce
    (reference feature_parallel_tree_learner.cpp:74).  leaf_id comes back
    replicated (every shard partitions identically)."""
    p = dataclasses.replace(params, axis_name=axis_name)

    def local(bins, grad, hess, mask, num_bins, nan_bins, feature_mask,
              monotone, interaction_sets, rng, is_cat, forced, cegb_penalty,
              cegb_used, quant_scales, bundle_end, feature_contri):
        return grow_tree(
            bins, grad, hess, mask, num_bins, nan_bins, feature_mask, p,
            monotone=monotone, interaction_sets=interaction_sets, rng=rng,
            is_cat=is_cat, forced=forced, cegb_penalty=cegb_penalty,
            cegb_used=cegb_used, quant_scales=quant_scales,
            bundle_end=bundle_end, feature_contri=feature_contri,
        )

    rep = P()
    if feature_parallel:
        sh = sh2 = rep  # rows replicated; features sliced inside grow_tree
        leaf_out = rep
    else:
        sh = P(axis_name)
        sh2 = P(axis_name, None)
        leaf_out = sh
    fn = _shard_map(
        local,
        mesh=mesh,
        in_specs=(sh2, sh, sh, sh, rep, rep, rep, rep, rep, rep, rep, rep,
                  rep, rep, rep, rep, rep),
        out_specs=(
            jax.tree.map(lambda _: rep, TreeArrays(*([0] * len(TreeArrays._fields)))),
            leaf_out,
        ),
    )
    return instrumented_jit(fn, label="parallel/sharded_grow")


def make_mesh(n_devices: Optional[int] = None, axis_name: str = DATA_AXIS) -> Mesh:
    """1-D device mesh over the data axis."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis_name,))


def shard_rows(
    arr, mesh: Mesh, axis_name: str = DATA_AXIS, process_local: bool = False
):
    """Place a host array with rows sharded over the mesh axis.

    ``process_local=True``: ``arr`` holds only THIS process's rows and the
    global array is their concatenation in process order — the reference's
    ``pre_partition`` contract (each machine loads its own partition,
    src/io/dataset_loader.cpp:210) via
    ``jax.make_array_from_process_local_data``; no process ever materializes
    the global matrix."""
    spec = P(axis_name, *([None] * (np.ndim(arr) - 1)))
    if process_local and jax.process_count() > 1:
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, spec), np.asarray(arr)
        )
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, spec))


def shard_cols(
    arr, mesh: Mesh, axis_name: str = DATA_AXIS, process_local: bool = False
):
    """Place a host [K, N] array with COLUMNS (rows of the data) sharded."""
    if process_local and jax.process_count() > 1:
        return jax.make_array_from_process_local_data(
            NamedSharding(mesh, P(None, axis_name)), np.asarray(arr)
        )
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, P(None, axis_name)))


def allgather_host_varlen(arr: np.ndarray, return_counts: bool = False):
    """Allgather variable-length per-process host rows; returns the global
    concatenation (process order) on every process — with ``return_counts``
    also the per-process row counts (to re-split the concat).

    The reference syncs init statistics with Network::Allreduce
    (objective_function.cpp ObtainAutomaticInitialScore); here the full
    label/weight columns are gathered instead — O(8 bytes/row), negligible
    next to the bin matrix that stays process-local."""
    from jax.experimental import multihost_utils

    arr = np.asarray(arr)
    counts = multihost_utils.process_allgather(
        np.asarray([arr.shape[0]], np.int32)
    ).reshape(-1)
    mx = int(counts.max())
    padded = np.zeros((mx,) + arr.shape[1:], arr.dtype)
    padded[: arr.shape[0]] = arr
    gathered = allgather_host_exact(padded)  # [nproc, mx, ...]
    out = np.concatenate(
        [gathered[i, : int(c)] for i, c in enumerate(counts)], axis=0
    )
    return (out, counts) if return_counts else out


def allgather_host_exact(arr: np.ndarray) -> np.ndarray:
    """process_allgather that preserves 64-bit payloads bit-exactly.

    ``multihost_utils.process_allgather`` routes through jax arrays, which
    (with x64 disabled) silently truncate float64/int64 to 32 bits — fatal
    for bin boundaries and label statistics.  64-bit inputs ride through as
    uint32 pairs instead."""
    from jax.experimental import multihost_utils

    arr = np.ascontiguousarray(arr)
    if arr.dtype.itemsize == 8:
        as32 = arr.view(np.uint32)  # [..., 2 * last]
        out = np.asarray(multihost_utils.process_allgather(as32))
        return out.view(arr.dtype)
    return np.asarray(multihost_utils.process_allgather(arr))


def replicate(arr, mesh: Mesh):
    return jax.device_put(jnp.asarray(arr), NamedSharding(mesh, P()))


def make_data_parallel_train_step(
    mesh: Mesh,
    params: GrowerParams,
    learning_rate: float,
    objective_grad: Callable[[jnp.ndarray, jnp.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
    axis_name: str = DATA_AXIS,
):
    """Build a jitted full training step over the mesh.

    The returned step takes row-sharded (bins, label, score) plus replicated
    (num_bins, nan_bins, feature_mask) and performs: gradients (local) ->
    grow_tree with psummed histograms (collectives over ICI) -> score update
    (local gather).  Semantics match DataParallelTreeLearner: local histogram,
    global reduction, global split selection, local partition.
    """
    p = params if params.axis_name == axis_name else GrowerParams(
        **{**params.__dict__, "axis_name": axis_name}
    )

    def step(bins, label, score, num_bins, nan_bins, feature_mask):
        grad, hess = objective_grad(score, label)
        mask = jnp.ones_like(grad)
        tree, leaf_id = grow_tree(
            bins, grad, hess, mask, num_bins, nan_bins, feature_mask, p
        )
        new_score = score + learning_rate * tree.leaf_value[leaf_id]
        return new_score, tree

    sharded = P(axis_name)
    sharded2 = P(axis_name, None)
    rep = P()
    fn = _shard_map(
        step,
        mesh=mesh,
        in_specs=(sharded2, sharded, sharded, rep, rep, rep),
        out_specs=(sharded, rep),
    )
    return instrumented_jit(fn, label="parallel/train_step")


def l2_gradients(score: jnp.ndarray, label: jnp.ndarray):
    return score - label, jnp.ones_like(score)


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    retries: int = 3,
    backoff: float = 1.0,
) -> None:
    """Multi-host initialization (the reference's machine-list / MPI init,
    src/network/linkers_socket.cpp:25 / linkers_mpi.cpp) via jax.distributed.

    Defaults come from the launcher's env vars when present
    (``python -m lightgbm_tpu.parallel.launcher -n N script.py``).

    Coordination-service startup is the flakiest moment of a multi-host
    run (coordinator not yet listening, port briefly in TIME_WAIT after a
    relaunch), so the initialize call retries up to ``retries`` times with
    exponential backoff starting at ``backoff`` seconds before giving up."""
    import time as _time

    from ..obs.registry import get_session
    from ..utils.log import log_warning
    from .launcher import env_distributed_config

    kwargs = env_distributed_config() or {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    attempts = max(1, int(retries))
    for attempt in range(attempts):
        try:
            jax.distributed.initialize(**kwargs)
            return
        except Exception as exc:
            if attempt + 1 >= attempts:
                raise
            delay = backoff * (2.0**attempt)
            get_session().record(
                {
                    "event": "init_distributed_retry",
                    "attempt": attempt + 1,
                    "delay_s": delay,
                    "error": f"{type(exc).__name__}: {exc}"[:300],
                }
            )
            log_warning(
                f"[resilience] jax.distributed.initialize failed "
                f"(attempt {attempt + 1}/{attempts}: {type(exc).__name__}); "
                f"retrying in {delay:.1f}s"
            )
            _time.sleep(delay)
