"""SHAP feature contributions (pred_contrib) via exact TreeSHAP.

Reference analog: ``Tree::TreeSHAP`` / ``GBDT::PredictContrib`` path
(src/io/tree.cpp TreeSHAP implementation, from Lundberg et al.'s algorithm).
Host NumPy implementation: contributions are an explainability feature, not a
training-hot-path; per-row cost is O(num_leaves * depth^2) like the reference.

Output layout matches LightGBM: ``[N, (num_features + 1) * num_class]`` with
the last column per class being the expected value (bias).
"""

from __future__ import annotations

from typing import List

import numpy as np

from .tree import (
    K_CATEGORICAL_MASK,
    K_DEFAULT_LEFT_MASK,
    K_ZERO_THRESHOLD,
    MISSING_NAN,
    MISSING_ZERO,
    Tree,
)


class _PathElement:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0, one_fraction=0.0, pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight

    def copy(self):
        return _PathElement(
            self.feature_index, self.zero_fraction, self.one_fraction, self.pweight
        )


def _extend_path(path: List[_PathElement], unique_depth: int, zero_fraction, one_fraction, feature_index):
    path[unique_depth].feature_index = feature_index
    path[unique_depth].zero_fraction = zero_fraction
    path[unique_depth].one_fraction = one_fraction
    path[unique_depth].pweight = 1.0 if unique_depth == 0 else 0.0
    for i in range(unique_depth - 1, -1, -1):
        path[i + 1].pweight += one_fraction * path[i].pweight * (i + 1) / (unique_depth + 1)
        path[i].pweight = zero_fraction * path[i].pweight * (unique_depth - i) / (unique_depth + 1)


def _unwind_path(path: List[_PathElement], unique_depth: int, path_index: int):
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = path[i].pweight
            path[i].pweight = next_one_portion * (unique_depth + 1) / ((i + 1) * one_fraction)
            next_one_portion = tmp - path[i].pweight * zero_fraction * (unique_depth - i) / (unique_depth + 1)
        else:
            path[i].pweight = path[i].pweight * (unique_depth + 1) / (zero_fraction * (unique_depth - i))
    for i in range(path_index, unique_depth):
        path[i].feature_index = path[i + 1].feature_index
        path[i].zero_fraction = path[i + 1].zero_fraction
        path[i].one_fraction = path[i + 1].one_fraction


def _unwound_path_sum(path: List[_PathElement], unique_depth: int, path_index: int) -> float:
    one_fraction = path[path_index].one_fraction
    zero_fraction = path[path_index].zero_fraction
    next_one_portion = path[unique_depth].pweight
    total = 0.0
    for i in range(unique_depth - 1, -1, -1):
        if one_fraction != 0:
            tmp = next_one_portion * (unique_depth + 1) / ((i + 1) * one_fraction)
            total += tmp
            next_one_portion = path[i].pweight - tmp * zero_fraction * (unique_depth - i) / (unique_depth + 1)
        else:
            total += path[i].pweight / (zero_fraction * (unique_depth - i) / (unique_depth + 1))
    return total


def _decide_left(tree: Tree, node: int, fval: float) -> bool:
    dt = int(tree.decision_type[node])
    if dt & K_CATEGORICAL_MASK:
        if np.isnan(fval) or fval < 0:
            return False
        int_fval = int(fval)
        cat_idx = int(tree.threshold[node])
        b0, b1 = tree.cat_boundaries[cat_idx], tree.cat_boundaries[cat_idx + 1]
        w = int_fval // 32
        return bool(
            b0 + w < b1 and (int(tree.cat_threshold[b0 + w]) >> (int_fval % 32)) & 1
        )
    missing = (dt >> 2) & 3
    if np.isnan(fval) and missing != MISSING_NAN:
        fval = 0.0
    if (missing == MISSING_ZERO and abs(fval) <= K_ZERO_THRESHOLD) or (
        missing == MISSING_NAN and np.isnan(fval)
    ):
        return bool(dt & K_DEFAULT_LEFT_MASK)
    return fval <= tree.threshold[node]


def _node_weight(tree: Tree, node: int) -> float:
    """Data count passing through a node (internal: internal_count; leaf: leaf_count)."""
    if node < 0:
        return float(tree.leaf_count[~node])
    return float(tree.internal_count[node])


def _tree_shap_recurse(
    tree: Tree,
    row: np.ndarray,
    phi: np.ndarray,
    node: int,
    unique_depth: int,
    parent_path: List[_PathElement],
    parent_zero_fraction: float,
    parent_one_fraction: float,
    parent_feature_index: int,
):
    path = [p.copy() for p in parent_path[:unique_depth]] + [
        _PathElement() for _ in range(2)
    ]
    _extend_path(path, unique_depth, parent_zero_fraction, parent_one_fraction, parent_feature_index)

    if node < 0:  # leaf
        leaf = ~node
        for i in range(1, unique_depth + 1):
            w = _unwound_path_sum(path, unique_depth, i)
            el = path[i]
            phi[el.feature_index] += (
                w * (el.one_fraction - el.zero_fraction) * tree.leaf_value[leaf]
            )
        return

    hot = (
        int(tree.left_child[node])
        if _decide_left(tree, node, float(row[tree.split_feature[node]]))
        else int(tree.right_child[node])
    )
    cold = (
        int(tree.right_child[node])
        if hot == int(tree.left_child[node])
        else int(tree.left_child[node])
    )
    w_node = max(_node_weight(tree, node), 1e-300)
    hot_zero_fraction = _node_weight(tree, hot) / w_node
    cold_zero_fraction = _node_weight(tree, cold) / w_node
    incoming_zero_fraction = 1.0
    incoming_one_fraction = 1.0

    # if this feature already appears on the path, undo its previous split
    feature = int(tree.split_feature[node])
    path_index = -1
    for i in range(1, unique_depth + 1):
        if path[i].feature_index == feature:
            path_index = i
            break
    if path_index >= 0:
        incoming_zero_fraction = path[path_index].zero_fraction
        incoming_one_fraction = path[path_index].one_fraction
        _unwind_path(path, unique_depth, path_index)
        unique_depth -= 1

    _tree_shap_recurse(
        tree,
        row,
        phi,
        hot,
        unique_depth + 1,
        path,
        hot_zero_fraction * incoming_zero_fraction,
        incoming_one_fraction,
        feature,
    )
    _tree_shap_recurse(
        tree,
        row,
        phi,
        cold,
        unique_depth + 1,
        path,
        cold_zero_fraction * incoming_zero_fraction,
        0.0,
        feature,
    )


def tree_shap(tree: Tree, row: np.ndarray, num_features: int) -> np.ndarray:
    """phi[num_features + 1]: per-feature contributions + expected value."""
    phi = np.zeros(num_features + 1)
    if tree.num_leaves <= 1:
        phi[-1] = float(tree.leaf_value[0])
        return phi
    phi[-1] = tree_expected_value(tree)
    _tree_shap_recurse(tree, row, phi, 0, 0, [], 1.0, 1.0, -1)
    return phi


def tree_expected_value(tree: Tree) -> float:
    """Leaf-count weighted mean output (reference Tree expected value)."""
    total = float(tree.leaf_count.sum())
    if total <= 0:
        return float(np.mean(tree.leaf_value[: tree.num_leaves]))
    return float(
        (tree.leaf_value[: tree.num_leaves] * tree.leaf_count[: tree.num_leaves]).sum()
        / total
    )


def predict_contrib(booster, X: np.ndarray, t0: int, t1: int) -> np.ndarray:
    """Booster-level pred_contrib (reference GBDT::PredictContrib)."""
    k = booster.num_tree_per_iteration
    num_f = booster.max_feature_idx + 1
    n = X.shape[0]
    out = np.zeros((n, k, num_f + 1))
    for idx in range(t0, t1):
        tree = booster.models_[idx]
        kk = idx % k
        for i in range(n):
            out[i, kk] += tree_shap(tree, X[i], num_f)
    if k == 1:
        return out[:, 0, :]
    return out.reshape(n, k * (num_f + 1))
