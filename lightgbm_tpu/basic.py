"""Compatibility shims mirroring python-package/lightgbm/basic.py exports.

The reference's basic.py is the ctypes bridge to the C ABI; here the Booster
and Dataset are native Python+JAX (no C ABI), so this module only carries the
auxiliary names users import from ``lightgbm.basic``.
"""

from __future__ import annotations

from .boosting.gbdt import Booster  # noqa: F401
from .dataset import Dataset  # noqa: F401


class LGBMDeprecationWarning(FutureWarning):
    """Deprecation warning class used by the package."""


class LightGBMError(Exception):
    """Error thrown by this package (reference: basic.py LightGBMError)."""
