"""Fault-tolerance subsystem: checkpoint/resume, graceful degradation,
numerics guard rails, and a fault-injection harness.

The reference implementation recovers from mid-run death only through
``snapshot_freq`` model snapshots (src/boosting/gbdt_model_text.cpp), which
lose sampler/RNG state and therefore cannot reproduce the uninterrupted
run.  Long preemptible-TPU runs need more: ``checkpoint.py`` snapshots the
FULL trainer state (model, score cache, RNG stream, bagging mask, adaptive
``leaf_batch`` EMA, telemetry counters) atomically so a killed run resumes
byte-identical; ``chaos.py`` injects the failures (SIGKILL, NaN gradients,
Pallas raises) that the recovery tests prove we survive.
"""

class NumericsError(RuntimeError):
    """Raised by the opt-in ``check_numerics`` guard when gradients,
    hessians, or split gains go non-finite, naming the iteration and
    objective so the poisoned step is identifiable without a debugger.

    A plain ``RuntimeError`` subclass (not ``basic.LightGBMError``) because
    ``basic`` imports the Booster, which imports this package — the guard
    must stay import-cycle-free.
    """


from . import chaos  # noqa: E402
from .checkpoint import (  # noqa: E402
    atomic_write_bytes,
    atomic_write_text,
    latest_checkpoint,
    list_checkpoints,
    restore_checkpoint,
    save_checkpoint,
)

__all__ = [
    "NumericsError",
    "chaos",
    "atomic_write_bytes",
    "atomic_write_text",
    "latest_checkpoint",
    "list_checkpoints",
    "restore_checkpoint",
    "save_checkpoint",
]
