"""Fault-injection harness for the resilience tests.

Hooks are armed from test (or smoke-script) code and consulted at three
seams of the training loop:

- ``kill_at_iteration(k)``   -> ``Booster.update`` SIGKILLs the process the
  moment iteration ``k`` starts, simulating a preemption.  SIGKILL (not an
  exception) so no ``finally:`` block can tidy up — resume must work from
  the last on-disk checkpoint alone.
- ``poison_gradients_at(k)`` -> the gradient fetch overwrites one entry
  with NaN at iteration ``k``, exercising the ``check_numerics`` guard.
- ``force_pallas_raise(k)``  -> the fused grow-step dispatcher raises
  :class:`InjectedPallasFailure` from iteration ``k`` on, simulating a
  Mosaic compile/launch failure so the XLA-oracle fallback path is
  reachable on any backend.

Every consult is a no-op costing one dict truthiness check when nothing is
armed, so production runs pay nothing for carrying the hooks.
"""

from __future__ import annotations

import os
import signal
from typing import Any, Dict, Optional, Tuple


class InjectedFault(RuntimeError):
    """Base class for faults raised by the chaos harness."""


class InjectedPallasFailure(InjectedFault):
    """Stands in for a Mosaic kernel compile/launch failure."""


_ARMED: Dict[str, Any] = {}


def arm(name: str, value: Any = True) -> None:
    _ARMED[name] = value


def disarm(name: str) -> None:
    _ARMED.pop(name, None)


def reset() -> None:
    """Disarm every hook (call from test teardown)."""
    _ARMED.clear()


def armed(name: str) -> Any:
    return _ARMED.get(name)


def kill_at_iteration(iteration: int) -> None:
    """SIGKILL this process when boosting iteration ``iteration`` starts."""
    arm("kill_at_iteration", int(iteration))


def poison_gradients_at(iteration: int, value: float = float("nan")) -> None:
    """Overwrite one gradient entry with ``value`` at ``iteration``."""
    arm("poison_gradients", (int(iteration), float(value)))


def force_pallas_raise(at_iteration: int = 0) -> None:
    """Make the fused grow-step dispatcher raise from ``at_iteration`` on."""
    arm("force_pallas_raise", int(at_iteration))


# ---------------------------------------------------------------- consults


def on_iteration(iteration: int) -> None:
    """Consulted at the top of ``Booster.update``."""
    if not _ARMED:
        return
    k = _ARMED.get("kill_at_iteration")
    if k is not None and iteration >= k:
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_poison_gradients(grad, hess, iteration: int) -> Tuple[Any, Any]:
    """Consulted after the gradient fetch; poisons grad[..., 0] once."""
    if not _ARMED:
        return grad, hess
    p = _ARMED.get("poison_gradients")
    if p is None or iteration != p[0]:
        return grad, hess
    flat = grad.reshape(-1)
    flat = flat.at[0].set(p[1])
    return flat.reshape(grad.shape), hess


def maybe_raise_pallas(where: str, iteration: Optional[int] = None) -> None:
    """Consulted before dispatching the fused Pallas grow step.

    With an iteration (per-call host consult in ``_grow_one``) it fires
    once the armed threshold is reached — simulating a runtime launch
    failure mid-train.  With ``iteration=None`` (trace-time consult inside
    the dispatcher) it fires only when armed at threshold <= 0 —
    simulating a Mosaic COMPILE failure, which can only surface at trace
    time, i.e. before the first iteration completes.
    """
    if not _ARMED:
        return
    t = _ARMED.get("force_pallas_raise")
    if t is None:
        return
    if (iteration is None and t <= 0) or (iteration is not None and iteration >= t):
        raise InjectedPallasFailure(
            f"injected Pallas failure in {where}"
            + ("" if iteration is None else f" at iteration {iteration}")
        )
