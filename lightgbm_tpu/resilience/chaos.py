"""Fault-injection harness for the resilience tests.

Hooks are armed from test (or smoke-script) code and consulted at three
seams of the training loop:

- ``kill_at_iteration(k)``   -> ``Booster.update`` SIGKILLs the process the
  moment iteration ``k`` starts, simulating a preemption.  SIGKILL (not an
  exception) so no ``finally:`` block can tidy up — resume must work from
  the last on-disk checkpoint alone.
- ``poison_gradients_at(k)`` -> the gradient fetch overwrites one entry
  with NaN at iteration ``k``, exercising the ``check_numerics`` guard.
- ``force_pallas_raise(k)``  -> the fused grow-step dispatcher raises
  :class:`InjectedPallasFailure` from iteration ``k`` on, simulating a
  Mosaic compile/launch failure so the XLA-oracle fallback path is
  reachable on any backend.

Every consult is a no-op costing one dict truthiness check when nothing is
armed, so production runs pay nothing for carrying the hooks.
"""

from __future__ import annotations

import os
import signal
from typing import Any, Dict, Optional, Tuple


class InjectedFault(RuntimeError):
    """Base class for faults raised by the chaos harness."""


class InjectedPallasFailure(InjectedFault):
    """Stands in for a Mosaic kernel compile/launch failure."""


_ARMED: Dict[str, Any] = {}


def arm(name: str, value: Any = True) -> None:
    _ARMED[name] = value


def disarm(name: str) -> None:
    _ARMED.pop(name, None)


def reset() -> None:
    """Disarm every hook (call from test teardown)."""
    _ARMED.clear()


def armed(name: str) -> Any:
    return _ARMED.get(name)


def kill_at_iteration(iteration: int) -> None:
    """SIGKILL this process when boosting iteration ``iteration`` starts."""
    arm("kill_at_iteration", int(iteration))


def poison_gradients_at(iteration: int, value: float = float("nan")) -> None:
    """Overwrite one gradient entry with ``value`` at ``iteration``."""
    arm("poison_gradients", (int(iteration), float(value)))


def force_pallas_raise(at_iteration: int = 0) -> None:
    """Make the fused grow-step dispatcher raise from ``at_iteration`` on."""
    arm("force_pallas_raise", int(at_iteration))


def kill_during_warmup(at_step: int = 1) -> None:
    """Abort a serving-registry ladder warmup at bucket ``at_step``.

    Models the warmup worker dying mid-ladder during a hot-swap (the
    injected-exception stand-in for a SIGKILL, same precedent as
    ``force_pallas_raise`` for Mosaic failures — a literal SIGKILL would
    take the serving process with it, which is exactly what the swap path
    must never let a *warmup* failure do).  The registry's hot_swap must
    leave the old generation serving and dump the flight ring."""
    arm("kill_during_warmup", int(at_step))


# ---------------------------------------------------------------- consults


def on_iteration(iteration: int) -> None:
    """Consulted at the top of ``Booster.update``."""
    if not _ARMED:
        return
    k = _ARMED.get("kill_at_iteration")
    if k is not None and iteration >= k:
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_kill_warmup(scope: str, step: int) -> None:
    """Consulted between ladder buckets in the serving registry's warmup."""
    if not _ARMED:
        return
    k = _ARMED.get("kill_during_warmup")
    if k is not None and step >= k:
        raise InjectedFault(
            f"injected warmup kill for {scope} at ladder step {step}"
        )


def maybe_poison_gradients(grad, hess, iteration: int) -> Tuple[Any, Any]:
    """Consulted after the gradient fetch; poisons grad[..., 0] once."""
    if not _ARMED:
        return grad, hess
    p = _ARMED.get("poison_gradients")
    if p is None or iteration != p[0]:
        return grad, hess
    flat = grad.reshape(-1)
    flat = flat.at[0].set(p[1])
    return flat.reshape(grad.shape), hess


def flight_dump_drill_numerics(workdir: str) -> str:
    """Drill: poisoned gradients must leave a flight dump behind.

    Arms ``poison_gradients_at`` under ``check_numerics`` on a tiny train,
    asserts the run dies with :class:`NumericsError` AND that the flight
    recorder wrote a valid ``flight_*.json`` into ``workdir`` carrying the
    critical ``numerics`` alert.  Returns the dump path.  Imports lazily —
    the harness module must stay import-cheap for production runs.
    """
    import numpy as np

    from .. import engine
    from ..dataset import Dataset
    from . import NumericsError

    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 5))
    y = X[:, 0] + 0.1 * rng.normal(size=300)
    poison_gradients_at(3)
    try:
        try:
            engine.train(
                {
                    "objective": "regression", "num_leaves": 7,
                    "verbosity": -1, "check_numerics": True,
                    "checkpoint_dir": workdir,
                },
                Dataset(X, y), 6,
            )
        except NumericsError:
            pass
        else:
            raise AssertionError(
                "poisoned gradients did not raise NumericsError"
            )
    finally:
        disarm("poison_gradients")
    return _assert_flight_dump(workdir, "numerics")


def flight_dump_drill_degradation(workdir: str) -> str:
    """Drill: the fused-kernel degradation latch must leave a flight dump.

    Arms ``force_pallas_raise`` mid-train on the fused path; the run must
    COMPLETE (the latch falls back to the XLA oracle) and the latch must
    have dumped the flight ring into ``workdir``.  Returns the dump path.
    """
    import numpy as np

    from .. import engine
    from ..dataset import Dataset

    rng = np.random.default_rng(0)
    X = rng.normal(size=(600, 8)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    force_pallas_raise(2)
    try:
        booster = engine.train(
            {
                "objective": "binary", "num_leaves": 7, "verbosity": -1,
                "hist_mode": "seg", "grow_fused": "on",
                "checkpoint_dir": workdir,
            },
            Dataset(X, y), 4,
        )
    finally:
        disarm("force_pallas_raise")
    assert booster.current_iteration() >= 1, "degraded run did not continue"
    return _assert_flight_dump(workdir, "degradation")


def _assert_flight_dump(
    workdir: str, reason_prefix: str, require_iterations: bool = True
) -> str:
    """Shared dump validity assertions for the drills above."""
    import json

    from ..obs.flight import FLIGHT_SCHEMA, list_flight_dumps

    dumps = list_flight_dumps(workdir)
    assert dumps, f"no flight_*.json written to {workdir}"
    with open(dumps[-1]) as f:
        doc = json.load(f)
    assert doc["schema"] == FLIGHT_SCHEMA, doc.get("schema")
    assert doc["reason"].startswith(reason_prefix), doc["reason"]
    if require_iterations:
        n_iter_events = sum(
            1 for e in doc["events"] if e.get("event") == "iteration"
        )
        # the contract is "last >= 32 iteration events OR every iteration
        # the run got through" — these drills die early, so all iterations
        # so far must be present
        assert n_iter_events >= min(32, 1), doc["n_events"]
    if reason_prefix == "numerics":
        assert any(
            a.get("rule") == "numerics" and a.get("severity") == "critical"
            for a in doc["alerts"]
        ), f"numerics alert missing from dump alerts: {doc['alerts']}"
    return dumps[-1]


def _serving_drill_fixture(workdir: str, n_trees: int = 3):
    """Shared setup for the serving drills: two tiny models (same shape,
    different data so their outputs differ) and a live ServingServer over
    the first, with the flight recorder's fault_dir pointed at workdir."""
    import numpy as np

    from .. import engine
    from ..dataset import Dataset
    from ..obs.flight import get_flight
    from ..serving import serve

    params = {"objective": "regression", "num_leaves": 7, "verbosity": -1}
    rng = np.random.default_rng(0)
    X = rng.normal(size=(600, 6))
    b1 = engine.train(params, Dataset(X, X[:, 0] + 0.1 * X[:, 1]), n_trees)
    b2 = engine.train(params, Dataset(X, X[:, 1] - 0.3 * X[:, 2]), n_trees)
    # after the trains: each train run resets the ring and re-points
    # fault_dir (to "" here — no checkpoint dir), so configure last
    get_flight().configure(fault_dir=workdir)
    server = serve(
        {"drill": b1}, deadline_ms=2.0, max_batch=512, port=0
    )
    return server, b1, b2, rng


def swap_under_load_drill(workdir: str) -> str:
    """Drill: hot-swap while concurrent requests are in flight.

    Every response must match one model version bit-exactly (no mixed
    outputs), the swap must land a sticky flight event, and an explicit
    post-swap dump into ``workdir`` must validate.  Returns the dump path.
    """
    import threading
    import time

    import numpy as np

    from ..obs.flight import get_flight

    server, b1, b2, rng = _serving_drill_fixture(workdir)
    try:
        Xq = rng.normal(size=(64, 6))
        p1, p2 = b1.predict(Xq), b2.predict(Xq)
        futures, stop = [], threading.Event()

        def client():
            # paced + bounded so the swap-long window doesn't bury the
            # batcher under an unbounded future backlog
            for _ in range(300):
                if stop.is_set():
                    break
                futures.append(server.predict_async(Xq))
                time.sleep(0.002)

        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        server.swap("drill", b2)
        stop.set()
        for t in threads:
            t.join()
        mixed = 0
        for fut in futures:
            vals = fut.result(timeout=30.0).values
            if not (np.array_equal(vals, p1) or np.array_equal(vals, p2)):
                mixed += 1
        assert mixed == 0, f"{mixed} responses mixed model generations"
        assert any(
            e.get("event") == "serve_model_swap"
            for e in get_flight().sticky_events()
        ), "swap left no sticky flight event"
        get_flight().dump("swap_under_load")
    finally:
        server.stop()
    return _assert_flight_dump(
        workdir, "swap_under_load", require_iterations=False
    )


def kill_during_warmup_drill(workdir: str) -> str:
    """Drill: a warmup death mid-hot-swap must not take down serving.

    Arms ``kill_during_warmup`` and attempts a swap: the swap must fail
    with :class:`InjectedFault`, the OLD generation must keep serving
    (bit-exact against the old model), and the registry must have dumped
    a valid ``swap_warmup_failure`` flight ring into ``workdir``.
    Returns the dump path.
    """
    import numpy as np

    server, b1, b2, rng = _serving_drill_fixture(workdir)
    try:
        Xq = rng.normal(size=(32, 6))
        kill_during_warmup(1)
        try:
            try:
                server.swap("drill", b2)
            except InjectedFault:
                pass
            else:
                raise AssertionError(
                    "kill_during_warmup did not abort the swap"
                )
        finally:
            disarm("kill_during_warmup")
        served = server.predict(Xq, timeout=30.0)
        assert np.array_equal(served, b1.predict(Xq)), (
            "old generation is not serving bit-exactly after failed swap"
        )
        snap = server.serving_snapshot()
        assert snap["models"][0]["version"] == 1, snap["models"]
    finally:
        server.stop()
    return _assert_flight_dump(
        workdir, "swap_warmup_failure", require_iterations=False
    )


def maybe_raise_pallas(where: str, iteration: Optional[int] = None) -> None:
    """Consulted before dispatching the fused Pallas grow step.

    With an iteration (per-call host consult in ``_grow_one``) it fires
    once the armed threshold is reached — simulating a runtime launch
    failure mid-train.  With ``iteration=None`` (trace-time consult inside
    the dispatcher) it fires only when armed at threshold <= 0 —
    simulating a Mosaic COMPILE failure, which can only surface at trace
    time, i.e. before the first iteration completes.
    """
    if not _ARMED:
        return
    t = _ARMED.get("force_pallas_raise")
    if t is None:
        return
    if (iteration is None and t <= 0) or (iteration is not None and iteration >= t):
        raise InjectedPallasFailure(
            f"injected Pallas failure in {where}"
            + ("" if iteration is None else f" at iteration {iteration}")
        )
