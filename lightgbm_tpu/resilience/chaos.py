"""Fault-injection harness for the resilience tests.

Hooks are armed from test (or smoke-script) code and consulted at three
seams of the training loop:

- ``kill_at_iteration(k)``   -> ``Booster.update`` SIGKILLs the process the
  moment iteration ``k`` starts, simulating a preemption.  SIGKILL (not an
  exception) so no ``finally:`` block can tidy up — resume must work from
  the last on-disk checkpoint alone.
- ``poison_gradients_at(k)`` -> the gradient fetch overwrites one entry
  with NaN at iteration ``k``, exercising the ``check_numerics`` guard.
- ``force_pallas_raise(k)``  -> the fused grow-step dispatcher raises
  :class:`InjectedPallasFailure` from iteration ``k`` on, simulating a
  Mosaic compile/launch failure so the XLA-oracle fallback path is
  reachable on any backend.

Every consult is a no-op costing one dict truthiness check when nothing is
armed, so production runs pay nothing for carrying the hooks.
"""

from __future__ import annotations

import os
import signal
from typing import Any, Dict, Optional, Tuple


class InjectedFault(RuntimeError):
    """Base class for faults raised by the chaos harness."""


class InjectedPallasFailure(InjectedFault):
    """Stands in for a Mosaic kernel compile/launch failure."""


_ARMED: Dict[str, Any] = {}


def arm(name: str, value: Any = True) -> None:
    _ARMED[name] = value


def disarm(name: str) -> None:
    _ARMED.pop(name, None)


def reset() -> None:
    """Disarm every hook (call from test teardown)."""
    _ARMED.clear()


def armed(name: str) -> Any:
    return _ARMED.get(name)


def kill_at_iteration(iteration: int) -> None:
    """SIGKILL this process when boosting iteration ``iteration`` starts."""
    arm("kill_at_iteration", int(iteration))


def poison_gradients_at(iteration: int, value: float = float("nan")) -> None:
    """Overwrite one gradient entry with ``value`` at ``iteration``."""
    arm("poison_gradients", (int(iteration), float(value)))


def force_pallas_raise(at_iteration: int = 0) -> None:
    """Make the fused grow-step dispatcher raise from ``at_iteration`` on."""
    arm("force_pallas_raise", int(at_iteration))


# ---------------------------------------------------------------- consults


def on_iteration(iteration: int) -> None:
    """Consulted at the top of ``Booster.update``."""
    if not _ARMED:
        return
    k = _ARMED.get("kill_at_iteration")
    if k is not None and iteration >= k:
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_poison_gradients(grad, hess, iteration: int) -> Tuple[Any, Any]:
    """Consulted after the gradient fetch; poisons grad[..., 0] once."""
    if not _ARMED:
        return grad, hess
    p = _ARMED.get("poison_gradients")
    if p is None or iteration != p[0]:
        return grad, hess
    flat = grad.reshape(-1)
    flat = flat.at[0].set(p[1])
    return flat.reshape(grad.shape), hess


def flight_dump_drill_numerics(workdir: str) -> str:
    """Drill: poisoned gradients must leave a flight dump behind.

    Arms ``poison_gradients_at`` under ``check_numerics`` on a tiny train,
    asserts the run dies with :class:`NumericsError` AND that the flight
    recorder wrote a valid ``flight_*.json`` into ``workdir`` carrying the
    critical ``numerics`` alert.  Returns the dump path.  Imports lazily —
    the harness module must stay import-cheap for production runs.
    """
    import numpy as np

    from .. import engine
    from ..dataset import Dataset
    from . import NumericsError

    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 5))
    y = X[:, 0] + 0.1 * rng.normal(size=300)
    poison_gradients_at(3)
    try:
        try:
            engine.train(
                {
                    "objective": "regression", "num_leaves": 7,
                    "verbosity": -1, "check_numerics": True,
                    "checkpoint_dir": workdir,
                },
                Dataset(X, y), 6,
            )
        except NumericsError:
            pass
        else:
            raise AssertionError(
                "poisoned gradients did not raise NumericsError"
            )
    finally:
        disarm("poison_gradients")
    return _assert_flight_dump(workdir, "numerics")


def flight_dump_drill_degradation(workdir: str) -> str:
    """Drill: the fused-kernel degradation latch must leave a flight dump.

    Arms ``force_pallas_raise`` mid-train on the fused path; the run must
    COMPLETE (the latch falls back to the XLA oracle) and the latch must
    have dumped the flight ring into ``workdir``.  Returns the dump path.
    """
    import numpy as np

    from .. import engine
    from ..dataset import Dataset

    rng = np.random.default_rng(0)
    X = rng.normal(size=(600, 8)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    force_pallas_raise(2)
    try:
        booster = engine.train(
            {
                "objective": "binary", "num_leaves": 7, "verbosity": -1,
                "hist_mode": "seg", "grow_fused": "on",
                "checkpoint_dir": workdir,
            },
            Dataset(X, y), 4,
        )
    finally:
        disarm("force_pallas_raise")
    assert booster.current_iteration() >= 1, "degraded run did not continue"
    return _assert_flight_dump(workdir, "degradation")


def _assert_flight_dump(workdir: str, reason_prefix: str) -> str:
    """Shared dump validity assertions for the drills above."""
    import json

    from ..obs.flight import FLIGHT_SCHEMA, list_flight_dumps

    dumps = list_flight_dumps(workdir)
    assert dumps, f"no flight_*.json written to {workdir}"
    with open(dumps[-1]) as f:
        doc = json.load(f)
    assert doc["schema"] == FLIGHT_SCHEMA, doc.get("schema")
    assert doc["reason"].startswith(reason_prefix), doc["reason"]
    n_iter_events = sum(
        1 for e in doc["events"] if e.get("event") == "iteration"
    )
    # the contract is "last >= 32 iteration events OR every iteration the
    # run got through" — these drills die early, so all iterations so far
    # must be present
    assert n_iter_events >= min(32, 1), doc["n_events"]
    if reason_prefix == "numerics":
        assert any(
            a.get("rule") == "numerics" and a.get("severity") == "critical"
            for a in doc["alerts"]
        ), f"numerics alert missing from dump alerts: {doc['alerts']}"
    return dumps[-1]


def maybe_raise_pallas(where: str, iteration: Optional[int] = None) -> None:
    """Consulted before dispatching the fused Pallas grow step.

    With an iteration (per-call host consult in ``_grow_one``) it fires
    once the armed threshold is reached — simulating a runtime launch
    failure mid-train.  With ``iteration=None`` (trace-time consult inside
    the dispatcher) it fires only when armed at threshold <= 0 —
    simulating a Mosaic COMPILE failure, which can only surface at trace
    time, i.e. before the first iteration completes.
    """
    if not _ARMED:
        return
    t = _ARMED.get("force_pallas_raise")
    if t is None:
        return
    if (iteration is None and t <= 0) or (iteration is not None and iteration >= t):
        raise InjectedPallasFailure(
            f"injected Pallas failure in {where}"
            + ("" if iteration is None else f" at iteration {iteration}")
        )
