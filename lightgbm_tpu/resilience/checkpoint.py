"""Iteration-granular atomic checkpointing for the training loop.

A checkpoint is a pickle of ``Booster._checkpoint_state()`` — the full
trainer state (model dump, device score cache, RNG key, bagging-mask
cache, adaptive ``leaf_batch`` EMA/cap, CEGB feature-usage set, telemetry
counters) — written with the tmp+fsync+rename idiom so a kill at ANY
byte offset leaves either the previous checkpoint or the new one, never a
torn file.  ``restore_checkpoint`` rehydrates a freshly constructed
training Booster to the exact post-iteration state, so the resumed run
replays the identical RNG stream and produces a byte-identical dump.
"""

from __future__ import annotations

import os
import pickle
import re
import tempfile
from typing import List, Optional, Tuple

from ..obs import get_flight, get_session
from ..utils.log import log_info

_CKPT_RE = re.compile(r"^ckpt_iter_(\d+)\.pkl$")


def _ckpt_name(iteration: int) -> str:
    return f"ckpt_iter_{iteration:08d}.pkl"


def atomic_write_bytes(path: str, payload: bytes) -> None:
    """Write ``payload`` to ``path`` via tmp file + fsync + rename.

    The tmp file lives in the destination directory so ``os.replace`` is
    a same-filesystem atomic rename; a crash mid-write can only leave a
    stray ``*.tmp``, never a truncated ``path``.
    """
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp", dir=directory
    )
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # Best-effort directory fsync so the rename itself is durable.
    try:
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))


def list_checkpoints(directory: str) -> List[Tuple[int, str]]:
    """All ``ckpt_iter_*.pkl`` files in ``directory`` as (iter, path),
    sorted by iteration ascending."""
    if not os.path.isdir(directory):
        return []
    out: List[Tuple[int, str]] = []
    for name in os.listdir(directory):
        m = _CKPT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    out.sort()
    return out


def latest_checkpoint(directory: str) -> Optional[str]:
    cks = list_checkpoints(directory)
    return cks[-1][1] if cks else None


def save_checkpoint(booster, directory: str, keep_last: Optional[int] = None) -> str:
    """Snapshot ``booster`` into ``directory`` and prune old checkpoints.

    Returns the checkpoint path.  ``keep_last`` defaults to the booster's
    ``checkpoint_keep`` config (older checkpoints beyond it are deleted;
    pass 0/None-config to keep everything).
    """
    from ..obs.trace import get_tracer

    with get_tracer().span(
        "lifecycle/checkpoint", "lifecycle", args={"directory": directory}
    ) as sp:
        state = booster._checkpoint_state()
        if keep_last is None:
            keep_last = int(getattr(booster.config, "checkpoint_keep", 0))
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, _ckpt_name(state["iter"]))
        atomic_write_bytes(path, pickle.dumps(state, protocol=4))
        if sp is not None:
            sp.args.update({"iter": state["iter"], "path": path})
    ses = get_session()
    ses.inc("checkpoints_saved")
    event = {"event": "checkpoint", "iter": state["iter"], "path": path}
    ses.record(event, defer=True)
    # a fault dump names the newest durable checkpoint it pairs with
    flight = get_flight()
    flight.note_checkpoint(path)
    flight.note_event(event)
    if keep_last and keep_last > 0:
        for _, old in list_checkpoints(directory)[:-keep_last]:
            try:
                os.unlink(old)
            except OSError:
                pass
    return path


def restore_checkpoint(booster, path_or_dir: str) -> int:
    """Restore ``booster`` from a checkpoint file, or from the latest
    checkpoint when given a directory.  Returns the restored iteration."""
    path = path_or_dir
    if os.path.isdir(path_or_dir):
        latest = latest_checkpoint(path_or_dir)
        if latest is None:
            raise FileNotFoundError(
                f"no checkpoint (ckpt_iter_*.pkl) found in {path_or_dir!r}"
            )
        path = latest
    with open(path, "rb") as f:
        state = pickle.load(f)
    booster._restore_checkpoint_state(state)
    ses = get_session()
    ses.inc("checkpoints_restored")
    ses.record(
        {"event": "checkpoint_restore", "iter": state["iter"], "path": path},
        defer=True,
    )
    log_info(f"[resilience] resumed from {path} at iteration {state['iter']}")
    return int(state["iter"])
