"""Config-file driven CLI (reference: src/main.cpp, src/application/
application.cpp — Application::Run dispatching train/predict/convert_model,
config parsing conventions from include/LightGBM/config.h:1-16).

Usage mirrors the reference binary:

    python -m lightgbm_tpu config=train.conf [key=value ...]
    python -m lightgbm_tpu task=predict data=test.tsv input_model=model.txt
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import Any, Dict, List

import numpy as np

from .boosting import create_booster
from .boosting.gbdt import Booster
from .config import Config
from .dataset import Dataset, _load_text_file
from .engine import train as engine_train


def parse_args(argv: List[str]) -> Dict[str, Any]:
    """key=value args; config file first, CLI overrides (reference
    Application::Application, config precedence CLI > file)."""
    cli: Dict[str, Any] = {}
    for tok in argv:
        if "=" not in tok:
            raise SystemExit(f"arguments must be key=value, got {tok!r}")
        key, v = tok.split("=", 1)
        cli[key.strip()] = v.strip().strip('"')
    params: Dict[str, Any] = {}
    conf = cli.get("config", cli.get("config_file"))
    if conf:
        for line in Path(conf).read_text().splitlines():
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            key, v = line.split("=", 1)
            params.setdefault(key.strip(), v.strip().strip('"'))
    params.update(cli)  # CLI wins
    return params


def run_train(params: Dict[str, Any], cfg: Config) -> None:
    if not cfg.data:
        raise SystemExit("task=train requires data=<training file>")
    dtrain = Dataset(cfg.data, params=params)
    valid_sets = []
    valid_names = []
    for i, vpath in enumerate(cfg.valid):
        valid_sets.append(dtrain.create_valid(vpath))
        valid_names.append(Path(vpath).stem)
    from .callback import log_evaluation

    callbacks = []
    if cfg.verbosity > 0 and (valid_sets or cfg.is_provide_training_metric):
        callbacks.append(log_evaluation(max(1, cfg.metric_freq)))
    if cfg.is_provide_training_metric:
        valid_sets.insert(0, dtrain)
        valid_names.insert(0, "training")
    booster = engine_train(
        params,
        dtrain,
        num_boost_round=cfg.num_iterations,
        valid_sets=valid_sets,
        valid_names=valid_names,
        callbacks=callbacks,
        init_model=params.get("input_model") or None,
        # resume_from=<ckpt file or checkpoint_dir>: full-state resume
        # (engine also honors cfg.resume_from; explicit for clarity)
        resume_from=cfg.resume_from or None,
    )
    out = params.get("output_model", "LightGBM_model.txt")
    booster.save_model(out)
    print(f"Finished training; model written to {out}")
    if cfg.checkpoint_dir and cfg.checkpoint_interval > 0:
        print(f"Checkpoints written to {cfg.checkpoint_dir}")
    if cfg.telemetry and cfg.telemetry_out:
        print(f"Telemetry events written to {cfg.telemetry_out}")


def run_predict(params: Dict[str, Any], cfg: Config) -> None:
    model_path = params.get("input_model")
    if not model_path:
        raise SystemExit("task=predict requires input_model=<model file>")
    if not cfg.data:
        raise SystemExit("task=predict requires data=<input file>")
    # pass the CLI params through: the streaming-engine knobs
    # (pred_chunk_rows / pred_num_buffers / pred_shard_devices /
    # pred_aot_compile) live in Config and must reach the booster
    booster = Booster(params, model_file=model_path)
    loaded = _load_text_file(cfg.data, cfg)
    X = loaded["data"]
    pred = booster.predict(
        X,
        raw_score=cfg.predict_raw_score,
        pred_leaf=cfg.predict_leaf_index,
        pred_contrib=cfg.predict_contrib,
        start_iteration=cfg.start_iteration_predict,
        num_iteration=(
            cfg.num_iteration_predict if cfg.num_iteration_predict > 0 else None
        ),
    )
    out = params.get("output_result", "LightGBM_predict_result.txt")
    np.savetxt(out, np.asarray(pred), fmt="%.10g", delimiter="\t")
    print(f"Finished prediction; results written to {out}")


def run_refit(params: Dict[str, Any], cfg: Config) -> None:
    """task=refit: re-fit leaf values of input_model on new data
    (reference: application.cpp task=refit -> GBDT::RefitTree)."""
    model_path = params.get("input_model")
    if not model_path:
        raise SystemExit("task=refit requires input_model=<model file>")
    if not cfg.data:
        raise SystemExit("task=refit requires data=<training file>")
    booster = Booster(model_file=model_path)
    booster.params.update(params)
    loaded = _load_text_file(cfg.data, cfg)
    new_booster = booster.refit(
        loaded["data"],
        loaded["label"],
        decay_rate=cfg.refit_decay_rate,
        weight=loaded.get("weight"),
        group=loaded.get("group"),
        init_score=loaded.get("init_score"),
    )
    out = params.get("output_model", "LightGBM_model.txt")
    new_booster.save_model(out)
    print(f"Finished refit; model written to {out}")


def run_save_binary(params: Dict[str, Any], cfg: Config) -> None:
    """task=save_binary: load + bin the data, write the binary dataset
    (reference: application.cpp TaskType::kSaveBinary — construct, then
    Dataset::SaveBinaryFile)."""
    if not cfg.data:
        raise SystemExit("task=save_binary requires data=<training file>")
    ds = Dataset(cfg.data, params=params)
    ds.construct()
    out = params.get("output_model", cfg.data + ".bin")
    ds.save_binary(out)
    print(f"Finished saving binary dataset to {out}")


def run_convert_model(params: Dict[str, Any], cfg: Config) -> None:
    """task=convert_model: JSON dump, or standalone if-else C++ with
    convert_model_language=cpp (reference: GBDT::SaveModelToIfElse,
    src/boosting/gbdt_model_text.cpp:289)."""
    model_path = params.get("input_model")
    if not model_path:
        raise SystemExit("task=convert_model requires input_model=<model file>")
    booster = Booster(model_file=model_path)
    lang = str(params.get("convert_model_language", "")).lower()
    if lang in ("cpp", "c++"):
        from .codegen import model_to_cpp

        out = params.get("convert_model", "gbdt_prediction.cpp")
        with open(out, "w") as fp:
            fp.write(model_to_cpp(booster))
        print(f"Model converted to C++ at {out}")
        return
    import json

    out = params.get("convert_model", "gbdt_prediction.json")
    with open(out, "w") as fp:
        json.dump(booster.dump_model(), fp, indent=2)
    print(f"Model dumped to {out}")


def main(argv=None) -> None:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv:
        print(__doc__)
        raise SystemExit(2)
    params = parse_args(argv)
    cfg = Config.from_params(params)
    task = cfg.task
    if task == "train":
        run_train(params, cfg)
    elif task in ("predict", "prediction", "test"):
        run_predict(params, cfg)
    elif task == "convert_model":
        run_convert_model(params, cfg)
    elif task == "save_binary":
        run_save_binary(params, cfg)
    elif task == "refit":
        run_refit(params, cfg)
    else:
        raise SystemExit(f"unknown task: {task!r}")


if __name__ == "__main__":
    main()
