"""Dask interface — cluster front-end over the multi-controller launcher.

Reference analog: python-package/lightgbm/dask.py (worker discovery via
``client.scheduler_info()``, per-worker ``_train_part`` tasks, a
``machines`` string wiring the workers into one training cluster, model
collected from the first worker).

The TPU-native transport differs: instead of the reference's socket
Allreduce ring, every worker process joins a ``jax.distributed``
multi-controller cluster (``lightgbm_tpu.parallel.init_distributed``) and
trains with ``pre_partition`` process-local data — collectives ride XLA
(ICI/DCN).  The dask client is only the *scheduler*: it places one
``_train_part`` task per worker and ships each worker its data partition.

dask itself is optional and duck-typed: any object with
``scheduler_info()`` and ``submit(fn, *args, workers=[addr])`` returning
futures with ``.result()`` works (the test suite drives the whole flow
with a mock client whose "workers" are local subprocesses).

Partition contract
------------------
Training quality and determinism depend on HOW rows land on workers, so
the split rules are explicit:

* plain array-likes (numpy / scipy) are split into ``n_workers``
  CONTIGUOUS row chunks in the caller's row order (``_partition_data``)
  — no shuffling, so a sorted-by-time frame stays time-ordered per
  worker and the model is reproducible for a fixed worker count;
* ranking (``group=``) never splits a query across workers: chunk cuts
  snap to query boundaries, and because multi-process training pads no
  rows, the per-worker row counts must come out EQUAL — otherwise the
  fit fails fast with the offending cut points (rearrange groups or
  change the worker count);
* the per-worker partition is the unit the distributed binner samples
  from (`pre_partition`), so pathological per-worker distributions
  (e.g. one worker holding all positives) are the caller's to avoid —
  same contract as the reference's dask.py, which follows the
  collection's existing partitioning;
* actual dask collections (``dask.array`` / ``dask.dataframe``) are
  REJECTED with guidance rather than silently ``compute()``d on the
  driver: honoring their own partitioning needs ``to_delayed()`` and a
  per-partition scatter, which requires dask at runtime — this
  environment ships without dask, so that path stays unimplemented
  behind the type check in ``_partition_data`` (first thing to lift if
  dask becomes available: map each delayed partition to one worker and
  skip ``_split_rows`` entirely)."""

from __future__ import annotations

import socket
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .sklearn import LGBMClassifier, LGBMRanker, LGBMRegressor


def _worker_addresses(client) -> List[str]:
    """Sorted worker addresses from the scheduler (reference dask.py
    ``_machines_to_worker_map`` input)."""
    info = client.scheduler_info()
    workers = info.get("workers", {})
    if not workers:
        raise ValueError("no dask workers available to train on")
    return sorted(workers)


def _host_of(address: str) -> str:
    """'tcp://10.0.0.5:8786' -> '10.0.0.5'."""
    hp = address.rsplit("://", 1)[-1]
    return hp.rsplit(":", 1)[0] if ":" in hp else hp


def _split_rows(arr, n_parts: int, boundaries: Optional[np.ndarray] = None):
    """Split rows into n_parts contiguous chunks; with ``boundaries``
    (cumulative query sizes) the cuts snap to query boundaries so no query
    is split across workers."""
    n = arr.shape[0] if hasattr(arr, "shape") else len(arr)
    if boundaries is None:
        cuts = [(n * i) // n_parts for i in range(1, n_parts)]
    else:
        cuts = []
        for i in range(1, n_parts):
            target = (n * i) // n_parts
            j = int(np.searchsorted(boundaries, target, side="left"))
            cuts.append(int(boundaries[min(j, len(boundaries) - 1)]))
    out, prev = [], 0
    for c in list(cuts) + [n]:
        out.append(arr[prev:c])
        prev = c
    return out


def _partition_data(X, y, sample_weight, group, n_workers: int):
    """Per-worker part dicts.  Plain array-likes are split contiguously
    (group-aware for ranking).  Real dask collections would arrive already
    partitioned (reference dask.py ``_train`` follows the collection's own
    partitioning); without dask in this environment they are rejected with
    guidance rather than silently gathered."""
    if hasattr(X, "to_delayed") or hasattr(X, "dask"):
        raise NotImplementedError(
            "dask-collection inputs need dask installed at runtime; pass "
            "numpy/scipy arrays instead (they are split contiguously per "
            "worker)"
        )
    boundaries = None
    if group is not None:
        boundaries = np.cumsum(np.asarray(group, np.int64))
        n_rows = int(boundaries[-1])
        # multi-process ranking requires EQUAL per-worker row counts
        # (queries cannot be weight-0 padded, gbdt._init_train) — the even
        # cut must land exactly on query boundaries
        bset = set(int(b) for b in boundaries)
        bad = [
            (n_rows * i) // n_workers
            for i in range(1, n_workers)
            if (n_rows * i) % n_workers or (n_rows * i) // n_workers not in bset
        ]
        if bad:
            raise ValueError(
                "distributed ranking needs query sizes that split the rows "
                f"EQUALLY across {n_workers} workers (queries are never "
                f"split and cannot be padded); no query boundary at row(s) "
                f"{bad} — rearrange groups or change the worker count"
            )
    xs = _split_rows(np.asarray(X), n_workers, boundaries)
    ys = _split_rows(np.asarray(y), n_workers, boundaries)
    ws = (
        _split_rows(np.asarray(sample_weight), n_workers, boundaries)
        if sample_weight is not None
        else [None] * n_workers
    )
    if group is not None:
        g = np.asarray(group, np.int64)
        bounds = np.concatenate([[0], np.cumsum(g)])
        row_cuts = np.concatenate([[0], np.cumsum([x.shape[0] for x in xs])])
        gs = []
        for i in range(n_workers):
            lo = int(np.searchsorted(bounds, row_cuts[i]))
            hi = int(np.searchsorted(bounds, row_cuts[i + 1]))
            gs.append(g[lo:hi])
    else:
        gs = [None] * n_workers
    return [
        {"data": xs[i], "label": ys[i], "weight": ws[i], "group": gs[i]}
        for i in range(n_workers)
    ]


def _train_part(
    params: Dict[str, Any],
    part: Dict[str, Any],
    process_id: int,
    num_processes: int,
    coordinator: str,
    num_boost_round: int,
) -> Optional[str]:
    """Runs ON a worker: join the jax.distributed cluster, train on the
    local partition (``pre_partition`` process-local feeding), return the
    model text from process 0 only (reference dask.py ``_train_part``
    returns the booster on one worker)."""
    from .dataset import Dataset
    from .engine import train as _train
    from .parallel import init_distributed

    if num_processes > 1:
        init_distributed(
            coordinator_address=coordinator,
            num_processes=num_processes,
            process_id=process_id,
        )
    ds_params = dict(params)
    ds_params["pre_partition"] = num_processes > 1
    ds = Dataset(
        part["data"],
        label=part["label"],
        weight=part.get("weight"),
        group=part.get("group"),
        params=ds_params,
    )
    booster = _train(ds_params, ds, num_boost_round=num_boost_round)
    if num_processes > 1:
        import jax

        if jax.process_index() != 0:
            return None
    return booster.model_to_string()


# one-entry per-process booster cache: real dask workers are long-lived, so
# repeated _predict_part calls for the same model skip the text-format parse
_PREDICT_BOOSTER_CACHE: Dict[int, Any] = {}


def _predict_part(
    model_str: str, X_part: np.ndarray, predict_kwargs: Dict[str, Any]
):
    """Runs ON a worker: load (or reuse) the booster and stream the local
    partition through the chunked prediction engine.  Output rides back to
    the driver in partition order."""
    from .boosting.gbdt import Booster

    key = hash(model_str)
    booster = _PREDICT_BOOSTER_CACHE.get(key)
    if booster is None:
        _PREDICT_BOOSTER_CACHE.clear()
        booster = _PREDICT_BOOSTER_CACHE[key] = Booster(model_str=model_str)
    return booster.predict(X_part, **predict_kwargs)


class _DaskLGBMModel:
    """Mixin implementing the distributed fit over a dask-like client."""

    def _resolve_client(self):
        client = getattr(self, "client", None) or self._other_params.get(
            "client"
        )
        if client is None:
            try:
                from distributed import default_client  # type: ignore

                client = default_client()
            except Exception:
                raise ValueError(
                    "no dask client: pass client=... to the estimator"
                )
        return client

    def _dask_fit(self, X, y, sample_weight=None, group=None, **kwargs):
        if kwargs:
            raise NotImplementedError(
                "DaskLGBM fit does not support these arguments yet: "
                + ", ".join(sorted(kwargs))
            )
        if isinstance(self, LGBMClassifier):
            # mirror LGBMClassifier.fit label handling (classes recorded,
            # labels encoded to 0..K-1, num_class set for multiclass)
            y = np.asarray(y)
            self._classes = np.unique(y)
            self._n_classes = len(self._classes)
            y = np.searchsorted(self._classes, y).astype(np.float64)
            if self.objective is None and self._n_classes > 2:
                self._other_params.setdefault("num_class", self._n_classes)
        client = self._resolve_client()
        workers = _worker_addresses(client)
        n = len(workers)
        parts = _partition_data(X, y, sample_weight, group, n)
        params = {
            k: v
            for k, v in self._lgb_params().items()
            if k not in ("client", "local_listen_port")
        }
        params.setdefault("tree_learner", "data")
        # reference dask.py uses local_listen_port (default 12400) as the
        # base of its machines string; here it is the jax.distributed
        # coordinator port on the first worker's host
        port = int(self._other_params.get("local_listen_port", 12400))
        host = _host_of(workers[0])
        if host in ("127.0.0.1", "localhost", ""):
            host = "127.0.0.1"
        coordinator = f"{host}:{port}"
        futures = [
            client.submit(
                _train_part,
                params,
                parts[i],
                i,
                n,
                coordinator,
                self.n_estimators,
                workers=[w],
            )
            for i, w in enumerate(workers)
        ]
        results = [f.result() for f in futures]
        model_str = next(s for s in results if s)
        from .boosting.gbdt import Booster

        self._Booster = Booster(model_str=model_str)
        return self

    def _dask_predict(self, X, **kwargs):
        """Partition-wise streaming predict: contiguous row chunks fan out
        to the workers (same split rule as fit), each worker streams its
        partition through the chunked engine (``_predict_part``), and the
        driver concatenates in partition order — so the result is
        bit-identical to a single-host ``Booster(model_str=...).predict``
        over the same rows."""
        client = self._resolve_client()
        workers = _worker_addresses(client)
        if hasattr(X, "to_delayed") or hasattr(X, "dask"):
            raise NotImplementedError(
                "dask-collection inputs need dask installed at runtime; "
                "pass numpy/scipy arrays (split contiguously per worker)"
            )
        X = np.asarray(X, dtype=np.float64)
        parts = _split_rows(X, len(workers))
        model_str = self.booster_.model_to_string()
        futures = [
            (i, client.submit(_predict_part, model_str, parts[i], kwargs, workers=[w]))
            for i, w in enumerate(workers)
            if parts[i].shape[0]
        ]
        results = [f.result() for _, f in futures]
        if not results:
            return self.booster_.predict(X, **kwargs)  # 0-row input
        return (
            results[0]
            if len(results) == 1
            else np.concatenate(results, axis=0)
        )

    def predict(self, X, distributed: bool = False, **kwargs):
        """Local streaming predict by default; ``distributed=True`` fans the
        rows out to the training workers partition-wise (each worker loads
        the model once and streams its chunk).  Classifier label/proba
        semantics are applied on the driver either way."""
        if not distributed:
            return super().predict(X, **kwargs)
        out = self._dask_predict(X, **kwargs)
        if (
            isinstance(self, LGBMClassifier)
            and not kwargs.get("raw_score")
            and not kwargs.get("pred_leaf")
            and not kwargs.get("pred_contrib")
        ):
            if out.ndim == 1:  # binary: booster emits P(class 1)
                return self._classes[(out > 0.5).astype(int)]
            return self._classes[np.argmax(out, axis=1)]
        return out

    def to_local(self):
        """A plain (non-dask) estimator carrying the trained booster
        (reference dask.py ``to_local``)."""
        cls = {
            DaskLGBMRegressor: LGBMRegressor,
            DaskLGBMClassifier: LGBMClassifier,
            DaskLGBMRanker: LGBMRanker,
        }[type(self)]
        local = cls(**self.get_params())
        local._Booster = self._Booster
        local._classes = getattr(self, "_classes", None)
        local._n_classes = getattr(self, "_n_classes", -1)
        return local


class DaskLGBMRegressor(_DaskLGBMModel, LGBMRegressor):
    def __init__(self, client=None, **kwargs):
        self.client = client
        super().__init__(**kwargs)

    def fit(self, X, y, sample_weight=None, **kwargs):
        return self._dask_fit(X, y, sample_weight=sample_weight, **kwargs)


class DaskLGBMClassifier(_DaskLGBMModel, LGBMClassifier):
    def __init__(self, client=None, **kwargs):
        self.client = client
        super().__init__(**kwargs)

    def fit(self, X, y, sample_weight=None, **kwargs):
        return self._dask_fit(X, y, sample_weight=sample_weight, **kwargs)

    def predict_proba(self, X, distributed: bool = False, **kwargs):
        if not distributed:
            return super().predict_proba(X, **kwargs)
        prob = self._dask_predict(X, **kwargs)
        if self._n_classes <= 2 and prob.ndim == 1:
            return np.stack([1.0 - prob, prob], axis=1)
        return prob


class DaskLGBMRanker(_DaskLGBMModel, LGBMRanker):
    def __init__(self, client=None, **kwargs):
        self.client = client
        super().__init__(**kwargs)

    def fit(self, X, y, sample_weight=None, group=None, **kwargs):
        if group is None:
            raise ValueError("DaskLGBMRanker.fit requires group=")
        return self._dask_fit(
            X, y, sample_weight=sample_weight, group=group, **kwargs
        )
