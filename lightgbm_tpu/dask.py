"""Dask interface placeholder (reference: python-package/lightgbm/dask.py).

dask is not installed in this environment; the TPU-native road to
multi-machine training is a jax.distributed multi-controller run
(``lightgbm_tpu.parallel.launcher`` / ``init_distributed``) — meshes span all
processes' devices and the grower's psum rides ICI/DCN. These classes exist
for API parity and raise with that guidance, mirroring the reference's
behavior when dask is absent.
"""

from __future__ import annotations

_MSG = (
    "dask is not installed; for distributed training use "
    "lightgbm_tpu.parallel.init_distributed (jax.distributed multi-controller) "
    "with tree_learner='data', or the process launcher "
    "`python -m lightgbm_tpu.parallel.launcher -n N script.py`"
)


class _DaskUnavailable:
    def __init__(self, *args, **kwargs):
        raise ImportError(_MSG)


class DaskLGBMClassifier(_DaskUnavailable):
    pass


class DaskLGBMRegressor(_DaskUnavailable):
    pass


class DaskLGBMRanker(_DaskUnavailable):
    pass
