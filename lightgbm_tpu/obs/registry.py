"""Telemetry registry: process-global counters, gauges and per-iteration
event records.

Reference analog: the C++ tree has ``Common::Timer global_timer``
(include/LightGBM/utils/common.h:979) as its only runtime observability.
Here the registry is the structured superset the perf work needs: every hot
path (booster update, grower, streaming predictor, collectives) reports into
one process-global :class:`TelemetrySession`, and each boosting iteration /
predict chunk becomes one JSON-serializable event.

Disabled (the default) the session is a handful of attribute checks — hot
paths test ``session.enabled`` once and skip everything else, so training
pays no measurable overhead.  Enabled, events accumulate in memory
(``session.events``) and, when a sink path is configured, stream to a JSONL
file (one event per line).

Iteration events are written DEFERRED: the event is visible in
``session.events`` immediately, but its JSONL line is flushed when the next
event arrives (or at ``flush_pending``/``close``), so late annotations —
eval metrics computed after the update — land inside the same line.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
from typing import Any, Dict, List, Optional

from .trace import note_phase as _note_phase


class _NullPhase:
    """Shared no-op context manager handed out when telemetry is off."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_PHASE = _NullPhase()


def _jsonable(obj: Any) -> Any:
    """Best-effort conversion of numpy/jax scalars inside an event."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    item = getattr(obj, "item", None)
    if callable(item):
        try:
            return item()
        except Exception:
            pass
    return str(obj)


class TelemetrySession:
    """Process-global telemetry state (counters / gauges / events)."""

    def __init__(self) -> None:
        self.enabled = False
        self.sync_timing = False
        # deep-device observability knobs (obs_device_accounting /
        # obs_collectives): executable cost/memory capture costs an extra
        # trace per jit label, so it is explicit opt-in; measured
        # collectives ride along whenever telemetry is on
        self.device_accounting = False
        self.measure_collectives = False
        self.counters: Dict[str, int] = {}
        self.gauges: Dict[str, float] = {}
        self.events: List[Dict[str, Any]] = []
        self.sink_path = ""
        self._sink = None
        self._pending: Optional[Dict[str, Any]] = None
        self._phases: Optional[Dict[str, float]] = None
        self._lock = threading.Lock()

    # ---------------------------------------------------------- lifecycle
    def configure(
        self,
        enabled: bool = True,
        sync_timing: bool = False,
        sink_path: str = "",
        device_accounting: Optional[bool] = None,
        measure_collectives: Optional[bool] = None,
    ) -> "TelemetrySession":
        """(Re)configure the session; opens the JSONL sink when given."""
        with self._lock:
            self.enabled = bool(enabled)
            self.sync_timing = bool(sync_timing) and self.enabled
            if device_accounting is not None:
                self.device_accounting = bool(device_accounting) and self.enabled
            elif not self.enabled:
                self.device_accounting = False
            if measure_collectives is not None:
                self.measure_collectives = (
                    bool(measure_collectives) and self.enabled
                )
            elif not self.enabled:
                self.measure_collectives = False
            if sink_path != self.sink_path or not enabled:
                self._flush_pending_locked()
                if self._sink is not None:
                    self._sink.close()
                    self._sink = None
                self.sink_path = ""
            if enabled and sink_path and self._sink is None:
                self._sink = open(sink_path, "a")
                self.sink_path = sink_path
        return self

    def close(self) -> None:
        with self._lock:
            self._flush_pending_locked()
            if self._sink is not None:
                self._sink.close()
                self._sink = None
            self.sink_path = ""

    def reset(self) -> None:
        """Clear recorded data; keeps enabled/sink configuration."""
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.events.clear()
            self._pending = None
            self._phases = None

    # --------------------------------------------------- counters / gauges
    def inc(self, name: str, n: int = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def set_gauge(self, name: str, value: float) -> None:
        if not self.enabled:
            return
        with self._lock:
            self.gauges[name] = value

    def update_gauges(self, values: Dict[str, float]) -> None:
        """Set many gauges under one lock acquisition (the serving plane
        publishes its whole latency window atomically so a concurrent
        /metrics scrape never sees p50 from one window and p99 from the
        next)."""
        if not self.enabled:
            return
        with self._lock:
            self.gauges.update(values)

    def set_gauge_max(self, name: str, value: float) -> None:
        """Monotone-max gauge (HBM watermarks, worst-case executable cost
        across ladder buckets: re-recording never lowers the reading)."""
        if not self.enabled:
            return
        with self._lock:
            prev = self.gauges.get(name)
            if prev is None or value > prev:
                self.gauges[name] = value

    def restore_counters(self, counters: Dict[str, int]) -> None:
        """Merge checkpointed counter values into the live session so a
        resumed run's counters continue from the killed run's totals."""
        if not self.enabled:
            return
        with self._lock:
            for name, value in counters.items():
                self.counters[name] = self.counters.get(name, 0) + int(value)

    # -------------------------------------------------------------- events
    def record(self, event: Dict[str, Any], defer: bool = False) -> None:
        """Append an event; write its JSONL line (deferred events are
        flushed when the next event arrives, so they stay annotatable)."""
        if not self.enabled:
            return
        with self._lock:
            self._flush_pending_locked()
            self.events.append(event)
            if self._sink is None:
                return
            if defer:
                self._pending = event
            else:
                self._write_locked(event)

    def record_alert(self, event: Dict[str, Any]) -> None:
        """Record an alert without flushing a deferred iteration event.

        Plain :meth:`record` flushes the pending deferred event first; an
        alert raised between an iteration's ``update`` and its late eval
        annotation must not do that (the annotation would land on the
        alert instead, and the iteration's JSONL line would miss it).  The
        alert is inserted *before* the pending event in ``events`` and its
        JSONL line is written immediately; the pending event stays pending
        and stays ``events[-1]`` for ``annotate_last``.
        """
        if not self.enabled:
            return
        with self._lock:
            if self._pending is not None and self.events and (
                self.events[-1] is self._pending
            ):
                self.events.insert(len(self.events) - 1, event)
            else:
                self.events.append(event)
            if self._sink is not None:
                self._write_locked(event)

    def annotate_last(self, fields: Dict[str, Any]) -> None:
        """Merge fields into the most recent event (pre-flush for JSONL)."""
        if not self.enabled:
            return
        with self._lock:
            if self.events:
                self.events[-1].update(fields)

    def flush_pending(self) -> None:
        with self._lock:
            self._flush_pending_locked()

    def _flush_pending_locked(self) -> None:
        if self._pending is not None and self._sink is not None:
            self._write_locked(self._pending)
        self._pending = None

    def _write_locked(self, event: Dict[str, Any]) -> None:
        self._sink.write(json.dumps(_jsonable(event)) + "\n")
        self._sink.flush()

    # -------------------------------------------------------- phase timing
    def begin_iteration(self) -> None:
        """Open a per-iteration phase accumulator (see :meth:`phase`)."""
        if self.enabled:
            self._phases = {}

    def end_iteration(self) -> Dict[str, float]:
        """Close the accumulator; returns {phase: seconds}."""
        phases, self._phases = self._phases, None
        return phases or {}

    def phase(self, name: str):
        """Context manager accumulating host wall time for ``name`` into the
        open iteration accumulator.  A shared no-op when telemetry is off
        (or no iteration is open), so hot paths can call it unconditionally.
        """
        if not self.enabled or self._phases is None:
            return _NULL_PHASE
        return _PhaseTimer(self._phases, name)

    def sync(self, value: Any) -> None:
        """Block on device values inside a phase when ``obs_sync_timing`` is
        set, so the phase wall measures device time, not dispatch time."""
        if self.enabled and self.sync_timing and value is not None:
            import jax

            jax.block_until_ready(value)


class _PhaseTimer:
    __slots__ = ("_acc", "_name", "_t0")

    def __init__(self, acc: Dict[str, float], name: str) -> None:
        self._acc = acc
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self._acc[self._name] = self._acc.get(self._name, 0.0) + dt
        # phase walls double as trace spans under the open iteration/launch
        # span (obs/trace.py); no-op when tracing is off or no span is open
        _note_phase(self._name, self._t0, dt)
        return False


_SESSION = TelemetrySession()


def get_session() -> TelemetrySession:
    """The process-global telemetry session."""
    return _SESSION


@contextlib.contextmanager
def session_disabled():
    """Temporarily disable telemetry (used by bench harness internals)."""
    prev = _SESSION.enabled
    _SESSION.enabled = False
    try:
        yield
    finally:
        _SESSION.enabled = prev
