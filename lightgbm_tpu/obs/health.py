"""Host-side health watchdog: per-iteration rule evaluation over telemetry.

The watchdog never touches tracers or device values: it is evaluated once
per boosting iteration from the *already-recorded* iteration event and the
live counter/gauge tables (GL003/GL010-clean by construction — everything
it reads was pulled to the host by the telemetry layer under its own
gating).  Each rule emits a severity-tagged ``alert`` event into the
registry (JSONL sink included) and the flight recorder ring, with a
per-rule cooldown so a persistent condition alerts once per window
instead of once per iteration.

Rules (all thresholds are constructor kwargs; config exposes only the
on/off switch to keep the Config surface small):

==================  ========================================================
``throughput``      iteration wall regressed vs an EMA of recent walls
                    (compile iterations excluded — retraces legitimately
                    spike the wall)
``numerics``        the non-finite guard tripped (``numerics/guard_trips``
                    counter delta) — CRITICAL; training is about to abort
``commit_rate``     adaptive-``leaf_batch`` commit-rate EMA collapsed while
                    batched growth is engaged
``refine_rate``     int8 histogram near-tie refine rate spiked — the
                    2-digit accumulator is re-doing too much work in f32,
                    usually a symptom of near-constant gain landscapes
``straggler``       per-host iteration-wall skew (max/mean) exceeds bound
``hbm``             device bytes-in-use grew well past the run's baseline
                    (leak / fragmentation watch)
``serve_deadline``  serving-plane deadline-miss rate exceeded its ceiling
                    (driven by the micro-batcher's windowed stats via
                    :meth:`observe_serving`, not the training iteration
                    cadence)
==================  ========================================================
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .flight import get_flight
from .registry import TelemetrySession, get_session

SEV_WARN = "warn"
SEV_CRITICAL = "critical"

_SEV_RANK = {SEV_WARN: 1, SEV_CRITICAL: 2}


class HealthWatchdog:
    """Stateful per-run watchdog; one instance per training Booster."""

    def __init__(
        self,
        warmup_iters: int = 5,
        cooldown_iters: int = 10,
        activity_window: int = 25,
        throughput_ema_alpha: float = 0.3,
        throughput_factor: float = 3.0,
        commit_rate_floor: float = 0.25,
        refine_rate_ceiling: float = 0.5,
        straggler_skew_ceiling: float = 1.5,
        hbm_growth_factor: float = 1.5,
        hbm_growth_floor_bytes: float = 64 * 1024 * 1024,
        deadline_miss_ceiling: float = 0.25,
        deadline_miss_min_requests: int = 16,
    ) -> None:
        self.warmup_iters = int(warmup_iters)
        self.cooldown_iters = int(cooldown_iters)
        self.activity_window = int(activity_window)
        self.throughput_ema_alpha = float(throughput_ema_alpha)
        self.throughput_factor = float(throughput_factor)
        self.commit_rate_floor = float(commit_rate_floor)
        self.refine_rate_ceiling = float(refine_rate_ceiling)
        self.straggler_skew_ceiling = float(straggler_skew_ceiling)
        self.hbm_growth_factor = float(hbm_growth_factor)
        self.hbm_growth_floor_bytes = float(hbm_growth_floor_bytes)
        self.deadline_miss_ceiling = float(deadline_miss_ceiling)
        self.deadline_miss_min_requests = int(deadline_miss_min_requests)
        self._wall_ema: Optional[float] = None
        self._hbm_baseline: Optional[float] = None
        self._seen = 0
        self._guard_trips_seen = 0
        self._last_fired: Dict[str, int] = {}
        self._last_alert: Dict[str, Dict[str, Any]] = {}
        self._last_iter = -1
        self.alerts_emitted = 0

    # ------------------------------------------------------------ emission
    def _emit(
        self,
        out: List[Dict[str, Any]],
        it: int,
        rule: str,
        severity: str,
        message: str,
        value: float,
        threshold: float,
    ) -> None:
        last = self._last_fired.get(rule)
        if last is not None and (it - last) < self.cooldown_iters:
            # still refresh the remembered alert so health() reflects the
            # latest reading during the cooldown window
            self._last_alert[rule]["value"] = value
            self._last_alert[rule]["iter"] = it
            return
        alert = {
            "event": "alert",
            "rule": rule,
            "severity": severity,
            "iter": it,
            "message": message,
            "value": value,
            "threshold": threshold,
        }
        self._last_fired[rule] = it
        self._last_alert[rule] = alert
        self.alerts_emitted += 1
        out.append(alert)

    # ---------------------------------------------------------- evaluation
    def observe(
        self,
        event: Dict[str, Any],
        ses: Optional[TelemetrySession] = None,
    ) -> List[Dict[str, Any]]:
        """Evaluate all rules against one finished iteration.

        ``event`` is the iteration event dict built by ``Booster.update``;
        gauges/counters are read from the live session.  Emitted alerts are
        recorded into the registry and flight ring, and returned.
        """
        ses = ses or get_session()
        it = int(event.get("iter", self._last_iter + 1))
        self._last_iter = it
        # warmup is counted in ITERATIONS, not observe() calls: a launch
        # event covers `steps` iterations (train_steps_per_launch=N calls
        # observe once per window), so advancing by 1 would silently
        # stretch the warmup window N-fold.  Cooldown and the activity
        # window already use `iter`-denominated arithmetic, which a launch
        # event advances by N on its own.
        self._seen += max(1, int(event.get("steps", 1)))
        out: List[Dict[str, Any]] = []
        gauges = ses.gauges
        counters = ses.counters

        # numerics guard trips: critical, no warmup — a trip at iteration 0
        # matters as much as one at iteration 1000.
        trips = int(counters.get("numerics/guard_trips", 0))
        if trips > self._guard_trips_seen:
            self._emit(
                out, it, "numerics", SEV_CRITICAL,
                "non-finite guard tripped "
                f"({trips - self._guard_trips_seen} new)",
                float(trips), 0.0,
            )
            self._guard_trips_seen = trips

        # throughput EMA regression (compile iterations excluded from both
        # the EMA and the comparison — a retrace wall is not a regression)
        wall = event.get("wall_ms")
        compiled = bool(event.get("compiles_delta"))
        if wall is not None and not compiled:
            wall = float(wall)
            ema = self._wall_ema
            if ema is not None and self._seen > self.warmup_iters:
                bound = self.throughput_factor * ema
                if wall > bound:
                    self._emit(
                        out, it, "throughput", SEV_WARN,
                        f"iteration wall {wall:.1f} ms > "
                        f"{self.throughput_factor:g}x EMA {ema:.1f} ms",
                        wall, bound,
                    )
            a = self.throughput_ema_alpha
            self._wall_ema = wall if ema is None else (1 - a) * ema + a * wall

        # adaptive-leaf_batch commit-rate collapse
        rate = gauges.get("grower.commit_rate")
        k_eff = gauges.get("grower.leaf_batch_effective", 1.0)
        if (
            rate is not None
            and k_eff > 1.0
            and self._seen > self.warmup_iters
            and rate < self.commit_rate_floor
        ):
            self._emit(
                out, it, "commit_rate", SEV_WARN,
                f"batched-growth commit rate {rate:.3f} < "
                f"{self.commit_rate_floor:g} at K={k_eff:g}",
                float(rate), self.commit_rate_floor,
            )

        # int8 near-tie refine-rate spike (only meaningful when engaged)
        refine = gauges.get("hist/near_tie_refine_rate")
        if (
            refine is not None
            and gauges.get("hist/int8_engaged")
            and refine > self.refine_rate_ceiling
        ):
            self._emit(
                out, it, "refine_rate", SEV_WARN,
                f"int8 near-tie refine rate {refine:.3f} > "
                f"{self.refine_rate_ceiling:g}",
                float(refine), self.refine_rate_ceiling,
            )

        # straggler skew (multi-host rollup gauges, when present)
        skew = gauges.get("straggler/skew")
        if skew is not None and skew > self.straggler_skew_ceiling:
            self._emit(
                out, it, "straggler", SEV_WARN,
                f"iteration-wall skew max/mean {skew:.2f} > "
                f"{self.straggler_skew_ceiling:g}",
                float(skew), self.straggler_skew_ceiling,
            )

        # HBM watermark growth vs run baseline
        in_use = gauges.get("memory/hbm_bytes_in_use")
        if in_use is not None:
            base = self._hbm_baseline
            if base is None or in_use < base:
                self._hbm_baseline = base = float(in_use)
            bound = max(
                self.hbm_growth_factor * base,
                base + self.hbm_growth_floor_bytes,
            )
            if in_use > bound:
                self._emit(
                    out, it, "hbm", SEV_WARN,
                    f"device bytes in use {in_use:.3e} > "
                    f"{self.hbm_growth_factor:g}x baseline {base:.3e}",
                    float(in_use), bound,
                )

        if out:
            flight = get_flight()
            for alert in out:
                ses.inc("alerts_total")
                ses.inc(f"alerts/{alert['rule']}")
                ses.record_alert(alert)
                flight.note_alert(alert)
        return out

    def observe_serving(
        self,
        event: Dict[str, Any],
        ses: Optional[TelemetrySession] = None,
    ) -> List[Dict[str, Any]]:
        """Evaluate the serving rules against one micro-batcher stats
        window.  The serving plane has no boosting iterations, so the
        batcher's dispatched-batch count stands in for ``iter`` in the
        cooldown/activity bookkeeping (same monotonic role: one tick per
        unit of work)."""
        ses = ses or get_session()
        it = int(event.get("iter", self._last_iter + 1))
        self._last_iter = max(self._last_iter, it)
        out: List[Dict[str, Any]] = []
        miss = event.get("deadline_miss_rate")
        requests = int(event.get("requests", 0))
        if (
            miss is not None
            and requests >= self.deadline_miss_min_requests
            and miss > self.deadline_miss_ceiling
        ):
            # per-request attribution (when the batcher publishes it) tells
            # the operator WHERE the missed time went without a trace dump:
            # queue wait (worker busy / overload) vs device dispatch
            attribution = ""
            queue_p99 = event.get("queue_ms_p99")
            device_p99 = event.get("device_ms_p99")
            if queue_p99 is not None and device_p99 is not None:
                attribution = (
                    f" (queue p99 {float(queue_p99):.1f} ms, "
                    f"device p99 {float(device_p99):.1f} ms)"
                )
            self._emit(
                out, it, "serve_deadline", SEV_WARN,
                f"serving deadline-miss rate {miss:.3f} > "
                f"{self.deadline_miss_ceiling:g} over {requests} requests"
                + attribution,
                float(miss), self.deadline_miss_ceiling,
            )
        if out:
            flight = get_flight()
            for alert in out:
                ses.inc("alerts_total")
                ses.inc(f"alerts/{alert['rule']}")
                ses.record_alert(alert)
                flight.note_alert(alert)
        return out

    def note_fault(
        self,
        rule: str,
        it: int,
        message: str,
        ses: Optional[TelemetrySession] = None,
    ) -> Dict[str, Any]:
        """Register an externally-detected critical fault (guard-rail trip)
        as an active alert — used by the fault-dump path, which runs
        outside the per-iteration :meth:`observe` cadence.  Syncs the
        guard-trip counter watermark so a later observe doesn't re-alert
        the same trip."""
        alert = {
            "event": "alert",
            "rule": rule,
            "severity": SEV_CRITICAL,
            "iter": int(it),
            "message": message,
            "value": 1.0,
            "threshold": 0.0,
        }
        self._last_fired[rule] = int(it)
        self._last_alert[rule] = alert
        self._last_iter = max(self._last_iter, int(it))
        self.alerts_emitted += 1
        if ses is not None:
            self._guard_trips_seen = int(
                ses.counters.get(
                    "numerics/guard_trips", self._guard_trips_seen
                )
            )
        return alert

    # -------------------------------------------------------------- status
    def active_alerts(self) -> List[Dict[str, Any]]:
        """Alerts whose rule fired within the recent activity window."""
        return [
            dict(alert)
            for rule, alert in sorted(self._last_alert.items())
            if self._last_iter - self._last_fired[rule] <= self.activity_window
        ]

    def status(self) -> str:
        """Worst severity among active alerts: ``ok``/``warn``/``critical``."""
        worst = 0
        for alert in self.active_alerts():
            worst = max(worst, _SEV_RANK.get(alert["severity"], 1))
        return {0: "ok", 1: SEV_WARN, 2: SEV_CRITICAL}[worst]
