"""Live device-memory watermarks via ``device.memory_stats()``.

Sampled at phase boundaries (after grow, at iteration end, after a
streaming-predict run) when ``obs_device_accounting`` is on.  TPU/GPU
runtimes report allocator stats (``bytes_in_use`` / ``peak_bytes_in_use``);
the CPU backend returns ``None`` — the first unsupported probe latches a
process-global flag so every later call is a single boolean test (the
documented graceful no-op; see README "Deep profiling").
"""

from __future__ import annotations

from typing import Optional

from .registry import get_session

_SUPPORTED: Optional[bool] = None  # None = not probed yet


def sample_device_memory(tag: str = "") -> None:
    """Record HBM in-use/peak gauges summed over local devices.

    Gauges: ``memory/hbm_bytes_in_use`` (last sample),
    ``memory/hbm_peak_bytes`` (max-merged watermark) and, with ``tag``,
    ``memory/hbm_peak_bytes/<tag>`` for the phase-resolved watermark.
    """
    ses = get_session()
    if not (ses.enabled and ses.device_accounting):
        return
    global _SUPPORTED
    if _SUPPORTED is False:
        return
    import jax

    in_use = 0
    peak = 0
    found = False
    try:
        devices = jax.local_devices()
    except Exception:
        _SUPPORTED = False
        return
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        found = True
        used = int(stats.get("bytes_in_use", 0))
        in_use += used
        peak += int(stats.get("peak_bytes_in_use", used))
    if not found:
        _SUPPORTED = False
        return
    _SUPPORTED = True
    ses.set_gauge("memory/hbm_bytes_in_use", float(in_use))
    ses.set_gauge_max("memory/hbm_peak_bytes", float(peak))
    if tag:
        ses.set_gauge_max(f"memory/hbm_peak_bytes/{tag}", float(peak))


def device_memory_supported() -> Optional[bool]:
    """Tri-state: True/False once probed, None before the first sample."""
    return _SUPPORTED
