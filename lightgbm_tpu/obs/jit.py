"""Compile accounting: count actual XLA retraces across the whole library.

``jax.jit`` only re-invokes the wrapped Python callable on a trace-cache
miss, so wrapping the function with a counter increment counts retraces
EXACTLY — including AOT ``fn.lower(...).compile()`` paths, which trace once
per lower.  Every ``jax.jit`` call site in the library routes through
:func:`instrumented_jit`; the streaming predictor's executable cache
additionally reports each compiled bucket via :func:`note_compile`, so
``compile_count()`` is the one process-global number a no-recompile test can
assert on (generalizing ``predict.streaming_compile_count()``).
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Dict, Optional

import jax

_lock = threading.Lock()
_count = 0
_by_label: Dict[str, int] = {}


def note_compile(label: str = "jit") -> None:
    """Record one trace/compile under ``label``."""
    global _count
    with _lock:
        _count += 1
        _by_label[label] = _by_label.get(label, 0) + 1


def compile_count() -> int:
    """Total traces/compiles this process (instrumented jits + the
    streaming predictor's AOT bucket executables)."""
    # the read takes _lock like note_compile's read-modify-write: int loads
    # are CPython-atomic, but pairing the read with the lock keeps the
    # counter exact under free-threaded builds and guarantees a reader
    # never observes _count and _by_label mid-update relative to each other
    with _lock:
        return _count


def compile_counts_by_label() -> Dict[str, int]:
    """Per-call-site breakdown of :func:`compile_count`."""
    with _lock:
        return dict(_by_label)


def instrumented_jit(fun=None, *, label: Optional[str] = None, **jit_kwargs):
    """Drop-in ``jax.jit`` that counts retraces.

    Usable like ``jax.jit``: direct call, decorator, or through
    ``functools.partial``-style keyword binding::

        f = instrumented_jit(impl)
        @instrumented_jit
        def g(x): ...
        @functools.partial(instrumented_jit, static_argnames=("n",))
        def h(x, n): ...

    ``functools.wraps`` preserves ``__wrapped__`` so jax's signature
    inspection (static_argnames resolution) sees the original function.
    """
    if fun is None:
        return functools.partial(instrumented_jit, label=label, **jit_kwargs)
    name = label or getattr(fun, "__name__", "jit")

    @functools.wraps(fun)
    def _traced(*args: Any, **kwargs: Any):
        note_compile(name)
        return fun(*args, **kwargs)

    return jax.jit(_traced, **jit_kwargs)
