"""Compile accounting: count actual XLA retraces across the whole library,
and (opt-in) capture each compiled executable's cost/memory analysis.

``jax.jit`` only re-invokes the wrapped Python callable on a trace-cache
miss, so wrapping the function with a counter increment counts retraces
EXACTLY — including AOT ``fn.lower(...).compile()`` paths, which trace once
per lower.  Every ``jax.jit`` call site in the library routes through
:func:`instrumented_jit`; the streaming predictor's executable cache
additionally reports each compiled bucket via :func:`note_compile`, so
``compile_count()`` is the one process-global number a no-recompile test can
assert on (generalizing ``predict.streaming_compile_count()``).

Executable accounting (``obs_device_accounting=True``): when a call
retraces, the wrapper re-lowers with the same concrete arguments and records
``Compiled.cost_analysis()`` (FLOPs, bytes accessed) and
``Compiled.memory_analysis()`` (temp/argument/output/generated-code bytes)
as per-label ``cost/*`` / ``memory/*`` gauges.  The re-lower traces the
function a second time, which is why this is opt-in; the duplicate trace is
suppressed from the retrace counters so the no-recompile invariants stay
exact.  A cache HIT on a label whose analyses are not yet known (it was
traced before accounting was enabled — an earlier train in the same
process) triggers the same one-time capture; after that, hits just replay
the memoized gauge values into the current session, so a session started
after the traces were made still sees the full cost/memory families.
Backends whose executables expose neither analysis degrade to a silent
no-op (absent gauge keys, never an error).
"""

from __future__ import annotations

import functools
import threading
from typing import Any, Dict, Optional

import jax

from .registry import get_session

_lock = threading.Lock()
_count = 0
_by_label: Dict[str, int] = {}
# bumped on every counted trace: __call__ compares before/after to detect
# "this call traced" without touching jax internals
_epoch = 0
_tls = threading.local()  # .suppress set during the accounting re-lower


def note_compile(label: str = "jit") -> None:
    """Record one trace/compile under ``label``."""
    global _count, _epoch
    if getattr(_tls, "suppress", False):
        return  # accounting re-lower: not a new logical trace
    with _lock:
        _count += 1
        _epoch += 1
        _by_label[label] = _by_label.get(label, 0) + 1


def compile_count() -> int:
    """Total traces/compiles this process (instrumented jits + the
    streaming predictor's AOT bucket executables)."""
    # the read takes _lock like note_compile's read-modify-write: int loads
    # are CPython-atomic, but pairing the read with the lock keeps the
    # counter exact under free-threaded builds and guarantees a reader
    # never observes _count and _by_label mid-update relative to each other
    with _lock:
        return _count


def compile_counts_by_label() -> Dict[str, int]:
    """Per-call-site breakdown of :func:`compile_count`."""
    with _lock:
        return dict(_by_label)


def _trace_epoch() -> int:
    with _lock:
        return _epoch


# --------------------------------------------------- executable accounting
_COST_KEYS = (("flops", "flops"), ("bytes accessed", "bytes_accessed"))
_MEMORY_KEYS = (
    ("temp_size_in_bytes", "temp_bytes"),
    ("argument_size_in_bytes", "argument_bytes"),
    ("output_size_in_bytes", "output_bytes"),
    ("generated_code_size_in_bytes", "generated_code_bytes"),
)
# analyses survive session resets: a label traced before this session (an
# earlier train in the same process, a predictor ladder already warm) can
# replay its recorded gauges into the fresh session without re-lowering
_seen_executables: Dict[Any, Dict[str, float]] = {}  # (label, id(compiled))
_label_analyses: Dict[str, Dict[str, float]] = {}  # label -> gauge values


def _extract_analyses(label: str, compiled: Any) -> Dict[str, float]:
    """Pull cost/memory analysis out of a ``Compiled`` as a gauge-name ->
    value map.  Any backend that raises or returns nothing for an analysis
    contributes no keys — graceful no-op."""
    out: Dict[str, float] = {}
    try:
        ca = compiled.cost_analysis()
    except Exception:
        ca = None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if isinstance(ca, dict):
        for src, dst in _COST_KEYS:
            v = ca.get(src)
            if isinstance(v, (int, float)) and v >= 0:
                out[f"cost/{label}/{dst}"] = float(v)
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        for src, dst in _MEMORY_KEYS:
            v = getattr(ma, src, None)
            if isinstance(v, (int, float)) and v >= 0:
                out[f"memory/{label}/{dst}"] = float(v)
    return out


def record_executable(label: str, compiled: Any) -> None:
    """Record a ``Compiled``'s cost/memory analysis as per-label gauges.

    Gauges are max-merged: a label compiled at several shapes (ladder
    buckets, retraces) reports its worst case.
    """
    ses = get_session()
    vals = _extract_analyses(label, compiled)
    prior = _label_analyses.setdefault(label, {})
    for name, v in vals.items():
        prior[name] = max(prior.get(name, 0.0), v)
        ses.set_gauge_max(name, v)


def note_executable(label: str, compiled: Any) -> None:
    """Record an already-AOT-compiled executable (streaming predictor's
    bucket ladder).  Analysis runs once per object; repeat cache hits only
    replay the recorded gauges (so a fresh session still sees them)."""
    ses = get_session()
    if not (ses.enabled and ses.device_accounting):
        return
    key = (label, id(compiled))
    vals = _seen_executables.get(key)
    if vals is None:
        vals = _extract_analyses(label, compiled)
        _seen_executables[key] = vals
        prior = _label_analyses.setdefault(label, {})
        for name, v in vals.items():
            prior[name] = max(prior.get(name, 0.0), v)
    for name, v in vals.items():
        ses.set_gauge_max(name, v)


def _has_tracer(leaves) -> bool:
    return any(isinstance(l, jax.core.Tracer) for l in leaves)


class _InstrumentedJit:
    """``jax.jit`` wrapper that counts retraces and (opt-in) captures the
    compiled executable's cost/memory analysis on each trace."""

    def __init__(self, fun, label: str, jit_kwargs: Dict[str, Any]) -> None:
        self._label = label
        # introspectable by the lint IR pass (GL013 donation audit) and any
        # other tooling that needs the entry's declared jit contract
        self.jit_kwargs: Dict[str, Any] = dict(jit_kwargs)

        @functools.wraps(fun)
        def _traced(*args: Any, **kwargs: Any):
            note_compile(label)
            return fun(*args, **kwargs)

        self._jit = jax.jit(_traced, **jit_kwargs)
        # __wrapped__/__name__ flow through so jax's signature inspection
        # (static_argnames resolution by callers) sees the original function
        functools.update_wrapper(self, fun)

    def __call__(self, *args: Any, **kwargs: Any):
        ses = get_session()
        if not (ses.enabled and ses.device_accounting):
            return self._jit(*args, **kwargs)
        before = _trace_epoch()
        out = self._jit(*args, **kwargs)
        if _trace_epoch() != before:
            self._capture(args, kwargs)
        else:
            cached = _label_analyses.get(self._label)
            if cached is None:
                # cache hit on a trace made before accounting was enabled
                # (e.g. an earlier train in this process): lower once to
                # recover the artifact, then the label is cached for good
                self._capture(args, kwargs)
            else:
                for name, v in cached.items():
                    ses.set_gauge_max(name, v)
        return out

    def _capture(self, args, kwargs) -> None:
        """Re-lower with the call's concrete args and record the compiled
        artifact's analyses.  Never raises: accounting must not break
        training.  Skipped under an outer trace (tracer args — e.g. a
        nested jit inside shard_map), where lowering is not meaningful."""
        try:
            leaves = jax.tree_util.tree_leaves((args, kwargs))
            if _has_tracer(leaves):
                return
            # memoize the attempt (even an empty result) so a backend whose
            # executables expose no analyses is not re-lowered on every call
            _label_analyses.setdefault(self._label, {})
            _tls.suppress = True
            try:
                lowered = self._jit.lower(*args, **kwargs)
                compiled = lowered.compile()
            finally:
                _tls.suppress = False
            record_executable(self._label, compiled)
            self._record_donated(lowered)
        except Exception:
            pass

    def _record_donated(self, lowered: Any) -> None:
        """Gauge ``memory/<label>/donated_bytes``: HBM the entry hands back
        to the allocator per call (``args_info`` donated flags x aval
        bytes).  Lowering-level, so it is exact even on backends where the
        runtime ignores donation (CPU)."""
        try:
            total = 0
            for info in jax.tree_util.tree_leaves(lowered.args_info):
                if not getattr(info, "donated", False):
                    continue
                shape = getattr(info, "shape", None)
                dtype = getattr(info, "dtype", None)
                if shape is None or not hasattr(dtype, "itemsize"):
                    continue
                n = 1
                for d in shape:
                    n *= int(d)
                total += n * int(dtype.itemsize)
            if not total:  # only donating entries contribute a gauge
                return
            name = f"memory/{self._label}/donated_bytes"
            prior = _label_analyses.setdefault(self._label, {})
            prior[name] = max(prior.get(name, 0.0), float(total))
            get_session().set_gauge_max(name, float(total))
        except Exception:
            pass

    def lower(self, *args: Any, **kwargs: Any):
        return self._jit.lower(*args, **kwargs)

    def __getattr__(self, name: str):
        # delegate everything else (clear_cache, eval_shape, ...) to the jit
        return getattr(self._jit, name)


def instrumented_jit(fun=None, *, label: Optional[str] = None, **jit_kwargs):
    """Drop-in ``jax.jit`` that counts retraces (and, with
    ``obs_device_accounting``, captures executable cost/memory analysis).

    Usable like ``jax.jit``: direct call, decorator, or through
    ``functools.partial``-style keyword binding::

        f = instrumented_jit(impl)
        @instrumented_jit
        def g(x): ...
        @functools.partial(instrumented_jit, static_argnames=("n",))
        def h(x, n): ...
    """
    if fun is None:
        return functools.partial(instrumented_jit, label=label, **jit_kwargs)
    name = label or getattr(fun, "__name__", "jit")
    return _InstrumentedJit(fun, name, jit_kwargs)
