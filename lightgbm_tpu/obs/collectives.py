"""Measured collectives: timed psum/pmax wrappers for the grower's sites.

The analytic model (``parallel.psum_bytes_per_iteration``) predicts the
bytes the data-parallel grower's psums move; this module MEASURES them.
Each wrapped site stages two tiny host callbacks around the collective:

* ``begin`` reads ``time.perf_counter_ns`` (packed into 2x uint32 — an f32
  payload loses ns precision) after the operand is ready;
* ``end`` fires once the collective's result is ready and accumulates
  ``{calls, bytes, wall_ns}`` per site into a host-side accumulator.

Ordering is by data dependency, not ``ordered=True``: the begin timestamp is
folded into the operand (``x + 0``) and the end callback consumes both the
timestamp and a probe of the result, so XLA cannot move either across the
collective.  Payload bytes come from traced shapes — exact, no host sync.

Per-device semantics: every mesh device executes the callbacks, so the
accumulator holds ``mesh_size`` times the logical payload; the booster
divides by the mesh size when it rolls a snapshot into per-iteration
telemetry (``collective_measured/*``).

``measure`` is a TRACE-TIME flag: it rides in ``GrowerParams`` (a static jit
argument), so toggling it retraces instead of silently reusing a stale
trace.  With ``measure=False`` the wrappers compile to the bare collective.

Double-buffered sites: the grower's overlap path (``overlap_collectives``)
splits the frontier histogram psum into ``hist_db0`` / ``hist_db1`` —
member-half k's reduction issued while member-half k+1's histograms build.
Both buffers are measured like any other site; ``measured_summary`` sums
every ``psum/*`` key, so the per-iteration byte total is invariant under
overlap on/off (the same payload, in two launches).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .trace import note_collective

_LOCK = threading.Lock()
_ACC: Dict[str, Dict[str, float]] = {}  # site -> calls / bytes / wall_ns

_T0_SHAPE = jax.ShapeDtypeStruct((2,), jnp.uint32)
_TE_SHAPE = jax.ShapeDtypeStruct((), jnp.uint32)


def _begin_host(_probe) -> np.ndarray:
    t = time.perf_counter_ns()
    return np.array([t >> 32, t & 0xFFFFFFFF], np.uint32)


def _end_host(site: str, nbytes: int, t0, _probe) -> np.ndarray:
    t = time.perf_counter_ns()
    t0 = np.asarray(t0, np.uint64)
    start = (int(t0[0]) << 32) | int(t0[1])
    with _LOCK:
        acc = _ACC.setdefault(
            site, {"calls": 0, "bytes": 0, "wall_ns": 0}
        )
        acc["calls"] += 1
        acc["bytes"] += nbytes
        acc["wall_ns"] += max(0, t - start)
    # the measured site doubles as a trace span with payload-byte args,
    # parented under the ambient training span (host clocks only — the
    # begin/end brackets above are already concrete host ints)
    note_collective(site, start, t, nbytes)
    return np.uint32(0)


def collectives_snapshot(reset: bool = False) -> Dict[str, Dict[str, float]]:
    """Copy of the per-site accumulator; ``reset=True`` also clears it."""
    with _LOCK:
        out = {k: dict(v) for k, v in _ACC.items()}
        if reset:
            _ACC.clear()
    return out


def _payload_bytes(leaves) -> int:
    # no int()/float() on traced values: .size and .dtype.itemsize are
    # static python ints even on tracers
    total = 0
    for l in leaves:
        total += l.size * l.dtype.itemsize
    return total


def _timed(op, x, axis_name, site: str):
    from jax.experimental import io_callback

    leaves = jax.tree_util.tree_leaves(x)
    nbytes = _payload_bytes(leaves)
    # probe: 1-element slice of the first operand leaf, so `begin` cannot
    # fire before the operand exists (timestamps bracket the collective)
    probe = lax.reshape(leaves[0], (leaves[0].size,))[:1]
    t0 = io_callback(_begin_host, _T0_SHAPE, probe)
    zero_in = (t0[0] ^ t0[0]).astype(jnp.uint32)  # == 0, depends on t0
    x = jax.tree_util.tree_map(
        lambda l: l + zero_in.astype(l.dtype), x
    )
    out = op(x, axis_name)
    out_leaves = jax.tree_util.tree_leaves(out)
    out_probe = lax.reshape(out_leaves[0], (out_leaves[0].size,))[:1]
    te = io_callback(
        functools.partial(_end_host, site, nbytes), _TE_SHAPE, t0, out_probe
    )
    zero_out = (te ^ te).astype(jnp.uint32)
    return jax.tree_util.tree_map(
        lambda l: l + zero_out.astype(l.dtype), out
    )


def timed_psum(x, axis_name: Optional[str], *, site: str, measure: bool = False):
    """``lax.psum`` that (when ``measure``) logs wall time and bytes."""
    if not measure or axis_name is None:
        return lax.psum(x, axis_name)
    return _timed(lax.psum, x, axis_name, f"psum/{site}")


def timed_pmax(x, axis_name: Optional[str], *, site: str, measure: bool = False):
    """``lax.pmax`` with the same instrumentation as :func:`timed_psum`."""
    if not measure or axis_name is None:
        return lax.pmax(x, axis_name)
    return _timed(lax.pmax, x, axis_name, f"pmax/{site}")


def timed_pmin(x, axis_name: Optional[str], *, site: str, measure: bool = False):
    """``lax.pmin`` with the same instrumentation as :func:`timed_psum`."""
    if not measure or axis_name is None:
        return lax.pmin(x, axis_name)
    return _timed(lax.pmin, x, axis_name, f"pmin/{site}")


def measured_summary(
    snapshot: Dict[str, Dict[str, float]], mesh_size: int
) -> Dict[str, float]:
    """Collapse a per-site snapshot to LOGICAL totals (one device's view).

    Every device runs the callbacks, so calls/bytes divide by the mesh
    size; wall_ns is averaged the same way (mean across devices)."""
    d = max(1, int(mesh_size))
    bytes_total = sum(v["bytes"] for v in snapshot.values())
    psum_bytes = sum(
        v["bytes"] for k, v in snapshot.items() if k.startswith("psum/")
    )
    calls = sum(v["calls"] for v in snapshot.values())
    wall_ns = sum(v["wall_ns"] for v in snapshot.values())
    return {
        "bytes": bytes_total / d,
        "psum_bytes": psum_bytes / d,
        "calls": calls / d,
        "wall_ms": wall_ns / d / 1e6,
    }
