"""Distributed tracing: an always-on span recorder exporting Chrome trace JSON.

The rest of the obs plane speaks counters, gauges and windowed percentiles;
this module answers "where did *this* request's 13.6 ms go?" and "what
happened *inside* launch window [24, 32)?" with a correlated span timeline
loadable in Perfetto / ``chrome://tracing``.

Design mirrors the flight recorder (``obs/flight.py``): one process-global
:class:`TraceRecorder` holding a bounded ring of finished spans, always on
by default, near-zero cost when idle — span creation is one attribute check
when inactive, and recording is a dict append under a lock.  Spans carry
stable ``trace_id``/``span_id``/``parent_id`` links (W3C trace-context
sized: 16-byte / 8-byte hex), monotonic-clock timestamps
(``time.perf_counter_ns`` — host clocks ONLY, never tracer values, so the
recorder is GL003-clean by construction), and a category used by the
per-category sampling knobs.

Span taxonomy (see README "Distributed tracing"):

* ``train``      — ``train/run`` > ``train/launch`` > ``train/iteration``
                   (launch-window per-iteration children are reconstructed
                   from device-side counters and flagged ``synthetic: true``
                   — device-uniform time division, not measurement)
* ``phase``      — ``registry.phase`` timers as children of the open
                   iteration/launch span
* ``collective`` — ``timed_psum``/``timed_pmax`` sites with payload bytes
* ``serve``      — ``serve/batch`` > {``serve/request`` >
                   ``serve/queue_wait``, ``serve/batch_assembly``,
                   ``serve/device_dispatch``, ``serve/unpad_respond``}
* ``lifecycle``  — checkpoint writes, hot-swap warm/flip/drain, refresh
                   refits, degradation latches, fault dumps

Export is the Chrome trace-event JSON array format (``ph``/``ts``/``dur``/
``pid``/``tid``), written atomically (tmp+fsync+rename) on demand
(``Booster.dump_trace``, ``GET /trace``) and automatically next to every
flight dump (``trace_<ts>_<pid>_<n>.json`` pairs ``flight_...``).
"""

from __future__ import annotations

import contextlib
import json
import os
import re
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple, Union

from .flight import _atomic_write_text

TRACE_SCHEMA = "lgbtpu.trace.v1"

MIN_CAPACITY = 64
DEFAULT_CAPACITY = 4096

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$"
)


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """Parse a W3C ``traceparent`` header into ``(trace_id, parent_span_id)``.

    Returns None for missing/malformed headers and for the all-zero ids the
    spec reserves as invalid."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    trace_id, parent_id = m.group(2), m.group(3)
    if trace_id == "0" * 32 or parent_id == "0" * 16:
        return None
    return trace_id, parent_id


def format_traceparent(trace_id: str, span_id: str) -> str:
    """Render a W3C ``traceparent`` header (version 00, sampled flag)."""
    return f"00-{trace_id}-{span_id}-01"


class SpanHandle:
    """An open span: identity + start time; recorded when ended."""

    __slots__ = (
        "name", "cat", "trace_id", "span_id", "parent_id",
        "t0_us", "args", "tid", "_attached", "_ambient",
    )

    def __init__(
        self, name: str, cat: str, trace_id: str, span_id: str,
        parent_id: Optional[str], t0_us: int, args: Dict[str, Any], tid: int,
    ) -> None:
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.t0_us = t0_us
        self.args = args
        self.tid = tid
        self._attached = False
        self._ambient = False


class TraceRecorder:
    """Bounded ring of finished spans with Chrome trace-event export."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self._lock = threading.Lock()
        self._spans: Deque[Dict[str, Any]] = deque(
            maxlen=max(MIN_CAPACITY, int(capacity))
        )
        self.active = True
        self.default_rate = 1.0
        self.rates: Dict[str, float] = {}
        self.spans_total = 0
        self.dropped_total = 0
        self.last_dump_path = ""
        self.dump_count = 0
        self._cat_seen: Dict[str, int] = {}
        self._tls = threading.local()
        self._ambient: Optional[SpanHandle] = None
        # thread ident -> (small tid, thread name) for readable Perfetto rows
        self._tids: Dict[int, Tuple[int, str]] = {}

    # ---------------------------------------------------------- lifecycle
    def configure(
        self,
        capacity: Optional[int] = None,
        active: Optional[bool] = None,
        default_rate: Optional[float] = None,
        rates: Optional[Dict[str, float]] = None,
    ) -> "TraceRecorder":
        """(Re)configure; shrinking the ring counts truncated spans as
        dropped so the eviction accounting stays honest."""
        with self._lock:
            if capacity is not None and capacity != self._spans.maxlen:
                cap = max(MIN_CAPACITY, int(capacity))
                lost = max(0, len(self._spans) - cap)
                self.dropped_total += lost
                self._spans = deque(self._spans, maxlen=cap)
            if active is not None:
                self.active = bool(active)
            if default_rate is not None:
                self.default_rate = min(1.0, max(0.0, float(default_rate)))
            if rates is not None:
                self.rates = {
                    str(k): min(1.0, max(0.0, float(v)))
                    for k, v in rates.items()
                }
        return self

    def reset(self) -> None:
        """Clear spans and counters; keeps capacity/active/sampling."""
        with self._lock:
            self._spans.clear()
            self.spans_total = 0
            self.dropped_total = 0
            self._cat_seen.clear()
            self._ambient = None

    @property
    def capacity(self) -> int:
        return self._spans.maxlen or 0

    # ------------------------------------------------------------- helpers
    @staticmethod
    def now_us() -> int:
        """Monotonic microseconds (same epoch as ``time.perf_counter``)."""
        return time.perf_counter_ns() // 1000

    @staticmethod
    def new_trace_id() -> str:
        return os.urandom(16).hex()

    @staticmethod
    def new_span_id() -> str:
        return os.urandom(8).hex()

    def _tid(self) -> int:
        ident = threading.get_ident()
        got = self._tids.get(ident)
        if got is None:
            with self._lock:
                got = self._tids.get(ident)
                if got is None:
                    got = (len(self._tids) + 1, threading.current_thread().name)
                    self._tids[ident] = got
        return got[0]

    def _sampled(self, cat: str) -> bool:
        """Deterministic per-category sampling: of every K spans in a
        category, accept ~rate*K (counter-based, reproducible in tests)."""
        rate = self.rates.get(cat, self.default_rate)
        if rate >= 1.0:
            return True
        if rate <= 0.0:
            return False
        with self._lock:
            n = self._cat_seen.get(cat, 0) + 1
            self._cat_seen[cat] = n
        return int(n * rate) > int((n - 1) * rate)

    def _append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped_total += 1
            self._spans.append(rec)
            self.spans_total += 1

    # ---------------------------------------------------------- span API
    def current(self) -> Optional[SpanHandle]:
        """The innermost open span on this thread, else the ambient span
        (the open training iteration/launch — used by host callbacks that
        fire on runtime threads, e.g. measured collectives)."""
        stack = getattr(self._tls, "stack", None)
        if stack:
            return stack[-1]
        return self._ambient

    def begin(
        self,
        name: str,
        cat: str = "train",
        *,
        trace_id: Optional[str] = None,
        parent: Optional[Union[SpanHandle, str]] = None,
        args: Optional[Dict[str, Any]] = None,
        attach: bool = False,
        ambient: bool = False,
    ) -> Optional[SpanHandle]:
        """Open a span; returns None when inactive or sampled out (every
        consumer treats a None handle as a no-op).  ``attach`` pushes the
        span on this thread's parent stack so nested begins/phases become
        children; ``ambient`` additionally publishes it as the process-wide
        fallback parent for cross-thread children."""
        if not self.active or not self._sampled(cat):
            return None
        cur = self.current()
        parent_id: Optional[str] = None
        if isinstance(parent, SpanHandle):
            parent_id = parent.span_id
            trace_id = trace_id or parent.trace_id
        elif isinstance(parent, str) and parent:
            parent_id = parent
        elif cur is not None:
            parent_id = cur.span_id
            trace_id = trace_id or cur.trace_id
        h = SpanHandle(
            name, cat, trace_id or self.new_trace_id(), self.new_span_id(),
            parent_id, self.now_us(), dict(args or {}), self._tid(),
        )
        if attach:
            stack = getattr(self._tls, "stack", None)
            if stack is None:
                stack = self._tls.stack = []
            stack.append(h)
            h._attached = True
        if ambient:
            self._ambient = h
            h._ambient = True
        return h

    def end(
        self,
        handle: Optional[SpanHandle],
        extra: Optional[Dict[str, Any]] = None,
        end_us: Optional[int] = None,
    ) -> None:
        """Close a span and record it; a None handle is a no-op."""
        if handle is None:
            return
        if handle._attached:
            stack = getattr(self._tls, "stack", None)
            if stack and handle in stack:
                stack.remove(handle)
            handle._attached = False
        if handle._ambient:
            if self._ambient is handle:
                self._ambient = None
            handle._ambient = False
        if extra:
            handle.args.update(extra)
        t1 = self.now_us() if end_us is None else int(end_us)
        self._append(
            {
                "name": handle.name,
                "cat": handle.cat,
                "trace_id": handle.trace_id,
                "span_id": handle.span_id,
                "parent_id": handle.parent_id,
                "ts": handle.t0_us,
                "dur": max(0, t1 - handle.t0_us),
                "tid": handle.tid,
                "args": handle.args,
            }
        )

    @contextlib.contextmanager
    def span(self, name: str, cat: str = "train", **kwargs):
        """Context-managed span, attached as the current parent."""
        h = self.begin(name, cat, attach=True, **kwargs)
        try:
            yield h
        finally:
            self.end(h)

    def instant(
        self,
        name: str,
        cat: str = "lifecycle",
        args: Optional[Dict[str, Any]] = None,
        parent: Optional[Union[SpanHandle, str]] = None,
    ) -> None:
        """Record a zero-duration (Chrome ``ph: "i"``) event."""
        if not self.active or not self._sampled(cat):
            return
        trace_id = None
        parent_id = None
        if isinstance(parent, SpanHandle):
            parent_id, trace_id = parent.span_id, parent.trace_id
        elif isinstance(parent, str) and parent:
            parent_id = parent
        else:
            cur = self.current()
            if cur is not None:
                parent_id, trace_id = cur.span_id, cur.trace_id
        self._append(
            {
                "name": name,
                "cat": cat,
                "trace_id": trace_id or self.new_trace_id(),
                "span_id": self.new_span_id(),
                "parent_id": parent_id,
                "ts": self.now_us(),
                "dur": None,
                "tid": self._tid(),
                "args": dict(args or {}),
            }
        )

    def add_span(
        self,
        name: str,
        cat: str,
        t0_us: int,
        dur_us: int,
        *,
        trace_id: Optional[str] = None,
        span_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        args: Optional[Dict[str, Any]] = None,
        synthetic: bool = False,
        tid: Optional[int] = None,
    ) -> Optional[str]:
        """Record a finished span with explicit timestamps (the launch
        replay's synthetic per-iteration children and the batcher's stage
        decomposition both build spans after the fact).  Bypasses sampling
        — the enclosing span already made the sampling decision."""
        if not self.active:
            return None
        sid = span_id or self.new_span_id()
        rec = {
            "name": name,
            "cat": cat,
            "trace_id": trace_id or self.new_trace_id(),
            "span_id": sid,
            "parent_id": parent_id,
            "ts": int(t0_us),
            "dur": max(0, int(dur_us)),
            "tid": self._tid() if tid is None else int(tid),
            "args": dict(args or {}),
        }
        if synthetic:
            rec["synthetic"] = True
        self._append(rec)
        return sid

    # ------------------------------------------------------------- queries
    def spans(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._spans)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active": self.active,
                "capacity": self._spans.maxlen,
                "ring": len(self._spans),
                "spans_total": self.spans_total,
                "dropped_total": self.dropped_total,
                "last_dump": self.last_dump_path,
            }

    # -------------------------------------------------------------- export
    def chrome_trace(self) -> Dict[str, Any]:
        """The ring as a Chrome trace-event JSON object (Perfetto-loadable).

        Spans become ``ph: "X"`` complete events sorted by timestamp
        (monotonic ``ts``), instants become ``ph: "i"``; span identity and
        parent links ride in ``args`` so the tree survives the format."""
        with self._lock:
            spans = list(self._spans)
            tids = sorted(
                (small, name) for small, name in self._tids.values()
            )
        pid = os.getpid()
        events: List[Dict[str, Any]] = [
            {
                "name": "process_name", "ph": "M", "ts": 0,
                "pid": pid, "tid": 0, "args": {"name": "lightgbm_tpu"},
            }
        ]
        for small, name in tids:
            events.append(
                {
                    "name": "thread_name", "ph": "M", "ts": 0,
                    "pid": pid, "tid": small, "args": {"name": name},
                }
            )
        for rec in sorted(spans, key=lambda r: r["ts"]):
            args = dict(rec["args"])
            args["trace_id"] = rec["trace_id"]
            args["span_id"] = rec["span_id"]
            if rec.get("parent_id"):
                args["parent_id"] = rec["parent_id"]
            if rec.get("synthetic"):
                args["synthetic"] = True
            ev: Dict[str, Any] = {
                "name": rec["name"],
                "cat": rec["cat"],
                "ph": "i" if rec["dur"] is None else "X",
                "ts": rec["ts"],
                "pid": pid,
                "tid": rec["tid"],
                "args": args,
            }
            if rec["dur"] is None:
                ev["s"] = "t"
            else:
                ev["dur"] = rec["dur"]
            events.append(ev)
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "metadata": {
                "schema": TRACE_SCHEMA,
                "spans_total": self.spans_total,
                "dropped_total": self.dropped_total,
            },
        }

    def chrome_trace_json(self) -> str:
        return json.dumps(self.chrome_trace())

    def dump(self, path: str) -> str:
        """Atomically write the Chrome trace JSON to ``path``; returns it."""
        _atomic_write_text(path, self.chrome_trace_json())
        with self._lock:
            self.last_dump_path = path
            self.dump_count += 1
        return path

    def dump_fault(self, directory: str, suffix: str) -> str:
        """Best-effort dump next to a flight dump (``trace_<suffix>.json``,
        where ``suffix`` matches the flight file's ``<ts>_<pid>_<n>``).
        Never raises — this runs on fault paths."""
        if not self.active or not directory:
            return ""
        try:
            return self.dump(os.path.join(directory, f"trace_{suffix}.json"))
        except Exception:
            return ""


_TRACER = TraceRecorder()


def get_tracer() -> TraceRecorder:
    """The process-global trace recorder."""
    return _TRACER


# --------------------------------------------------------------- hot hooks
def note_phase(name: str, t0_s: float, dur_s: float) -> None:
    """Record a ``registry.phase`` timer as a child span of the open
    iteration/launch span.  ``t0_s`` is a ``time.perf_counter`` reading —
    the same clock as span timestamps, so no epoch conversion is needed.
    No-op (one attribute check + one current() lookup) when tracing is off
    or no span is open, so the phase hot path stays cheap."""
    tr = _TRACER
    if not tr.active:
        return
    parent = tr.current()
    if parent is None or not tr._sampled("phase"):
        return
    tr.add_span(
        f"phase/{name}", "phase", int(t0_s * 1e6), int(dur_s * 1e6),
        trace_id=parent.trace_id, parent_id=parent.span_id, tid=parent.tid,
    )


def note_collective(site: str, t0_ns: int, t1_ns: int, nbytes: int) -> None:
    """Record one measured-collective site call as a span with payload-byte
    args, parented under the ambient training span when one is open.  Host
    clocks only (the io_callback's perf_counter_ns brackets) — never tracer
    values."""
    tr = _TRACER
    if not tr.active:
        return
    parent = tr.current()
    if not tr._sampled("collective"):
        return
    tr.add_span(
        f"collective/{site}", "collective", t0_ns // 1000,
        max(0, t1_ns - t0_ns) // 1000,
        trace_id=parent.trace_id if parent else None,
        parent_id=parent.span_id if parent else None,
        args={"payload_bytes": int(nbytes)},
    )
