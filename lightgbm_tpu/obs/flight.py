"""Flight recorder: always-on bounded ring buffer with dump-on-fault.

Post-hoc telemetry (the JSONL sink) answers "what happened over the whole
run" — but only if the process lives long enough to flush it, and only if
the operator remembered to turn it on.  The flight recorder is the black
box for everything else: a process-global, bounded ``deque`` of the last N
iteration events and alerts that costs one append per iteration, plus an
atomic ``dump()`` that snapshots the ring, the live counter/gauge tables
and the active alerts into ``flight_<ts>.json`` next to the checkpoint
directory *before* the process dies.

Fault sites wired in (see ``boosting/gbdt.py`` and ``engine.py``):

* ``NumericsError`` — the non-finite guard rails dump before raising;
* the fused-kernel degradation latch — dump when falling back to the XLA
  oracle, so the triggering iteration's context survives;
* SIGTERM/preemption — :func:`install_sigterm_handler` dumps and then
  chains to the previously installed handler.

This module is intentionally import-cycle-free: it must not import
``resilience`` (``resilience.checkpoint`` imports ``..obs``), so the
tmp+fsync+rename atomic-write idiom is restated locally.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import tempfile
import threading
import time
from typing import Any, Deque, Dict, List, Optional

FLIGHT_SCHEMA = "lgbtpu.flight.v1"

# Floor on ring capacity: the dump-on-fault contract promises the last
# >= 32 iteration events whenever the run got that far.
MIN_CAPACITY = 32
DEFAULT_CAPACITY = 256
_MAX_ALERTS = 128
_MAX_STICKY = 64


def _atomic_write_text(path: str, text: str) -> None:
    """tmp + fsync + os.replace in the destination directory, so a kill at
    any byte offset leaves either no file or a complete one."""
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", suffix=".tmp", dir=directory
    )
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


class FlightRecorder:
    """Bounded ring of recent events + alerts with atomic fault dumps."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY) -> None:
        self._lock = threading.Lock()
        self._events: Deque[Dict[str, Any]] = collections.deque(
            maxlen=max(MIN_CAPACITY, int(capacity))
        )
        self._alerts: Deque[Dict[str, Any]] = collections.deque(
            maxlen=_MAX_ALERTS
        )
        self._sticky: Deque[Dict[str, Any]] = collections.deque(
            maxlen=_MAX_STICKY
        )
        self.active = True
        self.fault_dir = ""
        self.run_info: Dict[str, Any] = {}
        self.last_checkpoint = ""
        self.last_dump_path = ""
        self.last_trace_path = ""
        self.dump_count = 0

    # ---------------------------------------------------------- lifecycle
    def configure(
        self,
        capacity: Optional[int] = None,
        fault_dir: Optional[str] = None,
        run_info: Optional[Dict[str, Any]] = None,
        active: Optional[bool] = None,
    ) -> "FlightRecorder":
        with self._lock:
            if capacity is not None and capacity != self._events.maxlen:
                self._events = collections.deque(
                    self._events, maxlen=max(MIN_CAPACITY, int(capacity))
                )
            if fault_dir is not None:
                self.fault_dir = fault_dir
            if run_info is not None:
                self.run_info = dict(run_info)
            if active is not None:
                self.active = bool(active)
        return self

    def reset(self) -> None:
        """Clear the ring (new train run); keeps capacity/fault_dir."""
        with self._lock:
            self._events.clear()
            self._alerts.clear()
            self._sticky.clear()
            self.last_checkpoint = ""
            self.last_dump_path = ""
            self.last_trace_path = ""
            self.dump_count = 0

    @property
    def capacity(self) -> int:
        return self._events.maxlen or 0

    # -------------------------------------------------------------- feeds
    def note_event(self, event: Dict[str, Any]) -> None:
        """Append one event to the ring (O(1), evicts the oldest)."""
        if not self.active:
            return
        with self._lock:
            self._events.append(event)

    def note_alert(self, alert: Dict[str, Any]) -> None:
        """Record a watchdog alert (kept separately so a burst of events
        cannot evict the alert history before a dump)."""
        if not self.active:
            return
        with self._lock:
            self._alerts.append(alert)
            self._events.append(alert)

    def note_sticky(self, event: Dict[str, Any]) -> None:
        """Record a rare, high-value lifecycle event (model swap, refresh
        promotion) that must survive ring eviction: kept in a separate
        bounded deque so a flood of per-batch events can never push the
        swap history out of a dump, and mirrored into the ring so dumps
        still show it in chronological context."""
        if not self.active:
            return
        with self._lock:
            self._sticky.append(event)
            self._events.append(event)

    def note_checkpoint(self, path: str) -> None:
        if not self.active:
            return
        with self._lock:
            self.last_checkpoint = path

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def alerts(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._alerts)

    def sticky_events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._sticky)

    # -------------------------------------------------------------- dumps
    def snapshot(self, reason: str = "") -> Dict[str, Any]:
        """JSON-serializable snapshot of the ring + live telemetry tables."""
        from .registry import _jsonable, get_session

        ses = get_session()
        with self._lock:
            events = list(self._events)
            alerts = list(self._alerts)
            sticky = list(self._sticky)
            snap = {
                "schema": FLIGHT_SCHEMA,
                "reason": reason,
                "dumped_at_unix": time.time(),
                "pid": os.getpid(),
                "run_info": dict(self.run_info),
                "last_checkpoint": self.last_checkpoint,
                "ring_capacity": self._events.maxlen,
                "n_events": len(events),
                "n_alerts": len(alerts),
            }
        snap["counters"] = dict(ses.counters)
        snap["gauges"] = dict(ses.gauges)
        snap["events"] = events
        snap["alerts"] = alerts
        snap["sticky_events"] = sticky
        return _jsonable(snap)

    def dump(self, reason: str, directory: Optional[str] = None) -> str:
        """Atomically write ``flight_<ts>.json``; returns the path.

        Never raises: this runs on fault paths (a dump failure must not
        mask the original ``NumericsError``/signal).  Returns "" when no
        destination directory is known or the write fails.
        """
        target = directory or self.fault_dir
        if not self.active or not target:
            return ""
        try:
            os.makedirs(target, exist_ok=True)
            ts = time.strftime("%Y%m%d_%H%M%S", time.gmtime())
            suffix = f"{ts}_{os.getpid()}_{self.dump_count}"
            path = os.path.join(target, f"flight_{suffix}.json")
            _atomic_write_text(
                path, json.dumps(self.snapshot(reason), indent=1)
            )
            # pair the black box with the span timeline: the trace recorder
            # dumps trace_<same suffix>.json next to this flight dump (best
            # effort — a trace failure must not lose the flight dump)
            from .trace import get_tracer

            trace_path = get_tracer().dump_fault(target, suffix)
            with self._lock:
                self.last_dump_path = path
                if trace_path:
                    self.last_trace_path = trace_path
                self.dump_count += 1
            return path
        except Exception:
            return ""


_FLIGHT = FlightRecorder()


def get_flight() -> FlightRecorder:
    """The process-global flight recorder."""
    return _FLIGHT


def list_flight_dumps(directory: str) -> List[str]:
    """All ``flight_*.json`` files in ``directory``, sorted by mtime."""
    if not os.path.isdir(directory):
        return []
    out = [
        os.path.join(directory, n)
        for n in os.listdir(directory)
        if n.startswith("flight_") and n.endswith(".json")
    ]
    out.sort(key=lambda p: (os.path.getmtime(p), p))
    return out


# ------------------------------------------------------------------ SIGTERM
_prev_sigterm: Optional[Any] = None
_sigterm_installed = False


def _on_sigterm(signum, frame):  # pragma: no cover - exercised in subprocess
    _FLIGHT.dump("sigterm")
    prev = _prev_sigterm
    if callable(prev):
        prev(signum, frame)
        return
    # Default disposition: restore it and re-raise so the exit status is
    # the conventional "killed by SIGTERM".
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    os.kill(os.getpid(), signal.SIGTERM)


def install_sigterm_handler() -> bool:
    """Dump the flight ring on SIGTERM, then chain to the previous handler.

    Installed by ``engine.train`` for the duration of training (main
    thread only — ``signal.signal`` raises elsewhere, in which case this
    is a no-op returning False).  Idempotent.
    """
    global _prev_sigterm, _sigterm_installed
    if _sigterm_installed:
        return True
    if threading.current_thread() is not threading.main_thread():
        return False
    try:
        _prev_sigterm = signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError):
        return False
    _sigterm_installed = True
    return True


def uninstall_sigterm_handler() -> None:
    global _prev_sigterm, _sigterm_installed
    if not _sigterm_installed:
        return
    try:
        signal.signal(
            signal.SIGTERM,
            _prev_sigterm if _prev_sigterm is not None else signal.SIG_DFL,
        )
    except (ValueError, OSError):
        pass
    _prev_sigterm = None
    _sigterm_installed = False
