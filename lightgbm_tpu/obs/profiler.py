"""``jax.profiler`` trace wiring: capture a window of boosting iterations.

``Config.profile_trace_dir`` plus ``profile_iter_start``/``profile_iter_end``
drive ``jax.profiler.start_trace``/``stop_trace`` from the training loop —
the standard way to get a TensorBoard-loadable device trace of exactly the
steady-state iterations (skipping compile/warmup noise).  The grower's
``jax.named_scope`` labels (partition / histogram / split_scan /
candidate_refresh / bookkeeping — or ``fused_grow_step`` replacing the
partition/histogram pair when the fused Pallas grow step is engaged, see
ops/pallas/grow_step.py) and the predictor's ``TraceAnnotation`` phases
appear inside the captured trace.
"""

from __future__ import annotations

import jax

from ..utils.log import log_warning


class TraceWindow:
    """Start/stop a profiler trace over an inclusive iteration window.

    ``end_iter < 0`` means "until training ends" (the caller's ``close()``
    in a finally block stops the trace).  A failed start (e.g. profiler
    already active in the process) degrades to a warning, never an error.
    """

    def __init__(self, trace_dir: str, start_iter: int = 0, end_iter: int = -1):
        self.trace_dir = trace_dir or ""
        self.start_iter = max(0, int(start_iter))
        self.end_iter = int(end_iter)
        self._active = False
        self._done = False

    @property
    def active(self) -> bool:
        return self._active

    def on_iteration_start(self, it: int) -> None:
        if not self.trace_dir or self._active or self._done:
            return
        if it >= self.start_iter:
            try:
                jax.profiler.start_trace(self.trace_dir)
                self._active = True
            except Exception as e:  # profiler busy / unwritable dir
                self._done = True
                log_warning(f"profile_trace_dir: start_trace failed: {e!r}")

    def on_iteration_end(self, it: int) -> None:
        if self._active and 0 <= self.end_iter <= it:
            self.close()

    def close(self) -> None:
        if self._active:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                log_warning(f"profile_trace_dir: stop_trace failed: {e!r}")
            self._active = False
            self._done = True
