"""Per-host telemetry aggregation (multi-host rollup + straggler gauges).

Reference analog: ``Network::GlobalSyncUpByMin/Max/Mean`` (include/LightGBM/
network.h:169-240) — every machine contributes a scalar, the allreduce hands
back the min/max/mean.  Here the unit is the whole telemetry session: each
host snapshots its counters/gauges/iteration walls, the snapshots are
allgathered (64-bit-safe JSON-over-uint8 ride on
``parallel.allgather_host_varlen``), and every host derives the identical
merged view:

* counters merge by SUM (exact — they are event counts/bytes);
* gauges merge by min/max/mean (``agg/<name>/min|max|mean``);
* per-host mean iteration walls become straggler gauges
  (``straggler/iter_wall_ms_max|mean|skew`` — skew = max/mean, the
  slowest-host multiplier the reference's sync-up would expose).

Single-process runs roll up the local snapshot alone (identity merge), so
the export schema is the same shape on a laptop and on a pod.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from .registry import TelemetrySession, get_session


def host_snapshot(ses: Optional[TelemetrySession] = None) -> Dict[str, Any]:
    """This host's contribution to the rollup."""
    ses = ses or get_session()
    iter_walls = [
        float(e.get("wall_ms", 0.0))
        for e in ses.events
        if e.get("event") == "iteration"
    ]
    import jax

    # exclude the DERIVED gauges a previous rollup folded back into the
    # session: the session is a process-global singleton, so a long-lived
    # process that trains repeatedly (serving refresh loops, sweeps, test
    # suites) would otherwise re-aggregate agg/* into agg/agg/* — gauge
    # count triples per rollup.  Filtering keeps rollup idempotent.
    gauges = {
        name: v
        for name, v in ses.gauges.items()
        if not name.startswith(("agg/", "straggler/"))
    }
    return {
        "process": int(jax.process_index()),
        "counters": dict(ses.counters),
        "gauges": gauges,
        "iter_wall_ms": iter_walls,
    }


def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """GlobalSyncUp-style merge: counters sum; gauges min/max/mean;
    straggler gauges from per-host mean iteration walls."""
    counters: Dict[str, int] = {}
    gauge_vals: Dict[str, List[float]] = {}
    host_walls: List[float] = []
    for s in snaps:
        for name, v in (s.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + int(v)
        for name, v in (s.get("gauges") or {}).items():
            gauge_vals.setdefault(name, []).append(float(v))
        walls = s.get("iter_wall_ms") or []
        if walls:
            host_walls.append(float(np.mean(walls)))
    gauges: Dict[str, float] = {}
    for name, vals in gauge_vals.items():
        gauges[f"agg/{name}/min"] = float(min(vals))
        gauges[f"agg/{name}/max"] = float(max(vals))
        gauges[f"agg/{name}/mean"] = float(np.mean(vals))
    straggler: Dict[str, float] = {}
    if host_walls:
        mx = float(max(host_walls))
        mean = float(np.mean(host_walls))
        straggler["straggler/iter_wall_ms_max"] = mx
        straggler["straggler/iter_wall_ms_mean"] = mean
        straggler["straggler/skew"] = mx / mean if mean > 0 else 1.0
    return {
        "hosts": len(snaps),
        "counters": counters,
        "gauges": gauges,
        "straggler": straggler,
    }


def _allgather_snapshots(snap: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Exchange JSON snapshots across processes (identity when single)."""
    import jax

    if jax.process_count() <= 1:
        return [snap]
    # lazy import breaks the obs <-> parallel cycle (parallel imports
    # obs.jit at module scope)
    from ..parallel import allgather_host_varlen

    payload = np.frombuffer(
        json.dumps(snap, sort_keys=True).encode("utf-8"), dtype=np.uint8
    )
    gathered, counts = allgather_host_varlen(payload, return_counts=True)
    snaps = []
    off = 0
    for c in counts:
        c = int(c)
        snaps.append(json.loads(bytes(gathered[off : off + c]).decode("utf-8")))
        off += c
    return snaps


def global_rollup(ses: Optional[TelemetrySession] = None) -> Optional[Dict[str, Any]]:
    """Merge every host's counters/gauges into this session's export.

    Records one ``host_rollup`` event (JSONL sink included) and folds the
    merged ``agg/*`` and ``straggler/*`` gauges into the session so
    ``Booster.telemetry()`` carries the global view.  Never raises —
    telemetry must not take a training run down at the finish line."""
    ses = ses or get_session()
    if not ses.enabled:
        return None
    try:
        snaps = _allgather_snapshots(host_snapshot(ses))
        merged = merge_snapshots(snaps)
        for name, v in merged["gauges"].items():
            ses.set_gauge(name, v)
        for name, v in merged["straggler"].items():
            ses.set_gauge(name, v)
        ses.record({"event": "host_rollup", **merged})
        return merged
    except Exception:
        return None
